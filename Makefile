# Top-level convenience targets (parity: reference ./configure && make).
.PHONY: all native test test-quick test-native asan bench smoke \
	telemetry-check chaos stream lint help

all: native

native:
	$(MAKE) -C quiver_tpu/cpp

test:
	python -m pytest tests/ -q

test-native:
	$(MAKE) -C quiver_tpu/cpp test

asan:
	$(MAKE) -C quiver_tpu/cpp asan

bench:
	python bench.py

smoke:
	python bench.py --small --iters 5

test-quick:
	python -m pytest tests/ -m "not slow" -q

# telemetry suite + the no-HTTP-exporter-in-hot-paths guard
telemetry-check:
	python -m pytest tests/ -m telemetry -q

# deterministic fault-injection suite (docs/RESILIENCE.md)
chaos:
	python -m pytest tests/ -m chaos -q

# delta-CSR overlay / temporal sampling / ingestion suite (docs/STREAMING.md)
stream:
	python -m pytest tests/ -m stream -q

# quiverlint: hot-path static analysis (docs/STATIC_ANALYSIS.md)
lint:
	python -m quiver_tpu.analysis quiver_tpu bench.py

help:
	@echo "targets: native | test | test-quick | test-native | asan | bench | smoke | telemetry-check | chaos | stream | lint"
