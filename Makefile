# Top-level convenience targets (parity: reference ./configure && make).
.PHONY: all native test test-quick test-native asan bench smoke \
	telemetry-check chaos stream lint sanitize recovery crash qos \
	paged timeline perfgate fleet fleet-chaos mesh help

all: native

native:
	$(MAKE) -C quiver_tpu/cpp

test:
	python -m pytest tests/ -q

test-native:
	$(MAKE) -C quiver_tpu/cpp test

asan:
	$(MAKE) -C quiver_tpu/cpp asan

bench:
	python bench.py

smoke:
	python bench.py --small --iters 5

test-quick:
	python -m pytest tests/ -m "not slow" -q

# telemetry suite + the no-HTTP-exporter-in-hot-paths guard
telemetry-check:
	python -m pytest tests/ -m telemetry -q

# deterministic fault-injection suite (docs/RESILIENCE.md)
chaos:
	python -m pytest tests/ -m chaos -q

# delta-CSR overlay / temporal sampling / ingestion suite (docs/STREAMING.md)
stream:
	python -m pytest tests/ -m stream -q

# quiverlint: hot-path + whole-program concurrency + staging-dataflow
# static analysis (docs/STATIC_ANALYSIS.md); --strict-baseline also
# fails on stale baseline entries, rule-hash mismatches, and stale
# sync-ok waivers so the debt ledger can only shrink.  benchmarks/ is
# report-only against its own committed baseline: harness code gets
# linted and diffed, but doesn't gate.
lint:
	python -m quiver_tpu.analysis --strict-baseline quiver_tpu bench.py
	python -m quiver_tpu.analysis --report-only \
		--baseline quiverlint.bench.baseline.json benchmarks

# quick suite + chaos + mesh harnesses under both runtime witnesses
# (QUIVER_SANITIZE=1 wraps threading.Lock/RLock AND the device->host
# coercion points; docs/STATIC_ANALYSIS.md)
sanitize:
	QUIVER_SANITIZE=1 python -m pytest tests/ -m "not slow" -q
	QUIVER_SANITIZE=1 python -m pytest tests/ -m chaos -q
	QUIVER_SANITIZE=1 python -m pytest tests/ -m mesh -q

# WAL / checkpoint / program-registry durability suite (docs/RECOVERY.md)
recovery:
	python -m pytest tests/ -m recovery -q

# kill -9 crash harness: real child processes SIGKILLed mid-ingest under
# a seeded chaos plan, then recovered — zero acked loss, monotone
# version, bit-identical sampling (docs/RECOVERY.md)
crash:
	python -m pytest tests/ -m crash -q

# multi-tenant QoS suite + the closed-loop burst harness in smoke mode
# (docs/RESILIENCE.md "QoS & degradation ladder")
qos:
	python -m pytest tests/ -m qos -q
	python benchmarks/qos_load.py --smoke

# paged feature store + ragged page-gather kernel suite: bit-identical
# equivalence vs the staged merge, retrace budget, page-residency
# recovery (docs/FEATURE_CACHE.md)
paged:
	python -m pytest tests/ -m paged -q

# unified timeline / program attribution / perfgate suite
# (docs/OBSERVABILITY.md "Timeline & program attribution")
timeline:
	python -m pytest tests/ -m timeline -q

# noise-aware perf-regression gate vs the committed baseline in
# .bench_state.json (docs/BENCHMARKS.md "Perfgate"); exit 1 = regression
perfgate:
	python benchmarks/perfgate.py

# elastic replicated serving fleet suite: router, membership, WAL
# shipping edge cases, drain/rejoin (docs/FLEET.md)
fleet:
	python -m pytest tests/ -m fleet -q

# replica-failover chaos harness: 3 real replica processes, kill -9 one
# mid-burst, prove zero lost answers + warm rejoin (docs/FLEET.md)
fleet-chaos:
	python -m pytest tests/ -m fleet -q
	python benchmarks/fleet_chaos.py --smoke --scenario all

# mesh-native sharded serving suite: 8-virtual-device CPU rehearsal,
# sharded gather/sampling bit-identity, shard-group failover, coherent
# group WAL (docs/SHARDING.md)
mesh:
	python -m pytest tests/ -m mesh -q

help:
	@echo "targets: native | test | test-quick | test-native | asan | bench | smoke | telemetry-check | chaos | stream | lint | sanitize | recovery | crash | qos | paged | timeline | perfgate | fleet | fleet-chaos | mesh | help"
