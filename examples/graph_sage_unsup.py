"""Unsupervised GraphSAGE — link-prediction objective.

TPU-native counterpart of
``/root/reference/examples/pyg/graph_sage_unsup_quiver.py``: positive
pairs are sampled edges, negatives are random nodes, loss is
``-log s(z_u . z_v) - log s(-z_u . z_neg)`` on embeddings produced through
the sampled-neighborhood encoder.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse
import time

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=20_000)
    ap.add_argument("--classes", type=int, default=16)
    ap.add_argument("--batch-size", type=int, default=256)
    ap.add_argument("--steps", type=int, default=30)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import optax

    from quiver_tpu import Feature, GraphSageSampler
    from quiver_tpu.models import GraphSAGE
    from quiver_tpu.utils.synthetic import community_graph

    # community structure gives unsupervised learning something to find
    topo, feat, comm = community_graph(args.nodes, args.classes,
                                       intra_deg=8, inter_deg=2)
    feature = Feature(device_cache_size="10G").from_cpu_tensor(feat)
    sampler = GraphSageSampler(topo, [10, 5])
    model = GraphSAGE(hidden=64, out_dim=32, num_layers=2, dropout=0.0)

    rng = np.random.default_rng(0)
    B = args.batch_size
    src_all = np.repeat(
        np.arange(topo.node_count), np.asarray(topo.degree)
    )

    def make_batch(i):
        # positive pairs: random edges (u -> v); negatives: random nodes
        eids = rng.integers(0, topo.edge_count, B)
        u, v = src_all[eids], topo.indices[eids].astype(np.int64)
        neg = rng.integers(0, topo.node_count, B)
        seeds = np.concatenate([u, v, neg])
        batch = sampler.sample(seeds, key=jax.random.PRNGKey(i))
        x = feature[np.asarray(batch.n_id)]
        return batch, x

    b0, x0 = make_batch(0)
    params = model.init(jax.random.PRNGKey(1), x0, b0.layers)
    tx = optax.adam(1e-3)
    opt = tx.init(params)

    @jax.jit
    def step(params, opt, x, blocks):
        def loss_fn(p):
            z = model.apply(p, x, blocks)          # [3B, 32]
            zu, zv, zn = z[:B], z[B:2 * B], z[2 * B:]
            pos = jax.nn.log_sigmoid((zu * zv).sum(-1))
            neg = jax.nn.log_sigmoid(-(zu * zn).sum(-1))
            return -(pos + neg).mean()

        loss, g = jax.value_and_grad(loss_fn)(params)
        upd, opt = tx.update(g, opt, params)
        return optax.apply_updates(params, upd), opt, loss

    t0 = time.perf_counter()
    for i in range(args.steps):
        batch, x = make_batch(i)
        params, opt, loss = step(params, opt, x, batch.layers)
        if i % 10 == 0 or i == args.steps - 1:
            print(f"step {i}: loss {float(loss):.4f}")
    print(f"{args.steps} unsup steps in {time.perf_counter() - t0:.2f}s")

    # probe: do embeddings separate communities? (cosine sim intra vs inter)
    probe = rng.integers(0, topo.node_count, 3 * B)
    pb = sampler.sample(probe, key=jax.random.PRNGKey(99))
    z = np.asarray(model.apply(params, feature[np.asarray(pb.n_id)],
                               pb.layers))
    z = z / np.linalg.norm(z, axis=1, keepdims=True)
    same = comm[probe[:, None]] == comm[probe[None, :]]
    sims = z @ z.T
    print(f"intra-community cos sim {sims[same].mean():.3f} vs "
          f"inter {sims[~same].mean():.3f}")


if __name__ == "__main__":
    main()
