"""Heterogeneous R-GAT training — mag240m-class schema.

TPU-native counterpart of the reference's mag240m benchmark
(``/root/reference/benchmarks/ogbn-mag240m/``): paper/author/institution
graph, hetero neighbor sampling, R-GAT.  Synthetic schema-compatible data
unless the real dataset is wired in.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse
import time

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--papers", type=int, default=20_000)
    ap.add_argument("--authors", type=int, default=10_000)
    ap.add_argument("--institutions", type=int, default=500)
    ap.add_argument("--dim", type=int, default=64)
    ap.add_argument("--classes", type=int, default=20)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--steps", type=int, default=20)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import optax

    from quiver_tpu import Feature
    from quiver_tpu.hetero import HeteroCSRTopo, HeteroGraphSageSampler
    from quiver_tpu.models import RGAT

    rng = np.random.default_rng(0)

    def edges(n_src, n_dst, avg):
        deg = rng.poisson(avg, n_dst)
        dst = np.repeat(np.arange(n_dst), deg)
        return np.stack([rng.integers(0, n_src, len(dst)), dst])

    counts = {"paper": args.papers, "author": args.authors,
              "institution": args.institutions}
    topo = HeteroCSRTopo.from_edge_index_dict(
        {
            ("paper", "cites", "paper"): edges(args.papers, args.papers, 8),
            ("author", "writes", "paper"): edges(args.authors, args.papers, 4),
            ("institution", "employs", "author"):
                edges(args.institutions, args.authors, 2),
        },
        counts,
    )
    dims = {"paper": args.dim, "author": args.dim // 2, "institution": 16}
    from quiver_tpu import HeteroFeature

    feats = HeteroFeature.from_cpu_tensors(
        {t: rng.normal(size=(counts[t], dims[t])).astype(np.float32)
         for t in counts},
        device_cache_size="10G",
    )
    labels = rng.integers(0, args.classes, args.papers)

    sampler = HeteroGraphSageSampler(
        topo,
        sizes=[{("paper", "cites", "paper"): 8,
                ("author", "writes", "paper"): 4,
                ("institution", "employs", "author"): 2}] * 2,
        seed_type="paper",
    )
    model = RGAT(hidden=64, out_dim=args.classes, num_layers=2,
                 in_dims=dims, heads=4, dropout=0.0)
    tx = optax.adam(1e-3)
    B = args.batch_size

    fetch = feats.lookup

    b0 = sampler.sample(np.arange(B), key=jax.random.PRNGKey(0))
    params = model.init(jax.random.PRNGKey(1), fetch(b0), b0)
    opt = tx.init(params)

    @jax.jit
    def step(params, opt, xs, batch, labs):
        def loss_fn(p):
            logits = model.apply(p, xs, batch)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, labs
            ).mean()

        loss, g = jax.value_and_grad(loss_fn)(params)
        upd, opt = tx.update(g, opt, params)
        return optax.apply_updates(params, upd), opt, loss

    t0 = time.perf_counter()
    for i in range(args.steps):
        seeds = rng.integers(0, args.papers, B)
        batch = sampler.sample(seeds, key=jax.random.PRNGKey(2 + i))
        params, opt, loss = step(params, opt, fetch(batch), batch,
                                 jnp.asarray(labels[seeds]))
        if i % 5 == 0 or i == args.steps - 1:
            print(f"step {i}: loss {float(loss):.4f}")
    dt = time.perf_counter() - t0
    print(f"{args.steps} R-GAT steps in {dt:.2f}s "
          f"({dt / args.steps * 1e3:.0f} ms/step)")


if __name__ == "__main__":
    main()
