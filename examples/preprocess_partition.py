"""Offline probability-guided feature partitioning — the preprocessing
step of multi-host training.

TPU-native counterpart of
``/root/reference/benchmarks/ogbn-papers100M/preprocess.py`` (:119-211):
per-host access probabilities from the train split, greedy partitioning,
artifacts on disk, then at train time PartitionInfo/DistFeature load them.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=50_000)
    ap.add_argument("--edges", type=int, default=500_000)
    ap.add_argument("--dim", type=int, default=32)
    ap.add_argument("--hosts", type=int, default=4)
    ap.add_argument("--fanout", type=int, nargs="+", default=[15, 10, 5])
    ap.add_argument("--out", default="/tmp/quiver_tpu_partition")
    args = ap.parse_args()

    from quiver_tpu import (
        CSRTopo, GraphSageSampler, quiver_partition_feature,
        load_quiver_feature_partition,
    )
    from quiver_tpu.utils.synthetic import synthetic_csr

    indptr, indices = synthetic_csr(args.nodes, args.edges)
    topo = CSRTopo(indptr=indptr, indices=indices)
    rng = np.random.default_rng(0)
    feature = rng.normal(size=(args.nodes, args.dim)).astype(np.float32)

    # per-host train splits -> per-host access probabilities (the
    # cal_next recurrence), exactly the reference's preprocessing recipe
    sampler = GraphSageSampler(topo, args.fanout)
    train_idx = rng.permutation(args.nodes)[: args.nodes // 2]
    shards = np.array_split(train_idx, args.hosts)
    probs = [
        np.asarray(sampler.sample_prob(shard, topo.node_count))
        for shard in shards
    ]
    print(f"probabilities computed for {args.hosts} hosts")

    parts, orders, book = quiver_partition_feature(
        feature, probs, args.out
    )
    sizes = [len(p) for p in parts]
    print(f"partitions: {sizes} (balance "
          f"{min(sizes) / max(sizes):.2f}), artifacts in {args.out}")

    # verify round-trip like the training side would
    ids0, cache0, feat0, book0 = load_quiver_feature_partition(0, args.out)
    assert np.allclose(feat0, feature[ids0])
    print(f"partition 0: {len(ids0)} nodes, cache order head "
          f"{cache0[:5].tolist()}")
    print("load_quiver_feature_partition round-trip OK; feed `book` to "
          "PartitionInfo.from_partition_book(...) at train time")


if __name__ == "__main__":
    main()
