"""GNN model serving — Reddit-style deployment.

TPU-native counterpart of
``/root/reference/examples/serving/reddit/reddit_serving.py``: client
streams push id-batches; the batcher routes small expansions to the CPU
sampler lane and big ones to the TPU lane; the inference server runs
sample -> feature -> model with bucketed shapes and reports tp99.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse
import queue
import threading
import time

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=50_000)
    ap.add_argument("--edges", type=int, default=500_000)
    ap.add_argument("--dim", type=int, default=64)
    ap.add_argument("--clients", type=int, default=3)
    ap.add_argument("--requests-per-client", type=int, default=30)
    args = ap.parse_args()

    import jax

    from quiver_tpu import (
        CSRTopo, Feature, GraphSageSampler, RequestBatcher, HybridSampler,
        InferenceServer_Debug, generate_neighbour_num,
    )
    from quiver_tpu.serving import ServingRequest
    from quiver_tpu.models import GraphSAGE

    rng = np.random.default_rng(0)
    deg = np.maximum(rng.lognormal(2, 1, args.nodes), 1).astype(np.int64)
    deg = (deg * args.edges / deg.sum()).astype(np.int64) + 1
    src = np.repeat(np.arange(args.nodes), deg)
    dst = rng.integers(0, args.nodes, len(src))
    topo = CSRTopo(edge_index=np.stack([src, dst]))
    feat = rng.normal(size=(args.nodes, args.dim)).astype(np.float32)

    feature = Feature(device_cache_size="10G").from_cpu_tensor(feat)
    sizes = [10, 5]
    tpu_sampler = GraphSageSampler(topo, sizes)
    cpu_sampler = GraphSageSampler(topo, sizes, mode="CPU")
    model = GraphSAGE(hidden=128, out_dim=41, num_layers=2, dropout=0.0)
    b0 = tpu_sampler.sample(np.arange(8, dtype=np.int64))
    params = model.init(jax.random.PRNGKey(0),
                        feature[np.asarray(b0.n_id)], b0.layers)
    apply_fn = jax.jit(lambda p, x, blocks: model.apply(p, x, blocks))

    # pre-warm the serving buckets so request latency excludes compiles
    from quiver_tpu import InferenceServer as _IS

    for bucket in _IS.BUCKETS:
        if bucket > 32:
            break
        bb = tpu_sampler.sample(np.arange(bucket, dtype=np.int64))
        apply_fn(params, feature[np.asarray(bb.n_id)], bb.layers)

    nn_num = generate_neighbour_num(topo, sizes, mode="expected")
    streams = [queue.Queue() for _ in range(args.clients)]
    rb = RequestBatcher(streams, neighbour_num=nn_num,
                        threshold=float(np.percentile(nn_num, 30) * 2),
                        mode="Auto").start()
    hs = HybridSampler(cpu_sampler, rb.cpu_batched_queue,
                       num_workers=2).start()
    server = InferenceServer_Debug(
        tpu_sampler, feature, apply_fn, params,
        rb.device_batched_queue, hs.sampled_queue,
    ).start()

    def client(cid):
        crng = np.random.default_rng(cid)
        for i in range(args.requests_per_client):
            ids = crng.integers(0, args.nodes, crng.integers(1, 32))
            streams[cid].put(ServingRequest(ids=ids, client=cid, seq=i))
            time.sleep(crng.exponential(0.01))

    threads = [threading.Thread(target=client, args=(c,))
               for c in range(args.clients)]
    for t in threads:
        t.start()

    total = args.clients * args.requests_per_client
    for _ in range(total):
        req, out = server.result_queue.get(timeout=120)
        assert out.shape[0] == len(req.ids)
    for t in threads:
        t.join()
    stats = server.stats()
    rb.stop(); hs.stop(); server.stop()
    print(f"served {stats['count']}: avg {stats['avg_latency_ms']:.1f}ms "
          f"p99 {stats['p99_latency_ms']:.1f}ms "
          f"{stats['throughput_rps']:.0f} rps")


if __name__ == "__main__":
    main()
