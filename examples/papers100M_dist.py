"""Multi-chip distributed training — papers100M-class setup.

TPU-native counterpart of
``/root/reference/benchmarks/ogbn-papers100M/train_quiver_multi_node.py``:
there, each host keeps a feature partition (probability-partitioned), an
NCCL request/response exchange serves remote rows, and DDP syncs grads.
Here the same roles are played by: a row-sharded graph
(:class:`DistGraphSampler`), a partitioned :class:`DistFeature` with
all-to-all lookup, and a vmap-DP train step whose gradient psum XLA inserts
from the shardings.

Runs on whatever mesh is available (8 virtual CPU devices in tests; a real
slice in production).  Synthetic data unless OGB + dataset present.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse
import time

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=200_000)
    ap.add_argument("--edges", type=int, default=2_000_000)
    ap.add_argument("--dim", type=int, default=64)
    ap.add_argument("--classes", type=int, default=32)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--hier", type=int, default=0, metavar="N_HOSTS",
                    help="use the two-tier ICI x DCN HierFeature over an "
                         "[N_HOSTS, devices/N_HOSTS] mesh (degree-ordered "
                         "hot tier covering 30%% of nodes)")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import optax

    from quiver_tpu import (
        CSRTopo, DistFeature, DistGraphSampler, PartitionInfo,
        GraphSageSampler,
    )
    from quiver_tpu.models import GraphSAGE
    from quiver_tpu.parallel import TrainState, make_train_step
    from quiver_tpu.utils.mesh import make_mesh

    mesh = make_mesh(("data",))
    nd = int(mesh.shape["data"])
    print(f"mesh: {nd} devices")

    rng = np.random.default_rng(0)
    deg = np.maximum(
        rng.lognormal(2.0, 1.0, args.nodes), 1
    ).astype(np.int64)
    deg = (deg * args.edges / deg.sum()).astype(np.int64) + 1
    src = np.repeat(np.arange(args.nodes), deg)
    dst = rng.integers(0, args.nodes, len(src))
    topo = CSRTopo(edge_index=np.stack([src, dst]))
    feat = rng.normal(size=(args.nodes, args.dim)).astype(np.float32)
    labels = rng.integers(0, args.classes, args.nodes)

    # graph row-sharded over the mesh; feature partitioned over the mesh
    sampler = DistGraphSampler(topo, mesh, sizes=[10, 5])
    hier_feat = hier_old2new = None
    if args.hier:
        from jax.sharding import Mesh
        from quiver_tpu import HierFeature

        H = args.hier
        hmesh = Mesh(np.array(jax.devices()[:nd]).reshape(H, nd // H),
                     ("dcn", "ici"))
        order = np.argsort(-topo.degree, kind="stable")
        hier_old2new = np.empty(args.nodes, dtype=np.int32)
        hier_old2new[order] = np.arange(args.nodes, dtype=np.int32)
        hier_feat = HierFeature.from_global_feature(
            feat[order], hmesh, hot_count=int(args.nodes * 0.3),
            global2host=(np.arange(args.nodes) % H).astype(np.int32))
    else:
        g2h = rng.integers(0, nd, topo.node_count).astype(np.int32)
        info = PartitionInfo(host=0, hosts=nd, global2host=g2h)
        dist_feat = DistFeature.from_global_feature(feat, mesh, info)

    model = GraphSAGE(hidden=128, out_dim=args.classes, num_layers=2,
                      dropout=0.0)
    tx = optax.adam(1e-3)
    B = args.batch_size

    def sample_round(step):
        seeds = rng.integers(0, topo.node_count, (nd, B))
        n_id, n_mask, num, blocks = sampler.sample(seeds, key=step)
        if hier_feat is not None:
            ids = hier_old2new[np.asarray(n_id)]
            out = hier_feat.lookup(
                ids.reshape(hier_feat.H, hier_feat.C, -1))
            xs = jnp.asarray(out).reshape(nd, -1, args.dim)
        else:
            xs = dist_feat.lookup(np.asarray(n_id))
        labs = jnp.asarray(labels[seeds])
        return n_id, blocks, xs, labs

    n_id0, blocks0, xs0, labs0 = sample_round(0)
    params = model.init(
        jax.random.PRNGKey(0), xs0[0],
        jax.tree_util.tree_map(lambda l: l[0], blocks0),
    )
    state = TrainState.create(params, tx)
    step_fn = make_train_step(
        lambda p, x, blocks, train=False, rngs=None: model.apply(
            p, x, blocks, train=train, rngs=rngs
        ), tx, mesh=mesh,
    )

    masks = jnp.ones((nd, B), bool)
    t0 = time.perf_counter()
    for i in range(args.steps):
        n_id, blocks, xs, labs = sample_round(i)
        state, loss = step_fn(state, xs, blocks, labs, masks,
                              jax.random.PRNGKey(i))
        if i % 5 == 0 or i == args.steps - 1:
            print(f"step {i}: loss {float(loss):.4f}")
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0
    print(f"{args.steps} DP steps x {nd} replicas x {B} seeds "
          f"in {dt:.2f}s ({dt / args.steps * 1e3:.0f} ms/step)")
    if hier_feat is not None:
        st = hier_feat.traffic_stats()
        print(f"hier: last-batch DCN crossings "
              f"{int(st['dcn_crossings'].sum())}, drops "
              f"{int(st['drops'].sum())}")


if __name__ == "__main__":
    main()
