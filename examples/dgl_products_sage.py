"""DGL-loop integration — quiver_tpu sampler + Feature under a DGL-style
training script (parity direction: reference
examples/dgl/ogbn_products_sage_quiver.py, which pairs quiver.Feature
with a DGL NeighborSampler loop and dglnn.SAGEConv blocks).

Two modes:
  * dgl installed: quiver_tpu samples convert to real DGL MFG blocks
    (``interop.to_dgl_blocks``) and train a dgl.nn SAGE.
  * dgl absent (this image): the same loop runs a pure-torch SAGEConv
    over ``interop.block_specs`` — identical math (mean aggregation +
    the h_dst = h[:n_dst] idiom), proving the adapter contract without
    the dependency.

Run: python examples/dgl_products_sage.py [--nodes 20000 --steps 30]
"""

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=20_000)
    ap.add_argument("--classes", type=int, default=16)
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--batch-size", type=int, default=512)
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()
    if args.cpu:
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        jax.config.update("jax_platforms", "cpu")

    import torch
    import torch.nn.functional as F

    from quiver_tpu import Feature, GraphSageSampler
    from quiver_tpu.interop import block_specs, to_torch
    from quiver_tpu.utils.synthetic import community_graph

    try:
        import dgl
        import dgl.nn.pytorch as dglnn

        from quiver_tpu.interop import to_dgl_blocks

        have_dgl = True
    except ImportError:
        have_dgl = False
    print(f"dgl available: {have_dgl}")

    topo, feat, labels = community_graph(
        args.nodes, args.classes, intra_deg=8, inter_deg=2, noise=0.6,
        feat_extra=16, seed=0)
    sampler = GraphSageSampler(topo, [10, 5])
    feature = Feature(device_cache_size=topo.node_count,
                      cache_unit="rows").from_cpu_tensor(feat)
    dim = feat.shape[1]

    if have_dgl:
        class SAGE(torch.nn.Module):
            def __init__(self):
                super().__init__()
                self.l1 = dglnn.SAGEConv(dim, 64, "mean")
                self.l2 = dglnn.SAGEConv(64, args.classes, "mean")

            def forward(self, blocks, x):
                h = x
                for layer, block in zip((self.l1, self.l2), blocks):
                    h_dst = h[: block.num_dst_nodes()]
                    h = layer(block, (h, h_dst))
                    if layer is self.l1:
                        h = F.relu(h)
                return h
    else:
        class TorchSAGEConv(torch.nn.Module):
            """dglnn.SAGEConv('mean')-equivalent over a block spec."""

            def __init__(self, din, dout):
                super().__init__()
                self.w_self = torch.nn.Linear(din, dout)
                self.w_neigh = torch.nn.Linear(din, dout, bias=False)

            def forward(self, spec, h, h_dst):
                src, dst, _, _, n_dst = spec
                agg = torch.zeros((n_dst, h.shape[1]), dtype=h.dtype)
                cnt = torch.zeros((n_dst, 1), dtype=h.dtype)
                idx = torch.from_numpy(dst.astype(np.int64))
                agg.index_add_(0, idx, h[torch.from_numpy(
                    src.astype(np.int64))])
                cnt.index_add_(0, idx, torch.ones((len(dst), 1)))
                mean = agg / cnt.clamp(min=1)
                return self.w_self(h_dst) + self.w_neigh(mean)

        class SAGE(torch.nn.Module):
            def __init__(self):
                super().__init__()
                self.l1 = TorchSAGEConv(dim, 64)
                self.l2 = TorchSAGEConv(64, args.classes)

            def forward(self, blocks, x):
                h = x
                for layer, spec in zip((self.l1, self.l2), blocks):
                    h_dst = h[: spec[4]]
                    h = layer(spec, h, h_dst)
                    if layer is self.l1:
                        h = F.relu(h)
                return h

    model = SAGE()
    opt = torch.optim.Adam(model.parameters(), lr=1e-2)
    rng = np.random.default_rng(1)
    t0 = time.perf_counter()
    losses = []
    for step in range(args.steps):
        seeds = rng.integers(0, topo.node_count, args.batch_size)
        batch = sampler.sample(seeds)
        x = to_torch(feature[np.asarray(batch.n_id)])
        blocks = to_dgl_blocks(batch) if have_dgl else block_specs(batch)
        out = model(blocks, x)
        y = torch.from_numpy(labels[seeds].astype(np.int64))
        loss = F.cross_entropy(out[: args.batch_size], y)
        opt.zero_grad()
        loss.backward()
        opt.step()
        losses.append(float(loss))
        if step % 10 == 0:
            print(f"step {step}: loss {loss:.3f}")
    dt = time.perf_counter() - t0
    print(f"{args.steps} steps in {dt:.1f}s; loss {losses[0]:.3f} -> "
          f"{np.mean(losses[-5:]):.3f} "
          f"({'dgl blocks' if have_dgl else 'block_specs fallback'})")
    assert np.mean(losses[-5:]) < losses[0]


if __name__ == "__main__":
    main()
