"""ogbn-products GraphSAGE training — the flagship example.

TPU-native counterpart of
``/root/reference/examples/pyg/ogbn_products_sage_quiver.py`` (quality bar
from that file's header: test acc ~0.787).  Shows the same "3-line swap"
shape: build CSRTopo -> GraphSageSampler -> Feature, then a normal training
loop; everything device-side is jitted.

Runs on the real dataset when OGB + the data are available
(``--root``), otherwise generates a synthetic products-scale graph so the
pipeline is exercisable anywhere (no-egress environments included).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse
import time

import numpy as np
import jax
import jax.numpy as jnp
import optax

from quiver_tpu import CSRTopo, Feature, GraphSageSampler
from quiver_tpu.models import GraphSAGE
from quiver_tpu.parallel import TrainState, make_train_step


def load_dataset(root, synthetic_nodes=200_000, force_synthetic=False):
    try:
        if force_synthetic:
            raise ImportError("--force-synthetic")
        from ogb.nodeproppred import NodePropPredDataset

        ds = NodePropPredDataset("ogbn-products", root=root)
        graph, labels = ds[0]
        split = ds.get_idx_split()
        src, dst = graph["edge_index"]
        # symmetrize like PyG's to_undirected
        s = np.concatenate([src, dst])
        d = np.concatenate([dst, src])
        topo = CSRTopo(edge_index=np.stack([s, d]))
        return (topo, graph["node_feat"].astype(np.float32),
                labels.squeeze().astype(np.int32),
                split["train"], split["valid"], split["test"], 47)
    except Exception as e:
        print(f"[synthetic fallback: {e}]")
        rng = np.random.default_rng(0)
        n, n_cls = synthetic_nodes, 47
        comm = rng.integers(0, n_cls, n)
        deg = np.maximum(rng.lognormal(2.5, 1.0, n), 1).astype(np.int64)
        src = np.repeat(np.arange(n), deg)
        # 70% intra-community edges for learnability
        intra = rng.random(len(src)) < 0.7
        dst = np.where(
            intra,
            (src + rng.integers(1, 50, len(src)) * n_cls) % n,
            rng.integers(0, n, len(src)),
        )
        topo = CSRTopo(edge_index=np.stack([src, dst]))
        feat = np.eye(n_cls, dtype=np.float32)[comm]
        feat = np.concatenate(
            [feat, rng.normal(0, 0.5, (n, 100 - n_cls)).astype(np.float32)],
            axis=1,
        )
        idx = rng.permutation(n)
        return (topo, feat, comm.astype(np.int32),
                idx[: n // 2], idx[n // 2: n * 3 // 4], idx[n * 3 // 4:],
                n_cls)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--root", default="/data/products")
    ap.add_argument("--synthetic-nodes", type=int, default=200_000,
                    help="fallback graph size when OGB data is absent")
    ap.add_argument("--force-synthetic", action="store_true",
                    help="skip the OGB path outright (deterministic smoke)")
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--batch-size", type=int, default=1024)
    ap.add_argument("--cache", default="200M",
                    help="device feature-cache budget (quiver.Feature)")
    ap.add_argument("--dp", action="store_true",
                    help="data-parallel over all devices (the reference's "
                         "multi-GPU table: 11.1s -> 3.25s on 1 -> 4 GPUs)")
    args = ap.parse_args()

    topo, feat, labels, train_idx, valid_idx, _, n_cls = load_dataset(
        args.root, synthetic_nodes=args.synthetic_nodes,
        force_synthetic=args.force_synthetic,
    )
    print(f"graph: {topo.node_count:,} nodes, {topo.edge_count:,} edges")

    # ---- the 3-line quiver swap ----------------------------------------
    sampler = GraphSageSampler(topo, sizes=[15, 10, 5])
    feature = Feature(device_cache_size=args.cache,
                      csr_topo=topo).from_cpu_tensor(feat)
    # --------------------------------------------------------------------

    model = GraphSAGE(hidden=256, out_dim=n_cls, num_layers=3)
    tx = optax.adam(3e-3)
    B = args.batch_size

    seeds0 = train_idx[:B]
    b0 = sampler.sample(seeds0)
    x0 = feature[np.asarray(b0.n_id)]
    params = model.init(jax.random.PRNGKey(0), x0, b0.layers)
    state = TrainState.create(params, tx)

    if args.dp:
        from quiver_tpu.utils.mesh import make_mesh

        mesh = make_mesh(("data",))
        ndev = int(mesh.shape["data"])
        dp_step = make_train_step(
            lambda p, x, blocks, train=False, rngs=None: model.apply(
                p, x, blocks, train=train, rngs=rngs
            ),
            tx, mesh=mesh,
        )
        print(f"data-parallel over {ndev} devices")
        rng = np.random.default_rng(1)
        for epoch in range(args.epochs):
            order = rng.permutation(len(train_idx))
            t0 = time.perf_counter()
            n_rounds = len(train_idx) // (B * ndev)
            loss = None
            for r in range(n_rounds):
                parts = []
                for d in range(ndev):
                    seeds = train_idx[order[(r * ndev + d) * B:
                                            (r * ndev + d + 1) * B]]
                    bt = sampler.sample(
                        seeds, key=jax.random.PRNGKey(r * ndev + d))
                    parts.append((bt, feature[np.asarray(bt.n_id)],
                                  jnp.asarray(labels[seeds])))
                xs = jnp.stack([p[1] for p in parts])
                blocks = jax.tree_util.tree_map(
                    lambda *ls: jnp.stack(ls), *[p[0].layers for p in parts]
                )
                labs = jnp.stack([p[2] for p in parts])
                masks = jnp.ones((ndev, B), bool)
                state, loss = dp_step(state, xs, blocks, labs, masks,
                                      jax.random.PRNGKey(r))
            jax.block_until_ready(loss)
            print(f"epoch {epoch}: {time.perf_counter() - t0:.2f}s "
                  f"({n_rounds} rounds x {ndev} replicas x {B}), "
                  f"loss {float(loss):.4f}")
        return

    step = make_train_step(
        lambda p, x, blocks, train=False, rngs=None: model.apply(
            p, x, blocks, train=train, rngs=rngs
        ),
        tx,
    )

    # fully-cached features unlock the fused pipeline: sample + gather +
    # train in ONE jit, no host work in the steady-state loop
    fused = None
    if feature.cache_count >= feature.node_count:
        from quiver_tpu.pipeline import make_fused_train_step

        fused = make_fused_train_step(
            sampler, feature,
            lambda p, x, blocks, train=False, rngs=None: model.apply(
                p, x, blocks, train=train, rngs=rngs
            ), tx,
        )
        print("using fused on-device pipeline")

    rng = np.random.default_rng(1)
    ones = jnp.ones((B,), bool)
    for epoch in range(args.epochs):
        order = rng.permutation(len(train_idx))
        t0 = time.perf_counter()
        losses = []
        n_batches = len(train_idx) // B
        for i in range(n_batches):
            seeds = train_idx[order[i * B: (i + 1) * B]]
            if fused is not None:
                state, loss = fused(state, jnp.asarray(seeds, jnp.int32),
                                    jnp.asarray(labels[seeds]), ones,
                                    jax.random.PRNGKey(10_000 + i))
            else:
                batch = sampler.sample(seeds, key=jax.random.PRNGKey(
                    epoch * n_batches + i))
                x = feature[np.asarray(batch.n_id)]
                lab = jnp.asarray(labels[seeds])
                state, loss = step(state, x, batch.layers, lab, ones,
                                   jax.random.PRNGKey(10_000 + i))
            losses.append(loss)
        jax.block_until_ready(losses[-1])
        dt = time.perf_counter() - t0
        print(f"epoch {epoch}: {dt:.2f}s, loss {np.mean(jax.device_get(jnp.stack(losses))):.4f}")

        # quick validation accuracy on a few batches
        correct = total = 0
        for i in range(min(10, len(valid_idx) // B)):
            seeds = valid_idx[i * B: (i + 1) * B]
            batch = sampler.sample(seeds)
            x = feature[np.asarray(batch.n_id)]
            logits = model.apply(state.params, x, batch.layers)
            pred = np.asarray(jnp.argmax(logits, -1))
            correct += (pred == labels[seeds]).sum()
            total += len(seeds)
        if total:
            print(f"  val acc (sampled): {correct / total:.4f}")

    # exact layer-wise inference for the final score (parity with the
    # reference's full-graph eval) — feasible when features fit HBM
    if feature.cache_count >= feature.node_count:
        from quiver_tpu.models import full_graph_inference

        x_full = feature.hot
        if feature.feature_order is not None:
            # hot rows are cache-ordered; inference needs old-id order
            x_full = x_full[jnp.asarray(feature.feature_order)]
        logits = full_graph_inference(
            state.params, x_full, topo.indptr, topo.indices, 3
        )
        pred = np.asarray(jnp.argmax(logits, -1))
        acc = (pred[valid_idx] == labels[valid_idx]).mean()
        print(f"full-graph val acc: {acc:.4f}")


if __name__ == "__main__":
    main()
