"""Train GraphSAGE on a graph whose edge array exceeds the HBM budget,
on ONE chip — quiver_tpu's UVA mode.

Reference scenario: ``examples/pyg/ogbn_products_sage_quiver.py`` with
``mode="UVA"`` — the CSR lives in pinned host memory and the GPU samples
it in place.  Here the byte-budgeted hot rows (degree-ordered) sample on
the TPU while the cold tail samples on the native host sampler,
overlapped per hop (``quiver_tpu/uva.py``).

Synthetic by default so it runs anywhere:

    python examples/big_graph_single_chip.py --nodes 500000 --deg 20 \
        --graph-budget 20M --feature-budget 100M
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse
import time

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=200_000)
    ap.add_argument("--deg", type=int, default=15)
    ap.add_argument("--dim", type=int, default=64)
    ap.add_argument("--classes", type=int, default=16)
    ap.add_argument("--graph-budget", default="10M",
                    help="HBM byte budget for the edge array's hot tier")
    ap.add_argument("--feature-budget", default="40M",
                    help="HBM byte budget for the feature hot tier")
    ap.add_argument("--batch-size", type=int, default=512)
    ap.add_argument("--steps", type=int, default=30)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import optax

    from quiver_tpu import CSRTopo, Feature, GraphSageSampler, SeedLoader
    from quiver_tpu.models import GraphSAGE
    from quiver_tpu.parallel import TrainState, make_train_step
    from quiver_tpu.utils.synthetic import synthetic_csr

    rng = np.random.default_rng(0)
    indptr, indices = synthetic_csr(args.nodes, args.nodes * args.deg, 0)
    topo = CSRTopo(indptr=indptr, indices=indices)
    feat = rng.normal(size=(args.nodes, args.dim)).astype(np.float32)
    labels = rng.integers(0, args.classes, args.nodes)
    train_idx = rng.choice(args.nodes, args.nodes // 10, replace=False)

    # BOTH big arrays get budgeted hot tiers: edges via UVA mode,
    # features via the cached Feature store
    sampler = GraphSageSampler(topo, [15, 10, 5], mode="UVA",
                               uva_budget=args.graph_budget)
    feature = Feature(device_cache_size=args.feature_budget,
                      csr_topo=topo).from_cpu_tensor(feat)
    model = GraphSAGE(hidden=128, out_dim=args.classes, num_layers=3)
    tx = optax.adam(3e-3)

    loader = SeedLoader(train_idx, sampler, feature, labels=labels,
                        batch_size=args.batch_size)
    b0, x0, y0, m0 = next(iter(loader))
    print("uva split:", sampler._uva.stats())
    params = model.init(jax.random.PRNGKey(0), x0, b0.layers)
    state = TrainState.create(params, tx)
    step = make_train_step(
        lambda p, x, blocks, train=False, rngs=None: model.apply(
            p, x, blocks, train=train, rngs=rngs), tx)

    t0 = time.perf_counter()
    n = 0
    for batch, x, y, m in loader:
        state, loss = step(state, x, batch.layers, y, m,
                           jax.random.PRNGKey(n))
        n += 1
        if n >= args.steps:
            break
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0
    print(f"{n} steps in {dt:.2f}s ({dt / n * 1e3:.0f} ms/step), "
          f"final loss {float(loss):.3f}")


if __name__ == "__main__":
    main()
