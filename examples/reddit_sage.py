"""Reddit GraphSAGE — BASELINE config #1.

TPU-native counterpart of ``/root/reference/examples/pyg/reddit_quiver.py``
(2-layer SAGE, fanout [25, 10]).  Real dataset if PyG/OGB data is present
at ``--root``; synthetic Reddit-scale otherwise.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse
import time

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--batch-size", type=int, default=1024)
    ap.add_argument("--cache", default="400M")
    ap.add_argument("--synthetic-nodes", type=int, default=232_965)
    ap.add_argument("--synthetic-classes", type=int, default=41)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import optax

    from quiver_tpu import CSRTopo, Feature, GraphSageSampler
    from quiver_tpu.models import GraphSAGE
    from quiver_tpu.parallel import TrainState, make_train_step, Prefetcher
    from quiver_tpu.utils.synthetic import community_graph

    n_cls = args.synthetic_classes
    topo, feat, labels = community_graph(
        args.synthetic_nodes, n_cls, intra_deg=30, inter_deg=10,
        feat_extra=602 - n_cls,  # Reddit dim = 602
    )
    train_idx = np.random.default_rng(0).permutation(
        topo.node_count
    )[: topo.node_count // 2]
    print(f"graph: {topo.node_count:,} nodes, {topo.edge_count:,} edges")

    sampler = GraphSageSampler(topo, sizes=[25, 10])
    feature = Feature(device_cache_size=args.cache,
                      csr_topo=topo).from_cpu_tensor(feat)

    model = GraphSAGE(hidden=256, out_dim=n_cls, num_layers=2)
    tx = optax.adam(1e-2)
    B = args.batch_size
    b0 = sampler.sample(train_idx[:B])
    params = model.init(jax.random.PRNGKey(0),
                        feature[np.asarray(b0.n_id)], b0.layers)
    state = TrainState.create(params, tx)
    step = make_train_step(
        lambda p, x, blocks, train=False, rngs=None: model.apply(
            p, x, blocks, train=train, rngs=rngs
        ), tx,
    )
    ones = jnp.ones((B,), bool)
    n_batches = len(train_idx) // B
    rng = np.random.default_rng(1)

    def make_batch(i):
        seeds = train_idx[i * B: (i + 1) * B]
        batch = sampler.sample(seeds, key=jax.random.PRNGKey(i))
        return batch, feature[np.asarray(batch.n_id)], \
            jnp.asarray(labels[seeds]), seeds

    for epoch in range(args.epochs):
        rng.shuffle(train_idx)
        t0 = time.perf_counter()
        correct = total = 0
        for batch, x, lab, seeds in Prefetcher(range(n_batches),
                                               make_batch, depth=2):
            state, loss = step(state, x, batch.layers, lab, ones,
                               jax.random.PRNGKey(epoch))
        jax.block_until_ready(loss)
        dt = time.perf_counter() - t0
        # sampled train accuracy on last batch
        logits = model.apply(state.params, x, batch.layers)
        acc = float((jnp.argmax(logits, -1) == lab).mean())
        print(f"epoch {epoch}: {dt:.2f}s, loss {float(loss):.4f}, "
              f"batch acc {acc:.3f}")


if __name__ == "__main__":
    main()
