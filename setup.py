"""Build/packaging for quiver_tpu.

Reference parity: the reference's ``setup.py`` + CMake build
(``/root/reference/setup.py``, ``CMakeLists.txt``) compile a CUDA torch
extension; here the native piece is a plain C++ shared library (ctypes ABI,
no pybind11) compiled with g++ — either at install time (this file) or
lazily on first use (``quiver_tpu/cpp/native.py``).
"""

import subprocess
from pathlib import Path

from setuptools import setup, find_packages
from setuptools.command.build_py import build_py


class BuildWithNative(build_py):
    def run(self):
        src = Path(__file__).parent / "quiver_tpu/cpp/csrc/quiver_cpu.cpp"
        out = Path(__file__).parent / "quiver_tpu/cpp/libquiver_cpu.so"
        try:
            subprocess.run(
                ["g++", "-O3", "-std=c++17", "-shared", "-fPIC",
                 "-pthread", "-o", str(out), str(src)],
                check=True,
            )
        except Exception as e:  # lazy build still available at runtime
            print(f"[setup] native build skipped: {e}")
        super().run()


setup(
    name="quiver-tpu",
    version="0.1.0",
    description=(
        "TPU-native graph-learning data layer: neighbor sampling, cached "
        "feature store, distributed feature exchange, GNN serving"
    ),
    packages=find_packages(include=["quiver_tpu", "quiver_tpu.*"]),
    package_data={"quiver_tpu.cpp": ["csrc/*.cpp", "*.so"]},
    python_requires=">=3.10",
    install_requires=["jax", "flax", "optax", "numpy"],
    cmdclass={"build_py": BuildWithNative},
)
