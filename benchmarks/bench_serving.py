"""Serving latency/throughput benchmark (p50/p99/rps).

Mirrors the reference's serving claims (README.md:66-70: 35x lower latency,
8x throughput vs DGL/PyG on a 2-GPU server; tp99 figure).  Drives the full
RequestBatcher -> HybridSampler -> InferenceServer_Debug pipeline with a
Poisson open-loop client over a synthetic Reddit-scale graph.
"""

import argparse
import queue
import sys
import time

sys.path.insert(0, ".")

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=232_965)   # Reddit scale
    ap.add_argument("--edges", type=int, default=11_606_919)
    ap.add_argument("--dim", type=int, default=128)
    ap.add_argument("--fanout", type=int, nargs="+", default=[25, 10])
    ap.add_argument("--requests", type=int, default=200)
    ap.add_argument("--rps", type=float, default=100.0)
    ap.add_argument("--batch-max", type=int, default=64)
    args = ap.parse_args()

    import jax

    from bench import build_graph
    from quiver_tpu import (
        CSRTopo, Feature, GraphSageSampler, RequestBatcher, HybridSampler,
        InferenceServer_Debug, generate_neighbour_num,
    )
    from quiver_tpu.serving import ServingRequest
    from quiver_tpu.models import GraphSAGE

    rng = np.random.default_rng(0)
    indptr, indices = build_graph(args.nodes, args.edges)
    topo = CSRTopo(indptr=indptr, indices=indices)
    feat = rng.normal(size=(args.nodes, args.dim)).astype(np.float32)
    feature = Feature(device_cache_size="100G").from_cpu_tensor(feat)
    tpu_sampler = GraphSageSampler(topo, args.fanout)
    cpu_sampler = GraphSageSampler(topo, args.fanout, mode="CPU")
    model = GraphSAGE(hidden=256, out_dim=41, num_layers=len(args.fanout),
                      dropout=0.0)

    seeds0 = np.arange(args.batch_max, dtype=np.int64)
    b0 = tpu_sampler.sample(seeds0)
    x0 = feature[np.asarray(b0.n_id)]
    params = model.init(jax.random.PRNGKey(0), x0, b0.layers)
    apply_fn = jax.jit(lambda p, x, blocks: model.apply(p, x, blocks))
    nn_num = generate_neighbour_num(topo, args.fanout, mode="expected")
    stream = queue.Queue()
    rb = RequestBatcher([stream], neighbour_num=nn_num,
                        threshold=float(np.percentile(nn_num, 30) * 4),
                        mode="Auto").start()
    hs = HybridSampler(cpu_sampler, rb.cpu_batched_queue,
                       num_workers=4).start()
    server = InferenceServer_Debug(
        tpu_sampler, feature, apply_fn, params,
        rb.device_batched_queue, hs.sampled_queue,
    )
    server.warmup()  # every bucket compiled before traffic: p99 is real
    server.start()

    # open-loop Poisson arrivals
    t_next = time.perf_counter()
    for i in range(args.requests):
        t_next += rng.exponential(1.0 / args.rps)
        now = time.perf_counter()
        if t_next > now:
            time.sleep(t_next - now)
        ids = rng.integers(0, args.nodes, rng.integers(1, args.batch_max))
        stream.put(ServingRequest(ids=ids, client=0, seq=i))

    got = 0
    while got < args.requests:
        server.result_queue.get(timeout=120)
        got += 1
    stats = server.stats()
    rb.stop(); hs.stop(); server.stop()
    print(
        f"served {stats['count']} requests @ {args.rps} rps offered | "
        f"avg {stats['avg_latency_ms']:.1f} ms, "
        f"p50 {stats['p50_latency_ms']:.1f} ms, "
        f"p99 {stats['p99_latency_ms']:.1f} ms, "
        f"throughput {stats['throughput_rps']:.1f} rps"
    )


if __name__ == "__main__":
    main()
