"""Replica-failover chaos harness (``make fleet-chaos``).

Stands up a REAL 3-replica fleet — one ingest leader plus two read
followers, each a separate OS process booted through the fleet join
path (shared checkpoints + shared JAX persistent compilation cache,
warmup, ``seal``) — then drives seeded zipfian traffic through a
:class:`~quiver_tpu.fleet.router.FleetRouter` in three phases
(baseline → burst → cool) and, mid-burst, ``kill -9``s one follower.
No drain, no warning: the next poll of its socket fails and the
router's re-dispatch path is the only thing standing between an
in-flight request and silence.

The contract this harness proves (asserted by ``tests/test_fleet.py``
on the returned report, and by ``--check`` from the command line):

  * **zero lost answers** — every request submitted to the router is
    answered: ``ok``, a typed shed, or a typed
    ``NoReplicaAvailable``; ``unanswered`` is identically 0 across all
    phases (the kill included);
  * **bounded failover impact** — burst-phase p99 (which contains the
    kill) stays under ``2×`` the baseline p99, and the cool phase
    returns to baseline-grade latency;
  * **warm rejoin** — the killed replica restarts under the same id
    and the shared caches: its boot must HIT the persistent
    compilation cache (``pcache_hits > 0``), write zero new cache
    entries, survive post-warmup traffic under a sealed registry, and
    its staleness watermark must return to 0 (≤ the configured bound)
    once serving;
  * **fleet-wide observability** (docs/OBSERVABILITY.md) — the router
    runs with federation on and every process records its timeline; a
    seeded ``fleet.serve`` fault on one follower forces redispatches
    whose trace_id lands on TWO replica timelines.  After the run the
    harness exports the merged Perfetto trace via
    ``timeline.export_fleet`` and asserts it is loadable, contains
    events from ≥ 2 processes, and shows both dispatch attempts of one
    redispatched trace_id on two different replica tracks — and that
    ``/debug/fleet/trace``-style reconstruction finds the story.

Three further autonomy scenarios ride behind ``--scenario`` (the
default remains the follower-kill story above; ``--scenario all`` runs
everything):

  * ``leader`` — the LEADER is ``kill -9``ed mid-burst with
    ``fleet_election=on``: the most caught-up follower must promote
    itself under a strictly higher fenced epoch, the promoted WAL
    frontier must cover every append the dead leader acked (zero acked
    loss), writes must flow through the promoted lane, and an append
    stamped with the deposed epoch must be refused by the fence;
  * ``walstream`` — followers with PRIVATE recovery roots (no shared
    WAL directory, no shared checkpoints) replicate purely over the
    leader's socket WAL stream, survive a seeded mid-stream cut by
    resuming from their committed LSN, and converge to staleness ≤
    ``fleet_max_staleness_lsn``;
  * ``autoscale`` — a compressed diurnal cycle with a 10× burst: the
    federation-driven autoscaler, taught two synthetic prior days,
    must warm-spawn ≥ 1 replica BEFORE the burst peak, hold gold p99
    under 2× baseline through it, drain back down after, and never
    flap inside a cooldown window.

The model stage is deliberately tiny (default replica service: a
versioned graph touch) so the harness runs on CPU in minutes; the
router, membership, WAL shipping, breakers, and the kill are all the
production code paths.  On CPU the latency numbers are a rehearsal —
``bench.py`` stamps the section ``source: cpu_rehearsal`` so nothing
quotes them as device truth; the *loss and rejoin* assertions are
backend-independent and hold everywhere.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, ".")

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
N_NODES = 64

# gold is the hot class (priority 3 >= fleet_hot_priority default):
# its zipfian head traffic routes power-of-two-choices
TENANTS = ("gold:rate=2000,burst=500,weight=8,priority=3;"
           "silver:rate=1000,burst=250,weight=4,priority=2;"
           "bronze:rate=500,burst=125,weight=2,priority=1")
_TENANT_MIX = ("gold", "gold", "gold", "silver", "silver", "bronze")

# one child program serves both roles; argv decides.  The leader boots
# the recovery tier, seeds + checkpoints the shared root, then ingests
# steadily so WAL shipping stays live during the run.  Followers join
# through checkpoint restore + WAL tail.  Both warm a sampler and seal
# at retrace budget 0 — a cold compile after warmup aborts the child.
# Under ``fleet_election=on`` a follower that wins an election flips to
# the ingest loop by itself; every leader (original or promoted)
# publishes its acked WAL frontier to ``acked-<rid>.json`` so the
# parent can prove zero acked loss across a kill -9.  A
# ``drain-<rid>`` trigger file makes the child drain and exit — the
# autoscaler's scale-down choreography.
_REPLICA_CHILD = r"""
import glob, json, os, sys, time
import numpy as np
import quiver_tpu.config as config_mod

(root, fleet_dir, cache_dir, rid, role, ingest_rps, serve_every,
 chaos_seed, walstream_fault_after) = sys.argv[1:10]
# budget 4, not 0: the stream sampler legitimately builds one program
# per delta-overlay BUCKET it serves (geometric growth schedule), and
# live ingest crosses a few buckets after warmup.  The seal still
# gates: anything beyond bucket growth crashes the replica.
config_mod.update(recovery_dir=root, recovery_cache_dir=cache_dir,
                  recovery_retrace_budget=4)

from quiver_tpu import GraphSageSampler
from quiver_tpu.fleet import FleetReplica
from quiver_tpu.recovery.registry import get_program_registry
from quiver_tpu.resilience import chaos
from quiver_tpu.stream import StreamingGraph
from quiver_tpu.telemetry import flightrec, timeline
from quiver_tpu.utils.rng import make_key
from quiver_tpu.utils.topology import CSRTopo

N = 64

# every process records its own timeline; the parent's federation
# pulls /debug/timeline from each and merges them onto one wall clock
timeline.enable()
plan = chaos.ChaosPlan(seed=int(chaos_seed))
armed = False
if int(serve_every) > 0:
    # deterministic serve faults on THIS follower: accepted requests
    # answer `unavailable` after trace rehydration, so the router
    # redispatches and the same trace_id lands on a second replica's
    # timeline — the cross-process story the merged trace must show
    plan.fail("fleet.serve", times=None, after=1, every=int(serve_every))
    armed = True
if int(walstream_fault_after) > 0:
    # one mid-stream cut on the leader's walstream endpoint: the Nth
    # shipped frame dies in flight, the socket drops, and the follower
    # must resume from its committed LSN on reconnect
    plan.fail("fleet.walstream.send", after=int(walstream_fault_after),
              times=1)
    armed = True
if armed:
    chaos.install(plan)

def factory():
    src = np.arange(N, dtype=np.int64)
    dst = (src + 1) % N
    return StreamingGraph(CSRTopo(edge_index=np.stack([src, dst])),
                          delta_capacity=65536)

holder = {}

def warmup(graph):
    s = GraphSageSampler(graph, sizes=[3, 2], gather_mode="xla",
                         dedup="none")
    s.sample(np.arange(8), key=make_key(0))
    holder["sampler"] = s
    holder["graph"] = graph

def service(ids, tenant):
    # drive the WARMED sampler (fixed shape: no recompile under seal)
    # and stamp the stage span into the active fleet trace
    t0 = time.perf_counter()
    holder["sampler"].sample(np.arange(8), key=make_key(0))
    flightrec.event("sample", {"seconds": time.perf_counter() - t0})
    g = holder.get("graph")
    return {"n": len(ids),
            "version": int(g.version) if g is not None else -1}

before = set(glob.glob(os.path.join(cache_dir, "**"), recursive=True))
t0 = time.perf_counter()
rep = FleetReplica(rid, fleet_dir=fleet_dir, root=root,
                   graph_factory=factory, role=role,
                   warmup=warmup, seal=True, service_fn=service).boot()
rep.expose_metrics()
if role == "leader":
    # seed + checkpoint so followers have a restore point
    for i in range(64):
        rep.lane.submit([i % N], [(i * 7 + 3) % N])
    for _ in range(64):
        _u, res = rep.lane.results.get(timeout=30)
        if isinstance(res, Exception):
            raise res
    rep.manager.checkpoint(timeout=30)
# post-seal traffic through the warmed sampler: budget 0 makes any
# cold compile after warmup a crash, not a p99 cliff
for k in range(1, 4):
    holder["sampler"].sample(np.arange(8), key=make_key(k))
reg = get_program_registry()
after = set(glob.glob(os.path.join(cache_dir, "**"), recursive=True))
print(json.dumps({
    "ready": True, "replica": rid, "role": role,
    "boot_seconds": round(time.perf_counter() - t0, 3),
    "pcache_hits": reg.persistent_cache_hits,
    "new_cache_files": len(after - before),
    "sampler_builds": reg.stats().get("sampler", {}).get("builds", 0),
}), flush=True)

ack_path = os.path.join(fleet_dir, "acked-" + rid + ".json")
drain_path = os.path.join(fleet_dir, "drain-" + rid)

def write_ack(i):
    # atomic so the parent never reads a torn frontier; this file is
    # the "what did the dead leader ack" evidence after a kill -9
    tmp = ack_path + ".tmp"
    with open(tmp, "w") as f:
        f.write(json.dumps({"i": i,
                            "wal_next_lsn": int(rep.manager.wal.next_lsn)}))
    os.replace(tmp, ack_path)

period = 1.0 / max(float(ingest_rps), 1.0)
i = 64
while True:
    if os.path.exists(drain_path):
        rep.drain()
        rep.stop()
        sys.exit(0)
    # a follower that won an election flips to the ingest loop: the
    # promoted lane is the proof that writes flow post-failover
    if rep.role == "leader" and rep.lane is not None:
        rep.lane.submit([i % N], [(i * 7 + 3) % N])
        _u, res = rep.lane.results.get(timeout=30)
        if isinstance(res, Exception):
            raise res
        write_ack(i)
        i += 1
        time.sleep(period)
    else:
        time.sleep(0.05)
"""


def _spawn(root, fleet_dir, cache_dir, rid, role, ingest_rps=100.0,
           serve_fault_every=0, chaos_seed=0, walstream_fault_after=0,
           extra_env=None):
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO,
               PYTHONUNBUFFERED="1",
               QUIVER_TPU_FLEET_SHIP_POLL_MS="10",
               QUIVER_TPU_FLEET_SHIP_GRACE_MS="60",
               QUIVER_TPU_FLEET_HEARTBEAT_S="0.2")
    env.update(extra_env or {})
    return subprocess.Popen(
        [sys.executable, "-c", _REPLICA_CHILD, root, fleet_dir,
         cache_dir, rid, role, str(ingest_rps),
         str(int(serve_fault_every)), str(int(chaos_seed)),
         str(int(walstream_fault_after))],
        cwd=REPO, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True)


def _wait_ready(proc, timeout=300.0):
    """Read child stdout until its READY JSON line."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        line = proc.stdout.readline()
        if not line:
            raise RuntimeError(
                f"replica child died during boot:\n{proc.stderr.read()}")
        try:
            doc = json.loads(line)
        except ValueError:
            continue
        if doc.get("ready"):
            return doc
    raise TimeoutError("replica child never reported ready")


def _wait_serving(directory, rid, timeout=120.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        info = directory.get(rid)
        if info is not None and info.state == "serving" and \
                info.fresh(directory.heartbeat_timeout_s):
            return info
        time.sleep(0.05)
    raise TimeoutError(f"replica {rid} never reached serving")


def _percentile(xs, p):
    if not xs:
        return 0.0
    return float(np.percentile(np.asarray(xs, dtype=np.float64), p))


def _reap(proc):
    if proc is None or proc.poll() is not None:
        return
    proc.terminate()
    try:
        proc.wait(timeout=10)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.wait(timeout=10)


def _observability(router, fed, trace_file: str) -> dict:
    """Export the merged fleet trace and distil the evidence
    :func:`check` asserts on: trace loadable, events from ≥ 2
    processes, one redispatched trace_id with both attempts recorded
    and visible on two replica tracks, reconstruction joins."""
    from quiver_tpu.telemetry import timeline

    timeline.export_fleet(trace_file)
    with open(trace_file) as f:
        doc = json.load(f)
    track: dict = {}
    events = []
    for e in doc.get("traceEvents", ()):
        if e.get("ph") == "M":
            if e.get("name") == "process_name":
                track[e["pid"]] = e["args"]["name"]
        else:
            events.append(e)
    obs: dict = {
        "trace_path": trace_file,
        "trace_events": len(events),
        "trace_processes": sorted({track.get(e["pid"], str(e["pid"]))
                                   for e in events}),
    }
    by_tid: dict = {}
    for e in events:
        tid = (e.get("args") or {}).get("trace_id")
        if tid:
            by_tid.setdefault(tid, set()).add(
                track.get(e["pid"], str(e["pid"])))
    redis = [h for h in router.hop_records(limit=router.hop_capacity)
             if len(h.get("attempts", ())) >= 2]
    obs["redispatched_hops"] = len(redis)
    chosen = None
    for h in reversed(redis):  # newest first: its events are retained
        tracks = by_tid.get(h["trace_id"], set())
        if sum(1 for t in tracks if t.startswith("replica")) >= 2:
            chosen = h
            break
    if chosen is None and redis:
        chosen = redis[-1]
    if chosen is not None:
        tid = chosen["trace_id"]
        tracks = sorted(by_tid.get(tid, ()))
        obs["redispatched_trace_id"] = tid
        obs["redispatch_attempts"] = [
            {"replica": a["replica"], "outcome": a["outcome"]}
            for a in chosen["attempts"]]
        obs["trace_tracks"] = tracks
        obs["trace_replica_tracks"] = [
            t for t in tracks if t.startswith("replica")]
        recon = fed.reconstruct(tid)
        obs["reconstruction_found"] = bool(recon.get("found"))
        obs["reconstructed_replicas"] = sorted(recon.get("replicas", ()))
    return obs


def run_fleet_chaos(smoke: bool = False, seed: int = 0,
                    workdir: str | None = None,
                    trace_path: str | None = None) -> dict:
    """Run the failover scenario; returns the structured report."""
    from quiver_tpu.fleet import FleetRouter, MembershipDirectory
    from quiver_tpu.resilience.errors import NoReplicaAvailable
    from quiver_tpu.resilience.qos import (QoSController, install_qos,
                                           parse_tenant_spec)
    from quiver_tpu import telemetry
    from quiver_tpu.telemetry import timeline

    rng = np.random.default_rng(seed)
    tmp = workdir or tempfile.mkdtemp(prefix="fleet_chaos_")
    root = os.path.join(tmp, "dur")
    fleet_dir = os.path.join(tmp, "fleet")
    cache_dir = os.path.join(tmp, "pcache")
    os.makedirs(cache_dir, exist_ok=True)

    n_req = {"baseline": 200, "burst": 400, "cool": 200} if smoke else \
            {"baseline": 600, "burst": 1200, "cool": 600}

    install_qos(QoSController(classes=parse_tenant_spec(TENANTS),
                              default="bronze", ingest="bronze"))
    directory = MembershipDirectory(fleet_dir,
                                    heartbeat_timeout_s=2.0)
    procs: dict = {}
    report: dict = {"seed": seed, "smoke": smoke,
                    "phases": {}, "failover": {}, "rejoin": {}}
    t_start = time.perf_counter()
    timeline_was_on = timeline.on()
    timeline.enable()
    try:
        procs["r0"] = _spawn(root, fleet_dir, cache_dir, "r0", "leader")
        boot0 = _wait_ready(procs["r0"])
        # r1 carries the seeded serve-fault plan: ~1/4 of its admitted
        # requests answer `unavailable` after trace rehydration, so the
        # merged trace shows redispatched ids on two replica tracks
        procs["r1"] = _spawn(root, fleet_dir, cache_dir, "r1",
                             "follower", serve_fault_every=4,
                             chaos_seed=seed)
        procs["r2"] = _spawn(root, fleet_dir, cache_dir, "r2",
                             "follower")
        boot1 = _wait_ready(procs["r1"])
        boot2 = _wait_ready(procs["r2"])
        for rid in ("r0", "r1", "r2"):
            _wait_serving(directory, rid)
        report["cold_boots"] = [boot0, boot1, boot2]

        # 64 partitions (not the 8-partition default): the 3-member
        # ring must give EVERY replica ownership of some partitions, so
        # the faulted follower actually sees traffic to redispatch
        router = FleetRouter(directory, partitions=64, scan_ttl_s=0.05,
                             request_timeout_s=2.0, federation=True)

        def drive(phase: str, count: int, kill_at: int | None = None):
            lat, counts = [], {"offered": 0, "ok": 0, "shed": 0,
                              "error": 0, "unroutable": 0,
                              "unanswered": 0}
            for i in range(count):
                if kill_at is not None and i == kill_at:
                    _kill9("r2")
                ids = [int(rng.zipf(1.7)) % N_NODES,
                       int(rng.integers(N_NODES))]
                tenant = _TENANT_MIX[int(rng.integers(len(_TENANT_MIX)))]
                counts["offered"] += 1
                t0 = time.perf_counter()
                try:
                    reply = router.request(ids, tenant=tenant, seq=i)
                    status = reply.get("status", "error")
                    counts["ok" if status == "ok" else
                           "shed" if status == "shed" else "error"] += 1
                except NoReplicaAvailable:
                    counts["unroutable"] += 1
                except Exception:
                    counts["unanswered"] += 1
                lat.append((time.perf_counter() - t0) * 1e3)
            counts["p50_ms"] = round(_percentile(lat, 50), 3)
            counts["p99_ms"] = round(_percentile(lat, 99), 3)
            report["phases"][phase] = counts

        def _kill9(rid: str):
            proc = procs[rid]
            os.kill(proc.pid, signal.SIGKILL)
            proc.wait(timeout=30)
            report["failover"]["kill_returncode"] = proc.returncode
            report["failover"]["killed"] = rid

        drive("baseline", n_req["baseline"])
        drive("burst", n_req["burst"], kill_at=n_req["burst"] // 3)

        # warm rejoin: same replica id, same shared caches
        t_rejoin = time.perf_counter()
        procs["r2"] = _spawn(root, fleet_dir, cache_dir, "r2",
                             "follower")
        rejoin = _wait_ready(procs["r2"])
        info = _wait_serving(directory, "r2")
        rejoin["rejoin_seconds"] = round(
            time.perf_counter() - t_rejoin, 3)
        rejoin["staleness_lsn_at_serving"] = info.staleness_lsn
        # the watermark must come back under the bound once serving
        from quiver_tpu.config import get_config

        bound = get_config().fleet_max_staleness_lsn
        deadline = time.time() + 30
        while time.time() < deadline:
            info = directory.get("r2")
            if info is not None and info.staleness_lsn <= bound:
                break
            time.sleep(0.05)
        rejoin["staleness_lsn_final"] = info.staleness_lsn
        rejoin["staleness_bound"] = bound
        rejoin["within_bound"] = info.staleness_lsn <= bound
        report["rejoin"] = rejoin

        drive("cool", n_req["cool"])

        # federation sweeps: harvest heartbeat clock pairs (≥ 2 ticks
        # apart so the offset estimator sees distinct pairs), scrape
        # every member, then export + dissect the merged fleet trace
        for _ in range(3):
            router.federation.scrape_once()
            time.sleep(0.3)
        report["observability"] = _observability(
            router, router.federation,
            trace_path or os.path.join(tmp, "fleet_trace.json"))

        base_p99 = report["phases"]["baseline"]["p99_ms"] or 1e-9
        report["failover"]["p99_ratio_burst_vs_baseline"] = round(
            report["phases"]["burst"]["p99_ms"] / base_p99, 3)
        report["failover"]["p99_ratio_cool_vs_baseline"] = round(
            report["phases"]["cool"]["p99_ms"] / base_p99, 3)
        snap = telemetry.snapshot()["counters"]
        report["failover"]["redispatches"] = sum(
            v for k, v in snap.items()
            if k.startswith("fleet_router_redispatch_total"))
        report["failover"]["unroutable_total"] = sum(
            v for k, v in snap.items()
            if k.startswith("fleet_router_unroutable_total"))
        report["lost_answers"] = sum(
            p["unanswered"] for p in report["phases"].values())
        report["elapsed_seconds"] = round(
            time.perf_counter() - t_start, 1)
        router.close()
    finally:
        if not timeline_was_on:
            timeline.disable()
        for proc in procs.values():
            _reap(proc)
        for proc in procs.values():
            if proc.stdout:
                proc.stdout.close()
            if proc.stderr:
                proc.stderr.close()
    try:
        import jax

        report["backend"] = jax.default_backend()
    except Exception:
        report["backend"] = "unknown"
    return report


def check(report: dict) -> list:
    """The acceptance criteria as data; returns failure strings."""
    fails = []
    if report.get("lost_answers", 1) != 0:
        fails.append(f"lost answers: {report.get('lost_answers')}")
    if report["failover"].get("kill_returncode") != -signal.SIGKILL:
        fails.append("replica was not SIGKILLed "
                     f"({report['failover'].get('kill_returncode')})")
    rejoin = report.get("rejoin", {})
    # warm = the boot HIT the shared compilation cache and survived the
    # sealed retrace budget (a crash would have failed _wait_ready).
    # new_cache_files stays informational: live ingest can cross a
    # delta bucket between cold boot and rejoin, making one fresh
    # compile legitimate.
    if not rejoin.get("pcache_hits", 0) > 0:
        fails.append("rejoin was cold: pcache_hits == 0")
    if not rejoin.get("within_bound", False):
        fails.append(f"staleness {rejoin.get('staleness_lsn_final')} "
                     f"over bound {rejoin.get('staleness_bound')}")
    ratio = report["failover"].get("p99_ratio_burst_vs_baseline", 99.0)
    if ratio >= 2.0:
        fails.append(f"failover p99 ratio {ratio} >= 2.0")
    # the merged failover trace: produced, loadable, cross-process, and
    # carrying one redispatched trace_id end to end
    obs = report.get("observability", {})
    if obs.get("trace_events", 0) <= 0:
        fails.append("merged fleet trace missing or empty")
    if len(obs.get("trace_processes", ())) < 2:
        fails.append("merged trace lacks events from >= 2 processes "
                     f"({obs.get('trace_processes')})")
    if len(obs.get("redispatch_attempts", ())) < 2:
        fails.append("no redispatched request with both dispatch "
                     "attempts recorded")
    if len(obs.get("trace_replica_tracks", ())) < 2:
        fails.append("redispatched trace_id not on two replica tracks "
                     f"({obs.get('trace_replica_tracks')})")
    if not obs.get("reconstruction_found", False):
        fails.append("cross-process trace reconstruction found no "
                     "record")
    return fails


# -------------------------------------------------- fleet autonomy
# election clocks for the leader-kill scenario: detection in ~1.2s,
# candidates stagger 0.4s per rank, fence re-checks on every append
_ELECTION_ENV = {
    "QUIVER_TPU_FLEET_ELECTION": "on",
    "QUIVER_TPU_FLEET_ELECTION_POLL_S": "0.1",
    "QUIVER_TPU_FLEET_ELECTION_STAGGER_S": "0.4",
    "QUIVER_TPU_FLEET_ELECTION_FENCE_RECHECK_S": "0",
    "QUIVER_TPU_FLEET_HEARTBEAT_TIMEOUT_S": "1.2",
}


def _scrape_counter_sum(directory, rid: str, name: str) -> float:
    """Sum one counter family straight off a replica's ``/metrics``."""
    import urllib.request

    from quiver_tpu.fleet import parse_prometheus_text

    info = directory.get(rid)
    if info is None:
        return 0.0
    port = int((info.detail or {}).get("metrics_port", 0) or 0)
    if not port:
        return 0.0
    with urllib.request.urlopen(
            f"http://{info.host}:{port}/metrics", timeout=5) as r:
        text = r.read().decode()
    scrape, _errs = parse_prometheus_text(text)
    return sum(v for (n, _l), v in scrape["counters"].items()
               if n == name)


def _drive_phases(router, rng, report, n_req, kill=None):
    """The shared request driver: zipfian traffic per phase, optional
    mid-burst kill callback, loss accounting identical to the failover
    scenario's contract."""
    from quiver_tpu.resilience.errors import NoReplicaAvailable

    for phase, count in n_req.items():
        kill_at = count // 3 if (kill and phase == "burst") else None
        lat, counts = [], {"offered": 0, "ok": 0, "shed": 0,
                           "error": 0, "unroutable": 0, "unanswered": 0}
        for i in range(count):
            if kill_at is not None and i == kill_at:
                kill()
            ids = [int(rng.zipf(1.7)) % N_NODES,
                   int(rng.integers(N_NODES))]
            tenant = _TENANT_MIX[int(rng.integers(len(_TENANT_MIX)))]
            counts["offered"] += 1
            t0 = time.perf_counter()
            try:
                reply = router.request(ids, tenant=tenant, seq=i)
                status = reply.get("status", "error")
                counts["ok" if status == "ok" else
                       "shed" if status == "shed" else "error"] += 1
            except NoReplicaAvailable:
                counts["unroutable"] += 1
            except Exception:
                counts["unanswered"] += 1
            lat.append((time.perf_counter() - t0) * 1e3)
        counts["p50_ms"] = round(_percentile(lat, 50), 3)
        counts["p99_ms"] = round(_percentile(lat, 99), 3)
        report["phases"][phase] = counts
    report["lost_answers"] = sum(
        p["unanswered"] for p in report["phases"].values())
    base_p99 = report["phases"].get("baseline", {}).get("p99_ms") or 1e-9
    if "burst" in report["phases"]:
        report["failover"]["p99_ratio_burst_vs_baseline"] = round(
            report["phases"]["burst"]["p99_ms"] / base_p99, 3)


def run_leader_failover(smoke: bool = False, seed: int = 0,
                        workdir: str | None = None) -> dict:
    """Leader kill -9 mid-burst → fenced promotion of the most
    caught-up follower: strictly higher epoch, zero acked WAL loss,
    writes flowing through the promoted lane, and the deposed epoch's
    append refused by the fence."""
    from quiver_tpu.fleet import FleetRouter, MembershipDirectory
    from quiver_tpu.fleet.election import (ElectionDirectory, EpochFence,
                                           FencedWAL, StaleEpochError)
    from quiver_tpu.resilience.qos import (QoSController, install_qos,
                                           parse_tenant_spec)

    rng = np.random.default_rng(seed)
    tmp = workdir or tempfile.mkdtemp(prefix="fleet_leaderkill_")
    root = os.path.join(tmp, "dur")
    fleet_dir = os.path.join(tmp, "fleet")
    cache_dir = os.path.join(tmp, "pcache")
    os.makedirs(cache_dir, exist_ok=True)
    n_req = {"baseline": 150, "burst": 300, "cool": 150} if smoke else \
            {"baseline": 400, "burst": 800, "cool": 400}
    install_qos(QoSController(classes=parse_tenant_spec(TENANTS),
                              default="bronze", ingest="bronze"))
    directory = MembershipDirectory(fleet_dir, heartbeat_timeout_s=2.0)
    procs: dict = {}
    report: dict = {"seed": seed, "smoke": smoke,
                    "scenario": "leader_failover",
                    "phases": {}, "failover": {}}
    t_start = time.perf_counter()
    router = None
    try:
        procs["r0"] = _spawn(root, fleet_dir, cache_dir, "r0", "leader",
                             ingest_rps=150.0, extra_env=_ELECTION_ENV)
        boots = [_wait_ready(procs["r0"])]
        for rid in ("r1", "r2"):
            procs[rid] = _spawn(root, fleet_dir, cache_dir, rid,
                                "follower", extra_env=_ELECTION_ENV)
        boots += [_wait_ready(procs["r1"]), _wait_ready(procs["r2"])]
        for rid in ("r0", "r1", "r2"):
            _wait_serving(directory, rid)
        report["cold_boots"] = boots
        old = directory.leader()
        report["failover"]["old_leader"] = old.replica_id
        report["failover"]["old_epoch"] = old.epoch

        router = FleetRouter(directory, partitions=64, scan_ttl_s=0.05,
                             request_timeout_s=2.0)
        t_kill = [None]

        def kill_leader():
            proc = procs["r0"]
            os.kill(proc.pid, signal.SIGKILL)
            proc.wait(timeout=30)
            t_kill[0] = time.perf_counter()
            report["failover"]["kill_returncode"] = proc.returncode
            report["failover"]["killed"] = "r0"

        _drive_phases(router, rng, report,
                      {"baseline": n_req["baseline"],
                       "burst": n_req["burst"]}, kill=kill_leader)

        # fenced promotion: a follower must take over with a strictly
        # higher epoch (the burst usually contains it; wait out stragglers)
        promoted = None
        deadline = time.time() + 60
        while time.time() < deadline:
            info = directory.leader()
            if (info is not None and info.replica_id != "r0"
                    and info.epoch > report["failover"]["old_epoch"]):
                promoted = info
                break
            time.sleep(0.05)
        if promoted is None:
            raise TimeoutError("no follower promoted after leader kill")
        report["failover"]["promoted"] = promoted.replica_id
        report["failover"]["new_epoch"] = promoted.epoch
        report["failover"]["failover_seconds"] = round(
            time.perf_counter() - t_kill[0], 3)

        # zero acked loss + writes flow: the successor's WAL frontier
        # must cover everything the dead leader acked, then keep moving
        with open(os.path.join(fleet_dir, "acked-r0.json")) as f:
            acked = json.load(f)
        target = acked["wal_next_lsn"]
        deadline = time.time() + 60
        frontier = -1
        while time.time() < deadline:
            info = directory.get(promoted.replica_id)
            if info is not None:
                frontier = info.wal_next_lsn
                if frontier >= target + 5:
                    break
            time.sleep(0.05)
        report["failover"]["acked_wal_next_lsn"] = target
        report["failover"]["promoted_wal_next_lsn"] = frontier
        report["failover"]["zero_acked_loss"] = frontier >= target
        report["failover"]["writes_flow"] = frontier >= target + 5

        # the deposed epoch is fenced: an append stamped with the dead
        # leader's epoch refuses before it can touch the log
        class _NeverWAL:
            def append(self, payload):
                raise AssertionError("fence let a deposed append through")

        fence = EpochFence(ElectionDirectory(fleet_dir),
                           report["failover"]["old_epoch"], "r0",
                           recheck_s=0.0)
        try:
            FencedWAL(_NeverWAL(), fence).append(b"deposed-write")
            report["failover"]["stale_epoch_append_refused"] = False
        except StaleEpochError:
            report["failover"]["stale_epoch_append_refused"] = True

        _drive_phases(router, rng, report, {"cool": n_req["cool"]})
        report["lost_answers"] = sum(
            p["unanswered"] for p in report["phases"].values())
        report["elapsed_seconds"] = round(
            time.perf_counter() - t_start, 1)
    finally:
        if router is not None:
            router.close()
        for proc in procs.values():
            _reap(proc)
        for proc in procs.values():
            if proc.stdout:
                proc.stdout.close()
            if proc.stderr:
                proc.stderr.close()
    return report


def check_leader_failover(report: dict) -> list:
    fails = []
    f = report.get("failover", {})
    if report.get("lost_answers", 1) != 0:
        fails.append(f"lost answers: {report.get('lost_answers')}")
    if f.get("kill_returncode") != -signal.SIGKILL:
        fails.append(f"leader not SIGKILLed ({f.get('kill_returncode')})")
    if not f.get("promoted") or f.get("promoted") == f.get("old_leader"):
        fails.append(f"no distinct follower promoted ({f.get('promoted')})")
    if not f.get("new_epoch", -1) > f.get("old_epoch", -1):
        fails.append(f"promotion epoch not strictly higher "
                     f"({f.get('old_epoch')} -> {f.get('new_epoch')})")
    if not f.get("zero_acked_loss", False):
        fails.append(f"acked WAL records lost: frontier "
                     f"{f.get('promoted_wal_next_lsn')} < acked "
                     f"{f.get('acked_wal_next_lsn')}")
    if not f.get("writes_flow", False):
        fails.append("writes do not flow through the promoted leader")
    if not f.get("stale_epoch_append_refused", False):
        fails.append("deposed stale-epoch append was NOT refused")
    ratio = f.get("p99_ratio_burst_vs_baseline", 99.0)
    if ratio >= 2.0:
        fails.append(f"failover p99 ratio {ratio} >= 2.0")
    return fails


def run_walstream_chaos(smoke: bool = False, seed: int = 0,
                        workdir: str | None = None) -> dict:
    """Socket-shipped followers with NO shared WAL directory: each
    follower owns a private recovery root and tails the leader purely
    over TCP, survives a seeded mid-stream cut by resuming from its
    committed LSN, and converges to staleness ≤ the configured bound."""
    from quiver_tpu.config import get_config
    from quiver_tpu.fleet import FleetRouter, MembershipDirectory
    from quiver_tpu.resilience.qos import (QoSController, install_qos,
                                           parse_tenant_spec)

    rng = np.random.default_rng(seed)
    tmp = workdir or tempfile.mkdtemp(prefix="fleet_walstream_")
    fleet_dir = os.path.join(tmp, "fleet")
    cache_dir = os.path.join(tmp, "pcache")
    os.makedirs(cache_dir, exist_ok=True)
    env = {"QUIVER_TPU_FLEET_WALSTREAM": "on"}
    n_req = 200 if smoke else 600
    install_qos(QoSController(classes=parse_tenant_spec(TENANTS),
                              default="bronze", ingest="bronze"))
    directory = MembershipDirectory(fleet_dir, heartbeat_timeout_s=2.0)
    procs: dict = {}
    report: dict = {"seed": seed, "smoke": smoke,
                    "scenario": "walstream", "phases": {},
                    "failover": {}, "stream": {}, "followers": {}}
    t_start = time.perf_counter()
    router = None
    try:
        # the 41st shipped frame dies mid-send: one follower's catch-up
        # is cut and must resume (the leader seeds 64 records, so the
        # cut lands inside the initial stream)
        procs["r0"] = _spawn(os.path.join(tmp, "dur-r0"), fleet_dir,
                             cache_dir, "r0", "leader",
                             ingest_rps=150.0, chaos_seed=seed,
                             walstream_fault_after=40, extra_env=env)
        boots = [_wait_ready(procs["r0"])]
        for rid in ("r1", "r2"):
            # PRIVATE WAL roots: the follower's wal/ is its own (and
            # stays empty — the socket is the only log channel), while
            # ckpt/ links to the shared checkpoint store (the object-
            # store analog) so restore + gap resync have a floor to
            # stream from once the leader truncates behind a checkpoint
            private = os.path.join(tmp, f"dur-{rid}")
            os.makedirs(private, exist_ok=True)
            os.symlink(os.path.join(tmp, "dur-r0", "ckpt"),
                       os.path.join(private, "ckpt"))
            procs[rid] = _spawn(private, fleet_dir, cache_dir, rid,
                                "follower", extra_env=env)
        boots += [_wait_ready(procs["r1"], timeout=600),
                  _wait_ready(procs["r2"], timeout=600)]
        for rid in ("r0", "r1", "r2"):
            _wait_serving(directory, rid)
        report["cold_boots"] = boots

        router = FleetRouter(directory, partitions=64, scan_ttl_s=0.05,
                             request_timeout_s=2.0)
        _drive_phases(router, rng, report, {"baseline": n_req})

        # followers must converge under the staleness bound while the
        # leader keeps appending at 150 rps
        bound = get_config().fleet_max_staleness_lsn
        deadline = time.time() + 60
        stale = {}
        while time.time() < deadline:
            stale = {rid: directory.get(rid).staleness_lsn
                     for rid in ("r1", "r2")
                     if directory.get(rid) is not None}
            if len(stale) == 2 and all(v <= bound
                                       for v in stale.values()):
                break
            time.sleep(0.1)
        for rid, v in stale.items():
            report["followers"][rid] = {
                "staleness_lsn": v, "within_bound": v <= bound}
        report["stream"]["staleness_bound"] = bound
        report["stream"]["leader_resumes"] = _scrape_counter_sum(
            directory, "r0", "fleet_walstream_resumes_total")
        report["stream"]["leader_sent"] = _scrape_counter_sum(
            directory, "r0", "fleet_walstream_sent_total")
        report["stream"]["follower_reconnects"] = sum(
            _scrape_counter_sum(directory, rid,
                                "fleet_walstream_reconnects_total")
            for rid in ("r1", "r2"))
        report["stream"]["crc_errors"] = sum(
            _scrape_counter_sum(directory, rid,
                                "fleet_walstream_crc_errors_total")
            for rid in ("r1", "r2"))
        report["elapsed_seconds"] = round(
            time.perf_counter() - t_start, 1)
    finally:
        if router is not None:
            router.close()
        for proc in procs.values():
            _reap(proc)
        for proc in procs.values():
            if proc.stdout:
                proc.stdout.close()
            if proc.stderr:
                proc.stderr.close()
    return report


def check_walstream(report: dict) -> list:
    fails = []
    if report.get("lost_answers", 1) != 0:
        fails.append(f"lost answers: {report.get('lost_answers')}")
    followers = report.get("followers", {})
    if len(followers) < 2:
        fails.append(f"expected 2 socket followers, saw "
                     f"{sorted(followers)}")
    for rid, f in followers.items():
        if not f.get("within_bound", False):
            fails.append(f"follower {rid} staleness "
                         f"{f.get('staleness_lsn')} over bound "
                         f"{report['stream'].get('staleness_bound')}")
    s = report.get("stream", {})
    if not s.get("leader_resumes", 0) >= 1:
        fails.append("mid-stream cut never forced a resume-from-LSN")
    if not s.get("follower_reconnects", 0) >= 1:
        fails.append("no follower reconnected after the stream cut")
    if s.get("crc_errors", 0) != 0:
        fails.append(f"receiver-side CRC errors: {s.get('crc_errors')}")
    return fails


def run_diurnal_autoscale(smoke: bool = False, seed: int = 0,
                          workdir: str | None = None) -> dict:
    """A compressed diurnal cycle with a 10× burst: the predictor is
    taught two synthetic prior days, then one live day runs — the
    profile must trigger a predictive warm spawn BEFORE the burst
    window, the joined replica serves through the peak, and the scaler
    drains back down after, never flapping inside a cooldown window."""
    from quiver_tpu.fleet import FleetRouter, MembershipDirectory
    from quiver_tpu.fleet.autoscaler import (DiurnalPredictor,
                                             FleetAutoscaler)
    from quiver_tpu.resilience.errors import NoReplicaAvailable
    from quiver_tpu.resilience.qos import (QoSController, install_qos,
                                           parse_tenant_spec)

    rng = np.random.default_rng(seed)
    tmp = workdir or tempfile.mkdtemp(prefix="fleet_autoscale_")
    root = os.path.join(tmp, "dur")
    fleet_dir = os.path.join(tmp, "fleet")
    cache_dir = os.path.join(tmp, "pcache")
    os.makedirs(cache_dir, exist_ok=True)
    period = 45.0 if smoke else 90.0
    burst_lo, burst_hi = 0.5, 0.8          # burst window (phase)
    low_rps, burst_rps = 10.0, 100.0       # the 10x diurnal swing
    rps_per_replica = 30.0
    cooldown = period / 6
    horizon = period * 0.3                 # looks into the burst early
    install_qos(QoSController(classes=parse_tenant_spec(TENANTS),
                              default="bronze", ingest="bronze"))
    directory = MembershipDirectory(fleet_dir, heartbeat_timeout_s=2.0)
    procs: dict = {}
    report: dict = {"seed": seed, "smoke": smoke,
                    "scenario": "autoscale", "phases": {},
                    "failover": {}, "autoscale": {}}
    t_start = time.perf_counter()
    router = None
    try:
        procs["r0"] = _spawn(root, fleet_dir, cache_dir, "r0", "leader",
                             ingest_rps=50.0)
        boots = [_wait_ready(procs["r0"])]
        procs["f1"] = _spawn(root, fleet_dir, cache_dir, "f1",
                             "follower")
        boots.append(_wait_ready(procs["f1"]))
        for rid in ("r0", "f1"):
            _wait_serving(directory, rid)
        report["cold_boots"] = boots

        router = FleetRouter(directory, partitions=64, scan_ttl_s=0.05,
                             request_timeout_s=2.0, federation=True)
        fed = router.federation

        # teach two synthetic prior days so the live day's ramp is a
        # RECURRING pattern the profile anticipates, not a surprise
        buckets = 18
        t0 = time.time() + 1.0
        predictor = DiurnalPredictor(period_s=period, buckets=buckets,
                                     alpha=0.7, window=64)
        for day in (2, 1):
            for b in range(buckets):
                phase = (b + 0.5) / buckets
                ts = t0 - day * period + phase * period
                predictor.observe(
                    ts, burst_rps if burst_lo <= phase < burst_hi
                    else low_rps)

        next_id = [2]
        joins, drains, decisions = [], [], []

        def spawn_fn(count):
            for _ in range(count):
                rid = f"f{next_id[0]}"
                next_id[0] += 1
                procs[rid] = _spawn(root, fleet_dir, cache_dir, rid,
                                    "follower")
                joins.append({"replica": rid, "spawn_phase": round(
                    (time.time() - t0) / period, 3)})

        def drain_fn(victim):
            if victim:
                open(os.path.join(fleet_dir, f"drain-{victim}"),
                     "w").close()
                drains.append({"replica": victim, "phase": round(
                    (time.time() - t0) / period, 3)})

        def snapshot_fn():
            fed.scrape_once()
            return fed.fleet_snapshot()

        scaler = FleetAutoscaler(
            snapshot_fn, spawn_fn, drain_fn, directory=directory,
            predictor=predictor, min_replicas=2, max_replicas=4,
            cooldown_s=cooldown, rps_per_replica=rps_per_replica,
            horizon_s=horizon, up_ratio=0.8, down_ratio=0.5)
        scaler.evaluate_once()  # prime the rate estimator

        # ---- the live day: paced traffic + the control loop --------
        lat = {"baseline": [], "burst": [], "after": []}
        counts = {"offered": 0, "ok": 0, "shed": 0, "error": 0,
                  "unroutable": 0, "unanswered": 0}
        serving_phase: dict = {}
        while time.time() < t0:
            time.sleep(0.01)
        next_eval = t0
        next_req = t0
        i = 0
        while True:
            now = time.time()
            phase = (now - t0) / period
            if phase >= 1.0:
                break
            in_burst = burst_lo <= phase < burst_hi
            window = ("burst" if in_burst else
                      "baseline" if phase < burst_lo else "after")
            if now >= next_eval:
                d = scaler.evaluate_once()
                decisions.append({"phase": round(phase, 3),
                                  "action": d["action"],
                                  "target": d["target"],
                                  "current": d["current"],
                                  "predicted_rps":
                                      round(d["predicted_rps"], 1),
                                  "reason": d["reason"]})
                for j in joins:
                    rid = j["replica"]
                    if rid not in serving_phase:
                        info = directory.get(rid)
                        if info is not None and info.state == "serving":
                            serving_phase[rid] = round(phase, 3)
                            j["serving_phase"] = serving_phase[rid]
                next_eval = now + 0.5
            ids = [int(rng.zipf(1.7)) % N_NODES,
                   int(rng.integers(N_NODES))]
            tenant = _TENANT_MIX[int(rng.integers(len(_TENANT_MIX)))]
            counts["offered"] += 1
            t_req = time.perf_counter()
            try:
                reply = router.request(ids, tenant=tenant, seq=i)
                status = reply.get("status", "error")
                counts["ok" if status == "ok" else
                       "shed" if status == "shed" else "error"] += 1
            except NoReplicaAvailable:
                counts["unroutable"] += 1
            except Exception:
                counts["unanswered"] += 1
            if tenant == "gold":
                lat[window].append((time.perf_counter() - t_req) * 1e3)
            i += 1
            rate = burst_rps if in_burst else low_rps
            next_req += 1.0 / rate
            sleep_s = next_req - time.time()
            if sleep_s > 0:
                time.sleep(sleep_s)
            else:
                next_req = time.time()  # saturated: don't death-spiral

        # epilogue: idle ticks until the post-burst drain lands (the
        # day may end inside the cooldown that follows the last spawn)
        deadline = time.time() + 2 * cooldown + 5
        while not drains and time.time() < deadline:
            d = scaler.evaluate_once()
            decisions.append({"phase": round(
                (time.time() - t0) / period, 3), "action": d["action"],
                "target": d["target"], "current": d["current"],
                "predicted_rps": round(d["predicted_rps"], 1),
                "reason": d["reason"]})
            time.sleep(0.5)

        for name in ("baseline", "burst", "after"):
            counts[f"gold_p99_{name}_ms"] = round(
                _percentile(lat[name], 99), 3)
        report["phases"]["live_day"] = counts
        report["lost_answers"] = counts["unanswered"]

        # late joiners already printed their ready line; collect it now
        for j in joins:
            proc = procs.get(j["replica"])
            if proc is not None and proc.poll() is None:
                try:
                    j.update(_wait_ready(proc, timeout=60))
                except Exception as e:
                    j["ready_error"] = str(e)

        peak_phase = (burst_lo + burst_hi) / 2
        warm_before_peak = [
            j for j in joins
            if j.get("pcache_hits", 0) > 0
            and j.get("serving_phase", 9.9) < peak_phase]
        acts = [d for d in decisions if d["action"] != "hold"]
        gaps = [round((b["phase"] - a["phase"]) * period, 2)
                for a, b in zip(acts, acts[1:])]
        base_p99 = counts["gold_p99_baseline_ms"] or 1e-9
        report["autoscale"] = {
            "period_s": period, "cooldown_s": cooldown,
            "burst_window_phase": [burst_lo, burst_hi],
            "joins": joins, "drains": drains,
            "decisions": decisions,
            "warm_joins_before_peak": len(warm_before_peak),
            "scale_down_after_burst": bool(drains),
            "min_action_gap_s": min(gaps) if gaps else None,
            "gold_p99_ratio_burst_vs_baseline": round(
                counts["gold_p99_burst_ms"] / base_p99, 3),
        }
        report["elapsed_seconds"] = round(
            time.perf_counter() - t_start, 1)
    finally:
        if router is not None:
            router.close()
        for proc in procs.values():
            _reap(proc)
        for proc in procs.values():
            if proc.stdout:
                proc.stdout.close()
            if proc.stderr:
                proc.stderr.close()
    return report


def check_autoscale(report: dict) -> list:
    fails = []
    a = report.get("autoscale", {})
    if report.get("lost_answers", 1) != 0:
        fails.append(f"lost answers: {report.get('lost_answers')}")
    if not a.get("warm_joins_before_peak", 0) >= 1:
        fails.append("no warm join landed before the burst peak "
                     f"(joins: {a.get('joins')})")
    if not a.get("scale_down_after_burst", False):
        fails.append("no scale-down after the burst passed")
    gap = a.get("min_action_gap_s")
    # 0.6s slack: decisions are sampled on a 0.5s cadence, so two
    # actions one cooldown apart can stamp up to one tick closer
    if gap is not None and gap < a.get("cooldown_s", 0) - 0.6:
        fails.append(f"membership flapped: actions {gap}s apart, "
                     f"cooldown {a.get('cooldown_s')}s")
    ratio = a.get("gold_p99_ratio_burst_vs_baseline", 99.0)
    if ratio >= 2.0:
        fails.append(f"gold p99 ratio {ratio} >= 2.0")
    return fails


_SCENARIOS = {
    "failover": (run_fleet_chaos, check),
    "leader": (run_leader_failover, check_leader_failover),
    "walstream": (run_walstream_chaos, check_walstream),
    "autoscale": (run_diurnal_autoscale, check_autoscale),
}


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="short phases (CI-sized run)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--json", action="store_true",
                    help="print the full report as JSON")
    ap.add_argument("--check", action="store_true",
                    help="exit 1 unless every acceptance criterion "
                         "holds (p99 ratio included — use on a quiet "
                         "machine)")
    ap.add_argument("--scenario", default="failover",
                    choices=sorted(_SCENARIOS) + ["all"],
                    help="which chaos story to run: follower kill "
                         "(failover), leader kill + fenced promotion "
                         "(leader), socket WAL shipping (walstream), "
                         "diurnal predictive scaling (autoscale), or "
                         "all of them in sequence")
    args = ap.parse_args()
    names = sorted(_SCENARIOS) if args.scenario == "all" \
        else [args.scenario]
    rc = 0
    for name in names:
        run_fn, check_fn = _SCENARIOS[name]
        report = run_fn(smoke=args.smoke, seed=args.seed)
        if args.json:
            print(json.dumps(report, indent=2, sort_keys=True))
        else:
            _print_report(name, report)
        # loss/promotion/staleness criteria are backend-independent;
        # p99 ratios are only meaningful on a quiet machine, so they
        # gate under --check
        fails = check_fn(report)
        gated = fails if args.check else \
            [x for x in fails if "p99" not in x]
        for msg in gated:
            print(f"FAIL[{name}]: {msg}", file=sys.stderr)
        rc = rc or (1 if gated else 0)
    return rc


def _print_report(scenario: str, report: dict) -> None:
    print(f"=== scenario: {scenario} ===")
    for name, p in report.get("phases", {}).items():
        line = (f"{name:9s} offered={p['offered']:5d} ok={p['ok']:5d} "
                f"shed={p['shed']:4d} unroutable={p['unroutable']:3d} "
                f"unanswered={p['unanswered']:3d}")
        if "p99_ms" in p:
            line += f" p50={p['p50_ms']:.2f}ms p99={p['p99_ms']:.2f}ms"
        print(line)
    f = report.get("failover", {})
    if f.get("killed"):
        print(f"failover  killed={f.get('killed')} "
              f"rc={f.get('kill_returncode')} "
              f"redispatches={f.get('redispatches')} "
              f"p99x={f.get('p99_ratio_burst_vs_baseline')}")
    if "promoted" in f:
        print(f"promotion {f.get('old_leader')} (epoch "
              f"{f.get('old_epoch')}) -> {f.get('promoted')} (epoch "
              f"{f.get('new_epoch')}) in {f.get('failover_seconds')}s "
              f"frontier={f.get('promoted_wal_next_lsn')} acked="
              f"{f.get('acked_wal_next_lsn')} fenced="
              f"{f.get('stale_epoch_append_refused')}")
    r = report.get("rejoin", {})
    if r:
        print(f"rejoin    {r.get('rejoin_seconds')}s "
              f"pcache_hits={r.get('pcache_hits')} "
              f"new_cache_files={r.get('new_cache_files')} "
              f"staleness={r.get('staleness_lsn_final')} "
              f"(bound {r.get('staleness_bound')}) "
              f"backend={report.get('backend')}")
    s = report.get("stream", {})
    if s:
        print(f"stream    sent={s.get('leader_sent')} "
              f"resumes={s.get('leader_resumes')} "
              f"reconnects={s.get('follower_reconnects')} "
              f"crc_errors={s.get('crc_errors')} followers="
              f"{report.get('followers')}")
    a = report.get("autoscale", {})
    if a:
        print(f"autoscale joins={a.get('joins')} "
              f"drains={a.get('drains')} warm_before_peak="
              f"{a.get('warm_joins_before_peak')} min_gap="
              f"{a.get('min_action_gap_s')}s gold_p99x="
              f"{a.get('gold_p99_ratio_burst_vs_baseline')}")
    o = report.get("observability", {})
    if o:
        print(f"trace     events={o.get('trace_events')} "
              f"processes={o.get('trace_processes')} "
              f"redispatched={o.get('redispatched_trace_id')} "
              f"on_tracks={o.get('trace_replica_tracks')} "
              f"reconstructed={o.get('reconstruction_found')}")
    print(f"lost_answers={report.get('lost_answers')} "
          f"elapsed={report.get('elapsed_seconds')}s")


if __name__ == "__main__":
    sys.exit(main())
