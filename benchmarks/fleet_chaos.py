"""Replica-failover chaos harness (``make fleet-chaos``).

Stands up a REAL 3-replica fleet — one ingest leader plus two read
followers, each a separate OS process booted through the fleet join
path (shared checkpoints + shared JAX persistent compilation cache,
warmup, ``seal``) — then drives seeded zipfian traffic through a
:class:`~quiver_tpu.fleet.router.FleetRouter` in three phases
(baseline → burst → cool) and, mid-burst, ``kill -9``s one follower.
No drain, no warning: the next poll of its socket fails and the
router's re-dispatch path is the only thing standing between an
in-flight request and silence.

The contract this harness proves (asserted by ``tests/test_fleet.py``
on the returned report, and by ``--check`` from the command line):

  * **zero lost answers** — every request submitted to the router is
    answered: ``ok``, a typed shed, or a typed
    ``NoReplicaAvailable``; ``unanswered`` is identically 0 across all
    phases (the kill included);
  * **bounded failover impact** — burst-phase p99 (which contains the
    kill) stays under ``2×`` the baseline p99, and the cool phase
    returns to baseline-grade latency;
  * **warm rejoin** — the killed replica restarts under the same id
    and the shared caches: its boot must HIT the persistent
    compilation cache (``pcache_hits > 0``), write zero new cache
    entries, survive post-warmup traffic under a sealed registry, and
    its staleness watermark must return to 0 (≤ the configured bound)
    once serving;
  * **fleet-wide observability** (docs/OBSERVABILITY.md) — the router
    runs with federation on and every process records its timeline; a
    seeded ``fleet.serve`` fault on one follower forces redispatches
    whose trace_id lands on TWO replica timelines.  After the run the
    harness exports the merged Perfetto trace via
    ``timeline.export_fleet`` and asserts it is loadable, contains
    events from ≥ 2 processes, and shows both dispatch attempts of one
    redispatched trace_id on two different replica tracks — and that
    ``/debug/fleet/trace``-style reconstruction finds the story.

The model stage is deliberately tiny (default replica service: a
versioned graph touch) so the harness runs on CPU in minutes; the
router, membership, WAL shipping, breakers, and the kill are all the
production code paths.  On CPU the latency numbers are a rehearsal —
``bench.py`` stamps the section ``source: cpu_rehearsal`` so nothing
quotes them as device truth; the *loss and rejoin* assertions are
backend-independent and hold everywhere.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, ".")

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
N_NODES = 64

# gold is the hot class (priority 3 >= fleet_hot_priority default):
# its zipfian head traffic routes power-of-two-choices
TENANTS = ("gold:rate=2000,burst=500,weight=8,priority=3;"
           "silver:rate=1000,burst=250,weight=4,priority=2;"
           "bronze:rate=500,burst=125,weight=2,priority=1")
_TENANT_MIX = ("gold", "gold", "gold", "silver", "silver", "bronze")

# one child program serves both roles; argv decides.  The leader boots
# the recovery tier, seeds + checkpoints the shared root, then ingests
# steadily so WAL shipping stays live during the run.  Followers join
# through checkpoint restore + WAL tail.  Both warm a sampler and seal
# at retrace budget 0 — a cold compile after warmup aborts the child.
_REPLICA_CHILD = r"""
import glob, json, os, sys, time
import numpy as np
import quiver_tpu.config as config_mod

(root, fleet_dir, cache_dir, rid, role, ingest_rps, serve_every,
 chaos_seed) = sys.argv[1:9]
# budget 4, not 0: the stream sampler legitimately builds one program
# per delta-overlay BUCKET it serves (geometric growth schedule), and
# live ingest crosses a few buckets after warmup.  The seal still
# gates: anything beyond bucket growth crashes the replica.
config_mod.update(recovery_dir=root, recovery_cache_dir=cache_dir,
                  recovery_retrace_budget=4)

from quiver_tpu import GraphSageSampler
from quiver_tpu.fleet import FleetReplica
from quiver_tpu.recovery.registry import get_program_registry
from quiver_tpu.resilience import chaos
from quiver_tpu.stream import StreamingGraph
from quiver_tpu.telemetry import flightrec, timeline
from quiver_tpu.utils.rng import make_key
from quiver_tpu.utils.topology import CSRTopo

N = 64

# every process records its own timeline; the parent's federation
# pulls /debug/timeline from each and merges them onto one wall clock
timeline.enable()
if int(serve_every) > 0:
    # deterministic serve faults on THIS follower: accepted requests
    # answer `unavailable` after trace rehydration, so the router
    # redispatches and the same trace_id lands on a second replica's
    # timeline — the cross-process story the merged trace must show
    chaos.install(chaos.ChaosPlan(seed=int(chaos_seed)).fail(
        "fleet.serve", times=None, after=1, every=int(serve_every)))

def factory():
    src = np.arange(N, dtype=np.int64)
    dst = (src + 1) % N
    return StreamingGraph(CSRTopo(edge_index=np.stack([src, dst])),
                          delta_capacity=65536)

holder = {}

def warmup(graph):
    s = GraphSageSampler(graph, sizes=[3, 2], gather_mode="xla",
                         dedup="none")
    s.sample(np.arange(8), key=make_key(0))
    holder["sampler"] = s
    holder["graph"] = graph

def service(ids, tenant):
    # drive the WARMED sampler (fixed shape: no recompile under seal)
    # and stamp the stage span into the active fleet trace
    t0 = time.perf_counter()
    holder["sampler"].sample(np.arange(8), key=make_key(0))
    flightrec.event("sample", {"seconds": time.perf_counter() - t0})
    g = holder.get("graph")
    return {"n": len(ids),
            "version": int(g.version) if g is not None else -1}

before = set(glob.glob(os.path.join(cache_dir, "**"), recursive=True))
t0 = time.perf_counter()
rep = FleetReplica(rid, fleet_dir=fleet_dir, root=root,
                   graph_factory=factory, role=role,
                   warmup=warmup, seal=True, service_fn=service).boot()
rep.expose_metrics()
if role == "leader":
    # seed + checkpoint so followers have a restore point
    for i in range(64):
        rep.lane.submit([i % N], [(i * 7 + 3) % N])
    for _ in range(64):
        _u, res = rep.lane.results.get(timeout=30)
        if isinstance(res, Exception):
            raise res
    rep.manager.checkpoint(timeout=30)
# post-seal traffic through the warmed sampler: budget 0 makes any
# cold compile after warmup a crash, not a p99 cliff
for k in range(1, 4):
    holder["sampler"].sample(np.arange(8), key=make_key(k))
reg = get_program_registry()
after = set(glob.glob(os.path.join(cache_dir, "**"), recursive=True))
print(json.dumps({
    "ready": True, "replica": rid, "role": role,
    "boot_seconds": round(time.perf_counter() - t0, 3),
    "pcache_hits": reg.persistent_cache_hits,
    "new_cache_files": len(after - before),
    "sampler_builds": reg.stats().get("sampler", {}).get("builds", 0),
}), flush=True)

if role == "leader":
    period = 1.0 / max(float(ingest_rps), 1.0)
    i = 64
    while True:
        rep.lane.submit([i % N], [(i * 7 + 3) % N])
        _u, res = rep.lane.results.get(timeout=30)
        i += 1
        time.sleep(period)
else:
    while True:
        time.sleep(0.5)
"""


def _spawn(root, fleet_dir, cache_dir, rid, role, ingest_rps=100.0,
           serve_fault_every=0, chaos_seed=0):
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO,
               PYTHONUNBUFFERED="1",
               QUIVER_TPU_FLEET_SHIP_POLL_MS="10",
               QUIVER_TPU_FLEET_SHIP_GRACE_MS="60",
               QUIVER_TPU_FLEET_HEARTBEAT_S="0.2")
    return subprocess.Popen(
        [sys.executable, "-c", _REPLICA_CHILD, root, fleet_dir,
         cache_dir, rid, role, str(ingest_rps),
         str(int(serve_fault_every)), str(int(chaos_seed))],
        cwd=REPO, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True)


def _wait_ready(proc, timeout=300.0):
    """Read child stdout until its READY JSON line."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        line = proc.stdout.readline()
        if not line:
            raise RuntimeError(
                f"replica child died during boot:\n{proc.stderr.read()}")
        try:
            doc = json.loads(line)
        except ValueError:
            continue
        if doc.get("ready"):
            return doc
    raise TimeoutError("replica child never reported ready")


def _wait_serving(directory, rid, timeout=120.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        info = directory.get(rid)
        if info is not None and info.state == "serving" and \
                info.fresh(directory.heartbeat_timeout_s):
            return info
        time.sleep(0.05)
    raise TimeoutError(f"replica {rid} never reached serving")


def _percentile(xs, p):
    if not xs:
        return 0.0
    return float(np.percentile(np.asarray(xs, dtype=np.float64), p))


def _reap(proc):
    if proc is None or proc.poll() is not None:
        return
    proc.terminate()
    try:
        proc.wait(timeout=10)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.wait(timeout=10)


def _observability(router, fed, trace_file: str) -> dict:
    """Export the merged fleet trace and distil the evidence
    :func:`check` asserts on: trace loadable, events from ≥ 2
    processes, one redispatched trace_id with both attempts recorded
    and visible on two replica tracks, reconstruction joins."""
    from quiver_tpu.telemetry import timeline

    timeline.export_fleet(trace_file)
    with open(trace_file) as f:
        doc = json.load(f)
    track: dict = {}
    events = []
    for e in doc.get("traceEvents", ()):
        if e.get("ph") == "M":
            if e.get("name") == "process_name":
                track[e["pid"]] = e["args"]["name"]
        else:
            events.append(e)
    obs: dict = {
        "trace_path": trace_file,
        "trace_events": len(events),
        "trace_processes": sorted({track.get(e["pid"], str(e["pid"]))
                                   for e in events}),
    }
    by_tid: dict = {}
    for e in events:
        tid = (e.get("args") or {}).get("trace_id")
        if tid:
            by_tid.setdefault(tid, set()).add(
                track.get(e["pid"], str(e["pid"])))
    redis = [h for h in router.hop_records(limit=router.hop_capacity)
             if len(h.get("attempts", ())) >= 2]
    obs["redispatched_hops"] = len(redis)
    chosen = None
    for h in reversed(redis):  # newest first: its events are retained
        tracks = by_tid.get(h["trace_id"], set())
        if sum(1 for t in tracks if t.startswith("replica")) >= 2:
            chosen = h
            break
    if chosen is None and redis:
        chosen = redis[-1]
    if chosen is not None:
        tid = chosen["trace_id"]
        tracks = sorted(by_tid.get(tid, ()))
        obs["redispatched_trace_id"] = tid
        obs["redispatch_attempts"] = [
            {"replica": a["replica"], "outcome": a["outcome"]}
            for a in chosen["attempts"]]
        obs["trace_tracks"] = tracks
        obs["trace_replica_tracks"] = [
            t for t in tracks if t.startswith("replica")]
        recon = fed.reconstruct(tid)
        obs["reconstruction_found"] = bool(recon.get("found"))
        obs["reconstructed_replicas"] = sorted(recon.get("replicas", ()))
    return obs


def run_fleet_chaos(smoke: bool = False, seed: int = 0,
                    workdir: str | None = None,
                    trace_path: str | None = None) -> dict:
    """Run the failover scenario; returns the structured report."""
    from quiver_tpu.fleet import FleetRouter, MembershipDirectory
    from quiver_tpu.resilience.errors import NoReplicaAvailable
    from quiver_tpu.resilience.qos import (QoSController, install_qos,
                                           parse_tenant_spec)
    from quiver_tpu import telemetry
    from quiver_tpu.telemetry import timeline

    rng = np.random.default_rng(seed)
    tmp = workdir or tempfile.mkdtemp(prefix="fleet_chaos_")
    root = os.path.join(tmp, "dur")
    fleet_dir = os.path.join(tmp, "fleet")
    cache_dir = os.path.join(tmp, "pcache")
    os.makedirs(cache_dir, exist_ok=True)

    n_req = {"baseline": 200, "burst": 400, "cool": 200} if smoke else \
            {"baseline": 600, "burst": 1200, "cool": 600}

    install_qos(QoSController(classes=parse_tenant_spec(TENANTS),
                              default="bronze", ingest="bronze"))
    directory = MembershipDirectory(fleet_dir,
                                    heartbeat_timeout_s=2.0)
    procs: dict = {}
    report: dict = {"seed": seed, "smoke": smoke,
                    "phases": {}, "failover": {}, "rejoin": {}}
    t_start = time.perf_counter()
    timeline_was_on = timeline.on()
    timeline.enable()
    try:
        procs["r0"] = _spawn(root, fleet_dir, cache_dir, "r0", "leader")
        boot0 = _wait_ready(procs["r0"])
        # r1 carries the seeded serve-fault plan: ~1/4 of its admitted
        # requests answer `unavailable` after trace rehydration, so the
        # merged trace shows redispatched ids on two replica tracks
        procs["r1"] = _spawn(root, fleet_dir, cache_dir, "r1",
                             "follower", serve_fault_every=4,
                             chaos_seed=seed)
        procs["r2"] = _spawn(root, fleet_dir, cache_dir, "r2",
                             "follower")
        boot1 = _wait_ready(procs["r1"])
        boot2 = _wait_ready(procs["r2"])
        for rid in ("r0", "r1", "r2"):
            _wait_serving(directory, rid)
        report["cold_boots"] = [boot0, boot1, boot2]

        # 64 partitions (not the 8-partition default): the 3-member
        # ring must give EVERY replica ownership of some partitions, so
        # the faulted follower actually sees traffic to redispatch
        router = FleetRouter(directory, partitions=64, scan_ttl_s=0.05,
                             request_timeout_s=2.0, federation=True)

        def drive(phase: str, count: int, kill_at: int | None = None):
            lat, counts = [], {"offered": 0, "ok": 0, "shed": 0,
                              "error": 0, "unroutable": 0,
                              "unanswered": 0}
            for i in range(count):
                if kill_at is not None and i == kill_at:
                    _kill9("r2")
                ids = [int(rng.zipf(1.7)) % N_NODES,
                       int(rng.integers(N_NODES))]
                tenant = _TENANT_MIX[int(rng.integers(len(_TENANT_MIX)))]
                counts["offered"] += 1
                t0 = time.perf_counter()
                try:
                    reply = router.request(ids, tenant=tenant, seq=i)
                    status = reply.get("status", "error")
                    counts["ok" if status == "ok" else
                           "shed" if status == "shed" else "error"] += 1
                except NoReplicaAvailable:
                    counts["unroutable"] += 1
                except Exception:
                    counts["unanswered"] += 1
                lat.append((time.perf_counter() - t0) * 1e3)
            counts["p50_ms"] = round(_percentile(lat, 50), 3)
            counts["p99_ms"] = round(_percentile(lat, 99), 3)
            report["phases"][phase] = counts

        def _kill9(rid: str):
            proc = procs[rid]
            os.kill(proc.pid, signal.SIGKILL)
            proc.wait(timeout=30)
            report["failover"]["kill_returncode"] = proc.returncode
            report["failover"]["killed"] = rid

        drive("baseline", n_req["baseline"])
        drive("burst", n_req["burst"], kill_at=n_req["burst"] // 3)

        # warm rejoin: same replica id, same shared caches
        t_rejoin = time.perf_counter()
        procs["r2"] = _spawn(root, fleet_dir, cache_dir, "r2",
                             "follower")
        rejoin = _wait_ready(procs["r2"])
        info = _wait_serving(directory, "r2")
        rejoin["rejoin_seconds"] = round(
            time.perf_counter() - t_rejoin, 3)
        rejoin["staleness_lsn_at_serving"] = info.staleness_lsn
        # the watermark must come back under the bound once serving
        from quiver_tpu.config import get_config

        bound = get_config().fleet_max_staleness_lsn
        deadline = time.time() + 30
        while time.time() < deadline:
            info = directory.get("r2")
            if info is not None and info.staleness_lsn <= bound:
                break
            time.sleep(0.05)
        rejoin["staleness_lsn_final"] = info.staleness_lsn
        rejoin["staleness_bound"] = bound
        rejoin["within_bound"] = info.staleness_lsn <= bound
        report["rejoin"] = rejoin

        drive("cool", n_req["cool"])

        # federation sweeps: harvest heartbeat clock pairs (≥ 2 ticks
        # apart so the offset estimator sees distinct pairs), scrape
        # every member, then export + dissect the merged fleet trace
        for _ in range(3):
            router.federation.scrape_once()
            time.sleep(0.3)
        report["observability"] = _observability(
            router, router.federation,
            trace_path or os.path.join(tmp, "fleet_trace.json"))

        base_p99 = report["phases"]["baseline"]["p99_ms"] or 1e-9
        report["failover"]["p99_ratio_burst_vs_baseline"] = round(
            report["phases"]["burst"]["p99_ms"] / base_p99, 3)
        report["failover"]["p99_ratio_cool_vs_baseline"] = round(
            report["phases"]["cool"]["p99_ms"] / base_p99, 3)
        snap = telemetry.snapshot()["counters"]
        report["failover"]["redispatches"] = sum(
            v for k, v in snap.items()
            if k.startswith("fleet_router_redispatch_total"))
        report["failover"]["unroutable_total"] = sum(
            v for k, v in snap.items()
            if k.startswith("fleet_router_unroutable_total"))
        report["lost_answers"] = sum(
            p["unanswered"] for p in report["phases"].values())
        report["elapsed_seconds"] = round(
            time.perf_counter() - t_start, 1)
        router.close()
    finally:
        if not timeline_was_on:
            timeline.disable()
        for proc in procs.values():
            _reap(proc)
        for proc in procs.values():
            if proc.stdout:
                proc.stdout.close()
            if proc.stderr:
                proc.stderr.close()
    try:
        import jax

        report["backend"] = jax.default_backend()
    except Exception:
        report["backend"] = "unknown"
    return report


def check(report: dict) -> list:
    """The acceptance criteria as data; returns failure strings."""
    fails = []
    if report.get("lost_answers", 1) != 0:
        fails.append(f"lost answers: {report.get('lost_answers')}")
    if report["failover"].get("kill_returncode") != -signal.SIGKILL:
        fails.append("replica was not SIGKILLed "
                     f"({report['failover'].get('kill_returncode')})")
    rejoin = report.get("rejoin", {})
    # warm = the boot HIT the shared compilation cache and survived the
    # sealed retrace budget (a crash would have failed _wait_ready).
    # new_cache_files stays informational: live ingest can cross a
    # delta bucket between cold boot and rejoin, making one fresh
    # compile legitimate.
    if not rejoin.get("pcache_hits", 0) > 0:
        fails.append("rejoin was cold: pcache_hits == 0")
    if not rejoin.get("within_bound", False):
        fails.append(f"staleness {rejoin.get('staleness_lsn_final')} "
                     f"over bound {rejoin.get('staleness_bound')}")
    ratio = report["failover"].get("p99_ratio_burst_vs_baseline", 99.0)
    if ratio >= 2.0:
        fails.append(f"failover p99 ratio {ratio} >= 2.0")
    # the merged failover trace: produced, loadable, cross-process, and
    # carrying one redispatched trace_id end to end
    obs = report.get("observability", {})
    if obs.get("trace_events", 0) <= 0:
        fails.append("merged fleet trace missing or empty")
    if len(obs.get("trace_processes", ())) < 2:
        fails.append("merged trace lacks events from >= 2 processes "
                     f"({obs.get('trace_processes')})")
    if len(obs.get("redispatch_attempts", ())) < 2:
        fails.append("no redispatched request with both dispatch "
                     "attempts recorded")
    if len(obs.get("trace_replica_tracks", ())) < 2:
        fails.append("redispatched trace_id not on two replica tracks "
                     f"({obs.get('trace_replica_tracks')})")
    if not obs.get("reconstruction_found", False):
        fails.append("cross-process trace reconstruction found no "
                     "record")
    return fails


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="short phases (CI-sized run)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--json", action="store_true",
                    help="print the full report as JSON")
    ap.add_argument("--check", action="store_true",
                    help="exit 1 unless every acceptance criterion "
                         "holds (p99 ratio included — use on a quiet "
                         "machine)")
    args = ap.parse_args()
    report = run_fleet_chaos(smoke=args.smoke, seed=args.seed)
    if args.json:
        print(json.dumps(report, indent=2, sort_keys=True))
    else:
        for name, p in report["phases"].items():
            print(f"{name:9s} offered={p['offered']:5d} ok={p['ok']:5d} "
                  f"shed={p['shed']:4d} unroutable={p['unroutable']:3d} "
                  f"unanswered={p['unanswered']:3d} "
                  f"p50={p['p50_ms']:.2f}ms p99={p['p99_ms']:.2f}ms")
        f = report["failover"]
        r = report["rejoin"]
        print(f"failover  killed={f.get('killed')} "
              f"rc={f.get('kill_returncode')} "
              f"redispatches={f.get('redispatches')} "
              f"p99x={f.get('p99_ratio_burst_vs_baseline')}")
        print(f"rejoin    {r.get('rejoin_seconds')}s "
              f"pcache_hits={r.get('pcache_hits')} "
              f"new_cache_files={r.get('new_cache_files')} "
              f"staleness={r.get('staleness_lsn_final')} "
              f"(bound {r.get('staleness_bound')}) "
              f"backend={report['backend']}")
        o = report.get("observability", {})
        print(f"trace     events={o.get('trace_events')} "
              f"processes={o.get('trace_processes')} "
              f"redispatched={o.get('redispatched_trace_id')} "
              f"on_tracks={o.get('trace_replica_tracks')} "
              f"reconstructed={o.get('reconstruction_found')}")
        print(f"lost_answers={report['lost_answers']} "
              f"elapsed={report['elapsed_seconds']}s")
    # loss/rejoin criteria are backend-independent; the p99 ratio is
    # only meaningful on a quiet machine, so it gates under --check
    hard_fails = [x for x in check(report) if "p99" not in x]
    gated = check(report) if args.check else hard_fails
    for msg in gated:
        print(f"FAIL: {msg}", file=sys.stderr)
    return 1 if gated else 0


if __name__ == "__main__":
    sys.exit(main())
