"""Staged TPU probe: find which compile/execute step is slow over the
axon tunnel.  Each stage logs start/stop with wall time; run under nohup
and tail the log."""

import os
import sys
import time

os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 ".jax_cache"),
)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

T0 = time.perf_counter()


def log(msg):
    print(f"[{time.perf_counter() - T0:8.1f}s] {msg}", flush=True)


def stage(name):
    log(f"--- {name}")


stage("import jax + device init")
import jax
import jax.numpy as jnp

log(f"devices: {jax.devices()}")

stage("trivial jit")
x = jnp.arange(8.0)
y = jax.jit(lambda a: a * 2 + 1)(x)
y.block_until_ready()
log(f"trivial ok: {np.asarray(y)[:3]}")

stage("big array upload (490MB)")
arr = np.arange(123_000_000, dtype=np.int32)
d = jax.device_put(arr)
d.block_until_ready()
log("upload ok")

stage("simple take gather (1M from 123M)")
ids = jnp.asarray(np.random.default_rng(0).integers(0, 123_000_000, 1_000_000,
                                                    dtype=np.int32))
g = jax.jit(lambda a, i: jnp.take(a, i))
r = g(d, ids)
r.block_until_ready()
log("take compile+run ok")
t = time.perf_counter()
for _ in range(5):
    r = g(d, ids)
r.block_until_ready()
log(f"take steady: {(time.perf_counter() - t) / 5 * 1e3:.1f} ms")

from quiver_tpu import CSRTopo, GraphSageSampler
from quiver_tpu.utils.synthetic import synthetic_csr

stage("small graph (100K/2M) one-hop xla")
indptr, indices = synthetic_csr(100_000, 2_000_000, 0)
topo_s = CSRTopo(indptr=indptr, indices=indices)
s = GraphSageSampler(topo_s, [15], gather_mode="xla")
seeds = np.random.default_rng(1).integers(0, 100_000, 256).astype(np.int32)
b = s.sample(seeds)
b.n_id.block_until_ready()
log("small one-hop xla ok")

stage("small graph 3-hop xla [15,10,5] B=256")
s3 = GraphSageSampler(topo_s, [15, 10, 5], gather_mode="xla")
b = s3.sample(seeds)
b.n_id.block_until_ready()
log("small 3-hop xla ok")

stage("small graph 3-hop lanes B=256")
s3l = GraphSageSampler(topo_s, [15, 10, 5], gather_mode="lanes")
b = s3l.sample(seeds)
b.n_id.block_until_ready()
log("small 3-hop lanes ok")

stage("products graph gen+upload")
indptr, indices = synthetic_csr(2_449_029, 123_718_280, 0)
topo = CSRTopo(indptr=indptr, indices=indices)
topo.to_device()
log("products upload ok")

stage("products one-hop xla B=256")
s1 = GraphSageSampler(topo, [15], gather_mode="xla")
b = s1.sample(seeds % 2_449_029)
b.n_id.block_until_ready()
log("products one-hop xla ok")

stage("products 3-hop xla B=256")
sp = GraphSageSampler(topo, [15, 10, 5], gather_mode="xla")
b = sp.sample(seeds % 2_449_029)
b.n_id.block_until_ready()
log("products 3-hop xla ok")
t = time.perf_counter()
for i in range(5):
    b = sp.sample(seeds % 2_449_029, key=jax.random.PRNGKey(i))
b.n_id.block_until_ready()
log(f"products 3-hop xla steady: {(time.perf_counter() - t) / 5 * 1e3:.1f} "
    f"ms/batch")

stage("products 3-hop lanes B=256")
spl = GraphSageSampler(topo, [15, 10, 5], gather_mode="lanes")
b = spl.sample(seeds % 2_449_029)
b.n_id.block_until_ready()
log("products 3-hop lanes ok")
t = time.perf_counter()
for i in range(5):
    b = spl.sample(seeds % 2_449_029, key=jax.random.PRNGKey(i))
b.n_id.block_until_ready()
log(f"products 3-hop lanes steady: {(time.perf_counter() - t) / 5 * 1e3:.1f} "
    f"ms/batch")

log("ALL STAGES DONE")
