"""Closed-loop multi-tenant QoS load harness (``make qos``).

Drives the REAL serving pipeline — RequestBatcher admission,
WeightedFairLane scheduling, InferenceServer coalescing + continuous
batching, SLOWatchdog-fed degradation ladder — under a seeded,
three-phase diurnal load:

  1. ``baseline``  — every tenant offers its steady rate,
  2. ``burst``     — a zipfian tenant mix (the floor class is the heavy
     hitter) offers ``burst_x`` times the steady load, with scripted
     chaos faults firing on the device lane mid-burst,
  3. ``cool``      — back to steady rates, long enough for the ladder
     to walk fully back to level 0.

The model stage is a deterministic stub (a short busy-wait per batch),
so the harness needs no accelerator and runs in seconds; everything
*around* the model — queues, fair scheduling, token buckets, sheds,
failover, the ladder — is the production code path.

Closed loop: each phase ends with a barrier that waits until every
submitted request has been ANSWERED (ok / shed / rejected / error), so
phase accounting is exact, not sampled.

Report (:func:`run_qos_load`): per-tenant, per-phase offered / ok /
shed / rejected / error counts, p50/p99 latency, goodput; ladder
history, peak level, and final reversal state (level, fanout fraction,
cold-cache admission flag).  ``tests/test_qos.py`` asserts the
acceptance criteria on exactly this dict.
"""

from __future__ import annotations

import argparse
import json
import queue
import sys
import threading
import time

sys.path.insert(0, ".")

import numpy as np

# tenant spec used by the harness: gold is provisioned far above its
# offered rate (its quota never rejects), bronze is the floor class and
# the zipfian heavy hitter whose burst must not starve the others
TENANTS = ("gold:rate=800,burst=200,weight=8,priority=3;"
           "silver:rate=400,burst=100,weight=4,priority=2;"
           "bronze:rate=200,burst=60,weight=2,priority=1;"
           "ingest:rate=100,burst=50,weight=1,priority=0")

# steady per-tenant offered rates (requests/s); the zipfian burst skews
# toward the END of this list (bronze-heavy)
STEADY_RPS = {"gold": 40.0, "silver": 30.0, "bronze": 30.0}


class _StubBatch:
    __slots__ = ("n_id", "layers")

    def __init__(self, n_id):
        self.n_id = n_id
        self.layers = ()


class _StubSampler:
    """Deterministic sampler stand-in with the live-fanout knob the
    ladder's L1 step drives (the assertion target for reversal)."""

    mode = "CPU"

    def __init__(self):
        self.fanout_frac = 1.0

    def set_fanout_frac(self, frac):
        self.fanout_frac = float(frac)

    def sample(self, ids):
        return _StubBatch(np.asarray(ids))


class _StubFeature:
    """Row gather stand-in; node_count=0 keeps the server from trying
    to attach a real cold cache to it."""

    node_count = 0
    cache_count = 0

    def __getitem__(self, ids):
        return np.zeros((len(ids), 4), dtype=np.float32)


def _busy_wait(seconds: float) -> None:
    # sleep() under-runs on some platforms for sub-ms waits; a spin
    # keeps the simulated service time honest enough for queueing
    end = time.perf_counter() + seconds
    while time.perf_counter() < end:
        pass


def _make_apply(service_s: float):
    def apply_fn(params, x, layers):
        _busy_wait(service_s)
        return np.zeros((len(x), 2), dtype=np.float32)

    return apply_fn


def _percentile(xs, p):
    return float(np.percentile(np.asarray(xs), p)) if xs else 0.0


def _schedule(rng, phases, steady, burst_x):
    """Pre-generate the full arrival schedule: a time-sorted list of
    ``(t_offset, phase, tenant, n_ids)``.  Burst arrivals follow a
    zipfian tenant mix weighted toward the floor class."""
    sched = []
    t0 = 0.0
    tenants = list(steady)
    # zipf-ish burst weights, heaviest on the LAST (lowest) class
    zipf = np.array([1.0 / (len(tenants) - i) for i in range(len(tenants))])
    zipf = zipf / zipf.sum()
    for name, dur, mult in phases:
        for ti, tenant in enumerate(tenants):
            rate = steady[tenant] * (mult * zipf[ti] * len(tenants)
                                     if mult > 1 else 1.0)
            n = int(rate * dur)
            ts = t0 + rng.uniform(0.0, dur, size=n)
            for t in ts:
                sched.append((float(t), name, tenant,
                              int(rng.integers(1, 6))))
        t0 += dur
    sched.sort(key=lambda e: e[0])
    return sched


def run_qos_load(smoke: bool = False, seed: int = 0,
                 qos_enabled: bool = True, with_chaos: bool = True,
                 burst_x: float = 10.0) -> dict:
    """Run the harness and return the report dict.  Restores all
    process-wide state (config, telemetry, qos, chaos) on exit."""
    import quiver_tpu.config as config_mod
    from quiver_tpu import telemetry
    from quiver_tpu.resilience import chaos as chaos_mod
    from quiver_tpu.resilience import qos as qos_mod
    from quiver_tpu.resilience.qos import QoSController, serving_ladder
    from quiver_tpu.serving import (HybridSampler, InferenceServer,
                                    RequestBatcher, ServingRequest)
    from quiver_tpu.ops.coldcache import ColdRowCache
    from quiver_tpu.telemetry.slo import SLOWatchdog

    cfg = config_mod.get_config()
    keys = ("qos_enabled", "qos_tenants", "serving_deadline_ms",
            "serving_queue_depth",
            "qos_breach_ticks", "qos_recover_ticks", "qos_admit_window_ms")
    saved = {k: getattr(cfg, k) for k in keys}
    telemetry.set_enabled(True)
    telemetry.reset()
    qos_mod.reset()
    config_mod.update(
        qos_enabled=qos_enabled, qos_tenants=TENANTS,
        serving_deadline_ms=0,      # latency is reported, not a deadline
        serving_queue_depth=64,     # small lanes: watermark sheds engage
        qos_breach_ticks=1, qos_recover_ticks=1,
        qos_admit_window_ms=1.0,
    )

    rng = np.random.default_rng(seed)
    dur = 0.5 if smoke else 2.0
    phases = [("baseline", dur, 1.0), ("burst", dur * 1.5, burst_x),
              ("cool", dur * 1.5, 1.0)]
    sched = _schedule(rng, phases, STEADY_RPS, burst_x)

    controller = None
    ladder = None
    sampler = _StubSampler()
    cold_cache = ColdRowCache(capacity=64, n_rows=1024)
    if qos_enabled:
        controller = qos_mod.install_qos(QoSController())
        ladder = serving_ladder(controller, sampler=sampler,
                                cold_cache=cold_cache)
    # SLO objective the burst is sized to breach: the stub service time
    # times the burst backlog pushes p99 far over this
    watchdog = SLOWatchdog(interval_s=3600.0, p99_ms=40.0,
                           error_ratio=1.1, coldcache_hit_floor=0.0)
    if ladder is not None:
        ladder.attach(watchdog, objectives=("p99_latency",))

    results: "queue.Queue" = queue.Queue()
    stream: "queue.Queue" = queue.Queue()
    # mode="Auto" with no neighbour_num sends everything to the device
    # lane; the ladder's cpu_floor step then reroutes the floor class to
    # the CPU lane, which HybridSampler + the server's cpu loop consume
    rb = RequestBatcher([stream], mode="Auto", result_queue=results,
                        qos=controller).start()
    hs = HybridSampler(sampler, rb.cpu_batched_queue, num_workers=2,
                       result_queue=results).start()
    server = InferenceServer(
        sampler, _StubFeature(), _make_apply(0.008), params=None,
        device_batched_queue=rb.device_batched_queue,
        cpu_sampled_queue=hs.sampled_queue,
        result_queue=results, fused=False, max_coalesce=4,
        cpu_sampler=sampler, qos=controller,
    ).start()

    # collector: every answer, tagged by the seq->(phase, tenant) map
    meta: dict = {}
    stats: dict = {}
    answered = [0]
    ans_lock = threading.Lock()
    done = threading.Event()

    def _bucket(phase, tenant):
        return stats.setdefault((phase, tenant), {
            "offered": 0, "ok": 0, "shed": 0, "rejected": 0,
            "error": 0, "latencies": []})

    def _collect():
        from quiver_tpu.resilience.errors import (DeadlineExceeded,
                                                  LoadShed, QuotaExceeded)

        while not done.is_set() or answered[0] < len(meta):
            try:
                req, ans = results.get(timeout=0.2)
            except queue.Empty:
                continue
            phase, tenant = meta.get(req.seq, ("?", "?"))
            b = _bucket(phase, tenant)
            if isinstance(ans, QuotaExceeded):
                b["rejected"] += 1
            elif isinstance(ans, (LoadShed, DeadlineExceeded)):
                b["shed"] += 1
            elif isinstance(ans, Exception):
                b["error"] += 1
            else:
                b["ok"] += 1
                b["latencies"].append(time.perf_counter() - req.t_enqueue)
            with ans_lock:
                answered[0] += 1

    collector = threading.Thread(target=_collect, daemon=True)
    collector.start()

    # SLO ticker driving the ladder (one observe per evaluation)
    tick_stop = threading.Event()

    def _ticker():
        while not tick_stop.wait(0.15):
            watchdog.evaluate_once()

    ticker = threading.Thread(target=_ticker, daemon=True)
    ticker.start()

    peak_level = 0
    if with_chaos and qos_enabled:
        # scripted mid-burst faults on the device lane: 3 one-shot
        # failures starting partway into the burst phase's traffic
        burst_start = sum(1 for e in sched if e[1] == "baseline")
        plan = chaos_mod.ChaosPlan(seed=seed)
        plan.fail("serving.device_lane", times=3,
                  after=burst_start + 20, every=15)
        chaos_mod.install(plan)

    t_start = time.perf_counter()
    seq = 0
    phase_end = {}
    t_acc = 0.0
    for name, d, _ in phases:
        t_acc += d
        phase_end[name] = t_acc
    cur_phase = phases[0][0]
    for t_off, phase, tenant, n in sched:
        if phase != cur_phase:
            # phase barrier: wait until everything submitted so far is
            # answered before the next phase's clock starts (closed loop)
            while True:
                with ans_lock:
                    if answered[0] >= seq:
                        break
                time.sleep(0.005)
            cur_phase = phase
        now = time.perf_counter() - t_start
        if t_off > now:
            time.sleep(t_off - now)
        ids = np.asarray(rng.integers(0, 1024, size=n), dtype=np.int64)
        meta[seq] = (phase, tenant)
        req = ServingRequest(ids=ids, client=0, seq=seq, tenant=tenant)
        _bucket(phase, tenant)["offered"] += 1
        stream.put(req)
        seq += 1
        if ladder is not None:
            peak_level = max(peak_level, ladder.level)
    # final barrier, then let the ladder walk home on an idle system
    while True:
        with ans_lock:
            if answered[0] >= seq:
                break
        time.sleep(0.005)
    if ladder is not None:
        deadline = time.perf_counter() + (5.0 if not smoke else 3.0)
        while ladder.level > 0 and time.perf_counter() < deadline:
            time.sleep(0.05)

    tick_stop.set()
    ticker.join(timeout=2.0)
    done.set()
    collector.join(timeout=2.0)
    chaos_mod.uninstall()
    rb.stop()
    hs.stop()
    server.stop()

    report = {
        "seed": seed, "smoke": smoke, "qos_enabled": qos_enabled,
        "burst_x": burst_x, "requests": seq,
        "phases": [p[0] for p in phases],
        "tenants": {},
        "peak_level": peak_level,
        "final_level": ladder.level if ladder is not None else 0,
        "fanout_frac": sampler.fanout_frac,
        "coldcache_paused": cold_cache.admission_paused,
        "ladder": ladder.status() if ladder is not None else None,
    }
    for (phase, tenant), b in sorted(stats.items()):
        lat = b.pop("latencies")
        entry = dict(b)
        entry["p50_ms"] = round(_percentile(lat, 50) * 1e3, 2)
        entry["p99_ms"] = round(_percentile(lat, 99) * 1e3, 2)
        dur_s = phases[[p[0] for p in phases].index(phase)][1]
        entry["goodput_rps"] = round(b["ok"] / dur_s, 1)
        report["tenants"].setdefault(tenant, {})[phase] = entry

    # restore process-wide state
    telemetry.reset()
    qos_mod.reset()
    config_mod.update(**saved)
    return report


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--burst-x", type=float, default=10.0)
    ap.add_argument("--no-chaos", action="store_true")
    ap.add_argument("--no-qos", action="store_true")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args()
    rep = run_qos_load(smoke=args.smoke, seed=args.seed,
                       qos_enabled=not args.no_qos,
                       with_chaos=not args.no_chaos, burst_x=args.burst_x)
    if args.json:
        print(json.dumps(rep, indent=2))
        return
    print(f"qos_load: {rep['requests']} requests, burst x{rep['burst_x']}, "
          f"peak ladder level {rep['peak_level']}, "
          f"final level {rep['final_level']} "
          f"(fanout {rep['fanout_frac']}, "
          f"coldcache_paused={rep['coldcache_paused']})")
    hdr = f"{'tenant':<8} {'phase':<9} {'offer':>6} {'ok':>6} {'shed':>5} " \
          f"{'rej':>5} {'err':>4} {'p50ms':>7} {'p99ms':>8} {'rps':>7}"
    print(hdr)
    for tenant, by_phase in sorted(rep["tenants"].items()):
        for phase in rep["phases"]:
            e = by_phase.get(phase)
            if e is None:
                continue
            print(f"{tenant:<8} {phase:<9} {e['offered']:>6} {e['ok']:>6} "
                  f"{e['shed']:>5} {e['rejected']:>5} {e['error']:>4} "
                  f"{e['p50_ms']:>7.1f} {e['p99_ms']:>8.1f} "
                  f"{e['goodput_rps']:>7.1f}")


if __name__ == "__main__":
    main()
