"""Noise-aware perf-regression gate (docs/BENCHMARKS.md "Perfgate").

``bench.py`` answers "how fast is the library?"; this gate answers the
cheaper CI question "did THIS change make it slower?".  It runs a
k-rep micro-bench over a fixed set of library hot paths (WAL append,
span + timeline emit overhead, Prometheus exposition, the CPU
sampler), compares each metric's MIN-of-k (timing noise on a shared
host is strictly additive, so the min is the stable run-to-run
estimator; median + MAD ride along to size the noise threshold)
against the committed baseline in ``.bench_state.json`` (top-level
``"perfgate"`` key, one entry per backend), and writes a
``PERFGATE.json`` verdict.

Noise model: wall-clock micro-benches on shared runners jitter, so a
raw threshold would flap.  A metric regresses only when the slowdown
clears BOTH bars:

  * ``config.perfgate_mad_mult`` x the MAD-derived robust sigma
    (1.4826 x max(baseline MAD, current MAD)) — statistically clear of
    the measured run-to-run noise;
  * ``config.perfgate_rel_floor`` x baseline — large enough in
    relative terms to be worth gating on at all (a statistically-clear
    2% drift on a 40 µs metric is not a gate-worthy regression).

Honesty stamping (same rules as bench.py): the verdict carries the
backend this process actually initialized and
``source: "cpu_rehearsal"`` unless it ran on real silicon — a CPU CI
verdict can never masquerade as device evidence.  CI runs with
``--report-only`` on CPU-only runners: the verdict is still written
and uploaded, but the exit code stays 0 (soft-fail).

Exit codes: 0 = pass / baseline seeded / report-only; 1 = regression.

Test hook: ``QUIVER_PERFGATE_INJECT`` multiplies measured medians by a
factor (``"2.0"`` for all metrics, or ``"wal_append:3.0"`` for one) —
the synthetic regression the acceptance test drives through the real
compare path.
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import tempfile
import time
from typing import Callable, Dict, List, Optional

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

STATE_PATH = os.path.join(_REPO, ".bench_state.json")
OUT_PATH = os.path.join(_REPO, "PERFGATE.json")

# the mesh_gather metric needs a multi-device mesh.  When this module
# loads before jax initializes (CI: `python benchmarks/perfgate.py`),
# stage the CPU-rehearsal virtual slice; embedders that already booted
# a backend (bench --check, tests) are unaffected — the flag is only
# read at backend init, and the metric clamps its shard count to the
# devices actually visible.
if "--xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8").strip()


# ---------------------------------------------------------------- metrics
def _m_wal_append() -> float:
    """ms per 200 batched-fsync WAL appends (blockio + framing path)."""
    from quiver_tpu.recovery.wal import WriteAheadLog

    with tempfile.TemporaryDirectory() as root:
        wal = WriteAheadLog(root, fsync="batch", batch_bytes=1 << 20)
        payload = b"x" * 128
        t0 = time.perf_counter()
        for _ in range(200):
            wal.append(payload)
        dt = time.perf_counter() - t0
        wal.close()
    return dt * 1e3


def _m_spans() -> float:
    """ms per 5000 span open/close (aggregation path, no retention)."""
    from quiver_tpu import telemetry

    tracer = telemetry.SpanTracer(tracing=False)
    t0 = time.perf_counter()
    for _ in range(5000):
        with tracer.span("perfgate.scope"):
            pass
    return (time.perf_counter() - t0) * 1e3


def _m_timeline_emit() -> float:
    """ms per 5000 timeline emits into a private ring set."""
    from quiver_tpu.telemetry import timeline

    timeline.reset()
    if not timeline.enable(capacity=8192):
        raise RuntimeError("telemetry disabled")
    try:
        t0 = time.perf_counter()
        for _ in range(5000):
            timeline.emit("perfgate.emit", cat="app", dur_s=1e-6)
        return (time.perf_counter() - t0) * 1e3
    finally:
        timeline.reset()


def _m_prom_text() -> float:
    """ms to render a 600-series registry snapshot as Prometheus text."""
    from quiver_tpu.telemetry import MetricsRegistry
    from quiver_tpu.telemetry.export import to_prometheus_text

    reg = MetricsRegistry()
    for i in range(200):
        reg.counter("perfgate_counter_total", shard=str(i)).inc(float(i))
        reg.gauge("perfgate_gauge", shard=str(i)).set(float(i))
        reg.histogram("perfgate_hist_seconds", shard=str(i)).observe(
            i * 1e-3)
    snap = reg.snapshot()
    t0 = time.perf_counter()
    to_prometheus_text(snap)
    return (time.perf_counter() - t0) * 1e3


def _m_sampler_cpu() -> float:
    """ms per CPU-lane sample batch on a 20K-node synthetic graph."""
    import numpy as np

    from quiver_tpu import CSRTopo
    from quiver_tpu.sampler import GraphSageSampler

    rng = np.random.default_rng(0)
    n, deg = 20_000, 15
    indices = rng.integers(0, n, size=n * deg, dtype=np.int64)
    indptr = np.arange(0, n * deg + 1, deg, dtype=np.int64)
    topo = CSRTopo(indptr=indptr, indices=indices)
    sampler = GraphSageSampler(topo, [10, 5], mode="CPU")
    seeds = rng.integers(0, n, size=256, dtype=np.int64)
    sampler.sample(seeds)  # warm (allocators, native table setup)
    t0 = time.perf_counter()
    for _ in range(5):
        sampler.sample(seeds)
    return (time.perf_counter() - t0) / 5 * 1e3


def _m_fleet_trace_stamp() -> float:
    """ms per 1000 fleet trace stamp + finish pairs — the federation-ON
    request-path bookkeeping (TraceContext, payload stamp, hop record,
    timeline slice when on) without any network in the number."""
    from quiver_tpu.fleet import FleetRouter, MembershipDirectory
    from quiver_tpu.telemetry import flightrec

    with tempfile.TemporaryDirectory() as fdir:
        router = FleetRouter(MembershipDirectory(fdir),
                             federation=True, scan_ttl_s=60.0)
        rec = flightrec.get_recorder()
        try:
            t0 = time.perf_counter()
            for _ in range(1000):
                req = {"ids": [1], "tenant": None}
                ctx, hop = router._trace_begin(req, None, 1)
                if ctx is None:
                    raise RuntimeError("telemetry disabled")
                router._trace_finish(hop, ctx)
                rec.finish(ctx, 0.0, lane="perfgate")
            dt = time.perf_counter() - t0
        finally:
            router.close()
    return dt * 1e3


def _m_fleet_router_off() -> float:
    """ms per 200 federation-OFF ``router.request`` round trips against
    an in-process echo replica — the one-config-check request path the
    disabled plane must keep byte-identical to PR 13."""
    import socketserver
    import threading

    from quiver_tpu.fleet import FleetRouter, MembershipDirectory
    from quiver_tpu.fleet.membership import ReplicaInfo

    class _Echo(socketserver.StreamRequestHandler):
        def handle(self):
            while True:
                if not self.rfile.readline():
                    return
                self.wfile.write(b'{"status": "ok"}\n')

    class _Srv(socketserver.ThreadingTCPServer):
        allow_reuse_address = True
        daemon_threads = True

    with tempfile.TemporaryDirectory() as fdir:
        srv = _Srv(("127.0.0.1", 0), _Echo)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        try:
            directory = MembershipDirectory(fdir,
                                            heartbeat_timeout_s=60.0)
            directory.announce(ReplicaInfo(
                "echo", state="serving", port=srv.server_address[1]))
            router = FleetRouter(directory, scan_ttl_s=60.0,
                                 federation=False)
            router.request([1])  # warm: scan, ring, breaker, socket
            t0 = time.perf_counter()
            for i in range(200):
                router.request([1], seq=i)
            dt = time.perf_counter() - t0
            router.close()
        finally:
            srv.shutdown()
            srv.server_close()
    return dt * 1e3


def _m_mesh_gather() -> float:
    """ms per warmed 4-shard mesh gather batch (B=256) on the CPU
    rehearsal mesh — the steady-state sharded-serving hot path: shard
    ownership planning, the shard_map collective, halo accounting.
    Clamps to the visible device count when an embedder initialized a
    smaller backend before the rehearsal flag could be staged."""
    import jax
    import numpy as np

    from quiver_tpu.mesh import MeshFeature

    rng = np.random.default_rng(0)
    table = rng.standard_normal((20_000, 32)).astype(np.float32)
    mf = MeshFeature(table, n_shards=min(4, jax.device_count()))
    ids = rng.integers(0, 20_000, 256)
    mf[ids].block_until_ready()  # warm: faults, restack, gather build
    t0 = time.perf_counter()
    for _ in range(10):
        out = mf[ids]
    out.block_until_ready()
    return (time.perf_counter() - t0) / 10 * 1e3


def _m_quiverlint_run() -> float:
    """ms for one full quiverlint pass over the lint targets — parse,
    ONE shared Program build, every per-file and program rule (QT001..
    QT015 incl. the staging-dataflow fixpoint).  The v3 one-parse
    architecture is only honest if whole-repo analysis stays cheap
    enough for tier-1; this metric is the receipt."""
    from quiver_tpu.analysis import analyze_paths

    t0 = time.perf_counter()
    res = analyze_paths(["quiver_tpu", "bench.py"], root=_REPO)
    dt = time.perf_counter() - t0
    if res.errors:
        raise RuntimeError(f"lint errors: {res.errors[:3]}")
    return dt * 1e3


METRICS: Dict[str, Callable[[], float]] = {
    "wal_append": _m_wal_append,
    "spans": _m_spans,
    "timeline_emit": _m_timeline_emit,
    "prom_text": _m_prom_text,
    "sampler_cpu": _m_sampler_cpu,
    "fleet_trace_stamp": _m_fleet_trace_stamp,
    "fleet_router_off": _m_fleet_router_off,
    "mesh_gather": _m_mesh_gather,
    "quiverlint_run": _m_quiverlint_run,
}


# ---------------------------------------------------------------- measure
def _mad(xs: List[float]) -> float:
    med = statistics.median(xs)
    return statistics.median([abs(x - med) for x in xs])


def measure(k: int, log=print) -> Dict[str, dict]:
    """Median-of-k per metric.  A metric that raises is reported as
    skipped (``error``), never crashes the gate — CI must degrade, not
    die, when e.g. the native sampler isn't built."""
    out: Dict[str, dict] = {}
    for name, fn in METRICS.items():
        try:
            fn()  # one warmup rep outside the sample
            xs = [fn() for _ in range(k)]
        except Exception as e:  # noqa: BLE001 — degrade per metric
            log(f"[perfgate] metric {name} skipped: {e}")
            out[name] = {"error": f"{type(e).__name__}: {e}"}
            continue
        # min is the gate's point estimate: timing noise on a shared
        # host is strictly additive, so min-of-k is far more stable
        # run-to-run than the median; median+MAD still size the noise
        # threshold and ride along for the report
        out[name] = {"min_ms": round(min(xs), 4),
                     "median_ms": round(statistics.median(xs), 4),
                     "mad_ms": round(_mad(xs), 4), "k": k,
                     "samples_ms": [round(x, 4) for x in xs]}
    return out


def _apply_injection(measured: Dict[str, dict], spec: str,
                     log=print) -> None:
    """QUIVER_PERFGATE_INJECT: synthetic slowdown through the real
    compare path ("2.0" = all metrics, "name:2.0" = one)."""
    name = None
    if ":" in spec:
        name, _, spec = spec.partition(":")
    try:
        factor = float(spec)
    except ValueError:
        log(f"[perfgate] bad QUIVER_PERFGATE_INJECT {spec!r}; ignored")
        return
    for m, rec in measured.items():
        if "median_ms" in rec and (name is None or m == name):
            rec["median_ms"] = round(rec["median_ms"] * factor, 4)
            if "min_ms" in rec:
                rec["min_ms"] = round(rec["min_ms"] * factor, 4)
            rec["injected_factor"] = factor


# ---------------------------------------------------------------- baseline
def _load_state(path: str) -> dict:
    try:
        raw = json.load(open(path))
        return raw if isinstance(raw, dict) else {}
    except Exception:
        return {}


def load_baseline(path: str, backend: str) -> Optional[dict]:
    gate = _load_state(path).get("perfgate")
    if isinstance(gate, dict):
        entry = gate.get(backend)
        if isinstance(entry, dict) and isinstance(entry.get("metrics"),
                                                  dict):
            return entry
    return None


def save_baseline(path: str, backend: str, measured: Dict[str, dict],
                  device: bool) -> None:
    """Read-merge-replace under the same flock bench.py's section saver
    takes, so a concurrent bench run can't lose either side's write."""
    import fcntl

    metrics = {m: {"min_ms": r.get("min_ms", r["median_ms"]),
                   "median_ms": r["median_ms"], "mad_ms": r["mad_ms"],
                   "k": r["k"]}
               for m, r in measured.items() if "median_ms" in r}
    with open(path + ".lock", "w") as lk:
        fcntl.flock(lk, fcntl.LOCK_EX)
        try:
            disk = _load_state(path)
            disk.setdefault("version", 2)
            disk.setdefault("states", {})
            disk.setdefault("perfgate", {})[backend] = {
                "metrics": metrics, "device": device,
                "source": "live_device" if device else "cpu_rehearsal",
            }
            tmp = path + ".tmp"
            with open(tmp, "w") as fh:
                json.dump(disk, fh, indent=1, sort_keys=True)
            os.replace(tmp, path)
        finally:
            fcntl.flock(lk, fcntl.LOCK_UN)


# ---------------------------------------------------------------- verdict
def compare(baseline: dict, measured: Dict[str, dict], mad_mult: float,
            rel_floor: float) -> dict:
    metrics = {}
    regressions = []
    for name, base in baseline["metrics"].items():
        cur = measured.get(name)
        if cur is None or "median_ms" not in cur:
            metrics[name] = {"baseline_ms": base["median_ms"],
                             "status": "skipped",
                             "error": (cur or {}).get("error")}
            continue
        b_min = base.get("min_ms", base["median_ms"])
        c_min = cur.get("min_ms", cur["median_ms"])
        sigma = 1.4826 * max(base.get("mad_ms", 0.0), cur["mad_ms"], 1e-6)
        threshold = max(mad_mult * sigma, rel_floor * b_min)
        delta = c_min - b_min
        regressed = delta > threshold
        rec = {
            "baseline_ms": b_min, "current_ms": c_min,
            "delta_ms": round(delta, 4),
            "threshold_ms": round(threshold, 4),
            "rel_change": round(delta / b_min, 4) if b_min else None,
            "status": "regression" if regressed else "pass",
        }
        if "injected_factor" in cur:
            rec["injected_factor"] = cur["injected_factor"]
        metrics[name] = rec
        if regressed:
            regressions.append(name)
    new = sorted(set(m for m, r in measured.items() if "median_ms" in r)
                 - set(baseline["metrics"]))
    return {"metrics": metrics, "regressions": regressions,
            "new_metrics": new}


def run_gate(k: Optional[int] = None, seed: bool = False,
             report_only: bool = False, state_path: str = STATE_PATH,
             out_path: str = OUT_PATH, log=print) -> int:
    from quiver_tpu.config import get_config

    cfg = get_config()
    if k is None:
        k = int(cfg.perfgate_k)
    try:
        import jax

        backend = jax.default_backend()
    except Exception:
        backend = "none"
    device = backend not in ("cpu", "none")

    measured = measure(k, log=log)
    inject = os.environ.get("QUIVER_PERFGATE_INJECT", "").strip()
    if inject:
        _apply_injection(measured, inject, log=log)

    verdict = {
        "backend": backend,
        "device": device,
        "source": "live_device" if device else "cpu_rehearsal",
        "report_only": bool(report_only),
        "k": k,
        "mad_mult": float(cfg.perfgate_mad_mult),
        "rel_floor": float(cfg.perfgate_rel_floor),
        "measured": measured,
    }
    baseline = load_baseline(state_path, backend)
    if seed or baseline is None:
        save_baseline(state_path, backend, measured, device)
        verdict["status"] = "seeded"
        verdict["note"] = ("baseline seeded for backend "
                           f"{backend!r}; commit .bench_state.json")
        code = 0
    else:
        cmp = compare(baseline, measured, float(cfg.perfgate_mad_mult),
                      float(cfg.perfgate_rel_floor))
        verdict.update(cmp)
        verdict["status"] = ("regression" if cmp["regressions"]
                             else "pass")
        code = 1 if cmp["regressions"] else 0

    try:  # in-process visibility for embedders (bench --check, tests);
        # a no-op when telemetry is off
        from quiver_tpu import telemetry

        telemetry.gauge("perfgate_pass_state").set(
            0.0 if verdict.get("regressions") else 1.0)
        telemetry.gauge("perfgate_regressions").set(
            float(len(verdict.get("regressions", ()))))
    except Exception:
        pass
    with open(out_path, "w") as f:
        json.dump(verdict, f, indent=2, sort_keys=True)
    log(f"[perfgate] {verdict['status']} (backend={backend}, "
        f"source={verdict['source']}) -> {out_path}")
    for name in verdict.get("regressions", []):
        m = verdict["metrics"][name]
        log(f"[perfgate]   REGRESSION {name}: {m['baseline_ms']} -> "
            f"{m['current_ms']} ms (threshold +{m['threshold_ms']} ms)")
    if report_only and code:
        log("[perfgate] report-only: regression reported, exit 0")
        return 0
    return code


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--seed", action="store_true",
                    help="(re)write the baseline for this backend")
    ap.add_argument("--report-only", action="store_true",
                    help="write the verdict but always exit 0 (CI on "
                         "CPU-only runners)")
    ap.add_argument("--k", type=int, default=None,
                    help="repeats per metric (default config.perfgate_k)")
    ap.add_argument("--state", default=STATE_PATH,
                    help="baseline file (default .bench_state.json)")
    ap.add_argument("--out", default=OUT_PATH,
                    help="verdict file (default PERFGATE.json)")
    args = ap.parse_args(argv)
    return run_gate(k=args.k, seed=args.seed,
                    report_only=args.report_only, state_path=args.state,
                    out_path=args.out)


if __name__ == "__main__":
    sys.exit(main())
