"""Fold a harvested bench_FINAL.json into docs/tpu_measured.json.

Run after benchmarks/tpu_retry_loop.sh lands a valid harvest:

    python benchmarks/harvest_commit.py [/tmp/tpu_runs/bench_FINAL.json]

Validates the harvest gate (device==true, backend=="tpu",
headline_source=="live") and REFUSES replayed or CPU evidence.  Live
sections replace same-named committed ones; prior committed sections the
harvest did not re-measure are kept (they remain labeled by their own
source).  Prints a one-line summary for the commit message.
"""

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
MEASURED = os.path.join(REPO, "docs", "tpu_measured.json")


def main():
    src = sys.argv[1] if len(sys.argv) > 1 else "/tmp/tpu_runs/bench_FINAL.json"
    sys.path.insert(0, REPO)
    from bench import is_live_harvest  # the ONE gate, shared with
    # tpu_retry_loop.sh's validity check

    lines = [ln for ln in open(src) if ln.strip()]
    harvest = json.loads(lines[-1])
    if not is_live_harvest(harvest):
        sys.exit(f"REFUSED: not live TPU evidence "
                 f"(device={harvest.get('device')} "
                 f"backend={harvest.get('backend')} "
                 f"source={harvest.get('headline_source')})")
    try:
        measured = json.load(open(MEASURED))
    except Exception:
        measured = {"sections": {}}
    live = {k: v for k, v in harvest["sections"].items()
            if isinstance(v, dict) and "source" not in v}
    # kept sections predate this harvest: stamp each with the prior
    # top-level source BEFORE it is overwritten, or old evidence would
    # silently re-date to the new harvest
    prior_source = measured.get("source", "earlier measurement")
    kept = {}
    for k, v in measured.get("sections", {}).items():
        if k in live:
            continue
        if isinstance(v, dict) and "source" not in v:
            v = dict(v, source=prior_source)
        kept[k] = v
    measured["sections"] = {**kept, **live}
    measured["source"] = (
        f"on-chip harvest {time.strftime('%Y-%m-%d %H:%MZ', time.gmtime())}"
        f" (benchmarks/tpu_retry_loop.sh); earlier sections retain their "
        f"own source notes")
    measured["headline"] = {
        "value": harvest["value"], "unit": harvest.get("unit"),
        "vs_baseline": harvest.get("vs_baseline"),
    }
    with open(MEASURED, "w") as f:
        json.dump(measured, f, indent=1)
    print(f"merged {len(live)} live sections into docs/tpu_measured.json: "
          f"{sorted(live)}; headline {harvest['value']:.3g} "
          f"(vs_baseline {harvest.get('vs_baseline')})")


if __name__ == "__main__":
    main()
