"""Model-quality stand-in for the reference's ogbn-products accuracy run.

The reference trains 3-layer GraphSAGE on ogbn-products to test acc
~0.787 (`examples/pyg/ogbn_products_sage_quiver.py:1`, reference repo).
OGB data cannot be staged here (zero egress), so this harness trains the
SAME pipeline (GraphSageSampler -> Feature -> fused train step) on a
synthetic products-scale community graph whose labels are only
recoverable by aggregating neighbours: per-node features carry the class
one-hot at noise sigma where a feature-only classifier is weak, while
~80% homophilous edges let a GNN average the noise away.  Numbers are
published as a documented stand-in, not as OGB accuracy.

Run:  python benchmarks/quality_run.py            (500K nodes, CPU-sized)
      python benchmarks/quality_run.py --products (2.45M nodes, for TPU)
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run_quality(n_nodes=500_000, n_classes=47, dim=100, batch=1024,
                fanout=(15, 10, 5), epochs=3, train_frac=0.08,
                val_frac=0.016, noise=1.2, intra_deg=40, inter_deg=10,
                hidden=256, lr=3e-3, seed=0, steps_per_epoch=None,
                eval_batches=24, label_noise=0.15, log=print):
    """Train GraphSAGE through the full quiver_tpu pipeline; return loss
    curve, per-epoch val accuracy, held-out test accuracy, epoch times.

    All seeds fixed; the noise level (sigma=1.2 on a one-hot signal)
    makes single-node features weak — a majority vote over the ~80%%
    homophilous sampled neighbourhood is what the model must learn, so
    accuracy genuinely certifies sampler+gather+training correctness
    (parity intent: reference `examples/pyg/ogbn_products_sage_quiver.py`
    train/test loop).

    ``label_noise``: fraction of OBSERVED labels (train and eval alike)
    flipped uniformly to a DIFFERENT class — the irreducible noise real
    datasets carry.  A flipped label never equals the true class, so the
    Bayes-optimal predictor (the true community) scores exactly
    ``1 - rho`` (0.85 at rho=0.15): a converged pipeline should approach
    the returned ``bayes_ceiling``, not 1.0 (a saturating synthetic task
    certifies nothing).
    """
    import jax
    import jax.numpy as jnp
    import optax

    from quiver_tpu import Feature, GraphSageSampler
    from quiver_tpu.models import GraphSAGE
    from quiver_tpu.parallel import TrainState
    from quiver_tpu.pipeline import make_fused_train_step
    from quiver_tpu.utils.rng import make_key
    from quiver_tpu.utils.synthetic import community_graph

    t0 = time.perf_counter()
    topo, feat, labels = community_graph(
        n_nodes, n_classes, intra_deg=intra_deg, inter_deg=inter_deg,
        noise=noise, feat_extra=dim - n_classes, seed=seed)
    if label_noise > 0:
        nrng = np.random.default_rng(seed + 7)
        flip = nrng.random(n_nodes) < label_noise
        offs = nrng.integers(1, n_classes, n_nodes).astype(np.int32)
        labels = np.where(flip, (labels + offs) % n_classes, labels)
        labels = labels.astype(np.int32)
    bayes = 1.0 - label_noise
    log(f"graph: N={topo.node_count:,} E={topo.edge_count:,} "
        f"dim={feat.shape[1]} label_noise={label_noise} "
        f"(bayes ceiling ~{bayes:.3f}) ({time.perf_counter() - t0:.1f}s)")

    rng = np.random.default_rng(seed + 1)
    perm = rng.permutation(n_nodes)
    n_train = int(train_frac * n_nodes)
    n_val = int(val_frac * n_nodes)
    train_ids = perm[:n_train]
    val_ids = perm[n_train:n_train + n_val]
    test_ids = perm[n_train + n_val:]

    sampler = GraphSageSampler(topo, list(fanout))
    feature = Feature(device_cache_size=n_nodes,
                      cache_unit="rows").from_cpu_tensor(feat)
    model = GraphSAGE(hidden=hidden, out_dim=n_classes, num_layers=len(fanout))
    tx = optax.adam(lr)

    b0 = sampler.sample(train_ids[:batch].astype(np.int32))
    x0 = feature[np.asarray(b0.n_id)]
    params = model.init(make_key(0), x0, b0.layers)
    state = TrainState.create(params, tx)
    step = make_fused_train_step(
        sampler, feature,
        lambda p, x, blocks, train=False, rngs=None: model.apply(
            p, x, blocks, train=train, rngs=rngs), tx)

    apply_fn = jax.jit(
        lambda p, x, blocks: model.apply(p, x, blocks, train=False))

    labels_d = jnp.asarray(labels)
    ones = jnp.ones((batch,), bool)

    def predict_acc(ids, max_batches, key0):
        """Sampled inference accuracy over fixed-size batches (bucketed
        to one executable; ids are shuffled, so a capped batch count is
        an unbiased subsample).  A set smaller than one batch is padded
        by wrapping and scored on the valid prefix only — small smoke
        configs must report a real accuracy, not a silent 0.0."""
        if len(ids) == 0:
            return float("nan")
        nb = min(max_batches, max(1, len(ids) // batch))
        correct = total = 0
        for i in range(nb):
            chunk = ids[i * batch: (i + 1) * batch]
            valid = len(chunk)
            if valid < batch:
                chunk = np.resize(chunk, batch)
            s = chunk.astype(np.int32)
            b = sampler.sample(s, key=make_key(key0 + i))
            x = feature[b.n_id]
            logits = apply_fn(state.params, x, b.layers)
            pred = np.asarray(jnp.argmax(logits[:batch], axis=-1))
            correct += int((pred[:valid] == labels[s[:valid]]).sum())
            total += valid
        return correct / max(total, 1)

    # always at least one step; a train split smaller than spe*batch
    # wraps around (np.resize repeats), so tiny --nodes configs still run
    spe = steps_per_epoch or max(1, n_train // batch)
    losses, val_accs, epoch_times = [], [], []
    gstep = 0
    for ep in range(epochs):
        ep_t0 = time.perf_counter()
        order = rng.permutation(train_ids)
        if len(order) < spe * batch:
            order = np.resize(order, spe * batch)
        ep_losses = []
        for i in range(spe):
            s = jnp.asarray(order[i * batch: (i + 1) * batch]
                            .astype(np.int32))
            state, loss = step(state, s, jnp.take(labels_d, s), ones,
                               make_key(1000 + gstep))
            gstep += 1
            if i % 32 == 0:
                ep_losses.append(float(loss))
        float(loss)  # sync before timing
        dt = time.perf_counter() - ep_t0
        acc = predict_acc(val_ids, eval_batches, key0=500_000 + ep)
        losses.append(round(float(np.mean(ep_losses)), 4))
        val_accs.append(round(acc, 4))
        epoch_times.append(round(dt, 2))
        log(f"epoch {ep}: mean loss {losses[-1]}, val acc {acc:.4f}, "
            f"{dt:.1f}s ({spe} steps)")

    test_acc = predict_acc(rng.permutation(test_ids), eval_batches * 2,
                           key0=900_000)
    log(f"test acc: {test_acc:.4f}")
    return dict(losses=losses, val_accs=val_accs,
                test_acc=round(test_acc, 4),
                bayes_ceiling=round(bayes, 4),
                acc_vs_ceiling=round(test_acc / bayes, 4),
                epoch_s=epoch_times,
                steps_per_epoch=spe, batch=batch, fanout=list(fanout),
                n_nodes=n_nodes, n_classes=n_classes, noise=noise,
                label_noise=label_noise, seed=seed,
                dataset="synthetic-community (OGB stand-in)")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--products", action="store_true",
                    help="full 2.45M-node scale (TPU-sized)")
    ap.add_argument("--nodes", type=int, default=None)
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--steps-per-epoch", type=int, default=None)
    ap.add_argument("--cpu", action="store_true",
                    help="pin the CPU backend (else backend default)")
    args = ap.parse_args()
    if args.cpu:
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        jax.config.update("jax_platforms", "cpu")
    n = args.nodes or (2_449_029 if args.products else 500_000)
    out = run_quality(n_nodes=n, epochs=args.epochs,
                      steps_per_epoch=args.steps_per_epoch,
                      log=lambda *a: print(*a, file=sys.stderr, flush=True))
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
