"""Hardware autotune: probe the gather-mode / batch-size space on the
current accelerator and persist the winners as library defaults.

Run once per hardware generation:

    python benchmarks/autotune.py [--nodes N --edges E]

Writes ``.quiver_tpu_tuned.json`` at the repo root;
``quiver_tpu.config.get_config()`` picks it up automatically, so samplers
constructed with ``gather_mode="auto"`` use the measured winner.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, ".")

import numpy as np

TUNED_PATH = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), ".quiver_tpu_tuned.json")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=2_449_029)
    ap.add_argument("--edges", type=int, default=123_718_280)
    ap.add_argument("--fanout", type=int, nargs="+", default=[15, 10, 5])
    ap.add_argument("--batch", type=int, default=512)
    args = ap.parse_args()

    import jax

    from bench import build_graph
    from quiver_tpu import CSRTopo, GraphSageSampler

    indptr, indices = build_graph(args.nodes, args.edges)
    topo = CSRTopo(indptr=indptr, indices=indices)
    rng = np.random.default_rng(0)
    seeds = rng.integers(0, topo.node_count, args.batch).astype(np.int32)

    results = {}
    for gm in ("lanes", "lanes_fused", "xla"):
        try:
            s = GraphSageSampler(topo, args.fanout, gather_mode=gm)
            s.sample(seeds).n_id.block_until_ready()
            t0 = time.perf_counter()
            for r in range(3):
                s.sample(seeds,
                         key=jax.random.PRNGKey(r)).n_id.block_until_ready()
            results[gm] = (time.perf_counter() - t0) / 3
            print(f"{gm}: {results[gm] * 1e3:.1f} ms/batch")
        except Exception as e:
            print(f"{gm}: skipped ({type(e).__name__})")
    if not results:
        print("no mode succeeded; nothing written")
        return
    best = min(results, key=results.get)
    payload = {
        "gather_mode": best,
        "backend": jax.default_backend(),
        "device": str(jax.devices()[0]),
        "probe_ms": {k: round(v * 1e3, 2) for k, v in results.items()},
    }
    with open(TUNED_PATH, "w") as f:
        json.dump(payload, f, indent=2)
    print(f"tuned defaults -> {TUNED_PATH}: {payload}")


if __name__ == "__main__":
    main()
