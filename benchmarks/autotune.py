"""Hardware autotune: probe the gather-mode and sampling-RNG space on the
current accelerator and persist the winners as library defaults.

Run once per hardware generation:

    python benchmarks/autotune.py [--fanout 15 10 5 --batch 512]

Writes ``.quiver_tpu_tuned.json`` at the repo root;
``quiver_tpu.config.get_config()`` picks it up automatically, so samplers
constructed with ``gather_mode="auto"`` / ``sample_rng="auto"`` use the
measured winners.

Every probe runs in a killable SUBPROCESS (``bench.probe_sampler_
subprocess``): on a tunnel-attached TPU a wedged remote compile blocks
the probing thread inside a C call where no signal is ever delivered —
an in-process probe can hang this tool forever.
"""

import argparse
import json
import os
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# single source for the tuned-file location: bench._tuned_path



def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--fanout", type=int, nargs="+", default=[15, 10, 5])
    ap.add_argument("--batch", type=int, default=512)
    ap.add_argument("--timeout", type=int, default=420,
                    help="hard per-probe subprocess timeout (s)")
    args = ap.parse_args()

    import jax

    from bench import probe_sampler_subprocess

    def probe(gm, srng="auto"):
        tag = f"{gm}" + (f"+{srng}" if srng != "auto" else "")
        try:
            ms = probe_sampler_subprocess(gm, args.fanout, args.batch,
                                          args.timeout, sample_rng=srng)
        except subprocess.TimeoutExpired:
            print(f"{tag}: TIMEOUT after {args.timeout}s (killed)")
            return None
        except Exception as e:
            print(f"{tag}: skipped ({e})")
            return None
        print(f"{tag}: {ms:.1f} ms/batch")
        return ms

    from bench import GATHER_MODES_VERSION, PROBE_MODES, _tuned_path

    tuned_path = _tuned_path()

    results = {gm: ms for gm in PROBE_MODES
               if (ms := probe(gm)) is not None}
    if not results:
        print("no mode succeeded; nothing written")
        return
    best = min(results, key=results.get)

    # A/B the uniform source under the winning gather mode (key-based
    # jax.random.uniform vs counter-hash — docs/TPU_MEASUREMENTS.md
    # round 2 measured hash 1.5-2x faster on v5e; verify per hardware)
    rng_results = {srng: ms for srng in ("key", "hash")
                   if (ms := probe(best, srng)) is not None}

    payload = {
        "gather_mode": best,
        "device": str(jax.devices()[0]),
        # without this tag bench.pick_gather_mode distrusts the file and
        # re-probes every session (version gate on the mode set)
        "modes_version": GATHER_MODES_VERSION,
        "probe_ms": {k: round(v, 2) for k, v in results.items()},
    }
    if rng_results:
        payload["sample_rng"] = min(rng_results, key=rng_results.get)
        payload["rng_probe_ms"] = {
            k: round(v, 2) for k, v in rng_results.items()
        }
    # merge (bench.merge_tuned) so a dedup winner persisted by the e2e
    # A/B survives an autotune re-run
    from bench import merge_tuned

    written = merge_tuned(payload, jax.default_backend(), tuned_path)
    print(f"tuned defaults -> {tuned_path}: {written}")


if __name__ == "__main__":
    main()
