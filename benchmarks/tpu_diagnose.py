"""Bounded TPU diagnosis: which compiles are slow over the axon tunnel.

Each stage runs under a SIGALRM timeout and logs pass/fail + wall time, so
one pathological compile cannot consume a whole tunnel-up window.  Run by
``benchmarks/tpu_retry_loop.sh`` whenever the tunnel comes back.

Key experiment: jit-compile latency of threefry vs rbg RNG — round 2's
working hypothesis for the products-scale sampler compile hang.
"""

import os
import signal
import sys
import time

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/root/repo/.jax_cache")
sys.path.insert(0, "/root/repo")

import numpy as np

T0 = time.perf_counter()


def log(m):
    print(f"[{time.perf_counter() - T0:7.1f}s] {m}", flush=True)


class Timeout(Exception):
    pass


def _alarm(sig, frm):
    raise Timeout()


signal.signal(signal.SIGALRM, _alarm)


def stage(name, seconds, fn):
    log(f"--- {name} (limit {seconds}s)")
    signal.alarm(seconds)
    t0 = time.perf_counter()
    try:
        out = fn()
        dt = time.perf_counter() - t0
        log(f"ok {name}: {dt:.2f}s" + (f" -> {out}" if out else ""))
        return True
    except Timeout:
        log(f"TIMEOUT {name}")
        return False
    except Exception as e:
        log(f"FAIL {name}: {type(e).__name__}: {e}")
        return False
    finally:
        signal.alarm(0)


def main():
    import jax
    import jax.numpy as jnp

    stage("device init", 300, lambda: str(jax.devices()))
    stage("trivial jit", 120,
          lambda: float(jax.jit(lambda x: x * 2)(jnp.ones(8))[0]))

    key_t = jax.random.key(0, impl="threefry2x32")
    key_r = jax.random.key(0, impl="rbg")

    stage("uniform rbg compile", 180,
          lambda: jax.jit(
              lambda k: jax.random.uniform(k, (1024, 15))
          )(key_r).block_until_ready() and None)
    stage("uniform threefry compile", 180,
          lambda: jax.jit(
              lambda k: jax.random.uniform(k, (1024, 15))
          )(key_t).block_until_ready() and None)

    from quiver_tpu import CSRTopo, GraphSageSampler
    from quiver_tpu.utils.synthetic import synthetic_csr

    indptr, indices = synthetic_csr(100_000, 2_000_000, 0)
    topo = CSRTopo(indptr=indptr, indices=indices)

    def hop(gm, key):
        s = GraphSageSampler(topo, [15], gather_mode=gm)
        seeds = np.arange(256, dtype=np.int32)
        s.sample(seeds, key=key).n_id.block_until_ready()

    stage("one-hop xla + rbg", 240, lambda: hop("xla", key_r))
    stage("one-hop xla + threefry", 240, lambda: hop("xla", key_t))
    stage("one-hop pallas + rbg", 240, lambda: hop("pallas", key_r))
    stage("one-hop lanes + rbg", 240, lambda: hop("lanes", key_r))

    def hop3(gm):
        s = GraphSageSampler(topo, [15, 10, 5], gather_mode=gm)
        seeds = np.arange(1024, dtype=np.int32)
        s.sample(seeds, key=key_r).n_id.block_until_ready()
        t0 = time.perf_counter()
        for i in range(3):
            s.sample(seeds, key=jax.random.fold_in(key_r, i)
                     ).n_id.block_until_ready()
        return f"{(time.perf_counter() - t0) / 3 * 1e3:.1f} ms/batch steady"

    stage("3-hop xla + rbg (small graph)", 300, lambda: hop3("xla"))
    stage("3-hop pallas + rbg (small graph)", 300, lambda: hop3("pallas"))

    def hop3_hash():
        s = GraphSageSampler(topo, [15, 10, 5], gather_mode="xla",
                             sample_rng="hash")
        s.sample(np.arange(1024, dtype=np.int32),
                 key=key_r).n_id.block_until_ready()

    stage("3-hop xla + HASH rng (small graph)", 300, hop3_hash)

    # ---- cold-tier placement experiment: can the TPU gather rows from a
    # host-memory-kind array under jit (the true zero-copy analogue)?
    def pinned_host_gather():
        from jax.sharding import SingleDeviceSharding

        dev = jax.devices()[0]
        rows = np.random.default_rng(0).normal(
            size=(200_000, 128)).astype(np.float32)
        try:
            host_shard = SingleDeviceSharding(dev, memory_kind="pinned_host")
        except TypeError:
            return "SingleDeviceSharding has no memory_kind — skip"
        arr = jax.device_put(rows, host_shard)
        idx = jnp.asarray(np.random.default_rng(1).integers(
            0, 200_000, 50_000, dtype=np.int32))

        @jax.jit
        def take(a, i):
            return jnp.take(a, i, axis=0)

        out = take(arr, idx)
        out.block_until_ready()
        t0 = time.perf_counter()
        for _ in range(5):
            out = take(arr, idx)
        out.block_until_ready()
        dt = (time.perf_counter() - t0) / 5
        gbs = 50_000 * 128 * 4 / dt / 1e9
        return f"pinned_host gather {gbs:.2f} GB/s ({dt * 1e3:.1f} ms)"

    stage("pinned_host cold gather", 240, pinned_host_gather)

    def host_roundtrip_gather():
        rows = np.random.default_rng(0).normal(
            size=(200_000, 128)).astype(np.float32)
        idx = np.random.default_rng(1).integers(0, 200_000, 50_000)
        t0 = time.perf_counter()
        for _ in range(5):
            out = jnp.asarray(rows[idx])
        out.block_until_ready()
        dt = (time.perf_counter() - t0) / 5
        return (f"host-gather+H2D {50_000 * 128 * 4 / dt / 1e9:.2f} GB/s "
                f"({dt * 1e3:.1f} ms)")

    stage("host numpy gather + upload", 240, host_roundtrip_gather)
    log("DIAGNOSE DONE")


if __name__ == "__main__":
    main()
