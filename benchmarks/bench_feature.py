"""Feature-collection throughput benchmark (GB/s).

Mirrors the reference's feature benchmarks behind
docs/Introduction_en.md:90-126 (single-device cache 14.82 GB/s; NVLink
clique 108.6 GB/s).  Compares:
  * XLA row gather (``jnp.take``) — the Feature hot path
  * Pallas pipelined-DMA gather (``ops.pallas.gather_rows``)
  * Feature with partial cache (hot/cold mix, host tail)
"""

import argparse
import sys
import time

sys.path.insert(0, ".")

import numpy as np


def bench(name, fn, *args, iters=20, bytes_per_iter=0):
    import jax

    fn(*args)
    jax.block_until_ready(fn(*args))
    t0 = time.perf_counter()
    outs = [fn(*args) for _ in range(iters)]
    jax.block_until_ready(outs[-1])
    dt = time.perf_counter() - t0
    gbs = bytes_per_iter * iters / dt / 1e9
    print(f"{name:<42} {gbs:8.2f} GB/s  ({dt / iters * 1e3:.2f} ms)")
    return gbs


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=2_449_029)
    ap.add_argument("--dim", type=int, default=100)
    ap.add_argument("--rows", type=int, default=500_000)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from quiver_tpu import CSRTopo, Feature
    from quiver_tpu.ops.pallas.gather_kernel import gather_rows

    rng = np.random.default_rng(0)
    n, d, m = args.nodes, args.dim, args.rows
    feat = rng.normal(size=(n, d)).astype(np.float32)
    table = jnp.asarray(feat)
    idx = jnp.asarray(rng.integers(0, n, m, dtype=np.int32))
    nbytes = m * d * 4

    take = jax.jit(lambda t, i: jnp.take(t, i, axis=0))
    bench("XLA row gather (full HBM)", take, table, idx,
          bytes_per_iter=nbytes)
    try:
        m_pad = m // 256 * 256
        bench("Pallas DMA row gather",
              lambda t, i: gather_rows(t, i[:m_pad]), table, idx,
              bytes_per_iter=m_pad * d * 4)
    except Exception as e:
        print(f"pallas gather failed: {e}")

    # Feature with 20% HBM cache, degree-ordered (reference's headline
    # config: 20% cache -> 14.82 GB/s on ogbn-products)
    deg_like = rng.lognormal(3, 1, n)
    order = np.argsort(-deg_like)
    indptr = np.zeros(n + 1, dtype=np.int64)
    indptr[1:] = np.cumsum(
        np.maximum(deg_like / deg_like.sum() * (n * 10), 1).astype(int)
    )
    topo = CSRTopo(indptr=indptr,
                   indices=np.zeros(int(indptr[-1]), dtype=np.int32))
    f20 = Feature(device_cache_size=int(n * 0.2) * d * 4,
                  csr_topo=topo).from_cpu_tensor(feat)
    host_idx = np.asarray(rng.integers(0, n, m))

    def feature_gather():
        return f20[host_idx]

    bench("quiver Feature (20% HBM cache + host tail)", feature_gather,
          bytes_per_iter=nbytes, iters=5)
    full = Feature(device_cache_size="100G").from_cpu_tensor(feat)
    bench("quiver Feature (100% HBM)", lambda: full[host_idx],
          bytes_per_iter=nbytes, iters=10)


if __name__ == "__main__":
    main()
