"""On-chip decomposition of the sampling hot path.

Round-2 diagnosis measured 775 ms/batch steady for the 3-hop xla pipeline
(B=1024, [15,10,5], 100K-node graph) — ~1.4M SEPS vs the 34.29M baseline.
This script times each ingredient separately so the slow op is identified
by measurement, not speculation:

  * dispatch overhead (steady trivial jit over the axon tunnel)
  * RNG steady throughput: threefry vs rbg vs counter-hash
  * element gather: serialized `take` vs lanes row-gather+select, at
    hop-1/2/3 index counts from small and products-sized tables
  * feature-style row gather GB/s
  * full 3-hop steady for each (gather_mode, rng) combo

Each stage is SIGALRM-bounded so one pathological compile cannot eat the
tunnel window.
"""

import os
import signal
import sys
import time

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/root/repo/.jax_cache")
sys.path.insert(0, "/root/repo")

import numpy as np

T0 = time.perf_counter()


def log(m):
    print(f"[{time.perf_counter() - T0:7.1f}s] {m}", flush=True)


class Timeout(Exception):
    pass


signal.signal(signal.SIGALRM, lambda s, f: (_ for _ in ()).throw(Timeout()))


def stage(name, seconds, fn):
    log(f"--- {name} (limit {seconds}s)")
    signal.alarm(seconds)
    t0 = time.perf_counter()
    try:
        out = fn()
        dt = time.perf_counter() - t0
        log(f"ok {name}: {dt:.2f}s" + (f" -> {out}" if out else ""))
        return out
    except Timeout:
        log(f"TIMEOUT {name}")
    except Exception as e:
        log(f"FAIL {name}: {type(e).__name__}: {str(e)[:300]}")
    finally:
        signal.alarm(0)


def main():
    import jax
    import jax.numpy as jnp

    stage("device init", 300, lambda: str(jax.devices()))

    def timeit(fn, *argsets, iters=10):
        """Compile, then steady-state ms/call (block only at the end).

        ``argsets`` is a LIST of per-call argument tuples, cycled — the
        remote-execution path replay-caches identical-args calls (see
        docs/TPU_MEASUREMENTS.md "Methodology trap"), so every iteration
        must present fresh input buffers.
        """
        if argsets and not isinstance(argsets[0], tuple):
            argsets = [tuple(argsets)]  # legacy single-argset call
        r = fn(*argsets[0])
        jax.block_until_ready(r)
        t0 = time.perf_counter()
        for i in range(iters):
            r = fn(*argsets[i % len(argsets)])
        jax.block_until_ready(r)
        return (time.perf_counter() - t0) / iters * 1e3

    # --- 1. dispatch overhead (varied scalar per call)
    f_triv = jax.jit(lambda x: x + 1)
    xs = [(jnp.full(8, float(i)),) for i in range(30)]
    stage("dispatch steady", 120,
          lambda: f"{timeit(f_triv, *xs, iters=30):.2f} ms/call")

    # --- 2. RNG steady (1M draws, the hop-3 shape); fresh key per call
    for impl in ("threefry2x32", "rbg"):
        keys = [(jax.random.key(i, impl=impl),) for i in range(10)]
        f = jax.jit(lambda k: jax.random.uniform(k, (1 << 20,)))
        stage(f"rng {impl} 1M uniform", 240,
              lambda f=f, keys=keys: f"{timeit(f, *keys):.2f} ms")
    from quiver_tpu.ops.sample import _uniform
    hkeys = [(jax.random.key(i, impl="rbg"),) for i in range(10)]
    f_hash = jax.jit(lambda k: _uniform(k, (1 << 20,), "hash"))
    stage("rng hash 1M uniform", 240,
          lambda: f"{timeit(f_hash, *hkeys):.2f} ms")

    # --- 3. element gather modes
    from quiver_tpu.ops.fastgather import element_gather, prepare_table

    rng = np.random.default_rng(0)
    for tab_n, tag in ((2_000_000, "2M"), (123_718_280, "124M")):
        tab = jnp.asarray(rng.integers(0, 1 << 30, tab_n, dtype=np.int32))
        tab2d = prepare_table(tab)
        jax.block_until_ready(tab2d)
        for m in (16_384, 163_840, 1_048_576):
            idxs = [jnp.asarray(rng.integers(0, tab_n, m, dtype=np.int32))
                    for _ in range(6)]
            f_take = jax.jit(lambda t, i: jnp.take(t, i, mode="clip"))
            f_lane = jax.jit(element_gather)
            stage(f"take {tag} m={m}", 240,
                  lambda: f"{timeit(*[f_take] + [(tab, i) for i in idxs], iters=6):.2f} ms")
            stage(f"lanes {tag} m={m}", 240,
                  lambda: f"{timeit(*[f_lane] + [(tab2d, i) for i in idxs], iters=6):.2f} ms")
        del tab, tab2d

    # --- 4. feature row gather GB/s (2.4M x 128 f32 ~ 1.25 GB)
    feat = jnp.asarray(rng.normal(size=(2_400_000, 128)).astype(np.float32))
    jax.block_until_ready(feat)
    idsets = [(feat, jnp.asarray(
        rng.integers(0, 2_400_000, 180_224, dtype=np.int32)))
        for _ in range(6)]
    f_row = jax.jit(lambda t, i: jnp.take(t, i, axis=0))

    def rowg():
        ms = timeit(f_row, *idsets, iters=6)
        gbs = 180_224 * 128 * 4 / (ms / 1e3) / 1e9
        return f"{ms:.2f} ms = {gbs:.1f} GB/s"

    stage("feature row gather 180K x 128", 240, rowg)
    del feat

    # --- 5. full 3-hop steady per config
    from quiver_tpu import CSRTopo, GraphSageSampler
    from quiver_tpu.utils.synthetic import synthetic_csr

    indptr, indices = synthetic_csr(100_000, 2_000_000, 0)
    topo = CSRTopo(indptr=indptr, indices=indices)
    seeds = np.arange(1024, dtype=np.int32)

    for gm in ("xla", "lanes"):
        for rng_name, impl in (("threefry", "threefry2x32"), ("rbg", "rbg"),
                               ("hash", "rbg")):
            key = jax.random.key(0, impl=impl)
            # explicit "key" (NOT "auto" — auto resolves to hash on
            # accelerators, which would make all three rows measure hash)
            srng = "hash" if rng_name == "hash" else "key"

            def run(gm=gm, key=key, srng=srng):
                s = GraphSageSampler(topo, [15, 10, 5], gather_mode=gm,
                                     sample_rng=srng)
                out = s.sample(seeds, key=key)
                jax.block_until_ready(out.n_id)
                t0 = time.perf_counter()
                for i in range(5):
                    out = s.sample(seeds, key=jax.random.fold_in(key, i))
                jax.block_until_ready(out.n_id)
                ms = (time.perf_counter() - t0) / 5 * 1e3
                seps = 1024 * (15 + 15 * 10 + 15 * 10 * 5) / (ms / 1e3)
                return f"{ms:.1f} ms/batch = {seps / 1e6:.2f}M SEPS"

            stage(f"3hop {gm}+{rng_name}", 300, run)

    log("PROFILE2 DONE")


if __name__ == "__main__":
    main()
