"""End-to-end GraphSAGE epoch-time benchmark.

Mirrors the reference's e2e table (docs/Introduction_en.md:142-158:
ogbn-products 3-layer GraphSAGE, quiver 11.1s -> 3.25s on 1 -> 4 GPUs vs
PyG 36.5s).  Synthetic products-scale graph; single chip here, the DP
variant scales with the mesh (see examples/papers100M_dist.py).
"""

import argparse
import sys
import time

sys.path.insert(0, ".")

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=2_449_029)
    ap.add_argument("--edges", type=int, default=123_718_280)
    ap.add_argument("--dim", type=int, default=100)
    ap.add_argument("--classes", type=int, default=47)
    ap.add_argument("--train-frac", type=float, default=0.08)
    ap.add_argument("--batch-size", type=int, default=1024)
    ap.add_argument("--cache", default="800M")
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--fused", action="store_true",
                    help="force the fused one-jit pipeline (requires the "
                         "cache budget to cover all features)")
    ap.add_argument("--profile", default=None,
                    help="dump a jax.profiler trace to this dir")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import optax

    from bench import build_graph
    from quiver_tpu import CSRTopo, Feature, GraphSageSampler
    from quiver_tpu.models import GraphSAGE
    from quiver_tpu.parallel import TrainState, make_train_step, Prefetcher

    rng = np.random.default_rng(0)
    indptr, indices = build_graph(args.nodes, args.edges)
    topo = CSRTopo(indptr=indptr, indices=indices)
    feat = rng.normal(size=(args.nodes, args.dim)).astype(np.float32)
    labels = rng.integers(0, args.classes, args.nodes)
    train_idx = rng.choice(args.nodes,
                           int(args.nodes * args.train_frac), replace=False)

    sampler = GraphSageSampler(topo, [15, 10, 5])
    feature = Feature(device_cache_size=args.cache,
                      csr_topo=topo).from_cpu_tensor(feat)
    model = GraphSAGE(hidden=256, out_dim=args.classes, num_layers=3)
    tx = optax.adam(3e-3)
    B = args.batch_size

    b0 = sampler.sample(train_idx[:B])
    x0 = feature[np.asarray(b0.n_id)]
    params = model.init(jax.random.PRNGKey(0), x0, b0.layers)
    state = TrainState.create(params, tx)
    step = make_train_step(
        lambda p, x, blocks, train=False, rngs=None: model.apply(
            p, x, blocks, train=train, rngs=rngs
        ), tx,
    )

    n_batches = len(train_idx) // B
    ones = jnp.ones((B,), bool)

    fused = None
    if args.fused or feature.cache_count >= feature.node_count:
        from quiver_tpu.pipeline import make_fused_train_step

        fused = make_fused_train_step(
            sampler, feature,
            lambda p, x, blocks, train=False, rngs=None: model.apply(
                p, x, blocks, train=train, rngs=rngs
            ), tx,
        )
        print("pipeline: fused (sample+gather+step in one jit)")
    else:
        print("pipeline: two-stage (prefetch + step)")

    def make_batch(i):
        seeds = train_idx[i * B: (i + 1) * B]
        batch = sampler.sample(seeds, key=jax.random.PRNGKey(i))
        x = feature[np.asarray(batch.n_id)]
        return batch, x, jnp.asarray(labels[seeds])

    import contextlib

    prof = (
        jax.profiler.trace(args.profile) if args.profile
        else contextlib.nullcontext()
    )
    with prof:
        for epoch in range(args.epochs):
            rng.shuffle(train_idx)
            t0 = time.perf_counter()
            loss = None
            if fused is not None:
                for i in range(n_batches):
                    host_seeds = train_idx[i * B: (i + 1) * B]
                    state, loss = fused(
                        state, jnp.asarray(host_seeds, jnp.int32),
                        jnp.asarray(labels[host_seeds]), ones,
                        jax.random.PRNGKey(i),
                    )
            else:
                for batch, x, lab in Prefetcher(range(n_batches),
                                                make_batch, depth=2):
                    state, loss = step(state, x, batch.layers, lab, ones,
                                       jax.random.PRNGKey(1))
            jax.block_until_ready(loss)
            dt = time.perf_counter() - t0
            print(f"epoch {epoch}: {dt:.2f}s "
                  f"({n_batches} batches, "
                  f"{dt / n_batches * 1e3:.1f} ms/batch) "
                  f"loss={float(loss):.3f}")
    print("reference bar: quiver 1-GPU 11.1s/epoch, 4-GPU 3.25s "
          "(products, real data)")


if __name__ == "__main__":
    main()
