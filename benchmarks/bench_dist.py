"""Distributed data-layer benchmark: sharded sampling + feature exchange.

Counterpart of the reference's multi-node benchmarks
(``benchmarks/ogbn-papers100M/``) reduced to the data-layer ops: steps/sec
of (row-sharded sample -> all-to-all feature lookup -> DP step) over
whatever mesh exists (virtual CPU mesh in dev, a real slice in prod).
Also races DistFeature (all-to-all) vs RingFeature (rotation) lookups.
"""

import argparse
import sys
import time

sys.path.insert(0, ".")

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=500_000)
    ap.add_argument("--edges", type=int, default=10_000_000)
    ap.add_argument("--dim", type=int, default=128)
    ap.add_argument("--batch-size", type=int, default=512)
    ap.add_argument("--iters", type=int, default=10)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from bench import build_graph
    from quiver_tpu import (
        CSRTopo, DistFeature, DistGraphSampler, PartitionInfo, RingFeature,
    )
    from quiver_tpu.utils.mesh import make_mesh

    mesh = make_mesh(("data",))
    nd = int(mesh.shape["data"])
    print(f"mesh: {nd} devices")
    rng = np.random.default_rng(0)
    indptr, indices = build_graph(args.nodes, args.edges)
    topo = CSRTopo(indptr=indptr, indices=indices)
    feat = rng.normal(size=(args.nodes, args.dim)).astype(np.float32)

    sampler = DistGraphSampler(topo, mesh, sizes=[10, 5])
    g2h = rng.integers(0, nd, topo.node_count).astype(np.int32)
    info = PartitionInfo(host=0, hosts=nd, global2host=g2h)
    df = DistFeature.from_global_feature(feat, mesh, info)
    rf = RingFeature(feat, mesh)

    B = args.batch_size
    seed_rounds = [rng.integers(0, topo.node_count, (nd, B))
                   for _ in range(args.iters + 1)]

    # warm
    n_id, *_ = sampler.sample(seed_rounds[0], key=0)
    df.lookup(np.asarray(n_id)).block_until_ready()
    t0 = time.perf_counter()
    for i in range(args.iters):
        n_id, n_mask, num, blocks = sampler.sample(seed_rounds[i + 1],
                                                   key=i)
        x = df.lookup(np.asarray(n_id))
    jax.block_until_ready(x)
    dt = time.perf_counter() - t0
    edges = sum(int(np.asarray(b.mask).sum()) for b in blocks) * args.iters
    print(f"sharded sample+exchange: {dt / args.iters * 1e3:.1f} ms/round "
          f"({edges / dt / 1e6:.2f}M SEPS incl. exchange, {nd} replicas)")

    # DistFeature vs RingFeature on identical demand
    P = n_id.shape[1]
    ids = np.asarray(n_id)
    for name, f in (("all-to-all DistFeature", df.lookup),
                    ("ring RingFeature", rf.lookup)):
        f(ids).block_until_ready()
        t0 = time.perf_counter()
        for _ in range(args.iters):
            out = f(ids)
        jax.block_until_ready(out)
        dt = time.perf_counter() - t0
        gbs = args.iters * nd * P * args.dim * 4 / dt / 1e9
        print(f"{name:<24} {dt / args.iters * 1e3:7.1f} ms  {gbs:6.2f} GB/s")


if __name__ == "__main__":
    main()
