"""Sampling throughput benchmark (SEPS) across configurations.

Mirrors the reference's sampling benchmarks
(``/root/reference/benchmarks/ogbn_products/bench_quiver_sampler.py``-style
scripts behind docs/Introduction_en.md:38-45).  Run on the real TPU chip:

    python benchmarks/bench_sampling.py [--nodes N --edges E]

Prints a table over {batch size} x {dedup mode} x {gather mode}.
"""

import argparse
import sys
import time

sys.path.insert(0, ".")

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=2_449_029)
    ap.add_argument("--edges", type=int, default=123_718_280)
    ap.add_argument("--fanout", type=int, nargs="+", default=[15, 10, 5])
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--batches", type=int, nargs="+",
                    default=[512, 1024, 2048])
    args = ap.parse_args()

    import jax

    from bench import build_graph  # repo-root bench utilities
    from quiver_tpu import CSRTopo, GraphSageSampler

    indptr, indices = build_graph(args.nodes, args.edges)
    topo = CSRTopo(indptr=indptr, indices=indices)
    topo.to_device()
    print(f"graph: N={topo.node_count:,} E={topo.edge_count:,} "
          f"fanout={args.fanout}")

    rows = []
    for dedup in ("none", "hop"):
        for gm in ("xla", "lanes"):
            for B in args.batches:
                s = GraphSageSampler(topo, args.fanout, dedup=dedup,
                                     gather_mode=gm)
                rng = np.random.default_rng(0)
                batches = [rng.integers(0, topo.node_count, B,
                                        dtype=np.int32)
                           for _ in range(args.iters + 2)]
                out = s.sample(batches[0], key=jax.random.PRNGKey(0))
                out.n_id.block_until_ready()
                s.sample(batches[1]).n_id.block_until_ready()
                t0 = time.perf_counter()
                outs = [s.sample(batches[2 + i],
                                 key=jax.random.PRNGKey(i))
                        for i in range(args.iters)]
                outs[-1].n_id.block_until_ready()
                dt = time.perf_counter() - t0
                edges = sum(
                    int(np.asarray(b.mask).sum())
                    for o in outs for b in o.layers
                )
                seps = edges / dt
                rows.append((dedup, gm, B, seps))
                print(f"dedup={dedup:<5} gather={gm:<6} B={B:<5} "
                      f"{seps / 1e6:8.2f}M SEPS "
                      f"({dt / args.iters * 1e3:.1f} ms/batch)")
    best = max(rows, key=lambda r: r[3])
    print(f"\nbest: dedup={best[0]} gather={best[1]} B={best[2]} "
          f"-> {best[3] / 1e6:.2f}M SEPS "
          f"(reference UVA baseline: 34.29M)")


if __name__ == "__main__":
    main()
