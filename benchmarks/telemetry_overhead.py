"""Telemetry overhead microbenchmark.

Backs the acceptance bound: the sampling hot loop with telemetry
*disabled* (noop singletons) must run within 5% of a build with the
instrumentation deleted — measured here as enabled-vs-disabled A/B on
the same loop, plus raw per-op costs of the primitives themselves.

    python benchmarks/telemetry_overhead.py [--nodes N --iters K]

CPU-safe (JAX_PLATFORMS=cpu works); no device required.
"""

import argparse
import sys
import time

sys.path.insert(0, ".")

import numpy as np


def _per_op_costs(reps=200_000):
    """Raw cost of one counter inc / histogram observe, on vs off."""
    from quiver_tpu import telemetry

    rows = []
    for enabled in (True, False):
        telemetry.set_enabled(enabled)
        telemetry.reset()
        c = telemetry.counter("ovh_counter", mode="bench")
        h = telemetry.histogram("ovh_hist", mode="bench")
        t0 = time.perf_counter()
        for _ in range(reps):
            c.inc()
        t_inc = (time.perf_counter() - t0) / reps
        t0 = time.perf_counter()
        for _ in range(reps):
            h.observe(1e-3)
        t_obs = (time.perf_counter() - t0) / reps
        t0 = time.perf_counter()
        for _ in range(reps):
            telemetry.counter("ovh_counter", mode="bench")
        t_lookup = (time.perf_counter() - t0) / reps
        rows.append((enabled, t_inc, t_obs, t_lookup))
        print(f"  enabled={enabled!s:<5} counter.inc {t_inc * 1e9:7.1f} ns"
              f"   hist.observe {t_obs * 1e9:7.1f} ns"
              f"   registry lookup {t_lookup * 1e9:7.1f} ns")
    telemetry.set_enabled(True)
    telemetry.reset()
    return rows


def _sampling_loop_seconds(sampler, batches, iters, key_fn):
    t0 = time.perf_counter()
    outs = [sampler.sample(batches[i], key=key_fn(i)) for i in range(iters)]
    outs[-1].n_id.block_until_ready()
    return time.perf_counter() - t0


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=100_000)
    ap.add_argument("--edges", type=int, default=1_000_000)
    ap.add_argument("--batch", type=int, default=1024)
    ap.add_argument("--fanout", type=int, nargs="+", default=[15, 10])
    ap.add_argument("--iters", type=int, default=30)
    ap.add_argument("--repeats", type=int, default=5)
    args = ap.parse_args()

    print("per-op primitive costs:")
    _per_op_costs()

    import jax

    from bench import build_graph
    from quiver_tpu import CSRTopo, GraphSageSampler, telemetry

    indptr, indices = build_graph(args.nodes, args.edges)
    topo = CSRTopo(indptr=indptr, indices=indices)
    topo.to_device()
    s = GraphSageSampler(topo, args.fanout, dedup="hop")
    rng = np.random.default_rng(0)
    batches = [rng.integers(0, topo.node_count, args.batch, dtype=np.int32)
               for _ in range(args.iters)]
    key_fn = jax.random.PRNGKey

    # warm the jit caches before any timed pass
    s.sample(batches[0], key=key_fn(0)).n_id.block_until_ready()

    print(f"\nsampling loop: N={topo.node_count:,} B={args.batch} "
          f"fanout={args.fanout} iters={args.iters} "
          f"(best of {args.repeats})")
    best = {}
    for enabled in (True, False):
        telemetry.set_enabled(enabled)
        telemetry.reset()
        best[enabled] = min(
            _sampling_loop_seconds(s, batches, args.iters, key_fn)
            for _ in range(args.repeats))
        print(f"  telemetry={'on ' if enabled else 'off'} "
              f"{best[enabled] / args.iters * 1e3:7.2f} ms/batch")
    telemetry.set_enabled(True)

    overhead = best[True] / best[False] - 1.0
    print(f"\nenabled-vs-disabled overhead: {overhead * 100:+.2f}% "
          f"(acceptance bound for the disabled path: <= 5% vs "
          f"uninstrumented; the disabled path is the noop singleton, "
          f"so its cost IS the residual instrumentation cost)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
