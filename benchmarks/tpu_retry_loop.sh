#!/bin/bash
# Persistent TPU harvester: whenever the axon tunnel is up, run the
# bounded diagnosis, then the full bench (results timestamped under
# /tmp/tpu_runs).  Safe to leave running all session.
mkdir -p /tmp/tpu_runs
n=0
while true; do
  n=$((n+1))
  ts=$(date +%H%M%S)
  # quick init probe with hard timeout: is the tunnel up at all?
  if timeout 240 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
    echo "[$ts] tunnel UP - diagnose" >> /tmp/tpu_runs/loop.log
    timeout 2400 python /root/repo/benchmarks/tpu_diagnose.py \
      > /tmp/tpu_runs/diag_$ts.log 2>&1
    echo "[$(date +%H%M%S)] diagnose rc=$? - bench" >> /tmp/tpu_runs/loop.log
    timeout 3600 python /root/repo/bench.py --iters 20 --ab-dedup \
      > /tmp/tpu_runs/bench_$ts.json 2> /tmp/tpu_runs/bench_$ts.log
    echo "[$(date +%H%M%S)] bench rc=$?" >> /tmp/tpu_runs/loop.log
    # one full harvest is enough; park and let the operator decide more
    echo "[$(date +%H%M%S)] harvest complete - sleeping 600" >> /tmp/tpu_runs/loop.log
    sleep 600
  else
    echo "[$ts] tunnel down (attempt $n)" >> /tmp/tpu_runs/loop.log
    sleep 120
  fi
done
