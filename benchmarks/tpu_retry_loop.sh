#!/bin/bash
# Persistent TPU harvester: whenever the axon tunnel is up, run the full
# bench (results timestamped under /tmp/tpu_runs).  Retries every 2 min
# while the tunnel is down; stops only after a bench run emits a valid
# final JSON line (checked via json.loads on the last stdout line).
# Safe to leave running all session.
mkdir -p /tmp/tpu_runs
n=0
bench_tries=0
while true; do
  n=$((n+1))
  ts=$(date +%H%M%S)
  # quick init probe with hard timeout: is the tunnel up at all?
  if timeout 240 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
    echo "[$ts] tunnel UP - bench" >> /tmp/tpu_runs/loop.log
    # 19800s > the ~17,300s worst-case sum of per-section bounds (banked
    # sampling 900 + probe 10x420 + sampling 2x960 + dedup/uva 2x960 +
    # feature 660 + e2e 3x1260 + serving 3x900 + quality 1200 +
    # init/graph): the outer timeout is a last resort, not the per-run
    # pacing (bench.py converts its SIGTERM to a clean SystemExit so
    # section attempt budgets survive; resume makes later attempts cheap)
    timeout 19800 python /root/repo/bench.py --iters 20 --ab-dedup \
      > /tmp/tpu_runs/bench_$ts.json 2> /tmp/tpu_runs/bench_$ts.log
    rc=$?
    echo "[$(date +%H%M%S)] bench rc=$rc" >> /tmp/tpu_runs/loop.log
    if python - "$ts" << 'EOF'
import json, sys
ts = sys.argv[1]
sys.path.insert(0, "/root/repo")
try:
    from bench import is_live_harvest  # ONE gate, shared with
    # harvest_commit.py: watchdog fallbacks (device:false), backfilled
    # headlines (headline_source:"prior"), and silent CPU-backend runs
    # all parse but must NOT stop the retry loop
    lines = [l for l in open(f"/tmp/tpu_runs/bench_{ts}.json") if l.strip()]
    ok = is_live_harvest(json.loads(lines[-1]))
except Exception:
    ok = False
sys.exit(0 if ok else 1)
EOF
    then
      cp /tmp/tpu_runs/bench_$ts.json /tmp/tpu_runs/bench_FINAL.json
      # land the evidence IN THE REPO: the driver's end-of-round commit
      # picks these up even if no interactive session is alive.  The
      # section results themselves are already in /root/repo/.bench_state.json
      # (bench.py writes it under the TPU fingerprint as it goes), so a
      # later driver bench run inherits every finished section either way.
      cp /tmp/tpu_runs/bench_$ts.json /root/repo/docs/tpu_bench_harvest.json
      echo "[$(date +%H%M%S)] HARVEST COMPLETE -> bench_FINAL.json + repo docs/tpu_bench_harvest.json" >> /tmp/tpu_runs/loop.log
      exit 0
    fi
    # invalid/partial result: back off before retrying (bench.py resumes
    # finished sections from .bench_state.json, so retries are cheap),
    # and give up after 8 bench attempts rather than spin all session
    bench_tries=$((bench_tries+1))
    if [ "$bench_tries" -ge 8 ]; then
      echo "[$(date +%H%M%S)] giving up after $bench_tries bench attempts" >> /tmp/tpu_runs/loop.log
      exit 1
    fi
    sleep 300
  else
    echo "[$ts] tunnel down (attempt $n)" >> /tmp/tpu_runs/loop.log
    sleep 120
  fi
done
