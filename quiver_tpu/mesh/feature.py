"""Row-range-sharded feature store: one logical table over a mesh.

The fleet's replicas each hold the WHOLE feature table; this store
holds ``1/n_shards`` of it per device and serves a batch gather as a
**sharded gather with a halo exchange expressed as a collective** —
the ``shard_map`` formulation of what the dist tier hand-rolls as a
host-planned all-to-all (``dist/feature.py``), and the TPU shape of
torch-quiver's ``quiver_partition_feature`` clique sharding.

Layout (docs/SHARDING.md):

  * Rows are split into contiguous ranges of ``rows_per_shard``
    (ownership is ``id // rows_per_shard`` — a shift, not a lookup).
  * Each shard owns a :class:`~quiver_tpu.ops.paged.PagedStore` over
    ITS range only: the frame pool and page table are sharded by row
    range, and a page fault touches exactly one shard's pool — faults
    stay shard-local, the single-device fault path
    (``PagedStore._fault_pages``: one whole-page H2D, CLOCK eviction,
    the ``feature_page_*`` metrics) is reused verbatim.
  * The mesh-wide views the collective reads — frames
    ``[S, F, R, D]`` and the page->frame table ``[S, P]`` — carry
    ``NamedSharding(P("shard"))``; they are restacked only after a
    fault dirtied a shard, so the steady state moves zero bytes.

The gather itself runs ONE executable per pow2-padded batch size
(key ``("gather", B_pad, n_shards)`` in the ``mesh_feature`` program
cache): each shard gathers the rows it owns from its local frames and
contributes a dtype-minimum sentinel elsewhere; an all-reduce ``pmax``
over the ``shard`` axis is the halo exchange that assembles the full
``[B, D]`` batch on every shard.  ``pmax`` (not ``psum``) keeps the
combine bit-exact: the owner's row wins unchanged — no ``-0.0 + 0.0``
renormalization — so the result is bit-identical to the single-device
staged path (``tests/test_mesh.py`` pins it; the one documented hole
is a feature value equal to the sentinel itself, i.e. ``-inf``).

Overflow honesty: a batch whose page working set exceeds a shard's
overlay pool falls back to an exact host-table gather for the WHOLE
batch (``feature_page_fallback_total`` ticks) — correctness first,
the counter makes the mis-sizing visible, same contract as the
single-device paged store.
"""

from __future__ import annotations

import threading
from typing import Optional, Sequence

import numpy as np

from .. import telemetry
from ..ops.paged import PageTable, PagedStore, default_page_rows
from ..recovery.registry import program_cache
from .topology import SHARD_AXIS, build_mesh, row_shard, shard_ranges

__all__ = ["MeshFeature"]


class _ShardFaultFns:
    """The ``_feature`` surface each shard's ``PagedStore`` expects from
    its owner (``ops/paged.py`` fault contract): a per-``k_pad`` cached
    scatter.  All shards share one pool geometry, so every shard
    resolves to the SAME executables in the owner's program cache."""

    def __init__(self, owner: "MeshFeature"):
        self._owner = owner

    def _paged_fault_fn(self, k_pad: int):
        return self._owner._fault_fn(k_pad)


class MeshFeature:
    """One logical feature table served by ``n_shards`` devices."""

    _guarded_by = {"_dirty": "_lock", "_frames_g": "_lock",
                   "_lookup_g": "_lock", "restacks": "_lock",
                   "fallbacks": "_lock"}

    def __init__(self, table: np.ndarray, n_shards: Optional[int] = None,
                 mesh=None, page_rows: int = 0,
                 pool_pages: Optional[int] = None):
        import jax.numpy as jnp

        from ..config import get_config

        cfg = get_config()
        if n_shards is None:
            n_shards = cfg.mesh_shards
        self.n_shards = int(n_shards)
        if self.n_shards < 1:
            raise ValueError(
                f"MeshFeature needs n_shards >= 1 (config.mesh_shards "
                f"is off); got {self.n_shards}")
        table = np.ascontiguousarray(table)
        self.node_count, self.dim = table.shape
        self.dtype = table.dtype
        self.cache_count = 0      # no replicated hot prefix: rows shard
        self.mesh = mesh if mesh is not None else build_mesh(self.n_shards)
        self.axis = SHARD_AXIS
        self.rows_per_shard, self.ranges = shard_ranges(
            self.node_count, self.n_shards)
        row_bytes = self.dim * self.dtype.itemsize
        self.page_rows = int(page_rows) or default_page_rows(row_bytes)
        self._pages_per_shard = -(-self.rows_per_shard // self.page_rows)
        if pool_pages is None:
            pool_pages = int(cfg.mesh_pool_pages)
        # pool=0 sizes each shard's pool to hold its whole range — the
        # memory win over replication is the 1/n_shards split itself;
        # smaller pools trade faults for HBM and are an explicit choice
        self.pool_pages = int(pool_pages) or self._pages_per_shard
        self._table_np = table
        self._fns = _ShardFaultFns(self)
        self._stores = []
        for lo, hi in self.ranges:
            rows = np.zeros((self.rows_per_shard, self.dim),
                            dtype=self.dtype)
            rows[: hi - lo] = table[lo:hi]
            pt = PageTable(n_rows=self.rows_per_shard, cache_count=0,
                           page_rows=self.page_rows,
                           pool_pages=self.pool_pages)
            store = PagedStore(pt, rows, cache_count=0, dim=self.dim,
                               dtype=self.dtype)
            store._feature = self._fns
            self._stores.append(store)
        self.pool_pages = self._stores[0].table.pool_pages  # post-clamp
        if np.issubdtype(self.dtype, np.floating):
            self._sentinel = np.array(-np.inf, dtype=self.dtype)
        else:
            self._sentinel = np.array(np.iinfo(self.dtype).min,
                                      dtype=self.dtype)
        self._frames_sharding = row_shard(self.mesh)
        self._cache = program_cache("mesh_feature", owner=self)
        self._lock = threading.Lock()
        self._frames_g = None
        self._lookup_g = None
        self._dirty = True
        self.restacks = 0
        self.fallbacks = 0
        from . import _set_active_feature

        _set_active_feature(self)

    # -- executables ---------------------------------------------------
    def _fault_fn(self, k_pad: int):
        """Shared-across-shards scatter of a pow2-padded fault batch
        into a shard's frame pool (pad slot = ``n_frames``, dropped) —
        the mesh twin of ``Feature._paged_fault_fn``."""
        import jax

        fn = self._cache.get(("pgfault", k_pad))
        if fn is None:

            @jax.jit
            def fn(frames, slots, pages):
                return frames.at[slots].set(pages, mode="drop")

            # quiverlint: ignore[QT014] -- k_pad is pow2-padded at the
            # fault site (ops/paged._fault); the edge runs through the
            # duck-typed PagedStore._feature -> _ShardFaultFns shim,
            # which the resolver cannot follow.
            self._cache[("pgfault", k_pad)] = fn
        return fn

    def _gather_fn(self, b_pad: int):
        """The sharded gather + halo-exchange collective for one padded
        batch size: ONE executable per ``(B_pad, n_shards)``."""
        import jax
        import jax.numpy as jnp
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        key = ("gather", b_pad, self.n_shards)
        fn = self._cache.get(key)
        if fn is None:
            axis = self.axis
            rps = self.rows_per_shard
            page_rows = self.page_rows
            n_frames = self._stores[0].table.n_frames
            sentinel = jnp.asarray(self._sentinel)

            def _local(frames, lookup, ids):
                # blocks: frames [1, F, R, D], lookup [1, P]; ids [Bp]
                s = jax.lax.axis_index(axis)
                local = ids - s * rps
                own = (local >= 0) & (local < rps)
                lid = jnp.clip(local, 0, rps - 1)
                frame = lookup[0, lid // page_rows]
                ok = own & (frame >= 0)
                rows = frames[0][jnp.clip(frame, 0, n_frames - 1),
                                 lid % page_rows]
                part = jnp.where(ok[:, None], rows, sentinel)
                # the halo exchange: owners broadcast their rows, the
                # sentinel loses everywhere — bit-exact all-reduce
                return jax.lax.pmax(part, axis)

            fn = jax.jit(shard_map(
                _local, mesh=self.mesh,
                in_specs=(P(axis), P(axis), P()), out_specs=P()))
            self._cache[key] = fn
        return fn

    # -- faulting / restack (host-side planning) -----------------------
    def _fault_shards(self, ids: np.ndarray,
                      owner: np.ndarray) -> Optional[bool]:
        """Fault every shard's touched pages (shard-local, one H2D per
        shard).  Returns None when some shard's pool cannot hold this
        batch's working set (caller falls back), else whether any page
        actually faulted (caller marks the views dirty).  Call with
        ``_lock`` held."""
        import jax.numpy as jnp

        dirtied = False
        for s, store in enumerate(self._stores):
            local = ids[owner == s] - s * self.rows_per_shard
            if local.size == 0:
                continue
            pages = np.unique(local // self.page_rows)
            resident = store.frame_of_pages()[pages] >= 0
            if resident.all():
                continue
            if store._fault_pages(pages, jnp, telemetry) is None:
                store.fallbacks += 1
                return None
            dirtied = True
        return dirtied

    def _stacked_views(self):
        """Fresh mesh-wide sharded views (frames ``[S,F,R,D]``, lookup
        ``[S,P]``) from the shards' current pools; only reached after a
        fault dirtied a shard — the steady state moves zero bytes.
        Call with ``_lock`` held."""
        import jax
        import jax.numpy as jnp

        frames = jnp.stack([s.frames for s in self._stores])
        lookup = np.stack([s.frame_of_pages() for s in self._stores])
        return (jax.device_put(frames, self._frames_sharding),
                jax.device_put(jnp.asarray(lookup),
                               self._frames_sharding))

    # -- the batch gather ----------------------------------------------
    def __getitem__(self, node_idx):
        import jax.numpy as jnp

        from ..feature import _pow2_bucket

        ids = np.asarray(node_idx, dtype=np.int64).reshape(-1)
        B = len(ids)
        if B == 0:
            return jnp.zeros((0, self.dim), dtype=self.dtype)
        with telemetry.histogram("mesh_shard_gather_seconds").time():
            owner = ids // self.rows_per_shard
            with self._lock:
                faulted = self._fault_shards(ids, owner)
                if faulted is None:
                    # pool overflow on some shard: exact host gather —
                    # answered, never dropped (single-device contract)
                    self.fallbacks += 1
                    telemetry.counter("feature_page_fallback_total").inc()
                    return jnp.asarray(self._table_np[ids])
                if faulted:
                    self._dirty = True
                if self._dirty:
                    self._frames_g, self._lookup_g = self._stacked_views()
                    self._dirty = False
                    self.restacks += 1
                frames_g, lookup_g = self._frames_g, self._lookup_g
            b_pad = _pow2_bucket(B)
            ids_pad = np.full(b_pad, -1, dtype=np.int32)
            ids_pad[:B] = ids
            out = self._gather_fn(b_pad)(frames_g, lookup_g,
                                         jnp.asarray(ids_pad))[:B]
        # logical halo volume of the replicated combine: every owned row
        # crosses to the other (n-1) shards.  Analytic on rehearsal —
        # transport counters need real interconnect telemetry.
        halo = float(B * self.dim * self.dtype.itemsize
                     * (self.n_shards - 1))
        telemetry.counter("mesh_halo_bytes_total", direction="send").inc(
            halo)
        telemetry.counter("mesh_halo_bytes_total", direction="recv").inc(
            halo)
        return out

    # -- warmup / introspection ----------------------------------------
    def warm_executables(self, buckets: Optional[Sequence[int]] = None
                         ) -> int:
        """Pre-build the gather collective for a pow2 ladder of batch
        sizes (serving calls this from ``warmup()`` so a fresh frontier
        size never stalls a request on a compile).  Returns the number
        of executables built."""
        if buckets is None:
            from ..feature import _pow2_bucket

            top = _pow2_bucket(min(self.node_count, 1 << 13))
            buckets, b = [], 1
            while b <= top:
                buckets.append(b)
                b <<= 1
        before = len(self._cache)
        for b in buckets:
            self._gather_fn(int(b))
        return len(self._cache) - before

    def stats(self) -> dict:
        with self._lock:
            per_shard = [dict(range=list(r),
                              resident_pages=s.table.resident_pages(),
                              fallbacks=s.fallbacks)
                         for r, s in zip(self.ranges, self._stores)]
            return dict(
                n_shards=self.n_shards, rows_per_shard=self.rows_per_shard,
                page_rows=self.page_rows, pool_pages=self.pool_pages,
                pages_per_shard=self._pages_per_shard,
                executables=len(self._cache),
                restacks=self.restacks, fallbacks=self.fallbacks,
                shards=per_shard)

    def size(self, dim: int) -> int:
        return (self.node_count, self.dim)[dim]

    def __repr__(self):
        return (f"MeshFeature(nodes={self.node_count}, dim={self.dim}, "
                f"shards={self.n_shards}, page_rows={self.page_rows}, "
                f"pool_pages={self.pool_pages})")
