"""Explicit device-mesh construction for the sharded serving tier.

ROADMAP item 1 (docs/SHARDING.md): the fleet scales by *replication*
— every process holds the whole graph — so a graph that cannot fit one
host has no serving story.  This module is the topology layer under
``quiver_tpu.mesh``: it builds the explicit ``jax.sharding.Mesh`` a
shard group serves over, names the two axes the tier partitions along
(``data`` for batch parallelism, ``shard`` for row-range sharding —
the TPU shape of torch-quiver's ``p2pCliqueTopo`` GPU cliques), and
exposes the ``NamedSharding`` helpers + regex partition rules every
sharded structure in ``mesh/feature.py`` / ``mesh/sampler.py`` places
arrays with.

CPU rehearsal: the whole tier runs anywhere via
``XLA_FLAGS=--xla_force_host_platform_device_count=8`` (the suite-wide
virtual mesh ``tests/conftest.py`` already forces).  Device count is a
process-boot decision in XLA — it cannot be raised after ``jax``
initializes — so :func:`require_devices` fails with the exact flag to
set instead of letting ``Mesh`` construction die on an opaque reshape
error.
"""

from __future__ import annotations

import re
from typing import List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["DATA_AXIS", "SHARD_AXIS", "require_devices", "build_mesh",
           "row_shard", "replicated", "shard_ranges",
           "match_partition_rules"]

DATA_AXIS = "data"
SHARD_AXIS = "shard"

_FLAG_HINT = ("--xla_force_host_platform_device_count=<n> (in XLA_FLAGS, "
              "before jax initializes)")


def require_devices(n: int) -> None:
    """Fail fast — with the rehearsal flag spelled out — when the
    process has fewer devices than the mesh needs.  XLA fixes the
    device count at backend init, so this is not recoverable in
    process; the error must say how to boot correctly."""
    import jax

    have = jax.device_count()
    if have < n:
        raise RuntimeError(
            f"mesh needs {n} devices but this process has {have}; on "
            f"CPU, rehearse a virtual slice with {_FLAG_HINT}")


def build_mesh(n_shards: int, data: int = 1,
               devices: Optional[Sequence] = None):
    """An explicit ``(data, shard)`` mesh over ``data * n_shards``
    devices (first devices win when more are available).  ``data=1``
    (the serving default) still carries the axis, so partition specs
    written against the two-axis shape need no rewrite when batch
    parallelism turns on."""
    from ..utils.mesh import make_mesh

    n_shards = int(n_shards)
    data = int(data)
    if n_shards < 1 or data < 1:
        raise ValueError(
            f"mesh axes must be >= 1, got data={data} shard={n_shards}")
    need = data * n_shards
    require_devices(need)
    if devices is None:
        import jax

        devices = jax.devices()[:need]
    return make_mesh((DATA_AXIS, SHARD_AXIS), shape=(data, n_shards),
                     devices=devices)


def row_shard(mesh, axis: str = SHARD_AXIS):
    """Rows partitioned along ``axis``, every other dim replicated —
    the placement of each sharded structure's leading shard dim."""
    from jax.sharding import NamedSharding, PartitionSpec

    return NamedSharding(mesh, PartitionSpec(axis))


def replicated(mesh):
    """Fully replicated placement (frontier ids, combine outputs)."""
    from jax.sharding import NamedSharding, PartitionSpec

    return NamedSharding(mesh, PartitionSpec())


def shard_ranges(n_rows: int, n_shards: int
                 ) -> Tuple[int, List[Tuple[int, int]]]:
    """Balanced contiguous row ranges: ``rows_per_shard`` (the padded
    per-shard extent — ownership is ``id // rows_per_shard``, a shift
    not a table lookup) and the half-open ``[lo, hi)`` range each shard
    actually owns (the last may be short; its pad rows are zeros and
    unreachable, since every real id maps below ``hi``)."""
    n_rows, n_shards = int(n_rows), int(n_shards)
    if n_rows < 1 or n_shards < 1:
        raise ValueError(f"need n_rows>=1, n_shards>=1; got "
                         f"{n_rows}, {n_shards}")
    rows_per_shard = -(-n_rows // n_shards)
    ranges = [(s * rows_per_shard, min((s + 1) * rows_per_shard, n_rows))
              for s in range(n_shards)]
    return rows_per_shard, ranges


def match_partition_rules(rules: Sequence[Tuple[str, object]], tree):
    """Regex -> ``PartitionSpec`` mapping over a param pytree (the
    SNIPPETS.md exemplar shape): the first rule whose pattern searches
    the ``/``-joined path of a leaf supplies its spec.  An unmatched
    leaf raises — silent replication of a tensor someone meant to
    shard is how HBM budgets get blown."""
    import jax

    def _assign(path, _leaf):
        name = "/".join(str(getattr(p, "key", getattr(p, "idx", p)))
                        for p in path)
        for pattern, spec in rules:
            if re.search(pattern, name):
                return spec
        raise ValueError(f"no partition rule matches param {name!r}")

    return jax.tree_util.tree_map_with_path(_assign, tree)
