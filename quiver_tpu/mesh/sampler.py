"""Frontier exchange over the mesh: row-range-sharded neighbor sampling.

Each shard holds the CSR rows of ITS row range only (local ``indptr``
over ``rows_per_shard`` rows, global ids in ``indices``) and samples
the full frontier with :func:`~quiver_tpu.ops.sample.
sample_neighbors_overlay` — the SAME op the stream tier serves — under
a ``seed_mask`` marking the rows it owns.  The op's uniforms are keyed
by ``(key, B, k)`` alone, never by seed ids, so every shard reproduces
the exact draw stream of the single-device sampler for the rows it
owns; the per-shard outputs are disjoint by construction and a
``pmax``/``psum`` collective over the ``shard`` axis (the frontier
exchange) reassembles the global ``SampleOut`` **bit-identically** to
the unsharded path (``tests/test_mesh.py`` pins it).

Executable accounting (docs/RETRACE.md discipline): the per-shard
sampling op is ONE module-level jit whose shapes are uniform across
shards — local ``indptr`` is ``rows_per_shard + 1`` everywhere and
``indices`` pads to one pow2 bucket over the *largest* shard — so its
key is effectively extended by the shard count (``rows_per_shard``
moves when ``n_shards`` does) and N shards reuse ONE executable.  The
combine is cached under ``("combine", B, k, n_shards)`` in the
``mesh_sampler`` program cache.  Steady-state serving over a fixed
frontier-size ladder builds nothing.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from .. import telemetry
from ..analysis.staging import no_sync
from ..ops.sample import SampleOut, sample_neighbors_overlay
from ..recovery.registry import program_cache
from .topology import SHARD_AXIS, build_mesh, row_shard, shard_ranges

__all__ = ["MeshSampler"]


def _pow2(n: int) -> int:
    b = 1
    while b < max(int(n), 1):
        b <<= 1
    return b


class MeshSampler:
    """One-hop frontier sampling over a row-range-sharded CSR."""

    def __init__(self, indptr: np.ndarray, indices: np.ndarray,
                 n_shards: Optional[int] = None, mesh=None,
                 gather_mode: str = "xla", sample_rng: str = "auto"):
        import jax.numpy as jnp

        from ..config import get_config, resolve_sample_rng

        cfg = get_config()
        if n_shards is None:
            n_shards = cfg.mesh_shards
        self.n_shards = int(n_shards)
        if self.n_shards < 1:
            raise ValueError(
                f"MeshSampler needs n_shards >= 1 (config.mesh_shards "
                f"is off); got {self.n_shards}")
        self.mesh = mesh if mesh is not None else build_mesh(self.n_shards)
        self.axis = SHARD_AXIS
        self.gather_mode = gather_mode
        self.sample_rng = resolve_sample_rng(sample_rng, gather_mode)
        indptr = np.asarray(indptr, dtype=np.int32)
        indices = np.asarray(indices, dtype=np.int32)
        self.node_count = len(indptr) - 1
        self.rows_per_shard, self.ranges = shard_ranges(
            self.node_count, self.n_shards)
        # one pow2 edge bucket over the largest shard: uniform shapes ->
        # ONE sampling executable reused by every shard
        edge_pad = _pow2(max(
            int(indptr[hi] - indptr[lo]) for lo, hi in self.ranges))
        self._indptr, self._indices = [], []
        for lo, hi in self.ranges:
            lp = np.zeros(self.rows_per_shard + 1, dtype=np.int32)
            lp[: hi - lo + 1] = indptr[lo:hi + 1] - indptr[lo]
            lp[hi - lo + 1:] = lp[hi - lo]      # pad rows: degree 0
            li = np.zeros(edge_pad, dtype=np.int32)
            li[: lp[hi - lo]] = indices[indptr[lo]:indptr[hi]]
            self._indptr.append(jnp.asarray(lp))
            self._indices.append(jnp.asarray(li))
        # frozen-graph mesh tier: no tombstones, empty delta overlay —
        # the overlay op with zero deltas is bitwise the frozen sampler
        self._tomb = jnp.zeros(edge_pad, dtype=jnp.int32)
        self._d_indptr = jnp.zeros(self.rows_per_shard + 1,
                                   dtype=jnp.int32)
        self._d_indices = jnp.zeros(8, dtype=jnp.int32)
        self._sharding = row_shard(self.mesh)
        self._edge_base = np.asarray(
            [int(indptr[lo]) for lo, _ in self.ranges], dtype=np.int32)
        self._jitted = program_cache("mesh_sampler", owner=self)
        from . import _set_active_sampler

        _set_active_sampler(self)

    # ------------------------------------------------------------------
    def _combine_fn(self, B: int, k: int):
        """The frontier exchange: per-shard disjoint ``SampleOut``
        blocks -> the global sample, as a collective over ``shard``."""
        import jax
        import jax.numpy as jnp
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        key = ("combine", B, k, self.n_shards)
        fn = self._jitted.get(key)
        if fn is None:
            axis = self.axis

            def _local(nbrs, mask, counts, eid, base):
                # exactly one shard owns each seed row: ids are >= 0
                # there and -1 on every other shard, so pmax selects
                # the owner's block unchanged; counts sum (others are 0)
                nb = jax.lax.pmax(nbrs[0], axis)
                mk = jax.lax.pmax(mask[0].astype(jnp.int32), axis) > 0
                # int32 cast makes the count-sum provably integer (QT015
                # bit-exactness contract): psum is reserved for counts,
                # payload rows go through the pmax sentinel above
                ct = jax.lax.psum(counts[0].astype(jnp.int32), axis)
                # shard-local edge positions -> global: offset by the
                # shard's first edge (eid stays -1 where masked)
                ei = jnp.where(eid[0] >= 0, eid[0] + base[0],
                               jnp.int32(-1))
                ei = jax.lax.pmax(ei, axis)
                return nb, mk, ct, ei

            fn = jax.jit(shard_map(
                _local, mesh=self.mesh,
                in_specs=(P(axis), P(axis), P(axis), P(axis), P(axis)),
                out_specs=(P(), P(), P(), P())))
            # quiverlint: ignore[QT014] -- raw B is deliberate: the mesh
            # sampler is bit-identical to the single-device path under
            # the same key, and padding seeds would change RNG
            # consumption; serving feeds pow2-padded batches, and
            # seal()/retrace_budget guard steady-state.
            self._jitted[key] = fn
        return fn

    def sample(self, seeds, k: int, key) -> SampleOut:
        """One dense ``[B, k]`` hop over the sharded CSR, bit-identical
        to the single-device sampler under the same ``key``."""
        import jax
        import jax.numpy as jnp

        seeds = np.asarray(seeds, dtype=np.int64).reshape(-1)
        B = len(seeds)
        outs = []
        for s, (lo, hi) in enumerate(self.ranges):
            owned = (seeds >= lo) & (seeds < hi)
            telemetry.gauge("mesh_shard_frontier_rows",
                            shard=str(s)).set(float(owned.sum()))
            local = np.clip(seeds - lo, 0, self.rows_per_shard - 1)
            outs.append(sample_neighbors_overlay(
                self._indptr[s], self._indices[s], self._tomb,
                self._d_indptr, self._d_indices,
                jnp.asarray(local, jnp.int32), k, key,
                seed_mask=jnp.asarray(owned),
                gather_mode=self.gather_mode,
                sample_rng=self.sample_rng))
        stack = [jax.device_put(jnp.stack(xs), self._sharding)
                 for xs in (tuple(o.nbrs for o in outs),
                            tuple(o.mask for o in outs),
                            tuple(o.counts for o in outs),
                            tuple(o.eid for o in outs))]
        base = jax.device_put(jnp.asarray(self._edge_base),
                              self._sharding)
        # the cross-shard combine dispatches collectives; a host sync
        # here would serialize the whole mesh per hop
        with no_sync("mesh combine"):
            nb, mk, ct, ei = self._combine_fn(B, k)(*stack, base)
        return SampleOut(nbrs=nb, mask=mk, counts=ct, eid=ei)

    def stats(self) -> dict:
        return dict(n_shards=self.n_shards,
                    rows_per_shard=self.rows_per_shard,
                    node_count=self.node_count,
                    executables=len(self._jitted))

    def __repr__(self):
        return (f"MeshSampler(nodes={self.node_count}, "
                f"shards={self.n_shards})")
