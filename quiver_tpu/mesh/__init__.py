"""``quiver_tpu.mesh`` — mesh-native sharded serving (docs/SHARDING.md).

Turns N devices into ONE logical serving replica: the feature table
and sampler frontier are sharded by row range across an explicit
``jax.sharding.Mesh`` (``data``/``shard`` axes), the cross-shard halo
exchange is a ``shard_map`` collective instead of the dist tier's
hand-rolled all-to-all, and the fleet routes to *shard groups* (see
``fleet/router.py``) whose members checkpoint coherently through
per-shard WAL segments (``recovery/shardwal.py``).

Everything here is OFF by default: with ``config.mesh_shards == 0``
nothing in this package is imported by the serving path and every
other tier is byte-identical to the unsharded build.

The weakref registry below backs ``GET /debug/mesh`` — the most
recently constructed :class:`MeshFeature` / :class:`MeshSampler` in
the process, same pattern as ``fleet.router.fleet_status``.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional
from weakref import ref as _weakref

_ACTIVE_LOCK = threading.Lock()
_ACTIVE_FEATURE: Optional[Callable] = None
_ACTIVE_SAMPLER: Optional[Callable] = None


def _set_active_feature(feature) -> None:
    global _ACTIVE_FEATURE
    with _ACTIVE_LOCK:
        _ACTIVE_FEATURE = _weakref(feature)


def _set_active_sampler(sampler) -> None:
    global _ACTIVE_SAMPLER
    with _ACTIVE_LOCK:
        _ACTIVE_SAMPLER = _weakref(sampler)


def mesh_status() -> dict:
    """The ``GET /debug/mesh`` document; ``{"active": False}`` when no
    mesh structure is live in this process."""
    with _ACTIVE_LOCK:
        feature = _ACTIVE_FEATURE() if _ACTIVE_FEATURE is not None \
            else None
        sampler = _ACTIVE_SAMPLER() if _ACTIVE_SAMPLER is not None \
            else None
    if feature is None and sampler is None:
        from ..config import get_config

        return {"active": False,
                "mesh_shards": int(get_config().mesh_shards)}
    doc: dict = {"active": True}
    if feature is not None:
        doc["feature"] = feature.stats()
        doc["n_shards"] = feature.n_shards
    if sampler is not None:
        doc["sampler"] = sampler.stats()
        doc.setdefault("n_shards", sampler.n_shards)
    return doc


from .feature import MeshFeature  # noqa: E402  (registry must exist first)
from .sampler import MeshSampler  # noqa: E402
from .topology import (DATA_AXIS, SHARD_AXIS, build_mesh,  # noqa: E402
                       match_partition_rules, replicated, require_devices,
                       row_shard, shard_ranges)

__all__ = ["MeshFeature", "MeshSampler", "mesh_status", "build_mesh",
           "row_shard", "replicated", "shard_ranges",
           "match_partition_rules", "require_devices", "DATA_AXIS",
           "SHARD_AXIS"]
