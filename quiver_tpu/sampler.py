"""Multi-hop GraphSAGE sampler — TPU-native GraphSageSampler.

Reference parity: ``srcs/python/quiver/pyg/sage_sampler.py:40-178``.  The
reference returns PyG's ``(n_id, batch_size, adjs)`` with ragged
``edge_index`` per layer; we return a :class:`SampledBatch` of dense
``[B_l, k_l]`` blocks (static shapes, jit-able end to end) plus adapters to
the ragged PyG form.

Modes (vs reference UVA/GPU/CPU, ``sage_sampler.py:55-81``):
  * ``"TPU"`` — topology in HBM, sampling under jit (replaces both GPU and
    UVA: there is no zero-copy middle tier on TPU; big graphs shard instead).
  * ``"CPU"`` — native C++ host sampler (``quiver_tpu.cpp``), used by the
    serving hybrid path and the mixed sampler.

Padded-frontier discipline: layer l's frontier is padded to
``P_l = min(P_{l-1} * (1 + k_l), frontier_caps[l])``.  With no caps the
result is exact (every sampled node kept); caps trade a vanishing amount of
tail-dropping for bounded shapes — measured frontiers on power-law graphs
sit far below the no-dedup bound, so a cap ~2x the typical frontier loses
~nothing and keeps XLA shapes small.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from . import telemetry
from .ops.sample import (sample_neighbors, sample_neighbors_overlay,
                         sample_neighbors_weighted, row_cumsum_weights)
from .ops.reindex import reindex
from .utils.topology import CSRTopo

__all__ = ["GraphSageSampler", "SampledBatch", "LayerBlock"]


class LayerBlock(NamedTuple):
    """One message-passing layer's bipartite block, dense form.

    Targets are the first ``num_targets`` entries of the *previous* (inner)
    frontier; ``nbr_local[b, j]`` indexes into this layer's frontier
    (``n_id``) to find source nodes.
    """

    nbr_local: jax.Array   # [T, k] int32 indices into this layer's n_id
    mask: jax.Array        # [T, k] bool
    num_targets: jax.Array  # scalar int32 (valid targets; T is the pad)
    eid: Optional[jax.Array] = None  # [T, k] int32 global edge ids (-1 pad)


class SampledBatch(NamedTuple):
    n_id: jax.Array         # [P] int32 final (outermost) frontier, padded
    n_id_mask: jax.Array    # [P] bool
    num_nodes: jax.Array    # scalar int32
    batch_size: int         # static: number of seed nodes
    layers: Tuple[LayerBlock, ...]  # outermost-first (PyG adjs order)
    drops: Optional[jax.Array] = None  # [L] per-hop frontier-cap drop
    # counts for THIS batch (overflow_stats(batch) reads it; the
    # sampler-level last_drops is unreliable under prefetching)
    version: Optional[int] = None  # streaming: the graph version this
    # batch sampled (the snapshot's), None on frozen-CSR samplers

    def to_pyg_adjs(self):
        """Ragged ``(n_id, batch_size, [Adj])`` view, PyG-compatible.

        Host-side (numpy); mirrors ``sage_sampler.py:118-147``'s return.
        Each Adj is ``(edge_index[2, e], e_id[e], (n_src, n_dst))``.

        Sizes are the PADDED per-layer frontier lengths: each hop's target
        frontier is by construction a *prefix* of its source frontier (both
        pipelines append new nodes after the previous frontier), so the
        standard PyG shrinking loop ``x = x[:size[1]]`` between layers
        slices exactly the next layer's node set.  Masked pad slots hold
        node 0 and are referenced by no edge, so they flow through as inert
        rows; ``n_id`` is returned in full (padded) for the same reason.
        """
        adjs = []
        n_src = int(self.n_id.shape[0])
        for blk in self.layers:
            m = np.asarray(blk.mask)  # quiverlint: sync-ok[PyG export boundary]
            nbr = np.asarray(blk.nbr_local)  # quiverlint: sync-ok[PyG export boundary]
            t, k = m.shape
            row = np.repeat(np.arange(t, dtype=np.int64), k).reshape(t, k)
            col = nbr.astype(np.int64)
            e = m.reshape(-1)
            edge_index = np.stack([col.reshape(-1)[e], row.reshape(-1)[e]])
            # quiverlint: sync-ok[PyG export boundary]
            e_id = (np.asarray(blk.eid).reshape(-1)[e]
                    if blk.eid is not None else np.empty(0, np.int64))
            adjs.append((edge_index, e_id, (n_src, t)))
            n_src = t  # this layer's targets = next (inner) layer's sources
        return (np.asarray(self.n_id), self.batch_size, adjs)  # quiverlint: sync-ok[PyG export boundary]


def _sample_pipeline_nodedup(indptr, indices, seeds, key, sizes,
                             gather_mode="xla", cum_weights=None,
                             return_eid=False, sample_rng="auto"):
    """Traced multi-hop pipeline WITHOUT dedup — the TPU hot path.

    Design note (why no hash table / no sort): the reference dedups every
    hop because on GPU the saved gathers/compute outweigh a hash-table
    kernel (reindex.cu.hpp).  On TPU the trade inverts: sort/searchsorted/
    scatter are the *slow* ops (measured: a hop-3-sized sort costs ~10x the
    sampling itself) while the MXU/HBM make duplicated frontier rows nearly
    free.  So the hot path relabels **positionally**: the hop-l frontier is
    ``concat(prev_frontier, sampled_nbrs.flat)`` and neighbor j of target b
    lives at position ``P_prev + b*k + j`` — no table, no sort, no scatter.
    Duplicate nodes compute duplicate embeddings (= original GraphSAGE
    tree-expansion semantics); validity masks carry through.  Exact-dedup
    per hop stays available via ``dedup="hop"`` for parity.
    """
    B = seeds.shape[0]
    frontier = seeds.astype(jnp.int32)
    fmask = jnp.ones((B,), dtype=bool)
    blocks = []
    keys = jax.random.split(key, len(sizes))
    for l, k in enumerate(sizes):
        if cum_weights is not None:
            out = sample_neighbors_weighted(indptr, indices, cum_weights,
                                            frontier, k, keys[l],
                                            seed_mask=fmask,
                                            sample_rng=sample_rng,
                                            gather_mode=gather_mode)
        else:
            out = sample_neighbors(indptr, indices, frontier, k, keys[l],
                                   seed_mask=fmask,
                                   gather_mode=gather_mode,
                                   sample_rng=sample_rng)
        t = frontier.shape[0]
        pos = (t + jnp.arange(t, dtype=jnp.int32)[:, None] * k
               + jnp.arange(k, dtype=jnp.int32)[None, :])
        blocks.append(
            LayerBlock(
                nbr_local=jnp.where(out.mask, pos, 0),
                mask=out.mask,
                num_targets=fmask.sum().astype(jnp.int32),
                # None lets XLA DCE the eid computation entirely — an
                # extra [T, k] int32 per hop is ~40% more sampler output
                # HBM traffic, only worth it for edge-featured models
                eid=out.eid if return_eid else None,
            )
        )
        frontier = jnp.concatenate(
            [frontier, jnp.where(out.mask, out.nbrs, 0).reshape(-1)]
        )
        fmask = jnp.concatenate([fmask, out.mask.reshape(-1)])
    num_nodes = fmask.sum().astype(jnp.int32)
    drops = jnp.zeros((len(sizes),), jnp.int32)  # nothing ever dropped
    return frontier, fmask, num_nodes, tuple(blocks[::-1]), drops


def _sample_pipeline_overlay(indptr, indices, tomb, d_indptr, d_indices,
                             seeds, key, sizes, base_ts=None, d_ts=None,
                             window_lo=None, window_hi=None,
                             gather_mode="xla", return_eid=False,
                             sample_rng="auto", windowed=False):
    """Traced multi-hop pipeline over base CSR + delta overlay.

    Structurally identical to :func:`_sample_pipeline_nodedup` (same key
    split, same positional relabel), with the one-hop op swapped for
    :func:`~quiver_tpu.ops.sample.sample_neighbors_overlay` — so with an
    empty delta segment and no tombstones the outputs are bitwise
    identical to the frozen positional pipeline (the streaming tier's
    equivalence contract).
    """
    B = seeds.shape[0]
    frontier = seeds.astype(jnp.int32)
    fmask = jnp.ones((B,), dtype=bool)
    blocks = []
    keys = jax.random.split(key, len(sizes))
    for l, k in enumerate(sizes):
        out = sample_neighbors_overlay(
            indptr, indices, tomb, d_indptr, d_indices, frontier, k,
            keys[l], seed_mask=fmask, base_ts=base_ts, d_ts=d_ts,
            window_lo=window_lo, window_hi=window_hi,
            gather_mode=gather_mode, sample_rng=sample_rng,
            windowed=windowed)
        t = frontier.shape[0]
        pos = (t + jnp.arange(t, dtype=jnp.int32)[:, None] * k
               + jnp.arange(k, dtype=jnp.int32)[None, :])
        blocks.append(
            LayerBlock(
                nbr_local=jnp.where(out.mask, pos, 0),
                mask=out.mask,
                num_targets=fmask.sum().astype(jnp.int32),
                eid=out.eid if return_eid else None,
            )
        )
        frontier = jnp.concatenate(
            [frontier, jnp.where(out.mask, out.nbrs, 0).reshape(-1)]
        )
        fmask = jnp.concatenate([fmask, out.mask.reshape(-1)])
    num_nodes = fmask.sum().astype(jnp.int32)
    drops = jnp.zeros((len(sizes),), jnp.int32)
    return frontier, fmask, num_nodes, tuple(blocks[::-1]), drops


def _sample_pipeline(indptr, indices, seeds, key, sizes, caps,
                     gather_mode="xla", cum_weights=None,
                     return_eid=False, sample_rng="auto"):
    """Traced multi-hop pipeline: outward sampling with per-hop dedup."""
    B = seeds.shape[0]
    frontier = seeds.astype(jnp.int32)
    fmask = jnp.ones((B,), dtype=bool)
    blocks = []
    drops = []  # per-hop count of frontier nodes dropped by the cap
    keys = jax.random.split(key, len(sizes))
    for l, (k, cap) in enumerate(zip(sizes, caps)):
        if cum_weights is not None:
            out = sample_neighbors_weighted(indptr, indices, cum_weights,
                                            frontier, k, keys[l],
                                            seed_mask=fmask,
                                            sample_rng=sample_rng,
                                            gather_mode=gather_mode)
        else:
            out = sample_neighbors(indptr, indices, frontier, k, keys[l],
                                   seed_mask=fmask, gather_mode=gather_mode,
                                   sample_rng=sample_rng)
        r = reindex(frontier, out.nbrs, out.mask, seed_mask=fmask)
        blocks.append(
            LayerBlock(
                nbr_local=r.local_nbrs,
                mask=r.mask,
                num_targets=fmask.sum().astype(jnp.int32),
                eid=out.eid if return_eid else None,
            )
        )
        n_id, n_mask = r.n_id, r.n_id_mask
        drop = jnp.int32(0)
        if cap is not None and n_id.shape[0] > cap:
            # Keep the prefix: seeds region is intact (caps must be >= T);
            # dropped tail nodes get masked out of this layer's block.
            drop = n_mask[cap:].sum().astype(jnp.int32)
            n_id, n_mask = n_id[:cap], n_mask[:cap]
            keep = blocks[-1].nbr_local < cap
            blocks[-1] = blocks[-1]._replace(
                mask=blocks[-1].mask & keep,
                nbr_local=jnp.where(keep, blocks[-1].nbr_local, 0),
                eid=(jnp.where(keep, blocks[-1].eid, jnp.int32(-1))
                     if blocks[-1].eid is not None else None),
            )
        drops.append(drop)
        frontier, fmask = n_id, n_mask
    num_nodes = fmask.sum().astype(jnp.int32)
    return frontier, fmask, num_nodes, tuple(blocks[::-1]), jnp.stack(drops)


def _is_stream_graph(obj) -> bool:
    """Duck-typed StreamingGraph detection (no static import cycle):
    anything exposing ``snapshot()`` + ``base`` samples via the overlay
    pipeline."""
    return hasattr(obj, "snapshot") and hasattr(obj, "base")


def run_pipeline(dedup, indptr, indices, seeds, key, sizes, caps,
                 gather_mode="xla", cum_weights=None, return_eid=False,
                 sample_rng="auto", overlay=None):
    """Dispatch to the dedup='none' or dedup='hop' traced pipeline — the
    single place that mapping lives (sampler jit + fused train/eval).

    ``overlay`` (a dict of delta-CSR arrays + window scalars, see
    ``GraphSageSampler._build_stream_jit``) routes to the streaming
    overlay pipeline; it rides the positional (``dedup='none'``)
    formulation only.
    """
    if overlay is not None:
        if dedup != "none":
            raise ValueError(
                "overlay sampling rides the positional pipeline only "
                f"(dedup='none'); got dedup={dedup!r}")
        if cum_weights is not None:
            raise ValueError("overlay sampling is uniform-only")
        return _sample_pipeline_overlay(
            indptr, indices, overlay["tomb"], overlay["d_indptr"],
            overlay["d_indices"], seeds, key, sizes,
            base_ts=overlay.get("base_ts"), d_ts=overlay.get("d_ts"),
            window_lo=overlay.get("window_lo"),
            window_hi=overlay.get("window_hi"),
            gather_mode=gather_mode, return_eid=return_eid,
            sample_rng=sample_rng,
            windowed=bool(overlay.get("windowed", False)))
    if dedup == "none":
        return _sample_pipeline_nodedup(indptr, indices, seeds, key, sizes,
                                        gather_mode=gather_mode,
                                        cum_weights=cum_weights,
                                        return_eid=return_eid,
                                        sample_rng=sample_rng)
    return _sample_pipeline(indptr, indices, seeds, key, sizes, caps,
                            gather_mode=gather_mode,
                            cum_weights=cum_weights, return_eid=return_eid,
                            sample_rng=sample_rng)


class GraphSageSampler:
    """K-hop neighbor sampler over a CSR graph.

    Args:
      csr_topo: :class:`CSRTopo`.
      sizes: fanout per layer, e.g. ``[15, 10, 5]`` (outward order, like PyG).
      device: jax device for the topology (None = default).
      mode: ``"TPU"`` (jit, default) or ``"CPU"`` (native host sampler).
      frontier_caps: optional per-layer cap on the padded frontier size
        (see module docstring).  Only meaningful with ``dedup="hop"``.
      dedup: ``"auto"`` (default — the measured library default:
        ``config.resolve_dedup``, overridable by the tuned file written
        from bench.py's on-chip e2e A/B), ``"none"`` (TPU hot path —
        positional relabel, no sort; frontier may contain duplicate
        nodes) or ``"hop"`` (reference-parity exact dedup each hop via
        ``ops.reindex``).
      edge_weights: optional ``[E]`` weights; hops then draw neighbors
        weight-proportionally WITH replacement
        (``ops.sample_neighbors_weighted``, reference weight_sample path).
      return_eid: materialize per-edge global CSR positions in
        ``LayerBlock.eid`` (and ``to_pyg_adjs`` e_id) for edge-featured
        models.  Off by default: it costs an extra ``[T, k]`` int32 per
        hop of output traffic, and the reference's default e_id is empty
        too (``sage_sampler.py:143``).
    """

    def __init__(self, csr_topo: CSRTopo, sizes: Sequence[int], device=None,
                 mode: str = "TPU",
                 frontier_caps: Optional[Sequence[Optional[int]]] = None,
                 dedup: str = "auto", gather_mode: str = "auto",
                 edge_weights=None, return_eid: bool = False,
                 uva_budget: Union[int, str, None] = None,
                 sample_rng: str = "auto", uva_overlap: bool = True,
                 uva_timings: Optional[dict] = None):
        assert mode in ("TPU", "CPU", "UVA", "GPU"), mode
        if mode == "GPU":  # compat alias from the reference API
            mode = "TPU"
        # streaming graphs (quiver_tpu.stream.StreamingGraph) are duck-
        # typed to avoid a static sampler -> stream import cycle; they
        # sample through the jitted overlay pipeline (TPU mode,
        # positional relabel, uniform draws only)
        is_stream = _is_stream_graph(csr_topo)
        if is_stream:
            if mode not in ("TPU",):
                raise ValueError(
                    f"StreamingGraph samples in TPU mode only, got "
                    f"{mode!r} (compact to a frozen CSRTopo for "
                    "CPU/UVA sampling)")
            if dedup == "auto":
                dedup = "none"
        if mode == "UVA" and uva_budget is None:
            mode = "TPU"  # whole graph fits the (unbounded) budget
        from .config import (resolve_dedup, resolve_gather_mode,
                             resolve_sample_rng)

        if mode == "UVA" and dedup == "auto":
            # UVA's hot/cold split rides the positional pipeline only;
            # a tuned/env 'hop' winner must not crash it (an EXPLICIT
            # dedup="hop" still hits the assert below)
            dedup = "none"
        dedup = resolve_dedup(dedup)
        self.gather_mode = resolve_gather_mode(gather_mode, sample_rng)
        self.sample_rng = resolve_sample_rng(sample_rng, self.gather_mode)
        self.return_eid = return_eid
        self.csr_topo = csr_topo  # property setter: splits stream/frozen
        if is_stream:
            assert dedup == "none", (
                "StreamingGraph: positional pipeline only (dedup='none')")
            assert edge_weights is None, (
                "StreamingGraph: uniform sampling only")
        self.sizes = list(sizes)
        # live fanout scale (QoS degradation ladder L1).  Applies to the
        # HOST sampling path only: device pipelines bake ``sizes`` into
        # the jitted closure, and recompiling under overload is exactly
        # the wrong reaction — the CPU lane is where brownout headroom
        # is won anyway.
        self._fanout_frac = 1.0
        self.mode = mode
        self.dedup = dedup
        self.device = device
        self.frontier_caps = (
            list(frontier_caps) if frontier_caps is not None
            else [None] * len(self.sizes)
        )
        assert len(self.frontier_caps) == len(self.sizes)
        from .recovery.registry import program_cache

        self._jitted = program_cache(
            "sampler", owner=self)  # batch_size -> compiled pipeline
        # (mixed-size workloads — e.g. serving buckets — must not evict
        # each other)
        self._cpu = None
        self.uva_budget = uva_budget
        # uva_overlap=False serializes the device/host tiers (the A/B
        # baseline for the overlap claim); uva_timings accumulates the
        # cold tier's host wall ("host_s") when a dict is passed
        self.uva_overlap = uva_overlap
        self.uva_timings = uva_timings
        self._uva = None
        if mode == "UVA":
            assert dedup == "none", "UVA mode: positional pipeline only"
            assert edge_weights is None, "UVA mode: uniform sampling only"
            assert not return_eid, (
                "UVA mode: hot-tier edge positions are sub-CSR local, so "
                "global eids are unavailable; use TPU or CPU mode"
            )
        self._cum_weights = None
        self._edge_weights = edge_weights
        if edge_weights is not None and mode == "TPU":
            cw = row_cumsum_weights(csr_topo.indptr, edge_weights)
            import jax.numpy as _jnp

            from .ops.fastgather import pad_table_128

            # edge-value fill: clipped probes past E read a harmless
            # value; the lanes/pallas gathers require 128-multiple tables
            self._cum_weights = pad_table_128(
                _jnp.asarray(cw), fill=float(cw[-1]) if len(cw) else None)
        if mode == "TPU":
            if self._stream is not None:
                self._stream.snapshot(device)  # warm the device view
            else:
                csr_topo.to_device(device)

    # -- topology access ----------------------------------------------
    @property
    def csr_topo(self):
        """The live base CSR.  For streaming graphs this follows the
        compactor's base swaps; single-hop helpers (``sample_layer``,
        ``sample_prob``) read it and therefore see the base WITHOUT the
        pending delta overlay — multi-hop :meth:`sample` is the overlay-
        aware path."""
        if self._stream is not None:
            return self._stream.base
        return self._csr_topo

    @csr_topo.setter
    def csr_topo(self, value):
        if _is_stream_graph(value):
            self._stream = value
            self._csr_topo = None
        else:
            self._stream = None
            self._csr_topo = value

    # -- single-hop API (parity with sample_layer / reindex,
    #    sage_sampler.py:83-116) --------------------------------------
    def sample_layer(self, batch, size: int, key=None):
        indptr, indices = self.csr_topo.to_device(self.device)
        if key is None:
            from .utils.rng import make_key

            key = make_key(0)
        seeds = jnp.asarray(np.asarray(batch), dtype=jnp.int32)
        return sample_neighbors(indptr, indices, seeds, size, key)

    def reindex(self, inputs, nbrs, mask):
        return reindex(jnp.asarray(np.asarray(inputs), jnp.int32), nbrs, mask)

    def sample_sub(self, seeds, size: int, key=None):
        """One-hop subgraph extraction: dedup'd node set + relabeled COO.

        Parity: ``TorchQuiver::sample_sub`` (quiver_sample.cu:258-303) —
        returns ``(nodes, row, col)`` where ``nodes[:len(seeds)] == seeds``
        and (row, col) are local-id edges of the sampled subgraph.
        """
        seeds = np.asarray(seeds)
        out = self.sample_layer(seeds, size, key=key)
        r = self.reindex(seeds, out.nbrs, out.mask)
        num = int(r.num_nodes)  # quiverlint: sync-ok[host subgraph export]
        nodes = np.asarray(r.n_id)[:num]  # quiverlint: sync-ok[host subgraph export]
        m = np.asarray(r.mask)  # quiverlint: sync-ok[host subgraph export]
        local = np.asarray(r.local_nbrs)  # quiverlint: sync-ok[host subgraph export]
        row = np.repeat(np.arange(len(seeds)), out.nbrs.shape[1]).reshape(
            m.shape
        )[m]
        col = local[m]
        return nodes, row, col

    # -- multi-hop API ------------------------------------------------
    def _build_jit(self, batch_size: int):
        indptr, indices = self.csr_topo.to_device(self.device)
        sizes = tuple(self.sizes)
        caps = tuple(self.frontier_caps)
        dedup = self.dedup
        gm = self.gather_mode
        cw = self._cum_weights

        ret_eid = self.return_eid

        srng = self.sample_rng

        @jax.jit
        def fn(seeds, key):
            return run_pipeline(dedup, indptr, indices, seeds, key, sizes,
                                caps, gather_mode=gm, cum_weights=cw,
                                return_eid=ret_eid, sample_rng=srng)

        return fn

    def _build_stream_jit(self, batch_size: int, windowed: bool):
        """Compile the overlay pipeline for one (batch, snapshot-shape)
        key.  Unlike :meth:`_build_jit` the topology arrays are traced
        ARGUMENTS, not closure constants: snapshot contents change every
        graph version, and baking them in would recompile per mutation.
        Executables therefore key on shapes only —
        ``(B, epad, delta_bucket, has_ts, windowed)`` — which is the
        additive-key discipline the retrace budget enforces."""
        sizes = tuple(self.sizes)
        gm = self.gather_mode
        srng = self.sample_rng
        ret_eid = self.return_eid
        caps = tuple(self.frontier_caps)

        @jax.jit
        def fn(indptr, indices, tomb, d_indptr, d_indices, base_ts, d_ts,
               seeds, key, window_lo, window_hi):
            overlay = dict(tomb=tomb, d_indptr=d_indptr,
                           d_indices=d_indices, base_ts=base_ts,
                           d_ts=d_ts, window_lo=window_lo,
                           window_hi=window_hi, windowed=windowed)
            return run_pipeline("none", indptr, indices, seeds, key,
                                sizes, caps, gather_mode=gm,
                                return_eid=ret_eid, sample_rng=srng,
                                overlay=overlay)

        return fn

    def sample(self, input_nodes, key=None,
               time_window=None) -> SampledBatch:
        """Sample k-hop neighborhood of ``input_nodes``.

        Returns a :class:`SampledBatch`; call ``.to_pyg_adjs()`` for the
        reference's ``(n_id, batch_size, adjs)`` tuple.

        ``time_window=(lo, hi)`` (streaming graphs with ``edge_ts``
        only) restricts draws to edges with ``lo <= ts < hi``; the
        window rides as traced scalars, so varying it never recompiles.

        Telemetry: each call folds into the ``sampler.sample`` span and
        the ``sampler_sample_seconds{mode}`` histogram (TPU mode times
        dispatch, not device completion — async), plus batch/seed
        counters.
        """
        mode = self.mode.lower()
        with telemetry.span("sampler.sample"), telemetry.histogram(
                "sampler_sample_seconds", mode=mode).time():
            batch = self._sample_impl(input_nodes, key,
                                      time_window=time_window)
        telemetry.counter("sampler_batches_total", mode=mode).inc()
        telemetry.counter("sampler_seeds_total", mode=mode).inc(
            float(batch.batch_size))
        return batch

    def _sample_impl(self, input_nodes, key=None,
                     time_window=None) -> SampledBatch:
        if self._stream is not None:
            return self._sample_stream(input_nodes, key, time_window)
        if time_window is not None:
            raise ValueError(
                "time_window requires a StreamingGraph with per-edge "
                "timestamps (quiver_tpu.stream)")
        if self.mode == "CPU":
            return self._sample_cpu(input_nodes)
        if self.mode == "UVA":
            return self._sample_uva(input_nodes, key)
        if isinstance(input_nodes, jax.Array):  # stay on device
            seeds = input_nodes.astype(jnp.int32)
        else:
            seeds = jnp.asarray(np.asarray(input_nodes), dtype=jnp.int32)
        B = seeds.shape[0]
        fn = self._jitted.get(B)
        if fn is None:
            # quiverlint: ignore[QT014] -- raw B is the sampler's
            # contract: one executable per seed-batch size, bit-stable
            # RNG per seed row (padding would consume extra key splits).
            # Serving pads upstream via _pad_ids; seal()/retrace_budget
            # guard the steady state.
            fn = self._jitted[B] = self._build_jit(B)
        if key is None:
            from .utils.rng import make_key

            key = make_key(np.random.randint(0, 2**31 - 1))
        n_id, n_mask, num_nodes, blocks, drops = fn(seeds, key)
        # [L] per-hop frontier-cap drop counts (always 0 without caps);
        # kept on device until someone asks via overflow_stats() — the
        # drop counter is incremented there, at materialization, so the
        # hot loop never pays a device sync for accounting
        self.last_drops = drops
        self._drops_recorded = False
        return SampledBatch(
            n_id=n_id, n_id_mask=n_mask, num_nodes=num_nodes,
            batch_size=B, layers=blocks, drops=drops,
        )

    def _sample_stream(self, input_nodes, key, time_window) -> SampledBatch:
        """Overlay-aware multi-hop sampling against the current
        :class:`~quiver_tpu.stream.graph.DeltaSnapshot`."""
        snap = self._stream.snapshot(self.device)
        windowed = time_window is not None
        if windowed and not snap.has_ts:
            raise ValueError(
                "time_window needs a StreamingGraph constructed with "
                "edge_ts")
        if isinstance(input_nodes, jax.Array):  # stay on device
            seeds = input_nodes.astype(jnp.int32)
        else:
            seeds = jnp.asarray(np.asarray(input_nodes), dtype=jnp.int32)
        B = seeds.shape[0]
        jk = ("stream", B, snap.epad, snap.delta_bucket, snap.has_ts,
              windowed)
        fn = self._jitted.get(jk)
        if fn is None:
            # quiverlint: ignore[QT014] -- B: same raw-batch-size
            # contract as the static path.  epad moves only at
            # compaction/fold (O(graph versions), not O(requests)) and
            # delta_bucket is _fanout_bucket-padded at snapshot build;
            # both ride the DeltaSnapshot NamedTuple, whose device-array
            # provenance the symbolic trace cannot follow.
            fn = self._jitted[jk] = self._build_stream_jit(B, windowed)
        if key is None:
            from .utils.rng import make_key

            key = make_key(np.random.randint(0, 2**31 - 1))
        if windowed:
            lo, hi = time_window
            # device scalars, not Python ints: traced operands, so a new
            # window is a new argument value — never a new executable
            window_lo = jnp.int32(lo)
            window_hi = jnp.int32(hi)
        else:
            window_lo = window_hi = None
        n_id, n_mask, num_nodes, blocks, drops = fn(
            snap.indptr, snap.indices, snap.tomb, snap.d_indptr,
            snap.d_indices, snap.base_ts, snap.d_ts, seeds, key,
            window_lo, window_hi)
        self.last_drops = drops
        self._drops_recorded = False
        return SampledBatch(
            n_id=n_id, n_id_mask=n_mask, num_nodes=num_nodes,
            batch_size=B, layers=blocks, drops=drops,
            version=snap.version,
        )

    def overflow_stats(self, batch: Optional[SampledBatch] = None):
        """[L] per-hop counts of frontier nodes dropped by ``frontier_caps``.

        Pass the :class:`SampledBatch` to get THAT batch's counts — the
        only reliable form when a loader samples ahead (``SeedLoader``
        dispatches batch i+1 before batch i is consumed, so the
        sampler-level "most recent call" is usually the next batch).
        Without ``batch``: the most recent ``sample`` call (None before
        any TPU-mode call; always zero without caps or ``dedup='none'``).
        """
        if batch is not None:
            # quiverlint: sync-ok[deliberate materialization point for drops]
            return None if batch.drops is None else np.asarray(batch.drops)
        if getattr(self, "last_drops", None) is None:
            return None
        # quiverlint: sync-ok[deliberate materialization point for drops]
        arr = np.asarray(self.last_drops)
        # count into the registry exactly once per sample() call (the
        # batch= form can't dedup across repeat queries, so only the
        # sampler-level path feeds the counter)
        if not getattr(self, "_drops_recorded", True):
            self._drops_recorded = True
            total = float(arr.sum())
            if total:
                telemetry.counter("sampler_frontier_drops_total",
                                  mode=self.mode.lower()).inc(total)
        return arr

    def _sample_uva(self, input_nodes, key) -> SampledBatch:
        """Hot/cold big-graph sampling (``quiver_tpu.uva``): HBM-budgeted
        hot rows on device, cold rows on the native host sampler,
        overlapped per hop."""
        from .uva import UVAGraph, sample_uva

        if self._uva is None:
            self._uva = UVAGraph(self.csr_topo, self.uva_budget)
        if key is None:
            from .utils.rng import make_key

            key = make_key(np.random.randint(0, 2**31 - 1))
        gm = self.gather_mode
        n_id, n_mask, num, blocks = sample_uva(
            self._uva, self.sizes, input_nodes, key, gather_mode=gm,
            sample_rng=self.sample_rng,
            overlap=self.uva_overlap, timings=self.uva_timings,
        )
        return SampledBatch(
            n_id=jnp.asarray(n_id), n_id_mask=jnp.asarray(n_mask),
            num_nodes=jnp.asarray(num), batch_size=len(input_nodes),
            layers=tuple(
                LayerBlock(jnp.asarray(nl), jnp.asarray(m),
                           jnp.asarray(t))
                for nl, m, t in blocks
            ),
        )

    def set_fanout_frac(self, frac: float) -> None:
        """Scale the host-path fanout to ``frac`` of the configured
        ``sizes`` (each layer floored at 1 neighbor).  ``1.0`` restores
        full fanout.  Reversible brownout knob for the QoS ladder —
        device executables are untouched (their sizes are compile-time
        constants)."""
        self._fanout_frac = float(min(max(frac, 0.0), 1.0))

    def _effective_sizes(self):
        frac = self._fanout_frac
        if frac >= 1.0:
            return self.sizes
        return [max(1, int(s * frac)) for s in self.sizes]

    def _sample_cpu(self, input_nodes) -> SampledBatch:
        from .cpp import native

        if self._cpu is None:
            self._cpu = native.CPUSampler(
                self.csr_topo.indptr, self.csr_topo.indices,
                edge_weights=self._edge_weights,
            )
        seeds = np.asarray(input_nodes, dtype=np.int64)
        n_id, n_mask, num_nodes, blocks = self._cpu.sample_multihop(
            seeds, self._effective_sizes()
        )
        return SampledBatch(
            n_id=jnp.asarray(n_id), n_id_mask=jnp.asarray(n_mask),
            num_nodes=jnp.asarray(num_nodes), batch_size=len(seeds),
            layers=tuple(
                LayerBlock(jnp.asarray(nl), jnp.asarray(m), jnp.asarray(t))
                for nl, m, t in blocks
            ),
        )

    # -- sampling probability (parity: sample_prob,
    #    sage_sampler.py:149-157 + cal_next, cuda_random.cu.hpp:72-104) --
    def sample_prob(self, train_idx, total_node_count: int):
        from .ops.prob import sample_prob as _sp

        indptr, indices = self.csr_topo.to_device(self.device)
        return _sp(indptr, indices, jnp.asarray(np.asarray(train_idx)),
                   total_node_count, self.sizes,
                   num_edges=self.csr_topo.edge_count)

    # -- spawn/IPC parity: jax is single-controller, nothing to share; keep
    #    the API so reference code ports 1:1 (sage_sampler.py:159-178). --
    def share_ipc(self):
        return self.csr_topo, self.sizes, self.mode

    @classmethod
    def lazy_from_ipc_handle(cls, ipc_handle):
        csr_topo, sizes, mode = ipc_handle
        return cls(csr_topo, sizes, mode=mode)

    def __repr__(self):
        return (
            f"GraphSageSampler(sizes={self.sizes}, mode={self.mode!r}, "
            f"dedup={self.dedup!r}, gather={self.gather_mode!r}, "
            f"graph={self.csr_topo!r})"
        )
