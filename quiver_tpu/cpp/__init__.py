from . import native
