// Native host-side sampler for quiver_tpu.
//
// Reference parity: the CPU sampler core (srcs/cpp/include/quiver/
// quiver.cpu.hpp:31-104) and CPUQuiver bindings (srcs/cpp/src/quiver/
// quiver.cpp:11-85).  Same contract as the TPU ops: dense [B, k] neighbor
// blocks + masks, dedup/relabel with seeds-first frontier and id-sorted
// remainder, so CPU and TPU backends are interchangeable bit-for-bit in
// structure (sampling randomness differs by backend, as in the reference).
//
// Exposed as a plain C ABI consumed via ctypes (no pybind11 in this image).

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <random>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

// splitmix64: cheap, seedable, stateless per-seed streams.
static inline uint64_t splitmix64(uint64_t x) {
    x += 0x9e3779b97f4a7c15ULL;
    x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ULL;
    x = (x ^ (x >> 27)) * 0x94d049bb133111ebULL;
    return x ^ (x >> 31);
}

struct Rng {
    uint64_t s;
    explicit Rng(uint64_t seed) : s(seed) {}
    uint64_t next() { return s = splitmix64(s); }
    // unbiased-enough range sample for sampling use
    int64_t below(int64_t n) { return (int64_t)(next() % (uint64_t)n); }
};

}  // namespace

extern "C" {

// One-hop sampling: up to k distinct neighbors per seed (reservoir, like
// quiver.cpu.hpp:60-104 which uses std::sample).  Parallel over seed chunks.
void qt_sample(const int64_t* indptr, const int32_t* indices,
               const int32_t* seeds, const uint8_t* seed_mask, int64_t B,
               int32_t k, uint64_t rng_seed, int32_t n_threads,
               int32_t* out_nbrs, uint8_t* out_mask, int32_t* out_counts) {
    if (n_threads <= 0) {
        n_threads = (int32_t)std::thread::hardware_concurrency();
        if (n_threads <= 0) n_threads = 1;
    }
    auto work = [&](int64_t lo, int64_t hi) {
        std::vector<int64_t> res(k);
        for (int64_t b = lo; b < hi; ++b) {
            int32_t* nb = out_nbrs + b * k;
            uint8_t* mk = out_mask + b * k;
            if (seed_mask && !seed_mask[b]) {
                out_counts[b] = 0;
                std::memset(mk, 0, k);
                std::fill(nb, nb + k, -1);
                continue;
            }
            const int64_t s = seeds[b];
            const int64_t beg = indptr[s], end = indptr[s + 1];
            const int64_t deg = end - beg;
            const int64_t cnt = deg < k ? deg : k;
            out_counts[b] = (int32_t)cnt;
            Rng rng(rng_seed * 0x2545F4914F6CDD1DULL + (uint64_t)b);
            if (deg <= k) {
                for (int64_t j = 0; j < cnt; ++j) nb[j] = indices[beg + j];
            } else {
                // reservoir over positions
                for (int64_t j = 0; j < k; ++j) res[j] = j;
                for (int64_t j = k; j < deg; ++j) {
                    int64_t r = rng.below(j + 1);
                    if (r < k) res[r] = j;
                }
                for (int64_t j = 0; j < k; ++j)
                    nb[j] = indices[beg + res[j]];
            }
            for (int64_t j = 0; j < k; ++j) mk[j] = j < cnt;
            for (int64_t j = cnt; j < k; ++j) nb[j] = -1;
        }
    };
    if (n_threads == 1 || B < 256) {
        work(0, B);
        return;
    }
    std::vector<std::thread> ts;
    int64_t chunk = (B + n_threads - 1) / n_threads;
    for (int32_t t = 0; t < n_threads; ++t) {
        int64_t lo = t * chunk, hi = std::min(B, lo + chunk);
        if (lo >= hi) break;
        ts.emplace_back(work, lo, hi);
    }
    for (auto& t : ts) t.join();
}

// Weighted one-hop sampling WITH replacement (parity: the reference's
// weight_sample thrust path, cuda_random.cu.hpp:149-221).  cumw is the
// per-row inclusive cumulative weight array produced by
// quiver_tpu.ops.sample.row_cumsum_weights — the same artifact the TPU
// weighted sampler uses, so CPU/TPU draws share one distribution.
void qt_sample_weighted(const int64_t* indptr, const int32_t* indices,
                        const float* cumw, const int32_t* seeds,
                        const uint8_t* seed_mask, int64_t B, int32_t k,
                        uint64_t rng_seed, int32_t n_threads,
                        int32_t* out_nbrs, uint8_t* out_mask,
                        int32_t* out_counts) {
    if (n_threads <= 0) {
        n_threads = (int32_t)std::thread::hardware_concurrency();
        if (n_threads <= 0) n_threads = 1;
    }
    auto work = [&](int64_t lo, int64_t hi) {
        for (int64_t b = lo; b < hi; ++b) {
            int32_t* nb = out_nbrs + b * k;
            uint8_t* mk = out_mask + b * k;
            if (seed_mask && !seed_mask[b]) {
                out_counts[b] = 0;
                std::memset(mk, 0, k);
                std::fill(nb, nb + k, -1);
                continue;
            }
            const int64_t s = seeds[b];
            const int64_t beg = indptr[s], end = indptr[s + 1];
            const int64_t deg = end - beg;
            const int64_t cnt = deg < k ? deg : k;
            out_counts[b] = (int32_t)cnt;
            Rng rng(rng_seed * 0x2545F4914F6CDD1DULL + (uint64_t)b);
            if (deg <= k) {  // all neighbors once (mask contract parity)
                for (int64_t j = 0; j < cnt; ++j) nb[j] = indices[beg + j];
            } else {
                const float total = cumw[end - 1];
                for (int64_t j = 0; j < k; ++j) {
                    // 53-bit uniform in [0, total)
                    double u = (double)(rng.next() >> 11) * 0x1p-53 * total;
                    const float* p = std::upper_bound(
                        cumw + beg, cumw + end, (float)u);
                    int64_t pos = p - (cumw + beg);
                    if (pos >= deg) pos = deg - 1;
                    nb[j] = indices[beg + pos];
                }
            }
            for (int64_t j = 0; j < k; ++j) mk[j] = j < cnt;
            for (int64_t j = cnt; j < k; ++j) nb[j] = -1;
        }
    };
    if (n_threads == 1 || B < 256) {
        work(0, B);
        return;
    }
    std::vector<std::thread> ts;
    int64_t chunk = (B + n_threads - 1) / n_threads;
    for (int32_t t = 0; t < n_threads; ++t) {
        int64_t lo = t * chunk, hi = std::min(B, lo + chunk);
        if (lo >= hi) break;
        ts.emplace_back(work, lo, hi);
    }
    for (auto& t : ts) t.join();
}

// Dedup + relabel, same contract as quiver_tpu.ops.reindex: n_id holds the
// (valid) seeds in their original slots, then the unique non-seed neighbors
// in ascending id order.  Returns the number of valid frontier nodes.
int64_t qt_reindex(const int32_t* seeds, const uint8_t* seed_mask, int64_t B,
                   const int32_t* nbrs, const uint8_t* mask, int32_t k,
                   int32_t* n_id, uint8_t* n_id_mask, int32_t* local_nbrs) {
    std::unordered_map<int32_t, int32_t> table;
    table.reserve((size_t)(B * 2));
    int64_t valid_seeds = 0;
    for (int64_t b = 0; b < B; ++b) {
        bool v = !seed_mask || seed_mask[b];
        n_id[b] = v ? seeds[b] : 0;
        n_id_mask[b] = v;
        if (v) {
            table.emplace(seeds[b], (int32_t)b);
            ++valid_seeds;
        }
    }
    std::vector<int32_t> rest;
    rest.reserve((size_t)(B * k));
    for (int64_t i = 0; i < B * k; ++i) {
        if (!mask[i]) continue;
        if (table.find(nbrs[i]) == table.end()) rest.push_back(nbrs[i]);
    }
    std::sort(rest.begin(), rest.end());
    rest.erase(std::unique(rest.begin(), rest.end()), rest.end());
    for (size_t r = 0; r < rest.size(); ++r) {
        n_id[B + r] = rest[r];
        n_id_mask[B + r] = 1;
        table.emplace(rest[r], (int32_t)(B + r));
    }
    for (int64_t i = rest.size() + B; i < B + B * k; ++i) {
        n_id[i] = 0;
        n_id_mask[i] = 0;
    }
    for (int64_t i = 0; i < B * k; ++i)
        local_nbrs[i] = mask[i] ? table[nbrs[i]] : 0;
    return valid_seeds + (int64_t)rest.size();
}

// COO -> CSR counting sort (parity: sparse.hpp:8-32 / quiver_sample.cu:463).
void qt_coo_to_csr(const int64_t* src, const int64_t* dst, int64_t E,
                   int64_t N, int64_t* indptr, int32_t* indices,
                   int64_t* eid) {
    std::vector<int64_t> cnt((size_t)N + 1, 0);
    for (int64_t e = 0; e < E; ++e) cnt[(size_t)src[e] + 1]++;
    for (int64_t i = 0; i < N; ++i) cnt[(size_t)i + 1] += cnt[(size_t)i];
    std::memcpy(indptr, cnt.data(), sizeof(int64_t) * (size_t)(N + 1));
    std::vector<int64_t> cur(cnt.begin(), cnt.end() - 1);
    for (int64_t e = 0; e < E; ++e) {
        int64_t p = cur[(size_t)src[e]]++;
        indices[p] = (int32_t)dst[e];
        if (eid) eid[p] = e;
    }
}

// Per-node expanded-neighborhood size (drives serving's request routing;
// parity: generate_neighbour_num.py:10-95).  For each node, run the fanout
// expansion counting *expected* sampled counts: prod over layers of
// min(deg, k) growth, computed exactly by BFS with multiplicities capped.
// Here we do the same thing the reference does: actually sample once.
void qt_neighbour_num(const int64_t* indptr, const int32_t* indices,
                      int64_t N, const int32_t* sizes, int32_t n_layers,
                      uint64_t rng_seed, int32_t n_threads, int64_t* out) {
    if (n_threads <= 0) {
        n_threads = (int32_t)std::thread::hardware_concurrency();
        if (n_threads <= 0) n_threads = 1;
    }
    auto work = [&](int64_t lo, int64_t hi) {
        std::vector<int32_t> frontier, next;
        for (int64_t v = lo; v < hi; ++v) {
            frontier.assign(1, (int32_t)v);
            int64_t total = 0;
            Rng rng(rng_seed * 0x9E3779B97F4A7C15ULL + (uint64_t)v);
            for (int32_t l = 0; l < n_layers; ++l) {
                const int32_t k = sizes[l];
                next.clear();
                for (int32_t u : frontier) {
                    int64_t beg = indptr[u], deg = indptr[u + 1] - beg;
                    int64_t cnt = deg < k ? deg : k;
                    if (deg <= k) {
                        for (int64_t j = 0; j < cnt; ++j)
                            next.push_back(indices[beg + j]);
                    } else {
                        for (int64_t j = 0; j < k; ++j)
                            next.push_back(indices[beg + rng.below(deg)]);
                    }
                }
                total += (int64_t)next.size();
                frontier.swap(next);
            }
            out[v] = total;
        }
    };
    std::vector<std::thread> ts;
    int64_t chunk = (N + n_threads - 1) / n_threads;
    for (int32_t t = 0; t < n_threads; ++t) {
        int64_t lo = t * chunk, hi = std::min(N, lo + chunk);
        if (lo >= hi) break;
        ts.emplace_back(work, lo, hi);
    }
    for (auto& t : ts) t.join();
}

}  // extern "C"
