// Native self-test for quiver_cpu.cpp (parity: tests/cpp/test_quiver_cpu.cpp
// in the reference — generated-graph fixtures, sample-validity properties).
// Build/run: make -C quiver_tpu/cpp test      (plain)
//            make -C quiver_tpu/cpp asan      (address+UB sanitizers)

#include <cassert>
#include <cstdint>
#include <cstdio>
#include <random>
#include <set>
#include <vector>

extern "C" {
void qt_sample(const int64_t*, const int32_t*, const int32_t*,
               const uint8_t*, int64_t, int32_t, uint64_t, int32_t,
               int32_t*, uint8_t*, int32_t*);
int64_t qt_reindex(const int32_t*, const uint8_t*, int64_t, const int32_t*,
                   const uint8_t*, int32_t, int32_t*, uint8_t*, int32_t*);
void qt_coo_to_csr(const int64_t*, const int64_t*, int64_t, int64_t,
                   int64_t*, int32_t*, int64_t*);
void qt_sample_weighted(const int64_t*, const int32_t*, const float*,
                        const int32_t*, const uint8_t*, int64_t, int32_t,
                        uint64_t, int32_t, int32_t*, uint8_t*, int32_t*);
void qt_neighbour_num(const int64_t*, const int32_t*, int64_t,
                      const int32_t*, int32_t, uint64_t, int32_t, int64_t*);
}

int main() {
    // --- random graph fixture
    const int64_t N = 500;
    std::mt19937_64 rng(7);
    std::vector<int64_t> src, dst;
    for (int64_t v = 0; v < N; ++v) {
        int64_t d = rng() % 12;
        for (int64_t j = 0; j < d; ++j) {
            src.push_back(v);
            dst.push_back((int64_t)(rng() % N));
        }
    }
    const int64_t E = (int64_t)src.size();
    std::vector<int64_t> indptr(N + 1), eid(E);
    std::vector<int32_t> indices(E);
    qt_coo_to_csr(src.data(), dst.data(), E, N, indptr.data(),
                  indices.data(), eid.data());
    assert(indptr[0] == 0 && indptr[N] == E);
    for (int64_t i = 0; i < N; ++i) assert(indptr[i] <= indptr[i + 1]);
    // eid maps back: dst[eid[p]] == indices[p]
    for (int64_t p = 0; p < E; ++p) assert(dst[(size_t)eid[p]] == indices[p]);

    // --- sampling properties: subset + counts + distinct positions
    const int32_t k = 5;
    std::vector<int32_t> seeds(N);
    for (int64_t i = 0; i < N; ++i) seeds[i] = (int32_t)i;
    std::vector<int32_t> nbrs(N * k), counts(N);
    std::vector<uint8_t> mask(N * k);
    qt_sample(indptr.data(), indices.data(), seeds.data(), nullptr, N, k,
              123, 4, nbrs.data(), mask.data(), counts.data());
    for (int64_t v = 0; v < N; ++v) {
        int64_t deg = indptr[v + 1] - indptr[v];
        int64_t expect = deg < k ? deg : k;
        assert(counts[v] == expect);
        std::multiset<int32_t> row(indices.begin() + indptr[v],
                                   indices.begin() + indptr[v + 1]);
        for (int32_t j = 0; j < k; ++j) {
            if (j < expect) {
                assert(mask[v * k + j]);
                assert(row.count(nbrs[v * k + j]) > 0);
            } else {
                assert(!mask[v * k + j]);
            }
        }
    }

    // --- reindex: seeds-first, bijection, resolvable locals
    const int64_t B = 32;
    std::vector<int32_t> n_id(B + B * k), local(B * k);
    std::vector<uint8_t> n_mask(B + B * k);
    int64_t num = qt_reindex(seeds.data(), nullptr, B, nbrs.data(),
                             mask.data(), k, n_id.data(), n_mask.data(),
                             local.data());
    std::set<int32_t> uniq;
    for (int64_t i = 0; i < B + B * k; ++i)
        if (n_mask[i]) uniq.insert(n_id[i]);
    assert((int64_t)uniq.size() == num);
    for (int64_t b = 0; b < B; ++b) assert(n_id[b] == seeds[b]);
    for (int64_t i = 0; i < B * k; ++i)
        if (mask[i]) assert(n_id[local[i]] == nbrs[i]);

    // --- weighted sampling: subset + counts, multithreaded
    {
        std::vector<float> cumw(E);
        for (int64_t v = 0; v < N; ++v) {
            float acc = 0.f;
            for (int64_t p = indptr[v]; p < indptr[v + 1]; ++p)
                cumw[p] = (acc += 1.0f + (float)(p % 3));
        }
        std::vector<int32_t> wn(N * k), wc(N);
        std::vector<uint8_t> wm(N * k);
        qt_sample_weighted(indptr.data(), indices.data(), cumw.data(),
                           seeds.data(), nullptr, N, k, 77, 4, wn.data(),
                           wm.data(), wc.data());
        for (int64_t v = 0; v < N; ++v) {
            int64_t deg = indptr[v + 1] - indptr[v];
            assert(wc[v] == (deg < k ? deg : k));
            std::multiset<int32_t> row(indices.begin() + indptr[v],
                                       indices.begin() + indptr[v + 1]);
            for (int32_t j = 0; j < wc[v]; ++j)
                assert(row.count(wn[v * k + j]) > 0);
        }
    }

    // --- neighbour_num: zero-degree rows expand to zero
    std::vector<int64_t> nn(N);
    int32_t sizes[2] = {3, 2};
    qt_neighbour_num(indptr.data(), indices.data(), N, sizes, 2, 9, 4,
                     nn.data());
    for (int64_t v = 0; v < N; ++v)
        if (indptr[v + 1] == indptr[v]) assert(nn[v] == 0);

    std::printf("native self-test OK (N=%lld E=%lld)\n",
                (long long)N, (long long)E);
    return 0;
}
