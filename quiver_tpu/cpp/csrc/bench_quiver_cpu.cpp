// Native CPU-sampler benchmark (parity: the reference's C++ micro-
// benchmarks under tests/cpp/).  Measures multi-hop sampled-edges/sec at
// ogbn-products scale against the reference's CPU baseline of 1.84M SEPS
// (docs/Introduction_en.md:38-41).
//
// Build/run: make -C quiver_tpu/cpp bench

#include <chrono>
#include <cstdint>
#include <cstdio>
#include <random>
#include <vector>

extern "C" {
void qt_sample(const int64_t*, const int32_t*, const int32_t*,
               const uint8_t*, int64_t, int32_t, uint64_t, int32_t,
               int32_t*, uint8_t*, int32_t*);
}

int main(int argc, char** argv) {
    const int64_t N = argc > 1 ? atoll(argv[1]) : 2'449'029;
    const int64_t E = argc > 2 ? atoll(argv[2]) : 123'718'280;
    const int sizes[3] = {15, 10, 5};
    const int64_t B = 1024;
    const int iters = 10;

    // lognormal-ish degree profile, like utils/synthetic.py
    std::mt19937_64 rng(0);
    std::lognormal_distribution<double> logn(3.0, 1.0);
    std::vector<double> raw(N);
    double tot = 0;
    for (auto& r : raw) tot += (r = logn(rng));
    std::vector<int64_t> indptr(N + 1, 0);
    for (int64_t i = 0; i < N; ++i) {
        int64_t d = (int64_t)(raw[i] / tot * E);
        indptr[i + 1] = indptr[i] + (d < 1 ? 1 : d);
    }
    const int64_t e_real = indptr[N];
    std::vector<int32_t> indices(e_real);
    for (auto& x : indices) x = (int32_t)(rng() % N);
    std::printf("graph: N=%lld E=%lld\n", (long long)N, (long long)e_real);

    // multi-hop, no-dedup positional frontier (mirrors the TPU pipeline)
    auto t0 = std::chrono::steady_clock::now();
    int64_t edges = 0;
    for (int it = 0; it < iters; ++it) {
        std::vector<int32_t> frontier(B);
        std::vector<uint8_t> fmask(B, 1);
        for (auto& s : frontier) s = (int32_t)(rng() % N);
        for (int l = 0; l < 3; ++l) {
            const int32_t k = sizes[l];
            const int64_t F = (int64_t)frontier.size();
            std::vector<int32_t> nbrs(F * k), counts(F);
            std::vector<uint8_t> mask(F * k);
            qt_sample(indptr.data(), indices.data(), frontier.data(),
                      fmask.data(), F, k, 7 + it * 31 + l, 0,
                      nbrs.data(), mask.data(), counts.data());
            for (int64_t i = 0; i < F; ++i) edges += counts[i];
            frontier.reserve(F + F * k);
            fmask.reserve(F + F * k);
            for (int64_t i = 0; i < F * k; ++i) {
                frontier.push_back(mask[i] ? nbrs[i] : 0);
                fmask.push_back(mask[i]);
            }
        }
    }
    double dt = std::chrono::duration<double>(
        std::chrono::steady_clock::now() - t0
    ).count();
    std::printf(
        "CPU sampling: %d batches of %lld, fanout [15,10,5]: "
        "%.2fM SEPS (%lld edges in %.2fs)\n"
        "reference CPU baseline: 1.84M SEPS\n",
        iters, (long long)B, edges / dt / 1e6, (long long)edges, dt);
    return 0;
}
