"""ctypes loader for the native host sampler (``csrc/quiver_cpu.cpp``).

Builds the shared library on first use with g++ (no pybind11 in the image);
falls back to a pure-numpy implementation when no compiler is available so
the package never hard-fails.  Parity target: ``CPUQuiver``
(``srcs/cpp/src/quiver/quiver.cpp:11-85``).
"""

from __future__ import annotations

import ctypes
import subprocess
import threading
from pathlib import Path
from typing import List, Optional, Sequence, Tuple

import numpy as np

_HERE = Path(__file__).resolve().parent
_SRC = _HERE / "csrc" / "quiver_cpu.cpp"
_LIB = _HERE / "libquiver_cpu.so"
_lock = threading.Lock()
_lib = None
_build_failed = False


def _build() -> Optional[ctypes.CDLL]:
    global _build_failed
    with _lock:
        if _LIB.exists() and _LIB.stat().st_mtime >= _SRC.stat().st_mtime:
            return ctypes.CDLL(str(_LIB))
        if _build_failed:
            return None
        cmd = [
            "g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
            "-o", str(_LIB), str(_SRC),
        ]
        try:
            subprocess.run(cmd, check=True, capture_output=True, timeout=300)
        except Exception:
            _build_failed = True
            return None
        return ctypes.CDLL(str(_LIB))


def _get_lib() -> Optional[ctypes.CDLL]:
    global _lib
    if _lib is None and not _build_failed:
        lib = _build()
        if lib is not None:
            i64p = np.ctypeslib.ndpointer(np.int64, flags="C")
            i32p = np.ctypeslib.ndpointer(np.int32, flags="C")
            u8p = np.ctypeslib.ndpointer(np.uint8, flags="C")
            lib.qt_sample.argtypes = [
                i64p, i32p, i32p, ctypes.c_void_p, ctypes.c_int64,
                ctypes.c_int32, ctypes.c_uint64, ctypes.c_int32,
                i32p, u8p, i32p,
            ]
            lib.qt_sample.restype = None
            f32p = np.ctypeslib.ndpointer(np.float32, flags="C")
            lib.qt_sample_weighted.argtypes = [
                i64p, i32p, f32p, i32p, ctypes.c_void_p, ctypes.c_int64,
                ctypes.c_int32, ctypes.c_uint64, ctypes.c_int32,
                i32p, u8p, i32p,
            ]
            lib.qt_sample_weighted.restype = None
            lib.qt_reindex.argtypes = [
                i32p, ctypes.c_void_p, ctypes.c_int64, i32p, u8p,
                ctypes.c_int32, i32p, u8p, i32p,
            ]
            lib.qt_reindex.restype = ctypes.c_int64
            lib.qt_coo_to_csr.argtypes = [
                i64p, i64p, ctypes.c_int64, ctypes.c_int64, i64p, i32p,
                ctypes.c_void_p,
            ]
            lib.qt_coo_to_csr.restype = None
            lib.qt_neighbour_num.argtypes = [
                i64p, i32p, ctypes.c_int64, i32p, ctypes.c_int32,
                ctypes.c_uint64, ctypes.c_int32, i64p,
            ]
            lib.qt_neighbour_num.restype = None
        _lib = lib
    return _lib


def native_available() -> bool:
    return _get_lib() is not None


def _as_u8_ptr(mask: Optional[np.ndarray]):
    if mask is None:
        return None
    return mask.ctypes.data_as(ctypes.c_void_p)


class CPUSampler:
    """Host-side sampler with the same dense-block contract as the TPU ops."""

    def __init__(self, indptr: np.ndarray, indices: np.ndarray,
                 n_threads: int = 0, seed: int = 0x5EED,
                 edge_weights: Optional[np.ndarray] = None):
        self.indptr = np.ascontiguousarray(indptr, dtype=np.int64)
        self.indices = np.ascontiguousarray(indices, dtype=np.int32)
        self.n_threads = n_threads
        self._seed = seed
        self._ctr = 0
        self.cum_weights = None
        if edge_weights is not None:
            from ..ops.sample import row_cumsum_weights

            self.cum_weights = np.ascontiguousarray(
                row_cumsum_weights(self.indptr, edge_weights),
                dtype=np.float32,
            )

    def _next_seed(self) -> int:
        self._ctr += 1
        return (self._seed * 1_000_003 + self._ctr) & (2**64 - 1)

    def sample_neighbors(self, seeds: np.ndarray, k: int,
                         seed_mask: Optional[np.ndarray] = None,
                         seed: Optional[int] = None):
        """``seed`` overrides the internal counter-derived RNG seed so
        callers holding a jax key can make the host tier reproducible
        (``uva.sample_uva``)."""
        seeds = np.ascontiguousarray(seeds, dtype=np.int32)
        B = len(seeds)
        nbrs = np.empty((B, k), dtype=np.int32)
        mask = np.empty((B, k), dtype=np.uint8)
        counts = np.empty(B, dtype=np.int32)
        sm = (
            None if seed_mask is None
            else np.ascontiguousarray(seed_mask, dtype=np.uint8)
        )
        lib = _get_lib()
        rng_seed = seed if seed is not None else self._next_seed()
        if lib is not None and self.cum_weights is not None:
            lib.qt_sample_weighted(
                self.indptr, self.indices, self.cum_weights, seeds,
                _as_u8_ptr(sm), B, k, rng_seed, self.n_threads,
                nbrs.reshape(-1), mask.reshape(-1), counts)
        elif self.cum_weights is not None:  # numpy weighted fallback
            rng = np.random.default_rng(rng_seed % 2**32)
            cw = self.cum_weights
            for b in range(B):
                if sm is not None and not sm[b]:
                    counts[b], mask[b], nbrs[b] = 0, 0, -1
                    continue
                beg, end = self.indptr[seeds[b]], self.indptr[seeds[b] + 1]
                deg = end - beg
                c = int(min(deg, k))
                counts[b] = c
                if deg <= k:
                    nbrs[b, :c] = self.indices[beg:end]
                else:
                    u = rng.random(k).astype(np.float64) * cw[end - 1]
                    pos = np.searchsorted(cw[beg:end], u, side="right")
                    nbrs[b, :k] = self.indices[beg + np.minimum(pos, deg - 1)]
                nbrs[b, c:] = -1
                mask[b] = np.arange(k) < c
            return nbrs, mask.astype(bool), counts
        elif lib is not None:
            lib.qt_sample(self.indptr, self.indices, seeds, _as_u8_ptr(sm),
                          B, k, rng_seed, self.n_threads,
                          nbrs.reshape(-1), mask.reshape(-1), counts)
        else:  # numpy fallback
            rng = np.random.default_rng(rng_seed % 2**32)
            for b in range(B):
                if sm is not None and not sm[b]:
                    counts[b] = 0
                    mask[b] = 0
                    nbrs[b] = -1
                    continue
                beg, end = self.indptr[seeds[b]], self.indptr[seeds[b] + 1]
                row = self.indices[beg:end]
                c = min(len(row), k)
                pick = row[:c] if len(row) <= k else rng.choice(
                    row, size=k, replace=False)
                counts[b] = c
                nbrs[b, :c] = pick[:c]
                nbrs[b, c:] = -1
                mask[b] = np.arange(k) < c
        return nbrs, mask.astype(bool), counts

    def reindex(self, seeds: np.ndarray, nbrs: np.ndarray, mask: np.ndarray,
                seed_mask: Optional[np.ndarray] = None):
        seeds = np.ascontiguousarray(seeds, dtype=np.int32)
        B, k = nbrs.shape
        nbrs = np.ascontiguousarray(nbrs, dtype=np.int32)
        m8 = np.ascontiguousarray(mask, dtype=np.uint8)
        sm = (
            None if seed_mask is None
            else np.ascontiguousarray(seed_mask, dtype=np.uint8)
        )
        n_id = np.zeros(B + B * k, dtype=np.int32)
        n_id_mask = np.zeros(B + B * k, dtype=np.uint8)
        local = np.zeros((B, k), dtype=np.int32)
        lib = _get_lib()
        if lib is not None:
            num = lib.qt_reindex(seeds, _as_u8_ptr(sm), B,
                                 nbrs.reshape(-1), m8.reshape(-1), k,
                                 n_id, n_id_mask, local.reshape(-1))
        else:
            table = {}
            num = 0
            for b in range(B):
                v = sm is None or bool(sm[b])
                n_id[b] = seeds[b] if v else 0
                n_id_mask[b] = v
                if v:
                    table[int(seeds[b])] = b
                    num += 1
            rest = sorted(
                {int(x) for x, mm in zip(nbrs.reshape(-1), m8.reshape(-1))
                 if mm and int(x) not in table}
            )
            for r, x in enumerate(rest):
                n_id[B + r] = x
                n_id_mask[B + r] = 1
                table[x] = B + r
            num += len(rest)
            flat = local.reshape(-1)
            for i, (x, mm) in enumerate(zip(nbrs.reshape(-1), m8.reshape(-1))):
                flat[i] = table[int(x)] if mm else 0
        return n_id, n_id_mask.astype(bool), int(num), local

    def sample_multihop(self, seeds: np.ndarray, sizes: Sequence[int]):
        """Dense multi-hop pipeline mirroring the TPU ``_sample_pipeline``."""
        frontier = np.asarray(seeds, dtype=np.int32)
        fmask = np.ones(len(frontier), dtype=np.uint8)
        blocks: List[Tuple[np.ndarray, np.ndarray, int]] = []
        num_nodes = len(frontier)
        for k in sizes:
            nbrs, mask, _ = self.sample_neighbors(frontier, k, fmask)
            n_id, n_mask, num_nodes, local = self.reindex(
                frontier, nbrs, mask, fmask
            )
            blocks.append((local, mask, int(fmask.sum())))
            frontier, fmask = n_id, n_mask.astype(np.uint8)
        return frontier, fmask.astype(bool), num_nodes, blocks[::-1]


def coo_to_csr_native(src, dst, node_count=None):
    src = np.ascontiguousarray(src, dtype=np.int64)
    dst = np.ascontiguousarray(dst, dtype=np.int64)
    if node_count is None:
        node_count = int(max(src.max(), dst.max())) + 1 if len(src) else 0
    lib = _get_lib()
    if lib is None:
        from ..utils.topology import coo_to_csr
        return coo_to_csr(src, dst, node_count)
    indptr = np.zeros(node_count + 1, dtype=np.int64)
    indices = np.empty(len(src), dtype=np.int32)
    eid = np.empty(len(src), dtype=np.int64)
    lib.qt_coo_to_csr(src, dst, len(src), node_count, indptr, indices,
                      eid.ctypes.data_as(ctypes.c_void_p))
    return indptr, indices, eid


def neighbour_num_native(indptr, indices, sizes, n_threads=0, seed=7):
    indptr = np.ascontiguousarray(indptr, dtype=np.int64)
    indices = np.ascontiguousarray(indices, dtype=np.int32)
    N = len(indptr) - 1
    out = np.zeros(N, dtype=np.int64)
    lib = _get_lib()
    sz = np.ascontiguousarray(sizes, dtype=np.int32)
    if lib is not None:
        lib.qt_neighbour_num(indptr, indices, N, sz, len(sz), seed,
                             n_threads, out)
        return out
    # numpy fallback: expected counts (deterministic upper-fidelity estimate)
    deg = (indptr[1:] - indptr[:-1]).astype(np.float64)
    sampler = CPUSampler(indptr, indices, seed=seed)
    for v in range(N):
        frontier = [v]
        total = 0
        for k in sizes:
            nxt = []
            for u in frontier:
                row = indices[indptr[u]:indptr[u + 1]]
                c = min(len(row), k)
                if len(row) <= k:
                    nxt.extend(row.tolist())
                else:
                    nxt.extend(
                        np.random.default_rng(v).choice(row, k).tolist())
            total += len(nxt)
            frontier = nxt
        out[v] = total
    return out
