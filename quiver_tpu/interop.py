"""PyTorch / PyG interop — consume quiver_tpu samples from torch code.

The reference IS a PyG add-on: its sampler returns ``(n_id, batch_size,
adjs)`` of torch tensors that drop into a PyG training loop
(``sage_sampler.py:118-147``, README.md:186-212's "3-line swap").  A user
migrating from it may keep a torch-side model while adopting this
framework's samplers/feature store; these converters make that a 3-line
swap in the other direction.

Zero-copy where possible (numpy bridging; both sides share memory on
CPU).  torch is an optional dependency — this module imports it lazily.
"""

from __future__ import annotations

import numpy as np

__all__ = ["to_torch_adjs", "to_torch", "TorchSampleLoader",
           "block_specs", "to_dgl_blocks"]


def to_torch(x):
    """jax/numpy array -> torch tensor (shared memory on CPU)."""
    import torch

    return torch.from_numpy(np.ascontiguousarray(np.asarray(x)))


def to_torch_adjs(batch):
    """:class:`SampledBatch` -> PyG-style ``(n_id, batch_size, adjs)``
    of torch tensors.

    Each adj is ``(edge_index [2, e] long, e_id long, (n_src, n_dst))`` —
    the exact contract of the reference sampler's return, so a PyG model
    loop consumes it unchanged (see ``SampledBatch.to_pyg_adjs`` for the
    padded-size semantics).
    """
    import torch

    n_id, bs, adjs = batch.to_pyg_adjs()
    out = []
    for edge_index, e_id, size in adjs:
        out.append((torch.from_numpy(edge_index.astype(np.int64)),
                    torch.from_numpy(e_id.astype(np.int64)), size))
    return torch.from_numpy(np.asarray(n_id).astype(np.int64)), bs, out


class TorchSampleLoader:
    """Iterate ``(n_id, batch_size, adjs, x, y)`` torch batches from a
    quiver_tpu sampler + feature store — the reference's
    ``for seeds in DataLoader: sample; feature[n_id]; model(...)`` loop
    packaged for a torch training script.
    """

    def __init__(self, train_idx, sampler, feature, labels=None,
                 batch_size: int = 1024, shuffle: bool = True, seed: int = 0):
        self.train_idx = np.array(train_idx, copy=True)
        self.sampler = sampler
        self.feature = feature
        self.labels = None if labels is None else np.asarray(labels)
        self.batch_size = batch_size
        self.shuffle = shuffle
        self._rng = np.random.default_rng(seed)

    def __len__(self):
        return (len(self.train_idx) + self.batch_size - 1) // self.batch_size

    def __iter__(self):
        import torch

        if self.shuffle:
            self._rng.shuffle(self.train_idx)
        B = self.batch_size
        for i in range(len(self)):
            seeds = self.train_idx[i * B: (i + 1) * B]
            batch = self.sampler.sample(seeds)
            n_id, bs, adjs = to_torch_adjs(batch)
            x = to_torch(self.feature[np.asarray(batch.n_id)])
            y = (torch.from_numpy(self.labels[seeds]) if self.labels
                 is not None else None)
            yield n_id, bs, adjs, x, y


# --------------------------------------------------------------- DGL side
# The reference's second-framework integration pairs its Feature store
# with a DGL training loop (reference examples/dgl/ogbn_products_sage_
# quiver.py: DGL NeighborSampler + quiver.Feature[input_nodes] + dglnn
# SAGEConv over MFG "blocks").  Mirrored here in both directions:
#   * block_specs / to_dgl_blocks: OUR sampler's output as DGL message-
#     flow-graph blocks, so a dgl.nn model consumes quiver_tpu samples;
#   * Feature already serves any torch loop via __getitem__ + to_torch —
#     the reference direction — shown in examples/dgl_products_sage.py.
# dgl itself stays an optional dependency (lazy import).

def block_specs(batch):
    """:class:`SampledBatch` -> per-layer MFG specs
    ``(src, dst, eid, n_src, n_dst)`` (numpy, outermost layer first).

    ``src``/``dst`` are frontier-local endpoints of each sampled edge
    (dst = the seed-side node), ``n_src``/``n_dst`` the padded frontier
    sizes — exactly ``dgl.create_block((src, dst), num_src_nodes=n_src,
    num_dst_nodes=n_dst)``'s contract, where the target frontier is a
    prefix of the source frontier (DGL's own block invariant).

    ``eid`` is empty unless the sampler was built with
    ``return_eid=True`` (eid materialization is otherwise DCE'd).
    """
    _, _, adjs = batch.to_pyg_adjs()
    specs = []
    for edge_index, e_id, (n_src, n_dst) in adjs:
        # PyG edge_index rows: [0] = neighbour (source), [1] = target
        specs.append((edge_index[0], edge_index[1], e_id,
                      int(n_src), int(n_dst)))
    return specs


def to_dgl_blocks(batch):
    """:class:`SampledBatch` -> list of DGL MFG blocks (outermost first),
    with sampled edge ids in ``block.edata["_ID"]``.

    Drop-in for the blocks a ``dgl.dataloading.NeighborSampler`` yields,
    so a dgl.nn model (e.g. ``dglnn.SAGEConv`` with the
    ``h_dst = h[:block.num_dst_nodes()]`` idiom) trains on quiver_tpu
    samples unchanged.  Requires dgl (optional dependency).
    """
    import dgl
    import torch

    blocks = []
    for src, dst, eid, n_src, n_dst in block_specs(batch):
        b = dgl.create_block(
            (torch.from_numpy(src.astype(np.int64)),
             torch.from_numpy(dst.astype(np.int64))),
            num_src_nodes=n_src, num_dst_nodes=n_dst)
        if len(eid) == len(src):  # sampler built with return_eid=True
            b.edata["_ID"] = torch.from_numpy(eid.astype(np.int64))
        blocks.append(b)
    return blocks
