"""Seed/batch loader — the training-loop front end.

The reference's examples drive sampling with a torch ``DataLoader`` over
the train-id tensor (``examples/pyg/ogbn_products_sage_quiver.py:138``:
``DataLoader(train_idx, batch_size=1024, shuffle=True)``) and call
sampler/feature per batch.  ``SeedLoader`` packages that loop TPU-style:
epoch shuffling, fixed batch shapes (last partial batch padded + masked,
never a recompile), and host-side prefetch of sample+gather behind the
accelerator (``parallel.Prefetcher``).
"""

from __future__ import annotations

from typing import Iterator

import numpy as np

from .parallel.prefetch import Prefetcher

__all__ = ["SeedLoader"]


class SeedLoader:
    """Iterate (SampledBatch, features, labels, label_mask) epochs.

    Args:
      train_idx: ``[T]`` seed node ids.
      sampler: :class:`GraphSageSampler` (or hetero variant).
      feature: :class:`Feature` (or any ``__getitem__`` over node ids).
      labels: optional ``[N]`` label array.
      batch_size: fixed batch size; the last partial batch is padded with
        repeats and masked via ``label_mask`` (static shapes, no recompile).
      shuffle: epoch shuffling.
      prefetch: host-side pipeline depth (0 disables).
    """

    def __init__(self, train_idx, sampler, feature, labels=None,
                 batch_size: int = 1024, shuffle: bool = True,
                 drop_last: bool = False, prefetch: int = 2, seed: int = 0):
        # own copy: epoch shuffling is in-place and must not permute the
        # caller's array (label alignment, cross-loader reproducibility)
        self.train_idx = np.array(train_idx, copy=True)
        self.sampler = sampler
        self.feature = feature
        self.labels = None if labels is None else np.asarray(labels)
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.prefetch = prefetch
        self._rng = np.random.default_rng(seed)
        self._epoch = 0
        self._lookahead = {}

    def __len__(self):
        n = len(self.train_idx)
        return n // self.batch_size if self.drop_last else (
            (n + self.batch_size - 1) // self.batch_size
        )

    def _sample(self, i: int):
        import jax

        B = self.batch_size
        seeds = self.train_idx[i * B: (i + 1) * B]
        valid = len(seeds)
        if valid < B:  # pad to the fixed shape, mask the tail
            seeds = np.concatenate(
                [seeds, np.repeat(seeds[:1] if valid else [0], B - valid)]
            )
        from .utils.rng import make_key

        key = make_key((self._epoch * 1_000_003 + i) & 0x7FFFFFFF)
        return seeds, valid, self.sampler.sample(seeds, key=key)

    def _make(self, i: int):
        import jax.numpy as jnp

        B = self.batch_size
        e = self._epoch  # keyed by epoch: a straggler worker from an
        # abandoned epoch can't feed its stale batch to the next one
        from .telemetry import flightrec

        if flightrec.tracing():
            # runs on the Prefetcher worker when prefetch > 0 (the
            # Prefetcher carries the consumer's context across), so the
            # event's thread field attributes loader-side work correctly
            flightrec.event("loader.batch", {"index": int(i)})
        got = self._lookahead.pop((e, i), None)
        seeds, valid, batch = got if got is not None else self._sample(i)
        if i + 1 < len(self):
            # dispatch the next batch's sample now and start its cold-tier
            # feature prefetch — the host gather for batch i+1 runs while
            # batch i is on the device (Feature.prefetch double-buffering).
            # With the cold-row overlay enabled this also WARMS it: the
            # prefetch worker stages through Feature._stage_overlay, so
            # batch i+1's recurring cold rows are admitted/resident
            # before __getitem__ consumes the staged batch.
            # n_id stays a device array here: Feature.prefetch materializes
            # it on ITS worker thread, so this thread never blocks on the
            # i+1 sample.
            nxt = self._sample(i + 1)
            self._lookahead[(e, i + 1)] = nxt
            if hasattr(self.feature, "prefetch"):
                self.feature.prefetch(nxt[2].n_id)
        x = self.feature[np.asarray(batch.n_id)]
        mask = jnp.arange(B) < valid
        if self.labels is not None:
            labels = jnp.asarray(self.labels[seeds])
        else:
            labels = jnp.zeros((B,), jnp.int32)
        return batch, x, labels, mask

    def __iter__(self) -> Iterator:
        if self.shuffle:
            self._rng.shuffle(self.train_idx)
        self._epoch += 1
        self._lookahead = {}
        n = len(self)
        if self.prefetch > 0:
            return iter(Prefetcher(range(n), self._make,
                                   depth=self.prefetch))
        return (self._make(i) for i in range(n))
