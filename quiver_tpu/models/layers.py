"""Dense-block GNN layers (Flax).

The reference delegates models to PyG (``SAGEConv``/``GATConv`` consuming
ragged ``edge_index``); examples at
``/root/reference/examples/pyg/ogbn_products_sage_quiver.py:31-70``.  We
keep the same math but consume quiver_tpu's dense ``[T, k]`` neighbor
blocks: aggregation is a gather + masked mean / masked softmax — batched,
static-shaped, fused by XLA into MXU-friendly matmuls, with no
segment-scatter in sight.
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..sampler import LayerBlock

__all__ = ["SAGEConv", "GATConv"]


class SAGEConv(nn.Module):
    """GraphSAGE mean aggregator: ``W_self x + W_nbr mean(x_N(v))``.

    Math parity with PyG's SAGEConv as used in the reference examples.
    ``dtype=jnp.bfloat16`` runs the matmuls on the MXU's native format
    (params stay float32; activations/compute cast — the standard TPU
    mixed-precision recipe).

    With ``edge_feat [T, k, De]`` (rows of an edge-feature table gathered
    via ``LayerBlock.eid``; the caller masks nothing — invalid slots are
    excluded here), aggregation becomes
    ``W_self x + W_nbr concat(mean x_N(v), mean e)``: the masked mean of
    a concat equals the concat of masked means, so the edge half is
    reduced separately and never materializes a ``[T, k, D+De]`` tensor.
    """

    features: int
    use_bias: bool = True
    dtype: object = None

    @nn.compact
    def __call__(self, x: jax.Array, block: LayerBlock,
                 edge_feat: Optional[jax.Array] = None) -> jax.Array:
        t = block.nbr_local.shape[0]
        x_src = jnp.take(x, block.nbr_local, axis=0)        # [T, k, D]
        m = block.mask[..., None].astype(x.dtype)
        cnt = jnp.maximum(m.sum(axis=1), 1.0)               # [T, 1]
        mean_nbr = (x_src * m).sum(axis=1) / cnt            # [T, D]
        if edge_feat is not None:
            mean_e = (edge_feat.astype(x.dtype) * m).sum(axis=1) / cnt
            mean_nbr = jnp.concatenate([mean_nbr, mean_e], axis=-1)
        x_tgt = x[:t]
        out = nn.Dense(self.features, use_bias=self.use_bias,
                       dtype=self.dtype, name="lin_self")(x_tgt)
        out = out + nn.Dense(self.features, use_bias=False,
                             dtype=self.dtype, name="lin_nbr")(mean_nbr)
        return out


class GATConv(nn.Module):
    """Multi-head graph attention over dense neighbor blocks.

    Masked softmax over the k sampled neighbors (+ self loop), per head;
    math parity with PyG GATConv under neighbor sampling.
    """

    features: int
    heads: int = 1
    concat: bool = True
    negative_slope: float = 0.2
    dtype: object = None

    @nn.compact
    def __call__(self, x: jax.Array, block: LayerBlock) -> jax.Array:
        h, f = self.heads, self.features
        t = block.nbr_local.shape[0]
        w = nn.Dense(h * f, use_bias=False, dtype=self.dtype,
                     name="lin")(x)
        w = w.reshape(x.shape[0], h, f)
        w_src = jnp.take(w, block.nbr_local, axis=0)         # [T, k, H, F]
        w_tgt = w[:t]                                        # [T, H, F]
        a_src = self.param("att_src", nn.initializers.glorot_uniform(),
                           (h, f))
        a_tgt = self.param("att_tgt", nn.initializers.glorot_uniform(),
                           (h, f))
        e_src = (w_src * a_src).sum(-1)                      # [T, k, H]
        e_tgt = (w_tgt * a_tgt).sum(-1)                      # [T, H]
        # self-loop joins the neighbor set, as in GATConv(add_self_loops);
        # its source-side term uses a_src on the node's own features
        e_self = (w_tgt * a_src).sum(-1) + e_tgt             # [T, H]
        e = nn.leaky_relu(
            jnp.concatenate([e_src + e_tgt[:, None], e_self[:, None]],
                            axis=1),
            negative_slope=self.negative_slope,
        )                                                    # [T, k+1, H]
        mask = jnp.concatenate(
            [block.mask, jnp.ones((t, 1), bool)], axis=1
        )[..., None]
        e = jnp.where(mask, e, -jnp.inf)
        alpha = jax.nn.softmax(e, axis=1)
        alpha = jnp.where(mask, alpha, 0.0)
        vals = jnp.concatenate([w_src, w_tgt[:, None]], axis=1)
        out = (alpha[..., None] * vals).sum(axis=1)          # [T, H, F]
        if self.concat:
            return out.reshape(t, h * f)
        return out.mean(axis=1)
