"""GraphSAGE model (Flax) over sampled dense blocks.

Parity target: the ``SAGE`` module of
``/root/reference/examples/pyg/ogbn_products_sage_quiver.py:31-70`` (3-layer
SAGEConv with ReLU + dropout between layers) and its quality bar (ogbn-
products test acc ≈ 0.787 per that file's header).
"""

from __future__ import annotations

from typing import Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from .layers import SAGEConv
from ..sampler import LayerBlock

__all__ = ["GraphSAGE"]


class GraphSAGE(nn.Module):
    hidden: int
    out_dim: int
    num_layers: int = 3
    dropout: float = 0.5
    dtype: object = None  # e.g. jnp.bfloat16 for MXU-native matmuls

    @nn.compact
    def __call__(self, x: jax.Array, blocks: Tuple[LayerBlock, ...],
                 train: bool = False,
                 edge_feat_table: jax.Array = None) -> jax.Array:
        """``edge_feat_table [E, De]`` (optional) turns every layer into
        an edge-featured aggregation: rows are gathered by the global
        edge positions in ``LayerBlock.eid`` (sample with
        ``return_eid=True``; -1 pad slots are clamped and masked out in
        the conv).  The reference forwards ``Adj.e_id`` for user-side
        lookup (``sage_sampler.py:143``); here the lookup runs under the
        model's jit."""
        assert len(blocks) == self.num_layers, (
            f"{len(blocks)} blocks for {self.num_layers} layers"
        )
        for i, blk in enumerate(blocks):
            efeat = None
            if edge_feat_table is not None:
                assert blk.eid is not None, (
                    "edge_feat_table needs eid blocks — sample with "
                    "return_eid=True"
                )
                efeat = jnp.take(edge_feat_table,
                                 jnp.maximum(blk.eid, 0), axis=0)
            dim = self.out_dim if i == self.num_layers - 1 else self.hidden
            x = SAGEConv(dim, dtype=self.dtype,
                         name=f"conv{i}")(x, blk, efeat)
            if i != self.num_layers - 1:
                x = nn.relu(x)
                x = nn.Dropout(self.dropout, deterministic=not train)(x)
        return x


def full_graph_inference(params, x, indptr, indices, num_layers: int,
                         edge_chunk: int = 4_000_000):
    """Exact layer-wise full-graph SAGE inference (legacy entry point).

    Delegates to :func:`quiver_tpu.models.inference.full_graph_inference`,
    which also handles GCN/GAT layouts; kept so round-1 call sites
    (``full_graph_inference(params, x, ip, ix, L)``) keep working.
    """
    from .inference import full_graph_inference as _gi

    return _gi(params, x, indptr, indices, num_layers,
               edge_chunk=edge_chunk)
