"""GraphSAGE model (Flax) over sampled dense blocks.

Parity target: the ``SAGE`` module of
``/root/reference/examples/pyg/ogbn_products_sage_quiver.py:31-70`` (3-layer
SAGEConv with ReLU + dropout between layers) and its quality bar (ogbn-
products test acc ≈ 0.787 per that file's header).
"""

from __future__ import annotations

from typing import Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from .layers import SAGEConv
from ..sampler import LayerBlock

__all__ = ["GraphSAGE"]


class GraphSAGE(nn.Module):
    hidden: int
    out_dim: int
    num_layers: int = 3
    dropout: float = 0.5
    dtype: object = None  # e.g. jnp.bfloat16 for MXU-native matmuls

    @nn.compact
    def __call__(self, x: jax.Array, blocks: Tuple[LayerBlock, ...],
                 train: bool = False) -> jax.Array:
        assert len(blocks) == self.num_layers, (
            f"{len(blocks)} blocks for {self.num_layers} layers"
        )
        for i, blk in enumerate(blocks):
            dim = self.out_dim if i == self.num_layers - 1 else self.hidden
            x = SAGEConv(dim, dtype=self.dtype, name=f"conv{i}")(x, blk)
            if i != self.num_layers - 1:
                x = nn.relu(x)
                x = nn.Dropout(self.dropout, deterministic=not train)(x)
        return x


def full_graph_inference(params, x, indptr, indices, num_layers: int,
                         edge_chunk: int = 4_000_000):
    """Exact layer-wise full-graph SAGE inference (legacy entry point).

    Delegates to :func:`quiver_tpu.models.inference.full_graph_inference`,
    which also handles GCN/GAT layouts; kept so round-1 call sites
    (``full_graph_inference(params, x, ip, ix, L)``) keep working.
    """
    from .inference import full_graph_inference as _gi

    return _gi(params, x, indptr, indices, num_layers,
               edge_chunk=edge_chunk)
