"""GraphSAGE model (Flax) over sampled dense blocks.

Parity target: the ``SAGE`` module of
``/root/reference/examples/pyg/ogbn_products_sage_quiver.py:31-70`` (3-layer
SAGEConv with ReLU + dropout between layers) and its quality bar (ogbn-
products test acc ≈ 0.787 per that file's header).
"""

from __future__ import annotations

from typing import Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from .layers import SAGEConv
from ..sampler import LayerBlock

__all__ = ["GraphSAGE"]


class GraphSAGE(nn.Module):
    hidden: int
    out_dim: int
    num_layers: int = 3
    dropout: float = 0.5

    @nn.compact
    def __call__(self, x: jax.Array, blocks: Tuple[LayerBlock, ...],
                 train: bool = False) -> jax.Array:
        assert len(blocks) == self.num_layers, (
            f"{len(blocks)} blocks for {self.num_layers} layers"
        )
        for i, blk in enumerate(blocks):
            dim = self.out_dim if i == self.num_layers - 1 else self.hidden
            x = SAGEConv(dim, name=f"conv{i}")(x, blk)
            if i != self.num_layers - 1:
                x = nn.relu(x)
                x = nn.Dropout(self.dropout, deterministic=not train)(x)
        return x


def full_graph_inference(params, x, indptr, indices, num_layers: int,
                         edge_chunk: int = 4_000_000):
    """Exact layer-wise full-graph inference with trained GraphSAGE params.

    The reference examples evaluate accuracy with PyG's layer-wise
    ``inference()`` over ALL neighbors (no sampling), e.g.
    ``examples/pyg/ogbn_products_sage_quiver.py``'s test pass.  Here the
    exact mean aggregation is a chunked ``segment_sum`` over the CSR edge
    array — one pass per layer, bandwidth-bound, no sampling noise.

    Args:
      params: the flax params of :class:`GraphSAGE` (``conv{i}`` keys).
      x: ``[N, D]`` full feature matrix (device).
      indptr/indices: host or device CSR (edge-chunk streamed).
    Returns ``[N, out_dim]`` logits.
    """
    import numpy as np

    p = params["params"] if "params" in params else params
    n = x.shape[0]
    indptr_np = np.asarray(indptr[: n + 1])
    indices_dev = jnp.asarray(np.asarray(indices)[: int(indptr_np[-1])])
    deg = jnp.asarray(
        (indptr_np[1:] - indptr_np[:-1]).astype(np.float32)
    )
    # per-edge target row (host once; streamed in chunks below)
    row_of_edge = np.repeat(
        np.arange(n, dtype=np.int64), indptr_np[1:] - indptr_np[:-1]
    )

    @jax.jit
    def agg_chunk(acc, h, rows, cols):
        return acc.at[rows].add(jnp.take(h, cols, axis=0))

    for i in range(num_layers):
        conv = p[f"conv{i}"]
        w_self = jnp.asarray(conv["lin_self"]["kernel"])
        b_self = jnp.asarray(conv["lin_self"]["bias"])
        w_nbr = jnp.asarray(conv["lin_nbr"]["kernel"])
        acc = jnp.zeros((n, x.shape[1]), x.dtype)
        e_total = len(row_of_edge)
        for lo in range(0, e_total, edge_chunk):
            hi = min(lo + edge_chunk, e_total)
            rows = jnp.asarray(row_of_edge[lo:hi])
            cols = indices_dev[lo:hi]
            acc = agg_chunk(acc, x, rows, cols)
        mean_nbr = acc / jnp.maximum(deg, 1.0)[:, None]
        x = x @ w_self + b_self + mean_nbr @ w_nbr
        if i != num_layers - 1:
            x = jax.nn.relu(x)
    return x
