"""Relational GAT (R-GAT) over heterogeneous sampled batches.

Parity target: the R-GAT used by the reference's mag240m benchmark
(``/root/reference/benchmarks/ogbn-mag240m/`` trains a hetero R-GAT through
PyG on top of quiver's feature store).  Dense-block formulation: each
relation contributes a masked-attention aggregation from its SRC type's
frontier into its DST targets; relations are summed, plus a per-type self
transform.
"""

from __future__ import annotations

from typing import Dict

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..hetero import HeteroLayerBlock, HeteroSampledBatch

__all__ = ["RGAT"]


class _RelAttention(nn.Module):
    """Single-relation multi-head attention (GAT-style) over dense blocks."""

    features: int
    heads: int

    @nn.compact
    def __call__(self, x_src, x_dst, block: HeteroLayerBlock):
        h, f = self.heads, self.features
        t = block.nbr_local.shape[0]
        w_src = nn.Dense(h * f, use_bias=False, name="w_src")(x_src)
        w_src = w_src.reshape(-1, h, f)
        w_dst = nn.Dense(h * f, use_bias=False, name="w_dst")(x_dst[:t])
        w_dst = w_dst.reshape(t, h, f)
        nbr = jnp.take(w_src, block.nbr_local, axis=0)      # [T, k, H, F]
        a_s = self.param("att_src", nn.initializers.glorot_uniform(), (h, f))
        a_d = self.param("att_dst", nn.initializers.glorot_uniform(), (h, f))
        e = nn.leaky_relu(
            (nbr * a_s).sum(-1) + ((w_dst * a_d).sum(-1))[:, None],
            negative_slope=0.2,
        )                                                   # [T, k, H]
        m = block.mask[..., None]
        e = jnp.where(m, e, -jnp.inf)
        alpha = jax.nn.softmax(e, axis=1)
        alpha = jnp.where(m, alpha, 0.0)
        out = (alpha[..., None] * nbr).sum(axis=1)          # [T, H, F]
        return out.reshape(t, h * f)


class RGAT(nn.Module):
    """Hetero R-GAT.

    Args:
      hidden: per-layer width (= heads * head_dim).
      out_dim: classifier width (applied to the seed type).
      num_layers: must equal the sampler's hop count.
      node_types / in_dims: feature width per node type (for the input
        projection).
    """

    hidden: int
    out_dim: int
    num_layers: int
    in_dims: Dict[str, int]
    heads: int = 4
    dropout: float = 0.5

    @nn.compact
    def __call__(self, xs: Dict[str, jax.Array],
                 batch: HeteroSampledBatch, train: bool = False):
        assert len(batch.layers) == self.num_layers
        # input projection per node type -> common width
        h = {
            t: nn.Dense(self.hidden, name=f"proj_{t}")(x)
            for t, x in xs.items()
        }
        head_dim = self.hidden // self.heads
        for l, hop_blocks in enumerate(batch.layers):
            new_h = {}
            # self transform for every type that has targets this layer
            tgt_len = {}
            for blk in hop_blocks:
                _, _, d_t = blk.relation
                tgt_len[d_t] = max(
                    tgt_len.get(d_t, 0), blk.nbr_local.shape[0]
                )
            for t, ln in tgt_len.items():
                new_h[t] = nn.Dense(self.hidden,
                                    name=f"self_{l}_{t}")(h[t][:ln])
            for blk in hop_blocks:
                s_t, name, d_t = blk.relation
                agg = _RelAttention(
                    head_dim, self.heads,
                    name=f"rel_{l}_{s_t}__{name}__{d_t}",
                )(h[s_t], h[d_t], blk)
                ln = tgt_len[d_t]
                pad = ln - agg.shape[0]
                if pad:
                    agg = jnp.pad(agg, ((0, pad), (0, 0)))
                new_h[d_t] = new_h[d_t] + agg
            # types with no incoming relation this hop keep their prefix
            for t in h:
                if t not in new_h:
                    new_h[t] = h[t]
                else:
                    new_h[t] = nn.relu(new_h[t])
                    new_h[t] = nn.Dropout(
                        self.dropout, deterministic=not train
                    )(new_h[t])
            h = new_h
        return nn.Dense(self.out_dim, name="classifier")(
            h[batch.seed_type][: batch.batch_size]
        )
