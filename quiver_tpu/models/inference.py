"""Exact full-graph layer-wise inference for all model families.

The reference evaluates accuracy with PyG's layer-wise ``inference()``
over ALL neighbors (no sampling) — e.g. the test pass of
``examples/pyg/ogbn_products_sage_quiver.py``.  Round 1 only had a
SAGE-specific version (VERDICT weak #8); this module does the exact
per-layer math for :class:`GraphSAGE`, :class:`GCN`, and :class:`GAT`
param layouts, streaming the CSR edge array in chunks so papers100M-scale
graphs fit (aggregation is a chunked ``.at[].add`` segment-sum; GAT does
the numerically-stable two-pass streaming softmax with a segment-max
prepass).

Entry point: :func:`full_graph_inference(model, params, x, indptr,
indices)` — dispatches on the flax module type.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["full_graph_inference"]


def _make_edge_stream(indptr_np, n, edge_chunk):
    """Build the CSR row expansion ONCE (E can be 10^8: ~1 GB host array)
    and return a re-iterable stream of (lo, hi, rows-on-device) chunks —
    every layer of every model walks the same chunks."""
    row_of_edge = np.repeat(
        np.arange(n, dtype=np.int64), indptr_np[1:] - indptr_np[:-1]
    )
    e_total = len(row_of_edge)

    def stream():
        for lo in range(0, e_total, edge_chunk):
            hi = min(lo + edge_chunk, e_total)
            yield lo, hi, jnp.asarray(row_of_edge[lo:hi])

    return stream


@jax.jit
def _seg_add(acc, vals, rows):
    return acc.at[rows].add(vals)


@jax.jit
def _seg_max(acc, vals, rows):
    return acc.at[rows].max(vals)


def _mean_aggregate(h, edge_stream, indices_dev, deg):
    acc = jnp.zeros_like(h)
    for lo, hi, rows in edge_stream():
        acc = _seg_add(acc, jnp.take(h, indices_dev[lo:hi], axis=0), rows)
    return acc / jnp.maximum(deg, 1.0)[:, None]


def _sage_layers(p):
    i = 0
    while f"conv{i}" in p:
        i += 1
    return i


def full_graph_inference(model, params=None, x=None, indptr=None,
                         indices=None, num_layers: int = None,
                         edge_chunk: int = 4_000_000):
    """Exact (no-sampling) logits ``[N, out_dim]`` for a trained model.

    Args:
      model: the flax module the params came from — ``GraphSAGE``, ``GCN``
        or ``GAT`` (used to pick the layer math; sampled-block modules and
        this exact path share parameters).  Legacy SAGE form accepted:
        ``full_graph_inference(params, x, indptr, indices, num_layers)``.
      params: flax params (``model.init`` output).
      x: ``[N, D]`` full feature matrix.
      indptr/indices: CSR (host arrays fine; edges streamed in chunks).
    """
    from .sage import GraphSAGE
    from .gcn import GCN
    from .gat import GAT

    if not hasattr(model, "apply"):  # legacy: (params, x, ip, ix, L)
        legacy = (model, params, x, indptr, indices)
        params, x, indptr, indices = legacy[:4]
        if num_layers is None:
            num_layers = legacy[4]
        assert num_layers is not None, "legacy form needs num_layers"
        model = GraphSAGE(hidden=0, out_dim=0, num_layers=num_layers)
        # (hidden/out_dim unused — layer shapes come from the params)

    p = params["params"] if "params" in params else params
    n = x.shape[0]
    indptr_np = np.asarray(indptr[: n + 1])
    indices_dev = jnp.asarray(np.asarray(indices)[: int(indptr_np[-1])])
    deg = jnp.asarray((indptr_np[1:] - indptr_np[:-1]).astype(np.float32))
    x = jnp.asarray(x)
    edge_stream = _make_edge_stream(indptr_np, n, edge_chunk)

    if isinstance(model, GraphSAGE):
        for i in range(model.num_layers):
            conv = p[f"conv{i}"]
            mean_nbr = _mean_aggregate(x, edge_stream, indices_dev, deg)
            x = (x @ jnp.asarray(conv["lin_self"]["kernel"])
                 + jnp.asarray(conv["lin_self"]["bias"])
                 + mean_nbr @ jnp.asarray(conv["lin_nbr"]["kernel"]))
            if i != model.num_layers - 1:
                x = jax.nn.relu(x)
        return x

    if isinstance(model, GCN):
        # TRUE symmetric normalization with self-loops — the semantics the
        # sampled GCNConv approximates with per-block degrees:
        # out_v = sum_{u in N(v) + {v}} w_u / sqrt((deg_u+1)(deg_v+1))
        norm = 1.0 / jnp.sqrt(deg + 1.0)
        for i in range(model.num_layers):
            lin = p[f"gcn{i}"]["lin"]
            w = x @ jnp.asarray(lin["kernel"]) + jnp.asarray(lin["bias"])
            acc = jnp.zeros_like(w)
            wn = w * norm[:, None]
            for lo, hi, rows in edge_stream():
                acc = _seg_add(
                    acc, jnp.take(wn, indices_dev[lo:hi], axis=0), rows
                )
            x = (acc + wn) * norm[:, None]
            if i != model.num_layers - 1:
                x = jax.nn.relu(x)
        return x

    if isinstance(model, GAT):
        for i in range(model.num_layers):
            last = i == model.num_layers - 1
            layer = p[f"gat{i}"]
            heads = 1 if last else model.heads
            wk = jnp.asarray(layer["lin"]["kernel"])
            f = wk.shape[1] // heads
            w = (x @ wk).reshape(n, heads, f)
            a_src = jnp.asarray(layer["att_src"])      # [H, F]
            a_tgt = jnp.asarray(layer["att_tgt"])
            e_src_all = (w * a_src).sum(-1)            # [N, H] src-side term
            e_tgt_all = (w * a_tgt).sum(-1)            # [N, H] tgt-side term
            slope = 0.2
            e_self = jax.nn.leaky_relu(e_src_all + e_tgt_all, slope)
            # pass 1: streaming segment-max of edge scores (incl. self)
            m = e_self
            for lo, hi, rows in edge_stream():
                e = jax.nn.leaky_relu(
                    jnp.take(e_src_all, indices_dev[lo:hi], axis=0)
                    + jnp.take(e_tgt_all, rows, axis=0), slope)
                m = _seg_max(m, e, rows)
            # pass 2: accumulate exp(e - m_v) * w_u and the denominator
            num = jnp.exp(e_self - m)[..., None] * w   # self-loop term
            den = jnp.exp(e_self - m)
            for lo, hi, rows in edge_stream():
                cols = indices_dev[lo:hi]
                e = jax.nn.leaky_relu(
                    jnp.take(e_src_all, cols, axis=0)
                    + jnp.take(e_tgt_all, rows, axis=0), slope)
                a = jnp.exp(e - jnp.take(m, rows, axis=0))
                num = _seg_add(num, a[..., None] * jnp.take(w, cols, axis=0),
                               rows)
                den = _seg_add(den, a, rows)
            out = num / den[..., None]                 # [N, H, F]
            x = out.reshape(n, heads * f) if not last else out.mean(axis=1)
            if not last:
                x = jax.nn.elu(x)
        return x

    raise TypeError(f"unsupported model type {type(model).__name__}")
