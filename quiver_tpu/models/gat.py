"""GAT model (Flax) over sampled dense blocks.

Parity target: the GAT example of the reference
(``/root/reference/examples/pyg/`` GAT variants) — multi-head attention
layers with ELU, final layer single-head mean.
"""

from __future__ import annotations

from typing import Tuple

import flax.linen as nn
import jax

from .layers import GATConv
from ..sampler import LayerBlock

__all__ = ["GAT"]


class GAT(nn.Module):
    hidden: int
    out_dim: int
    num_layers: int = 2
    heads: int = 4
    dropout: float = 0.5
    dtype: object = None

    @nn.compact
    def __call__(self, x: jax.Array, blocks: Tuple[LayerBlock, ...],
                 train: bool = False) -> jax.Array:
        assert len(blocks) == self.num_layers
        for i, blk in enumerate(blocks):
            last = i == self.num_layers - 1
            x = GATConv(
                self.out_dim if last else self.hidden,
                heads=1 if last else self.heads,
                concat=not last,
                dtype=self.dtype,
                name=f"gat{i}",
            )(x, blk)
            if not last:
                x = nn.elu(x)
                x = nn.Dropout(self.dropout, deterministic=not train)(x)
        return x
