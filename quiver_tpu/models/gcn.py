"""GCN over sampled dense blocks.

Parity note: the reference's examples are SAGE/GAT, but PyG users swapping
in quiver routinely run GCN through the same sampler; the dense-block
formulation needs only symmetric-ish degree normalization.  Under neighbor
sampling the exact symmetric normalization is approximated per block (as
PyG's GCNConv does with sampled subgraphs): ``1/sqrt((k_v+1)(k_u+1))``
using the sampled counts.
"""

from __future__ import annotations

from typing import Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..sampler import LayerBlock

__all__ = ["GCNConv", "GCN"]


class GCNConv(nn.Module):
    features: int
    dtype: object = None

    @nn.compact
    def __call__(self, x: jax.Array, block: LayerBlock) -> jax.Array:
        t = block.nbr_local.shape[0]
        w = nn.Dense(self.features, use_bias=True, dtype=self.dtype,
                     name="lin")(x)
        w_src = jnp.take(w, block.nbr_local, axis=0)        # [T, k, F]
        m = block.mask.astype(x.dtype)[..., None]
        deg = block.mask.sum(axis=1).astype(x.dtype)        # [T]
        # self-loop-augmented normalization with sampled degrees
        norm = 1.0 / jnp.sqrt(deg + 1.0)
        agg = (w_src * m).sum(axis=1) * norm[:, None]
        out = (agg + w[:t]) * norm[:, None]
        return out


class GCN(nn.Module):
    hidden: int
    out_dim: int
    num_layers: int = 2
    dropout: float = 0.5
    dtype: object = None

    @nn.compact
    def __call__(self, x: jax.Array, blocks: Tuple[LayerBlock, ...],
                 train: bool = False) -> jax.Array:
        assert len(blocks) == self.num_layers
        for i, blk in enumerate(blocks):
            last = i == self.num_layers - 1
            x = GCNConv(self.out_dim if last else self.hidden,
                        dtype=self.dtype, name=f"gcn{i}")(x, blk)
            if not last:
                x = nn.relu(x)
                x = nn.Dropout(self.dropout, deterministic=not train)(x)
        return x
