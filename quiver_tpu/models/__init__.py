from .layers import SAGEConv, GATConv
from .sage import GraphSAGE, full_graph_inference
from .gat import GAT
from .rgat import RGAT
from .gcn import GCN, GCNConv
