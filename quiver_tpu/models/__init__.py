from .layers import SAGEConv, GATConv
from .sage import GraphSAGE
from .gat import GAT
from .rgat import RGAT
from .gcn import GCN, GCNConv
