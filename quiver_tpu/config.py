"""Unified runtime configuration.

The reference has no config system — build-time env vars, constructor
kwargs, and hardcoded constants (SURVEY §5).  Here one small object holds
the library-wide defaults, overridable by env (``QUIVER_TPU_*``) or
programmatically (``quiver_tpu.config.update(...)``); constructors still
take explicit kwargs which always win.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Optional, Tuple

__all__ = ["Config", "get_config", "update"]


def _env(name: str, default, cast=str):
    v = os.environ.get(f"QUIVER_TPU_{name}")
    if v is None:
        return default
    if cast is bool:
        return v not in ("0", "", "false", "False")
    return cast(v)


@dataclass
class Config:
    # sampler
    gather_mode: str = field(
        default_factory=lambda: _env("GATHER_MODE", "auto")
    )
    sample_rng: str = field(
        default_factory=lambda: _env("SAMPLE_RNG", "auto")
    )
    dedup: str = field(default_factory=lambda: _env("DEDUP", "auto"))
    # feature store
    cache_policy: str = field(
        default_factory=lambda: _env("CACHE_POLICY", "device_replicate")
    )
    # cold-row overlay cache (docs/FEATURE_CACHE.md): "auto" = off until
    # enable_cold_cache() / the serving auto-enable; "off"/"0" = never;
    # an explicit size ("64M", or rows under cache_unit="rows") enables
    # the overlay at feature build time
    cold_cache_size: str = field(
        default_factory=lambda: _env("COLD_CACHE_SIZE", "auto")
    )
    cold_cache_policy: str = field(
        default_factory=lambda: _env("COLD_CACHE_POLICY", "clock")
    )
    cold_cache_admit: int = field(
        default_factory=lambda: _env("COLD_CACHE_ADMIT", 2, int)
    )
    # paged feature store (docs/FEATURE_CACHE.md): "off" (default) keeps
    # the staged three-tier merge byte-identical to PR 9; "on" packs
    # feature rows into fixed-size HBM pages and serves every gather
    # through the ragged Pallas page-gather kernel.  page_rows=0 sizes
    # pages automatically (smallest row count whose page is a multiple
    # of the 512B HBM transaction, >= 4KiB); pool_pages=0 sizes the
    # OVERLAY page pool off the host-page count (docs/FEATURE_CACHE.md).
    feature_paged: str = field(
        default_factory=lambda: _env("FEATURE_PAGED", "off")
    )
    feature_page_rows: int = field(
        default_factory=lambda: _env("FEATURE_PAGE_ROWS", 0, int)
    )
    feature_page_pool: int = field(
        default_factory=lambda: _env("FEATURE_PAGE_POOL", 0, int)
    )
    # serving
    serving_buckets: Tuple[int, ...] = (
        8, 16, 32, 64, 128, 256, 512, 1024, 2048
    )
    max_coalesce: int = field(
        default_factory=lambda: _env("MAX_COALESCE", 8, int)
    )
    # resilience (docs/RESILIENCE.md): per-request deadline budget in ms
    # (0 disables deadlines entirely — checks reduce to one `is None`),
    # bounded-lane capacity + shed watermarks (fractions of capacity,
    # hysteresis: shed above high until drained below low), and the
    # per-lane circuit breaker (consecutive failures to open, seconds
    # until a half-open probe, concurrent probes admitted)
    serving_deadline_ms: float = field(
        default_factory=lambda: _env("SERVING_DEADLINE_MS", 0.0, float)
    )
    serving_queue_depth: int = field(
        default_factory=lambda: _env("SERVING_QUEUE_DEPTH", 1024, int)
    )
    serving_queue_high_watermark: float = field(
        default_factory=lambda: _env(
            "SERVING_QUEUE_HIGH_WATERMARK", 0.9, float)
    )
    serving_queue_low_watermark: float = field(
        default_factory=lambda: _env(
            "SERVING_QUEUE_LOW_WATERMARK", 0.5, float)
    )
    serving_breaker_failures: int = field(
        default_factory=lambda: _env("SERVING_BREAKER_FAILURES", 5, int)
    )
    serving_breaker_reset_s: float = field(
        default_factory=lambda: _env("SERVING_BREAKER_RESET_S", 30.0, float)
    )
    serving_breaker_probes: int = field(
        default_factory=lambda: _env("SERVING_BREAKER_PROBES", 1, int)
    )
    # multi-tenant QoS (docs/RESILIENCE.md): disabled by default — the
    # serving hot path then pays exactly one attribute check.  Tenant
    # classes are declared as "name:rate=R,burst=B,weight=W,priority=P"
    # entries joined by ";" (the class allowlist — it bounds the tenant
    # label cardinality on serving metrics); unlabeled traffic maps to
    # qos_default_tenant.  The admit window is how long the device loop
    # holds an in-flight coalesced batch open for late arrivals
    # (continuous batching); the quantum is the deficit-round-robin
    # refill in ids-per-round per unit weight.  The ladder knobs gate
    # the adaptive degradation ladder: consecutive breaching SLO ticks
    # before stepping down, consecutive healthy ticks before stepping
    # back up, and the fanout fraction applied at ladder level >= 1.
    qos_enabled: bool = field(
        default_factory=lambda: _env("QOS_ENABLED", False, bool)
    )
    qos_tenants: str = field(
        default_factory=lambda: _env(
            "QOS_TENANTS",
            "gold:rate=200,burst=50,weight=8,priority=3;"
            "silver:rate=100,burst=25,weight=4,priority=2;"
            "bronze:rate=50,burst=15,weight=2,priority=1;"
            "ingest:rate=100,burst=50,weight=1,priority=0")
    )
    qos_default_tenant: str = field(
        default_factory=lambda: _env("QOS_DEFAULT_TENANT", "bronze")
    )
    qos_ingest_tenant: str = field(
        default_factory=lambda: _env("QOS_INGEST_TENANT", "ingest")
    )
    qos_admit_window_ms: float = field(
        default_factory=lambda: _env("QOS_ADMIT_WINDOW_MS", 2.0, float)
    )
    qos_quantum: int = field(
        default_factory=lambda: _env("QOS_QUANTUM", 64, int)
    )
    qos_degrade_fanout_frac: float = field(
        default_factory=lambda: _env("QOS_DEGRADE_FANOUT_FRAC", 0.5, float)
    )
    qos_breach_ticks: int = field(
        default_factory=lambda: _env("QOS_BREACH_TICKS", 2, int)
    )
    qos_recover_ticks: int = field(
        default_factory=lambda: _env("QOS_RECOVER_TICKS", 2, int)
    )
    # flight recorder (docs/OBSERVABILITY.md): ring-buffer capacity of
    # retained request records, and the e2e latency above which an
    # otherwise-healthy request counts as "slow" and is retained
    flightrec_capacity: int = field(
        default_factory=lambda: _env("FLIGHTREC_CAPACITY", 256, int)
    )
    flightrec_slow_ms: float = field(
        default_factory=lambda: _env("FLIGHTREC_SLOW_MS", 100.0, float)
    )
    # SLO objectives (telemetry.slo): p99 e2e latency ceiling, error
    # ratio ceiling, coldcache hit-rate floor (0 disables the floor),
    # and the watchdog evaluation interval
    slo_p99_ms: float = field(
        default_factory=lambda: _env("SLO_P99_MS", 250.0, float)
    )
    slo_error_ratio: float = field(
        default_factory=lambda: _env("SLO_ERROR_RATIO", 0.01, float)
    )
    slo_coldcache_hit_floor: float = field(
        default_factory=lambda: _env("SLO_COLDCACHE_HIT_FLOOR", 0.0, float)
    )
    slo_interval_s: float = field(
        default_factory=lambda: _env("SLO_INTERVAL_S", 5.0, float)
    )
    # streaming tier (quiver_tpu.stream): delta-segment capacity before
    # ingestion blocks on compaction, compactor cadence (seconds between
    # periodic folds; the watermark triggers early when the pending
    # fraction of capacity crosses it), and the edge-update ingestion
    # lane (queue depth, its own deadline class — 0 = no deadline — and
    # shed priority relative to query traffic)
    stream_delta_capacity: int = field(
        default_factory=lambda: _env("STREAM_DELTA_CAPACITY", 65536, int)
    )
    stream_compact_interval_s: float = field(
        default_factory=lambda: _env("STREAM_COMPACT_INTERVAL_S", 30.0,
                                     float)
    )
    stream_compact_watermark: float = field(
        default_factory=lambda: _env("STREAM_COMPACT_WATERMARK", 0.75,
                                     float)
    )
    stream_ingest_depth: int = field(
        default_factory=lambda: _env("STREAM_INGEST_DEPTH", 256, int)
    )
    stream_ingest_deadline_ms: float = field(
        default_factory=lambda: _env("STREAM_INGEST_DEADLINE_MS", 0.0,
                                     float)
    )
    stream_ingest_priority: int = field(
        default_factory=lambda: _env("STREAM_INGEST_PRIORITY", 1, int)
    )
    # durability / warm restart (quiver_tpu.recovery): the root the WAL
    # and checkpoints live under ("" = volatile, no durability), the WAL
    # fsync policy ("always" | "batch" | "off") + segment/batch sizing,
    # checkpoint cadence and retention, the replay deadline (0 = none),
    # the post-seal retrace budget per subsystem (-1 = count only,
    # never raise), and the JAX persistent compilation cache directory
    # ("" = off)
    recovery_dir: str = field(
        default_factory=lambda: _env("RECOVERY_DIR", "", str)
    )
    recovery_fsync: str = field(
        default_factory=lambda: _env("RECOVERY_FSYNC", "always", str)
    )
    recovery_segment_bytes: int = field(
        default_factory=lambda: _env("RECOVERY_SEGMENT_BYTES", 4 << 20, int)
    )
    recovery_batch_bytes: int = field(
        default_factory=lambda: _env("RECOVERY_BATCH_BYTES", 1 << 16, int)
    )
    recovery_checkpoint_interval_s: float = field(
        default_factory=lambda: _env("RECOVERY_CHECKPOINT_INTERVAL_S", 60.0,
                                     float)
    )
    recovery_checkpoint_keep: int = field(
        default_factory=lambda: _env("RECOVERY_CHECKPOINT_KEEP", 2, int)
    )
    recovery_deadline_s: float = field(
        default_factory=lambda: _env("RECOVERY_DEADLINE_S", 0.0, float)
    )
    recovery_retrace_budget: int = field(
        default_factory=lambda: _env("RECOVERY_RETRACE_BUDGET", -1, int)
    )
    recovery_cache_dir: str = field(
        default_factory=lambda: _env("RECOVERY_CACHE_DIR", "", str)
    )
    # tracing
    trace: bool = field(default_factory=lambda: _env("TRACE", False, bool))
    # unified timeline (telemetry.timeline): per-thread ring capacity in
    # events — a thread past capacity overwrites its own oldest events
    # (export reports the overwrite count), so a traced soak run is
    # bounded at threads x capacity x ~100B no matter how long it runs
    timeline_ring_capacity: int = field(
        default_factory=lambda: _env("TIMELINE_RING_CAPACITY", 8192, int)
    )
    # perf-regression gate (benchmarks/perfgate.py): repeats per metric
    # (the gate compares medians-of-k), the MAD multiplier above which a
    # slowdown counts as signal, and the relative-change floor below
    # which even a statistically-clear slowdown is ignored as too small
    # to gate on
    perfgate_k: int = field(
        default_factory=lambda: _env("PERFGATE_K", 5, int)
    )
    perfgate_mad_mult: float = field(
        default_factory=lambda: _env("PERFGATE_MAD_MULT", 5.0, float)
    )
    perfgate_rel_floor: float = field(
        default_factory=lambda: _env("PERFGATE_REL_FLOOR", 0.30, float)
    )
    # replicated serving fleet (quiver_tpu/fleet, docs/FLEET.md):
    # shared membership-directory path, placement shape (partitions /
    # virtual nodes on the consistent-hash ring), liveness cadence,
    # router re-dispatch budget, the QoS priority at or above which a
    # tenant routes power-of-two-choices, per-dispatch timeout, WAL
    # shipping poll/holdback cadence, and the staleness bound (in WAL
    # records) above which a follower should not be considered current
    fleet_dir: str = field(
        default_factory=lambda: _env("FLEET_DIR", "", str)
    )
    fleet_partitions: int = field(
        default_factory=lambda: _env("FLEET_PARTITIONS", 8, int)
    )
    fleet_vnodes: int = field(
        default_factory=lambda: _env("FLEET_VNODES", 64, int)
    )
    fleet_heartbeat_s: float = field(
        default_factory=lambda: _env("FLEET_HEARTBEAT_S", 0.5, float)
    )
    fleet_heartbeat_timeout_s: float = field(
        default_factory=lambda: _env("FLEET_HEARTBEAT_TIMEOUT_S", 3.0,
                                     float)
    )
    fleet_route_retries: int = field(
        default_factory=lambda: _env("FLEET_ROUTE_RETRIES", 2, int)
    )
    fleet_hot_priority: int = field(
        default_factory=lambda: _env("FLEET_HOT_PRIORITY", 3, int)
    )
    fleet_request_timeout_s: float = field(
        default_factory=lambda: _env("FLEET_REQUEST_TIMEOUT_S", 1.0,
                                     float)
    )
    fleet_ship_poll_ms: float = field(
        default_factory=lambda: _env("FLEET_SHIP_POLL_MS", 20.0, float)
    )
    fleet_ship_grace_ms: float = field(
        default_factory=lambda: _env("FLEET_SHIP_GRACE_MS", 250.0, float)
    )
    fleet_max_staleness_lsn: int = field(
        default_factory=lambda: _env("FLEET_MAX_STALENESS_LSN", 1024, int)
    )
    # fleet observability plane (quiver_tpu/fleet/federation.py,
    # docs/OBSERVABILITY.md): master switch for cross-process trace
    # propagation + metrics federation (off by default — the request
    # path pays exactly one config check when off), scraper cadence,
    # router hop-record ring capacity, and the eligible-replica floor
    # the fleet SLO watchdog alarms on
    fleet_federation: str = field(
        default_factory=lambda: _env("FLEET_FEDERATION", "off", str)
    )
    fleet_scrape_interval_s: float = field(
        default_factory=lambda: _env("FLEET_SCRAPE_INTERVAL_S", 0.5, float)
    )
    fleet_trace_ring: int = field(
        default_factory=lambda: _env("FLEET_TRACE_RING", 512, int)
    )
    fleet_min_eligible: int = field(
        default_factory=lambda: _env("FLEET_MIN_ELIGIBLE", 1, int)
    )
    # fleet autonomy (quiver_tpu/fleet/{election,walstream,autoscaler},
    # docs/FLEET.md): all three subsystems are OFF by default and the
    # off path is byte-identical — no threads, no metric keys, one
    # config-string check at construction time.
    #   election   — fenced leader auto-failover: followers race to
    #                claim an epoch-stamped leadership record when the
    #                leader's heartbeat expires; the epoch fences every
    #                WAL append / membership write of a deposed leader
    fleet_election: str = field(
        default_factory=lambda: _env("FLEET_ELECTION", "off", str)
    )
    fleet_election_poll_s: float = field(
        default_factory=lambda: _env("FLEET_ELECTION_POLL_S", 0.25, float)
    )
    # per-rank claim stagger: candidate rank r waits r * stagger before
    # claiming, so the most-caught-up follower wins uncontested unless
    # it too is dead (the O_EXCL claim keeps even a tie race safe)
    fleet_election_stagger_s: float = field(
        default_factory=lambda: _env("FLEET_ELECTION_STAGGER_S", 0.5,
                                     float)
    )
    # how often a fenced writer re-reads the claim directory on the
    # append path (0 = every append; tests use 0 for determinism)
    fleet_election_fence_recheck_s: float = field(
        default_factory=lambda: _env("FLEET_ELECTION_FENCE_RECHECK_S",
                                     0.05, float)
    )
    #   walstream  — leader-side socket WAL shipping (JSON-lines frame
    #                stream) so followers need no shared WAL directory
    fleet_walstream: str = field(
        default_factory=lambda: _env("FLEET_WALSTREAM", "off", str)
    )
    fleet_walstream_port: int = field(
        default_factory=lambda: _env("FLEET_WALSTREAM_PORT", 0, int)
    )
    #   autoscaler — federation-driven spawn/drain control loop with a
    #                diurnal-rate predictor, hysteresis and a cooldown
    fleet_autoscaler: str = field(
        default_factory=lambda: _env("FLEET_AUTOSCALER", "off", str)
    )
    fleet_autoscaler_interval_s: float = field(
        default_factory=lambda: _env("FLEET_AUTOSCALER_INTERVAL_S", 1.0,
                                     float)
    )
    fleet_autoscaler_min: int = field(
        default_factory=lambda: _env("FLEET_AUTOSCALER_MIN", 1, int)
    )
    fleet_autoscaler_max: int = field(
        default_factory=lambda: _env("FLEET_AUTOSCALER_MAX", 8, int)
    )
    fleet_autoscaler_cooldown_s: float = field(
        default_factory=lambda: _env("FLEET_AUTOSCALER_COOLDOWN_S", 30.0,
                                     float)
    )
    # serving capacity one replica is planned at, in requests/second —
    # the unit the diurnal predictor's rate forecast is divided by
    fleet_autoscaler_rps_per_replica: float = field(
        default_factory=lambda: _env("FLEET_AUTOSCALER_RPS_PER_REPLICA",
                                     200.0, float)
    )
    # prediction lead: scale for the rate expected this many seconds
    # ahead (a warm join must complete before the ramp arrives)
    fleet_autoscaler_horizon_s: float = field(
        default_factory=lambda: _env("FLEET_AUTOSCALER_HORIZON_S", 10.0,
                                     float)
    )
    # hysteresis band: scale up when predicted demand exceeds
    # up_ratio * capacity, down only when it falls below down_ratio *
    # capacity-after-drain — the gap is what prevents flapping
    fleet_autoscaler_up_ratio: float = field(
        default_factory=lambda: _env("FLEET_AUTOSCALER_UP_RATIO", 0.8,
                                     float)
    )
    fleet_autoscaler_down_ratio: float = field(
        default_factory=lambda: _env("FLEET_AUTOSCALER_DOWN_RATIO", 0.5,
                                     float)
    )
    # mesh-native sharded serving (quiver_tpu/mesh, docs/SHARDING.md):
    # number of row-range shards one logical replica spans (0 = off; the
    # whole mesh tier is dark and every code path is byte-identical to
    # the unsharded build), the shard-group id this process announces to
    # the fleet directory, this process's shard index within the group,
    # and the per-shard overlay pool size in pages (0 = size to the
    # batch working set at build)
    mesh_shards: int = field(
        default_factory=lambda: _env("MESH_SHARDS", 0, int)
    )
    mesh_group: str = field(
        default_factory=lambda: _env("MESH_GROUP", "", str)
    )
    mesh_shard_index: int = field(
        default_factory=lambda: _env("MESH_SHARD_INDEX", 0, int)
    )
    mesh_pool_pages: int = field(
        default_factory=lambda: _env("MESH_POOL_PAGES", 0, int)
    )


_config: Optional[Config] = None


def _load_tuned(cfg: Config, path: Optional[str] = None):
    """Fold in hardware-probed defaults (benchmarks/autotune.py), if any.
    Explicit env vars still win."""
    import json

    if path is None:
        path = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            ".quiver_tpu_tuned.json",
        )
    if not os.path.exists(path):
        return
    try:
        tuned = json.load(open(path))
        if not isinstance(tuned, dict):
            return
    except Exception:
        return
    # only apply results probed on THIS backend (a cpu-probed choice must
    # not override the TPU default and vice versa).  v2 files keep one
    # entry per backend under "backends" (bench.merge_tuned), so probing
    # on one backend can never erase another's evidence; flat v1 files
    # carry a single top-level "backend" tag.
    try:
        import jax

        backend = jax.default_backend()
    except Exception:
        return
    if isinstance(tuned.get("backends"), dict):
        tuned = tuned["backends"].get(backend)
        if not isinstance(tuned, dict):
            return
    elif tuned.get("backend") != backend:
        return
    gm = tuned.get("gather_mode")
    # a malformed tuned value ("blocked:0", "blockedx") is ignored like
    # every other invalid tuned value, not deferred to crash in
    # resolve_gather_mode later
    if (cfg.gather_mode == "auto" and isinstance(gm, str)
            and gm != "auto" and _is_valid_gather_mode(gm)):
        cfg.gather_mode = gm
    if (cfg.sample_rng == "auto"
            and tuned.get("sample_rng") in ("key", "hash")):
        cfg.sample_rng = tuned["sample_rng"]
    if cfg.dedup == "auto" and tuned.get("dedup") in ("none", "hop"):
        # written by bench.py's on-chip e2e none-vs-hop A/B — the
        # full-pipeline measurement, not the sampling microbenchmark
        # (the CPU dress rehearsal showed they can disagree)
        cfg.dedup = tuned["dedup"]


def resolve_sample_rng(sample_rng: str,
                       gather_mode: Optional[str] = None) -> str:
    """Map ``"auto"`` to the backend-measured best uniform source.

    Resolution order: explicit kwarg > gather-mode requirement >
    ``QUIVER_TPU_SAMPLE_RNG`` env / tuned file > backend default.
    Backend default (measured on a real v5e, docs/TPU_MEASUREMENTS.md
    round 2): ``"hash"`` (counter-hash uniforms) on accelerators — the
    3-hop pipeline runs 50.8M SEPS with hash vs 34.6M threefry / 31.3M
    rbg — and ``"key"`` (key-based ``jax.random.uniform``) on CPU, where
    threefry is fast and tests want reproducible streams.  PROVISIONAL:
    measured at 100K-node scale; pending products-scale re-measurement.

    ``gather_mode`` (the RESOLVED mode, if the caller has one): the
    fused Pallas window kernel (``pwindow``) only supports the in-kernel
    counter-hash, so ``auto`` resolves to ``"hash"`` under it regardless
    of backend — an explicit ``"key"`` still reaches the op and raises
    there (the user's choice is surfaced, not silently overridden).
    """
    if sample_rng not in ("auto", "key", "hash"):
        raise ValueError(f"sample_rng must be auto|key|hash, got "
                         f"{sample_rng!r}")
    if sample_rng != "auto":
        return sample_rng
    if gather_mode is not None and gather_mode.startswith("pwindow"):
        cfg = get_config()
        if cfg.sample_rng == "key":
            # the pin came from QUIVER_TPU_SAMPLE_RNG / the tuned file,
            # not an explicit kwarg (that returned above) — surface the
            # override instead of silently ignoring the pin
            import warnings

            warnings.warn(
                "sample_rng='key' pinned via env/tuned file is "
                "overridden to 'hash': gather_mode='pwindow' fuses the "
                "counter-hash RNG in-kernel. Pass sample_rng='key' "
                "explicitly to get a hard error, or pick a "
                "'blocked:U'/'lanes' gather mode to keep key-based "
                "draws.", stacklevel=2)
        return "hash"
    cfg = get_config()
    if cfg.sample_rng != "auto":
        return resolve_sample_rng(cfg.sample_rng)  # validates env/tuned too
    import jax

    return "hash" if jax.default_backend() not in ("cpu",) else "key"


def resolve_dedup(dedup: str) -> str:
    """Map ``"auto"`` to the measured frontier-dedup default.

    Resolution order: explicit kwarg > ``QUIVER_TPU_DEDUP`` env / tuned
    file (written by bench.py's on-chip e2e none-vs-hop A/B) > "none"
    (the positional-relabel hot path — round-2's sampling
    microbenchmarks; the e2e A/B may overturn it, which is exactly what
    the tuned overlay is for).
    """
    if dedup not in ("auto", "none", "hop"):
        raise ValueError(f"dedup must be auto|none|hop, got {dedup!r}")
    if dedup != "auto":
        return dedup
    cfg = get_config()
    if cfg.dedup != "auto":
        return resolve_dedup(cfg.dedup)
    return "none"


def _validate_gather_mode(gm) -> None:
    """One validator shared by the tuned-file loader (which catches and
    skips) and resolve_gather_mode (which lets it raise) — keeps
    parse_blocked's specific diagnostics ("blocked:U needs U >= 1")
    instead of a generic mode-list message."""
    if gm in ("auto", "xla", "lanes", "lanes_fused", "pallas"):
        return
    if isinstance(gm, str) and gm.startswith("blocked"):
        from .ops.blockgather import parse_blocked

        parse_blocked(gm)
        return
    if isinstance(gm, str) and gm.startswith("pwindow"):
        from .ops.pallas.window_sample_kernel import parse_pwindow

        parse_pwindow(gm)
        return
    raise ValueError(
        f"gather_mode must be one of (auto, xla, lanes, lanes_fused, "
        f"pallas) or 'blocked[:U]' or 'pwindow[:U]', got {gm!r}")


def _is_valid_gather_mode(gm) -> bool:
    try:
        _validate_gather_mode(gm)
    except Exception:
        return False
    return True


def resolve_gather_mode(gather_mode: str,
                        sample_rng: Optional[str] = None) -> str:
    """Map ``"auto"`` to the backend-measured best element-gather mode.

    Resolution order: explicit kwarg > ``QUIVER_TPU_GATHER_MODE`` env /
    tuned file > backend default.  Backend default: ``"lanes"``
    (row-gather + VPU lane select) on accelerators, where XLA's 1-D
    scalar gather serializes (docs/TPU_MEASUREMENTS.md round 2: 3-hop
    lanes 27 ms vs xla 237 ms per batch on v5e); plain ``"xla"`` take on
    CPU.  PROVISIONAL: those numbers come from a 100K-node graph — the
    ranking is pending re-measurement at production scale (100M+ nodes,
    where HBM pressure and table width change the gather trade-offs).

    ``sample_rng`` (the caller's RAW kwarg): when ``auto`` resolution
    lands on the Pallas ``pwindow`` kernel (hash-RNG-only) but the user
    explicitly asked for ``sample_rng="key"``, the choice degrades to
    the equivalent XLA ``blocked`` window mode instead of crashing a
    config the user never chose.  An EXPLICIT ``gather_mode="pwindow"``
    with ``"key"`` still raises at the op (the user's own combination is
    surfaced, not rewritten).
    """
    _validate_gather_mode(gather_mode)
    if gather_mode != "auto":
        return gather_mode
    cfg = get_config()
    if cfg.gather_mode != "auto":
        resolved = resolve_gather_mode(cfg.gather_mode)
    else:
        import jax

        resolved = "lanes" if jax.default_backend() not in ("cpu",) \
            else "xla"
    if resolved.startswith("pwindow") and sample_rng == "key":
        resolved = "blocked" + resolved[len("pwindow"):]
    return resolved


# config is frozen once per process, so anything read off it is
# process-lifetime-finite: cache keys built from config attributes
# cannot blow up executable cardinality.
# quiverlint: bucketed[config is frozen once per process]
def get_config() -> Config:
    global _config
    if _config is None:
        _config = Config()
        _load_tuned(_config)
        if _config.trace:
            from .utils import trace as _t

            _t.set_enabled(True)
    return _config


def update(**kwargs) -> Config:
    cfg = get_config()
    for k, v in kwargs.items():
        if not hasattr(cfg, k):
            raise AttributeError(f"unknown config field {k!r}")
        setattr(cfg, k, v)
    return cfg
