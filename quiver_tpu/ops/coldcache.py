"""Host-side metadata for the HBM cold-row overlay cache.

The budgeted feature tier (``Feature`` with ``cache_count <
node_count``) serves every cold row over the host link, every batch —
even when zipf-skewed traffic re-requests the same rows batch after
batch (BENCH_r05's budgeted tier is transport-limited).  The overlay
cache is a second device-resident tier *behind* the static degree-
ordered hot prefix: a fixed-capacity ``[C, dim]`` HBM table holding
whichever cold rows the traffic keeps touching.

Division of labor (mirrors the hot/cold split itself):

  * **this module** — pure-numpy slot bookkeeping: node-id -> slot map,
    online access-frequency tracking, second-touch admission, CLOCK or
    min-frequency eviction.  No jax imports; the probe/admit split in
    ``Feature._stage`` stays host-side numpy.
  * **feature.py** — the device side: one jax array per overlay, read
    by the cached three-way merge executables and written by cached
    scatter-update executables (static shapes, no retraces).

Thread-safety: instances are **externally synchronized** — every
caller holds the owning store's staging lock (``Feature._plock``)
across probe+admit so the metadata and the captured device table value
stay consistent (see ``Feature._stage``).

The paged feature store (``ops/paged.py``) reuses this class as its
**page table**: the "rows" become host pages, the slots become OVERLAY
frames, and residency/eviction/invalidation/checkpoint export all come
along unchanged (``admit_threshold=1`` there — a touched HOST page
must fault in to be served at all).

Policy notes:

  * *Second-touch admission* (``admit_threshold=2`` default): a row
    enters the overlay only on its ``admit_threshold``-th miss, so
    one-shot scans cannot flush rows the recurring traffic needs
    (ARC/2Q's ghost-list insight, sized to one counter per cold row).
    Duplicate ids inside one batch each count as a touch — a row
    requested twice in a single gather is recurring by definition.
  * *CLOCK eviction*: one ref bit per slot, set on hit, cleared as the
    hand sweeps; the sweep is batched (vectorized over the whole
    admission batch) rather than per-victim, which preserves CLOCK's
    second-chance semantics at numpy speed.
  * *min-frequency eviction* (``policy="minfreq"``): evict the resident
    slots with the smallest hit counts (argpartition over the per-slot
    frequency array) — stickier than CLOCK for stationary zipf traffic,
    slower to adapt when the hot set drifts.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

__all__ = ["ColdRowCache", "COLD_CACHE_POLICIES"]

COLD_CACHE_POLICIES = ("clock", "minfreq")


class ColdRowCache:
    """Fixed-capacity slot table + frequency tracker over a cold-id space.

    Args:
      capacity: number of overlay slots (rows of the device table).
      n_rows: size of the cold-id space being cached over (ids handed to
        :meth:`probe`/:meth:`admit` must be in ``[0, n_rows)``).
      policy: ``"clock"`` or ``"minfreq"`` eviction.
      admit_threshold: a row is admitted on its N-th observed miss
        (1 = admit on first miss).
    """

    def __init__(self, capacity: int, n_rows: int, policy: str = "clock",
                 admit_threshold: int = 2):
        capacity = int(capacity)
        n_rows = int(n_rows)
        if capacity <= 0:
            raise ValueError(f"overlay capacity must be > 0, got {capacity}")
        if policy not in COLD_CACHE_POLICIES:
            raise ValueError(f"cold-cache policy must be one of "
                             f"{COLD_CACHE_POLICIES}, got {policy!r}")
        if admit_threshold < 1:
            raise ValueError("admit_threshold must be >= 1")
        self.capacity = capacity
        self.n_rows = n_rows
        self.policy = policy
        self.admit_threshold = int(admit_threshold)
        self.slot_of = np.full(n_rows, -1, dtype=np.int32)
        self.node_of = np.full(capacity, -1, dtype=np.int64)
        self.freq = np.zeros(capacity, dtype=np.int64)   # per-slot hits
        self.ref = np.zeros(capacity, dtype=np.uint8)    # CLOCK ref bits
        self.touches = np.zeros(n_rows, dtype=np.int32)  # misses per row
        self.hand = 0
        self.next_free = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        # brownout switch (QoS degradation ladder L2): while True,
        # admit() stops taking new rows — probes and hits still serve,
        # but no slot churn / device row writes happen under overload
        self.admission_paused = False

    # ------------------------------------------------------------------
    def probe(self, ids: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """Hit/miss split for one batch of cold-space ids.

        Returns ``(hit_mask, slots)`` aligned with ``ids``; ``slots`` is
        only meaningful where ``hit_mask``.  Side effects: bumps per-slot
        frequency + CLOCK ref bits for hits, and per-row touch counts
        for misses (the admission evidence :meth:`admit` reads).
        """
        ids = np.asarray(ids, dtype=np.int64)
        slots = self.slot_of[ids]
        hit = slots >= 0
        hs = slots[hit]
        if hs.size:
            np.add.at(self.freq, hs, 1)
            self.ref[hs] = 1
            self.hits += int(hs.size)
        miss_ids = ids[~hit]
        if miss_ids.size:
            np.add.at(self.touches, miss_ids, 1)
            self.misses += int(miss_ids.size)
        return hit, slots

    # ------------------------------------------------------------------
    def admit(self, ids: np.ndarray,
              protect_slots=None) -> Tuple[np.ndarray, int]:
        """Assign slots to the missed rows that earned admission.

        ``ids`` are the missed cold-space ids of one batch (touch counts
        already bumped by :meth:`probe`).  Returns ``(slots, n_evicted)``
        where ``slots`` is aligned with ``ids`` (-1 = not admitted;
        duplicates of one id share its slot).  At most ``capacity`` rows
        admit per call; the overflow stays host-served this batch.

        ``protect_slots`` pins already-resident slots against this
        call's eviction sweep — the paged store passes the batch's
        OVERLAY-hit pages here, since the gather about to run reads
        them (evicting a same-batch hit would serve a retargeted page).
        The count of candidates is clipped so protection can never make
        the sweep need more victims than the unprotected slots can
        supply.
        """
        ids = np.asarray(ids, dtype=np.int64)
        out = np.full(len(ids), -1, dtype=np.int32)
        if not len(ids) or self.admission_paused:
            return out, 0
        cand = np.unique(ids[self.touches[ids] >= self.admit_threshold])
        n_prot = (len(np.unique(protect_slots))
                  if protect_slots is not None and len(protect_slots)
                  else 0)
        cand = cand[: self.capacity - n_prot]
        k = len(cand)
        if k == 0:
            return out, 0
        slots = np.empty(k, dtype=np.int32)
        n_new = min(self.capacity - self.next_free, k)
        if n_new:
            slots[:n_new] = np.arange(self.next_free, self.next_free + n_new,
                                      dtype=np.int32)
            self.next_free += n_new
        n_evicted = 0
        if k > n_new:
            # protect the slots just taken from the free list: their
            # ref/freq are still zero here, so an unprotected sweep
            # would hand them out twice (two ids sharing one slot)
            prot = slots[:n_new]
            if n_prot:
                prot = np.concatenate(
                    [prot, np.asarray(protect_slots, dtype=np.int32)])
            victims = self._evict(k - n_new, protect=prot)
            slots[n_new:] = victims
            old = self.node_of[victims]
            live = old >= 0
            self.slot_of[old[live]] = -1
            n_evicted = int(live.sum())
            self.evictions += n_evicted
        self.node_of[slots] = cand
        self.slot_of[cand] = slots
        self.freq[slots] = 1
        # insert with ref=0: the admission evidence (touches) is spent;
        # the ref bit tracks POST-admission reuse, so the sweep can tell
        # still-recurring rows from one-burst admits
        self.ref[slots] = 0
        self.touches[cand] = 0
        out = self.slot_of[ids]  # admitted ids resolve, the rest stay -1
        return out, n_evicted

    def _evict(self, need: int, protect=None) -> np.ndarray:
        prot = np.zeros(self.capacity, dtype=bool)
        if protect is not None and len(protect):
            prot[protect] = True
        if self.policy == "minfreq":
            # smallest-hit-count resident slots; O(C) per admission batch
            f = self.freq.copy()
            f[prot] = np.iinfo(f.dtype).max
            idx = np.argpartition(f, need - 1)[:need]
            return idx.astype(np.int32)
        # batched CLOCK: scan from the hand; slots with ref=0 are victims,
        # every slot passed on the way loses its ref bit (second chance)
        cap = self.capacity
        order = np.concatenate(
            [np.arange(self.hand, cap), np.arange(0, self.hand)]
        ).astype(np.int32)
        order = order[~prot[order]]
        zero_pos = np.nonzero(self.ref[order] == 0)[0]
        if len(zero_pos) >= need:
            last = int(zero_pos[need - 1])
            self.ref[order[: last + 1]] = 0
            self.hand = int(order[last] + 1) % cap
            return order[zero_pos[:need]]
        # a full sweep found < need zeros: every scanned bit is cleared,
        # the remainder comes from the (now all-zero) second sweep in order
        victims = order[zero_pos]
        taken = np.zeros(cap, dtype=bool)
        taken[victims] = True
        rest = order[~taken[order]][: need - len(victims)]
        self.ref[order] = 0
        out = np.concatenate([victims, rest]).astype(np.int32)
        self.hand = int(out[-1] + 1) % cap
        return out

    # ------------------------------------------------------------------
    def invalidate_rows(self, rows: np.ndarray) -> int:
        """Drop the given cold-space rows from the overlay.

        Called when the underlying feature rows mutate (stream edge/row
        updates): a resident slot would otherwise keep serving the stale
        value forever.  The freed slots keep ``ref=0``/``freq=0`` so the
        next CLOCK sweep hands them out first; touch counts are also
        reset so a mutated row must re-earn admission (second touch)
        rather than re-admitting off pre-mutation evidence.

        Returns the number of resident rows actually dropped.
        """
        rows = np.asarray(rows, dtype=np.int64)
        if rows.size == 0:
            return 0
        rows = rows[(rows >= 0) & (rows < self.n_rows)]
        if rows.size == 0:
            return 0
        slots = self.slot_of[rows]
        live = slots >= 0
        freed = slots[live]
        if freed.size:
            self.node_of[freed] = -1
            self.freq[freed] = 0
            self.ref[freed] = 0
            self.slot_of[rows[live]] = -1
        self.touches[rows] = 0
        return int(freed.size)

    # ------------------------------------------------------------------
    def export_state(self) -> dict:
        """Snapshot-able residency/frequency state (numpy copies +
        scalars; the recovery checkpoint pins the array dtypes on
        disk).  Caller holds the owning store's staging lock, same as
        every other entry point."""
        return {
            "capacity": self.capacity, "n_rows": self.n_rows,
            "policy": self.policy, "admit_threshold": self.admit_threshold,
            "hand": self.hand, "next_free": self.next_free,
            "hits": self.hits, "misses": self.misses,
            "evictions": self.evictions,
            "slot_of": self.slot_of.copy(), "node_of": self.node_of.copy(),
            "freq": self.freq.copy(), "ref": self.ref.copy(),
            "touches": self.touches.copy(),
        }

    def restore_state(self, state: dict) -> None:
        """Adopt a previously exported state.  The geometry (capacity,
        cold-row space) must match this instance — a warm restart with
        a re-sized overlay starts cold instead (the caller treats the
        ``ValueError`` as "no restore", not as a boot failure)."""
        if int(state["capacity"]) != self.capacity:
            raise ValueError(
                f"overlay capacity changed: snapshot has "
                f"{state['capacity']}, this cache has {self.capacity}")
        if int(state["n_rows"]) != self.n_rows:
            raise ValueError(
                f"cold-row space changed: snapshot has {state['n_rows']} "
                f"rows, this cache has {self.n_rows}")
        self.slot_of = np.array(state["slot_of"], dtype=np.int32)
        self.node_of = np.array(state["node_of"], dtype=np.int64)
        self.freq = np.array(state["freq"], dtype=np.int64)
        self.ref = np.array(state["ref"], dtype=np.uint8)
        self.touches = np.array(state["touches"], dtype=np.int32)
        self.hand = int(state["hand"])
        self.next_free = int(state["next_free"])
        self.hits = int(state["hits"])
        self.misses = int(state["misses"])
        self.evictions = int(state["evictions"])

    # ------------------------------------------------------------------
    @property
    def resident(self) -> int:
        return int((self.node_of >= 0).sum())

    def resident_bytes(self, row_bytes: int) -> int:
        """Device bytes the resident entries pin, given the bytes one
        cached unit occupies (a feature row here; a whole page when the
        paged store uses this class as its page table)."""
        return self.resident * int(row_bytes)

    def stats(self) -> dict:
        total = self.hits + self.misses
        return dict(
            capacity=self.capacity, resident=self.resident,
            hits=self.hits, misses=self.misses, evictions=self.evictions,
            hit_rate=(self.hits / total) if total else 0.0,
            policy=self.policy, admit_threshold=self.admit_threshold,
        )

    def __repr__(self):
        return (f"ColdRowCache(capacity={self.capacity}, "
                f"resident={self.resident}, policy={self.policy!r}, "
                f"hit_rate={self.stats()['hit_rate']:.3f})")
