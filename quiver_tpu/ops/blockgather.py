"""Blocked window gather — one covering-block gather serves ALL k draws
of a seed.

The k draws of one seed all read the same contiguous CSR window
``indices[start:end)`` (the reference's warp kernel exploits exactly this
contiguity with warp-wide coalesced loads, ``cuda_random.cu.hpp:8-69``).
The plain ``lanes`` mode ignores it: every draw pays an independent
[128]-row probe, 128x the payload per element.  Here, a seed whose
window spans at most ``U`` 128-lane rows is served by ONE ``[U, 128]``
block gather + a VPU one-hot select of its k lanes — issuing ``U`` rows
per seed instead of ``k``.  Seeds whose window spans more rows (the
degree-biased tail of a power-law frontier; ~13% at U=3 on a
products-like profile) are compacted into a capped fallback that uses
the classic per-draw path.  If more than the cap don't fit, the whole
batch falls back to the classic path via ``lax.cond`` — results are
bitwise identical on every route, only the traffic changes.

Expected issue-rate win at products scale (fanout [15,10,5], U=3,
cap=T/4): 2.2x / 1.8x / 1.2x fewer gathered rows per hop.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .fastgather import LANES, element_gather

__all__ = ["blocked_window_gather", "blocked_weighted_positions",
           "parse_blocked"]

DEFAULT_U = 3
FALLBACK_FRAC = 0.25


def parse_u_mode(mode: str, prefix: str, default: int = DEFAULT_U) -> int:
    """Parse ``"<prefix>"`` -> ``default`` / ``"<prefix>:4"`` -> 4.
    Anything else (e.g. the typo ``"blocked4"``) raises instead of
    silently running with the default block width.  Shared by the
    ``blocked`` (XLA) and ``pwindow`` (Pallas) window-gather modes."""
    if mode == prefix:
        return default
    if mode.startswith(prefix + ":"):
        u = int(mode.split(":", 1)[1])  # ValueError on a bad suffix
        if u < 1:
            raise ValueError(f"{prefix}:U needs U >= 1, got {mode!r}")
        return u
    raise ValueError(
        f"{prefix} gather mode must be '{prefix}' or '{prefix}:U', got "
        f"{mode!r}")


def parse_blocked(mode: str) -> int:
    return parse_u_mode(mode, "blocked")


def _fit_split(start, deg, U, B, fallback_frac):
    """Shared fit test + compaction bookkeeping.

    Returns (r0, fits, nfall, S, seed_of_slot, valid):
    ``fits[b]`` iff seed b's window [start, start+deg) spans <= U rows of
    the 128-lane table; non-fitting seeds are compacted into ``S`` slots
    (``seed_of_slot``, ``valid``).
    """
    S = min(max(int(B * fallback_frac), 8), B)
    r0 = jax.lax.shift_right_logical(start, 7)
    last = start + jnp.maximum(deg - 1, 0)
    fits = (jax.lax.shift_right_logical(last, 7) - r0) < U
    nfall = jnp.sum(~fits)
    slot = jnp.where(~fits, jnp.cumsum(~fits) - 1, S)
    seed_of_slot = jnp.zeros((S,), jnp.int32).at[slot].set(
        jnp.arange(B, dtype=jnp.int32), mode="drop")
    valid = jnp.arange(S, dtype=jnp.int32) < nfall
    return r0, fits, nfall, S, seed_of_slot, valid


def _block_gather(table2d, r0, B, U):
    """[B, U*128] covering blocks (rows clipped to the table)."""
    u_iota = jnp.arange(U, dtype=jnp.int32)
    rows = jnp.minimum(r0[:, None] + u_iota[None, :], table2d.shape[0] - 1)
    return jnp.take(table2d, rows, axis=0).reshape(B, U * LANES)


def _block_select(blk, rel):
    """vals[b, j] = blk[b, rel[b, j]] as a one-hot VPU reduction (XLA
    fuses the compare into the reduce; no [B, k, U*128] intermediate)."""
    width = blk.shape[1]
    onehot = rel[..., None] == jax.lax.broadcasted_iota(
        jnp.int32, (1, 1, width), 2)
    return jnp.sum(jnp.where(onehot, blk[:, None, :], 0), axis=2,
                   dtype=blk.dtype)


def blocked_window_gather(table2d, start, deg, pos, U=DEFAULT_U,
                          fallback_frac=FALLBACK_FRAC):
    """``vals[b, j] = table.flat[start[b] + pos[b, j]]`` where every row
    b's reads lie in its window ``[start[b], start[b] + deg[b])``.

    Args:
      table2d: ``[rows, 128]`` (a 128-padded flat table, reshaped).
      start: ``[B]`` int32 window starts (flat element offsets).
      deg: ``[B]`` int32 window lengths (0 allowed).
      pos: ``[B, k]`` int32 in-window positions (garbage rows allowed
        where the caller masks them out; must be in [0, max(deg-1, 0)]).
    """
    B, k = pos.shape
    nrows = table2d.shape[0]
    r0, fits, nfall, S, seed_of_slot, valid = _fit_split(
        start, deg, U, B, fallback_frac)
    idx = start[:, None] + pos

    def blocked(_):
        blk = _block_gather(table2d, r0, B, U)
        rel = jnp.clip(idx - (r0[:, None] << 7), 0, U * LANES - 1)
        vals = _block_select(blk, rel)
        fb_idx = jnp.take(idx, seed_of_slot, axis=0)
        fb_idx = jnp.where(valid[:, None], fb_idx, 0)
        fb_vals = element_gather(table2d, fb_idx)
        return vals.at[jnp.where(valid, seed_of_slot, B)].set(
            fb_vals, mode="drop")

    def classic(_):
        return element_gather(table2d, jnp.clip(idx, 0, nrows * LANES - 1))

    return jax.lax.cond(nfall <= S, blocked, classic, None)


def blocked_weighted_positions(cw2d, start, deg, u, U=DEFAULT_U,
                               fallback_frac=FALLBACK_FRAC,
                               bits: int = 24):
    """Weighted draw positions via ONE pass over the gathered CDF block.

    ``cw2d`` is the 128-padded per-row inclusive cumulative-weight table
    (``row_cumsum_weights``) reshaped ``[rows, 128]``; ``u[b, j]`` is the
    uniform draw already scaled by the row total.  For a fitting seed the
    first CDF entry exceeding ``u`` equals the COUNT of in-window entries
    ``<= u`` (the CDF is nondecreasing within a row) — one masked VPU
    reduction over the block replaces the classic ``bits``-round binary
    search of element gathers.  Non-fitting seeds take the classic
    search, compacted; cap overflow falls back wholesale (lax.cond).

    Returns ``pos[b, j]`` in ``[0, deg[b])`` (garbage where deg == 0;
    callers mask).
    """
    B, k = u.shape
    nrows = cw2d.shape[0]
    r0, fits, nfall, S, seed_of_slot, valid = _fit_split(
        start, deg, U, B, fallback_frac)

    def classic_search(starts, degs, us):
        """bits-round binary search over cw2d.flat (classic path)."""
        lo = jnp.broadcast_to(starts[:, None], us.shape)
        hi = jnp.broadcast_to((starts + degs)[:, None], us.shape)

        def step(_, lohi):
            lo, hi = lohi
            mid = (lo + hi) // 2
            cw = element_gather(cw2d, jnp.clip(mid, 0, nrows * LANES - 1))
            gt = cw > us
            return jnp.where(gt, lo, mid + 1), jnp.where(gt, mid, hi)

        lo, hi = jax.lax.fori_loop(0, bits, step, (lo, hi))
        return jnp.clip(lo - starts[:, None], 0,
                        jnp.maximum(degs[:, None] - 1, 0))

    def blocked(_):
        blk = _block_gather(cw2d, r0, B, U)                    # [B, U*128]
        off = start - (r0 << 7)                                # [B]
        win = jax.lax.broadcasted_iota(jnp.int32, (1, U * LANES), 1)
        in_win = ((win >= off[:, None])
                  & (win < (off + deg)[:, None]))              # [B, W]
        # count of in-window CDF entries <= u  ->  first-exceed position
        le = blk[:, None, :] <= u[:, :, None]                  # [B, k, W]
        cnt = jnp.sum(jnp.where(in_win[:, None, :], le, False), axis=2)
        pos = jnp.clip(cnt, 0, jnp.maximum(deg[:, None] - 1, 0))
        pos = pos.astype(jnp.int32)
        fb_pos = classic_search(
            jnp.where(valid, jnp.take(start, seed_of_slot), 0),
            jnp.where(valid, jnp.take(deg, seed_of_slot), 0),
            jnp.take(u, seed_of_slot, axis=0))
        return pos.at[jnp.where(valid, seed_of_slot, B)].set(
            fb_pos, mode="drop")

    def classic(_):
        return classic_search(start, deg, u)

    return jax.lax.cond(nfall <= S, blocked, classic, None)
