"""Fast scalar gather for TPU — the element-gather that graph sampling
lives on.

XLA lowers a 1-D ``table[idx]`` gather on TPU to a serialized
dynamic-slice loop (~tens of ns per element) — that was the measured
bottleneck of the sampling hop.  HBM, however, serves 512-byte transactions
regardless, and *row* gathers of ``[*, 128]`` blocks run at near-bandwidth.
So: reshape the table to ``[N/128, 128]``, row-gather the covering block of
each element, then select the lane on the VPU with a one-hot reduction.
Bandwidth cost is 128x the payload, but on products-scale sampling that is
still ~30x faster than the serialized scalar gather.

This is the TPU counterpart of the coalesced reads the reference's CUDA
kernels get from warp-wide loads (``cuda_random.cu.hpp:8-69``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["element_gather", "prepare_table", "pad_table_128"]

LANES = 128


def prepare_table(table: jax.Array) -> jax.Array:
    """Pad a 1-D table to a multiple of 128 and reshape to [rows, 128].

    Do this ONCE at graph-build time (CSRTopo.to_device) so the hot path
    pays no reshape.
    """
    n = table.shape[0]
    pad = (-n) % LANES
    if pad:
        table = jnp.concatenate(
            [table, jnp.zeros((pad,), table.dtype)]
        )
    return table.reshape(-1, LANES)


def pad_table_128(table, fill=None):
    """Pad a 1-D table to a multiple of 128 (host numpy or jnp).

    ``fill=None`` zero-pads; otherwise pads with ``fill`` (e.g. the last
    cumulative weight so clipped probes read a harmless value).  The
    lanes/pallas gather modes REQUIRE 128-multiple tables — ``_gather``
    rejects anything else rather than silently truncating.
    """
    n = table.shape[0]
    pad = (-n) % 128
    if not pad:
        return table
    val = fill if fill is not None else 0
    return jnp.concatenate(
        [table, jnp.full((pad,), val, table.dtype)]
    )


def element_gather(table2d: jax.Array, idx: jax.Array,
                   fused: bool = False) -> jax.Array:
    """``table.reshape(-1)[idx]`` via row gather + lane select.

    Args:
      table2d: ``[rows, 128]`` (from :func:`prepare_table`).
      idx: any-shape int32 flat element indices (must be < rows*128).
      fused: run the lane reduction as a Pallas kernel
        (``ops.pallas.element_gather_kernel``) so the ``[M, 128]`` row
        blocks stream through VMEM instead of landing in HBM.  Same
        result; pick by benchmark.
    """
    shape = idx.shape
    flat = idx.reshape(-1)
    row = jax.lax.shift_right_logical(flat, 7)
    lane = jnp.bitwise_and(flat, LANES - 1)
    rows = jnp.take(table2d, row, axis=0)              # [M, 128] row gather
    if fused:
        from .pallas.element_gather_kernel import lane_select, BLK

        m = flat.shape[0]
        pad = (-m) % BLK
        if pad:
            rows = jnp.concatenate(
                [rows, jnp.zeros((pad, LANES), rows.dtype)]
            )
            lane = jnp.concatenate([lane, jnp.zeros((pad,), lane.dtype)])
        return lane_select(rows, lane)[:m].reshape(shape)
    onehot = (
        lane[:, None]
        == jax.lax.broadcasted_iota(jnp.int32, (1, LANES), 1)
    )
    out = jnp.sum(jnp.where(onehot, rows, 0), axis=1, dtype=table2d.dtype)
    return out.reshape(shape)
