from .sample import (sample_neighbors, sample_neighbors_weighted,
                     row_cumsum_weights, SampleOut, to_ragged)
from .reindex import reindex, ReindexOut
from .prob import cal_neighbor_prob, sample_prob
