from .sample import sample_neighbors, SampleOut, to_ragged
from .reindex import reindex, ReindexOut
from .prob import cal_neighbor_prob, sample_prob
