"""Feature-access probability recurrence.

Reference parity: ``cal_next`` kernel (``cuda_random.cu.hpp:72-104``),
exposed as ``cal_neighbor_prob`` (``quiver_sample.cu:100-111``) and driven by
``GraphSageSampler.sample_prob`` (``sage_sampler.py:149-157``).  The metric:
expected number of times each node enters a sampled batch, layer by layer —
it drives the hot-cache split and the cross-host partitioner.

The CUDA kernel is a scatter-add over edges: node ``u`` with probability
``p[u]`` contributes ``p[u] * min(1, k/deg(u))`` to each of its neighbors.
On TPU that is one ``segment_sum`` over the edge array — a memory-bound op
XLA handles well; no custom kernel needed.
"""

from __future__ import annotations

import functools
from typing import Sequence

import jax
import jax.numpy as jnp

__all__ = ["cal_neighbor_prob", "sample_prob"]


@functools.partial(jax.jit, static_argnames=("num_edges",))
def cal_neighbor_prob(indptr: jax.Array, indices: jax.Array,
                      last_prob: jax.Array, k,
                      num_edges: int = None) -> jax.Array:
    """One layer of the access-probability recurrence.

    ``last_prob`` is ``[N]``; ``indptr``/``indices`` may be zero-padded
    beyond ``N+1``/``num_edges`` (see ``CSRTopo.to_device``).
    """
    n = last_prob.shape[0]
    e = num_edges if num_edges is not None else indices.shape[0]
    indptr = indptr[: n + 1]
    indices = indices[:e]
    deg = (indptr[1:] - indptr[:-1]).astype(jnp.float32)
    w = last_prob * jnp.minimum(1.0, k / jnp.maximum(deg, 1.0))
    # expand per-edge source weights: edge e belongs to row r(e)
    row_of_edge = jnp.searchsorted(
        indptr, jnp.arange(e, dtype=indptr.dtype), side="right"
    ) - 1
    contrib = w[row_of_edge]
    return jax.ops.segment_sum(contrib, indices, num_segments=n)


def sample_prob(indptr, indices, train_idx, total_node_count: int,
                sizes: Sequence[int], num_edges: int = None) -> jax.Array:
    """Multi-layer probability: parity with ``sample_prob``.

    Returns the last layer's accumulated probability vector (float32 [N]).
    """
    last = jnp.zeros((total_node_count,), jnp.float32).at[train_idx].set(1.0)
    for k in sizes:
        last = cal_neighbor_prob(indptr, indices, last, k,
                                 num_edges=num_edges)
    return last
