"""Pallas TPU kernel: feature-row gather with pipelined DMA.

The TPU-native ``quiver_tensor_gather`` (reference:
``srcs/cpp/include/quiver/shard_tensor.cu.hpp:7-61`` — warp-per-row byte
copy walking a device-pointer table).  Here there is one memory space to
walk (HBM) and the kernel's job is purely to keep many row DMAs in flight:
each grid program owns a block of output rows and round-robins NBUF
outstanding HBM->VMEM copies selected by the scalar-prefetched index
vector.

For very wide rows XLA's own gather is already near-bandwidth; this kernel
wins on mid-width rows (64-512 floats) where per-row launch overhead
dominates XLA's emitter.  Benchmarked against ``jnp.take`` in
``benchmarks/bench_feature.py``; ``Feature`` picks whichever is faster.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["gather_rows"]

NBUF = 4  # outstanding DMAs per program


def _kernel(idx_ref, table_ref, out_ref, sem):
    blk = out_ref.shape[0]
    base = pl.program_id(0) * blk

    def get_dma(slot, i):
        return pltpu.make_async_copy(
            table_ref.at[idx_ref[base + i]],
            out_ref.at[i],
            sem.at[slot],
        )

    # warm-up: fill the pipeline
    for w in range(NBUF):
        @pl.when(w < blk)
        def _(w=w):
            get_dma(w, w).start()

    def body(i, _):
        # wait i FIRST: its semaphore slot (i % NBUF) is the same slot
        # DMA i+NBUF will use, so the slot must drain before reuse
        get_dma(i % NBUF, i).wait()

        @pl.when(i + NBUF < blk)
        def _():
            get_dma((i + NBUF) % NBUF, i + NBUF).start()

        return 0

    jax.lax.fori_loop(0, blk, body, 0)


@functools.partial(jax.jit, static_argnames=("block", "interpret"))
def gather_rows(table: jax.Array, idx: jax.Array, block: int = 256,
                interpret: bool = False) -> jax.Array:
    """``table[idx]`` for 2-D ``table [N, D]``, ``idx [M]`` (M % block == 0,
    pad with 0s and slice if needed)."""
    m = idx.shape[0]
    assert m % block == 0, (m, block)
    d = table.shape[1]
    grid = (m // block,)
    return pl.pallas_call(
        _kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec(
                (block, d), lambda i, idx_ref: (i, 0),
                memory_space=pltpu.VMEM,
            ),
            scratch_shapes=[pltpu.SemaphoreType.DMA((NBUF,))],
        ),
        out_shape=jax.ShapeDtypeStruct((m, d), table.dtype),
        interpret=interpret,
    )(idx.astype(jnp.int32), table)
