"""Fused Pallas TPU sampling hop: PRNG + stratified positions + per-seed
window DMA + lane select in ONE kernel.

This is the TPU answer to the reference's warp sampling kernel
(``cuda_random.cu.hpp:8-69``): there, a warp serves one seed and its
coalesced loads ride the CSR window's contiguity.  Here, each seed's
contiguous ``indices[start, start+deg)`` window (<= ``U`` 128-lane rows)
is moved HBM->VMEM by ONE async copy — the coalesced unit on TPU — with
``SUB`` seeds' copies in flight per stage and double buffering across
stages.  The draws never leave VMEM until the final ``[B, k]`` payload:

  * the counter-hash uniforms (``ops/sample.py::_hash_uniform``) are
    re-derived in-kernel, op for op, from the same folded key words — so
    the kernel's draws are BITWISE IDENTICAL to the XLA hash path and
    every correctness test can compare exactly;
  * the stratified position formula is
    ``ops/sample.py::_stratified_positions``, reproduced exactly;
  * the select is a ``[SUB, kpad, 128]`` one-hot per window row — the
    same VPU cost XLA pays in ``ops/blockgather.py``, but with no
    ``[B, U*128]`` HBM intermediate (the blocked mode's block gather
    round-trips ~2x the window bytes through HBM; this kernel writes
    only the ``[B, 128]`` output row per seed).

Traffic per seed: ``U*512`` bytes in, 512 bytes out — vs the ``lanes``
mode's ``k*512`` in + ``k*512 * 2`` intermediate, and one DMA issue per
SEED instead of per DRAW (the per-element kernel's measured 26 ns/issue
bound, docs/TPU_MEASUREMENTS.md, divided by k).

Seeds whose window spans more than ``U`` rows are recomputed outside by
the compacted classic fallback (same policy/structure as
``ops/blockgather.py``); cap overflow falls back wholesale via
``lax.cond``.  Results are bitwise identical on every route.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["pallas_window_sample", "parse_pwindow"]

from ..blockgather import DEFAULT_U, FALLBACK_FRAC
from ..fastgather import LANES
# the kernel body re-derives the XLA hash path with the SAME finalizer
# and constants — imported, never copied, so they cannot diverge
from ..sample import HASH_PHI, _fmix32

SUB = 64      # seeds per stage = DMAs in flight per buffer
STAGES = 4    # stages per grid program (static unroll)
SPP = SUB * STAGES  # seeds per program
NBUF = 2      # double buffering


def parse_pwindow(mode: str) -> int:
    """``"pwindow"`` -> default U; ``"pwindow:4"`` -> 4."""
    from ..blockgather import parse_u_mode

    return parse_u_mode(mode, "pwindow", DEFAULT_U)


def _make_kernel(k: int, kpad: int, U: int):
    def kernel(r0c_ref, kw_ref, deg_ref, off_ref, table_ref, out_ref,
               win_ref, sem):
        # r0c_ref: SMEM [1, SPP] clipped covering-row starts (DMA addressing)
        # kw_ref:  SMEM [1, 2] folded key words (uint32)
        # deg_ref/off_ref: VMEM [SPP, 1] per-seed degree / in-block offset
        # table_ref: [R, 128] HBM (ANY); out_ref: VMEM [SPP, 128] block
        # win_ref: VMEM scratch [NBUF, SUB, U, 128]; sem: DMA [NBUF, SUB]
        pid = pl.program_id(0)
        k0 = kw_ref[0, 0]
        k1 = kw_ref[0, 1]

        def start_dmas(buf, st):
            base = st * SUB
            for e in range(SUB):
                pltpu.make_async_copy(
                    table_ref.at[pl.ds(r0c_ref[0, base + e], U)],
                    win_ref.at[buf, e],
                    sem.at[buf, e],
                ).start()

        def wait_dmas(buf, st):
            base = st * SUB
            for e in range(SUB):
                pltpu.make_async_copy(
                    table_ref.at[pl.ds(r0c_ref[0, base + e], U)],
                    win_ref.at[buf, e],
                    sem.at[buf, e],
                ).wait()

        start_dmas(0, 0)
        for st in range(STAGES):
            buf = st % NBUF
            if st + 1 < STAGES:
                start_dmas((st + 1) % NBUF, st + 1)

            # ---- in-kernel PRNG + positions (bitwise = the XLA hash path)
            deg = deg_ref[pl.ds(st * SUB, SUB), :]            # [SUB, 1] i32
            off = off_ref[pl.ds(st * SUB, SUB), :]            # [SUB, 1] i32
            e_iota = jax.lax.broadcasted_iota(jnp.uint32, (SUB, 1), 0)
            b = (pid.astype(jnp.uint32) * SPP
                 + jnp.uint32(st * SUB) + e_iota)              # [SUB, 1]
            j_iota = jax.lax.broadcasted_iota(jnp.int32, (1, kpad), 1)
            counter = b * jnp.uint32(k) + j_iota.astype(jnp.uint32)
            x = counter * jnp.uint32(HASH_PHI)
            x = _fmix32(x ^ k0)
            x = _fmix32(x ^ k1)
            # Mosaic has no uint32->f32 cast; x>>8 < 2^24 so the int32
            # detour is value-exact (bitwise = the XLA path's direct cast)
            u = ((x >> 8).astype(jnp.int32).astype(jnp.float32)
                 * jnp.float32(1.0 / (1 << 24)))
            degf = deg.astype(jnp.float32)                    # [SUB, 1]
            jf = j_iota.astype(jnp.float32)
            lo = jnp.floor(jf * degf / k)
            hi = jnp.floor((jf + 1) * degf / k)
            strat = lo + jnp.floor(u * jnp.maximum(hi - lo, 1.0))
            pos = jnp.where(deg <= k, j_iota, strat.astype(jnp.int32))
            pos = jnp.minimum(pos, jnp.maximum(deg - 1, 0))   # [SUB, kpad]
            rel = jnp.clip(off + pos, 0, U * LANES - 1)
            rel_row = rel >> 7
            rel_lane = rel & (LANES - 1)

            # ---- select from the DMA'd windows (one-hot per window row)
            wait_dmas(buf, st)
            lane_iota = jax.lax.broadcasted_iota(
                jnp.int32, (1, 1, LANES), 2)
            onehot = rel_lane[:, :, None] == lane_iota        # [SUB,kpad,128]
            vals = jnp.zeros((SUB, kpad), out_ref.dtype)
            for uu in range(U):
                wu = win_ref[buf, :, uu, :]                   # [SUB, 128]
                pick = jnp.where(
                    onehot & (rel_row[:, :, None] == uu),
                    wu[:, None, :], 0)
                vals = vals + jnp.sum(pick, axis=2)
            out_ref[st * SUB:(st + 1) * SUB, 0:kpad] = vals

    return kernel


@functools.partial(jax.jit,
                   static_argnames=("k", "U", "fallback_frac", "interpret"))
def pallas_window_sample(table2d: jax.Array, start: jax.Array,
                         deg: jax.Array, key: jax.Array, k: int,
                         U: int = DEFAULT_U,
                         fallback_frac: float = FALLBACK_FRAC,
                         interpret: bool = False) -> jax.Array:
    """One fused sampling hop: returns ``nbrs[b, j] =
    table.flat[start[b] + pos[b, j]]`` where ``pos`` is the stratified
    hash-RNG draw (``_stratified_positions`` of ``_hash_uniform(key,
    (B, k))``) — computed in-kernel for seeds whose window fits ``U``
    rows, by the identical XLA formula for the rest.

    ``table2d``: [R, 128] (128-padded flat table); ``start``/``deg``:
    [B] int32 window starts/lengths; ``key``: PRNG key (hash-folded).
    Rows where ``deg == 0`` return garbage (callers mask via counts).
    """
    from ..blockgather import _fit_split
    from ..fastgather import element_gather
    from ..sample import (_fold_key_words, _hash_uniform,
                          _stratified_positions)

    B = start.shape[0]
    R = table2d.shape[0]

    def classic(_=None):
        # the XLA route with identical draws — used for the early guards,
        # the cap-overflow wholesale fallback, and (compacted) the
        # non-fitting seeds, so every route stays bitwise equal
        u = _hash_uniform(key, (B, k))
        pos = _stratified_positions(u, deg, k)
        return element_gather(
            table2d, jnp.clip(start[:, None] + pos, 0, R * LANES - 1))

    if k > LANES or R < U:
        # fanout beyond one output row / table smaller than a window
        return classic()

    kpad = -(-k // 8) * 8  # next multiple of 8 (>= 8 for k >= 1)
    k0, k1 = _fold_key_words(key)
    r0, _fits, nfall, S, seed_of_slot, valid = _fit_split(
        start, deg, U, B, fallback_frac)
    r0c = jnp.clip(r0, 0, R - U)
    off = start - (r0c << 7)

    Bp = -(-B // SPP) * SPP
    padn = Bp - B
    padv = lambda a: (jnp.concatenate([a, jnp.zeros((padn,), a.dtype)])
                      if padn else a)
    r0c_p = padv(r0c).reshape(1, Bp)
    deg_p = padv(deg.astype(jnp.int32)).reshape(Bp, 1)
    off_p = padv(off).reshape(Bp, 1)
    kw = jnp.stack([k0, k1]).reshape(1, 2)

    def fused(_):
        out = pl.pallas_call(
            _make_kernel(k, kpad, U),
            grid=(Bp // SPP,),
            in_specs=[
                pl.BlockSpec((1, SPP), lambda i: (0, i),
                             memory_space=pltpu.SMEM),
                pl.BlockSpec((1, 2), lambda i: (0, 0),
                             memory_space=pltpu.SMEM),
                pl.BlockSpec((SPP, 1), lambda i: (i, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((SPP, 1), lambda i: (i, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec(memory_space=pl.ANY),
            ],
            out_specs=pl.BlockSpec((SPP, LANES), lambda i: (i, 0),
                                   memory_space=pltpu.VMEM),
            scratch_shapes=[
                pltpu.VMEM((NBUF, SUB, U, LANES), table2d.dtype),
                pltpu.SemaphoreType.DMA((NBUF, SUB)),
            ],
            out_shape=jax.ShapeDtypeStruct((Bp, LANES), table2d.dtype),
            interpret=interpret,
        )(r0c_p, kw, deg_p, off_p, table2d)
        vals = out[:B, :k]
        # non-fitting seeds: identical draws via the XLA formula, gathered
        # per element on the compacted slots (same policy as blockgather)
        u_all = _hash_uniform(key, (B, k))
        fb_start = jnp.where(valid, jnp.take(start, seed_of_slot), 0)
        fb_deg = jnp.where(valid, jnp.take(deg, seed_of_slot), 0)
        fb_pos = _stratified_positions(
            jnp.take(u_all, seed_of_slot, axis=0), fb_deg, k)
        fb_idx = jnp.clip(fb_start[:, None] + fb_pos, 0, R * LANES - 1)
        fb_vals = element_gather(table2d, fb_idx)
        return vals.at[jnp.where(valid, seed_of_slot, B)].set(
            fb_vals, mode="drop")

    return jax.lax.cond(nfall <= S, fused, classic, None)
