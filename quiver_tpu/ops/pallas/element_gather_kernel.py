"""Pallas TPU kernel: fused element gather (row-block + lane select).

The XLA formulation of the fast scalar gather (``ops/fastgather.py``)
materializes the gathered ``[M, 128]`` row blocks to HBM before the
one-hot lane reduction — 2x the necessary HBM traffic.  This kernel fuses
the two: each grid program loads its slice of indices (scalar prefetch),
row-gathers the covering 128-lane blocks HBM->VMEM via the XLA-level
prelude (done by the caller, streamed through the grid), and reduces to
one lane on the VPU before anything returns to HBM.

Layout: the caller supplies ``rows [M, 128]`` produced by ``jnp.take`` —
under jit the producer fuses INTO this pallas_call's input stream (XLA
pipelines HBM->VMEM block loads), so the intermediate never lands in HBM
as a whole.  The kernel itself is just the masked lane reduction, which is
exactly the part XLA's gather emitter refuses to fuse.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["lane_select"]

BLK = 1024  # indices per grid program


def _kernel(lane_ref, rows_ref, out_ref):
    lanes = lane_ref[:]                       # [BLK, 1] int32
    rows = rows_ref[:]                        # [BLK, 128]
    iota = jax.lax.broadcasted_iota(jnp.int32, rows.shape, 1)
    onehot = iota == lanes
    out_ref[:] = jnp.sum(
        jnp.where(onehot, rows, 0), axis=1, keepdims=True
    )


@functools.partial(jax.jit, static_argnames=("interpret",))
def lane_select(rows: jax.Array, lanes: jax.Array,
                interpret: bool = False) -> jax.Array:
    """``out[i] = rows[i, lanes[i]]`` — fused VPU lane reduction.

    ``rows``: [M, 128]; ``lanes``: [M] int32.  M must be a multiple of
    BLK (pad + slice at the call site).
    """
    m = rows.shape[0]
    assert m % BLK == 0, m
    out = pl.pallas_call(
        _kernel,
        grid=(m // BLK,),
        in_specs=[
            pl.BlockSpec((BLK, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((BLK, 128), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((BLK, 1), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((m, 1), rows.dtype),
        interpret=interpret,
    )(lanes.reshape(m, 1).astype(jnp.int32), rows)
    return out.reshape(m)
