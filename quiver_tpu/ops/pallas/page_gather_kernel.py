"""Pallas TPU kernel: ragged page-granularity feature gather.

The data-layer application of the Ragged Paged Attention design
(PAPERS.md, arxiv 2604.15464): feature rows live in fixed-size HBM
pages (``page_rows`` x row-bytes, sized to a multiple of the 512B HBM
transaction), and one kernel gathers a variable-length frontier by
walking ``(page, offset)`` pairs — whole-page DMAs instead of the
per-element transfers that leave both XLA's element gather and the
per-row-DMA kernel transaction-bound (BENCH_r05 ``micro_gather``:
~26 ms/1M elems for either).

Contract with the host-side planner (``ops/paged.py``):

  * the frontier is sorted by frame id, so each output block touches a
    *run* of pages; the planner emits, per block, the distinct frames
    the block needs (``blk_pages``, first-appearance order, at most
    ``ppb`` of them) and per row the block-local page index + in-page
    offset (``row_lp`` / ``row_off``);
  * the kernel DMAs each distinct page HBM->VMEM once (``NBUF``
    copies in flight), then serves every row of the block from VMEM —
    rows are VPU copies, transactions are page-sized;
  * padded rows (``B`` up to a multiple of ``block``; linear padding,
    never pow2) carry ``row_lp = row_off = 0`` — they read page slot 0
    of the scratch and are dropped by the caller's inverse-permutation
    take, so they can never read past a staged buffer.

Interpret mode (``interpret=True``) runs the same kernel logic on CPU;
tier-1 tests exercise exactly this path (no separate jnp re-
implementation to drift from the kernel).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["page_gather", "NBUF"]

NBUF = 4  # outstanding page DMAs per program


def _kernel(blk_pages_ref, blk_np_ref, row_lp_ref, row_off_ref,
            frames_ref, out_ref, scratch, sem, *, page_rows, ppb):
    blk = out_ref.shape[0]
    b = pl.program_id(0)
    n_pages = blk_np_ref[b]

    def page_dma(slot, k):
        # one whole page: frames[frame_id] -> scratch rows [k*R, (k+1)*R)
        return pltpu.make_async_copy(
            frames_ref.at[blk_pages_ref[b * ppb + k]],
            scratch.at[pl.ds(k * page_rows, page_rows)],
            sem.at[slot],
        )

    # warm-up: fill the DMA pipeline
    for w in range(NBUF):
        @pl.when(w < n_pages)
        def _(w=w):
            page_dma(w, w).start()

    def dma_body(k, _):
        # wait k FIRST: its semaphore slot (k % NBUF) is reused by DMA
        # k+NBUF, so the slot must drain before the next start
        page_dma(k % NBUF, k).wait()

        @pl.when(k + NBUF < n_pages)
        def _():
            page_dma((k + NBUF) % NBUF, k + NBUF).start()

        return 0

    jax.lax.fori_loop(0, n_pages, dma_body, 0)

    base = b * blk

    def row_body(i, _):
        # block-local page index + in-page offset -> one scratch row
        lp = row_lp_ref[base + i]
        off = row_off_ref[base + i]
        row = scratch[pl.ds(lp * page_rows + off, 1), :]
        out_ref[pl.ds(i, 1), :] = row
        return 0

    jax.lax.fori_loop(0, blk, row_body, 0)


@functools.partial(jax.jit,
                   static_argnames=("page_rows", "block", "ppb",
                                    "interpret"))
def page_gather(frames: jax.Array, blk_pages: jax.Array,
                blk_np: jax.Array, row_lp: jax.Array,
                row_off: jax.Array, *, page_rows: int, block: int,
                ppb: int, interpret: bool = False) -> jax.Array:
    """Gather ``M`` rows (M = len(row_lp), M % block == 0) out of paged
    ``frames [F, page_rows, D]``.

    Args:
      frames: the device frame pool (DEVICE pages + OVERLAY pool).
      blk_pages: ``[nb * ppb]`` int32 — per block, the distinct frame
        ids it reads (first-appearance order, padded with 0).
      blk_np: ``[nb]`` int32 — how many of each block's ``ppb`` entries
        are real.
      row_lp: ``[M]`` int32 — per row, index into its block's
        ``blk_pages`` entries.
      row_off: ``[M]`` int32 — per row, offset within its page.
      page_rows / block / ppb: static geometry (rows per page, output
        rows per grid program, max distinct pages per block).
    """
    m = row_lp.shape[0]
    assert m % block == 0, (m, block)
    d = frames.shape[2]
    nb = m // block
    return pl.pallas_call(
        functools.partial(_kernel, page_rows=page_rows, ppb=ppb),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=4,
            grid=(nb,),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec(
                (block, d), lambda i, *refs: (i, 0),
                memory_space=pltpu.VMEM,
            ),
            scratch_shapes=[
                pltpu.VMEM((ppb * page_rows, d), frames.dtype),
                pltpu.SemaphoreType.DMA((NBUF,)),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((m, d), frames.dtype),
        interpret=interpret,
    )(blk_pages.astype(jnp.int32), blk_np.astype(jnp.int32),
      row_lp.astype(jnp.int32), row_off.astype(jnp.int32), frames)
