"""Pallas TPU kernels (hot-path variants of the XLA ops).

* ``gather_kernel`` — DMA row gather over a budgeted feature table.
* ``element_gather_kernel`` — per-element DMA gather (BENCH_r05 probe).
* ``sample_gather_kernel`` / ``window_sample_kernel`` — fused PRNG +
  per-seed window DMA + lane select for sampling.
* ``page_gather_kernel`` — ragged whole-page gather for the paged
  feature store (``ops/paged.py``): pipelined page DMA, no pow2
  padding, one executable per batch size.

All kernels carry an ``interpret=`` escape hatch so CPU CI executes
the exact kernel logic under the Pallas interpreter.
"""
