"""Pallas TPU kernels (hot-path variants of the XLA ops)."""
