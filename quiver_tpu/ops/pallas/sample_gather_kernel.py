"""Pallas TPU kernel: fully-fused scalar gather with per-element row DMA.

The sampling hop's bottleneck op is ``table[idx]`` for huge 1-D ``table``
(indptr/indices) and ~10^4 scattered ``idx``.  The three formulations:

  * XLA gather: serialized dynamic-slice loop — latency-bound, slow.
  * ``lanes`` (ops/fastgather.py): row-gather ``[M, 128]`` blocks to HBM,
    then lane-select — near-bandwidth but moves 128x the payload TWICE
    (write + read of the intermediate).
  * **this kernel**: each element's covering 128-lane row is DMA'd
    HBM->VMEM directly (double-buffered groups of 128 outstanding copies,
    the CUDA-warp-per-element analogue of ``cuda_random.cu.hpp:8-69``'s
    coalesced loads), lane-selected on the VPU, and only the ``[M]``
    payload ever returns to HBM.  128x less HBM write traffic than lanes.

Used by ``gather_mode="pallas"`` in the samplers; falls back to lanes on
backends without mosaic support.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["pallas_element_gather"]

LANES = 128
GROUP = 128   # rows DMA'd per pipeline stage ([GROUP, 128] VMEM scratch)
NBUF = 2      # double buffering
GPB = 8       # groups per grid program -> BLOCK elements per program
BLOCK = GPB * GROUP


def _kernel(row_ref, lane_ref, table_ref, out_ref, rows_ref, sem):
    # row_ref: [1, GPB, GROUP] int32 covering-row ids — a per-program SMEM
    #   block (NOT whole-array scalar prefetch: at hop-3 index counts the
    #   full array is ~3.6 MB, 3.5x the 1 MB SMEM — measured OOM on v5e;
    #   3-D because Mosaic requires the trailing block dims be (8k, 128k))
    # lane_ref/out_ref: [GPB, GROUP] int32 VMEM blocks
    # table_ref: [R, 128] in HBM (ANY)
    # rows_ref: [NBUF, GROUP, 128] scratch; sem: [NBUF, GROUP] DMA sems

    def copies(buf, g):
        return [
            pltpu.make_async_copy(
                table_ref.at[row_ref[0, g, e]],
                rows_ref.at[buf, e],
                sem.at[buf, e],
            )
            for e in range(GROUP)
        ]

    for c in copies(0, 0):
        c.start()
    for g in range(GPB):  # static unroll: buffers/slices all literal
        buf = g % NBUF
        if g + 1 < GPB:
            for c in copies((g + 1) % NBUF, g + 1):
                c.start()
        for c in copies(buf, g):
            c.wait()
        rows = rows_ref[buf]                       # [GROUP, 128]
        lanes = lane_ref[g][:, None]               # [GROUP, 1]
        iota = jax.lax.broadcasted_iota(jnp.int32, rows.shape, 1)
        out_ref[g] = jnp.sum(jnp.where(iota == lanes, rows, 0), axis=1)


@functools.partial(jax.jit, static_argnames=("interpret",))
def pallas_element_gather(table2d: jax.Array, idx: jax.Array,
                          interpret: bool = False) -> jax.Array:
    """``table2d.reshape(-1)[idx]`` — fused row-DMA + lane-select.

    ``table2d``: [R, 128] (``fastgather.prepare_table``); ``idx``: any
    shape of flat element indices (< R*128).  Pads internally to BLOCK.
    """
    shape = idx.shape
    flat = idx.reshape(-1).astype(jnp.int32)
    m = flat.shape[0]
    mp = -(-m // BLOCK) * BLOCK
    if mp != m:
        flat = jnp.concatenate(
            [flat, jnp.zeros((mp - m,), jnp.int32)]
        )
    row = jax.lax.shift_right_logical(flat, 7).reshape(-1, GPB, GROUP)
    lane = jnp.bitwise_and(flat, LANES - 1).reshape(-1, GROUP)
    out = pl.pallas_call(
        _kernel,
        grid=(mp // BLOCK,),
        in_specs=[
            pl.BlockSpec((1, GPB, GROUP), lambda i: (i, 0, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((GPB, GROUP), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.ANY),
        ],
        out_specs=pl.BlockSpec((GPB, GROUP), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((NBUF, GROUP, LANES), table2d.dtype),
            pltpu.SemaphoreType.DMA((NBUF, GROUP)),
        ],
        out_shape=jax.ShapeDtypeStruct((mp // GROUP, GROUP),
                                       table2d.dtype),
        interpret=interpret,
    )(row, lane, table2d)
    return out.reshape(-1)[:m].reshape(shape)
