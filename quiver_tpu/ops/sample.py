"""Neighbor sampling ops — pure-XLA dense formulation.

Reference parity: the warp-per-row reservoir kernel
``srcs/cpp/include/quiver/cuda_random.cu.hpp:8-69`` and the 2-tensor
``sample_neighbor`` contract of ``quiver_sample.cu:113-191``.

TPU-first redesign: instead of ragged (flat neighbors + per-seed counts +
prefix sums), every op returns **dense ``[B, k]`` neighbor blocks with a
validity mask**.  Static shapes let XLA fuse the whole hop into a couple of
gathers; the mask replaces the CUDA prefix-sum/compaction step.  Downstream
(models, gather) consume the dense form natively; a ragged view is available
via :func:`to_ragged` for API parity.

Without-replacement sampling: the CUDA kernel does reservoir sampling.  On
TPU we use **stratified positions** — neighbor slot ``j`` draws uniformly
from window ``[floor(j*deg/k), floor((j+1)*deg/k))``.  For ``deg > k`` the
windows are disjoint and non-empty, so the k draws are distinct.  Marginals:
an element's inclusion probability is ``1/|window|`` with window sizes
``floor(deg/k)`` or ``ceil(deg/k)`` — exactly ``k/deg`` when ``k | deg``,
within a ``±k/deg`` relative factor otherwise (vs exact-uniform reservoir);
CSR neighbor order is arbitrary, so the tiny position-correlated bias has
no graph-semantic alignment.  No hash table, no atomics, no sequential
loop.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

__all__ = ["sample_neighbors", "sample_neighbors_overlay", "SampleOut",
           "to_ragged"]


class SampleOut(NamedTuple):
    """Dense one-hop sample: ``nbrs[b, j]`` valid where ``mask[b, j]``."""

    nbrs: jax.Array   # [B, k] int32 global neighbor ids (garbage where ~mask)
    mask: jax.Array   # [B, k] bool
    counts: jax.Array  # [B] int32 = min(degree, k), 0 for invalid seeds
    eid: Optional[jax.Array] = None  # [B, k] int32 global edge positions


# counter-hash constants — single source for the XLA path AND the fused
# Pallas window kernel, whose bitwise-identical-draws contract rests on
# never letting these diverge (ops/pallas/window_sample_kernel.py)
HASH_PHI = 0x9E3779B9    # Weyl increment (golden-ratio word)
HASH_MUL1 = 0x85EBCA6B   # murmur3 finalizer multipliers
HASH_MUL2 = 0xC2B2AE35


def _fmix32(x: jax.Array) -> jax.Array:
    """murmur3 32-bit finalizer: full avalanche (every input bit flips
    every output bit with ~1/2 probability).  Plain jnp elementwise ops —
    legal both under jit and inside a Pallas kernel body."""
    x = (x ^ (x >> 16)) * jnp.uint32(HASH_MUL1)
    x = (x ^ (x >> 13)) * jnp.uint32(HASH_MUL2)
    return x ^ (x >> 16)


def _fold_key_words(key: jax.Array):
    """Fold arbitrary-width PRNG key data into two 32-bit words via a
    POSITION-SENSITIVE multiplicative chain (a plain XOR fold would
    collapse word permutations of 4-word keys — rbg impls — onto one
    stream); threefry's two words enter order-distinguished too.

    Shared by :func:`_hash_uniform` and the fused Pallas window-sampling
    kernel (``ops/pallas/window_sample_kernel.py``), which reproduces the
    same uniforms in-kernel."""
    data = jax.random.key_data(key).astype(jnp.uint32).reshape(-1)
    k0 = jnp.uint32(0)
    k1 = jnp.uint32(HASH_PHI)
    for i, w in enumerate(data):
        k0 = (k0 ^ w) * jnp.uint32(HASH_MUL1) + jnp.uint32(i + 1)
        k1 = ((k1 + w) * jnp.uint32(HASH_MUL2)) ^ jnp.uint32(
            ((i + 1) * HASH_PHI) & 0xFFFFFFFF)
    return k0, k1


def _hash_uniform(key: jax.Array, shape) -> jax.Array:
    """Counter-based uniforms from a keyed integer hash — compiles to
    ~15 elementwise VPU ops, no RNG algorithm HLO at all.

    Escape hatch for backends where even the hardware-RNG lowering is
    slow to compile (``sample_rng="hash"``); statistical quality is ample
    for neighbor subsampling (the reference's curand Philox is likewise a
    counter hash, just with more rounds — ``cuda_random.cu.hpp:12-20``).

    Keying: the FULL key (both 32-bit words of a threefry key; folded
    words of wider impls) is injected between full-avalanche finalizer
    rounds, never as an additive counter offset — so two distinct keys
    produce structurally unrelated streams.  (The round-2 scheme offset
    ONE shared 2^32 counter stream by a 32-bit fold of the key; keys
    whose offsets landed near each other replayed identical uniform
    segments at shifted positions.  Cross-key tests:
    ``tests/test_sample.py::TestHashUniformCrossKey``.)
    """
    k0, k1 = _fold_key_words(key)
    n = 1
    for s in shape:
        n *= s
    # Weyl-spread counter, then key words between avalanche rounds
    x = jax.lax.iota(jnp.uint32, n).reshape(shape) * jnp.uint32(HASH_PHI)
    x = _fmix32(x ^ k0)
    x = _fmix32(x ^ k1)
    # 24-bit mantissa -> [0, 1)
    return (x >> 8).astype(jnp.float32) * jnp.float32(1.0 / (1 << 24))


def _uniform(key, shape, impl: str):
    if impl == "hash":
        return _hash_uniform(key, shape)
    return jax.random.uniform(key, shape, dtype=jnp.float32)


def _stratified_positions(u: jax.Array, deg: jax.Array, k: int) -> jax.Array:
    """In-window draw positions ``[B, k]`` from uniforms ``u`` — neighbor
    slot ``j`` draws from stratum ``[floor(j*deg/k), floor((j+1)*deg/k))``
    (distinct windows for ``deg > k``, identity for ``deg <= k``).

    Single source of truth for the position math: the XLA samplers and the
    fused Pallas window kernel (which re-derives the same expressions
    in-kernel, op for op, so its draws are bitwise identical) both follow
    this formula."""
    j = jnp.arange(k, dtype=jnp.int32)[None, :]              # [1, k]
    degf = deg.astype(jnp.float32)[:, None]                  # [B, 1]
    # Stratum bounds computed in float to avoid an int64 multiply;
    # deg < 2^24 holds for any real graph's max degree.
    lo = jnp.floor(j.astype(jnp.float32) * degf / k)
    hi = jnp.floor((j + 1).astype(jnp.float32) * degf / k)
    strat = lo + jnp.floor(u * jnp.maximum(hi - lo, 1.0))
    pos = jnp.where(deg[:, None] <= k, j, strat.astype(jnp.int32))
    return jnp.minimum(pos.astype(jnp.int32),
                       jnp.maximum(deg[:, None] - 1, 0))


def _gather(table: jax.Array, idx: jax.Array, mode: str) -> jax.Array:
    """Element gather dispatch: 'xla' = jnp.take (clipped); 'lanes' = the
    row-gather + lane-select path (``ops.fastgather``) that sidesteps XLA's
    serialized 1-D scalar gather on TPU.  Requires the table to be padded
    to a multiple of 128 (``CSRTopo.to_device`` guarantees it).

    'blocked*'/'pwindow*' apply only to the per-seed WINDOW gathers inside
    the samplers (``ops.blockgather`` / the fused Pallas window kernel);
    scattered [B] element gathers (the indptr reads) ride the lanes path
    under them — per-element DMA of indptr rows is the measured-losing
    pattern (docs/TPU_MEASUREMENTS.md: 26 ms/1M, issue-latency bound)."""
    if mode.startswith("blocked") or mode.startswith("pwindow"):
        mode = "lanes"
    if mode in ("lanes", "lanes_fused"):
        from .fastgather import element_gather

        assert table.shape[0] % 128 == 0, (
            f"lanes gather needs a 128-multiple table, got "
            f"{table.shape[0]} — pad with ops.fastgather.pad_table_128 "
            f"(CSRTopo.to_device / the samplers do this for you)"
        )
        m = table.shape[0]
        return element_gather(
            table[:m].reshape(-1, 128),
            jnp.clip(idx, 0, m - 1),
            fused=(mode == "lanes_fused"),
        )
    if mode == "pallas":
        from .pallas.sample_gather_kernel import pallas_element_gather

        assert table.shape[0] % 128 == 0, (
            f"pallas gather needs a 128-multiple table, got "
            f"{table.shape[0]} — pad with ops.fastgather.pad_table_128"
        )
        m = table.shape[0]
        return pallas_element_gather(
            table[:m].reshape(-1, 128), jnp.clip(idx, 0, m - 1)
        )
    return jnp.take(table, idx, mode="clip")


@functools.partial(jax.jit, static_argnames=("k", "gather_mode",
                                             "sample_rng"))
def sample_neighbors(
    indptr: jax.Array,
    indices: jax.Array,
    seeds: jax.Array,
    k: int,
    key: jax.Array,
    seed_mask: Optional[jax.Array] = None,
    gather_mode: str = "xla",
    sample_rng: str = "auto",
) -> SampleOut:
    """Sample up to ``k`` distinct neighbors per seed from a CSR graph.

    Args:
      indptr: ``[N+1]`` int32 CSR row pointers (device-resident).
      indices: ``[E]`` int32 CSR column indices.
      seeds: ``[B]`` int32 node ids.  Entries where ``seed_mask`` is False
        are treated as degree-0 (used for padded frontiers).
      k: fanout (static).
      key: PRNG key.
      seed_mask: optional ``[B]`` bool validity of seeds.

    Behavioral contract (vs ``cuda_random.cu.hpp:8-69``):
      * ``deg <= k``: all neighbors returned, in CSR order.
      * ``deg > k``: k distinct neighbors, inclusion probability k/deg each.
    """
    seeds = seeds.astype(jnp.int32)
    B = seeds.shape[0]
    start = _gather(indptr, seeds, gather_mode)
    end = _gather(indptr, seeds + 1, gather_mode)
    deg = end - start
    if seed_mask is not None:
        deg = jnp.where(seed_mask, deg, 0)
    counts = jnp.minimum(deg, k).astype(jnp.int32)

    j = jnp.arange(k, dtype=jnp.int32)[None, :]              # [1, k]
    u = _uniform(key, (B, k), sample_rng)
    pos = _stratified_positions(u, deg, k)

    mask = j < counts[:, None]
    idx = start[:, None] + pos
    if gather_mode.startswith("pwindow"):
        # fully-fused Pallas hop: PRNG + positions + window DMA + select
        # in one kernel — pos above survives only as the eid formula
        # (dead-code-eliminated when eid is unused downstream)
        from .pallas.window_sample_kernel import (pallas_window_sample,
                                                  parse_pwindow)

        backend = jax.default_backend()
        if backend not in ("tpu", "cpu"):
            # fail before Mosaic lowering produces an opaque XLA error —
            # pwindow is TPU-only (CPU rides pallas interpret mode)
            raise ValueError(
                f"gather_mode='pwindow' needs backend 'tpu' (Mosaic "
                f"kernel) or 'cpu' (interpret mode); running on "
                f"{backend!r} — use the XLA 'blocked:U' window mode "
                "there instead")
        assert indices.shape[0] % 128 == 0, (
            f"pwindow gather needs a 128-multiple indices table, got "
            f"{indices.shape[0]} — pad with ops.fastgather.pad_table_128"
        )
        if sample_rng != "hash":
            raise ValueError(
                "gather_mode='pwindow' fuses the counter-hash RNG "
                "in-kernel and requires sample_rng='hash' (the "
                "accelerator default); got sample_rng="
                f"{sample_rng!r}")
        nbrs = pallas_window_sample(
            indices.reshape(-1, 128), start, deg, key, k,
            U=parse_pwindow(gather_mode),
            # mosaic needs a real TPU; CPU runs ride interpret mode so
            # rehearsals and the virtual-mesh dryrun execute the same code
            interpret=jax.default_backend() == "cpu")
    elif gather_mode.startswith("blocked"):
        from .blockgather import blocked_window_gather, parse_blocked

        assert indices.shape[0] % 128 == 0, (
            f"blocked gather needs a 128-multiple indices table, got "
            f"{indices.shape[0]} — pad with ops.fastgather.pad_table_128"
        )
        nbrs = blocked_window_gather(
            indices.reshape(-1, 128), start, deg, pos,
            U=parse_blocked(gather_mode))
    else:
        nbrs = _gather(indices, idx, gather_mode)
    nbrs = jnp.where(mask, nbrs, jnp.int32(-1))
    # global edge positions of the draws: index into CSRTopo.eid / edge-
    # feature arrays.  The reference's CSR carries edge ids for the same
    # purpose (quiver.cu.hpp eid); PyG's Adj e_id slot can be filled from
    # this instead of the reference's empty tensor (sage_sampler.py:143).
    eid = jnp.where(mask, idx, jnp.int32(-1))
    return SampleOut(nbrs=nbrs, mask=mask, counts=counts, eid=eid)


@functools.partial(jax.jit, static_argnames=("k", "gather_mode",
                                             "sample_rng", "windowed"))
def sample_neighbors_overlay(
    indptr: jax.Array,
    indices: jax.Array,
    tomb: jax.Array,
    d_indptr: jax.Array,
    d_indices: jax.Array,
    seeds: jax.Array,
    k: int,
    key: jax.Array,
    seed_mask: Optional[jax.Array] = None,
    base_ts: Optional[jax.Array] = None,
    d_ts: Optional[jax.Array] = None,
    window_lo: Optional[jax.Array] = None,
    window_hi: Optional[jax.Array] = None,
    gather_mode: str = "xla",
    sample_rng: str = "auto",
    windowed: bool = False,
) -> SampleOut:
    """One-hop sampling over a base CSR **plus a delta-CSR overlay**.

    The streaming tier (``quiver_tpu.stream``) layers pending edge
    insertions (an append-only segment re-CSR'd per snapshot) and
    deletions (a tombstone table over base edge positions) on the frozen
    CSR.  This op draws from the **combined** neighborhood: a seed's
    degree is ``base_deg + delta_deg`` and the stratified positions index
    the virtual concatenation ``[base neighbors | delta neighbors]`` —
    identical position math to :func:`sample_neighbors`, so with zero
    deltas and no tombstones the outputs are bitwise identical to the
    frozen path (the equivalence contract ``tests/test_stream.py``
    enforces).

    Deletion/window semantics are **rejection, not resampling**: a draw
    landing on a tombstoned base edge (``tomb[pos] != 0``) or outside the
    half-open timestamp window ``[window_lo, window_hi)`` is masked out,
    so rows with many pending deletes can return fewer than
    ``min(deg, k)`` neighbors until the compactor folds the deltas in.
    That keeps the op one fused pass (no data-dependent second draw — a
    retrace/perf hazard); the compactor restores exact fanout.

    Args (beyond :func:`sample_neighbors`):
      tomb: ``[E_pad]`` int32, nonzero = base edge position deleted.
      d_indptr / d_indices: delta CSR over the same node-id space;
        ``d_indices`` is padded to the snapshot's pow2 fanout bucket so
        executable keys stay additive (coldcache discipline).
      base_ts / d_ts: optional ``[E_pad]`` int32 per-edge timestamps
        (required when ``windowed``).
      window_lo / window_hi: traced int32 scalars — changing the window
        does NOT retrace; only ``windowed`` (filter on/off) is static.

    Delta draws report ``eid = indices.shape[0] + delta_pos`` so edge ids
    stay unambiguous across the two segments.
    """
    seeds = seeds.astype(jnp.int32)
    B = seeds.shape[0]
    start = _gather(indptr, seeds, gather_mode)
    end = _gather(indptr, seeds + 1, gather_mode)
    bdeg = end - start
    dstart = _gather(d_indptr, seeds, gather_mode)
    dend = _gather(d_indptr, seeds + 1, gather_mode)
    ddeg = dend - dstart
    if seed_mask is not None:
        bdeg = jnp.where(seed_mask, bdeg, 0)
        ddeg = jnp.where(seed_mask, ddeg, 0)
    deg = bdeg + ddeg

    j = jnp.arange(k, dtype=jnp.int32)[None, :]              # [1, k]
    u = _uniform(key, (B, k), sample_rng)
    pos = _stratified_positions(u, deg, k)

    # position < base_deg draws from the base segment, the rest from the
    # delta segment (both index expressions clipped so the untaken side
    # of the select still gathers in-bounds)
    in_base = pos < bdeg[:, None]
    bidx = start[:, None] + jnp.minimum(
        pos, jnp.maximum(bdeg[:, None] - 1, 0))
    dpos = jnp.maximum(pos - bdeg[:, None], 0)
    didx = dstart[:, None] + dpos
    nbrs = jnp.where(
        in_base,
        _gather(indices, bidx, gather_mode),
        _gather(d_indices, didx, gather_mode),
    )
    live = jnp.where(
        in_base, _gather(tomb, bidx, gather_mode) == 0, True)
    if windowed:
        ets = jnp.where(
            in_base,
            _gather(base_ts, bidx, gather_mode),
            _gather(d_ts, didx, gather_mode),
        )
        live = live & (ets >= window_lo) & (ets < window_hi)
    mask = (j < jnp.minimum(deg, k)[:, None]) & live
    counts = mask.sum(axis=1).astype(jnp.int32)
    nbrs = jnp.where(mask, nbrs, jnp.int32(-1))
    eid = jnp.where(
        mask,
        jnp.where(in_base, bidx, jnp.int32(indices.shape[0]) + didx),
        jnp.int32(-1),
    )
    return SampleOut(nbrs=nbrs, mask=mask, counts=counts, eid=eid)


@functools.partial(jax.jit, static_argnames=("k", "bits", "sample_rng",
                                              "gather_mode"))
def sample_neighbors_weighted(
    indptr: jax.Array,
    indices: jax.Array,
    cum_weights: jax.Array,
    seeds: jax.Array,
    k: int,
    key: jax.Array,
    seed_mask: Optional[jax.Array] = None,
    bits: int = 24,
    sample_rng: str = "auto",
    gather_mode: str = "xla",
) -> SampleOut:
    """Weight-proportional neighbor sampling (WITH replacement).

    Parity: the reference's ``weight_sample`` path
    (``cuda_random.cu.hpp:149-221`` — thrust discrete-distribution draws
    per row).  TPU formulation: ``cum_weights[e]`` is the inclusive
    per-row cumulative weight (host-precomputed once via
    :func:`row_cumsum_weights`); each draw inverts the row CDF with a
    fixed-depth binary search (``bits`` iterations of clipped gathers —
    data-independent control flow, so XLA unrolls it).

    ``deg <= k`` rows return all neighbors once (mask semantics identical
    to :func:`sample_neighbors`).
    """
    seeds = seeds.astype(jnp.int32)
    B = seeds.shape[0]
    start = _gather(indptr, seeds, gather_mode)
    end = _gather(indptr, seeds + 1, gather_mode)
    deg = end - start
    if seed_mask is not None:
        deg = jnp.where(seed_mask, deg, 0)
    counts = jnp.minimum(deg, k).astype(jnp.int32)
    j = jnp.arange(k, dtype=jnp.int32)[None, :]
    mask = j < counts[:, None]

    # total row weight = cum_weights[end-1] (inclusive cumsum per row)
    total = jnp.where(
        deg > 0,
        _gather(cum_weights, jnp.maximum(end - 1, 0), gather_mode),
        0.0,
    )
    u = _uniform(key, (B, k), sample_rng) * total[:, None]

    if gather_mode.startswith("blocked"):
        # CDF inversion AND the neighbor reads both live in the seed's
        # contiguous window: one block gather + one VPU pass replaces the
        # ``bits``-round binary search of element gathers (ops.blockgather)
        from .blockgather import (blocked_weighted_positions,
                                  blocked_window_gather, parse_blocked)

        assert (cum_weights.shape[0] % 128 == 0
                and indices.shape[0] % 128 == 0), (
            "blocked gather needs 128-multiple tables — pad with "
            "ops.fastgather.pad_table_128"
        )
        U = parse_blocked(gather_mode)
        posl = blocked_weighted_positions(
            cum_weights.reshape(-1, 128), start, deg, u, U=U, bits=bits)
        # deg <= k: take all neighbors once instead of resampling
        posl = jnp.where(deg[:, None] <= k, j, posl)
        posl = jnp.minimum(posl, jnp.maximum(deg[:, None] - 1, 0))
        pos = start[:, None] + posl
        nbrs = blocked_window_gather(indices.reshape(-1, 128), start, deg,
                                     jnp.where(mask, posl, 0), U=U)
    else:
        # binary search for first position p in [start, end) with cw[p] > u
        lo = jnp.broadcast_to(start[:, None], (B, k))
        hi = jnp.broadcast_to(end[:, None], (B, k))

        def step(_, lohi):
            # the gather here runs ``bits`` times — with gather_mode="lanes"
            # each round is a near-bandwidth row gather instead of XLA's
            # serialized 1-D scalar gather (the dominant cost on TPU)
            lo, hi = lohi
            mid = (lo + hi) // 2
            cw = _gather(cum_weights, mid, gather_mode)
            gt = cw > u
            return jnp.where(gt, lo, mid + 1), jnp.where(gt, mid, hi)

        lo, hi = jax.lax.fori_loop(0, bits, step, (lo, hi))
        pos = jnp.clip(lo, start[:, None], jnp.maximum(end[:, None] - 1, 0))
        # deg <= k: take all neighbors once instead of resampling
        pos = jnp.where(deg[:, None] <= k, start[:, None] + j, pos)
        nbrs = _gather(indices, jnp.where(mask, pos, 0), gather_mode)
    nbrs = jnp.where(mask, nbrs, jnp.int32(-1))
    eid = jnp.where(mask, pos, jnp.int32(-1))
    return SampleOut(nbrs=nbrs, mask=mask, counts=counts, eid=eid)


def row_cumsum_weights(indptr, weights):
    """Host-side per-row inclusive cumulative weights for
    :func:`sample_neighbors_weighted`.  One pass at graph-build time."""
    import numpy as np

    indptr = np.asarray(indptr)
    # Accumulate in float64: a global float32 cumsum over E~1e8 edges has
    # ulp larger than typical per-edge weights, so late rows would get
    # quantized/zeroed relative weights.  Per-row totals are small, so the
    # final per-row float32 cast is safe.
    w = np.asarray(weights, dtype=np.float64)
    cw = np.cumsum(w)
    # subtract the cumsum value just before each row start
    prev = np.concatenate([[0.0], cw])[indptr[:-1]]
    out = cw - np.repeat(prev, np.diff(indptr))
    return out.astype(np.float32)


def to_ragged(out: SampleOut) -> Tuple[jax.Array, jax.Array]:
    """Dense ``[B, k]`` -> reference 2-tensor form (flat neighbors, counts).

    Matches ``TorchQuiver::sample_neighbor``'s return contract
    (``quiver_sample.cu:113-132``): neighbors of seed b occupy
    ``flat[offset[b] : offset[b] + counts[b]]``.  Host-side utility (uses a
    compaction scatter); not on the jit hot path.
    """
    nbrs = jnp.where(out.mask, out.nbrs, 0)
    counts = out.counts
    offsets = jnp.concatenate(
        [jnp.zeros(1, jnp.int32), jnp.cumsum(counts)[:-1].astype(jnp.int32)]
    )
    # quiverlint: sync-ok[ragged export is a host boundary by contract]
    total = int(counts.sum())
    flat_pos = offsets[:, None] + jnp.cumsum(out.mask, axis=1) - 1
    flat = jnp.zeros(total, dtype=jnp.int32)
    flat = flat.at[jnp.where(out.mask, flat_pos, total)].set(
        nbrs, mode="drop"
    )
    return flat, counts
