"""Paged feature store: page table, residency states, fault planner.

ROADMAP item 2: the Ragged Paged Attention design (PAPERS.md, arxiv
2604.15464) applied to the data layer.  Feature rows are packed into
fixed-size HBM pages (``page_rows`` x row-bytes, a multiple of the 512B
HBM transaction) and the three storage tiers of the staged merge — hot
prefix, coldcache overlay, host tail — collapse into **page residency
states** over one frame pool:

  * ``DEVICE`` — pages of the degree-ordered hot prefix; pinned
    resident at frames ``[0, hot_pages)``, never evicted.
  * ``OVERLAY`` — host pages currently faulted into the overlay pool
    (frames ``[hot_pages, hot_pages + pool)``); CLOCK-evicted.
  * ``HOST`` — pages resident only in the host tail; a gather touching
    one faults the whole page in as part of the batch's single H2D
    transfer.

One ragged Pallas kernel (``ops/pallas/page_gather_kernel.py``) then
gathers any frontier by walking ``(page, offset)`` pairs with
page-granularity DMA — no pow2 padding, no quarter-octave
``_fresh_bucket`` machinery, and ONE executable per batch size instead
of the staged path's additive ``(B, bucket)`` x ``("z"/"patch", bc/bh)``
grid.

Division of labor (mirrors ``ops/coldcache.py``):

  * **this module** — host-side planning: id -> (frame, offset)
    translation, fault detection, page-table bookkeeping (a
    :class:`~quiver_tpu.ops.coldcache.ColdRowCache` over host-*page*
    space, so CLOCK eviction, invalidation, and checkpoint
    export/restore are shared code), and the sorted block plan the
    kernel prefetches.
  * **feature.py** — orchestration: the staged-tuple plumbing
    (prefetch pool, ``_pending`` claims) and the per-``B`` program
    cache (``_paged_fn``; counted by ``retrace_guard`` and sealed by
    the recovery registry like every other executable cache).

Thread-safety: externally synchronized — the owning ``Feature`` holds
``_plock`` across :meth:`PagedStore.stage`, same contract as
``ColdRowCache``.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from ..analysis.staging import no_sync
from .coldcache import ColdRowCache

__all__ = ["PagedStore", "PageTable", "default_page_rows",
           "DEVICE", "OVERLAY", "HOST", "PAGE_STATES"]

# page residency states (docs/FEATURE_CACHE.md)
DEVICE, OVERLAY, HOST = 0, 1, 2
PAGE_STATES = {"DEVICE": DEVICE, "OVERLAY": OVERLAY, "HOST": HOST}

_TXN_BYTES = 512          # HBM transaction granularity (BENCH_r05)
_TARGET_PAGE_BYTES = 4096  # auto-sizing floor: 8 transactions per page
_VMEM_BUDGET = 2 << 20     # kernel scratch budget for the page window


def default_page_rows(row_bytes: int,
                      target_bytes: int = _TARGET_PAGE_BYTES) -> int:
    """Smallest row count whose page is a 512B-transaction multiple and
    at least ``target_bytes`` (the gather then moves whole transactions,
    never partial ones).  Falls back to a plain ``target_bytes`` fill
    when no multiple exists within 4096 rows (odd row widths)."""
    row_bytes = max(int(row_bytes), 1)
    fill = max(1, -(-target_bytes // row_bytes))
    for r in range(fill, fill + 4096):
        if (r * row_bytes) % _TXN_BYTES == 0:
            return r
    return fill


def _plan_geometry(page_rows: int, dim: int, itemsize: int
                   ) -> Tuple[int, int]:
    """(block, ppb) for the kernel: output rows per grid program and the
    worst-case distinct pages per block, fit to the VMEM scratch budget
    (every row of a block could touch its own page)."""
    page_bytes = max(page_rows * dim * itemsize, 1)
    block = max(8, min(128, _VMEM_BUDGET // page_bytes))
    # round down to a multiple of 8 so padded lengths stay lane-friendly
    block = max(8, (block // 8) * 8)
    return block, block


class PageTable:
    """Residency bookkeeping over the page space of one feature table.

    Pages partition the row space ``[0, N)``: page ``p`` covers rows
    ``[p*R, (p+1)*R)``.  The hot prefix is rounded UP to whole pages
    (``hot_pages``) — boundary rows past ``cache_count`` are filled
    from the host tail at build, so the padding is real data, not
    zeros, and the paged gather stays bit-identical to the staged
    merge.  Host pages are tracked by a :class:`ColdRowCache` whose
    "rows" are pages (``admit_threshold=1``: a touched HOST page must
    fault in to be served at all).
    """

    def __init__(self, n_rows: int, cache_count: int, page_rows: int,
                 pool_pages: int, policy: str = "clock"):
        assert page_rows > 0, page_rows
        self.page_rows = int(page_rows)
        self.n_rows = int(n_rows)
        self.n_pages = -(-self.n_rows // self.page_rows)
        self.hot_pages = (-(-int(cache_count) // self.page_rows)
                          if cache_count > 0 else 0)
        self.hot_pages = min(self.hot_pages, self.n_pages)
        self.n_host_pages = self.n_pages - self.hot_pages
        pool_pages = int(min(pool_pages, self.n_host_pages))
        self.pool_pages = max(pool_pages, 0)
        # page residency map: ColdRowCache over host-page ids — CLOCK
        # eviction, invalidation, and export/restore_state all reused
        self.cache = (ColdRowCache(self.pool_pages, self.n_host_pages,
                                   policy=policy, admit_threshold=1)
                      if self.pool_pages > 0 and self.n_host_pages > 0
                      else None)

    def state_of(self, page: int) -> int:
        """Residency state of one logical page (telemetry / tests)."""
        if page < self.hot_pages:
            return DEVICE
        if (self.cache is not None
                and self.cache.slot_of[page - self.hot_pages] >= 0):
            return OVERLAY
        return HOST

    @property
    def n_frames(self) -> int:
        return self.hot_pages + self.pool_pages

    def resident_pages(self) -> int:
        return self.hot_pages + (self.cache.resident
                                 if self.cache is not None else 0)


class PagedStore:
    """Device frame pool + fault planner behind ``Feature``'s paged path.

    Built by :meth:`Feature.enable_paging`.  Owns the ``[F, R, D]``
    frames array (DEVICE pages written once at build, OVERLAY pool
    faulted on demand), the reusable locked staging buffers for
    whole-page H2D fault transfers, and the block plan handed to the
    ragged kernel.  All mutation happens under the owning feature's
    ``_plock`` (**externally synchronized**, same contract as
    ``ColdRowCache`` — no lock of its own); the staged tuple captures
    the frames *value* at plan time, so a concurrent fault/evict can
    never retarget pages under an already-planned gather (jax arrays
    are immutable — the same capture discipline as ``_stage_overlay``).
    """

    def __init__(self, table: PageTable, host_rows, cache_count: int,
                 dim: int, dtype, hot_host=None):
        import jax
        import jax.numpy as jnp

        self.table = table
        self.dim = int(dim)
        self.dtype = np.dtype(dtype)
        self._feature = None            # owning Feature (set on attach)
        self._host = host_rows          # host tail [N - cache_count, D]
        self._cc = int(cache_count)
        R = table.page_rows
        self.page_bytes = R * self.dim * self.dtype.itemsize
        self.block, self.ppb = _plan_geometry(R, self.dim,
                                             self.dtype.itemsize)
        # frame pool: hot pages first (boundary page filled from the
        # host tail so its rows are real data), then the overlay pool
        frames_np = np.zeros((table.n_frames, R, self.dim),
                             dtype=self.dtype)
        hot_rows = min(table.hot_pages * R, table.n_rows)
        if hot_rows:
            flat = frames_np[:table.hot_pages].reshape(-1, self.dim)
            n_dev = min(self._cc, hot_rows)
            if n_dev:
                flat[:n_dev] = np.asarray(hot_host)[:n_dev]
            if hot_rows > n_dev:     # boundary page tail: host rows
                flat[n_dev:hot_rows] = np.asarray(
                    host_rows[:hot_rows - n_dev])
        self.frames = jnp.asarray(frames_np)
        self._page_bufs = {}            # k_pad -> [k_pad, R, D] staging
        self._interpret = jax.default_backend() != "tpu"
        self.fallbacks = 0              # batches the pool couldn't hold

    # ------------------------------------------------------------------
    def _fault_pages(self, host_pages: np.ndarray, jnp, telemetry
                    ) -> Optional[int]:
        """Fault the given (unique) HOST pages into the overlay pool as
        ONE whole-page H2D transfer.  Returns the number of pages
        faulted, or None when the pool cannot hold this batch's working
        set (the caller falls back to the staged path — correctness
        first, the counter makes the mis-sizing visible)."""
        cache = self.table.cache
        if cache is None:
            return None
        hit, _ = cache.probe(host_pages)
        fault = host_pages[~hit]
        if fault.size == 0:
            telemetry.counter("feature_page_hits_total").inc(
                float(host_pages.size))
            return 0
        # the batch's hit pages must survive the admission sweep: they
        # are about to be read by this very gather
        protect = cache.slot_of[host_pages[hit]]
        if fault.size + hit.sum() > cache.capacity:
            return None  # working set exceeds the pool: stage instead
        slots, n_evicted = cache.admit(fault, protect_slots=protect)
        if (slots < 0).any():
            return None  # admission couldn't place every fault
        R = self.table.page_rows
        k = int(fault.size)
        from ..feature import _pow2_bucket

        k_pad = _pow2_bucket(k)
        buf = self._page_bufs.get(k_pad)
        if buf is None or buf.shape != (k_pad, R, self.dim) \
                or buf.dtype != self.dtype:
            buf = np.zeros((k_pad, R, self.dim), dtype=self.dtype)
            self._page_bufs[k_pad] = buf
        base0 = self.table.hot_pages * R - self._cc  # host offset of page0
        for j, hp in enumerate(fault):
            lo = base0 + int(hp) * R
            hi = min(lo + R, len(self._host))
            rows = hi - lo
            buf[j, :rows] = self._host[lo:hi]
            if rows < R:               # partial tail page: zero pad
                buf[j, rows:] = 0
        pad_slot = np.full(k_pad, self.table.n_frames, dtype=np.int32)
        pad_slot[:k] = self.table.hot_pages + slots
        h2d_bytes = buf.nbytes         # whole padded transfer, host math
        rows_d = jnp.array(buf)        # copy: the buffer is reusable
        self.frames = self._feature._paged_fault_fn(k_pad)(
            self.frames, jnp.asarray(pad_slot), rows_d)
        telemetry.counter("feature_page_faults_total").inc(float(k))
        telemetry.counter("feature_page_hits_total").inc(
            float(int(hit.sum())))
        telemetry.counter("feature_h2d_bytes_total").inc(float(h2d_bytes))
        if n_evicted:
            telemetry.counter("feature_page_evictions_total").inc(
                float(n_evicted))
        telemetry.gauge("feature_page_resident_bytes").set(
            float(self.table.resident_pages() * self.page_bytes))
        from ..telemetry import flightrec, timeline

        if flightrec.tracing():
            # forwards to the unified timeline too, trace-correlated
            flightrec.event("feature.page_fault", {
                "pages": k, "evicted": int(n_evicted),
                "h2d_bytes": int(h2d_bytes)})
        elif timeline._ON:
            # faults from untraced gathers (warmup, loader prefetch)
            # still belong on the timeline
            timeline.emit("feature.page_fault", cat="paged", attrs={
                "pages": k, "evicted": int(n_evicted),
                "h2d_bytes": int(h2d_bytes)})
        return k

    # ------------------------------------------------------------------
    def stage(self, idx: np.ndarray, jnp, telemetry):
        """Translate (already feature-order-mapped) ids into the block
        plan the ragged kernel walks, faulting HOST pages first.

        Returns the staged tuple ``("pg", frames, blk_pages, blk_np,
        row_lp, row_off, rank, B)`` or ``None`` when the batch's page
        working set exceeds the overlay pool (caller stages instead).
        Caller holds the owning feature's ``_plock``.
        """
        R = self.table.page_rows
        t = self.table
        idx = idx.astype(np.int64)
        B = len(idx)
        page = idx // R
        is_host_space = page >= t.hot_pages
        if is_host_space.any():
            host_pages = np.unique(page[is_host_space] - t.hot_pages)
            if self._fault_pages(host_pages, jnp, telemetry) is None:
                self.fallbacks += 1
                telemetry.counter("feature_page_fallback_total").inc()
                return None
            slot = t.cache.slot_of[page[is_host_space] - t.hot_pages]
            assert (slot >= 0).all(), "fault left a HOST page unmapped"
        frame = page.astype(np.int32)
        if is_host_space.any():
            frame[is_host_space] = (t.hot_pages + slot).astype(np.int32)
        off = (idx % R).astype(np.int32)
        n_dev_rows = B - int(is_host_space.sum())
        telemetry.counter("feature_rows_total", tier="hot").inc(
            float(n_dev_rows))
        telemetry.counter("feature_rows_total", tier="cold").inc(
            float(B - n_dev_rows))
        # ---- sorted block plan (ragged: linear pad to `block`, not pow2)
        order = np.argsort(frame, kind="stable")
        sf, so = frame[order], off[order]
        blk = self.block
        Bpad = -(-B // blk) * blk
        nb = Bpad // blk
        row_lp = np.zeros(Bpad, dtype=np.int32)
        row_off = np.zeros(Bpad, dtype=np.int32)
        row_off[:B] = so
        blk_pages = np.zeros(nb * self.ppb, dtype=np.int32)
        blk_np = np.zeros(nb, dtype=np.int32)
        for b in range(nb):
            lo, hi = b * blk, min((b + 1) * blk, B)
            if lo >= B:
                break
            seg = sf[lo:hi]
            # distinct frames in first-appearance order: seg is sorted,
            # so np.unique's sorted order IS first-appearance order
            uniq, inv = np.unique(seg, return_inverse=True)
            blk_pages[b * self.ppb: b * self.ppb + len(uniq)] = uniq
            blk_np[b] = len(uniq)
            row_lp[lo:hi] = inv.astype(np.int32)
        rank = np.empty(B, dtype=np.int32)
        rank[order] = np.arange(B, dtype=np.int32)
        return ("pg", self.frames, jnp.asarray(blk_pages),
                jnp.asarray(blk_np), jnp.asarray(row_lp),
                jnp.asarray(row_off), jnp.asarray(rank), B)

    def frame_of_pages(self) -> np.ndarray:
        """Logical page -> resident frame map (``-1`` = HOST), the flat
        view the mesh tier stacks into its sharded page table — hot
        pages are pinned at their own index, overlay residents read
        from the CLOCK cache's slot map.  Caller holds ``_plock``."""
        t = self.table
        out = np.full(t.n_pages, -1, dtype=np.int32)
        out[:t.hot_pages] = np.arange(t.hot_pages, dtype=np.int32)
        if t.cache is not None:
            slot = t.cache.slot_of
            resident = slot >= 0
            out[t.hot_pages:][resident] = (
                t.hot_pages + slot[resident]).astype(np.int32)
        return out

    def finish(self, staged, feature):
        """Run the (cached) paged gather program over a staged plan."""
        (_, frames, blk_pages, blk_np, row_lp, row_off, rank, B) = staged
        fn = feature._paged_fn(B)
        # the gather itself must dispatch without blocking: callers
        # decide when (whether) to materialize the result
        with no_sync("paged gather"):
            return fn(frames, blk_pages, blk_np, row_lp, row_off, rank)

    # ------------------------------------------------------------------
    def invalidate_rows(self, rel_ids: np.ndarray) -> int:
        """Drop OVERLAY pages containing the given host-tail-relative
        row ids (stream mutations); DEVICE pages are a partition, not a
        cache — same contract as ``ColdRowCache.invalidate_rows``.
        Caller holds ``_plock``.  Returns pages dropped."""
        t = self.table
        if t.cache is None or rel_ids.size == 0:
            return 0
        R = t.page_rows
        pages = np.unique((rel_ids + self._cc) // R) - t.hot_pages
        dropped = t.cache.invalidate_rows(pages[pages >= 0])
        if dropped:
            from .. import telemetry

            telemetry.gauge("feature_page_resident_bytes").set(
                float(t.resident_pages() * self.page_bytes))
        return dropped

    # -- recovery (docs/RECOVERY.md) -----------------------------------
    def export_state(self) -> dict:
        """Page-table residency for a recovery checkpoint.  Flat dict:
        the page cache's arrays ride the existing ``_CC_PINNED``
        serialization; ``kind``/``page_rows`` are scalars in the
        checkpoint header, so a pre-paged build simply ignores them."""
        st = (self.table.cache.export_state()
              if self.table.cache is not None else {})
        st["kind"] = "paged"
        st["page_rows"] = self.table.page_rows
        return st

    def restore_state(self, state: dict) -> int:
        """Re-warm the overlay pool from a checkpointed page table:
        restore the residency map, then re-fault every resident page
        from the host tail (restoring the map without the page values
        would serve zeros).  Geometry mismatches raise ``ValueError``
        (the caller starts cold).  Returns rows re-warmed.  Caller
        holds ``_plock``."""
        import jax.numpy as jnp

        if int(state.get("page_rows", -1)) != self.table.page_rows:
            raise ValueError(
                f"page geometry changed: snapshot has page_rows="
                f"{state.get('page_rows')}, this store has "
                f"{self.table.page_rows}")
        cache = self.table.cache
        if cache is None:
            return 0
        cache.restore_state(state)
        slots = np.nonzero(cache.node_of >= 0)[0]
        if slots.size == 0:
            return 0
        R = self.table.page_rows
        base0 = self.table.hot_pages * R - self._cc
        pages_np = np.zeros((len(slots), R, self.dim), dtype=self.dtype)
        for j, s in enumerate(slots):
            lo = base0 + int(cache.node_of[s]) * R
            hi = min(lo + R, len(self._host))
            pages_np[j, :hi - lo] = self._host[lo:hi]
        frame_ids = (self.table.hot_pages + slots).astype(np.int32)
        self.frames = self.frames.at[jnp.asarray(frame_ids)].set(
            jnp.asarray(pages_np))
        from .. import telemetry

        telemetry.gauge("feature_page_resident_bytes").set(
            float(self.table.resident_pages() * self.page_bytes))
        return int(slots.size) * R

    # ------------------------------------------------------------------
    def stats(self) -> dict:
        t = self.table
        return dict(
            page_rows=t.page_rows, page_bytes=self.page_bytes,
            n_pages=t.n_pages, hot_pages=t.hot_pages,
            pool_pages=t.pool_pages,
            resident_pages=t.resident_pages(),
            fallbacks=self.fallbacks,
            block=self.block, ppb=self.ppb,
            cache=(t.cache.stats() if t.cache is not None else None),
        )

    def __repr__(self):
        t = self.table
        return (f"PagedStore(pages={t.n_pages}, hot={t.hot_pages}, "
                f"pool={t.pool_pages}, page_rows={t.page_rows}, "
                f"page_bytes={self.page_bytes})")
