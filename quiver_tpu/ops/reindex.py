"""Frontier dedup + relabel — the TPU replacement for the CUDA ordered hash
table (``srcs/cpp/include/quiver/reindex.cu.hpp:21-225`` and
``TorchQuiver::reindex_single``, ``quiver_sample.cu:305-357``).

Contract parity: given seeds and their sampled neighbors, produce
``n_id`` (unique frontier, seeds first — ``n_id[:B] == seeds``) and the
neighbor lists relabeled to local positions in ``n_id``.

TPU-first redesign: linear-probing hash tables with atomicCAS don't map to
the VPU.  Instead we sort once and use ``searchsorted``:
  1. membership of each neighbor in ``seeds`` via binary search,
  2. ``sort -> adjacent-unique -> compacting scatter`` for the non-seed
     remainder (first-occurrence order is NOT preserved for non-seeds — they
     come out id-sorted, which is a free locality win for the feature
     gather and is semantically irrelevant: the frontier is a set).
Everything is static-shaped: the frontier is padded to ``B + B*k`` (or a
user cap) with a valid-count scalar, the bucketing discipline that replaces
Quiver's dynamic allocations.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

__all__ = ["reindex", "ReindexOut"]

# plain int (not jnp scalar): a module-level jnp value would initialize the
# jax backend at import time
_SENTINEL = 2**31 - 1


class ReindexOut(NamedTuple):
    n_id: jax.Array        # [B + B*k] int32, padded with 0 beyond num_nodes
    num_nodes: jax.Array   # scalar int32: valid prefix length of n_id
    n_id_mask: jax.Array   # [B + B*k] bool validity
    local_nbrs: jax.Array  # [B, k] int32 positions into n_id (0 where ~mask)
    mask: jax.Array        # [B, k] bool (same as sample mask)


@functools.partial(jax.jit, static_argnames=())
def reindex(
    seeds: jax.Array,
    nbrs: jax.Array,
    mask: jax.Array,
    seed_mask: Optional[jax.Array] = None,
) -> ReindexOut:
    """Dedup (seeds ∪ nbrs) and relabel ``nbrs`` to local frontier ids.

    Args:
      seeds: ``[B]`` int32.  If ``seed_mask`` given, invalid seeds still
        occupy their slot in ``n_id`` (so local ids stay aligned across
        layers) but must not also appear as padded garbage — they're 0s.
      nbrs: ``[B, k]`` int32 from :func:`sample_neighbors`.
      mask: ``[B, k]`` bool.
    """
    seeds = seeds.astype(jnp.int32)
    B = seeds.shape[0]
    k = nbrs.shape[1]
    flatn = nbrs.reshape(-1)
    flatm = mask.reshape(-1)
    if seed_mask is None:
        seed_mask = jnp.ones((B,), dtype=bool)

    # --- membership of neighbors in seeds (binary search over sorted seeds).
    # Invalid seeds are pushed to the top of the sort key so they never match.
    seed_key = jnp.where(seed_mask, seeds, _SENTINEL)
    order = jnp.argsort(seed_key)
    seeds_sorted = seed_key[order]
    loc = jnp.searchsorted(seeds_sorted, flatn)
    locc = jnp.clip(loc, 0, B - 1)
    in_seeds = (seeds_sorted[locc] == flatn) & flatm
    seed_local = order[locc].astype(jnp.int32)

    # --- unique of the non-seed remainder.
    rest = jnp.where(flatm & ~in_seeds, flatn, _SENTINEL)
    rest_sorted = jnp.sort(rest)
    is_first = jnp.concatenate(
        [jnp.ones(1, bool), rest_sorted[1:] != rest_sorted[:-1]]
    ) & (rest_sorted != _SENTINEL)
    rank = jnp.cumsum(is_first) - 1  # position among uniques
    num_rest = is_first.sum().astype(jnp.int32)
    uniq = jnp.full((B * k,), _SENTINEL, dtype=jnp.int32)
    uniq = uniq.at[jnp.where(is_first, rank, B * k)].set(
        rest_sorted, mode="drop"
    )

    # --- local ids.
    rest_local = B + jnp.searchsorted(uniq, flatn).astype(jnp.int32)
    local = jnp.where(in_seeds, seed_local, rest_local)
    local = jnp.where(flatm, local, 0).reshape(B, k).astype(jnp.int32)

    # --- assemble padded frontier, seeds first.
    n_id = jnp.concatenate([jnp.where(seed_mask, seeds, 0),
                            jnp.where(uniq == _SENTINEL, 0, uniq)])
    pos = jnp.arange(B + B * k, dtype=jnp.int32)
    n_id_mask = jnp.where(
        pos < B, seed_mask[jnp.clip(pos, 0, B - 1)], (pos - B) < num_rest
    )
    num_nodes = n_id_mask.sum().astype(jnp.int32)
    return ReindexOut(
        n_id=n_id,
        num_nodes=num_nodes,
        n_id_mask=n_id_mask,
        local_nbrs=local,
        mask=mask,
    )
