"""quiver_tpu.stream — graph mutation as a first-class workload.

The streaming tier layers a **delta-CSR overlay** (append-only edge
segment + tombstone bitmap, ``stream.delta`` / ``stream.graph``) over
the frozen base CSR, samples through it inside the jitted pipeline
(``ops.sample.sample_neighbors_overlay``, optional temporal windows),
folds it back into a fresh base on cadence (``stream.compactor``), and
admits edge updates through a bounded serving lane with its own
deadline class (``stream.ingest``).  See docs/STREAMING.md for the
overlay model, the consistency guarantees, and the config knobs.

Quick start::

    from quiver_tpu.stream import StreamingGraph, IngestLane, Compactor
    g = StreamingGraph(csr_topo, edge_ts=ts)       # ts optional
    sampler = GraphSageSampler(g, sizes=[10, 5])   # overlay-aware
    g.attach_feature(feature)                      # row invalidation
    lane = IngestLane(g).start()                   # serving ingestion
    lane.submit(src, dst, ts=now)                  # ack on lane.results
    batch = sampler.sample(seeds, key, time_window=(t0, t1))
"""

from .compactor import Compactor, compact
from .delta import DeltaStore
from .graph import DeltaSnapshot, StreamingGraph
from .ingest import EdgeUpdate, IngestLane

__all__ = [
    "StreamingGraph", "DeltaSnapshot", "DeltaStore",
    "Compactor", "compact", "EdgeUpdate", "IngestLane",
]
