"""Serving ingestion lane for edge updates.

Edge mutations enter serving deployments through their own
:class:`~quiver_tpu.resilience.lanes.BoundedLane` — NOT the query lanes
— with their own deadline class (``config.stream_ingest_deadline_ms``)
and shed priority (``config.stream_ingest_priority``).  Keeping the
lane separate means a mutation burst sheds mutations, never queries,
and vice versa; the priority knob decides who wins when an operator
routes both through one consumer.

Every update is stamped at admission with ``t_enqueue``, an absolute
deadline, a flight-recorder trace (which itself carries the graph
version current at admission), and ``admitted_version`` — the
consistency handle: once the worker acks an update at version ``v``,
every sample taken from a snapshot with ``version >= v`` reflects it
(the e2e test in ``tests/test_stream.py`` enforces exactly this).

Results travel as ``(update, outcome)`` tuples on ``results``:
``outcome`` is ``("ok", applied_count, version)`` on success, or the
exception instance (``LoadShed`` / ``DeadlineExceeded`` from the shed
path, the raised error otherwise).

Chaos: ``stream.ingest`` fires inside the worker before the graph is
touched, so injected faults produce clean ``(update, exc)`` answers.

Durability (docs/RECOVERY.md): with a WAL attached
(``RecoveryManager.attach_lane``), the worker appends each update to
the log **before** applying it and only acks after both — an acked op
is durable, a ``WALWriteError`` is answered on ``results`` with the
graph untouched.  The crash semantics are *at-least-once*: a durable
record whose ack was lost to the crash replays on boot (graph
mutations are idempotent — re-adding an edge re-adds it, which the
consistency contract states in terms of acked ops only).  An op whose
*apply* fails after the append (delta overflow with
``compact_on_full=False``, a bad op) is nacked AND compensated with a
WAL abort record, so replay does not resurrect the rejected mutation;
only a nack from the fsync itself leaves the record's fate
indeterminate (see the caveats in ``recovery/wal.py``).
``CheckpointBarrier`` control items ride the same lane and run on the
writer thread between applies, which is what makes a snapshot's graph
state and WAL watermark agree exactly.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from .. import telemetry
from ..resilience import chaos
from ..resilience.deadline import deadline_for, shed_if_expired
from ..resilience.lanes import BoundedLane, WeightedFairLane
from ..resilience.qos import qos_from_config
from ..telemetry import flightrec
from .compactor import compact

__all__ = ["EdgeUpdate", "IngestLane", "CheckpointBarrier"]

log = logging.getLogger("quiver_tpu.stream")

_CHAOS_INGEST = chaos.point("stream.ingest")

_STOP = object()


@dataclass
class EdgeUpdate:
    """One edge mutation request (shed-compatible: carries the same
    admission fields as a ServingRequest)."""

    src: object
    dst: object
    ts: Optional[object] = None
    op: str = "add"                 # "add" | "remove"
    t_enqueue: float = 0.0          # perf_counter at admission
    deadline: Optional[float] = None
    priority: int = 0
    trace: object = None
    admitted_version: int = -1      # graph version at admission
    # QoS class (stamped at submit when a controller is installed —
    # ingestion traffic rides the configured ``qos_ingest_tenant``
    # class, so a mutation burst fair-shares against queries instead
    # of starving them)
    tenant_class: Optional[str] = None
    meta: dict = field(default_factory=dict)


@dataclass
class CheckpointBarrier:
    """A control item the writer thread executes between applies.

    Deliberately carries **no** ``t_enqueue``: ``BoundedLane`` admits
    attribute-less items as control traffic (never shed, never counted
    against depth priorities), so a checkpoint request cannot be load-
    shed into never happening.  The worker calls ``fn(applied_lsn)``
    and publishes the outcome through ``done``/``result``/``error``.
    """

    fn: Callable[[int], object]
    done: threading.Event = field(default_factory=threading.Event)
    result: object = None
    error: Optional[BaseException] = None


class IngestLane:
    """Bounded edge-update lane + single writer thread.

    One writer serializes graph mutations (the ``StreamingGraph`` lock
    makes concurrent writers safe, but a single writer keeps version
    order equal to ack order, which is what the consistency contract is
    stated in).  ``BufferError`` from a full delta segment triggers an
    inline compaction and a retry — backpressure folds, it never drops.
    """

    def __init__(self, graph: "StreamingGraph", depth: Optional[int] = None,
                 deadline_ms: Optional[float] = None,
                 priority: Optional[int] = None,
                 result_queue=None, compact_on_full: bool = True,
                 wal=None):
        from ..config import get_config

        cfg = get_config()
        self.graph = graph
        self.wal = wal                  # WriteAheadLog, or None = volatile
        self.checkpoint_fn = None       # set by RecoveryManager.attach_lane
        # writer-thread-private (worker + its barriers only — no lock):
        self._applied_lsn = -1          # newest WAL record folded into graph
        self._compacted = False         # inline compaction since last ckpt
        self.deadline_ms = float(
            deadline_ms if deadline_ms is not None
            else cfg.stream_ingest_deadline_ms)
        self.priority = int(priority if priority is not None
                            else cfg.stream_ingest_priority)
        self.results = (result_queue if result_queue is not None
                        else queue.Queue())
        maxsize = int(depth if depth is not None
                      else cfg.stream_ingest_depth)
        self._qos = qos_from_config()
        if self._qos is not None:
            self.lane = WeightedFairLane(
                "stream_ingest", self._qos.weights(),
                default_class=self._qos.ingest,
                maxsize=maxsize, result_queue=self.results)
        else:
            self.lane = BoundedLane(
                "stream_ingest", maxsize=maxsize,
                result_queue=self.results)
        self.compact_on_full = compact_on_full
        self._thread = threading.Thread(
            target=self._ingest_worker, daemon=True,
            name="quiver-stream-ingest")

    # -- producer side -------------------------------------------------
    def start(self) -> "IngestLane":
        self._thread.start()
        return self

    def submit(self, src, dst, ts=None, op: str = "add",
               priority: Optional[int] = None) -> EdgeUpdate:
        """Enqueue one edge update; returns the stamped request (its
        answer arrives on ``results``).  May shed a lower-priority
        queued update (or this one) under load — the shed victim is
        answered with ``LoadShed`` on ``results``."""
        now = time.perf_counter()
        upd = EdgeUpdate(
            src=src, dst=dst, ts=ts, op=op, t_enqueue=now,
            deadline=deadline_for(now, self.deadline_ms),
            priority=self.priority if priority is None else int(priority),
            trace=flightrec.new_trace(),
            admitted_version=self.graph.version,
            tenant_class=(self._qos.ingest
                          if self._qos is not None else None),
        )
        if upd.trace is not None:
            upd.trace.add("stream.enqueue",
                          {"op": op, "lane": "stream_ingest"})
        self.lane.put(upd)
        return upd

    # -- consumer side -------------------------------------------------
    def _apply(self, upd: EdgeUpdate) -> int:
        if upd.op == "add":
            try:
                return self.graph.add_edges(upd.src, upd.dst, upd.ts)
            except BufferError:
                if not self.compact_on_full:
                    raise
                compact(self.graph)  # backpressure: fold, then retry
                self._compacted = True
                return self.graph.add_edges(upd.src, upd.dst, upd.ts)
        if upd.op == "remove":
            return self.graph.remove_edges(upd.src, upd.dst)
        raise ValueError(f"unknown edge op {upd.op!r}")

    def _durable(self, upd: EdgeUpdate):
        """Append ``upd`` to the WAL (durable per its fsync policy);
        returns the LSN, or None when running volatile.  Raises
        :class:`~quiver_tpu.recovery.errors.WALWriteError` — answered
        on ``results`` like any other failure — when durability cannot
        be promised; the graph is then never touched."""
        if self.wal is None:
            return None
        from ..recovery.wal import encode_edge_op

        return self.wal.append(
            encode_edge_op(upd.op, upd.src, upd.dst, upd.ts))

    def _abort_durable(self, lsn: int) -> None:
        """Append a compensation record for a durable-but-nacked op.

        Best-effort: if the log refuses even this, replay will apply
        the rejected mutation (the at-least-once caveat documented in
        ``recovery/wal.py``) — counted, logged, never raised, because
        the producer is already being answered with the original
        error."""
        from ..recovery.wal import encode_abort

        try:
            self.wal.append(encode_abort(lsn))
            telemetry.counter("recovery_wal_abort_records_total").inc()
        except Exception as e:
            telemetry.counter("recovery_wal_abort_failures_total").inc()
            log.warning("could not abort nacked wal record %d: %s", lsn, e)

    def _run_barrier(self, item: CheckpointBarrier) -> None:
        try:
            item.result = item.fn(self._applied_lsn)
        except Exception as e:
            log.warning("checkpoint barrier failed: %s", e)
            item.error = e
        finally:
            item.done.set()

    def _ingest_worker(self):
        while True:
            item = self.lane.get()
            if item is _STOP:
                return
            if isinstance(item, CheckpointBarrier):
                self._run_barrier(item)
                continue
            lsn = None  # set iff the append fully succeeded (durable)
            try:
                if shed_if_expired(item, self.results, "stream_ingest"):
                    continue
                with flightrec.activate(item.trace):
                    _CHAOS_INGEST()
                    lsn = self._durable(item)
                    applied = self._apply(item)
                if lsn is not None:
                    self._applied_lsn = lsn
                version = self.graph.version
                if item.trace is not None:
                    item.trace.add("stream.applied",
                                   {"n": applied, "version": version})
                    flightrec.get_recorder().finish(
                        item.trace,
                        time.perf_counter() - item.t_enqueue,
                        status="ok", lane="stream_ingest")
                self.results.put((item, ("ok", applied, version)))
            except Exception as e:
                # answer the producer with the exception object (chaos
                # faults, bad ops) — an unanswered update would hang a
                # waiting producer forever
                if lsn is not None:
                    # the record is already durable but its apply was
                    # rejected: compensate, or replay would resurrect
                    # a mutation this nack just disclaimed
                    self._abort_durable(lsn)
                telemetry.counter("stream_ingest_errors_total").inc()
                if item.trace is not None:
                    flightrec.get_recorder().finish(
                        item.trace,
                        time.perf_counter() - item.t_enqueue,
                        status="error", lane="stream_ingest")
                self.results.put((item, e))
            if self._compacted and self.checkpoint_fn is not None:
                # an inline compaction folded the delta into a new base:
                # snapshot it so the covered WAL prefix can truncate.
                # Best-effort — a failed snapshot costs replay time only.
                self._compacted = False
                try:
                    self.checkpoint_fn(self._applied_lsn)
                except Exception as e:
                    log.warning("post-compaction checkpoint failed: %s", e)

    def request_checkpoint(self, fn=None) -> CheckpointBarrier:
        """Enqueue a checkpoint barrier for the writer thread; returns
        it immediately (wait on ``barrier.done``).  ``fn`` defaults to
        the attached manager's snapshot function."""
        fn = fn if fn is not None else self.checkpoint_fn
        if fn is None:
            raise ValueError("no checkpoint_fn attached to this lane")
        barrier = CheckpointBarrier(fn=fn)
        self.lane.put(barrier)
        return barrier

    def is_running(self) -> bool:
        return self._thread.is_alive()

    def stop(self, timeout: float = 5.0) -> None:
        from ..resilience.shutdown import join_and_reap

        self.lane.put(_STOP)
        join_and_reap([self._thread], timeout, component="stream.ingest")

    @property
    def depth(self) -> int:
        return self.lane.qsize()
