"""Host-side delta segment for the streaming graph tier.

The delta-CSR overlay keeps the base CSR **frozen** (so every device
placement and compiled executable built against it stays valid) and
accumulates mutations in two side structures:

  * an **append-only edge segment** — ``(src, dst[, ts])`` triples in
    arrival order, preallocated to ``capacity`` so steady-state ingestion
    never reallocates;
  * a **dead mark per pending edge** — a delta edge deleted before it
    ever reached a base CSR is marked dead here (base-edge deletions
    live in the owning :class:`~quiver_tpu.stream.graph.StreamingGraph`'s
    tombstone bitmap instead, since they address base CSR positions).

This module is pure numpy bookkeeping (no jax imports): the device view
of the segment is built per snapshot by ``StreamingGraph.snapshot`` —
live pending edges re-CSR'd over the node-id space and padded to a pow2
fanout bucket so executable keys stay additive.

Thread-safety: externally synchronized — every caller holds the owning
``StreamingGraph._lock`` (same division of labor as ``ColdRowCache`` /
``Feature._plock``).
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

__all__ = ["DeltaStore"]


class DeltaStore:
    """Preallocated append-only edge segment with dead marks.

    Args:
      capacity: maximum pending (uncompacted) edges; :meth:`add` raises
        ``BufferError`` past it — the compactor is expected to fold long
        before that (``config.stream_compact_watermark``).
      has_ts: store a per-edge int32 timestamp alongside each edge.
    """

    def __init__(self, capacity: int, has_ts: bool = False):
        capacity = int(capacity)
        if capacity <= 0:
            raise ValueError(f"delta capacity must be > 0, got {capacity}")
        self.capacity = capacity
        self.has_ts = bool(has_ts)
        self.n = 0  # appended (live + dead) pending edges
        self.src = np.zeros(capacity, dtype=np.int32)
        self.dst = np.zeros(capacity, dtype=np.int32)
        self.ts = np.zeros(capacity, dtype=np.int32) if has_ts else None
        self.dead = np.zeros(capacity, dtype=bool)

    # ------------------------------------------------------------------
    # quiverlint: requires-lock[StreamingGraph._lock]
    def add(self, src: np.ndarray, dst: np.ndarray,
            ts: Optional[np.ndarray] = None) -> int:
        """Append edges; returns the count appended.

        Raises ``BufferError`` when the segment cannot hold the batch —
        the caller (ingest worker) treats that as backpressure and forces
        a compaction instead of dropping updates.
        """
        src = np.atleast_1d(np.asarray(src, dtype=np.int32))
        dst = np.atleast_1d(np.asarray(dst, dtype=np.int32))
        if src.shape != dst.shape:
            raise ValueError("src/dst length mismatch")
        m = len(src)
        if self.n + m > self.capacity:
            raise BufferError(
                f"delta segment full ({self.n}+{m} > {self.capacity}): "
                "compact before ingesting more edges")
        if self.has_ts:
            if ts is None:
                raise ValueError(
                    "this graph carries per-edge timestamps: add() "
                    "requires ts")
            ts = np.atleast_1d(np.asarray(ts, dtype=np.int32))
            if ts.shape != src.shape:
                raise ValueError("ts length mismatch")
            self.ts[self.n:self.n + m] = ts
        sl = slice(self.n, self.n + m)
        self.src[sl] = src
        self.dst[sl] = dst
        self.dead[sl] = False
        self.n += m
        return m

    # quiverlint: requires-lock[StreamingGraph._lock]
    def kill(self, src: int, dst: int) -> bool:
        """Mark ONE live pending edge (src, dst) dead; last match wins
        (most-recently-added duplicate dies first).  Returns False when
        no live pending match exists (the caller then consults the base
        tombstones)."""
        n = self.n
        hits = np.nonzero(
            (self.src[:n] == src) & (self.dst[:n] == dst)
            & ~self.dead[:n]
        )[0]
        if not len(hits):
            return False
        self.dead[hits[-1]] = True
        return True

    # ------------------------------------------------------------------
    @property
    def live(self) -> int:
        """Pending edges that would survive a fold right now."""
        return int(self.n - self.dead[:self.n].sum())

    def live_edges(self) -> Tuple[np.ndarray, np.ndarray,
                                  Optional[np.ndarray]]:
        """``(src, dst, ts-or-None)`` copies of the live pending edges,
        in append order (the order a fold preserves per row)."""
        n = self.n
        keep = ~self.dead[:n]
        ts = self.ts[:n][keep].copy() if self.has_ts else None
        return self.src[:n][keep].copy(), self.dst[:n][keep].copy(), ts

    # quiverlint: requires-lock[StreamingGraph._lock]
    def clear(self) -> None:
        """Empty the segment (after its edges were folded into a base)."""
        self.n = 0

    def __repr__(self):
        return (f"DeltaStore(pending={self.n}, live={self.live}, "
                f"capacity={self.capacity}, has_ts={self.has_ts})")
