"""Compaction — fold the delta overlay into a fresh base CSR.

A fold concatenates the surviving base edges (tombstones dropped) with
the live delta edges and rebuilds CSR via the same stable counting sort
the initial graph build uses (``utils.topology.coo_to_csr``).  Stable
order gives every row ``[surviving base neighbors in base order, delta
neighbors in append order]`` — exactly the virtual concatenation the
overlay sampler draws from, so a seed's post-compaction neighborhood is
the overlay neighborhood with the dead entries squeezed out.  After the
swap the overlay is empty: sampling drops back to the zero-delta path,
which is bitwise-identical to a frozen-CSR sampler on the new base.

The swap runs under the graph lock and is **atomic** from the samplers'
point of view: in-flight snapshots keep their (immutable) device
arrays; the next ``snapshot()`` call sees the new base.  The fold
itself (numpy sort over E edges) also runs under the lock — mutations
arriving mid-fold would otherwise be folded twice or lost.  The pause
this imposes on ingestion is the quantity the bench's ``stream_ingest``
section reports (``stream_compact_pause_seconds``).

Chaos: ``stream.compact`` fires before any state is touched, so an
injected fault aborts the fold with the graph unchanged — the
:class:`Compactor` loop records it and retries next tick (the e2e chaos
test drives this path).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Optional

import numpy as np

from .. import telemetry
from ..resilience import chaos
from ..resilience.shutdown import join_and_reap
from ..utils.topology import CSRTopo, coo_to_csr

__all__ = ["compact", "Compactor"]

log = logging.getLogger("quiver_tpu.stream")

_CHAOS_COMPACT = chaos.point("stream.compact")


def compact(graph: "StreamingGraph") -> dict:
    """Fold ``graph``'s overlay into a fresh base CSR and swap it in.

    Returns fold stats; raises whatever the ``stream.compact`` chaos
    point injects (state untouched in that case).
    """
    t0 = time.perf_counter()
    with graph._lock:
        _CHAOS_COMPACT()
        base = graph._base
        n = base.node_count
        keep = ~graph._tomb
        dropped = int(graph._tomb.sum())
        d_src, d_dst, d_ts = graph._delta.live_edges()
        folded = len(d_src)
        # base edges back to COO rows, tombstones squeezed out
        bsrc = np.repeat(
            np.arange(n, dtype=np.int64), base.degree)[keep]
        src = np.concatenate([bsrc, d_src.astype(np.int64)])
        dst = np.concatenate(
            [base.indices[keep].astype(np.int64),
             d_dst.astype(np.int64)])
        indptr, indices, eid = coo_to_csr(src, dst, n)
        new_base = CSRTopo(indptr=indptr, indices=indices)
        new_base.feature_order = base.feature_order
        if graph.has_ts:
            ts = np.concatenate([graph._base_ts[keep], d_ts])
            graph._base_ts = ts[eid].astype(np.int32)
        # the swap: old base stays valid for in-flight snapshots (its
        # arrays are immutable); dropping our reference is the whole
        # invalidation — plus the explicit version bump + device-cache
        # invalidate so NOTHING can serve the old topology as current
        base.invalidate()
        graph._base = new_base
        graph._tomb = np.zeros(new_base.edge_count, dtype=bool)
        graph._tombstones = 0
        graph._delta.clear()
        graph._version += 1
        graph._snap = None
        version = graph._version
    pause = time.perf_counter() - t0
    telemetry.counter("stream_compactions_total").inc()
    telemetry.histogram("stream_compact_pause_seconds").observe(pause)
    telemetry.gauge("stream_overlay_bytes").set(0.0)
    telemetry.gauge("stream_graph_version_total").set(version)
    return dict(folded=folded, dropped=dropped, pause_s=pause,
                version=version, edges=new_base.edge_count)


class Compactor(threading.Thread):
    """Background thread folding the overlay on cadence or watermark.

    A fold triggers when either ``interval_s`` has elapsed since the
    last one **and** there is anything pending, or the pending fraction
    of delta capacity crosses ``watermark`` (checked every poll tick).
    """

    def __init__(self, graph: "StreamingGraph",
                 interval_s: Optional[float] = None,
                 watermark: Optional[float] = None,
                 poll_s: float = 0.05):
        from ..config import get_config

        cfg = get_config()
        super().__init__(daemon=True, name="quiver-stream-compactor")
        self.graph = graph
        self.interval_s = float(interval_s if interval_s is not None
                                else cfg.stream_compact_interval_s)
        self.watermark = float(watermark if watermark is not None
                               else cfg.stream_compact_watermark)
        self.poll_s = float(poll_s)
        self._stop_ev = threading.Event()
        self._last = time.perf_counter()

    def _due(self) -> bool:
        pending = self.graph.pending_deltas
        tombs = self.graph.tombstone_count
        if pending + tombs == 0:
            return False
        if pending >= self.watermark * self.graph._delta.capacity:
            return True
        return time.perf_counter() - self._last >= self.interval_s

    def run(self):
        while not self._stop_ev.wait(self.poll_s):
            try:
                if self._due():
                    compact(self.graph)
                    self._last = time.perf_counter()
            except Exception as e:
                # a failed fold (chaos, transient OOM) leaves the graph
                # unchanged; record it and retry next tick — silently
                # swallowing would let the overlay grow to capacity
                telemetry.counter("stream_compact_errors_total").inc()
                log.warning("compaction failed (will retry): %s", e)

    def stop(self, timeout: float = 5.0) -> None:
        self._stop_ev.set()
        join_and_reap([self], timeout, component="stream.compactor")
