"""StreamingGraph — a mutable graph view over a frozen base CSR.

The streaming tier's contract (docs/STREAMING.md):

  * the **base** :class:`~quiver_tpu.utils.topology.CSRTopo` never
    mutates in place — deletions of base edges set bits in a
    **tombstone bitmap** indexed by base edge position, insertions go to
    the :class:`~quiver_tpu.stream.delta.DeltaStore` append segment;
  * samplers consume immutable :class:`DeltaSnapshot`\\ s — one set of
    device arrays per graph version, built lazily and cached until the
    next mutation.  The delta segment is re-CSR'd per snapshot and
    padded to a **pow2 fanout bucket** so the jitted overlay pipeline's
    executable keys stay additive (coldcache discipline: executables key
    on the bucket, not the pending count);
  * the **compactor** (``stream.compactor``) folds tombstones + live
    delta edges into a fresh base CSR and swaps it in atomically under
    ``_lock`` — in-flight snapshots keep sampling the old arrays (jax
    arrays are immutable), the next ``snapshot()`` sees the new base;
  * every mutation bumps ``version``; the flight recorder stamps the
    version current at each request's admission
    (``flightrec.set_version_provider``), so traces pin the topology
    they sampled.

Invalidation wiring: row listeners registered via
:meth:`register_invalidation` / :meth:`attach_feature` run after every
mutation with the union of touched endpoints — that drops stale rows
from the coldcache overlay / per-host DistFeature overlay.  Listeners
run OUTSIDE ``_lock`` (they take their own store locks; holding both
would order ``_lock`` before ``Feature._plock`` here and invite the
reverse order elsewhere).
"""

from __future__ import annotations

import threading
from typing import Callable, List, NamedTuple, Optional

import numpy as np

from .. import telemetry
from ..telemetry import flightrec
from ..utils.topology import CSRTopo, coo_to_csr
from .delta import DeltaStore

__all__ = ["StreamingGraph", "DeltaSnapshot"]


def _pad128(a: np.ndarray) -> np.ndarray:
    """Zero-pad to a multiple of 128, never empty (lanes-gather shape
    contract, same as ``CSRTopo.to_device``)."""
    target = max(((len(a) + 127) // 128) * 128, 128)
    if target != len(a):
        a = np.concatenate([a, np.zeros(target - len(a), a.dtype)])
    return a


def _fanout_bucket(n: int) -> int:
    """Smallest pow2 >= n, floored at 128 — the static length the delta
    indices/ts tables pad to, so executables key on O(log capacity)
    buckets instead of every pending count."""
    b = 128
    while b < n:
        b *= 2
    return b


class DeltaSnapshot(NamedTuple):
    """Immutable device view of one graph version.

    All arrays are device-resident jax arrays; ``d_indices`` / ``d_ts``
    are padded to ``delta_bucket`` and ``tomb`` / ``base_ts`` to the
    base table pad, so an executable built for
    ``(epad, delta_bucket, has_ts)`` serves every later snapshot with
    the same key.
    """

    indptr: object         # [Npad] int32 base CSR row pointers
    indices: object        # [epad] int32 base CSR columns
    tomb: object           # [epad] int32, nonzero = base edge deleted
    d_indptr: object       # [Npad] int32 delta CSR row pointers
    d_indices: object      # [delta_bucket] int32 delta columns
    base_ts: Optional[object]  # [epad] int32 or None
    d_ts: Optional[object]     # [delta_bucket] int32 or None
    version: int
    epad: int
    delta_bucket: int
    has_ts: bool
    pending: int           # live delta edges in this snapshot


class StreamingGraph:
    """Thread-safe mutable graph: base CSR + tombstones + delta segment.

    Args:
      csr_topo: the initial base :class:`CSRTopo` (frozen from here on).
      edge_ts: optional ``[E]`` int32 per-edge timestamps aligned with
        ``csr_topo.indices`` order; providing them enables the samplers'
        temporal window filter (and makes ``add_edges`` require ``ts``).
      delta_capacity: pending-edge ceiling
        (default ``config.stream_delta_capacity``).
      device: jax device the snapshots place arrays on.
    """

    _guarded_by = {
        "_tomb": "_lock", "_delta": "_lock", "_version": "_lock",
        "_snap": "_lock", "_base": "_lock", "_base_ts": "_lock",
        "_tombstones": "_lock", "_listeners": "_lock",
    }

    def __init__(self, csr_topo: CSRTopo, edge_ts=None,
                 delta_capacity: Optional[int] = None, device=None):
        from ..config import get_config

        cfg = get_config()
        self._lock = threading.RLock()
        self._base = csr_topo
        self.has_ts = edge_ts is not None
        if self.has_ts:
            edge_ts = np.asarray(edge_ts, dtype=np.int32)
            if edge_ts.shape[0] != csr_topo.edge_count:
                raise ValueError(
                    f"edge_ts length {edge_ts.shape[0]} != edge_count "
                    f"{csr_topo.edge_count}")
        self._base_ts = edge_ts
        self._tomb = np.zeros(csr_topo.edge_count, dtype=bool)
        self._tombstones = 0  # live tombstone count (folds reset it)
        cap = int(delta_capacity if delta_capacity is not None
                  else cfg.stream_delta_capacity)
        self._delta = DeltaStore(cap, has_ts=self.has_ts)
        self._version = 0
        self._snap: Optional[DeltaSnapshot] = None
        self.device = device
        self._listeners: List[Callable] = []
        # flight records stamp the version current at their admission
        flightrec.set_version_provider(self._read_version)

    # -- read side -----------------------------------------------------
    @property
    def base(self) -> CSRTopo:
        return self._base

    @property
    def node_count(self) -> int:
        return self._base.node_count

    @property
    def version(self) -> int:
        return self._read_version()

    def _read_version(self) -> int:
        # int read is atomic under the GIL; used by the flightrec
        # provider on every trace admission, so it must stay lock-free
        return self._version

    @property
    def pending_deltas(self) -> int:
        with self._lock:
            return self._delta.live

    @property
    def tombstone_count(self) -> int:
        with self._lock:
            return self._tombstones

    # -- invalidation wiring -------------------------------------------
    def register_invalidation(self, fn: Callable) -> None:
        """``fn(rows: np.ndarray)`` runs after every mutation with the
        touched node ids (edge endpoints).  Exceptions propagate to the
        mutator — a listener that cannot invalidate must not fail
        silently, or the caches serve stale rows."""
        with self._lock:
            self._listeners.append(fn)

    def attach_feature(self, feature) -> None:
        """Wire a ``Feature`` / ``DistFeature``'s ``invalidate_rows``."""
        self.register_invalidation(feature.invalidate_rows)

    def close(self) -> None:
        """Unhook the flightrec version provider (tests / teardown)."""
        flightrec.set_version_provider(None)

    def _notify(self, rows: np.ndarray) -> None:
        if rows.size == 0:
            return
        # snapshot under the lock, call listeners outside it: a listener
        # (Feature.invalidate_rows) takes Feature._plock, and holding
        # _lock across that call would pin the _lock -> _plock edge into
        # every notification (see the class docstring's ordering note)
        with self._lock:
            listeners = list(self._listeners)
        if not listeners:
            return
        rows = np.unique(rows.astype(np.int64))
        for fn in listeners:
            fn(rows)

    # -- mutation side -------------------------------------------------
    def add_edges(self, src, dst, ts=None) -> int:
        """Append edges to the delta segment; returns the count applied.

        ``BufferError`` (segment full) propagates — callers treat it as
        backpressure (the ingest worker compacts and retries).
        """
        src = np.atleast_1d(np.asarray(src, dtype=np.int32))
        dst = np.atleast_1d(np.asarray(dst, dtype=np.int32))
        n = self._base.node_count
        if src.size and (int(src.max()) >= n or int(dst.max()) >= n
                         or int(src.min()) < 0 or int(dst.min()) < 0):
            raise ValueError(
                f"edge endpoints must be in [0, {n}) — node additions "
                "are not part of the streaming tier")
        with self._lock:
            m = self._delta.add(src, dst, ts)
            self._version += 1
            self._snap = None
            pending = self._delta.live
        telemetry.counter("stream_edges_applied_total", op="add").inc(m)
        telemetry.gauge("stream_graph_version_total").set(self._version)
        telemetry.gauge("stream_overlay_bytes").set(
            float(pending) * (12.0 if self.has_ts else 8.0))
        self._notify(np.concatenate([src, dst]))
        return m

    def remove_edges(self, src, dst) -> int:
        """Delete edges: tombstone a live base occurrence, else kill a
        live pending delta edge.  Returns the count actually deleted
        (absent edges are ignored)."""
        src = np.atleast_1d(np.asarray(src, dtype=np.int64))
        dst = np.atleast_1d(np.asarray(dst, dtype=np.int64))
        if src.shape != dst.shape:
            raise ValueError("src/dst length mismatch")
        removed = tombed = 0
        touched = []
        with self._lock:
            indptr, indices = self._base.indptr, self._base.indices
            for u, v in zip(src, dst):
                u, v = int(u), int(v)
                lo, hi = int(indptr[u]), int(indptr[u + 1])
                row = indices[lo:hi]
                hit = np.nonzero((row == v) & ~self._tomb[lo:hi])[0]
                if len(hit):
                    self._tomb[lo + hit[0]] = True
                    self._tombstones += 1
                    tombed += 1
                elif not self._delta.kill(u, v):
                    continue  # edge absent: no-op
                removed += 1
                touched.append((u, v))
            if removed:
                self._version += 1
                self._snap = None
                pending = self._delta.live
        if removed:
            if tombed:
                telemetry.counter("stream_tombstones_total").inc(tombed)
            telemetry.counter("stream_edges_applied_total",
                              op="remove").inc(removed)
            telemetry.gauge("stream_graph_version_total").set(self._version)
            telemetry.gauge("stream_overlay_bytes").set(
                float(pending) * (12.0 if self.has_ts else 8.0))
            self._notify(np.asarray(touched, dtype=np.int64).reshape(-1))
        return removed

    # -- snapshot side -------------------------------------------------
    def snapshot(self, device=None) -> DeltaSnapshot:
        """Device view of the current version (cached until a mutation).

        The delta segment's live edges are re-CSR'd over the node space
        (stable order: a row's delta neighbors keep append order — the
        same order a fold preserves, which is what makes post-compaction
        sampling bitwise-reproducible) and padded to the pow2 fanout
        bucket.
        """
        import jax
        import jax.numpy as jnp

        device = device if device is not None else self.device
        with self._lock:
            snap = self._snap
            if snap is not None:
                return snap
            n = self._base.node_count
            indptr, indices = self._base.to_device(device)
            epad = int(indices.shape[0])
            tomb = _pad128(self._tomb.astype(np.int32))
            if len(tomb) != epad:  # epad floor is 128 even for tiny E
                tomb = np.concatenate(
                    [tomb, np.zeros(epad - len(tomb), np.int32)])
            d_src, d_dst, d_ts = self._delta.live_edges()
            d_indptr64, d_indices, _ = coo_to_csr(d_src, d_dst, n)
            bucket = _fanout_bucket(len(d_indices))
            d_ind = np.zeros(bucket, dtype=np.int32)
            d_ind[:len(d_indices)] = d_indices
            d_ts_pad = None
            base_ts_pad = None
            if self.has_ts:
                order = np.argsort(d_src, kind="stable")
                d_ts_pad = np.zeros(bucket, dtype=np.int32)
                d_ts_pad[:len(d_indices)] = d_ts[order]
                base_ts_pad = _pad128(self._base_ts)
                if len(base_ts_pad) != epad:
                    base_ts_pad = np.concatenate(
                        [base_ts_pad,
                         np.zeros(epad - len(base_ts_pad), np.int32)])
            put = (lambda a: jax.device_put(jnp.asarray(a), device)
                   if device is not None else jnp.asarray(a))
            snap = DeltaSnapshot(
                indptr=indptr, indices=indices,
                tomb=put(tomb),
                d_indptr=put(_pad128(d_indptr64.astype(np.int32))),
                d_indices=put(d_ind),
                base_ts=None if base_ts_pad is None else put(base_ts_pad),
                d_ts=None if d_ts_pad is None else put(d_ts_pad),
                version=self._version, epad=epad, delta_bucket=bucket,
                has_ts=self.has_ts, pending=len(d_indices),
            )
            self._snap = snap
            return snap

    def __repr__(self):
        return (f"StreamingGraph(base={self._base!r}, "
                f"pending={self.pending_deltas}, "
                f"tombstones={self.tombstone_count}, "
                f"version={self.version})")
