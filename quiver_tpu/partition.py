"""Offline probability-weighted feature partitioning.

Reference parity: ``srcs/python/quiver/partition.py`` —
``partition_without_replication`` (chunked greedy scoring, :16-80),
``select_nodes`` (:83), ``partition_feature_without_replication`` (:95-160),
``quiver_partition_feature`` / ``load_quiver_feature_partition`` (:163-283).

The algorithm is identical in spirit (it's offline numpy/jnp math — the
reference ran it on GPU tensors, we run it through jnp so it jits on TPU or
CPU): nodes are assigned in probability-descending chunks to the partition
where their own access probability most exceeds the other partitions',
balancing partition sizes.
"""

from __future__ import annotations

import os
from typing import List, Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "partition_without_replication",
    "select_nodes",
    "partition_feature_without_replication",
    "quiver_partition_feature",
    "load_quiver_feature_partition",
]

CHUNK_NUM = 32


def partition_without_replication(
    probs: Sequence[np.ndarray], ids: Optional[np.ndarray] = None,
    chunk_num: int = CHUNK_NUM,
) -> List[np.ndarray]:
    """Assign each node to exactly one partition.

    Args:
      probs: per-partition access-probability vectors ``[N]`` (from
        ``GraphSageSampler.sample_prob`` per partition's train set).
      ids: optional subset of node ids to partition (default: all).

    Greedy chunked scheme (parity with partition.py:16-80): process nodes in
    descending total probability, in ``chunk_num`` rounds; within a round
    each partition takes (from the still-unassigned chunk) the nodes where
    its own probability minus the sum of the others' is largest, taking
    equal shares.
    """
    probs = [np.asarray(p, dtype=np.float64) for p in probs]
    n_parts = len(probs)
    N = probs[0].shape[0]
    if ids is None:
        ids = np.arange(N, dtype=np.int64)
    else:
        ids = np.asarray(ids, dtype=np.int64)
    total = sum(p[ids] for p in probs)
    order = ids[np.argsort(-total, kind="stable")]
    res: List[List[np.ndarray]] = [[] for _ in range(n_parts)]
    chunks = np.array_split(order, chunk_num)
    for ci, chunk in enumerate(chunks):
        if len(chunk) == 0:
            continue
        remaining = chunk.copy()
        share = int(np.ceil(len(chunk) / n_parts))
        # rotate the starting partition per chunk so small chunks don't
        # starve the high-numbered partitions
        for p in [(ci + q) % n_parts for q in range(n_parts)]:
            if len(remaining) == 0:
                break
            own = probs[p][remaining]
            others = sum(probs[q][remaining] for q in range(n_parts)
                         if q != p)
            score = own - others
            take = min(share, len(remaining))
            pick = np.argsort(-score, kind="stable")[:take]
            res[p].append(remaining[pick])
            keep = np.ones(len(remaining), dtype=bool)
            keep[pick] = False
            remaining = remaining[keep]
        if len(remaining):
            res[-1].append(remaining)
    return [
        np.concatenate(r) if r else np.empty(0, dtype=np.int64) for r in res
    ]


def select_nodes(probs: Sequence[np.ndarray], ids=None):
    """Split nodes into (accessed-by-any, never-accessed); parity :83."""
    total = sum(np.asarray(p, dtype=np.float64) for p in probs)
    if ids is not None:
        mask = np.zeros_like(total, dtype=bool)
        mask[np.asarray(ids)] = True
        total = np.where(mask, total, 0)
    accessed = np.nonzero(total > 0)[0]
    unaccessed = np.nonzero(total <= 0)[0]
    return accessed, unaccessed


def partition_feature_without_replication(
    probs: Sequence[np.ndarray], chunk_num: int = CHUNK_NUM
) -> Tuple[List[np.ndarray], List[np.ndarray], np.ndarray]:
    """Partition accessed nodes; also return per-partition hot-cache order.

    Returns (partition id lists, per-partition probability-descending cache
    order within the partition, unaccessed ids) — parity with
    partition.py:95-160 where each partition also gets a cache priority.
    """
    accessed, unaccessed = select_nodes(probs)
    parts = partition_without_replication(probs, accessed, chunk_num)
    orders = []
    for p, part in enumerate(parts):
        pr = np.asarray(probs[p], dtype=np.float64)[part]
        orders.append(part[np.argsort(-pr, kind="stable")])
    return parts, orders, unaccessed


def quiver_partition_feature(
    feature: np.ndarray, probs: Sequence[np.ndarray], result_path: str,
    chunk_num: int = CHUNK_NUM,
):
    """Write partition artifacts to disk (parity: partition.py:163-249).

    Layout: ``{result_path}/feature_partition_{p}/partition_res.npy`` (node
    ids), ``cache_res.npy`` (cache-priority order), ``feature.npy`` (rows),
    and a global ``feature_partition_book.npy`` (node -> partition).
    """
    feature = np.asarray(feature)
    parts, orders, unaccessed = partition_feature_without_replication(
        probs, chunk_num
    )
    n_parts = len(parts)
    book = np.full(feature.shape[0], -1, dtype=np.int32)
    os.makedirs(result_path, exist_ok=True)
    for p in range(n_parts):
        book[parts[p]] = p
    # unaccessed nodes round-robin so every row has a home
    if len(unaccessed):
        book[unaccessed] = np.arange(len(unaccessed)) % n_parts
        parts = [
            np.concatenate([parts[p], unaccessed[book[unaccessed] == p]])
            for p in range(n_parts)
        ]
    np.save(os.path.join(result_path, "feature_partition_book.npy"), book)
    for p in range(n_parts):
        d = os.path.join(result_path, f"feature_partition_{p}")
        os.makedirs(d, exist_ok=True)
        np.save(os.path.join(d, "partition_res.npy"), parts[p])
        np.save(os.path.join(d, "cache_res.npy"), orders[p])
        np.save(os.path.join(d, "feature.npy"), feature[parts[p]])
    return parts, orders, book


def load_quiver_feature_partition(partition_idx: int, result_path: str):
    """Load one partition's artifacts (parity: partition.py:252-283)."""
    d = os.path.join(result_path, f"feature_partition_{partition_idx}")
    ids = np.load(os.path.join(d, "partition_res.npy"))
    cache_order = np.load(os.path.join(d, "cache_res.npy"))
    feature = np.load(os.path.join(d, "feature.npy"))
    book = np.load(os.path.join(result_path, "feature_partition_book.npy"))
    return ids, cache_order, feature, book
