"""Ring-structured sharded feature gather.

Complement to :class:`DistFeature`'s all-to-all exchange.  When every
device needs rows scattered across ALL shards (dense demand — large
batches, small shard count), rotating the shards around the ring and
picking up matches each step moves each shard exactly once over ICI
(all-gather bandwidth) instead of paying per-request all-to-all overhead —
the same reasoning behind ring attention's rotation of KV blocks, applied
to the feature dimension.  Demand-sparse workloads should stay on
DistFeature.

Mechanism per step (``shard_map`` body, ``jax.lax.ppermute`` rotation):
every device holds the wanted-ids list; as each foreign shard arrives it
resolves ``ids in [base, base+rows)`` locally and accumulates.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:
    from jax import shard_map  # jax >= 0.8
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map  # type: ignore

__all__ = ["RingFeature"]


class RingFeature:
    """Row-range-sharded feature with ring-rotation lookup.

    Rows are contiguously range-sharded: device d owns
    ``[d*rows_per, (d+1)*rows_per)`` (pad the feature to a multiple).
    """

    def __init__(self, feature: np.ndarray, mesh: Mesh, axis: str = "data"):
        self.mesh = mesh
        self.axis = axis
        self.n = int(mesh.shape[axis])
        n_rows, d = feature.shape
        self.rows_per = (n_rows + self.n - 1) // self.n
        pad = self.rows_per * self.n - n_rows
        if pad:
            feature = np.concatenate(
                [feature, np.zeros((pad, d), feature.dtype)]
            )
        self.node_count = n_rows
        self.dim = d
        sh = NamedSharding(mesh, P(axis, None))
        self.shards = jax.device_put(feature, sh)
        self._fn = {}

    def _build(self, B: int):
        n, axis, rows_per = self.n, self.axis, self.rows_per

        def body(shard, ids):
            # shard: [rows_per, D] local; ids: [1, B] this device's wants
            ids = ids[0]
            me = jax.lax.axis_index(axis)
            # derive from a varying value so the carry's manual-axes
            # annotation is stable across the fori_loop (shard_map VMA)
            out = jnp.zeros((B, shard.shape[1]), shard.dtype) + (
                shard[0, 0] * 0
            )

            def step(s, carry):
                block, out = carry
                # block currently holds the shard of device (me - s) % n
                owner = (me - s) % n
                base = owner * rows_per
                local = ids - base
                hit = (local >= 0) & (local < rows_per)
                rows = jnp.take(block, jnp.clip(local, 0, rows_per - 1),
                                axis=0)
                out = jnp.where(hit[:, None], rows, out)
                # rotate: send my current block to the next device
                block = jax.lax.ppermute(
                    block, axis,
                    [(i, (i + 1) % n) for i in range(n)],
                )
                return block, out

            block, out = jax.lax.fori_loop(0, n, step, (shard, out))
            return out[None]

        f = shard_map(
            body, mesh=self.mesh,
            in_specs=(P(axis, None), P(axis, None)),
            out_specs=P(axis, None),
        )
        return jax.jit(f)

    def lookup(self, ids):
        """``ids``: [n_devices, B] per-device wanted rows -> [n, B, D]."""
        ids = jnp.asarray(ids, jnp.int32)
        nd, B = ids.shape
        assert nd == self.n
        if B not in self._fn:
            self._fn[B] = self._build(B)
        sh = NamedSharding(self.mesh, P(self.axis, None))
        return self._fn[B](self.shards, jax.device_put(ids, sh))
