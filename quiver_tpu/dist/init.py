"""Multi-host bootstrap + hybrid DCN x ICI meshes.

Reference parity: the NCCL-id bootstrap via torch TCPStore
(``benchmarks/ogbn-papers100M/train_quiver_multi_node.py:405-411``) and the
HostRankTable (``comm.py:5-39``).  In jax the id exchange is
``jax.distributed.initialize`` and the rank table is the device list's
``process_index`` — what remains worth wrapping is the **mesh layout**:
put the fast axis (ICI, intra-slice) minor and the slow axis (DCN,
cross-host) major, so feature shards exchange over ICI within a host
group and only partition traffic crosses DCN (the same NVLink-clique /
NCCL-tier split the reference hand-builds).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

__all__ = ["initialize", "make_hybrid_mesh"]


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None):
    """``jax.distributed.initialize`` passthrough (no-op if single
    process or already initialized)."""
    import jax

    try:
        if coordinator_address is not None:
            jax.distributed.initialize(coordinator_address, num_processes,
                                       process_id)
        else:
            jax.distributed.initialize()
    except (RuntimeError, ValueError):
        pass  # single-process / already initialized
    return jax.process_count(), jax.process_index()


def make_hybrid_mesh(ici_axis: str = "ici", dcn_axis: str = "dcn"):
    """Mesh [n_hosts, devices_per_host] with DCN major, ICI minor.

    On a single process this degenerates to [1, n_devices] — code written
    against the two axes runs unchanged (collectives over a size-1 axis
    are no-ops).
    """
    import jax
    from jax.sharding import Mesh

    devs = jax.devices()
    by_proc = {}
    for d in devs:
        by_proc.setdefault(d.process_index, []).append(d)
    n_proc = len(by_proc)
    per = min(len(v) for v in by_proc.values())
    grid = np.array(
        [sorted(v, key=lambda d: d.id)[:per]
         for _, v in sorted(by_proc.items())]
    )
    return Mesh(grid, (dcn_axis, ici_axis))
