"""Cross-host partitioned feature store — TPU-native ``DistFeature``.

Reference parity: ``PartitionInfo`` (``feature.py:461-526``) and
``DistFeature`` (``feature.py:529-567``) + the NCCL ``exchange``
(``comm.py:127-182``).

TPU-first redesign: the whole request/response dance — dispatch ids by
owner, send id lists, remote gather, send features back, scatter merge — is
ONE jitted ``shard_map`` body with two ``all_to_all``s.  Ragged per-host
request counts become fixed-capacity buckets with validity masks (the
static-shape discipline); XLA overlaps the collective with the local gather.

Layout: the partitioned feature lives as a single ``jax.Array`` of shape
``[n_parts * max_local, D]`` sharded over the mesh axis, so "host p's
shard" is rows ``[p*max_local, (p+1)*max_local)`` — device-local on p.
"""

from __future__ import annotations

import threading
from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:
    from jax import shard_map  # jax >= 0.8
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map  # type: ignore

from ..resilience import chaos
from ..resilience.deadline import check_ambient
from ..resilience.errors import PeerTimeout

__all__ = ["PartitionInfo", "DistFeature"]

# fault-injection site for the cross-host exchange (no-op unless a
# chaos plan is installed)
_CHAOS_EXCHANGE = chaos.point("dist.feature.exchange")


class PartitionInfo:
    """Node -> (owner, local slot) maps (parity: ``feature.py:461-526``).

    Args:
      device: this rank (kept for parity).
      host: host index of this rank.
      hosts: number of hosts (partitions).
      global2host: ``[N]`` int array, owner host per node.
      replicate: optional id array of nodes replicated on every host.
    """

    def __init__(self, device=0, host: int = 0, hosts: int = 1,
                 global2host=None, replicate=None):
        self.device = device
        self.host = host
        self.hosts = hosts
        self.global2host = np.asarray(global2host, dtype=np.int32)
        n = self.global2host.shape[0]
        self.replicate_mask = np.zeros(n, dtype=bool)
        if replicate is not None:
            self.replicate_mask[np.asarray(replicate)] = True
        # local slot of each node on its owner (replicated nodes get a slot
        # on EVERY host: they're appended after the owned block).
        owner = self.global2host.copy()
        self.global2local = np.zeros(n, dtype=np.int32)
        owned_counts = np.zeros(hosts, dtype=np.int64)
        order = np.argsort(owner, kind="stable")
        for h in range(hosts):
            ids = order[owner[order] == h]
            ids = ids[~self.replicate_mask[ids]]
            self.global2local[ids] = np.arange(len(ids), dtype=np.int32)
            owned_counts[h] = len(ids)
        self.owned_counts = owned_counts
        rep_ids = np.nonzero(self.replicate_mask)[0]
        self.rep_ids = rep_ids
        # replicated nodes: slot = owned_count(host) + rank in rep list —
        # assigned at build time per host (see DistFeature.build_shards).
        self.max_local = int(owned_counts.max() + len(rep_ids))

    @classmethod
    def from_partition_book(cls, book, device=0, host: int = 0,
                            hosts: Optional[int] = None, replicate=None):
        """Build from a ``feature_partition_book`` (node -> partition id),
        the artifact written by :func:`quiver_tpu.quiver_partition_feature`
        (parity: the loader flow at partition.py:252-283)."""
        book = np.asarray(book)
        return cls(device=device, host=host,
                   hosts=hosts if hosts is not None else int(book.max()) + 1,
                   global2host=book, replicate=replicate)

    def dispatch(self, ids: np.ndarray):
        """Parity helper (``feature.py:510-526``): bucket ids per host.

        Returns (list of id arrays per host, list of position arrays).
        Served from DistFeature's jitted path in production; kept for tests
        and API compat.
        """
        ids = np.asarray(ids)
        owner = np.where(self.replicate_mask[ids], self.host,
                         self.global2host[ids])
        out_ids, out_pos = [], []
        for h in range(self.hosts):
            m = owner == h
            out_ids.append(ids[m])
            out_pos.append(np.nonzero(m)[0])
        return out_ids, out_pos


class DistFeature:
    """Sharded feature with all-to-all remote lookup.

    Build with :meth:`from_global_feature` (single-controller: the full
    feature is available and gets laid out into shards), then index with
    ``dist_feature[ids]`` where ``ids`` is ``[n_hosts, B]`` (one query batch
    per host shard) or ``[B]`` (this host's batch, parity mode).

    :meth:`enable_cold_cache` attaches a per-host HBM overlay in front
    of the all-to-all: this host's recurring remote rows are served from
    a local device table instead of round-tripping the collective
    (``docs/FEATURE_CACHE.md``); the overlay state is guarded by
    ``_ov_lock`` (quiverlint QT003).
    """

    _guarded_by = {"_overlay": "_ov_lock"}

    def __init__(self, mesh: Mesh, info: PartitionInfo, axis: str = "data",
                 request_cap: Optional[int] = None):
        self.mesh = mesh
        self.info = info
        self.axis = axis
        self.n = int(mesh.shape[axis])
        assert self.n == info.hosts, (self.n, info.hosts)
        self.request_cap = request_cap
        self.shards = None       # [n*max_local, D] sharded
        self.g2l = None          # [N] int32 device (local slot incl. replicas)
        self.g2h = None          # [N] int32 device
        self._fn = {}
        self._host_source = None  # numpy global feature (overlay admission)
        self.cold_cache = None    # ColdRowCache over global-id space
        self._overlay = None      # jax.Array [C, D] per-host overlay table
        self._ov_lock = threading.Lock()
        # degrade telemetry: True when the most recent lookup fell back
        # to locally resolvable rows on a peer-shard timeout
        self.last_degraded = False
        self.last_degraded_mask = None

    @classmethod
    def from_global_feature(cls, feature: np.ndarray, mesh: Mesh,
                            info: PartitionInfo, axis: str = "data",
                            request_cap: Optional[int] = None):
        self = cls(mesh, info, axis, request_cap)
        n, d = feature.shape
        m = info.max_local
        shards = np.zeros((info.hosts, m, d), dtype=feature.dtype)
        g2l = info.global2local.copy()
        for h in range(info.hosts):
            owned = np.nonzero(
                (info.global2host == h) & ~info.replicate_mask
            )[0]
            shards[h, g2l[owned]] = feature[owned]
            base = info.owned_counts[h]
            if len(info.rep_ids):
                shards[h, base: base + len(info.rep_ids)] = (
                    feature[info.rep_ids]
                )
        # replicated nodes resolve to the local copy on every host; their
        # slot depends on the host's owned_count, so store per-host offset
        # and fold at lookup (slot = owned_count[host] + rep_rank).
        rep_rank = np.zeros(n, dtype=np.int32)
        rep_rank[info.rep_ids] = np.arange(len(info.rep_ids), dtype=np.int32)
        self._rep_rank = rep_rank
        self._host_source = np.asarray(feature)  # overlay admission source
        sharding = NamedSharding(mesh, P(axis, None, None))
        self.shards = jax.device_put(shards, sharding)
        self.g2l = jnp.asarray(g2l)
        self.g2h = jnp.asarray(info.global2host)
        self.rep_mask = jnp.asarray(info.replicate_mask)
        self.rep_rank = jnp.asarray(rep_rank)
        self.owned_counts = jnp.asarray(info.owned_counts.astype(np.int32))
        return self

    # ------------------------------------------------------------------
    def _build(self, B: int, cap: int):
        n, axis = self.n, self.axis
        g2l, g2h = self.g2l, self.g2h
        rep_mask, rep_rank = self.rep_mask, self.rep_rank
        owned_counts = self.owned_counts

        def body(shard, ids, valid):
            # shard: [1, m, D]; ids, valid: [1, B] — this rank's query batch.
            shard = shard[0]
            ids, valid = ids[0], valid[0]
            me = jax.lax.axis_index(axis)
            local_rep = rep_mask[ids]
            owner = jnp.where(local_rep, me, g2h[ids])
            owner = jnp.where(valid, owner, n)  # invalid -> nowhere
            # rank of each query within its destination bucket
            onehot = (owner[:, None] == jnp.arange(n)[None, :])
            rank_in = jnp.cumsum(onehot, axis=0) - 1
            slot = jnp.sum(jnp.where(onehot, rank_in, 0), axis=1)
            overflow = slot >= cap
            dest = jnp.where(valid & ~overflow, owner * cap + slot, n * cap)
            # requests: [n*cap] node ids (+1 shift, 0 = empty)
            reqs = jnp.zeros((n * cap,), jnp.int32).at[dest].add(
                (ids + 1).astype(jnp.int32), mode="drop"
            )
            reqs = reqs.reshape(n, cap)
            # ---- phase 1: ship request ids to owners
            recv = jax.lax.all_to_all(reqs, axis, split_axis=0,
                                      concat_axis=0, tiled=True)
            # recv: [n, cap] requests FROM each source rank, for me.
            rids = recv.reshape(-1) - 1
            rvalid = rids >= 0
            rid_safe = jnp.where(rvalid, rids, 0)
            lslot = jnp.where(
                rep_mask[rid_safe],
                owned_counts[me] + rep_rank[rid_safe],
                g2l[rid_safe],
            )
            feats = jnp.take(shard, lslot, axis=0)
            feats = jnp.where(rvalid[:, None], feats, 0)
            feats = feats.reshape(n, cap, -1)
            # ---- phase 2: ship features back to requesters
            back = jax.lax.all_to_all(feats, axis, split_axis=0,
                                      concat_axis=0, tiled=True)
            flat = back.reshape(n * cap, -1)
            gathered = jnp.take(flat, jnp.clip(dest, 0, n * cap - 1),
                                axis=0)
            out = jnp.where((valid & ~overflow)[:, None], gathered, 0)
            ocount = (valid & overflow).sum().astype(jnp.int32)
            return out[None], ocount[None]

        f = shard_map(
            body, mesh=self.mesh,
            in_specs=(P(axis, None, None), P(axis, None), P(axis, None)),
            out_specs=(P(axis, None, None), P(axis)),
        )
        return jax.jit(f)

    # -- per-host cold-row overlay (docs/FEATURE_CACHE.md) -------------
    def enable_cold_cache(self, rows: Optional[int] = None,
                          policy: Optional[str] = None,
                          admit_threshold: Optional[int] = None
                          ) -> "DistFeature":
        """Attach a per-host HBM overlay over the remote-row space.

        This host's recurring remote (non-replicated, other-owner) rows
        are admitted into a local ``[rows, D]`` device table; overlay
        hits drop out of the all-to-all entirely — their valid bit
        clears (freeing request-bucket capacity) and the rows come back
        as a device-side patch after the collective.
        """
        assert self._host_source is not None, (
            "enable_cold_cache needs from_global_feature (the host-side "
            "source copy feeds admission)"
        )
        from ..config import get_config
        from ..ops.coldcache import ColdRowCache

        cfg = get_config()
        n, d = self._host_source.shape
        if rows is None:
            rows = max(1024, self.info.max_local // 4)
        rows = int(min(rows, n))
        policy = policy or cfg.cold_cache_policy
        admit = (admit_threshold if admit_threshold is not None
                 else cfg.cold_cache_admit)
        with self._ov_lock:
            self.cold_cache = ColdRowCache(rows, n, policy=policy,
                                           admit_threshold=admit)
            self._overlay = jnp.zeros(
                (rows, d), dtype=self._host_source.dtype)
        return self

    def invalidate_rows(self, global_ids) -> int:
        """Drop mutated rows (GLOBAL node ids) from this host's overlay.

        Streaming mutations call this on every host (the overlay caches
        remote rows, so the mutating host cannot know who holds a stale
        copy — ``StreamingGraph.attach_feature`` wires the local store;
        multi-host deployments broadcast the touched ids alongside the
        edge updates themselves).  Same contract as
        ``Feature.invalidate_rows``: resident slots drop, admission
        evidence resets.  Returns overlay slots dropped.
        """
        from .. import telemetry

        if self.cold_cache is None:
            return 0
        ids = np.atleast_1d(np.asarray(global_ids, dtype=np.int64))
        with self._ov_lock:
            cache = self.cold_cache
            dropped = (cache.invalidate_rows(ids)
                       if cache is not None else 0)
        if dropped:
            telemetry.counter("coldcache_invalidated_rows_total").inc(
                dropped)
        return dropped

    def _ov_patch_fn(self, B, bucket, me):
        """Cached per-(B, bucket) patch program: scatter overlay hits
        into this host's output row (pad pos = B, dropped)."""
        key = ("ov_patch", B, bucket)
        fn = self._fn.get(key)
        if fn is None:

            @jax.jit
            def fn(out, table, slot, pos):
                rows = jnp.take(table, slot, axis=0)
                return out.at[me, pos].set(rows, mode="drop")

            self._fn[key] = fn
        return fn

    def _ov_admit_fn(self, bucket):
        """Cached per-bucket overlay scatter-update (pad slot =
        capacity, dropped).  No donation: an earlier patch closure may
        still hold the previous table value."""
        key = ("ov_admit", bucket)
        fn = self._fn.get(key)
        if fn is None:

            @jax.jit
            def fn(table, slots, rows):
                return table.at[slots].set(rows, mode="drop")

            self._fn[key] = fn
        return fn

    def _overlay_probe(self, ids, valid):
        """Host-side overlay step for this host's query row.

        Probes the remote non-replicated ids, clears the valid bit of
        hits (they skip the all-to-all), admits recurring misses from
        the host source copy, and returns a patch closure applying the
        hits to the collective's output — or None when nothing hit.
        Mirrors ``Feature._stage_overlay``'s atomicity: probe + admit +
        table update + table-value capture all under ``_ov_lock``.
        """
        from ..feature import _pow2_bucket
        from .. import telemetry

        me = self.info.host
        B = ids.shape[1]
        row = ids[me]
        cand = (valid[me] & ~self.info.replicate_mask[row]
                & (self.info.global2host[row] != me))
        pos_all = np.nonzero(cand)[0].astype(np.int32)
        if not len(pos_all):
            return None
        gids = row[pos_all].astype(np.int64)
        n_evicted = 0
        with self._ov_lock:
            cache = self.cold_cache
            hit_mask, slots = cache.probe(gids)
            n_hit = int(hit_mask.sum())
            table = self._overlay  # value consistent with the probe
            miss_ids = gids[~hit_mask]
            if len(miss_ids):
                adm, n_evicted = cache.admit(miss_ids)
                amask = adm >= 0
                if amask.any():
                    ba = _pow2_bucket(int(amask.sum()))
                    adm_slot = np.full(ba, cache.capacity, dtype=np.int32)
                    adm_slot[: int(amask.sum())] = adm[amask]
                    rows = np.zeros((ba, self._host_source.shape[1]),
                                    dtype=self._host_source.dtype)
                    rows[: int(amask.sum())] = (
                        self._host_source[miss_ids[amask]]
                    )
                    self._overlay = self._ov_admit_fn(ba)(
                        self._overlay, jnp.asarray(adm_slot),
                        jnp.asarray(rows))
            row_bytes = (self._host_source.shape[1]
                         * self._host_source.dtype.itemsize)
            resident_bytes = cache.resident_bytes(row_bytes)
        telemetry.gauge("dist_feature_overlay_resident_bytes").set(
            float(resident_bytes))
        telemetry.counter("dist_feature_coldcache_rows_total",
                          result="hit").inc(float(n_hit))
        telemetry.counter("dist_feature_coldcache_rows_total",
                          result="miss").inc(float(len(gids) - n_hit))
        if n_evicted:
            telemetry.counter(
                "dist_feature_coldcache_evictions_total").inc(
                float(n_evicted))
        from ..telemetry import flightrec

        if flightrec.tracing():
            flightrec.event("dist.exchange", {
                "probe_hit": int(n_hit),
                "probe_miss": int(len(gids) - n_hit),
                "evicted": int(n_evicted)})
        if n_hit == 0:
            return None
        hit_pos = pos_all[hit_mask]
        valid[me, hit_pos] = False  # hits skip the all-to-all
        bh = _pow2_bucket(n_hit)
        # bucket-edge discipline (see Feature._stage): the bucket covers
        # every real hit; padded lanes carry the out-of-range sentinel B
        assert n_hit <= bh, (n_hit, bh)
        ov_slot = np.zeros(bh, dtype=np.int32)
        ov_slot[:n_hit] = slots[hit_mask]
        ov_pos = np.full(bh, B, dtype=np.int32)
        ov_pos[:n_hit] = hit_pos
        fn = self._ov_patch_fn(B, bh, me)
        slot_d, pos_d = jnp.asarray(ov_slot), jnp.asarray(ov_pos)
        return lambda out: fn(out, table, slot_d, pos_d)

    def lookup(self, ids, valid=None):
        """``ids``: [n_hosts, B] int32 (one batch per host).  Returns
        [n_hosts, B, D] with each host's features resolved.

        After each call ``self.last_overflow`` holds a ``[n_hosts]`` device
        array counting queries that overflowed their destination bucket and
        got ZERO feature rows.  Always zero when ``request_cap`` is None
        (cap = B, the exact worst case); check :meth:`overflow_stats` when
        running with a reduced cap — training on silently zeroed features
        is the failure mode this guards against."""
        check_ambient("dist_feature")
        ov_patch = None
        if self.cold_cache is not None and not isinstance(ids, jax.Array):
            # host-side overlay probe needs host ids; device ids would
            # force a sync here, so they bypass the overlay entirely
            ids = np.asarray(ids, dtype=np.int32)
            valid = (np.ones(ids.shape, dtype=bool) if valid is None
                     else np.array(valid, dtype=bool))  # copy: bits clear
            ov_patch = self._overlay_probe(ids, valid)
        ids = jnp.asarray(ids, jnp.int32)
        nh, B = ids.shape
        if valid is None:
            valid = jnp.ones((nh, B), bool)
        cap = self.request_cap or B
        key = (B, cap)
        if key not in self._fn:
            self._fn[key] = self._build(B, cap)
        sharding = NamedSharding(self.mesh, P(self.axis, None))
        ids = jax.device_put(ids, sharding)
        valid = jax.device_put(valid, sharding)
        try:
            _CHAOS_EXCHANGE()
            out, overflow = self._fn[key](self.shards, ids, valid)
        except (PeerTimeout, TimeoutError):
            # peer shard timed out: degrade to the rows resolvable
            # WITHOUT the collective (owned / replicated / overlay-hit),
            # zeros elsewhere, flagged via last_degraded — stale-local
            # beats stalling the whole serving pipeline on one peer
            return self._degraded_lookup(np.asarray(ids),
                                         np.asarray(valid))
        self.last_degraded = False
        self.last_overflow = overflow
        self._overflow_recorded = False
        if ov_patch is not None:
            out = ov_patch(out)
        from ..telemetry import flightrec

        if flightrec.tracing():
            flightrec.event("dist.lookup", {
                "hosts": int(nh), "batch": int(B),
                "overlay_patched": ov_patch is not None})
        return out

    def _degraded_lookup(self, ids: np.ndarray, valid: np.ndarray):
        """Peer-timeout fallback: each host row keeps the rows its own
        shard can answer (owned by it, replicated everywhere, or — for
        this host — sitting in the cold-row overlay); everything else
        comes back zero.  ``last_degraded`` flags the result and
        ``last_degraded_mask`` says which rows are real."""
        # the request's deadline likely burned while the peer timed out:
        # shed HERE, before the local-rows gather, not after — the
        # serving loop installed the batch deadline as ambient scope
        check_ambient("dist_feature")
        from .. import telemetry
        from ..telemetry import flightrec

        info = self.info
        src = self._host_source
        assert src is not None, (
            "degraded lookup needs from_global_feature (the host-side "
            "source copy is the hot tier it serves from)")
        nh, B = ids.shape
        owner = info.global2host[ids]
        local = valid & (info.replicate_mask[ids]
                         | (owner == np.arange(nh)[:, None]))
        if self.cold_cache is not None:
            me = info.host
            pos = np.nonzero(valid[me] & ~local[me])[0]
            if len(pos):
                with self._ov_lock:
                    hit, _ = self.cold_cache.probe(
                        ids[me, pos].astype(np.int64))
                local[me, pos[hit]] = True
        out = np.zeros((nh, B, src.shape[1]), dtype=src.dtype)
        out[local] = src[ids[local]]
        self.last_degraded = True
        self.last_degraded_mask = local
        self.last_overflow = np.zeros((nh,), np.int32)
        self._overflow_recorded = True
        telemetry.counter("dist_feature_degraded_total").inc()
        if flightrec.tracing():
            flightrec.event("dist.lookup", {
                "degraded": True, "hosts": int(nh), "batch": int(B),
                "served": int(local.sum()),
                "dropped": int((valid & ~local).sum())})
        return out

    def overflow_stats(self):
        """Per-host dropped-query counts from the most recent lookup as a
        host int array (None before any call).  Materializing here also
        feeds ``dist_feature_overflow_total`` — at query time, never in
        the lookup hot path (that would force a device sync)."""
        if getattr(self, "last_overflow", None) is None:
            return None
        arr = np.asarray(self.last_overflow)
        if not getattr(self, "_overflow_recorded", True):
            self._overflow_recorded = True
            total = float(arr.sum())
            if total:
                from .. import telemetry

                telemetry.counter("dist_feature_overflow_total").inc(total)
        return arr

    def __getitem__(self, ids):
        ids = np.asarray(ids)
        if ids.ndim == 1:  # parity mode: same batch replicated per host
            if not getattr(self, "_warned_1d", False):
                import warnings

                warnings.warn(
                    "DistFeature[1-D ids] broadcasts the batch to every "
                    "host shard (n_hosts x bandwidth) — a parity shim for "
                    "the reference's per-rank __getitem__.  Pass "
                    "[n_hosts, B] ids to lookup() for the efficient path.",
                    stacklevel=2,
                )
                self._warned_1d = True
            out = self.lookup(np.tile(ids[None], (self.n, 1)))
            return out[self.info.host]
        return self.lookup(ids)
