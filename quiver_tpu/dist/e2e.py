"""End-to-end distributed training driver (shared by the multichip dryrun
and the scaled slow test).

The reference's multi-node path is the papers100M benchmark
(``benchmarks/ogbn-papers100M/train_quiver_multi_node.py:270-306``):
DDP ranks, row-partitioned DistFeature, NCCL exchange.  Here the same
shape runs as one jit program set over a mesh: row-sharded
:class:`DistGraphSampler` (all-to-all seed routing), all-to-all
:class:`DistFeature`, and a data-parallel train step (XLA psum = DDP).

``run_dist_training`` is sized by arguments so the driver's dryrun can run
it tiny and the slow test at 100K+ nodes with the reference fanout.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

__all__ = ["run_dist_training"]


def run_dist_training(n_devices: int, n_nodes: int = 256,
                      avg_deg: int = 8, feat_dim: int = 16,
                      batch_per_dev: int = 16,
                      sizes: Sequence[int] = (4, 3),
                      steps: int = 1, classes: int = 8,
                      lr: float = 3e-3, seed: int = 0,
                      learnable_labels: bool = True,
                      hier: Optional[tuple] = None):
    """Run ``steps`` DP training steps over an ``n_devices`` mesh.

    Returns a dict with per-step ``losses``, the sampler's summed overflow
    counts, and the feature-store overflow counts — callers assert on
    them.  Labels are a linear function of the features by default so the
    loss can actually decrease (random labels can't prove learning).

    ``hier=(n_hosts, hot_frac)`` swaps the flat DistFeature for the
    two-tier :class:`HierFeature` over a ``[n_hosts, n_devices/n_hosts]``
    DCN x ICI mesh (degree-ordered hot set); the result dict then also
    carries summed ``dcn_crossings``.
    """
    import jax
    import jax.numpy as jnp
    import optax

    from quiver_tpu import CSRTopo, DistFeature, PartitionInfo
    from quiver_tpu.dist.hier import HierFeature
    from quiver_tpu.dist.sampler import DistGraphSampler
    from quiver_tpu.models import GraphSAGE
    from quiver_tpu.parallel import TrainState, make_train_step
    from quiver_tpu.utils.mesh import make_mesh

    rng = np.random.default_rng(seed)
    deg = rng.poisson(avg_deg, n_nodes).astype(np.int64)
    src = np.repeat(np.arange(n_nodes), deg)
    dst = rng.integers(0, n_nodes, size=len(src))
    topo = CSRTopo(edge_index=np.stack([src, dst]))
    feat = rng.normal(size=(n_nodes, feat_dim)).astype(np.float32)
    if learnable_labels:
        w_true = rng.normal(size=(feat_dim, classes))
        labels = np.argmax(feat @ w_true, axis=1).astype(np.int32)
    else:
        labels = rng.integers(0, classes, n_nodes).astype(np.int32)

    mesh = make_mesh(("data",), devices=jax.devices()[:n_devices])
    hier_feat = None
    if hier is not None:
        from jax.sharding import Mesh

        n_hosts, hot_frac = hier
        C = n_devices // n_hosts
        hmesh = Mesh(
            np.array(jax.devices()[:n_devices]).reshape(n_hosts, C),
            ("dcn", "ici"),
        )
        # degree-descending order so the hot tier holds the high-traffic
        # rows; the sampler keeps GLOBAL ids, so remap at lookup time
        order = np.argsort(-topo.degree, kind="stable")
        old2new = np.empty(n_nodes, dtype=np.int32)
        old2new[order] = np.arange(n_nodes, dtype=np.int32)
        hot_count = int(n_nodes * hot_frac)
        g2h_hier = (np.arange(n_nodes) % n_hosts).astype(np.int32)
        hier_feat = HierFeature.from_global_feature(
            feat[order], hmesh, hot_count=hot_count,
            global2host=g2h_hier)
        hier_old2new = old2new
    dist_feat = None
    if hier is None:
        g2h = rng.integers(0, n_devices, topo.node_count).astype(np.int32)
        info = PartitionInfo(host=0, hosts=n_devices, global2host=g2h)
        dist_feat = DistFeature.from_global_feature(feat, mesh, info)
    sampler = DistGraphSampler(topo, mesh, sizes=list(sizes))

    model = GraphSAGE(hidden=32, out_dim=classes, num_layers=len(sizes),
                      dropout=0.0)
    B = batch_per_dev
    tx = optax.adam(lr)
    step_fn = make_train_step(
        lambda p, x, blocks, train=False, rngs=None: model.apply(
            p, x, blocks, train=train, rngs=rngs
        ),
        tx, mesh=mesh,
    )

    state = None
    losses = []
    sampler_overflow = np.zeros(len(sizes), dtype=np.int64)
    feat_overflow = 0
    dcn_crossings = 0
    masks = jnp.ones((n_devices, B), bool)
    for it in range(steps):
        seeds = rng.integers(0, n_nodes, (n_devices, B))
        n_id, n_mask, num, blocks = sampler.sample(seeds, key=seed + it)
        sampler_overflow += np.asarray(
            sampler.last_overflow
        ).sum(axis=0).astype(np.int64)
        if hier_feat is not None:
            ids = hier_old2new[np.asarray(n_id)]  # hot-order ids
            H, C = hier_feat.H, hier_feat.C
            out = hier_feat.lookup(ids.reshape(H, C, -1))
            st = hier_feat.traffic_stats()
            dcn_crossings += int(st["dcn_crossings"].sum())
            feat_overflow += int(st["drops"].sum())
            xs = jnp.asarray(out).reshape(n_devices, -1, feat_dim)
        else:
            xs = dist_feat.lookup(np.asarray(n_id))
            feat_overflow += int(np.asarray(dist_feat.last_overflow).sum())
        if state is None:
            params = model.init(
                jax.random.PRNGKey(1), xs[0],
                jax.tree_util.tree_map(lambda l: l[0], blocks),
            )
            state = TrainState.create(params, tx)
        labels_arr = jnp.asarray(labels[seeds])
        state, loss = step_fn(state, xs, blocks, labels_arr, masks,
                              jax.random.PRNGKey(100 + it))
        losses.append(float(loss))
    return dict(losses=losses, sampler_overflow=sampler_overflow,
                feature_overflow=feat_overflow, mesh=mesh,
                node_count=n_nodes, dcn_crossings=dcn_crossings)
