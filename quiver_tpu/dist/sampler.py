"""Distributed neighbor sampling over a row-sharded graph.

The reference handles graphs bigger than device memory with UVA: the CSR
stays in pinned host memory and CUDA kernels read it over PCIe
(``quiver.cu.hpp:16-26``, mode ``ZERO_COPY``).  The TPU equivalent is to
**shard the edge array over the mesh** and let ICI play the role of PCIe —
each device owns a contiguous row range (so ``indptr`` stays local and
dense), seeds are routed to their owner with the same fixed-capacity
all-to-all bucketing as :class:`quiver_tpu.dist.DistFeature`, sampled
neighbor blocks ride back on a second all-to-all.

papers100M at int32 is ~6.5 GB of indices — over a v5e-8 that is <1 GB per
chip, leaving HBM for features.  Single-chip sampling of a sharded graph is
the degenerate n=1 case (no collectives emitted).
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:
    from jax import shard_map  # jax >= 0.8
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map  # type: ignore

from ..resilience import chaos
from ..resilience.errors import PeerTimeout
from ..resilience.retry import Backoff, retry_call
from ..utils.topology import CSRTopo
from ..ops.sample import sample_neighbors
from ..sampler import LayerBlock, SampledBatch

__all__ = ["DistGraphSampler", "shard_csr_by_rows", "plan_row_shards"]

# fault-injection site for the per-hop all-to-all exchange (no-op
# unless a chaos plan is installed)
_CHAOS_EXCHANGE = chaos.point("dist.sampler.exchange")


def plan_row_shards(indptr, n_shards: int,
                    max_local_edges: int = 2**31 - 1):
    """Plan contiguous, edge-balanced row ranges from ``indptr`` alone.

    Returns ``row_starts`` ([n_shards+1] int64).  Raises if any shard's
    local edge count would overflow the int32 positions the on-device
    rebased indptr uses (same guard class as ``uva.py``'s hot tier) —
    this is the check the papers100M regime (>2^31 total edges,
    reference benchmarks/ogbn-papers100M/train_quiver_multi_node.py)
    rests on.  Needs no materialized edge array, so it is testable at
    any scale.
    """
    if n_shards < 1:
        raise ValueError(f"n_shards must be >= 1, got {n_shards}")
    indptr = np.asarray(indptr)
    n = len(indptr) - 1
    total = int(indptr[-1])
    target = total / n_shards
    row_starts = [0]
    for s in range(1, n_shards):
        row_starts.append(int(np.searchsorted(indptr, target * s)))
    row_starts.append(n)
    row_starts = np.asarray(row_starts, dtype=np.int64)
    local_edges = indptr[row_starts[1:]] - indptr[row_starts[:-1]]
    worst = int(local_edges.max())
    if worst > max_local_edges:
        need = -(-total // max_local_edges)
        raise ValueError(
            f"a row shard holds {worst:,} edges > int32 limit "
            f"{max_local_edges:,}; use at least ~{need} shards "
            f"(got {n_shards}) or a smaller graph partition"
        )
    if n > max_local_edges:
        raise ValueError(
            f"{n:,} nodes overflow the int32 row_starts/frontier ids"
        )
    return row_starts


def shard_csr_by_rows(topo: CSRTopo, n_shards: int):
    """Split a CSR into ``n_shards`` contiguous row ranges, balanced by
    edge count.  Returns (row_starts [n+1], local indptr list, local
    indices list) — local indptr is rebased to each shard's edge offset."""
    indptr = topo.indptr
    row_starts = plan_row_shards(indptr, n_shards)
    local_indptr, local_indices = [], []
    for s in range(n_shards):
        lo, hi = row_starts[s], row_starts[s + 1]
        ip = indptr[lo: hi + 1] - indptr[lo]
        local_indptr.append(ip.astype(np.int64))
        local_indices.append(
            topo.indices[indptr[lo]: indptr[hi]].astype(np.int32)
        )
    return row_starts, local_indptr, local_indices


class DistGraphSampler:
    """Multi-hop sampler over a row-sharded CSR on a device mesh.

    Args:
      topo: full host-side :class:`CSRTopo` (single-controller build).
      mesh: mesh whose ``axis`` dimension the edges shard over.
      sizes: fanouts (outward order).
      request_cap: per-destination bucket capacity as a fraction of the
        frontier (1.0 = worst case, always exact; smaller trades overflow
        drops for bandwidth — overflowed seeds just sample 0 neighbors).

    The per-hop exchange:
      1. owner = searchsorted(row_starts, frontier ids)
      2. all_to_all the bucketed ids to owners
      3. owner shard samples locally (dense ``[cap, k]`` + mask)
      4. all_to_all blocks back, unpacked to frontier order
    """

    def __init__(self, topo: CSRTopo, mesh: Mesh, sizes,
                 axis: str = "data", request_cap_frac: float = 1.0,
                 seed: int = 0, gather_mode: str = "auto",
                 sample_rng: str = "auto"):
        from ..config import resolve_gather_mode, resolve_sample_rng

        self.topo = topo
        self.mesh = mesh
        self.axis = axis
        gm = resolve_gather_mode(gather_mode, sample_rng)
        # rng resolves against the PRE-rewrite mode so auto still lands
        # on "hash" under a pwindow pick — keeping the per-shard draws
        # identical to the single-device pwindow stream
        self.sample_rng = resolve_sample_rng(sample_rng, gm)
        # pallas_call outputs need explicit vma annotations under
        # shard_map (jax >= 0.8 check_vma); until the kernels carry
        # them, every pallas-backed mode degrades to its XLA equivalent
        # for the per-shard local sampling: pwindow -> blocked (same
        # windows, same draws), pallas/lanes_fused -> lanes (same
        # row-gather + lane select, XLA-composed)
        if gm.startswith("pwindow"):
            gm = "blocked" + gm[len("pwindow"):]
        elif gm in ("pallas", "lanes_fused"):
            gm = "lanes"
        self.gather_mode = gm
        self.sizes = list(sizes)
        self.n = int(mesh.shape[axis])
        self.request_cap_frac = request_cap_frac
        row_starts, lips, lids = shard_csr_by_rows(topo, self.n)
        self.row_starts = jnp.asarray(row_starts, jnp.int32)
        # pad local shards to a common size, stack, shard over the mesh
        # (round up to 128 so the lanes gather's 128-lane reshape covers
        # the whole table — its tail truncation must never drop real rows)
        r128 = lambda v: -(-v // 128) * 128
        max_ip = r128(max(len(x) for x in lips))
        max_id = r128(max(len(x) for x in lids))
        # indptr pads repeat the final offset (padded "rows" read degree 0,
        # never negative — mirrors uva.py's hot-tier padding); indices pads
        # are plain zeros (never dereferenced: counts=min(deg,k) masks them)
        pad_edge = lambda a, m: np.pad(a, (0, m - len(a)), mode="edge")
        pad_zero = lambda a, m: np.pad(a, (0, m - len(a)))
        ip = np.stack([pad_edge(x, max_ip) for x in lips]).astype(np.int32)
        ix = np.stack([pad_zero(x, max_id) for x in lids]).astype(np.int32)
        sh2 = NamedSharding(mesh, P(axis, None))
        self.indptr_sh = jax.device_put(ip, sh2)
        self.indices_sh = jax.device_put(ix, sh2)
        self._fn = {}
        # retry pacing for the exchange path: short, jittered (so shards
        # that timed out together don't re-collide), seeded off the
        # sampler seed so runs replay byte-identically
        import random as _random

        self._retry_backoff = Backoff(0.005, cap_s=0.02, jitter=0.5,
                                      rng=_random.Random(seed))

    # ------------------------------------------------------------------
    def _hop(self, k: int, cap: int):
        n, axis = self.n, self.axis
        gm, srng = self.gather_mode, self.sample_rng
        row_starts = self.row_starts

        def body(ip, ix, ids, valid, key):
            # ip: [1, max_ip]; ix: [1, max_id]; ids/valid: [1, F]
            ip, ix, ids, valid = ip[0], ix[0], ids[0], valid[0]
            me = jax.lax.axis_index(axis)
            F = ids.shape[0]
            owner = (
                jnp.searchsorted(row_starts, ids, side="right") - 1
            ).astype(jnp.int32)
            owner = jnp.where(valid, owner, n)
            onehot = owner[:, None] == jnp.arange(n)[None, :]
            rank_in = jnp.cumsum(onehot, axis=0) - 1
            slot = jnp.sum(jnp.where(onehot, rank_in, 0), axis=1)
            overflow = slot >= cap
            ok = valid & ~overflow
            ocount = (valid & overflow).sum().astype(jnp.int32)
            dest = jnp.where(ok, owner * cap + slot, n * cap)
            reqs = jnp.zeros((n * cap,), jnp.int32).at[dest].add(
                ids + 1, mode="drop"
            ).reshape(n, cap)
            recv = jax.lax.all_to_all(reqs, axis, split_axis=0,
                                      concat_axis=0, tiled=True)
            rids = recv.reshape(-1) - 1
            rvalid = rids >= 0
            # rebase to local rows and sample from the local shard
            local = jnp.clip(rids - row_starts[me], 0, ip.shape[0] - 2)
            sub = jax.random.fold_in(key, me)
            out = sample_neighbors(ip, ix, local, k, sub,
                                   seed_mask=rvalid,
                                   gather_mode=gm, sample_rng=srng)
            # ship [n, cap, k] neighbor ids (+1, 0=invalid) back
            payload = jnp.where(out.mask, out.nbrs + 1, 0).reshape(
                n, cap, k
            )
            back = jax.lax.all_to_all(payload, axis, split_axis=0,
                                      concat_axis=0, tiled=True)
            flat = back.reshape(n * cap, k)
            got = jnp.take(flat, jnp.clip(dest, 0, n * cap - 1), axis=0)
            nbrs = jnp.where(ok[:, None], got - 1, -1)
            mask = nbrs >= 0
            return nbrs[None], mask[None], ocount

        return body

    def _build(self, B: int):
        from ..utils.rng import default_impl

        sizes = tuple(self.sizes)
        n, axis = self.n, self.axis
        frac = self.request_cap_frac
        prng_impl = default_impl()  # honors QUIVER_TPU_PRNG override

        def pipeline(ip, ix, seeds, valid, seed_scalar):
            # seeds/valid: [1, B] per-shard (every shard runs the same
            # program on ITS OWN seed batch — data-parallel sampling)
            key = jax.random.key(seed_scalar, impl=prng_impl)
            frontier, fmask = seeds[0], valid[0]
            blocks = []
            ocounts = []
            for l, k in enumerate(sizes):
                F = frontier.shape[0]
                if frac >= 1.0:
                    # truly exact: even if every frontier entry lands on one
                    # shard, slot < F, so overflow is impossible
                    cap = F
                else:
                    cap = min(max(int(np.ceil(F * frac / n)) * 2, 8), F)
                key, sub = jax.random.split(key)
                nbrs, mask, oc = self._hop(k, cap)(
                    ip, ix, frontier[None], fmask[None], sub
                )
                ocounts.append(oc)
                nbrs, mask = nbrs[0], mask[0]
                pos = (F + jnp.arange(F, dtype=jnp.int32)[:, None] * k
                       + jnp.arange(k, dtype=jnp.int32)[None, :])
                blocks.append(LayerBlock(
                    nbr_local=jnp.where(mask, pos, 0),
                    mask=mask,
                    num_targets=fmask.sum().astype(jnp.int32),
                ))
                frontier = jnp.concatenate(
                    [frontier, jnp.where(mask, nbrs, 0).reshape(-1)]
                )
                fmask = jnp.concatenate([fmask, mask.reshape(-1)])
            # leading [1] axis on every leaf so out_specs can globalize
            # the per-shard results onto the mesh axis
            blocks_out = tuple(
                LayerBlock(
                    nbr_local=b.nbr_local[None],
                    mask=b.mask[None],
                    num_targets=b.num_targets[None],
                )
                for b in blocks[::-1]  # outermost-first, like SampledBatch
            )
            return (frontier[None], fmask[None],
                    fmask.sum().astype(jnp.int32)[None], blocks_out,
                    jnp.stack(ocounts)[None])

        blocks_spec = tuple(
            LayerBlock(
                nbr_local=P(self.axis, None, None),
                mask=P(self.axis, None, None),
                num_targets=P(self.axis),
            )
            for _ in sizes
        )
        f = shard_map(
            pipeline, mesh=self.mesh,
            in_specs=(P(self.axis, None), P(self.axis, None),
                      P(self.axis, None), P(self.axis, None), P()),
            out_specs=(P(self.axis, None), P(self.axis, None),
                       P(self.axis), blocks_spec, P(self.axis, None)),
        )
        return jax.jit(f)

    def sample(self, seed_batches: np.ndarray, key=None):
        """``seed_batches``: [n_shards, B] — one seed batch per device;
        ``key``: int seed (PRNG keys are derived per shard inside).
        Returns per-shard :class:`SampledBatch`-style pytrees stacked on
        the leading axis.

        After each call ``self.last_overflow`` holds a ``[n_shards, L]``
        device array of per-hop counts of frontier entries that overflowed
        their destination bucket and were silently dropped (sampled 0
        neighbors).  Always zero at ``request_cap_frac=1.0``.
        """
        seeds = jnp.asarray(seed_batches, jnp.int32)
        nd, B = seeds.shape
        assert nd == self.n, (nd, self.n)
        valid = jnp.ones((nd, B), bool)
        if key is None:
            key = np.random.randint(0, 2**31 - 1)
        if B not in self._fn:
            self._fn[B] = self._build(B)
        sh = NamedSharding(self.mesh, P(self.axis, None))
        seeds = jax.device_put(seeds, sh)
        valid = jax.device_put(valid, sh)
        def _exchange():
            _CHAOS_EXCHANGE()
            return self._fn[B](
                self.indptr_sh, self.indices_sh, seeds, valid,
                jnp.int32(key),
            )

        def _on_retry(attempt, exc):
            from .. import telemetry

            telemetry.counter("dist_sampler_retries_total").inc()

        # one retried attempt with a short jittered backoff — a
        # transient peer stall usually clears; a second timeout surfaces
        # to the caller (sampling has no partial-answer degrade: a
        # frontier with holes would silently bias the training batch)
        n_id, n_mask, num, blocks, overflow = retry_call(
            _exchange, attempts=2, backoff=self._retry_backoff,
            retry_on=(PeerTimeout, TimeoutError), on_retry=_on_retry)
        self.last_overflow = overflow
        self._overflow_recorded = False
        return n_id, n_mask, num, blocks

    def overflow_stats(self):
        """Per-hop dropped-request counts from the most recent ``sample``
        call, as a host ``[n_shards, L]`` int array (None before any call).
        Parity note: the reference has no analogue — NCCL send/recv moves
        exact ragged sizes; fixed-capacity buckets are the TPU trade, so
        the drop counter is the safety net.  Materializing here also
        feeds ``dist_sampler_overflow_total`` — at query time, never in
        the sample hot path (that would force a device sync)."""
        if getattr(self, "last_overflow", None) is None:
            return None
        arr = np.asarray(self.last_overflow)
        if not getattr(self, "_overflow_recorded", True):
            self._overflow_recorded = True
            total = float(arr.sum())
            if total:
                from .. import telemetry

                telemetry.counter("dist_sampler_overflow_total").inc(total)
        return arr
