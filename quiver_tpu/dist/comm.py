"""Collective communication layer — TPU-native ``NcclComm``.

Reference parity: ``srcs/cpp/src/quiver/cuda/quiver_comm.cu:9-100`` (NCCL
wrapper) and ``srcs/python/quiver/comm.py`` (HostRankTable + the greedy
``schedule()`` host-pairing at comm.py:42-75).

TPU-first redesign: point-to-point send/recv and the contention-avoiding
pairing schedule disappear entirely — the exchange is expressed as
``jax.lax.all_to_all`` inside ``shard_map`` over a mesh axis, and XLA's
collective scheduler owns link contention (ICI within a slice, DCN across
hosts).  ``getNcclId``-style bootstrap is ``jax.distributed.initialize``.
"""

from __future__ import annotations

from typing import Optional

import jax
from jax.sharding import Mesh, PartitionSpec as P

try:
    from jax import shard_map  # jax >= 0.8
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map  # type: ignore

__all__ = ["TpuComm", "getNcclId"]


def getNcclId():
    """Parity shim: jax needs no explicit communicator id."""
    return b"jax-single-controller"


class TpuComm:
    """Mesh-axis collectives with the reference NcclComm's surface.

    Args:
      mesh: ``jax.sharding.Mesh``.
      axis: mesh axis name over which ranks (reference: hosts) are laid out.
    """

    def __init__(self, mesh: Mesh, axis: str = "data",
                 rank: Optional[int] = None):
        self.mesh = mesh
        self.axis = axis
        self.n = int(mesh.shape[axis])
        self.rank = rank if rank is not None else 0

    # -- primitives ----------------------------------------------------
    def allreduce(self, x):
        """Sum over the axis; parity: ``NcclComm::allreduce``."""
        f = shard_map(
            lambda v: jax.lax.psum(v[0], self.axis),
            mesh=self.mesh,
            in_specs=P(self.axis),
            out_specs=P(),
        )
        return f(x)

    def all_to_all(self, x):
        """Per-rank matrix exchange: ``x`` is ``[n, ...]`` sharded on axis 0
        with each rank holding ``[n_local=..., chunk]`` destined rows; result
        transposes the (source, dest) layout.  Replaces phase-1/phase-2
        send/recv loops of ``comm.py:153-181``."""

        def body(v):  # v: [1, n, ...] local block (sharded leading axis)
            out = jax.lax.all_to_all(
                v[0], self.axis, split_axis=0, concat_axis=0, tiled=True
            )
            return out[None]

        f = shard_map(
            body, mesh=self.mesh,
            in_specs=P(self.axis), out_specs=P(self.axis),
        )
        return f(x)

    def exchange(self, *args, **kwargs):
        raise NotImplementedError(
            "use quiver_tpu.dist.DistFeature for the feature exchange"
        )
