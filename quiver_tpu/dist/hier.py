"""Two-tier ICI x DCN feature store — the NVLink-clique x NCCL hierarchy.

Reference parity: the reference composes TWO remote-access tiers — the hot
set partitioned across a P2P clique and read over NVLink
(``feature.py:225-265`` + ``quiver_feature.cu:246-302``), and the cold
partition fetched from its owner host over NCCL (``feature.py:529-567`` +
``comm.py:127-182``).  ``HierFeature`` is the TPU equivalent over a hybrid
``[dcn, ici]`` mesh (:func:`quiver_tpu.dist.make_hybrid_mesh`):

  * **hot tier**: the top-``hot_count`` rows (degree/probability order),
    replicated per host group and SHARDED over the ICI axis — a hot lookup
    never leaves the host group; XLA's ici all_to_all plays NVLink.
  * **cold tier**: remaining rows partitioned by owner host (DCN axis) and
    sub-sharded over that host's chips (ICI axis).

One jitted ``shard_map`` body does the whole dance: route queries to their
owner host (DCN all_to_all) -> route to the owner chip within the host
(ICI all_to_all) -> local gather -> two reversed all_to_alls home.  Hot
queries are self-destined at the DCN stage, so they add ZERO cross-host
traffic — the property :meth:`traffic_stats` surfaces and
``tests/test_hier.py`` asserts against a flat mesh.

Everything is fixed-capacity buckets + validity masks (static shapes);
overflowed queries return zero rows and are COUNTED, never silent.
"""

from __future__ import annotations

from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:
    from jax import shard_map  # jax >= 0.8
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map  # type: ignore

__all__ = ["HierFeature"]


def _bucket(owner, valid, n_dest, cap):
    """Slot each element into its destination's fixed bucket.

    Returns (flat dest index in [0, n_dest*cap] — n_dest*cap means
    dropped/invalid, overflow mask).
    """
    owner = jnp.where(valid, owner, n_dest)
    onehot = owner[:, None] == jnp.arange(n_dest)[None, :]
    rank_in = jnp.cumsum(onehot, axis=0) - 1
    slot = jnp.sum(jnp.where(onehot, rank_in, 0), axis=1)
    overflow = valid & (slot >= cap)
    dest = jnp.where(valid & ~overflow, owner * cap + slot, n_dest * cap)
    return dest, overflow


def _scatter_ids(ids, dest, n_slots):
    """Pack (id+1) into the bucket layout; 0 = empty slot."""
    return jnp.zeros((n_slots,), jnp.int32).at[dest].add(
        (ids + 1).astype(jnp.int32), mode="drop"
    )


class HierFeature:
    """Hierarchical (host-group x chip) sharded feature store.

    Args:
      mesh: 2-axis mesh, DCN major / ICI minor (``make_hybrid_mesh``).
      hot_count: rows [0, hot_count) are the hot tier (callers order rows
        by degree/probability first, as ``Feature.from_cpu_tensor`` does).
      global2host: ``[N]`` owner host per node (cold rows; hot entries
        ignored).  Defaults to contiguous range partition of the cold tail.
      dcn_cap / ici_cap: per-destination bucket capacities for the two
        exchange stages (defaults = exact worst case: nothing dropped).
    """

    def __init__(self, mesh: Mesh, hot_count: int, global2host=None,
                 dcn_axis: str = "dcn", ici_axis: str = "ici",
                 dcn_cap: Optional[int] = None,
                 ici_cap: Optional[int] = None):
        self.mesh = mesh
        self.dcn_axis, self.ici_axis = dcn_axis, ici_axis
        self.H = int(mesh.shape[dcn_axis])
        self.C = int(mesh.shape[ici_axis])
        self.hot_count = hot_count
        self.global2host = global2host
        self.dcn_cap, self.ici_cap = dcn_cap, ici_cap
        self._fn = {}

    @classmethod
    def from_global_feature(cls, feature: np.ndarray, mesh: Mesh,
                            hot_count: int, global2host=None, **kw):
        self = cls(mesh, hot_count, global2host, **kw)
        N, D = feature.shape
        H, C = self.H, self.C
        hot_count = min(hot_count, N)
        self.hot_count = hot_count = hot_count - hot_count % C  # C-divisible
        self.node_count, self.dim = N, D

        # hot tier: [hot_count, D], sharded over ici, replicated over dcn
        hot = np.ascontiguousarray(feature[:hot_count])
        self.hot_shard = hot_count // C if C else 0
        if hot_count:
            self.hot = jax.device_put(
                hot, NamedSharding(mesh, P(self.ici_axis, None))
            )
        else:
            self.hot = jax.device_put(
                np.zeros((C, D), feature.dtype),
                NamedSharding(mesh, P(self.ici_axis, None)),
            )
            self.hot_shard = 1

        # cold tier: owner host per node, local slots, chip sub-shards
        n_cold = N - hot_count
        if global2host is None:
            # contiguous range partition of the cold tail
            g2h = np.minimum(
                (np.arange(N, dtype=np.int64) - hot_count)
                // max(1, -(-n_cold // H)), H - 1
            ).astype(np.int32)
            g2h[:hot_count] = 0
        else:
            g2h = np.asarray(global2host, dtype=np.int32).copy()
        self._g2h_np = g2h
        g2l = np.zeros(N, dtype=np.int32)
        counts = np.zeros(H, dtype=np.int64)
        cold_ids = np.arange(hot_count, N)
        for h in range(H):
            ids = cold_ids[g2h[cold_ids] == h]
            g2l[ids] = np.arange(len(ids), dtype=np.int32)
            counts[h] = len(ids)
        m = int(counts.max()) if n_cold else 1
        self.m_c = m_c = -(-m // C)  # per-chip cold rows
        m = m_c * C
        cold = np.zeros((H * m, D), dtype=feature.dtype)
        for h in range(H):
            ids = cold_ids[g2h[cold_ids] == h]
            cold[h * m + g2l[ids]] = feature[ids]
        self.cold = jax.device_put(
            cold, NamedSharding(mesh, P((self.dcn_axis, self.ici_axis),
                                        None)),
        )
        self.g2h = jnp.asarray(g2h)
        self.g2l = jnp.asarray(g2l)
        return self

    # ------------------------------------------------------------------
    def _build(self, B: int, dcap: int, icap: int):
        H, C = self.H, self.C
        dax, iax = self.dcn_axis, self.ici_axis
        hot_count, hot_shard, m_c = self.hot_count, self.hot_shard, self.m_c
        g2h, g2l = self.g2h, self.g2l

        def body(hot, cold, ids, valid):
            # hot: [hot_shard, D] (this chip's ici shard, same per host)
            # cold: [m_c, D] (this chip's slice of this host's partition)
            # ids/valid: [1, 1, B] — this chip's query batch
            ids, valid = ids[0, 0], valid[0, 0]
            me_h = jax.lax.axis_index(dax)
            is_hot = ids < hot_count
            dest_h = jnp.where(is_hot, me_h, g2h[ids])
            # ---- stage 1: route queries to their owner host over DCN
            d1, ovf1 = _bucket(dest_h, valid, H, dcap)
            reqs1 = _scatter_ids(ids, d1, H * dcap).reshape(H, dcap)
            recv1 = jax.lax.all_to_all(reqs1, dax, split_axis=0,
                                       concat_axis=0, tiled=True)
            r1 = recv1.reshape(-1) - 1          # [H*dcap] ids (-1 empty)
            v1 = r1 >= 0
            r1s = jnp.where(v1, r1, 0)
            # ---- stage 2: route to the owner chip within the host
            r1_hot = r1s < hot_count
            dest_c = jnp.where(r1_hot, r1s // jnp.int32(hot_shard),
                               g2l[r1s] // jnp.int32(m_c))
            d2, ovf2 = _bucket(dest_c, v1, C, icap)
            reqs2 = _scatter_ids(r1s, d2, C * icap).reshape(C, icap)
            recv2 = jax.lax.all_to_all(reqs2, iax, split_axis=0,
                                       concat_axis=0, tiled=True)
            r2 = recv2.reshape(-1) - 1          # [C*icap]
            v2 = r2 >= 0
            r2s = jnp.where(v2, r2, 0)
            # ---- local gather (hot slice or cold slice of this chip)
            hslot = r2s % jnp.int32(hot_shard)
            cslot = g2l[r2s] % jnp.int32(m_c)
            rows = jnp.where(
                (r2s < hot_count)[:, None],
                jnp.take(hot, hslot, axis=0),
                jnp.take(cold, cslot, axis=0),
            )
            rows = jnp.where(v2[:, None], rows, 0)
            # ---- reverse stage 2 (ICI) back to the in-host requester slot
            back2 = jax.lax.all_to_all(rows.reshape(C, icap, -1), iax,
                                       split_axis=0, concat_axis=0,
                                       tiled=True)
            flat2 = jnp.concatenate(
                [back2.reshape(C * icap, -1),
                 jnp.zeros((1, back2.shape[-1]), back2.dtype)]
            )
            rows1 = jnp.take(flat2, jnp.clip(d2, 0, C * icap), axis=0)
            rows1 = jnp.where(v1[:, None], rows1, 0)
            # ---- reverse stage 1 (DCN) home to the querying chip
            back1 = jax.lax.all_to_all(rows1.reshape(H, dcap, -1), dax,
                                       split_axis=0, concat_axis=0,
                                       tiled=True)
            flat1 = jnp.concatenate(
                [back1.reshape(H * dcap, -1),
                 jnp.zeros((1, back1.shape[-1]), back1.dtype)]
            )
            out = jnp.take(flat1, jnp.clip(d1, 0, H * dcap), axis=0)
            out = jnp.where((valid & ~ovf1)[:, None], out, 0)
            # ---- stats: cross-DCN query count + overflow drops
            dcn_cross = (valid & (dest_h != me_h)).sum().astype(jnp.int32)
            drops = (ovf1.sum() + (v1 & ovf2).sum()).astype(jnp.int32)
            return (out[None, None], dcn_cross[None, None],
                    drops[None, None])

        f = shard_map(
            body, mesh=self.mesh,
            in_specs=(P(self.ici_axis, None),
                      P((self.dcn_axis, self.ici_axis), None),
                      P(self.dcn_axis, self.ici_axis, None),
                      P(self.dcn_axis, self.ici_axis, None)),
            out_specs=(P(self.dcn_axis, self.ici_axis, None, None),
                       P(self.dcn_axis, self.ici_axis),
                       P(self.dcn_axis, self.ici_axis)),
        )
        return jax.jit(f)

    def lookup(self, ids, valid=None):
        """``ids``: [H, C, B] (one query batch per chip).  Returns
        [H, C, B, D]; :meth:`traffic_stats` afterwards for DCN counts."""
        ids = jnp.asarray(ids, jnp.int32)
        H, C, B = ids.shape
        assert (H, C) == (self.H, self.C), (ids.shape, self.H, self.C)
        if valid is None:
            valid = jnp.ones((H, C, B), bool)
        dcap = self.dcn_cap or B            # exact: one host can own all B
        icap = self.ici_cap or H * dcap     # exact: one chip can own all
        key = (B, dcap, icap)
        if key not in self._fn:
            self._fn[key] = self._build(B, dcap, icap)
        spec = NamedSharding(self.mesh, P(self.dcn_axis, self.ici_axis,
                                          None))
        ids = jax.device_put(ids, spec)
        valid = jax.device_put(valid, spec)
        out, cross, drops = self._fn[key](self.hot, self.cold, ids, valid)
        self.last_dcn_cross = cross
        self.last_drops = drops
        return out

    def traffic_stats(self):
        """Per-chip [H, C] counts from the last lookup: queries that
        crossed DCN, and bucket-overflow drops (0 at default caps)."""
        if getattr(self, "last_dcn_cross", None) is None:
            return None
        return dict(
            dcn_crossings=np.asarray(self.last_dcn_cross),
            drops=np.asarray(self.last_drops),
            dcn_bytes_est=int(
                np.asarray(self.last_dcn_cross).sum()
                * self.dim * np.dtype(np.float32).itemsize
            ),
        )
