from .comm import TpuComm, getNcclId
from .feature import DistFeature, PartitionInfo
