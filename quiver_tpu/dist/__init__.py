from .comm import TpuComm, getNcclId
from .feature import DistFeature, PartitionInfo
from .sampler import DistGraphSampler, shard_csr_by_rows
from .init import initialize, make_hybrid_mesh
from .ring import RingFeature
