from .comm import TpuComm, getNcclId
from .feature import DistFeature, PartitionInfo
from .sampler import DistGraphSampler, shard_csr_by_rows
