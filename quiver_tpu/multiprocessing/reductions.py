"""ForkingPickler reducers for quiver_tpu objects.

Parity: ``srcs/python/quiver/multiprocessing/reductions.py``.  The packed
form is host-side numpy (device arrays are fetched); children rebuild
lazily on first use so spawn cost is one host copy, not a device sync
storm.
"""

from __future__ import annotations

from multiprocessing.reduction import ForkingPickler

import numpy as np

from ..feature import Feature
from ..sampler import GraphSageSampler


def _host(tree):
    import jax

    def conv(x):
        # fetch device arrays; leave numpy (incl. memmap cold tiers) alone
        if isinstance(x, jax.Array):
            return np.asarray(x)
        return x

    return jax.tree_util.tree_map(conv, tree)


def rebuild_feature(handle):
    return Feature.lazy_from_ipc_handle(handle)


def reduce_feature(f: Feature):
    handle = _host(f.share_ipc())
    return (rebuild_feature, (handle,))


def rebuild_sampler(csr_topo, sizes, mode):
    return GraphSageSampler(csr_topo, sizes, mode=mode)


def reduce_sampler(s: GraphSageSampler):
    csr_topo, sizes, mode = s.share_ipc()
    return (rebuild_sampler, (csr_topo, sizes, mode))


def init_reductions():
    ForkingPickler.register(Feature, reduce_feature)
    ForkingPickler.register(GraphSageSampler, reduce_sampler)


init_reductions()
