"""Multiprocessing integration — ``import quiver_tpu.multiprocessing``
registers reducers so samplers/features can cross ``mp.spawn`` boundaries.

Reference parity: ``srcs/python/quiver/multiprocessing/reductions.py:1-34``
(ForkingPickler reducers over cudaIpc handles).  Single-controller JAX has
no cudaIpc: device arrays are materialized to host on pickle and re-placed
lazily in the child (first use), which is exactly the reference's
``lazy_from_ipc_handle`` flow minus the handle plumbing.  Worth noting:
within ONE process a thread pool (``quiver_tpu.mixed``/``serving``) needs
none of this — processes are only for user scripts that insist on
``mp.spawn`` symmetry with their torch code.
"""

from . import reductions  # noqa: F401  (import side effect = registration)
