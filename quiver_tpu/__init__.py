"""quiver_tpu — TPU-native graph-learning data layer.

A ground-up JAX/XLA/Pallas rebuild of the capabilities of
quiver-team/torch-quiver (reference at ``/root/reference``): k-hop neighbor
sampling, cached/sharded feature collection, cross-host feature exchange,
partitioning tools, and a GNN serving pipeline — designed for TPU (static
shapes, device meshes, XLA collectives) rather than translated from CUDA.

Public API parity map (reference ``srcs/python/quiver/__init__.py:1-21``):

  Feature, DistFeature, PartitionInfo      -> quiver_tpu.feature / .dist
  GraphSageSampler, MixedGraphSageSampler  -> quiver_tpu.sampler / .mixed
  SampleJob                                -> quiver_tpu.mixed
  CSRTopo                                  -> quiver_tpu.utils.topology
  p2pCliqueTopo / init_p2p                 -> quiver_tpu.utils.mesh (MeshTopo)
  NcclComm / getNcclId                     -> quiver_tpu.dist.comm (TpuComm)
  quiver_partition_feature, load_...       -> quiver_tpu.partition
  generate_neighbour_num                   -> quiver_tpu.neighbour_num
  RequestBatcher/HybridSampler/InferenceServer -> quiver_tpu.serving
"""

import os as _os

if _os.environ.get("QUIVER_SANITIZE") == "1":
    # Lock-witness sanitizer (quiverlint v2's dynamic half): must patch
    # threading.Lock/RLock BEFORE any other quiver module imports so
    # module- and instance-level locks constructed below get wrapped.
    # analysis.witness is stdlib-only — no jax cost on this path.
    from .analysis import witness as _witness

    _witness.install()

if _os.environ.get("JAX_PLATFORMS"):
    # honor an explicit JAX_PLATFORMS even where a site hook re-exports
    # its own after env setup: the config API takes final precedence.
    # No-op unless the var is set; guarded so an already-initialized
    # backend (user imported jax and touched devices first) never breaks
    # the import.
    try:
        import jax as _jax

        _jax.config.update("jax_platforms", _os.environ["JAX_PLATFORMS"])
    except Exception as _e:  # malformed value or backend already pinned
        import warnings as _warnings

        _warnings.warn(
            "JAX_PLATFORMS=%r override did not take (%s); the process may "
            "run on a different backend" % (_os.environ["JAX_PLATFORMS"], _e)
        )

from . import config
from .utils.topology import CSRTopo, coo_to_csr, parse_size, reindex_feature
from .utils.mesh import MeshTopo, make_mesh
from .sampler import GraphSageSampler, SampledBatch, LayerBlock
from .loader import SeedLoader
from .pipeline import make_fused_train_step, make_fused_eval_fn
from .mixed import MixedGraphSageSampler, SampleJob
from .feature import Feature, DeviceConfig
from .dist.feature import DistFeature, PartitionInfo
from .dist.comm import TpuComm
from .dist.sampler import DistGraphSampler
from .dist.ring import RingFeature
from .dist.init import initialize as distributed_initialize, make_hybrid_mesh
from .dist.hier import HierFeature
from .uva import UVAGraph
from .utils.rng import make_key
from .interop import to_torch_adjs, TorchSampleLoader
from .partition import (
    partition_without_replication,
    quiver_partition_feature,
    load_quiver_feature_partition,
)
from .hetero import (
    HeteroCSRTopo,
    HeteroGraphSageSampler,
    HeteroSampledBatch,
    HeteroLayerBlock,
    HeteroFeature,
)
from .neighbour_num import generate_neighbour_num
from . import multiprocessing  # registers mp reducers (parity: P10)
from .serving import (
    RequestBatcher,
    HybridSampler,
    InferenceServer,
    InferenceServer_Debug,
)

if _os.environ.get("QUIVER_SANITIZE") == "1":
    # Device-transfer witness (quiverlint v3's dynamic half) installs at
    # the END of import — unlike the lock witness it wraps jax's array
    # type, which must exist first.  Arms the `staging.no_sync()` region
    # gate as a side effect.
    from .analysis import transfer_witness as _transfer_witness

    _transfer_witness.install()

__version__ = "0.1.0"

__all__ = [
    "CSRTopo", "coo_to_csr", "parse_size", "reindex_feature",
    "MeshTopo", "make_mesh",
    "GraphSageSampler", "SampledBatch", "LayerBlock", "SeedLoader", "make_fused_train_step", "make_fused_eval_fn",
    "MixedGraphSageSampler", "SampleJob",
    "HeteroCSRTopo", "HeteroGraphSageSampler", "HeteroSampledBatch",
    "HeteroLayerBlock", "HeteroFeature",
    "Feature", "DeviceConfig",
    "DistFeature", "PartitionInfo", "TpuComm", "DistGraphSampler",
    "RingFeature", "distributed_initialize", "make_hybrid_mesh",
    "HierFeature", "UVAGraph", "make_key",
    "to_torch_adjs", "TorchSampleLoader",
    "partition_without_replication", "quiver_partition_feature",
    "load_quiver_feature_partition",
    "generate_neighbour_num",
    "RequestBatcher", "HybridSampler", "InferenceServer",
    "InferenceServer_Debug",
]
