"""Per-shard WAL segments with a coherent group manifest.

A mesh shard group (docs/SHARDING.md) is N processes serving ONE
logical replica, so its durable state must recover as one unit: a
checkpoint that contains shard 0's rows through LSN 40 and shard 1's
through LSN 37 is a replica that never existed.  This module gives
each shard its own :class:`~quiver_tpu.recovery.wal.WriteAheadLog`
(single-writer stays single-writer — no cross-process log contention)
under ``<root>/shard-<NN>/`` and makes the GROUP watermark explicit:

  * writes land per shard (``append(shard, payload)``), each log
    keeping its own LSN sequence and fsync policy;
  * ``publish_manifest()`` atomically publishes the vector of
    per-shard watermarks (``blockio.atomic_publish`` — readers see a
    complete old manifest or a complete new one, never a torn hybrid);
  * ``replay(shard)`` on warm boot yields each shard's records only
    **through its manifest watermark**, so a crash that landed between
    one shard's append and another's never replays into a state no
    coherent group ever occupied.  Records past the watermark are the
    un-acked tail — exactly the debris the single-log replay contract
    already allows — and are reported via :meth:`tail_lsns` so the
    caller can decide to re-drive or drop them.

The manifest is versioned monotonically; a stale writer that lost a
race publishes a lower version and :func:`load_manifest` keeps the
newest one it can parse.
"""

from __future__ import annotations

import json
import os
from typing import Iterator, List, Optional, Tuple

from . import blockio
from .errors import RecoveryError
from .wal import WriteAheadLog

__all__ = ["shard_wal_root", "ShardGroupWAL", "GroupManifest",
           "load_manifest"]

_MANIFEST = "group-manifest.json"


def shard_wal_root(root: str, shard: int) -> str:
    """The WAL directory of one shard inside a group root."""
    if shard < 0:
        raise ValueError(f"shard must be >= 0, got {shard}")
    return os.path.join(str(root), f"shard-{int(shard):02d}")


class GroupManifest:
    """The coherent-group watermark: one LSN per shard, versioned."""

    def __init__(self, n_shards: int, lsns: List[int], version: int = 0,
                 group: str = ""):
        self.n_shards = int(n_shards)
        self.lsns = [int(x) for x in lsns]
        self.version = int(version)
        self.group = str(group)
        if len(self.lsns) != self.n_shards:
            raise RecoveryError(
                f"manifest lsn vector has {len(self.lsns)} entries for "
                f"{self.n_shards} shards")

    def to_dict(self) -> dict:
        return {"n_shards": self.n_shards, "lsns": self.lsns,
                "version": self.version, "group": self.group}

    @classmethod
    def from_dict(cls, d: dict) -> "GroupManifest":
        return cls(n_shards=int(d["n_shards"]),
                   lsns=list(d["lsns"]),
                   version=int(d.get("version", 0)),
                   group=str(d.get("group", "")))


def load_manifest(root: str) -> Optional[GroupManifest]:
    """The group's published watermark, or None before the first
    publish.  A garbage manifest raises — boot must not silently
    replay everything a torn watermark no longer vouches for."""
    path = os.path.join(str(root), _MANIFEST)
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except FileNotFoundError:
        return None
    try:
        return GroupManifest.from_dict(json.loads(raw))
    except (ValueError, KeyError, TypeError) as e:
        raise RecoveryError(
            f"unreadable group manifest {path}: {e}") from e


class ShardGroupWAL:
    """N per-shard write-ahead logs + one atomic group watermark."""

    def __init__(self, root: str, n_shards: int, group: str = "",
                 fsync: Optional[str] = None,
                 segment_bytes: Optional[int] = None):
        if int(n_shards) < 1:
            raise ValueError(f"n_shards must be >= 1, got {n_shards}")
        self.root = str(root)
        self.n_shards = int(n_shards)
        self.group = str(group)
        os.makedirs(self.root, exist_ok=True)
        self.logs = [WriteAheadLog(shard_wal_root(self.root, s),
                                   fsync=fsync,
                                   segment_bytes=segment_bytes)
                     for s in range(self.n_shards)]
        existing = load_manifest(self.root)
        self._version = existing.version if existing is not None else 0

    # -- write side ----------------------------------------------------
    def append(self, shard: int, payload: bytes) -> int:
        """Durably append one record to one shard's log; returns its
        shard-local LSN (the manifest is NOT moved — call
        :meth:`publish_manifest` at the group commit point)."""
        return self.logs[int(shard)].append(payload)

    def sync(self) -> None:
        for wal in self.logs:
            wal.sync()

    def publish_manifest(self) -> GroupManifest:
        """Atomically publish the current per-shard watermarks as the
        group's coherent recovery point.  Syncs every log FIRST — a
        watermark must never vouch for bytes still in the page cache."""
        self.sync()
        self._version += 1
        manifest = GroupManifest(
            n_shards=self.n_shards,
            lsns=[wal.last_lsn for wal in self.logs],
            version=self._version, group=self.group)
        blockio.atomic_publish(
            os.path.join(self.root, _MANIFEST),
            json.dumps(manifest.to_dict(), sort_keys=True).encode())
        return manifest

    # -- read side (warm boot) ------------------------------------------
    def manifest(self) -> Optional[GroupManifest]:
        return load_manifest(self.root)

    def replay(self, shard: int,
               manifest: Optional[GroupManifest] = None,
               ) -> Iterator[Tuple[int, bytes]]:
        """Records of one shard **through the group watermark** — the
        coherent warm-boot stream.  With no manifest published yet,
        nothing replays (nothing was ever group-committed)."""
        manifest = self.manifest() if manifest is None else manifest
        if manifest is None:
            return
        through = manifest.lsns[int(shard)]
        for lsn, payload in self.logs[int(shard)].replay():
            if lsn > through:
                break
            yield lsn, payload

    def tail_lsns(self, manifest: Optional[GroupManifest] = None,
                  ) -> List[int]:
        """Per-shard count of durable records PAST the watermark — the
        un-acked tail a warm boot skipped; operators decide re-drive
        vs drop."""
        manifest = self.manifest() if manifest is None else manifest
        base = manifest.lsns if manifest is not None \
            else [-1] * self.n_shards
        return [max(wal.last_lsn - through, 0)
                for wal, through in zip(self.logs, base)]

    def truncate_through_manifest(self) -> int:
        """Drop sealed segments wholly covered by the watermark; the
        group's log-space reclaim.  Returns segments removed."""
        manifest = self.manifest()
        if manifest is None:
            return 0
        return sum(wal.truncate_through(through)
                   for wal, through in zip(self.logs, manifest.lsns))

    def stats(self) -> dict:
        manifest = self.manifest()
        return {
            "root": self.root, "group": self.group,
            "n_shards": self.n_shards,
            "last_lsns": [wal.last_lsn for wal in self.logs],
            "manifest": manifest.to_dict() if manifest else None,
            "tail": self.tail_lsns(manifest),
        }

    def close(self) -> None:
        for wal in self.logs:
            wal.close()
