"""Atomic-rename snapshots of graph + coldcache state.

One checkpoint is one file, ``ckpt-<seq>.qgr``, published through
``blockio.atomic_publish`` — readers see a complete old file or a
complete new one, never a torn hybrid.  The body is a JSON header
(format version, graph version, WAL watermark, array directory) plus a
concatenated array blob whose CRC-32C the header records.

Every array is **dtype- and endianness-pinned** in the header
(``"<i8"``, ``"<i4"``, ``"<u1"``): a snapshot written on any host
restores bit-identically on any other, and the round-trip test pins
exactly that.  Unknown format versions (or a bad magic / checksum) are
a *clean refusal* — :class:`SnapshotFormatError` /
:class:`CheckpointError`, never an exception from half-parsed bytes.

What a snapshot holds:

  * base CSR (``indptr``/``indices`` + optional ``feature_order`` and
    per-edge timestamps), the tombstone bitmap, and the **live** delta
    edges — together with ``graph_version``, the full
    ``StreamingGraph`` state at one instant (taken under the graph
    lock);
  * ``wal_lsn`` — the replay watermark: records with LSN <= it are
    already folded in, so boot replays strictly-greater LSNs and
    ``WriteAheadLog.truncate_through(wal_lsn)`` may drop the covered
    segments;
  * coldcache residency/frequency state per registered feature store
    (``ColdRowCache.export_state``), so a warm restart re-earns nothing
    that was already hot.
"""

from __future__ import annotations

import json
import logging
import os
import re
import struct
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

from .. import telemetry
from . import blockio
from .errors import CheckpointError, SnapshotFormatError

__all__ = ["CHECKPOINT_FORMAT", "CheckpointData", "save_checkpoint",
           "read_checkpoint", "load_checkpoint", "latest_checkpoint",
           "restore_graph"]

log = logging.getLogger("quiver_tpu.recovery")

CHECKPOINT_FORMAT = 1
_MAGIC = b"QCKP"
_PREFIX = struct.Struct("<4sII")  # magic, format version, header length
_FILE_RE = re.compile(r"^ckpt-(\d{12})\.qgr$")

# the pinned on-disk dtype per logical array name; everything else is a
# format error, not a silent cast
_PINNED = {
    "indptr": "<i8", "indices": "<i4", "feature_order": "<i8",
    "base_ts": "<i4", "tomb": "<u1",
    "d_src": "<i4", "d_dst": "<i4", "d_ts": "<i4",
}
_CC_PINNED = {
    "slot_of": "<i4", "node_of": "<i8", "freq": "<i8", "ref": "<u1",
    "touches": "<i4",
}


@dataclass
class CheckpointData:
    """A parsed snapshot: host numpy arrays + metadata, ready to restore."""

    graph_version: int
    wal_lsn: int
    has_ts: bool
    arrays: Dict[str, np.ndarray]
    coldcaches: Dict[str, dict] = field(default_factory=dict)
    path: str = ""


def _checkpoint_path(root: str, seq: int) -> str:
    return os.path.join(root, f"ckpt-{seq:012d}.qgr")


def _list_checkpoints(root: str) -> List[str]:
    try:
        names = os.listdir(root)
    except OSError:
        return []
    found = sorted(n for n in names if _FILE_RE.match(n))
    return [os.path.join(root, n) for n in found]


def latest_checkpoint(root: str) -> Optional[str]:
    paths = _list_checkpoints(root)
    return paths[-1] if paths else None


def _pack_arrays(arrays: Dict[str, np.ndarray], pins: Dict[str, str],
                 directory: List[dict], blob: List[bytes],
                 prefix: str = "") -> None:
    offset = sum(len(b) for b in blob)
    for name, arr in arrays.items():
        pin = pins.get(name)
        if pin is None:
            raise CheckpointError(f"no pinned dtype for array {name!r}")
        data = np.ascontiguousarray(np.asarray(arr), dtype=pin).tobytes()
        directory.append({"name": prefix + name, "dtype": pin,
                          "shape": list(np.asarray(arr).shape),
                          "offset": offset, "nbytes": len(data)})
        blob.append(data)
        offset += len(data)


def save_checkpoint(root: str, graph, coldcaches: Optional[dict] = None,
                    wal_lsn: int = -1, keep: Optional[int] = None) -> str:
    """Snapshot ``graph`` (a StreamingGraph) + coldcache states to
    ``root``; returns the published path.

    ``coldcaches`` maps a stable key (the caller's choice, e.g. a
    feature-store name) to a ``ColdRowCache.export_state()`` dict.
    ``keep`` bounds retained checkpoints (older files pruned after the
    new one is durable); default ``config.recovery_checkpoint_keep``.
    """
    from ..config import get_config

    cfg = get_config()
    keep = int(keep if keep is not None else cfg.recovery_checkpoint_keep)
    os.makedirs(root, exist_ok=True)
    with graph._lock:
        base = graph._base
        arrays = {
            "indptr": base.indptr, "indices": base.indices,
            "tomb": graph._tomb,
        }
        if base.feature_order is not None:
            arrays["feature_order"] = base.feature_order
        if graph.has_ts:
            arrays["base_ts"] = graph._base_ts
        d_src, d_dst, d_ts = graph._delta.live_edges()
        arrays["d_src"], arrays["d_dst"] = d_src, d_dst
        if d_ts is not None:
            arrays["d_ts"] = d_ts
        version = graph._version
        has_ts = graph.has_ts
    directory: List[dict] = []
    blob: List[bytes] = []
    _pack_arrays(arrays, _PINNED, directory, blob)
    cc_header: Dict[str, dict] = {}
    for key, state in (coldcaches or {}).items():
        if state is None:
            continue
        cc_arrays = {k: v for k, v in state.items()
                     if isinstance(v, np.ndarray)}
        scalars = {k: v for k, v in state.items()
                   if not isinstance(v, np.ndarray)}
        _pack_arrays(cc_arrays, _CC_PINNED, directory, blob,
                     prefix=f"cc/{key}/")
        cc_header[key] = {"scalars": scalars}
    body = b"".join(blob)
    header = {
        "format": CHECKPOINT_FORMAT,
        "graph_version": int(version),
        "wal_lsn": int(wal_lsn),
        "has_ts": bool(has_ts),
        "arrays": directory,
        "coldcaches": cc_header,
        "crc": blockio.crc32c(body),
    }
    hdr = json.dumps(header, sort_keys=True).encode()
    payload = _PREFIX.pack(_MAGIC, CHECKPOINT_FORMAT, len(hdr)) + hdr + body
    path = _checkpoint_path(root, int(version))
    blockio.atomic_publish(path, payload)
    telemetry.counter("recovery_checkpoints_total").inc()
    telemetry.gauge("recovery_checkpoint_bytes").set(float(len(payload)))
    if keep > 0:
        for old in _list_checkpoints(root)[:-keep]:
            try:
                os.unlink(old)
            except OSError:
                pass
    return path


def read_checkpoint(path: str) -> CheckpointData:
    """Parse one snapshot file; typed refusal on any format problem."""
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError as e:
        raise CheckpointError(f"cannot read checkpoint {path}: {e}") from e
    if len(data) < _PREFIX.size:
        raise SnapshotFormatError(f"{path}: truncated prefix "
                                  f"({len(data)} bytes)")
    magic, fmt, hdr_len = _PREFIX.unpack_from(data)
    if magic != _MAGIC:
        raise SnapshotFormatError(f"{path}: bad magic {magic!r}")
    if fmt != CHECKPOINT_FORMAT:
        raise SnapshotFormatError(
            f"{path}: snapshot format {fmt} is not supported by this "
            f"build (expected {CHECKPOINT_FORMAT}) — refusing to guess "
            f"at its layout")
    hdr_end = _PREFIX.size + hdr_len
    if hdr_end > len(data):
        raise SnapshotFormatError(f"{path}: truncated header")
    try:
        header = json.loads(data[_PREFIX.size:hdr_end])
    except ValueError as e:
        raise SnapshotFormatError(f"{path}: unparsable header: {e}") from e
    body = data[hdr_end:]
    if blockio.crc32c(body) != header.get("crc"):
        raise SnapshotFormatError(f"{path}: body checksum mismatch")
    arrays: Dict[str, np.ndarray] = {}
    for spec in header.get("arrays", []):
        off, nbytes = int(spec["offset"]), int(spec["nbytes"])
        if off + nbytes > len(body):
            raise SnapshotFormatError(
                f"{path}: array {spec['name']!r} overruns the blob")
        arr = np.frombuffer(body, dtype=np.dtype(spec["dtype"]),
                            offset=off,
                            count=nbytes // np.dtype(spec["dtype"]).itemsize)
        arrays[spec["name"]] = arr.reshape(spec["shape"])
    coldcaches: Dict[str, dict] = {}
    for key, cc in header.get("coldcaches", {}).items():
        state = dict(cc.get("scalars", {}))
        prefix = f"cc/{key}/"
        for name in list(arrays):
            if name.startswith(prefix):
                state[name[len(prefix):]] = arrays.pop(name)
        coldcaches[key] = state
    return CheckpointData(
        graph_version=int(header["graph_version"]),
        wal_lsn=int(header["wal_lsn"]), has_ts=bool(header["has_ts"]),
        arrays=arrays, coldcaches=coldcaches, path=path)


def load_checkpoint(root: str) -> Optional[CheckpointData]:
    """Newest loadable snapshot under ``root``; ``None`` when the
    directory holds none.  A corrupt newest file falls back to the next
    (with ``recovery_checkpoint_load_errors_total`` ticked); if every
    candidate refuses, the last typed error propagates — boot must not
    silently pretend there was nothing to restore.
    """
    paths = _list_checkpoints(root)
    last_error: Optional[CheckpointError] = None
    for path in reversed(paths):
        try:
            return read_checkpoint(path)
        except CheckpointError as e:
            telemetry.counter("recovery_checkpoint_load_errors_total").inc()
            log.warning("checkpoint %s unusable (%s); trying older", path, e)
            last_error = e
    if last_error is not None:
        raise last_error
    return None


def restore_graph(ckpt: CheckpointData, delta_capacity: Optional[int] = None,
                  device=None):
    """Rebuild a ``StreamingGraph`` from a parsed snapshot.

    The restored graph is bit-equivalent to the captured one: same base
    arrays, tombstones, live delta edges (re-appended in order), and
    the exact ``graph_version`` — version monotonicity across a restart
    is part of the consistency contract the crash harness checks.
    """
    from ..config import get_config
    from ..stream.graph import StreamingGraph
    from ..utils.topology import CSRTopo

    a = ckpt.arrays
    topo = CSRTopo(indptr=a["indptr"].astype(np.int64, copy=False),
                   indices=a["indices"].astype(np.int32, copy=False))
    if "feature_order" in a:
        topo.feature_order = a["feature_order"].astype(np.int64, copy=False)
    d_src = a.get("d_src")
    pending = int(len(d_src)) if d_src is not None else 0
    cfg_cap = int(delta_capacity if delta_capacity is not None
                  else get_config().stream_delta_capacity)
    base_ts = (a["base_ts"].astype(np.int32, copy=False)
               if ckpt.has_ts else None)
    g = StreamingGraph(topo, edge_ts=base_ts,
                       delta_capacity=max(cfg_cap, pending), device=device)
    with g._lock:
        tomb = a["tomb"].astype(bool)
        if tomb.shape[0] != topo.edge_count:
            raise SnapshotFormatError(
                f"{ckpt.path}: tombstone bitmap length {tomb.shape[0]} != "
                f"edge count {topo.edge_count}")
        g._tomb = tomb
        g._tombstones = int(tomb.sum())
        if pending:
            g._delta.add(d_src.astype(np.int32, copy=False),
                         a["d_dst"].astype(np.int32, copy=False),
                         a["d_ts"].astype(np.int32, copy=False)
                         if ckpt.has_ts else None)
        g._version = ckpt.graph_version
        g._snap = None
    return g
