"""Segmented, checksummed write-ahead log for the ingest lane.

Layout: ``<root>/wal-<start_lsn>.seg`` files of framed records
(``blockio.write_record``); a segment's filename carries the LSN of its
first record, so record ``k`` of segment ``s`` has LSN ``start(s)+k``
without any per-record header field.  LSNs are the replay watermark
currency: a checkpoint stores the LSN through which its state is
complete, boot replays strictly-greater records, and
``truncate_through`` deletes sealed segments wholly at-or-below it.

Durability contract (the reason this module exists): ``append``
returns only after the record is as durable as the fsync policy
promises —

  * ``"always"`` — fsync per append.  An acked edge op survives
    kill -9 *and* power loss.  This is the default and the mode the
    crash harness certifies.
  * ``"batch"`` — fsync when ``batch_bytes`` of unsynced records
    accumulate (plus on roll/close/``sync()``).  Survives kill -9 (the
    page cache belongs to the kernel, not the process); a power cut can
    lose the unsynced tail.
  * ``"off"`` — never fsync (tests, benches measuring everything else).

Failures (including injected ``recovery.wal_write`` / ``recovery.fsync``
chaos faults) raise :class:`~quiver_tpu.recovery.errors.WALWriteError`
— the ingest worker answers the submitting request with it, so a lost
write is a *reported* error, never a silent gap.

Replay walks every segment in LSN order: verified records come back as
``(lsn, payload)``; checksum-corrupt records are skipped with
``recovery_wal_corrupt_records_total`` ticked; a torn tail stops the
segment with ``recovery_wal_torn_tails_total`` ticked.  Neither crashes
boot — both are the expected debris of a crash-mid-write.  Opening a
log **truncates** any torn tail off the final segment first (same
counter), so resumed appends can never land behind bytes replay would
refuse to cross.

Two caveats shape what replay may legitimately contain beyond the
acked stream (the at-least-once side of the contract):

  * an op whose *apply* failed after a successful durable append is
    compensated with an **abort record** (:func:`encode_abort`) so
    replay skips it — a rejected mutation must not resurrect;
  * an op nacked because the *fsync itself* failed is in an
    indeterminate state — the bytes may or may not have reached media,
    and no trailing compensation can be promised on a log that just
    refused a sync.  Such a record MAY replay.  Nacked ops therefore
    must never be counted on in either direction; only acked ops are
    guaranteed present and only abort-compensated ops guaranteed
    absent.
"""

from __future__ import annotations

import os
import re
import struct
import threading
import time
from typing import Iterator, List, Optional, Tuple

import numpy as np

from .. import telemetry
from ..telemetry import timeline as _timeline
from ..resilience import chaos
from . import blockio
from .errors import WALError, WALWriteError

__all__ = ["WriteAheadLog", "encode_edge_op", "decode_edge_op",
           "encode_abort", "decode_abort", "FSYNC_POLICIES"]

FSYNC_POLICIES = ("always", "batch", "off")

_CHAOS_WAL_WRITE = chaos.point("recovery.wal_write")
_CHAOS_FSYNC = chaos.point("recovery.fsync")
_CHAOS_REPLAY = chaos.point("recovery.replay")

_SEG_RE = re.compile(r"^wal-(\d{20})\.seg$")


def _seg_name(start_lsn: int) -> str:
    return f"wal-{start_lsn:020d}.seg"


# -- edge-op record codec ---------------------------------------------------
# One record = one edge-mutation batch.  Endpoints and timestamps are
# pinned to little-endian int64 regardless of producer dtype, so a log
# written on one host replays identically on any other.

_OP_CODES = {"add": 1, "remove": 2}
_OP_NAMES = {v: k for k, v in _OP_CODES.items()}
_EDGE_HEADER = struct.Struct("<BBI")  # op code, has_ts, edge count


def encode_edge_op(op: str, src, dst, ts=None) -> bytes:
    code = _OP_CODES.get(op)
    if code is None:
        raise WALError(f"unknown edge op {op!r}")
    src = np.atleast_1d(np.asarray(src)).astype("<i8").ravel()
    dst = np.atleast_1d(np.asarray(dst)).astype("<i8").ravel()
    if src.shape != dst.shape:
        raise WALError(f"src/dst length mismatch: {src.shape} vs {dst.shape}")
    parts = [_EDGE_HEADER.pack(code, 1 if ts is not None else 0, len(src)),
             src.tobytes(), dst.tobytes()]
    if ts is not None:
        ts = np.atleast_1d(np.asarray(ts)).astype("<i8").ravel()
        if ts.shape != src.shape:
            raise WALError(f"ts length mismatch: {ts.shape} vs {src.shape}")
        parts.append(ts.tobytes())
    return b"".join(parts)


def decode_edge_op(payload: bytes):
    """``(op, src, dst, ts)`` from one record payload; typed
    :class:`WALError` on any framing inconsistency."""
    if len(payload) < _EDGE_HEADER.size:
        raise WALError(f"edge record too short: {len(payload)} bytes")
    code, has_ts, n = _EDGE_HEADER.unpack_from(payload)
    op = _OP_NAMES.get(code)
    if op is None:
        raise WALError(f"unknown edge op code {code}")
    want = _EDGE_HEADER.size + 8 * n * (3 if has_ts else 2)
    if len(payload) != want:
        raise WALError(f"edge record length {len(payload)} != expected "
                       f"{want} for {n} edges")
    off = _EDGE_HEADER.size
    src = np.frombuffer(payload, dtype="<i8", count=n, offset=off)
    off += 8 * n
    dst = np.frombuffer(payload, dtype="<i8", count=n, offset=off)
    off += 8 * n
    ts = (np.frombuffer(payload, dtype="<i8", count=n, offset=off)
          if has_ts else None)
    to_native = lambda a: a.astype(np.int64, copy=True)  # noqa: E731
    return op, to_native(src), to_native(dst), \
        (to_native(ts) if ts is not None else None)


# An abort is a compensation record: the durable record at
# ``target_lsn`` was answered with an error live (its apply failed
# AFTER the append), so replay must not fold it in — otherwise a
# recovered graph would contain a mutation the serving process
# rejected, and post-crash state would diverge from the state the
# crash harness certifies.  Aborts share the edge-op framing (code 3,
# one little-endian int64 "endpoint" carrying the target LSN) so an
# older reader treats them as an unknown-op skip, never a crash.

_ABORT_CODE = 3


def encode_abort(target_lsn: int) -> bytes:
    return (_EDGE_HEADER.pack(_ABORT_CODE, 0, 1)
            + struct.pack("<q", int(target_lsn)))


def decode_abort(payload: bytes) -> Optional[int]:
    """Target LSN when ``payload`` is an abort record, else None."""
    if len(payload) != _EDGE_HEADER.size + 8:
        return None
    code, _has_ts, _n = _EDGE_HEADER.unpack_from(payload)
    if code != _ABORT_CODE:
        return None
    return int(struct.unpack_from("<q", payload, _EDGE_HEADER.size)[0])


# -- the log ----------------------------------------------------------------

class WriteAheadLog:
    """Segmented append log; see module docstring for the contract."""

    _guarded_by = {
        "_f": "_lock", "_seg_written": "_lock", "_next_lsn": "_lock",
        "_seg_path": "_lock", "_unsynced": "_lock", "_closed": "_lock",
    }

    def __init__(self, root: str, segment_bytes: Optional[int] = None,
                 fsync: Optional[str] = None,
                 batch_bytes: Optional[int] = None):
        from ..config import get_config

        cfg = get_config()
        self.root = str(root)
        os.makedirs(self.root, exist_ok=True)
        self.segment_bytes = int(segment_bytes if segment_bytes is not None
                                 else cfg.recovery_segment_bytes)
        self.fsync_policy = str(fsync if fsync is not None
                                else cfg.recovery_fsync)
        if self.fsync_policy not in FSYNC_POLICIES:
            raise ValueError(f"fsync policy must be one of {FSYNC_POLICIES},"
                             f" got {self.fsync_policy!r}")
        self.batch_bytes = int(batch_bytes if batch_bytes is not None
                               else cfg.recovery_batch_bytes)
        self._lock = threading.RLock()  # re-entered by the _locked helpers
        self._f = None
        self._seg_path: Optional[str] = None
        self._seg_written = 0
        self._unsynced = 0
        self._closed = False
        # resume LSN accounting from what is already on disk: only the
        # LAST segment needs a scan (earlier counts are implied by the
        # next segment's start LSN).  Torn debris is truncated off the
        # tail HERE, before any append can reopen the segment — if the
        # very first record tore (crash mid-first-write), the slot
        # count is 0 and the next roll reuses the same wal-<start>.seg
        # name; appending behind un-truncated torn bytes would strand
        # every new record past the point replay stops at.
        segs = self._segments()
        if segs:
            start, path = segs[-1]
            self._next_lsn = start + _resume_segment(path)
        else:
            self._next_lsn = 0
        telemetry.gauge("recovery_wal_segments_total").set(float(len(segs)))

    # -- write side ---------------------------------------------------
    def append(self, payload: bytes) -> int:
        """Durably append one record; returns its LSN.

        Raises :class:`WALWriteError` on any write/fsync failure
        (including chaos faults) — the record must then be treated as
        NOT durable and the submitting request answered with the error.
        """
        t0 = time.perf_counter() if _timeline._ON else 0.0
        with self._lock:
            if self._closed:
                raise WALWriteError("append on closed WAL")
            try:
                _CHAOS_WAL_WRITE()
                if (self._f is None
                        or self._seg_written >= self.segment_bytes):
                    self._roll_locked()
                n = blockio.write_record(self._f, payload)
                self._seg_written += n
                self._unsynced += n
                lsn = self._next_lsn
                self._next_lsn += 1
                if self.fsync_policy == "always" or (
                        self.fsync_policy == "batch"
                        and self._unsynced >= self.batch_bytes):
                    self._sync_locked()
            except WALError:
                raise
            except Exception as e:
                raise WALWriteError(f"wal append failed: {e}") from e
        telemetry.counter("recovery_wal_records_total").inc()
        telemetry.counter("recovery_wal_bytes_total").inc(float(n))
        if _timeline._ON and t0:
            _timeline.emit("wal.append", cat="wal",
                           dur_s=time.perf_counter() - t0,
                           attrs={"lsn": lsn, "bytes": int(n)})
        return lsn

    def sync(self) -> None:
        """Flush + fsync the open segment (no-op under policy "off")."""
        with self._lock:
            if self._f is None or self._closed:
                return
            try:
                self._sync_locked()
            except WALError:
                raise
            except Exception as e:
                raise WALWriteError(f"wal fsync failed: {e}") from e

    def roll(self) -> None:
        """Seal the open segment and start a fresh one — called before a
        checkpoint so truncation can drop everything the checkpoint
        covers (the open segment is never deleted)."""
        with self._lock:
            if not self._closed:
                self._roll_locked()

    def _sync_locked(self) -> None:
        with self._lock:  # re-entrant: callers already hold it
            # the chaos point lives inside the policy gate: "off"
            # promises no fsync, so an injected fsync fault has nothing
            # real to stand in for there
            if self.fsync_policy != "off":
                t0 = time.perf_counter() if _timeline._ON else 0.0
                _CHAOS_FSYNC()
                self._f.flush()
                os.fsync(self._f.fileno())
                telemetry.counter("recovery_wal_fsyncs_total").inc()
                if _timeline._ON and t0:
                    _timeline.emit("wal.fsync", cat="wal",
                                   dur_s=time.perf_counter() - t0)
            self._unsynced = 0

    def _roll_locked(self) -> None:
        with self._lock:  # re-entrant: callers already hold it
            if self._f is not None:
                self._sync_locked()
                self._f.close()
            self._seg_path = os.path.join(self.root,
                                          _seg_name(self._next_lsn))
            self._f = blockio.append_open(self._seg_path)
            self._seg_written = 0
            self._unsynced = 0
        blockio.fsync_dir(self.root)
        telemetry.gauge("recovery_wal_segments_total").set(
            float(len(self._segments())))

    @property
    def last_lsn(self) -> int:
        """LSN of the newest appended record (-1 when empty)."""
        with self._lock:
            return self._next_lsn - 1

    @property
    def next_lsn(self) -> int:
        with self._lock:
            return self._next_lsn

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            if self._f is not None:
                try:
                    if self.fsync_policy != "off":
                        self._f.flush()
                        os.fsync(self._f.fileno())
                finally:
                    self._f.close()
                    self._f = None

    # -- read side ----------------------------------------------------
    def _segments(self) -> List[Tuple[int, str]]:
        out = []
        try:
            names = os.listdir(self.root)
        except OSError:
            return []
        for name in names:
            m = _SEG_RE.match(name)
            if m:
                out.append((int(m.group(1)), os.path.join(self.root, name)))
        out.sort()
        return out

    def replay(self) -> Iterator[Tuple[int, bytes]]:
        """Yield ``(lsn, payload)`` for every verified record on disk.

        Corrupt records are skipped (they still consume an LSN slot so
        later records keep their positions); a torn tail ends its
        segment.  Both tick telemetry; neither raises.  The
        ``recovery.replay`` chaos point fires once per segment.
        """
        with self._lock:
            # under fsync="batch"/"off" the open segment's tail may sit
            # in the stdio buffer — push it to the page cache so a live
            # replay sees every appended record
            if self._f is not None and not self._closed:
                self._f.flush()
        for start_lsn, path in self._segments():
            _CHAOS_REPLAY()
            with open(path, "rb") as f:
                data = f.read()
            lsn = start_lsn
            for kind, _off, payload in blockio.scan_records(data):
                if kind == "ok":
                    yield lsn, payload
                    lsn += 1
                elif kind == "corrupt":
                    telemetry.counter(
                        "recovery_wal_corrupt_records_total").inc()
                    lsn += 1
                else:  # torn
                    telemetry.counter("recovery_wal_torn_tails_total").inc()
                    break

    # -- truncation ---------------------------------------------------
    def truncate_through(self, lsn: int) -> int:
        """Delete sealed segments whose records all have LSN <= ``lsn``.

        Safe to call any time after the covering checkpoint is durably
        published; returns the number of segments removed.  The open
        segment (and the newest segment, whose record count the name of
        a successor would otherwise bound) is never deleted.
        """
        with self._lock:
            active = self._seg_path
        segs = self._segments()
        removed = 0
        for i, (start, path) in enumerate(segs):
            if path == active or i + 1 >= len(segs):
                continue
            next_start = segs[i + 1][0]
            if next_start - 1 <= lsn:
                try:
                    os.unlink(path)
                    removed += 1
                except OSError:
                    continue
        if removed:
            blockio.fsync_dir(self.root)
            telemetry.counter(
                "recovery_wal_truncated_segments_total").inc(removed)
            telemetry.gauge("recovery_wal_segments_total").set(
                float(len(self._segments())))
        return removed


def _resume_segment(path: str) -> int:
    """LSN slots consumed by a segment (ok + corrupt records) — how
    ``__init__`` resumes numbering.

    A torn tail ends the count AND is truncated off the file (through
    ``blockio.truncate_at``, the one sanctioned shortener), so a
    segment the log re-appends to can never put fresh records behind
    bytes replay refuses to cross.  The tick happens here instead of at
    replay for a trimmed tail — the debris is gone before replay runs."""
    with open(path, "rb") as f:
        data = f.read()
    n = 0
    torn_at = None
    for kind, off, _payload in blockio.scan_records(data):
        if kind == "torn":
            torn_at = off
            break
        n += 1
    if torn_at is not None:
        blockio.truncate_at(path, torn_at)
        telemetry.counter("recovery_wal_torn_tails_total").inc()
    return n
