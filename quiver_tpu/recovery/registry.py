"""Unified AOT program registry (ROADMAP item 5).

Every executable cache in the library — the sampler's per-batch jits
and stream-overlay programs, serving's fused per-bucket forwards, the
feature store's merge/admit grid, hetero's per-batch pipelines — used
to be an anonymous ``{}`` on its owner.  They still live on their
owners (the programs close over owner state, so cross-instance sharing
would be wrong), but each is now a :class:`ProgramCache` handed out by
the one :class:`ProgramRegistry`, which gives the fleet three things
the scattered dicts could not:

  * **one accounting surface** — ``registry_hits_total`` /
    ``registry_misses_total`` / ``registry_builds_total`` counters and
    a ``registry_programs_total`` size gauge, all labelled by
    subsystem;
  * **a retrace-budget gate** — after warmup the registry is
    ``seal()``\\ ed; every post-seal build ticks
    ``registry_retraces_post_seal_total`` and, past the per-subsystem
    budget, raises :class:`RetraceBudgetExceeded`.  A warm boot that
    compiles something cold is a bug this turns into a failure;
  * **persistent compilation** — ``enable_persistent_cache`` points
    JAX's compilation cache at a directory, so the *backend compile*
    (the 5.2–37.6 s/program cost BENCH_r05 measured) is paid once per
    fleet, not once per process.  ``persistent_cache_hits`` counts the
    disk hits via JAX's monitoring events; the warm-restart bench and
    crash-harness acceptance both key off it.

The retrace-guard pytest plugin keeps working unchanged: a
``ProgramCache`` is a real ``dict`` (``len()`` growth is what the
plugin measures), and the build methods it patches still run.
"""

from __future__ import annotations

import threading
import weakref
from typing import Dict, Optional

from .. import telemetry
from ..telemetry import profile as _profile
from ..telemetry import timeline as _timeline
from .errors import RetraceBudgetExceeded

__all__ = ["ProgramCache", "ProgramRegistry", "get_program_registry",
           "program_cache"]


class ProgramCache(dict):
    """A subsystem's executable cache: a dict that reports to the registry.

    The *probes* — ``get`` and ``in`` — tick hit/miss; they are what
    every owner's lookup idiom starts with (``fn = cache.get(B)`` /
    ``if B not in cache``).  ``[]`` reads are deliberately silent:
    they follow a probe in the same logical lookup, and ticking both
    would count one lookup twice and skew the hit-rate dashboards.
    Insertions tick builds and pass through the seal gate.  Locking is
    the owner's concern exactly as before (e.g. serving's
    double-checked ``_lock`` around ``_fused_fns``) — the registry's
    own counters take its internal lock.
    """

    def __init__(self, subsystem: str, registry: "ProgramRegistry"):
        super().__init__()
        self.subsystem = subsystem
        self._registry = registry

    def get(self, key, default=None):
        self._registry._tick(self.subsystem, dict.__contains__(self, key))
        return dict.get(self, key, default)

    def __contains__(self, key) -> bool:
        present = dict.__contains__(self, key)
        self._registry._tick(self.subsystem, present)
        return present

    def __setitem__(self, key, value) -> None:
        if _profile._ON:   # one global read when profiling is off
            value = _profile.wrap(self.subsystem, key, value)
        fresh = not dict.__contains__(self, key)
        dict.__setitem__(self, key, value)
        if fresh:
            self._registry._built(self.subsystem)

    def setdefault(self, key, default=None):
        if dict.__contains__(self, key):
            return dict.__getitem__(self, key)
        self[key] = default
        return default


def _zero_stats() -> Dict[str, int]:
    return {"hits": 0, "misses": 0, "builds": 0, "post_seal_builds": 0}


class ProgramRegistry:
    """Process-wide ledger over every :class:`ProgramCache`."""

    _guarded_by = {
        "_stats": "_lock", "_caches": "_lock", "_sealed": "_lock",
        "_budgets": "_lock", "_default_budget": "_lock",
        "_pcache_hits": "_lock", "_pcache_dir": "_lock",
    }

    def __init__(self):
        self._lock = threading.Lock()
        self._stats: Dict[str, Dict[str, int]] = {}
        self._caches: list = []  # (subsystem, weakref to ProgramCache)
        self._sealed = False
        self._budgets: Dict[str, int] = {}
        self._default_budget: Optional[int] = None
        self._pcache_hits = 0
        self._pcache_dir: Optional[str] = None

    # -- cache hand-out -----------------------------------------------
    def cache(self, subsystem: str, owner=None) -> ProgramCache:
        """A fresh executable cache accounted under ``subsystem``.

        ``owner`` is accepted for call-site documentation only; the
        registry holds the cache by weakref so a dropped owner never
        leaks its programs through the ledger.
        """
        c = ProgramCache(subsystem, self)
        with self._lock:
            self._stats.setdefault(subsystem, _zero_stats())
            self._caches.append((subsystem, weakref.ref(c)))
        return c

    # -- accounting (called by ProgramCache) --------------------------
    def _tick(self, subsystem: str, hit: bool) -> None:
        with self._lock:
            st = self._stats.setdefault(subsystem, _zero_stats())
            st["hits" if hit else "misses"] += 1
        if hit:
            telemetry.counter("registry_hits_total",
                              subsystem=subsystem).inc()
        else:
            telemetry.counter("registry_misses_total",
                              subsystem=subsystem).inc()

    def _built(self, subsystem: str) -> None:
        with self._lock:
            st = self._stats.setdefault(subsystem, _zero_stats())
            st["builds"] += 1
            sealed = self._sealed
            over = False
            if sealed:
                st["post_seal_builds"] += 1
                budget = self._budgets.get(subsystem, self._default_budget)
                over = budget is not None and \
                    st["post_seal_builds"] > budget
        telemetry.counter("registry_builds_total",
                          subsystem=subsystem).inc()
        if _timeline._ON:  # one global read when the timeline is off
            _timeline.emit("registry.build", cat="registry",
                           attrs={"subsystem": subsystem,
                                  "post_seal": bool(sealed)})
        if sealed:
            telemetry.counter("registry_retraces_post_seal_total",
                              subsystem=subsystem).inc()
            if over:
                raise RetraceBudgetExceeded(
                    f"subsystem {subsystem!r} built a program after "
                    f"seal() beyond its retrace budget "
                    f"({self._budgets.get(subsystem, self._default_budget)})"
                    f" — a warm boot compiled something cold")

    # -- the retrace-budget gate --------------------------------------
    def seal(self, budget: Optional[int] = None,
             per_subsystem: Optional[Dict[str, int]] = None) -> None:
        """Close the warmup window: post-seal builds are counted and,
        beyond the budget, fatal.  ``budget`` is the default allowance
        per subsystem (``None`` reads ``config.recovery_retrace_budget``;
        a negative value there means count-only, never raise)."""
        if budget is None:
            from ..config import get_config

            cfg_budget = int(get_config().recovery_retrace_budget)
            budget = None if cfg_budget < 0 else cfg_budget
        with self._lock:
            self._sealed = True
            self._default_budget = budget
            self._budgets = dict(per_subsystem or {})
            for st in self._stats.values():
                st["post_seal_builds"] = 0
        telemetry.gauge("registry_sealed_state").set(1.0)

    def unseal(self) -> None:
        with self._lock:
            self._sealed = False
        telemetry.gauge("registry_sealed_state").set(0.0)

    @property
    def sealed(self) -> bool:
        with self._lock:
            return self._sealed

    # -- introspection / metrics --------------------------------------
    def stats(self) -> Dict[str, Dict[str, int]]:
        with self._lock:
            out = {k: dict(v) for k, v in self._stats.items()}
            live = [(sub, ref()) for sub, ref in self._caches]
        for sub, c in live:
            if c is not None:
                out.setdefault(sub, _zero_stats())
                out[sub]["size"] = out[sub].get("size", 0) + len(c)
        for st in out.values():
            st.setdefault("size", 0)
        return out

    def export_metrics(self) -> Dict[str, Dict[str, int]]:
        """Publish per-subsystem sizes as gauges; returns the stats."""
        stats = self.stats()
        for sub, st in stats.items():
            telemetry.gauge("registry_programs_total", subsystem=sub).set(
                float(st["size"]))
        return stats

    # -- persistent compilation cache ---------------------------------
    def enable_persistent_cache(self, cache_dir: str) -> bool:
        """Point JAX's compilation cache at ``cache_dir`` (created if
        missing) and start counting disk hits.  Returns False — with
        the reason logged — when this JAX build refuses, so boot
        proceeds merely cold, not dead."""
        import logging
        import os

        log = logging.getLogger("quiver_tpu.recovery")
        try:
            os.makedirs(cache_dir, exist_ok=True)
            import jax

            jax.config.update("jax_compilation_cache_dir", str(cache_dir))
            jax.config.update("jax_persistent_cache_min_compile_time_secs",
                              0.0)
            try:
                jax.config.update(
                    "jax_persistent_cache_min_entry_size_bytes", -1)
            except Exception:  # older jax: flag absent, threshold default
                pass
            self._install_hit_listener()
        except Exception as e:
            log.warning("persistent compilation cache unavailable: %s", e)
            return False
        with self._lock:
            self._pcache_dir = str(cache_dir)
        return True

    def _install_hit_listener(self) -> None:
        global _HIT_LISTENER_INSTALLED
        with _LISTENER_LOCK:
            if _HIT_LISTENER_INSTALLED:
                return
            from jax import monitoring

            def _on_event(event, **kwargs):
                if "cache_hit" in event or "cache_hits" in event:
                    reg = get_program_registry()
                    with reg._lock:
                        reg._pcache_hits += 1
                    telemetry.counter(
                        "registry_persistent_cache_hits_total").inc()

            monitoring.register_event_listener(_on_event)
            _HIT_LISTENER_INSTALLED = True

    @property
    def persistent_cache_hits(self) -> int:
        with self._lock:
            return self._pcache_hits

    @property
    def persistent_cache_dir(self) -> Optional[str]:
        with self._lock:
            return self._pcache_dir


_REGISTRY: Optional[ProgramRegistry] = None
_REGISTRY_LOCK = threading.Lock()
_LISTENER_LOCK = threading.Lock()
_HIT_LISTENER_INSTALLED = False


def get_program_registry() -> ProgramRegistry:
    global _REGISTRY
    with _REGISTRY_LOCK:
        if _REGISTRY is None:
            _REGISTRY = ProgramRegistry()
        return _REGISTRY


def program_cache(subsystem: str, owner=None) -> ProgramCache:
    """The constructor the executable-cache owners call in place of
    ``{}`` — e.g. ``self._jitted = program_cache("sampler", owner=self)``."""
    return get_program_registry().cache(subsystem, owner=owner)
