"""RecoveryManager — the boot/checkpoint/health conductor.

One manager owns one durability root::

    <root>/wal/wal-<lsn>.seg     append log (wal.py)
    <root>/ckpt/ckpt-<seq>.qgr   snapshots  (checkpoint.py)

and walks a process through the readiness ladder the serving tier
exposes at ``/healthz``::

    booting -> replaying -> warming -> serving

``boot_degraded()`` climbs to *replaying*: the newest loadable
checkpoint is restored (or a fresh graph built) and the WAL opened —
the graph is already **servable but stale** (``health()["stale"]``),
which is the serve-degraded-while-replaying contract: reads are
answered from the checkpointed topology while the tail of the log
folds in.  ``finish_boot()`` replays strictly past the checkpoint
watermark, optionally runs a warmup, optionally ``seal()``\\ s the
program registry (turning any later cold compile into a budget
violation), and lands on *serving*.  ``boot()`` is both in sequence.

Checkpoints are **consistent by construction**: when an
:class:`~quiver_tpu.stream.ingest.IngestLane` is attached, the snapshot
runs as a *barrier* on the single writer thread — between two applies,
never inside one — so the captured graph state and the captured WAL
watermark (``lane._applied_lsn``) agree exactly.  The sequence is
roll → snapshot → truncate: the log is sealed first so truncation can
drop every segment the snapshot covers.

The replay deadline (``config.recovery_deadline_s``) bounds how long a
boot may chew log before the operator hears about it as a typed
:class:`RecoveryDeadlineExceeded` instead of a silent hang.
"""

from __future__ import annotations

import logging
import os
import threading
import time
import weakref
from typing import Callable, Dict, Optional

from .. import telemetry
from .checkpoint import load_checkpoint, restore_graph, save_checkpoint
from .errors import RecoveryDeadlineExceeded, RecoveryError, WALError
from .wal import WriteAheadLog, decode_abort, decode_edge_op

__all__ = ["RecoveryManager", "health_status", "set_active",
           "RECOVERY_STATES"]

log = logging.getLogger("quiver_tpu.recovery")

RECOVERY_STATES = ("booting", "replaying", "warming", "serving")
_STATE_CODE = {s: i for i, s in enumerate(RECOVERY_STATES)}


class RecoveryManager:
    """Crash-only lifecycle for one StreamingGraph deployment."""

    _guarded_by = {
        "_state": "_lock", "_stale": "_lock", "_features": "_lock",
        "_lane": "_lock", "_ckpt": "_lock", "_replayed": "_lock",
    }

    def __init__(self, root: Optional[str] = None,
                 graph_factory: Optional[Callable] = None,
                 delta_capacity: Optional[int] = None, device=None,
                 segment_bytes: Optional[int] = None,
                 fsync: Optional[str] = None):
        from ..config import get_config

        cfg = get_config()
        root = str(root if root is not None else cfg.recovery_dir)
        if not root:
            raise RecoveryError(
                "no durability root: pass root= or set "
                "QUIVER_TPU_RECOVERY_DIR / config.recovery_dir")
        self.root = root
        self.wal_dir = os.path.join(root, "wal")
        self.ckpt_dir = os.path.join(root, "ckpt")
        self.graph_factory = graph_factory
        self.delta_capacity = delta_capacity
        self.device = device
        self._wal_kwargs = {"segment_bytes": segment_bytes, "fsync": fsync}
        self.wal: Optional[WriteAheadLog] = None
        self.graph = None
        self._lock = threading.Lock()
        self._state = "booting"
        self._stale = False
        self._features: Dict[str, object] = {}
        self._lane = None
        self._ckpt = None
        self._replayed = 0
        self._replay_from = -1        # boot thread only
        self._boot_t0: Optional[float] = None
        self._boot_seconds: Optional[float] = None
        self._ckpt_thread: Optional[threading.Thread] = None
        self._ckpt_wake = threading.Event()
        set_active(self)

    # -- state ladder --------------------------------------------------
    def _set_state(self, state: str, stale: Optional[bool] = None) -> None:
        with self._lock:
            self._state = state
            if stale is not None:
                self._stale = stale
        telemetry.gauge("recovery_state").set(float(_STATE_CODE[state]))

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def health(self) -> dict:
        """The ``/healthz`` payload: readiness state + staleness flag.

        ``ready`` (and HTTP 200) only in the *serving* state; a
        replaying process answers 503 with ``stale: true`` so load
        balancers keep traffic away while operators can still see a
        live, progressing boot.
        """
        with self._lock:
            state, stale, replayed = self._state, self._stale, self._replayed
        graph = self.graph
        out = {
            "state": state,
            "ready": state == "serving",
            "stale": stale,
            "managed": True,
            "replayed_records": replayed,
        }
        if graph is not None:
            out["graph_version"] = int(graph.version)
        if self.wal is not None:
            out["wal_next_lsn"] = self.wal.next_lsn
        if self._boot_seconds is not None:
            out["boot_seconds"] = self._boot_seconds
        return out

    # -- boot ----------------------------------------------------------
    def boot_degraded(self):
        """Restore the newest checkpoint (or build fresh) and open the
        WAL; returns the graph, *servable but stale*, in state
        ``replaying``.  Call :meth:`finish_boot` to fold in the log tail
        and reach ``serving``."""
        from ..config import get_config

        cfg = get_config()
        self._boot_t0 = time.perf_counter()
        self._set_state("booting", stale=True)
        if cfg.recovery_cache_dir:
            from .registry import get_program_registry

            get_program_registry().enable_persistent_cache(
                cfg.recovery_cache_dir)
        os.makedirs(self.wal_dir, exist_ok=True)
        os.makedirs(self.ckpt_dir, exist_ok=True)
        ckpt = load_checkpoint(self.ckpt_dir)
        if ckpt is not None:
            graph = restore_graph(ckpt, delta_capacity=self.delta_capacity,
                                  device=self.device)
            self._replay_from = ckpt.wal_lsn
            log.info("restored checkpoint %s (graph version %d, "
                     "wal watermark %d)", ckpt.path, ckpt.graph_version,
                     ckpt.wal_lsn)
        elif self.graph_factory is not None:
            graph = self.graph_factory()
            self._replay_from = -1
        else:
            raise RecoveryError(
                f"no checkpoint under {self.ckpt_dir} and no graph_factory "
                "to build a fresh graph from")
        # quiverlint: ignore[QT008] -- set exactly once here, before the
        # checkpointer thread can exist; read-only references afterwards
        self.wal = WriteAheadLog(self.wal_dir, **self._wal_kwargs)
        self.graph = graph  # quiverlint: ignore[QT008] -- same: boot-once
        with self._lock:
            self._ckpt = ckpt
        self._set_state("replaying", stale=True)
        return graph

    def finish_boot(self, warmup: Optional[Callable] = None,
                    seal: bool = False) -> int:
        """Replay the WAL tail, warm, and flip to ``serving``.

        ``warmup`` (optional) runs with the recovered graph between
        replay and serving — the place to pre-build executables.  With
        ``seal=True`` the program registry is sealed afterwards, so a
        warm boot that still compiles past its retrace budget fails
        loudly.  Returns the number of records replayed.
        """
        from ..config import get_config
        from ..stream.compactor import compact

        if self.wal is None or self.graph is None:
            raise RecoveryError("finish_boot before boot_degraded")
        cfg = get_config()
        deadline_s = float(cfg.recovery_deadline_s)
        t0 = time.perf_counter()
        replayed = skipped = 0

        def _check_deadline() -> None:
            if deadline_s > 0 and (time.perf_counter()
                                   - self._boot_t0) > deadline_s:
                telemetry.counter("recovery_deadline_exceeded_total").inc()
                raise RecoveryDeadlineExceeded(
                    f"replay still running after {deadline_s:.1f}s "
                    f"({replayed} records in); raise "
                    "recovery_deadline_s or checkpoint more often")

        # Two passes over the tail: an abort record lands AFTER the
        # record it cancels, so the abort set must be complete before
        # anything is folded in.  Buffering the tail is fine — it only
        # spans back to the last checkpoint watermark.
        tail = []
        aborted = set()
        for lsn, payload in self.wal.replay():
            if lsn <= self._replay_from:
                continue
            _check_deadline()
            target = decode_abort(payload)
            if target is not None:
                aborted.add(target)
                continue
            tail.append((lsn, payload))
        for lsn, payload in tail:
            _check_deadline()
            if lsn in aborted:
                # durable but nacked live (apply failed after the
                # append): the rejected mutation must not resurrect
                telemetry.counter("recovery_replay_aborted_total").inc()
                continue
            try:
                op, src, dst, ts = decode_edge_op(payload)
            except WALError as e:
                # a verified-checksum record that doesn't decode is a
                # producer bug, not a torn write — skip it loudly
                log.warning("undecodable WAL record at lsn %d: %s", lsn, e)
                skipped += 1
                continue
            self._apply_replayed(op, src, dst, ts, compact)
            replayed += 1
        elapsed = time.perf_counter() - t0
        if replayed:
            telemetry.counter("recovery_replay_records_total").inc(replayed)
        if skipped:
            telemetry.counter("recovery_replay_skipped_total").inc(skipped)
        telemetry.gauge("recovery_replay_seconds").set(elapsed)
        with self._lock:
            self._replayed = replayed
        self._set_state("warming", stale=False)
        if warmup is not None:
            warmup(self.graph)
        if seal:
            from .registry import get_program_registry

            get_program_registry().seal()
        self._boot_seconds = time.perf_counter() - self._boot_t0
        telemetry.gauge("recovery_boot_seconds").set(self._boot_seconds)
        self._set_state("serving", stale=False)
        return replayed

    def boot(self, warmup: Optional[Callable] = None, seal: bool = False):
        """``boot_degraded()`` + ``finish_boot()``; returns the graph."""
        graph = self.boot_degraded()
        self.finish_boot(warmup=warmup, seal=seal)
        return graph

    def adopt(self, graph, applied_lsn: int,
              warmup: Optional[Callable] = None, seal: bool = False) -> int:
        """Promotion boot: take ownership of a WAL this process has been
        *following*, not writing.

        A promoted follower already holds a nearly-current graph (the
        shipped tail folded through ``applied_lsn``), so re-restoring
        the checkpoint would throw that warmth away.  Opening the log is
        the ownership handover — :class:`WriteAheadLog` resumes the
        append cursor and clears the dead leader's torn tail exactly as
        a same-process restart would — then only the records *past* the
        follower's applied watermark are folded, abort-aware (two
        passes, same as :meth:`finish_boot`).

        One divergence is unrecoverable by folding: an abort whose
        target is ``<= applied_lsn`` means the dead leader nacked a
        record this follower already applied (a late abort that crossed
        the failover).  Un-applying is not a graph operation, so that
        path falls back to a full checkpoint boot — correctness over
        warmth, and ``recovery_adopt_fallbacks_total`` says it
        happened.  Either way the manager lands on ``serving``; the
        caller re-reads ``self.graph`` (the fallback replaces it).
        Returns the number of records folded/replayed.
        """
        from ..stream.compactor import compact

        self._boot_t0 = time.perf_counter()
        self._set_state("booting", stale=True)
        os.makedirs(self.wal_dir, exist_ok=True)
        os.makedirs(self.ckpt_dir, exist_ok=True)
        wal = WriteAheadLog(self.wal_dir, **self._wal_kwargs)
        applied_lsn = int(applied_lsn)
        tail = []
        aborted = set()
        late_abort = None
        for lsn, payload in wal.replay():
            target = decode_abort(payload)
            if target is not None:
                if target <= applied_lsn:
                    late_abort = (lsn, target)
                    break
                aborted.add(target)
                continue
            if lsn <= applied_lsn:
                continue
            tail.append((lsn, payload))
        if late_abort is not None:
            telemetry.counter("recovery_adopt_fallbacks_total").inc()
            log.warning(
                "late abort at lsn %d targets already-applied lsn %d; "
                "adopted graph is ahead of the durable log — falling "
                "back to checkpoint boot", *late_abort)
            wal.close()
            self.boot_degraded()
            return self.finish_boot(warmup=warmup, seal=seal)
        # quiverlint: ignore[QT008] -- promotion handover: set once here,
        # before any lane or checkpointer exists for this manager
        self.wal = wal
        self.graph = graph  # quiverlint: ignore[QT008] -- same: adopt-once
        with self._lock:
            self._ckpt = load_checkpoint(self.ckpt_dir)
        self._set_state("replaying", stale=True)
        replayed = skipped = 0
        for lsn, payload in tail:
            if lsn in aborted:
                telemetry.counter("recovery_replay_aborted_total").inc()
                continue
            try:
                op, src, dst, ts = decode_edge_op(payload)
            except WALError as e:
                log.warning("undecodable WAL record at lsn %d: %s", lsn, e)
                skipped += 1
                continue
            self._apply_replayed(op, src, dst, ts, compact)
            replayed += 1
        if replayed:
            telemetry.counter("recovery_replay_records_total").inc(replayed)
        if skipped:
            telemetry.counter("recovery_replay_skipped_total").inc(skipped)
        with self._lock:
            self._replayed = replayed
        self._set_state("warming", stale=False)
        if warmup is not None:
            warmup(self.graph)
        if seal:
            from .registry import get_program_registry

            get_program_registry().seal()
        self._boot_seconds = time.perf_counter() - self._boot_t0
        telemetry.gauge("recovery_boot_seconds").set(self._boot_seconds)
        self._set_state("serving", stale=False)
        return replayed

    def _apply_replayed(self, op, src, dst, ts, compact) -> None:
        graph = self.graph
        if op == "add":
            try:
                graph.add_edges(src, dst, ts if graph.has_ts else None)
            except BufferError:
                compact(graph)  # same fold-then-retry as the live lane
                graph.add_edges(src, dst, ts if graph.has_ts else None)
        elif op == "remove":
            graph.remove_edges(src, dst)

    # -- attachment ----------------------------------------------------
    def attach_lane(self, lane) -> None:
        """Wire an IngestLane into the durability path: its worker
        appends to this WAL before applying (durable-before-ack) and
        executes this manager's checkpoints as barriers."""
        if self.wal is None:
            raise RecoveryError("attach_lane before boot_degraded")
        lane.wal = self.wal
        lane.checkpoint_fn = self._do_checkpoint
        with self._lock:
            self._lane = lane

    def attach_feature(self, name: str, feature) -> int:
        """Register a feature store for coldcache snapshot/restore.

        If the boot checkpoint carried overlay state under ``name``, it
        is restored now (best-effort: a shape/capacity mismatch logs
        and leaves the overlay cold — staleness of a *cache* is a perf
        regression, not a correctness loss).  Returns rows re-warmed.
        """
        with self._lock:
            self._features[str(name)] = feature
            ckpt = self._ckpt
        state = (ckpt.coldcaches.get(str(name))
                 if ckpt is not None else None)
        if state is None:
            return 0
        try:
            warmed = feature.restore_coldcache_state(state)
        except (ValueError, KeyError) as e:
            telemetry.counter(
                "recovery_coldcache_restore_errors_total").inc()
            log.warning("coldcache restore for %r failed (%s); "
                        "starting cold", name, e)
            return 0
        telemetry.counter("recovery_coldcache_rows_restored_total").inc(
            warmed)
        return warmed

    # -- checkpointing -------------------------------------------------
    def checkpoint(self, timeout: float = 60.0):
        """Take one consistent snapshot; returns its path.

        Routed through the attached lane's writer thread as a barrier
        when there is one (so it lands between applies, at that thread's
        exact ``_applied_lsn``); taken inline otherwise.
        """
        with self._lock:
            lane = self._lane
        if lane is not None and lane.is_running():
            barrier = lane.request_checkpoint()
            if not barrier.done.wait(timeout):
                raise RecoveryError(
                    f"checkpoint barrier not executed within {timeout}s "
                    "(ingest worker wedged?)")
            if barrier.error is not None:
                raise barrier.error
            return barrier.result
        wal_lsn = self.wal.last_lsn if self.wal is not None else -1
        return self._do_checkpoint(wal_lsn)

    def _do_checkpoint(self, wal_lsn: int):
        if self.graph is None:
            raise RecoveryError("checkpoint before boot")
        if self.wal is not None:
            self.wal.roll()
        with self._lock:
            features = dict(self._features)
        coldcaches = {}
        for name, feat in features.items():
            try:
                coldcaches[name] = feat.export_coldcache_state()
            except Exception as e:
                telemetry.counter(
                    "recovery_coldcache_export_errors_total").inc()
                log.warning("coldcache export for %r failed: %s", name, e)
        path = save_checkpoint(self.ckpt_dir, self.graph,
                               coldcaches=coldcaches, wal_lsn=wal_lsn)
        if self.wal is not None:
            self.wal.truncate_through(wal_lsn)
        return path

    def start_checkpointer(self,
                           interval_s: Optional[float] = None) -> None:
        """Periodic checkpoints on a daemon thread (default interval
        ``config.recovery_checkpoint_interval_s``)."""
        from ..config import get_config

        if self._ckpt_thread is not None:
            return
        interval = float(interval_s if interval_s is not None
                         else get_config().recovery_checkpoint_interval_s)
        self._ckpt_wake.clear()

        def _loop():
            while not self._ckpt_wake.wait(interval):
                try:
                    self.checkpoint()
                except Exception as e:
                    # a failed periodic snapshot costs replay time, not
                    # data — log it and keep the cadence
                    telemetry.counter(
                        "recovery_checkpoint_errors_total").inc()
                    log.warning("periodic checkpoint failed: %s", e)

        self._ckpt_thread = threading.Thread(
            target=_loop, daemon=True, name="quiver-recovery-ckpt")
        self._ckpt_thread.start()

    def stop_checkpointer(self, timeout: float = 5.0) -> None:
        from ..resilience.shutdown import join_and_reap

        t = self._ckpt_thread
        if t is None:
            return
        self._ckpt_wake.set()
        self._ckpt_thread = None
        join_and_reap([t], timeout, component="recovery.checkpointer")

    def close(self) -> None:
        """Stop the checkpointer and close the WAL (graph stays usable)."""
        self.stop_checkpointer()
        if self.wal is not None:
            self.wal.close()
        with _ACTIVE_LOCK:
            global _ACTIVE
            if _ACTIVE is not None and _ACTIVE() is self:
                _ACTIVE = None


# -- process-wide health surface (read by /healthz) -------------------------

_ACTIVE: Optional["weakref.ref[RecoveryManager]"] = None
_ACTIVE_LOCK = threading.Lock()


def set_active(manager: Optional[RecoveryManager]) -> None:
    """Make ``manager`` the one ``/healthz`` reports on (held weakly —
    a dropped manager reverts the endpoint to unmanaged)."""
    global _ACTIVE
    with _ACTIVE_LOCK:
        _ACTIVE = weakref.ref(manager) if manager is not None else None


def health_status() -> dict:
    """The process's readiness document.

    Unmanaged processes (no RecoveryManager constructed — every
    deployment predating this tier) report ``serving``/ready, so
    adding the endpoint never takes a healthy legacy deployment out of
    rotation.
    """
    with _ACTIVE_LOCK:
        ref = _ACTIVE
    mgr = ref() if ref is not None else None
    if mgr is None:
        return {"state": "serving", "ready": True, "stale": False,
                "managed": False}
    return mgr.health()
