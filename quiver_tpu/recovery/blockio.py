"""Durable block I/O — the ONLY recovery module allowed raw file writes.

Every byte the recovery tier persists flows through the two primitives
here, and quiverlint QT011 enforces that structurally: a bare
``open(..., "w")`` anywhere else under ``quiver_tpu/recovery/`` is a
lint failure.  The two blessed write paths are:

  * **checksummed records** — ``write_record`` frames a payload as
    ``magic | length | crc32c | payload`` so a reader can detect both a
    torn tail (partial write at the moment of a crash) and bit rot
    (checksum mismatch) and tell the two apart;
  * **atomic publish** — ``atomic_publish`` writes a complete file to a
    temp name, fsyncs it, then ``os.rename``\\ s over the target and
    fsyncs the directory: readers observe either the old file or the
    new one, never a half-written hybrid.

``truncate_at`` rounds out the set: the only sanctioned way to shorten
a file, used by the WAL to clear torn crash debris off a segment tail
before new records may land behind it.

The checksum is CRC-32C (Castagnoli, the iSCSI/ext4 polynomial) —
table-driven pure Python, no third-party wheel.  Records here are edge
batches of a few KB, where the table walk is noise next to the fsync.
"""

from __future__ import annotations

import os
import struct
import threading
from typing import Iterator, Optional, Tuple

__all__ = [
    "crc32c", "RECORD_MAGIC", "RECORD_HEADER_SIZE", "MAX_RECORD_BYTES",
    "write_record", "scan_records", "atomic_publish", "fsync_dir",
    "append_open", "truncate_at",
]

# -- CRC-32C (Castagnoli) ---------------------------------------------------

_POLY = 0x82F63B78


def _make_table() -> Tuple[int, ...]:
    table = []
    for i in range(256):
        c = i
        for _ in range(8):
            c = (c >> 1) ^ (_POLY if c & 1 else 0)
        table.append(c)
    return tuple(table)


_TABLE = _make_table()


def crc32c(data: bytes, crc: int = 0) -> int:
    """CRC-32C of ``data``; pass a previous value to continue a run."""
    crc ^= 0xFFFFFFFF
    table = _TABLE
    for b in memoryview(data):
        crc = (crc >> 8) ^ table[(crc ^ b) & 0xFF]
    return crc ^ 0xFFFFFFFF


# -- checksummed record framing ---------------------------------------------

RECORD_MAGIC = b"QW"
_HEADER = struct.Struct("<2sII")  # magic, payload length, crc32c(payload)
RECORD_HEADER_SIZE = _HEADER.size
# framing sanity bound: a "length" above this is treated as torn/garbage,
# not as an instruction to seek 4 GB ahead
MAX_RECORD_BYTES = 256 << 20


def write_record(f, payload: bytes) -> int:
    """Append one framed record to ``f``; returns bytes written.

    Durability is the caller's job (the WAL owns the fsync policy) —
    this writes into the OS page cache only.  Header and payload go out
    as ONE write so an unbuffered handle (``append_open``) makes a
    crash between them impossible; a raw handle may still short-write,
    so the loop retries the remainder (the tail of an interrupted loop
    is exactly the torn frame ``scan_records`` knows how to stop at).
    """
    if len(payload) > MAX_RECORD_BYTES:
        raise ValueError(f"record payload {len(payload)} bytes exceeds "
                         f"MAX_RECORD_BYTES {MAX_RECORD_BYTES}")
    header = _HEADER.pack(RECORD_MAGIC, len(payload), crc32c(payload))
    mv = memoryview(header + payload)
    total = len(mv)
    while mv:
        n = f.write(mv)
        if n is None or n >= len(mv):  # buffered handles take it whole
            break
        mv = mv[n:]
    return total


def scan_records(buf: bytes) -> Iterator[Tuple[str, int, Optional[bytes]]]:
    """Walk a segment's bytes yielding ``(kind, offset, payload)``.

    ``kind`` is ``"ok"`` (payload verified), ``"corrupt"`` (checksum
    mismatch but the frame resyncs — the record is skipped and the scan
    continues), or ``"torn"`` (the tail cannot be framed: partial
    header, truncated payload, or garbage where magic should be — the
    scan stops, which is the crash-at-write case).  A corrupt record
    only resyncs when the *next* frame boundary lands on EOF or a valid
    magic; anything else means the length field itself is suspect, and
    trusting it would misframe the rest of the log.
    """
    off, n = 0, len(buf)
    while off < n:
        if n - off < RECORD_HEADER_SIZE:
            yield "torn", off, None
            return
        magic, length, crc = _HEADER.unpack_from(buf, off)
        if magic != RECORD_MAGIC or length > MAX_RECORD_BYTES:
            yield "torn", off, None
            return
        end = off + RECORD_HEADER_SIZE + length
        if end > n:
            yield "torn", off, None
            return
        payload = bytes(buf[off + RECORD_HEADER_SIZE:end])
        if crc32c(payload) != crc:
            if end == n or buf[end:end + len(RECORD_MAGIC)] == RECORD_MAGIC:
                yield "corrupt", off, None
                off = end
                continue
            yield "torn", off, None
            return
        yield "ok", off, payload
        off = end


# -- atomic whole-file publication ------------------------------------------

def fsync_dir(path: str) -> None:
    """fsync a directory so a just-renamed entry survives power loss."""
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def atomic_publish(path: str, data: bytes, exclusive: bool = False) -> bool:
    """Publish ``data`` at ``path`` atomically: tmp + fsync + commit.

    A crash at any instant leaves either the previous file (or nothing)
    or the complete new one — the commit is the atomic point.  Stray
    ``*.tmp.<pid>`` files from a crashed writer are garbage readers
    must ignore (the checkpoint loader filters on the final name).

    Two commit modes:

      * default — ``os.rename``: last writer wins, readers always see
        a complete file (membership records, checkpoints);
      * ``exclusive=True`` — ``os.link``: the commit FAILS if ``path``
        already exists, making the publish a filesystem compare-and-
        swap.  Returns False when another writer already owns the name
        (how leader-election claims stay race-free: exactly one racer
        links its complete record under ``claim-<epoch>``).

    Returns True when this call published the file.
    """
    path = str(path)
    # pid AND thread id: two threads of one process publishing the same
    # target (heartbeat + state-change announce, claim racers in tests)
    # must not share a temp file — interleaved writes into one fd pair
    # would publish a hybrid
    tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
    fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o644)
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        if exclusive:
            try:
                os.link(tmp, path)
            except FileExistsError:
                return False
            finally:
                os.unlink(tmp)
        else:
            os.rename(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    fsync_dir(os.path.dirname(path) or ".")
    return True


def append_open(path: str):
    """Open a WAL segment for append — binary and **unbuffered**, so
    every ``write_record`` reaches the OS page cache before it returns.

    That is what makes the WAL's ``"batch"`` fsync policy honest about
    kill -9: once the write syscall returns, the bytes belong to the
    kernel and survive the process dying; a user-space stdio buffer
    would silently hold acked records hostage until it happened to
    fill."""
    return open(path, "ab", buffering=0)


def truncate_at(path: str, offset: int) -> None:
    """Truncate ``path`` to ``offset`` bytes and fsync the result.

    The third blessed write path (after records and atomic publish):
    how the WAL clears torn debris off a segment tail before appending
    behind it — a destructive-looking operation that only ever removes
    bytes replay already refuses to cross."""
    with open(path, "rb+") as f:
        f.truncate(int(offset))
        f.flush()
        os.fsync(f.fileno())
