"""Typed recovery failures (docs/RECOVERY.md).

Mirrors the resilience error tree (``resilience/errors.py``): every
durability failure surfaces as a :class:`RecoveryError` subclass so
callers can catch the whole family — or one precise mode — without
string matching.  The contract the crash harness enforces: a
durability fault is *answered* (on the ingest ``results`` queue, or
raised from ``boot()``), never silently swallowed — silent loss is the
one failure mode a WAL exists to rule out.
"""

from __future__ import annotations

__all__ = [
    "RecoveryError", "WALError", "WALWriteError", "SnapshotFormatError",
    "CheckpointError", "RecoveryDeadlineExceeded", "RetraceBudgetExceeded",
]


class RecoveryError(RuntimeError):
    """Base class for every durability / warm-restart failure."""


class WALError(RecoveryError):
    """Write-ahead-log failure (framing, decode, or I/O)."""


class WALWriteError(WALError):
    """An append or fsync did not reach durable storage.

    This is the error answered on the submitting request: the edge op
    was NOT acknowledged and MUST NOT be assumed durable.
    """


class CheckpointError(RecoveryError):
    """A checkpoint could not be written or read back."""


class SnapshotFormatError(CheckpointError):
    """Version-skewed or corrupt snapshot: clean refusal, not a crash."""


class RecoveryDeadlineExceeded(RecoveryError):
    """Replay exceeded ``config.recovery_deadline_s``."""


class RetraceBudgetExceeded(RecoveryError):
    """A sealed program registry minted more executables than its
    per-subsystem budget allows (warm boot compiled something cold)."""
