"""Crash-safe durability + warm restart (docs/RECOVERY.md).

The crash-only tier: a checksummed write-ahead log under the ingest
lane (``wal``), atomic-rename snapshots of graph + coldcache state
(``checkpoint``), the unified AOT program registry (``registry``), and
the boot/health conductor that ties them together (``manager``).

Import discipline: this package is imported at module level by hot-path
modules (sampler/serving/feature take their executable caches from
``registry``), so only the error tree and the registry load eagerly —
``wal`` / ``checkpoint`` / ``manager`` / ``blockio`` resolve lazily on
first attribute access.
"""

from __future__ import annotations

from .errors import (CheckpointError, RecoveryDeadlineExceeded,
                     RecoveryError, RetraceBudgetExceeded,
                     SnapshotFormatError, WALError, WALWriteError)
from .registry import (ProgramCache, ProgramRegistry, get_program_registry,
                       program_cache)

__all__ = [
    "RecoveryError", "WALError", "WALWriteError", "CheckpointError",
    "SnapshotFormatError", "RecoveryDeadlineExceeded",
    "RetraceBudgetExceeded",
    "ProgramCache", "ProgramRegistry", "get_program_registry",
    "program_cache",
    "blockio", "wal", "checkpoint", "manager", "shardwal",
    "WriteAheadLog", "RecoveryManager", "health_status",
    "ShardGroupWAL",
]

_LAZY = {
    "blockio": ".blockio", "wal": ".wal", "checkpoint": ".checkpoint",
    "manager": ".manager", "shardwal": ".shardwal",
}
_LAZY_NAMES = {
    "WriteAheadLog": ("wal", "WriteAheadLog"),
    "RecoveryManager": ("manager", "RecoveryManager"),
    "health_status": ("manager", "health_status"),
    "ShardGroupWAL": ("shardwal", "ShardGroupWAL"),
}


def __getattr__(name):
    import importlib

    if name in _LAZY:
        mod = importlib.import_module(_LAZY[name], __name__)
        globals()[name] = mod
        return mod
    if name in _LAZY_NAMES:
        mod_name, attr = _LAZY_NAMES[name]
        mod = importlib.import_module("." + mod_name, __name__)
        val = getattr(mod, attr)
        globals()[name] = val
        return val
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
