"""SLO watchdog — rolling burn rates over the metrics registry.

Declared objectives live in :mod:`quiver_tpu.config`:

  * ``slo_p99_ms`` — p99 end-to-end serving latency ceiling,
  * ``slo_error_ratio`` — errored / total request ratio ceiling,
  * ``slo_coldcache_hit_floor`` — coldcache hit-rate floor (0 disables;
    a budgeted feature tier whose overlay stops hitting is about to
    drag gather latency through the host link).

Each evaluation snapshots the registry, takes the delta against the
previous snapshot (so every tick scores only the *window* since the
last one — a rolling rate, not a lifetime average), computes the three
indicators, and compares against the objectives.  ``burn`` is the
standard burn-rate reading: observed / allowed for ceilings, allowed /
observed for floors — burn > 1 means the objective is breaching and the
error budget is being spent faster than provisioned.  Breaches tick
``slo_breaches_total{objective=...}`` and flip the objective's
``breaching`` bit in :meth:`SLOWatchdog.status`, which is what
``GET /debug/slo`` serves.

The watchdog thread is explicitly started
(``InferenceServer.start_slo_watchdog()`` or ``watchdog.start()``) —
``status()`` also evaluates on demand when no thread is running, so the
debug endpoint is always live.  Evaluation is read-only over snapshots:
it never touches the serving hot path and costs one registry snapshot
per tick.

QT003: evaluation state is written from the watchdog thread and read
from HTTP handler threads; both hold ``_lock``.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

from .registry import parse_metric_key, snapshot_delta

__all__ = ["SLOWatchdog", "get_watchdog", "reset"]


def _sum_counters(snap: dict, name: str,
                  where: Optional[dict] = None) -> float:
    total = 0.0
    for key, v in snap.get("counters", {}).items():
        n, labels = parse_metric_key(key)
        if n != name:
            continue
        if where and any(labels.get(k) != v2 for k, v2 in where.items()):
            continue
        total += v
    return total


def _merged_histogram(snap: dict, name: str):
    """Merge every labelled instance of ``name`` in a snapshot into one
    Histogram (lanes share the fixed default bounds, so the merge is
    exact)."""
    from .registry import Histogram

    h = None
    for key, d in snap.get("histograms", {}).items():
        n, _ = parse_metric_key(key)
        if n != name:
            continue
        if h is None:
            h = Histogram(bounds=d["bounds"])
        h.merge_dict(d)
    return h


class SLOWatchdog:
    """Periodic evaluator of serving SLOs against registry deltas."""

    _guarded_by = {"_state": "_lock", "_prev": "_lock", "_ticks": "_lock",
                   "_listeners": "_lock"}

    def __init__(self, registry=None, interval_s: Optional[float] = None,
                 p99_ms: Optional[float] = None,
                 error_ratio: Optional[float] = None,
                 coldcache_hit_floor: Optional[float] = None):
        from ..config import get_config

        cfg = get_config()
        if registry is None:
            from . import get_registry

            registry = get_registry()
        self.registry = registry
        self.interval_s = float(interval_s if interval_s is not None
                                else cfg.slo_interval_s)
        self.p99_ms = float(p99_ms if p99_ms is not None else cfg.slo_p99_ms)
        self.error_ratio = float(error_ratio if error_ratio is not None
                                 else cfg.slo_error_ratio)
        self.coldcache_hit_floor = float(
            coldcache_hit_floor if coldcache_hit_floor is not None
            else cfg.slo_coldcache_hit_floor)
        self._lock = threading.Lock()
        self._prev: Optional[dict] = None
        self._state: Dict[str, dict] = {}
        self._ticks = 0
        self._listeners: List = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def add_listener(self, fn) -> None:
        """Register ``fn(results)`` to run after every evaluation — the
        reaction hook (the QoS degradation ladder attaches here).
        Listener exceptions are swallowed: a broken reaction must not
        kill SLO scoring."""
        with self._lock:
            self._listeners.append(fn)

    # -- evaluation -----------------------------------------------------
    def evaluate_once(self) -> List[dict]:
        """Score one window (now - previous tick).  Returns the updated
        per-objective state and ticks ``slo_breaches_total`` for every
        breaching objective."""
        snap = self.registry.snapshot()
        with self._lock:
            prev = self._prev
            self._prev = snap
        window = snapshot_delta(prev, snap) if prev is not None else snap

        results = self._score(window)

        from . import counter

        for r in results:
            if r["breaching"]:
                counter("slo_breaches_total", objective=r["objective"]).inc()
        with self._lock:
            self._ticks += 1
            for r in results:
                st = self._state.setdefault(
                    r["objective"], {"breaches_total": 0})
                if r["breaching"]:
                    st["breaches_total"] += 1
                st.update(r)
            listeners = list(self._listeners)
        for fn in listeners:
            try:
                fn(results)
            except Exception:
                # a reaction bug must not kill the scoring loop — it is
                # accounted, and the ladder keeps its own telemetry
                counter("slo_listener_errors_total").inc()
        return results

    def _score(self, window: dict) -> List[dict]:
        """The objective battery for one window.  Subclasses replace
        this to swap objectives while keeping the tick/breach/listener
        machinery (the fleet federation scores federated snapshots
        through the same accounting — see fleet/federation.py)."""
        results = [self._eval_p99(window), self._eval_errors(window)]
        if self.coldcache_hit_floor > 0:
            results.append(self._eval_coldcache(window))
        return results

    def _eval_p99(self, window: dict) -> dict:
        h = _merged_histogram(window, "serving_request_seconds")
        n = h.count if h is not None else 0
        p99_ms = h.percentile(99) * 1e3 if n else 0.0
        return {
            "objective": "p99_latency",
            "target": self.p99_ms, "unit": "ms",
            "value": round(p99_ms, 3), "samples": int(n),
            "burn": round(p99_ms / self.p99_ms, 4) if self.p99_ms else 0.0,
            "breaching": bool(n and p99_ms > self.p99_ms),
        }

    def _eval_errors(self, window: dict) -> dict:
        err = _sum_counters(window, "serving_requests_total",
                           {"status": "error"})
        total = _sum_counters(window, "serving_requests_total")
        ratio = err / total if total else 0.0
        return {
            "objective": "error_ratio",
            "target": self.error_ratio, "unit": "ratio",
            "value": round(ratio, 6), "samples": int(total),
            "burn": (round(ratio / self.error_ratio, 4)
                     if self.error_ratio else 0.0),
            "breaching": bool(total and ratio > self.error_ratio),
        }

    def _eval_coldcache(self, window: dict) -> dict:
        hit = _sum_counters(window, "feature_coldcache_rows_total",
                            {"result": "hit"})
        miss = _sum_counters(window, "feature_coldcache_rows_total",
                             {"result": "miss"})
        total = hit + miss
        rate = hit / total if total else 1.0
        floor = self.coldcache_hit_floor
        return {
            "objective": "coldcache_hit_rate",
            "target": floor, "unit": "ratio",
            "value": round(rate, 6), "samples": int(total),
            # floor objective: burn > 1 means the hit rate fell below it
            "burn": round(floor / rate, 4) if rate else float(total > 0),
            "breaching": bool(total and rate < floor),
        }

    # -- status / thread ------------------------------------------------
    def status(self) -> dict:
        """JSON view for ``GET /debug/slo``.  Evaluates on demand when
        the thread isn't running — or hasn't completed its first tick
        yet — so the endpoint never serves stale nothing."""
        with self._lock:
            ticked = self._ticks > 0
        if (self._thread is None or not self._thread.is_alive()
                or not ticked):
            self.evaluate_once()
        with self._lock:
            objectives = [dict(v) for _, v in sorted(self._state.items())]
            ticks = self._ticks
        return {
            "interval_s": self.interval_s,
            "running": bool(self._thread is not None
                            and self._thread.is_alive()),
            "ticks": ticks,
            "objectives": objectives,
        }

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.evaluate_once()
            except Exception:  # a scoring bug must never kill the thread
                pass

    def start(self) -> "SLOWatchdog":
        """Start (idempotently) the evaluation thread."""
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop,
                                        name="quiver-slo-watchdog",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        # local import: resilience.shutdown itself imports telemetry
        from ..resilience.shutdown import join_and_reap

        self._stop.set()
        t = self._thread
        if t is not None:
            join_and_reap([t], max(self.interval_s * 2, 1.0),
                          component="telemetry.slo")
            self._thread = None


_WATCHDOG: Optional[SLOWatchdog] = None
_watchdog_lock = threading.Lock()


def get_watchdog() -> SLOWatchdog:
    """Process-wide watchdog (lazy; objectives read from config at
    first touch)."""
    global _WATCHDOG
    wd = _WATCHDOG
    if wd is None:
        with _watchdog_lock:
            wd = _WATCHDOG
            if wd is None:
                wd = _WATCHDOG = SLOWatchdog()
    return wd


def reset() -> None:
    """Stop and drop the singleton (tests)."""
    global _WATCHDOG
    with _watchdog_lock:
        if _WATCHDOG is not None:
            _WATCHDOG.stop()
        _WATCHDOG = None
