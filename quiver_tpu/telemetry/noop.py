"""Shared do-nothing singletons for ``QUIVER_TELEMETRY=off``.

Every facade entry point answers with one of these pre-built objects
when telemetry is disabled, so the instrumented hot paths pay only a
module-global bool check and a method call — no locks, no
``perf_counter``, and no net allocations (the zero-allocation property
is pinned by ``tests/test_telemetry.py``).

The noop span/timer is **stateless and reentrant**: ``__enter__``
returns the shared instance itself, so the same object can be live in
any number of nested/concurrent ``with`` blocks.
"""

from __future__ import annotations

__all__ = ["METRIC", "SPAN", "REGISTRY", "TRACER"]

_EMPTY_SNAPSHOT: dict = {"counters": {}, "gauges": {}, "histograms": {}}


class _NoopSpan:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class _NoopMetric:
    """Quacks like Counter, Gauge, and Histogram at once."""

    __slots__ = ()
    key = ""
    value = 0.0
    sum = 0.0
    count = 0
    mean = 0.0

    def inc(self, n: float = 1.0) -> None:
        pass

    def dec(self, n: float = 1.0) -> None:
        pass

    def set(self, v: float) -> None:
        pass

    def observe(self, v: float) -> None:
        pass

    def time(self):
        return SPAN

    def percentile(self, q: float) -> float:
        return 0.0


class _NoopRegistry:
    __slots__ = ()

    def counter(self, name, help=None, **labels):
        return METRIC

    def gauge(self, name, help=None, **labels):
        return METRIC

    def histogram(self, name, bounds=None, help=None, **labels):
        return METRIC

    def snapshot(self) -> dict:
        return {"counters": {}, "gauges": {}, "histograms": {}}

    def merge(self, snap) -> None:
        pass

    def reset(self) -> None:
        pass

    def __iter__(self):
        return iter(())


class _NoopTracer:
    __slots__ = ()
    tracing = False

    def span(self, name, block=None):
        return SPAN

    def set_tracing(self, on) -> None:
        pass

    def summary(self) -> dict:
        return {}

    def events(self):
        return []

    def reset(self) -> None:
        pass

    def chrome_trace(self) -> dict:
        return {"traceEvents": [], "displayTimeUnit": "ms"}


METRIC = _NoopMetric()
SPAN = _NoopSpan()
REGISTRY = _NoopRegistry()
TRACER = _NoopTracer()
