"""Exposition formats + optional HTTP endpoint.

Kept OUT of the hot path on purpose: nothing under ``quiver_tpu``
imports this module at import time (a guard test pins that), so the
stdlib ``http.server`` dependency only loads when someone actually
calls ``InferenceServer.expose_metrics()`` / ``start_http_server()``.

Three views:

  * ``to_prometheus_text(snapshot)`` — Prometheus exposition format
    (counters, gauges, and cumulative ``_bucket{le=...}`` histograms).
  * ``to_json(snapshot)`` — the snapshot itself, serialized.
  * ``start_http_server()`` — a daemon-threaded stdlib server exposing
    ``/metrics`` (text), ``/metrics.json``, and ``/trace.json`` (Chrome
    trace events, Perfetto-loadable).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from .registry import parse_metric_key

__all__ = ["to_prometheus_text", "to_json", "MetricsServer",
           "start_http_server"]


def _fmt_labels(labels: dict, extra: Optional[dict] = None) -> str:
    merged = dict(labels)
    if extra:
        merged.update(extra)
    if not merged:
        return ""
    inner = ",".join(f'{k}="{merged[k]}"' for k in sorted(merged))
    return "{" + inner + "}"


def _fmt_num(v: float) -> str:
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def to_prometheus_text(snapshot: dict) -> str:
    """Prometheus text exposition of a registry snapshot."""
    lines = []
    typed = set()

    def _type(name: str, kind: str) -> None:
        if name not in typed:
            typed.add(name)
            lines.append(f"# TYPE {name} {kind}")

    for key, v in sorted(snapshot.get("counters", {}).items()):
        name, labels = parse_metric_key(key)
        _type(name, "counter")
        lines.append(f"{name}{_fmt_labels(labels)} {_fmt_num(v)}")
    for key, v in sorted(snapshot.get("gauges", {}).items()):
        name, labels = parse_metric_key(key)
        _type(name, "gauge")
        lines.append(f"{name}{_fmt_labels(labels)} {_fmt_num(v)}")
    for key, d in sorted(snapshot.get("histograms", {}).items()):
        name, labels = parse_metric_key(key)
        _type(name, "histogram")
        cum = 0
        for bound, c in zip(d["bounds"], d["counts"]):
            cum += c
            lines.append(f"{name}_bucket"
                         f"{_fmt_labels(labels, {'le': _fmt_num(bound)})} "
                         f"{cum}")
        cum += d["counts"][-1]
        lines.append(
            f"{name}_bucket{_fmt_labels(labels, {'le': '+Inf'})} {cum}")
        lines.append(f"{name}_sum{_fmt_labels(labels)} {_fmt_num(d['sum'])}")
        lines.append(f"{name}_count{_fmt_labels(labels)} {cum}")
    return "\n".join(lines) + "\n"


def to_json(snapshot: dict, indent: Optional[int] = None) -> str:
    return json.dumps(snapshot, indent=indent, sort_keys=True)


class MetricsServer:
    """Daemon-threaded stdlib HTTP server over a registry + tracer."""

    def __init__(self, registry=None, tracer=None, host: str = "127.0.0.1",
                 port: int = 0):
        if registry is None or tracer is None:
            from . import get_registry, get_tracer
            registry = registry or get_registry()
            tracer = tracer or get_tracer()
        self.registry = registry
        self.tracer = tracer
        outer = self

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (stdlib API name)
                try:
                    if self.path.startswith("/metrics.json"):
                        body = to_json(outer.registry.snapshot(), indent=2)
                        ctype = "application/json"
                    elif self.path.startswith("/metrics"):
                        body = to_prometheus_text(outer.registry.snapshot())
                        ctype = "text/plain; version=0.0.4"
                    elif self.path.startswith("/trace.json"):
                        body = json.dumps(outer.tracer.chrome_trace())
                        ctype = "application/json"
                    else:
                        self.send_error(404)
                        return
                except Exception as e:  # pragma: no cover - defensive
                    self.send_error(500, str(e))
                    return
                data = body.encode()
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def log_message(self, *a):  # silence per-request stderr spam
                pass

        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="quiver-metrics-http",
                                        daemon=True)
        self._thread.start()

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)


def start_http_server(port: int = 0, host: str = "127.0.0.1",
                      registry=None, tracer=None) -> MetricsServer:
    """Start the metrics endpoint; ``port=0`` picks a free port (read it
    back from ``server.port``)."""
    return MetricsServer(registry=registry, tracer=tracer, host=host,
                         port=port)
