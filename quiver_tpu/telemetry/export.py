"""Exposition formats + optional HTTP endpoint.

Kept OUT of the hot path on purpose: nothing under ``quiver_tpu``
imports this module at import time (a guard test pins that), so the
stdlib ``http.server`` dependency only loads when someone actually
calls ``InferenceServer.expose_metrics()`` / ``start_http_server()``.

Three views:

  * ``to_prometheus_text(snapshot)`` — Prometheus exposition format
    (counters, gauges, and cumulative ``_bucket{le=...}`` histograms).
  * ``to_json(snapshot)`` — the snapshot itself, serialized.
  * ``start_http_server()`` — a daemon-threaded stdlib server exposing
    ``/metrics`` (text), ``/metrics.json``, ``/trace.json`` (Chrome
    trace events, Perfetto-loadable), plus the flight-recorder debug
    surface: ``/debug/requests`` (retained-request summaries),
    ``/debug/requests/<trace_id>`` (one full event log), ``/debug/slo``
    (watchdog objective status), ``/debug/breakers`` (per-lane
    circuit-breaker states), ``/debug/qos`` (tenant classes, token
    levels, degradation-ladder level + history), ``/debug/timeline``
    (the unified cross-subsystem Chrome trace — Perfetto-loadable),
    ``/debug/programs`` (top-K per-program time attribution, see
    ``telemetry.profile``), ``/debug/mesh`` (live mesh feature/sampler
    shard stats, see docs/SHARDING.md), and ``/debug/fleet`` (router +
    membership view of the replicated serving fleet, see
    docs/FLEET.md).  With a
    live fleet federation (docs/OBSERVABILITY.md), three more:
    ``/metrics/fleet`` (federated exposition), ``/debug/fleet/summary``
    (scrape health + fleet SLOs + clock offsets), and
    ``/debug/fleet/trace/<id>`` (cross-process request
    reconstruction).
    ``/healthz`` reports the recovery
    readiness ladder (200 only when ``serving``; 503 while
    booting/replaying/warming — see docs/RECOVERY.md); with
    ``health_fn=`` the document is instance-scoped (one fleet
    replica's ladder) instead of process-global.  ``HEAD``
    answers every route with the headers its ``GET`` would carry.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from .registry import parse_metric_key

__all__ = ["to_prometheus_text", "to_json", "MetricsServer",
           "start_http_server"]


def _escape_label_value(v) -> str:
    # Prometheus text format: label VALUES escape backslash, double
    # quote, and line feed (in that order — escaping the escapes first
    # keeps the round trip unambiguous).  Unescaped, a hostile tenant
    # name like `gold"} 1\n` splits the sample line and corrupts the
    # whole exposition.
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt_labels(labels: dict, extra: Optional[dict] = None) -> str:
    merged = dict(labels)
    if extra:
        merged.update(extra)
    if not merged:
        return ""
    inner = ",".join(f'{k}="{_escape_label_value(merged[k])}"'
                     for k in sorted(merged))
    return "{" + inner + "}"


def _fmt_num(v: float) -> str:
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _escape_help(text: str) -> str:
    # Prometheus text format: backslash and newline are the only escapes
    # in HELP text.
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def to_prometheus_text(snapshot: dict) -> str:
    """Prometheus text exposition of a registry snapshot."""
    lines = []
    typed = set()
    help_texts = snapshot.get("help", {})

    def _type(name: str, kind: str) -> None:
        if name not in typed:
            typed.add(name)
            text = help_texts.get(name)
            if text:
                lines.append(f"# HELP {name} {_escape_help(text)}")
            lines.append(f"# TYPE {name} {kind}")

    for key, v in sorted(snapshot.get("counters", {}).items()):
        name, labels = parse_metric_key(key)
        _type(name, "counter")
        lines.append(f"{name}{_fmt_labels(labels)} {_fmt_num(v)}")
    for key, v in sorted(snapshot.get("gauges", {}).items()):
        name, labels = parse_metric_key(key)
        _type(name, "gauge")
        lines.append(f"{name}{_fmt_labels(labels)} {_fmt_num(v)}")
    for key, d in sorted(snapshot.get("histograms", {}).items()):
        name, labels = parse_metric_key(key)
        _type(name, "histogram")
        cum = 0
        for bound, c in zip(d["bounds"], d["counts"]):
            cum += c
            lines.append(f"{name}_bucket"
                         f"{_fmt_labels(labels, {'le': _fmt_num(bound)})} "
                         f"{cum}")
        cum += d["counts"][-1]
        lines.append(
            f"{name}_bucket{_fmt_labels(labels, {'le': '+Inf'})} {cum}")
        lines.append(f"{name}_sum{_fmt_labels(labels)} {_fmt_num(d['sum'])}")
        lines.append(f"{name}_count{_fmt_labels(labels)} {cum}")
    return "\n".join(lines) + "\n"


def to_json(snapshot: dict, indent: Optional[int] = None) -> str:
    return json.dumps(snapshot, indent=indent, sort_keys=True)


class _ReuseAddrHTTPServer(ThreadingHTTPServer):
    # explicit SO_REUSEADDR: restarting an exporter (or a recovered
    # process re-binding its old port) must not fail on the previous
    # instance's sockets lingering in TIME_WAIT.  stdlib HTTPServer
    # happens to set this today; pin it so a restart-on-same-port is a
    # contract (tests/test_recovery.py), not an implementation detail.
    allow_reuse_address = True


class MetricsServer:
    """Daemon-threaded stdlib HTTP server over a registry + tracer."""

    def __init__(self, registry=None, tracer=None, host: str = "127.0.0.1",
                 port: int = 0, health_fn=None):
        # ``port=0`` binds an ephemeral port (read back via ``.port``)
        # so N replicas on one host never collide; ``health_fn`` scopes
        # /healthz to ONE serving instance (a fleet replica's ladder)
        # instead of the process-global recovery view.
        if registry is None or tracer is None:
            from . import get_registry, get_tracer
            registry = registry or get_registry()
            tracer = tracer or get_tracer()
        self.registry = registry
        self.tracer = tracer
        self.health_fn = health_fn
        outer = self

        class _Handler(BaseHTTPRequestHandler):
            def _payload(self):
                """Route ``self.path`` -> ``(body, ctype)`` or
                ``(body, ctype, status)``, or ``None`` for a 404.
                Shared by GET and HEAD so HEAD answers with the exact
                headers a GET would carry."""
                path = self.path
                if path.startswith("/healthz"):
                    if outer.health_fn is not None:
                        health = outer.health_fn()
                    else:
                        from ..recovery.manager import health_status

                        health = health_status()
                    # load balancers read the status code; humans read
                    # the body.  503 while booting/replaying/warming.
                    status = 200 if health.get("ready") else 503
                    return (json.dumps(health, indent=2),
                            "application/json", status)
                if path.startswith("/metrics/fleet"):
                    # matched BEFORE the /metrics prefix: the federated
                    # exposition (aggregates + per-replica series), 404
                    # when no federation is live in this process
                    from ..fleet.federation import get_federation

                    fed = get_federation()
                    if fed is None:
                        return None
                    return (fed.prometheus_text(),
                            "text/plain; version=0.0.4")
                if path.startswith("/metrics.json"):
                    return (to_json(outer.registry.snapshot(), indent=2),
                            "application/json")
                if path.startswith("/metrics"):
                    return (to_prometheus_text(outer.registry.snapshot()),
                            "text/plain; version=0.0.4")
                if path.startswith("/trace.json"):
                    return (json.dumps(outer.tracer.chrome_trace()),
                            "application/json")
                if path.startswith("/debug/requests"):
                    from .flightrec import get_recorder

                    rec = get_recorder()
                    from urllib.parse import unquote

                    parts = path.rstrip("/").split("/")
                    if len(parts) >= 4 and parts[3]:
                        # fleet trace_ids are origin-qualified and
                        # arrive percent-encoded from the federation
                        record = rec.get(unquote(parts[3]))
                        if record is None:
                            return None
                        return json.dumps(record, indent=2), "application/json"
                    body = json.dumps({
                        "capacity": rec.capacity,
                        "slow_threshold_s": rec.slow_threshold_s,
                        "count": len(rec.records()),
                        "records": rec.summaries(),
                    }, indent=2)
                    return body, "application/json"
                if path.startswith("/debug/slo"):
                    from .slo import get_watchdog

                    return (json.dumps(get_watchdog().status(), indent=2),
                            "application/json")
                if path.startswith("/debug/breakers"):
                    from ..resilience.breaker import breakers_status

                    return (json.dumps(breakers_status(), indent=2),
                            "application/json")
                if path.startswith("/debug/qos"):
                    from ..resilience.qos import qos_status

                    return (json.dumps(qos_status(), indent=2),
                            "application/json")
                if path.startswith("/debug/timeline"):
                    from . import timeline

                    # the merged Chrome trace itself: save the body,
                    # load it in Perfetto (docs/OBSERVABILITY.md)
                    return (json.dumps(timeline.chrome_trace()),
                            "application/json")
                if path.startswith("/debug/fleet/summary"):
                    from ..fleet.federation import federation_status

                    return (json.dumps(federation_status(), indent=2),
                            "application/json")
                if path.startswith("/debug/fleet/trace/"):
                    from urllib.parse import unquote

                    from ..fleet.federation import get_federation

                    fed = get_federation()
                    trace_id = unquote(
                        path[len("/debug/fleet/trace/"):].rstrip("/"))
                    if fed is None or not trace_id:
                        return None
                    doc = fed.reconstruct(trace_id)
                    if not doc.get("found"):
                        return (json.dumps(doc, indent=2),
                                "application/json", 404)
                    return json.dumps(doc, indent=2), "application/json"
                if path.startswith("/debug/fleet"):
                    from ..fleet.router import fleet_status

                    return (json.dumps(fleet_status(), indent=2),
                            "application/json")
                if path.startswith("/debug/mesh"):
                    from ..mesh import mesh_status

                    return (json.dumps(mesh_status(), indent=2),
                            "application/json")
                if path.startswith("/debug/programs"):
                    from . import profile

                    return (json.dumps(profile.debug_payload(), indent=2),
                            "application/json")
                return None

            def _respond(self, send_body: bool) -> None:
                try:
                    payload = self._payload()
                except Exception as e:  # pragma: no cover - defensive
                    self.send_error(500, str(e))
                    return
                if payload is None:
                    self.send_error(404)
                    return
                if len(payload) == 3:
                    body, ctype, status = payload
                else:
                    body, ctype = payload
                    status = 200
                data = body.encode()
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                if send_body:
                    self.wfile.write(data)

            def do_GET(self):  # noqa: N802 (stdlib API name)
                self._respond(send_body=True)

            def do_HEAD(self):  # noqa: N802 (stdlib API name)
                self._respond(send_body=False)

            def log_message(self, *a):  # silence per-request stderr spam
                pass

        self._httpd = _ReuseAddrHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="quiver-metrics-http",
                                        daemon=True)
        self._thread.start()

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def close(self) -> None:
        # local import: resilience.shutdown itself imports telemetry
        from ..resilience.shutdown import join_and_reap

        self._httpd.shutdown()
        self._httpd.server_close()
        join_and_reap([self._thread], 5.0, component="telemetry.export")


def start_http_server(port: int = 0, host: str = "127.0.0.1",
                      registry=None, tracer=None) -> MetricsServer:
    """Start the metrics endpoint; ``port=0`` picks a free port (read it
    back from ``server.port``)."""
    return MetricsServer(registry=registry, tracer=tracer, host=host,
                         port=port)
