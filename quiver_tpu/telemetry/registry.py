"""Process-wide metrics registry: counters, gauges, fixed-bucket histograms.

Design constraints, in order:

  * **hot-path cheap** — a counter ``inc`` is one lock acquire and one
    float add; a histogram ``observe`` adds one ``bisect``.  All metric
    handles are cached in the registry dict, so
    ``telemetry.counter("x").inc()`` in a per-batch loop costs a dict
    lookup + the increment (sub-µs against ms-scale batches).
  * **mergeable** — ``snapshot()`` returns a plain-JSON dict and
    ``merge()`` folds one into a registry, so dist workers / threads /
    subprocesses can aggregate by shipping snapshots (histograms merge
    exactly because buckets are fixed at creation; merge is associative
    and commutative).
  * **fixed buckets** — quantiles are read from bucket counts by linear
    interpolation, never from stored samples, so memory is O(buckets)
    no matter how many observations stream through (the serving p50/p99
    lists this replaces grew without bound).

Key encoding: a metric instance is addressed by ``name`` plus sorted
``labels``, flattened to the canonical string ``name{k=v,k2=v2}`` used
both as the registry key and in snapshots.  Label values must not
contain ``,``, ``=``, or ``}`` (enforced at creation).
"""

from __future__ import annotations

import bisect
import math
import threading
import time
from typing import Dict, Iterator, Optional, Sequence, Tuple

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "DEFAULT_TIME_BUCKETS", "metric_key", "parse_metric_key",
    "snapshot_delta", "summarize_snapshot",
]

# ~exponential grid, 10 buckets per decade (step ~1.26x => worst-case
# quantile interpolation error ~13% of the value) spanning 10µs .. 50s —
# wide enough for a noop'd counter tick and a cold XLA compile alike.
DEFAULT_TIME_BUCKETS: Tuple[float, ...] = tuple(
    round(1e-5 * 10 ** (i / 10.0), 12) for i in range(67)
)

_FORBIDDEN = set(",={}\"\n")


def metric_key(name: str, labels: Optional[dict] = None) -> str:
    """Canonical flat key: ``name`` or ``name{k=v,...}`` (sorted by k)."""
    if not labels:
        return name
    for k in labels:
        v = str(labels[k])
        if _FORBIDDEN & set(v) or _FORBIDDEN & set(str(k)):
            raise ValueError(
                f"label {k}={v!r} contains a reserved character ,=}}\"")
    inner = ",".join(f"{k}={labels[k]}" for k in sorted(labels))
    return f"{name}{{{inner}}}"


def parse_metric_key(key: str) -> Tuple[str, Dict[str, str]]:
    """Inverse of :func:`metric_key` (used by the exporters)."""
    if not key.endswith("}") or "{" not in key:
        return key, {}
    name, _, inner = key[:-1].partition("{")
    labels: Dict[str, str] = {}
    for part in inner.split(","):
        if part:
            k, _, v = part.partition("=")
            labels[k] = v
    return name, labels


class Counter:
    """Monotonically increasing float. ``inc(n)`` / ``.value``."""

    __slots__ = ("key", "_lock", "_value")

    def __init__(self, key: str = "", lock: Optional[threading.Lock] = None):
        self.key = key
        self._lock = lock or threading.Lock()
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError(f"counter {self.key or '<anon>'}: inc({n}) < 0")
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        return self._value


class Gauge:
    """Last-writer-wins float. ``set`` / ``inc`` / ``dec`` / ``.value``."""

    __slots__ = ("key", "_lock", "_value")

    def __init__(self, key: str = "", lock: Optional[threading.Lock] = None):
        self.key = key
        self._lock = lock or threading.Lock()
        self._value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    def dec(self, n: float = 1.0) -> None:
        with self._lock:
            self._value -= n

    @property
    def value(self) -> float:
        return self._value


class Histogram:
    """Fixed-bucket histogram; bucket ``i`` counts values ``<= bounds[i]``
    (strictly above ``bounds[i-1]``), with one implicit +inf overflow
    bucket.  Two histograms with identical bounds merge exactly by
    adding counts, which makes cross-worker aggregation associative."""

    __slots__ = ("key", "bounds", "counts", "sum", "min", "max", "_lock")

    def __init__(self, key: str = "",
                 bounds: Optional[Sequence[float]] = None,
                 lock: Optional[threading.Lock] = None):
        b = tuple(float(x) for x in (bounds or DEFAULT_TIME_BUCKETS))
        if list(b) != sorted(set(b)):
            raise ValueError(f"histogram {key or '<anon>'}: bounds must be "
                             "strictly increasing")
        self.key = key
        self.bounds = b
        self.counts = [0] * (len(b) + 1)
        self.sum = 0.0
        self.min = float("inf")
        self.max = float("-inf")
        self._lock = lock or threading.Lock()

    def observe(self, v: float) -> None:
        v = float(v)
        i = bisect.bisect_left(self.bounds, v)
        with self._lock:
            self.counts[i] += 1
            self.sum += v
            if v < self.min:
                self.min = v
            if v > self.max:
                self.max = v

    def time(self) -> "_HistTimer":
        """``with h.time(): ...`` observes the block's wall seconds."""
        return _HistTimer(self)

    @property
    def count(self) -> int:
        return sum(self.counts)

    @property
    def mean(self) -> float:
        n = self.count
        return self.sum / n if n else 0.0

    def percentile(self, q: float) -> float:
        """Approximate q-th percentile (q in [0, 100]) by linear
        interpolation inside the covering bucket, clamped to the
        observed min/max so small samples don't report a bucket edge
        far from any real observation."""
        with self._lock:
            counts = list(self.counts)
            lo_obs, hi_obs = self.min, self.max
        total = sum(counts)
        if not total:
            return 0.0
        # A histogram populated purely via merge_dict may lack observed
        # min/max (older snapshots, or deltas that dropped them): the
        # sentinels are +/-inf and would leak straight through the clamp
        # below.  Fall back to the finite bucket grid — values at or
        # beyond the last bound report the last finite bound, never inf.
        if not math.isfinite(hi_obs):
            hi_obs = self.bounds[-1]
        if not math.isfinite(lo_obs):
            lo_obs = self.bounds[0]
        target = max(q, 0.0) / 100.0 * total
        cum = 0.0
        for i, c in enumerate(counts):
            if cum + c >= target and c:
                lo = self.bounds[i - 1] if i > 0 else min(lo_obs, self.bounds[0])
                hi = self.bounds[i] if i < len(self.bounds) else hi_obs
                frac = (target - cum) / c
                v = lo + (hi - lo) * max(min(frac, 1.0), 0.0)
                return max(min(v, hi_obs), lo_obs)
            cum += c
        return hi_obs

    def to_dict(self) -> dict:
        with self._lock:
            return {
                "bounds": list(self.bounds),
                "counts": list(self.counts),
                "sum": self.sum,
                "min": None if self.min == float("inf") else self.min,
                "max": None if self.max == float("-inf") else self.max,
            }

    def merge_dict(self, d: dict) -> None:
        if tuple(float(x) for x in d["bounds"]) != self.bounds:
            raise ValueError(
                f"histogram {self.key or '<anon>'}: cannot merge across "
                "different bucket bounds")
        with self._lock:
            for i, c in enumerate(d["counts"]):
                self.counts[i] += c
            self.sum += d["sum"]
            if d.get("min") is not None and d["min"] < self.min:
                self.min = d["min"]
            if d.get("max") is not None and d["max"] > self.max:
                self.max = d["max"]


class _HistTimer:
    """Re-usable-per-call timing context for :meth:`Histogram.time`."""

    __slots__ = ("_h", "_t0")

    def __init__(self, h: Histogram):
        self._h = h

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._h.observe(time.perf_counter() - self._t0)
        return False


class MetricsRegistry:
    """Thread-safe name+labels -> metric store with snapshot/merge.

    QT003 lock discipline: the registry map is written from any thread
    that first touches a metric name; all mutations hold ``_lock`` (the
    unlocked ``.get()`` in ``_get`` is the double-checked fast path).
    """

    _guarded_by = {"_metrics": "_lock", "_help": "_lock"}

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, object] = {}
        # metric *family* name -> help text (one line per family in the
        # Prometheus exposition, regardless of label instances).
        self._help: Dict[str, str] = {}

    # -- handle accessors -------------------------------------------------
    def _get(self, cls, name: str, labels: dict, help: Optional[str] = None,
             **kw):
        key = metric_key(name, labels)
        m = self._metrics.get(key)
        if m is None or (help is not None and name not in self._help):
            with self._lock:
                if help is not None:
                    self._help.setdefault(name, str(help))
                m = self._metrics.get(key)
                if m is None:
                    m = cls(key, **kw)
                    self._metrics[key] = m
        if not isinstance(m, cls):
            raise TypeError(f"metric {key!r} already registered as "
                            f"{type(m).__name__}, requested "
                            f"{cls.__name__}")
        return m

    def counter(self, name: str, help: Optional[str] = None,
                **labels) -> Counter:
        return self._get(Counter, name, labels, help=help)

    def gauge(self, name: str, help: Optional[str] = None,
              **labels) -> Gauge:
        return self._get(Gauge, name, labels, help=help)

    def histogram(self, name: str,
                  bounds: Optional[Sequence[float]] = None,
                  help: Optional[str] = None,
                  **labels) -> Histogram:
        return self._get(Histogram, name, labels, help=help, bounds=bounds)

    def __iter__(self) -> Iterator[Tuple[str, object]]:
        with self._lock:
            items = list(self._metrics.items())
        return iter(sorted(items))

    # -- snapshot / merge -------------------------------------------------
    def snapshot(self) -> dict:
        """Plain-JSON view: ``{"counters": {key: v}, "gauges": {key: v},
        "histograms": {key: {bounds, counts, sum, min, max}}}``."""
        out = {"counters": {}, "gauges": {}, "histograms": {}}
        for key, m in self:
            if isinstance(m, Counter):
                out["counters"][key] = m.value
            elif isinstance(m, Gauge):
                out["gauges"][key] = m.value
            elif isinstance(m, Histogram):
                out["histograms"][key] = m.to_dict()
        with self._lock:
            if self._help:
                # Only when non-empty: snapshots without help text keep
                # their historical exact shape (and snapshot_delta
                # equality against {} still holds).
                out["help"] = dict(self._help)
        return out

    def merge(self, snap: dict) -> None:
        """Fold a snapshot in: counters/histograms add, gauges overwrite."""
        for key, v in snap.get("counters", {}).items():
            name, labels = parse_metric_key(key)
            self.counter(name, **labels).inc(v)
        for key, v in snap.get("gauges", {}).items():
            name, labels = parse_metric_key(key)
            self.gauge(name, **labels).set(v)
        for key, d in snap.get("histograms", {}).items():
            name, labels = parse_metric_key(key)
            self.histogram(name, bounds=d["bounds"], **labels).merge_dict(d)
        h = snap.get("help")
        if h:
            with self._lock:
                for name, text in h.items():
                    self._help.setdefault(name, text)

    def reset(self) -> None:
        with self._lock:
            self._metrics.clear()
            self._help.clear()


def snapshot_delta(before: dict, after: dict) -> dict:
    """``after - before`` for the additive parts (counters, histogram
    counts/sum); gauges pass through from ``after``.  Entries whose delta
    is zero are dropped, so a section that touched nothing contributes
    nothing.  Used by bench.py to attribute registry activity to one
    benchmark section."""
    out = {"counters": {}, "gauges": {}, "histograms": {}}
    cb = before.get("counters", {})
    for key, v in after.get("counters", {}).items():
        d = v - cb.get(key, 0.0)
        if d:
            out["counters"][key] = d
    out["gauges"] = dict(after.get("gauges", {}))
    hb = before.get("histograms", {})
    for key, d in after.get("histograms", {}).items():
        prev = hb.get(key)
        if prev is None or tuple(prev["bounds"]) != tuple(d["bounds"]):
            delta = dict(d)
        else:
            counts = [a - b for a, b in zip(d["counts"], prev["counts"])]
            if not any(counts):
                continue
            delta = {"bounds": d["bounds"], "counts": counts,
                     "sum": d["sum"] - prev["sum"],
                     "min": d.get("min"), "max": d.get("max")}
        if any(delta["counts"]):
            out["histograms"][key] = delta
    if not out["gauges"]:
        del out["gauges"]
    if not out["counters"]:
        del out["counters"]
    if not out["histograms"]:
        del out["histograms"]
    return out


def _quantile_from_dict(d: dict, q: float) -> float:
    h = Histogram(bounds=d["bounds"])
    h.merge_dict(d)
    return h.percentile(q)


def summarize_snapshot(snap: dict) -> dict:
    """Compact a snapshot for JSON artifacts: histograms collapse to
    ``{count, mean, p50, p99, max}`` (seconds for ``*_seconds`` metrics)
    instead of 60+ bucket counts.  Lossy — for merging keep the full
    snapshot."""
    out: dict = {}
    if snap.get("counters"):
        out["counters"] = {k: round(v, 6)
                           for k, v in snap["counters"].items()}
    if snap.get("gauges"):
        out["gauges"] = {k: round(v, 6) for k, v in snap["gauges"].items()}
    if snap.get("histograms"):
        hs = {}
        for key, d in snap["histograms"].items():
            n = sum(d["counts"])
            hs[key] = {
                "count": n,
                "mean": round(d["sum"] / n, 9) if n else 0.0,
                "p50": round(_quantile_from_dict(d, 50), 9),
                "p99": round(_quantile_from_dict(d, 99), 9),
                "max": d.get("max"),
            }
        out["histograms"] = hs
    return out
