"""Flight recorder — per-request trace context with tail-based retention.

PR 1's registry answers "how fast is each stage on average?"; this
module answers "why was *this* request slow?".  Every serving request
gets a :class:`TraceContext` at enqueue (``serving.py`` attaches it to
the ``ServingRequest``), stages append monotonic events as the request
moves queue_wait → coalesce → sample → gather → infer → finish, and at
finish the :class:`FlightRecorder` keeps the full event log only for
requests worth debugging — slow (> ``config.flightrec_slow_ms``),
errored, or explicitly flagged — and discards the rest.  Aggregates
(SALIENT, arxiv 2110.08450) show *that* the pipeline is imbalanced;
the retained tail shows *which* stage ate a given request's budget.

Cross-thread attribution uses a :mod:`contextvars` context-var holding
the tuple of active trace contexts (a coalesced device batch activates
every member's trace at once — they all wait for the batch, so they all
own its events).  Thread pools do NOT inherit context automatically, so
the two background boundaries capture it explicitly:

  * ``Feature.prefetch`` snapshots :func:`active` at submit time and
    re-activates it inside the worker, so the ``feature-prefetch``
    thread's coldcache / H2D events land on the originating request;
  * ``parallel.Prefetcher`` (the ``SeedLoader`` worker) runs
    ``make_batch`` under a ``contextvars.copy_context()`` taken at
    iteration start, so loader-driven prefetch work attributes the
    same way.

Gating: when ``QUIVER_TELEMETRY=off`` :func:`new_trace` returns None,
no context is ever activated, and :func:`event` / :func:`tracing` reduce
to one context-var read — no locks, no clocks, no allocations.  Hot
paths guard event construction with ``if flightrec.tracing():`` so even
the attrs dict is never built off a live trace.

QT003 lock discipline: the per-trace event list and the recorder's ring
are mutated from every pipeline thread; all writes hold the declared
locks (see ``_guarded_by``).
"""

from __future__ import annotations

import contextvars
import math
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

from . import timeline as _timeline

__all__ = [
    "TraceContext", "FlightRecorder",
    "new_trace", "current", "active", "activate", "event", "flag",
    "tracing", "get_recorder", "reset",
    "set_version_provider", "graph_version",
]

# -- graph-version stamping (quiver_tpu.stream) -------------------------
# The streaming tier registers its version counter here; every trace
# created afterwards carries the graph version that was current at its
# admission, so a retained flight record pins exactly which topology a
# slow/errored request sampled against.  None until a StreamingGraph
# registers (frozen-CSR deployments pay one global read per trace).
_VERSION_PROVIDER = None


def set_version_provider(fn) -> None:
    """Register a zero-arg callable returning the current graph version
    (``None`` unregisters).  Called by ``stream.StreamingGraph``."""
    global _VERSION_PROVIDER
    # quiverlint: ignore[QT008] -- single atomic reference rebind at
    # graph construction/teardown; readers snapshot it into a local and
    # tolerate one stale observation (graph_version falls back to None)
    _VERSION_PROVIDER = fn


def graph_version() -> Optional[int]:
    """Current graph version, or None when no streaming graph is live."""
    fn = _VERSION_PROVIDER
    if fn is None:
        return None
    return int(fn())

# events per trace are capped so one pathological request (a chunked
# giant batch, a retry loop) cannot grow without bound while in flight
_MAX_EVENTS_PER_TRACE = 2048

_ACTIVE: "contextvars.ContextVar[Optional[Tuple[TraceContext, ...]]]" = \
    contextvars.ContextVar("quiver_flightrec_active", default=None)

_id_lock = threading.Lock()
_id_counter = 0


def _next_trace_id() -> str:
    """Process-unique, monotonic, and grep-friendly: ``<pid>-<seq>``."""
    global _id_counter
    with _id_lock:
        _id_counter += 1
        n = _id_counter
    return f"{os.getpid():x}-{n:08x}"


class TraceContext:
    """One request's monotonic event log.

    Events are ``(t, name, thread, attrs)`` tuples with ``t`` from
    ``perf_counter`` — appended from whichever pipeline thread is doing
    the request's work at that moment, so ``thread`` is the
    attribution: a gather staged by the prefetch worker shows up as
    ``feature-prefetch_0``, not as the server loop that claimed it.
    """

    _guarded_by = {"events": "_lock", "dropped": "_lock",
                   "flagged": "_lock"}

    __slots__ = ("trace_id", "t_start", "wall_start", "events", "dropped",
                 "flagged", "graph_version", "tenant", "_lock")

    def __init__(self, trace_id: Optional[str] = None):
        self.trace_id = trace_id or _next_trace_id()
        self.t_start = time.perf_counter()
        self.wall_start = time.time()
        self.events: List[Tuple[float, str, str, Optional[dict]]] = []
        self.dropped = 0
        self.flagged = False
        # tenant label, stamped at admission by serving (None for
        # untenanted traffic); set-once before the request enters the
        # pipeline, so unguarded reads are safe like graph_version
        self.tenant: Optional[str] = None
        # topology version at admission (None without a streaming graph);
        # immutable after construction, so unguarded reads are safe
        self.graph_version = graph_version()
        self._lock = threading.Lock()

    def add(self, name: str, attrs: Optional[dict] = None) -> None:
        t = time.perf_counter()
        th = threading.current_thread().name
        with self._lock:
            if len(self.events) < _MAX_EVENTS_PER_TRACE:
                self.events.append((t, name, th, attrs))
            else:
                self.dropped += 1

    def flag(self) -> None:
        """Force retention at finish regardless of latency/status."""
        with self._lock:
            self.flagged = True

    def to_record(self, e2e_seconds: Optional[float] = None,
                  status: str = "ok", reason: Optional[str] = None,
                  lane: Optional[str] = None,
                  stages: Optional[dict] = None) -> dict:
        """Plain-JSON view; event times are seconds relative to enqueue."""
        with self._lock:
            events = list(self.events)
            dropped = self.dropped
            flagged = self.flagged
        rec = {
            "trace_id": self.trace_id,
            "wall_start": self.wall_start,
            "status": status,
            "flagged": flagged,
            "events": [
                {"t": max(t - self.t_start, 0.0), "name": name,
                 "thread": th, "attrs": attrs or {}}
                for t, name, th, attrs in events
            ],
            "events_dropped": dropped,
        }
        if self.graph_version is not None:
            rec["graph_version"] = self.graph_version
        if self.tenant is not None:
            rec["tenant"] = self.tenant
        if e2e_seconds is not None:
            rec["e2e_seconds"] = float(e2e_seconds)
        if reason is not None:
            rec["reason"] = reason
        if lane is not None:
            rec["lane"] = lane
        if stages:
            rec["stages"] = {k: float(v) for k, v in stages.items()}
        return rec


class _Activation:
    """Context manager installing a tuple of traces on the context-var."""

    __slots__ = ("_ctxs", "_token")

    def __init__(self, ctxs: Tuple[TraceContext, ...]):
        self._ctxs = ctxs
        self._token = None

    def __enter__(self):
        self._token = _ACTIVE.set(self._ctxs)
        return self

    def __exit__(self, *exc):
        _ACTIVE.reset(self._token)
        return False


class _NoopActivation:
    """Shared, stateless, reentrant — activating nothing costs nothing."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOOP_ACTIVATION = _NoopActivation()


def new_trace(trace_id: Optional[str] = None) -> Optional[TraceContext]:
    """A fresh trace context, or None when telemetry is disabled (the
    None threads through the pipeline for free: every consumer guards)."""
    from . import enabled

    if not enabled():
        return None
    ctx = TraceContext(trace_id)
    return ctx


def tracing() -> bool:
    """True iff the calling context has at least one live trace — ONE
    context-var read, so hot paths can guard event-dict construction."""
    return _ACTIVE.get() is not None


def active() -> Optional[Tuple[TraceContext, ...]]:
    """The raw active tuple (or None) — capture this before handing work
    to a thread pool, then re-activate inside the worker."""
    return _ACTIVE.get()


def current() -> Optional[TraceContext]:
    """First active trace context, for single-request call sites."""
    ctxs = _ACTIVE.get()
    return ctxs[0] if ctxs else None


def activate(ctx):
    """``with activate(ctx):`` — attribute the block's events to ``ctx``.

    Accepts a single :class:`TraceContext`, a sequence of them (a
    coalesced batch), a tuple captured via :func:`active`, or None /
    empty (returns a shared no-op so disabled pipelines allocate
    nothing).
    """
    if ctx is None:
        return _NOOP_ACTIVATION
    if isinstance(ctx, TraceContext):
        return _Activation((ctx,))
    ctxs = tuple(c for c in ctx if c is not None)
    if not ctxs:
        return _NOOP_ACTIVATION
    return _Activation(ctxs)


def event(name: str, attrs: Optional[dict] = None) -> None:
    """Append one event to every active trace; no-op off a live trace.

    Hot paths should guard with :func:`tracing` before building
    ``attrs`` so the dict literal itself is never allocated when no
    request is being traced.
    """
    ctxs = _ACTIVE.get()
    if ctxs is None:
        return
    for c in ctxs:
        c.add(name, attrs)
    if _timeline._ON:  # one global read when the timeline is off
        # a {"seconds": dt} attr is a stage interval that just closed:
        # surface it as a complete slice, anything else as an instant
        dur = attrs.get("seconds") if attrs else None
        _timeline.emit(name, dur_s=dur, attrs=attrs, trace=ctxs[0])


def flag() -> None:
    """Flag every active trace for retention (operator breadcrumb: mark
    the request you are about to debug, then pull /debug/requests)."""
    ctxs = _ACTIVE.get()
    if ctxs is None:
        return
    for c in ctxs:
        c.flag()


class FlightRecorder:
    """Tail-sampling ring buffer of finished request records.

    Fixed capacity (``config.flightrec_capacity``): retaining a record
    past capacity evicts the oldest, so steady-state memory is
    O(capacity x events-per-trace) no matter how long the server runs.
    Retention reasons, in precedence order: any non-ok status verbatim
    (``error`` — the request failed; ``shed`` — admission control or a
    deadline dropped it; ``rejected`` — the payload never parsed),
    then ``flagged`` (explicitly marked), then ``slow`` (end-to-end
    above ``config.flightrec_slow_ms``).  Everything else is discarded
    at finish and only ticks ``flightrec_dropped_total``.
    """

    _guarded_by = {"_ring": "_lock", "_by_id": "_lock"}

    def __init__(self, capacity: Optional[int] = None,
                 slow_threshold_s: Optional[float] = None):
        if capacity is None or slow_threshold_s is None:
            from ..config import get_config

            cfg = get_config()
            if capacity is None:
                capacity = int(cfg.flightrec_capacity)
            if slow_threshold_s is None:
                slow_threshold_s = float(cfg.flightrec_slow_ms) / 1e3
        self.capacity = max(int(capacity), 1)
        self.slow_threshold_s = float(slow_threshold_s)
        self._lock = threading.Lock()
        self._ring: List[dict] = []
        self._by_id: Dict[str, dict] = {}

    # -- finish-time decision -----------------------------------------
    def classify(self, ctx: TraceContext, e2e_seconds: float,
                 status: str) -> Optional[str]:
        if status != "ok":
            return status  # error / shed / rejected — all worth keeping
        if ctx.flagged:
            return "flagged"
        if e2e_seconds > self.slow_threshold_s:
            return "slow"
        return None

    def finish(self, ctx: Optional[TraceContext], e2e_seconds: float,
               status: str = "ok", lane: Optional[str] = None,
               stages: Optional[dict] = None) -> Optional[str]:
        """Retain or discard ``ctx``.  Returns the retention reason, or
        None when the record was dropped (the common, fast case)."""
        if ctx is None:  # telemetry disabled at enqueue: nothing to do
            return None
        from . import counter

        if _timeline._ON:  # one global read when the timeline is off
            # the request's end-to-end slice IS the correlation origin:
            # every stage event sharing its trace_id nests under it
            _timeline.emit("request", cat="serving", dur_s=e2e_seconds,
                           attrs={"status": status, "lane": lane},
                           trace=ctx)
        reason = self.classify(ctx, e2e_seconds, status)
        if reason is None:
            counter("flightrec_dropped_total").inc()
            return None
        rec = ctx.to_record(e2e_seconds, status=status, reason=reason,
                            lane=lane, stages=stages)
        with self._lock:
            while len(self._ring) >= self.capacity:
                old = self._ring.pop(0)
                self._by_id.pop(old["trace_id"], None)
            self._ring.append(rec)
            self._by_id[rec["trace_id"]] = rec
        counter("flightrec_retained_total", reason=reason).inc()
        return reason

    # -- read side -----------------------------------------------------
    def records(self) -> List[dict]:
        """Retained records, oldest first (full event logs)."""
        with self._lock:
            return list(self._ring)

    def get(self, trace_id: str) -> Optional[dict]:
        with self._lock:
            return self._by_id.get(trace_id)

    def summaries(self) -> List[dict]:
        """Index view for ``GET /debug/requests``: everything except the
        event log (pull ``/debug/requests/<trace_id>`` for that)."""
        out = []
        for rec in self.records():
            summary = {
                "trace_id": rec["trace_id"],
                "wall_start": rec["wall_start"],
                "e2e_ms": round(rec.get("e2e_seconds", 0.0) * 1e3, 3),
                "status": rec["status"],
                "reason": rec.get("reason"),
                "lane": rec.get("lane"),
                "n_events": len(rec["events"]),
            }
            if "tenant" in rec:
                summary["tenant"] = rec["tenant"]
            out.append(summary)
        return out

    def reset(self) -> None:
        with self._lock:
            self._ring.clear()
            self._by_id.clear()


_RECORDER: Optional[FlightRecorder] = None
_recorder_lock = threading.Lock()


def get_recorder() -> FlightRecorder:
    """Process-wide recorder (lazy: config is read at first touch)."""
    global _RECORDER
    rec = _RECORDER
    if rec is None:
        with _recorder_lock:
            rec = _RECORDER
            if rec is None:
                rec = _RECORDER = FlightRecorder()
    return rec


def reset() -> None:
    """Drop retained records, re-read config, unhook the graph-version
    provider (tests)."""
    global _RECORDER, _VERSION_PROVIDER
    with _recorder_lock:
        _RECORDER = None
    _VERSION_PROVIDER = None


def partition_check(record: dict, rel_tol: float = 0.25) -> bool:
    """Debug helper: do the record's stage intervals partition its
    end-to-end latency?  (Used by tests and worth keeping importable —
    an operator sanity check that the recorder's accounting is closed.)
    """
    stages = record.get("stages") or {}
    e2e = record.get("e2e_seconds")
    if e2e is None or not stages:
        return False
    s = sum(stages.values())
    return math.isclose(s, e2e, rel_tol=rel_tol, abs_tol=5e-3)
