"""quiver_tpu.telemetry — unified metrics + tracing for the data layer.

One process-wide :class:`MetricsRegistry` (counters / gauges /
fixed-bucket histograms, label support, mergeable snapshots) plus one
:class:`SpanTracer` (nested spans, Chrome trace-event export).  Hot
paths call the module-level helpers::

    from quiver_tpu import telemetry

    telemetry.counter("sampler_batches_total", mode="tpu").inc()
    with telemetry.histogram("feature_gather_seconds", tier="hot").time():
        ...
    with telemetry.span("sampler.sample"):
        ...

Gating: ``QUIVER_TELEMETRY=off`` (or ``0``/``false``/``no``) makes every
helper answer with a shared do-nothing singleton from :mod:`.noop` —
no locks, no clocks, no net allocations.  Default is ON: a counter inc
is sub-µs against the ms-scale batches it instruments.  Span *event
retention* (Chrome traces) stays opt-in via ``QUIVER_TPU_TRACE=1`` or
``get_tracer().set_tracing(True)`` either way.

The HTTP exporter lives in :mod:`.export` and is imported lazily —
see ``docs/OBSERVABILITY.md`` for the metric catalogue and label
conventions.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

from . import noop as _noop
from .registry import (Counter, Gauge, Histogram, MetricsRegistry,
                       DEFAULT_TIME_BUCKETS, metric_key, parse_metric_key,
                       snapshot_delta, summarize_snapshot)
from .spans import Span, SpanTracer

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "Span",
    "SpanTracer", "DEFAULT_TIME_BUCKETS", "metric_key", "parse_metric_key",
    "snapshot_delta", "summarize_snapshot",
    "enabled", "set_enabled", "get_registry", "get_tracer",
    "counter", "gauge", "histogram", "span",
    "snapshot", "merge", "reset",
]

_ENABLED = os.environ.get("QUIVER_TELEMETRY", "on").strip().lower() not in (
    "off", "0", "false", "no")

_registry = MetricsRegistry()
_tracer = SpanTracer()


def enabled() -> bool:
    return _ENABLED


def set_enabled(on: bool) -> None:
    """Flip telemetry at runtime (overrides ``QUIVER_TELEMETRY``)."""
    global _ENABLED
    # quiverlint: ignore[QT008] -- single atomic bool rebind; worker
    # readers tolerate one stale observation by design (noop fallback)
    _ENABLED = bool(on)


def get_registry() -> MetricsRegistry:
    return _registry if _ENABLED else _noop.REGISTRY


def get_tracer() -> SpanTracer:
    return _tracer if _ENABLED else _noop.TRACER


def counter(name: str, help: Optional[str] = None, **labels) -> Counter:
    if _ENABLED:
        return _registry.counter(name, help=help, **labels)
    return _noop.METRIC


def gauge(name: str, help: Optional[str] = None, **labels) -> Gauge:
    if _ENABLED:
        return _registry.gauge(name, help=help, **labels)
    return _noop.METRIC


def histogram(name: str, bounds: Optional[Sequence[float]] = None,
              help: Optional[str] = None, **labels) -> Histogram:
    if _ENABLED:
        return _registry.histogram(name, bounds=bounds, help=help, **labels)
    return _noop.METRIC


def span(name: str, block=None):
    return _tracer.span(name, block=block) if _ENABLED else _noop.SPAN


def snapshot() -> dict:
    """Snapshot of the *real* registry (even while disabled, so a
    paused session can still read what was collected)."""
    return _registry.snapshot()


def merge(snap: dict) -> None:
    _registry.merge(snap)


def reset() -> None:
    _registry.reset()
    _tracer.reset()
    # Companion singletons (lazy submodules — never imported just to
    # reset them if nothing ever touched them).
    import sys

    fr = sys.modules.get(__name__ + ".flightrec")
    if fr is not None:
        fr.reset()
    tl = sys.modules.get(__name__ + ".timeline")
    if tl is not None:
        tl.reset()
    pf = sys.modules.get(__name__ + ".profile")
    if pf is not None:
        pf.reset()
    slo = sys.modules.get(__name__ + ".slo")
    if slo is not None:
        slo.reset()
    br = sys.modules.get("quiver_tpu.resilience.breaker")
    if br is not None:
        br.reset()
