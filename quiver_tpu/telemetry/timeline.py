"""Unified timeline event bus — one Perfetto-loadable view of the fleet.

PR 1's spans answer "how long does each scope take on average?", the
flight recorder answers "why was this request slow?", Prometheus
counters answer "how much?".  None of them can show one batch's journey
*across* subsystems — a request that stalls because its gather faulted
twelve pages while the WAL fsync'd under a chaos delay and the QoS
ladder stepped down is four disconnected stories.  This module merges
them: every subsystem emits lightweight events into per-thread bounded
rings, and :func:`chrome_trace` serializes the union as Chrome
trace-event JSON (loadable in Perfetto / chrome://tracing) with the
flight-recorder correlation identity (``trace_id`` / ``tenant`` /
``graph_version``) stamped into each event's ``args``.

Sources that land here when the timeline is enabled:

  * **span closes** — :class:`~quiver_tpu.telemetry.spans.SpanTracer`
    forwards every closed span (``cat="span"``);
  * **flight-recorder events** — :func:`flightrec.event` forwards each
    request-scoped event; a ``{"seconds": dt}`` attr becomes a complete
    ("X") slice, anything else an instant;
  * **direct emits** — chaos injections, WAL append/fsync, page
    faults, QoS ladder transitions, ProgramRegistry builds, and the
    per-program profiler (:mod:`.profile`) call :func:`emit` at their
    own sites, so they appear even when no request trace is active.

Gating discipline (same as flightrec / chaos): the timeline is OFF by
default and every emit site guards with ``if timeline.on():`` — ONE
module-global read, no locks, no clocks, no allocations on the off
path (``QUIVER_TELEMETRY=off`` keeps it off no matter what; a pinned
test asserts ``on()`` reads exactly one global).  Enabled, each emit
is one thread-local ring append; rings are bounded
(``config.timeline_ring_capacity`` events per thread) so a runaway
emitter overwrites its own oldest events instead of growing without
bound.

QT003 lock discipline: rings are single-writer (thread-local); only
the ring *registry* is shared, and every mutation holds ``_REG_LOCK``.
Export snapshots each ring's buffer under the same lock — a torn read
of a concurrently-overwritten slot would interleave two events.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

__all__ = [
    "on", "enable", "disable", "reset",
    "emit", "instant", "events", "chrome_trace", "export", "status",
    "set_fleet_trace_provider", "export_fleet",
]

# THE gate.  Emit sites read this one module global (via :func:`on` or
# ``timeline._ON`` directly); everything else in this module is only
# reachable when it is True.
_ON = False

_REG_LOCK = threading.Lock()
_RINGS: List["_Ring"] = []
_TLS = threading.local()
_CAPACITY = 8192          # per-thread ring slots; re-read from config
_SEQ_LOCK = threading.Lock()


def _telemetry_enabled() -> bool:
    from . import enabled

    return enabled()


class _Ring:
    """One thread's bounded event buffer.

    Single writer (the owning thread): appends are lock-free — a list
    append / slot store is atomic under the GIL, and events are
    immutable tuples replaced whole, so a concurrent exporter can read
    a stale slot but never a torn one.  Only registration in the
    shared ``_RINGS`` list takes ``_REG_LOCK``.
    """

    __slots__ = ("tid", "thread_name", "buf", "n", "cap")

    def __init__(self, cap: int):
        self.tid = threading.get_ident()
        self.thread_name = threading.current_thread().name
        self.buf: List[tuple] = []
        self.n = 0          # total events ever emitted by this thread
        self.cap = cap

    def append(self, ev: tuple) -> None:
        if len(self.buf) < self.cap:
            self.buf.append(ev)
        else:
            self.buf[self.n % self.cap] = ev
        self.n += 1

    @property
    def dropped(self) -> int:
        return max(0, self.n - self.cap)

    def ordered(self) -> List[tuple]:
        """Events oldest-first (unwraps the ring)."""
        if self.n <= self.cap:
            return list(self.buf)
        i = self.n % self.cap
        return self.buf[i:] + self.buf[:i]


def on() -> bool:
    """True iff the timeline is recording — ONE module-global read, so
    hot paths can guard event construction for free when it is off."""
    return _ON


def enable(capacity: Optional[int] = None) -> bool:
    """Start recording.  Returns False (and stays off) when telemetry
    itself is disabled — ``QUIVER_TELEMETRY=off`` wins."""
    global _ON, _CAPACITY
    if not _telemetry_enabled():
        return False
    if capacity is None:
        from ..config import get_config

        capacity = int(get_config().timeline_ring_capacity)
    with _REG_LOCK:
        _CAPACITY = max(int(capacity), 1)
    # quiverlint: ignore[QT008] -- single atomic bool rebind; emit-site
    # readers tolerate one stale observation by design (a missed first
    # event, never a torn ring)
    _ON = True
    return True


def disable() -> None:
    global _ON
    # quiverlint: ignore[QT008] -- single atomic bool rebind, see enable
    _ON = False


def reset() -> None:
    """Drop every ring and stop recording (tests)."""
    global _ON, _TLS
    _ON = False
    with _REG_LOCK:
        _RINGS.clear()
        # orphan the thread-local rings: no longer registered, so the
        # exporter never sees them again; emitters lazily re-register.
        # The swap happens under _REG_LOCK with the clear so a racing
        # _ring() can never register a fresh ring against the old list.
        _TLS = threading.local()


def _ring() -> _Ring:
    # reset() swaps _TLS wholesale, so a stale ring can never be
    # resurrected here — the None check alone keeps emits lock-free
    tls = _TLS
    r = getattr(tls, "ring", None)
    if r is None:
        r = _Ring(_CAPACITY)
        with _REG_LOCK:
            _RINGS.append(r)
        tls.ring = r
    return r


def _seen_rings() -> List["_Ring"]:
    with _REG_LOCK:
        return list(_RINGS)


# serving's stage events predate dotted names; map them home
_CAT_MAP = {
    "sample": "serving", "gather": "serving", "infer": "serving",
    "dequeue": "serving", "enqueue": "serving", "request": "serving",
}


def _category(name: str) -> str:
    cat = _CAT_MAP.get(name)
    if cat is not None:
        return cat
    if "." in name:
        head = name.split(".", 1)[0]
        return {"feature": "paged"}.get(head, head)
    return "app"


def emit(name: str, cat: Optional[str] = None,
         dur_s: Optional[float] = None, t0: Optional[float] = None,
         attrs: Optional[dict] = None, trace=None) -> None:
    """Record one event on the calling thread's ring.

    Callers guard with ``if timeline.on():`` — this function assumes
    the gate already passed (calling it while off still works, it just
    pays the cost the guard exists to avoid).  ``dur_s`` makes a
    complete slice ("X"), otherwise an instant ("i"); ``t0`` backdates
    the slice start (defaults to now - dur).  ``trace`` overrides the
    flight-recorder correlation (a :class:`TraceContext`); by default
    the first active trace on this thread is stamped in.
    """
    t = time.perf_counter()
    if trace is None:
        from . import flightrec

        trace = flightrec.current()
    if t0 is None:
        t0 = t - (dur_s or 0.0)
    if cat is None:
        cat = _category(name)
    tid = None
    tenant = gver = None
    if trace is not None:
        tid = trace.trace_id
        tenant = trace.tenant
        gver = trace.graph_version
    _ring().append((t0, dur_s, name, cat, tid, tenant, gver, attrs))
    from . import counter

    counter("timeline_events_total", subsystem=cat).inc()


def instant(name: str, cat: Optional[str] = None,
            attrs: Optional[dict] = None) -> None:
    emit(name, cat=cat, attrs=attrs)


# -- read side ---------------------------------------------------------
def events() -> List[dict]:
    """Every retained event as plain dicts, per-thread order preserved
    within each thread, threads concatenated."""
    out = []
    for r in _seen_rings():
        for (t0, dur, name, cat, tid, tenant, gver, attrs) in r.ordered():
            e = {"t": t0, "name": name, "cat": cat,
                 "thread": r.thread_name, "tid": r.tid}
            if dur is not None:
                e["dur_s"] = dur
            if tid is not None:
                e["trace_id"] = tid
            if tenant is not None:
                e["tenant"] = tenant
            if gver is not None:
                e["graph_version"] = gver
            if attrs:
                e["attrs"] = dict(attrs)
            out.append(e)
    return out


def status() -> dict:
    rings = _seen_rings()
    return {
        "enabled": _ON,
        "threads": len(rings),
        "events": sum(min(r.n, r.cap) for r in rings),
        "dropped": sum(r.dropped for r in rings),
        "capacity_per_thread": _CAPACITY,
    }


def chrome_trace() -> dict:
    """Chrome trace-event JSON over every ring — complete "X" slices
    for duration events, "i" instants otherwise, one tid per emitting
    thread with its name as "M" metadata.  Timestamps are absolute
    ``perf_counter`` microseconds, the same clock every subsystem
    stamps, so merged events line up."""
    pid = os.getpid()
    evs: List[Dict[str, Any]] = [{
        "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
        "args": {"name": "quiver_tpu"},
    }]
    dropped = 0
    for r in _seen_rings():
        evs.append({"name": "thread_name", "ph": "M", "pid": pid,
                    "tid": r.tid, "args": {"name": r.thread_name}})
        dropped += r.dropped
        for (t0, dur, name, cat, tid, tenant, gver, attrs) in r.ordered():
            args: Dict[str, Any] = dict(attrs) if attrs else {}
            if tid is not None:
                args["trace_id"] = tid
            if tenant is not None:
                args["tenant"] = tenant
            if gver is not None:
                args["graph_version"] = gver
            e: Dict[str, Any] = {
                "name": name, "cat": cat, "pid": pid, "tid": r.tid,
                "ts": t0 * 1e6, "args": args,
            }
            if dur is not None:
                e["ph"] = "X"
                e["dur"] = dur * 1e6
            else:
                e["ph"] = "i"
                e["s"] = "t"
            evs.append(e)
    out: Dict[str, Any] = {"traceEvents": evs, "displayTimeUnit": "ms"}
    if dropped:
        out["otherData"] = {"dropped_events": dropped}
    return out


def export(path: str) -> str:
    """Write :func:`chrome_trace` to ``path``; returns the path."""
    with open(path, "w") as f:
        json.dump(chrome_trace(), f)
    return path


# -- fleet-merged export (provider hook) --------------------------------
# The fleet federation (quiver_tpu/fleet/federation.py) registers its
# merged-trace builder here, the same inversion flightrec uses for the
# graph-version provider: telemetry stays import-free of fleet, and
# `timeline.export_fleet(path)` works wherever a federation is live.
_FLEET_PROVIDER = None


def set_fleet_trace_provider(fn) -> None:
    """Register a zero-arg callable returning the fleet-merged Chrome
    trace document (``None`` unregisters).  Called by
    :class:`~quiver_tpu.fleet.federation.FleetFederation`."""
    global _FLEET_PROVIDER
    # quiverlint: ignore[QT008] -- single atomic reference rebind at
    # federation construction/teardown; export_fleet snapshots it into
    # a local and tolerates one stale observation
    _FLEET_PROVIDER = fn


def export_fleet(path: str) -> str:
    """Write the fleet-merged Chrome trace (router + every reachable
    replica, one process track each, wall-clock timebase) to ``path``;
    returns the path.  Requires a live
    :class:`~quiver_tpu.fleet.federation.FleetFederation`."""
    fn = _FLEET_PROVIDER
    doc = fn() if fn is not None else None
    if doc is None:
        raise RuntimeError(
            "no fleet federation active: construct a FleetFederation "
            "(or a FleetRouter with federation on) before export_fleet")
    with open(path, "w") as f:
        json.dump(doc, f)
    return path
