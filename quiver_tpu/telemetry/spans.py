"""Span tracer: nested wall-clock scopes + Chrome trace-event export.

Subsumes the old ``utils.trace.trace_scope`` / ``Timer`` pair.  Two
independent switches:

  * **aggregation** is always on for a live (non-noop) tracer: every
    span folds into ``{name: [count, total_s]}`` — this is what
    ``summary()`` (né ``trace_summary``) reads and costs one lock + two
    adds per span.
  * **event retention** (``set_tracing(True)`` or env
    ``QUIVER_TPU_TRACE=1``) additionally appends one event record per
    span — name, start/duration in µs, pid/tid, nesting depth — which
    ``chrome_trace()`` serializes as Chrome trace-event JSON
    (``{"traceEvents": [...]}``) loadable in Perfetto / chrome://tracing.

A word on async dispatch: like the old ``trace_scope``, a span around a
jitted call measures **dispatch** unless you pass ``block=`` an array
(or list of arrays) to ``block_until_ready`` before the span closes.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

from . import timeline as _timeline

__all__ = ["SpanTracer", "Span"]

_MAX_EVENTS = 200_000  # retention cap: ~25 MB of events, then drop


def _env_tracing() -> bool:
    return os.environ.get("QUIVER_TPU_TRACE", "").strip().lower() in (
        "1", "true", "on", "yes")


class Span:
    """One ``with``-scope.  Created per call (only when telemetry is
    enabled); closing folds into the tracer's aggregate and, when
    tracing, appends an event record."""

    __slots__ = ("_tracer", "name", "_block", "_t0", "_depth")

    def __init__(self, tracer: "SpanTracer", name: str, block=None):
        self._tracer = tracer
        self.name = name
        self._block = block

    def __enter__(self):
        tls = self._tracer._tls
        self._depth = getattr(tls, "depth", 0)
        tls.depth = self._depth + 1
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        blk = self._block
        if blk is not None:
            for x in (blk if isinstance(blk, (list, tuple)) else (blk,)):
                getattr(x, "block_until_ready", lambda: None)()
        t1 = time.perf_counter()
        self._tracer._tls.depth = self._depth
        self._tracer._close(self.name, self._t0, t1, self._depth)
        return False


class SpanTracer:
    """Aggregating tracer with optional Chrome-trace event retention."""

    def __init__(self, tracing: Optional[bool] = None):
        self._lock = threading.Lock()
        self._agg: Dict[str, List[float]] = {}   # name -> [count, total_s]
        self._events: List[dict] = []
        self._dropped = 0
        self._tls = threading.local()
        self._epoch = time.perf_counter()
        self._tracing = _env_tracing() if tracing is None else bool(tracing)

    # -- recording --------------------------------------------------------
    def span(self, name: str, block=None) -> Span:
        return Span(self, name, block=block)

    def _close(self, name: str, t0: float, t1: float, depth: int) -> None:
        dt = t1 - t0
        with self._lock:
            s = self._agg.get(name)
            if s is None:
                self._agg[name] = [1, dt]
            else:
                s[0] += 1
                s[1] += dt
            if self._tracing:
                if len(self._events) < _MAX_EVENTS:
                    self._events.append({
                        "name": name,
                        "ts_us": (t0 - self._epoch) * 1e6,
                        "dur_us": dt * 1e6,
                        "pid": os.getpid(),
                        "tid": threading.get_ident(),
                        "depth": depth,
                    })
                else:
                    self._dropped += 1
        if _timeline._ON:  # one global read when the timeline is off
            _timeline.emit(name, cat="span", dur_s=dt, t0=t0,
                           attrs={"depth": depth} if depth else None)

    # -- switches ---------------------------------------------------------
    @property
    def tracing(self) -> bool:
        return self._tracing

    def set_tracing(self, on: bool) -> None:
        self._tracing = bool(on)

    # -- readout ----------------------------------------------------------
    def summary(self) -> Dict[str, dict]:
        """``{name: {count, total_s, mean_ms}}`` — same shape the old
        ``trace_summary()`` returned."""
        with self._lock:
            return {
                name: {
                    "count": int(c),
                    "total_s": t,
                    "mean_ms": (t / c * 1e3) if c else 0.0,
                }
                for name, (c, t) in sorted(self._agg.items())
            }

    def events(self) -> List[dict]:
        with self._lock:
            return [dict(e) for e in self._events]

    def reset(self) -> None:
        with self._lock:
            self._agg.clear()
            self._events.clear()
            self._dropped = 0
            self._epoch = time.perf_counter()

    # -- Chrome trace-event JSON -----------------------------------------
    def chrome_trace(self) -> dict:
        """Chrome trace-event JSON (complete "X" events, µs units) —
        load via Perfetto (ui.perfetto.dev) or chrome://tracing."""
        with self._lock:
            evs = [
                {
                    "name": e["name"],
                    "ph": "X",
                    "ts": e["ts_us"],
                    "dur": e["dur_us"],
                    "pid": e["pid"],
                    "tid": e["tid"],
                    "args": {"depth": e["depth"]},
                }
                for e in self._events
            ]
            dropped = self._dropped
        out: Dict[str, Any] = {"traceEvents": evs, "displayTimeUnit": "ms"}
        if dropped:
            out["otherData"] = {"dropped_events": dropped}
        return out

    def export_chrome_trace(self, path: str) -> str:
        with open(path, "w") as f:
            json.dump(self.chrome_trace(), f)
        return path

    @staticmethod
    def parse_chrome_trace(data) -> List[dict]:
        """Inverse of :meth:`chrome_trace` for round-trip tests and
        offline analysis: accepts the dict (or its JSON string) and
        returns event records in :meth:`events` form."""
        if isinstance(data, (str, bytes)):
            data = json.loads(data)
        out = []
        for e in data.get("traceEvents", []):
            if e.get("ph") != "X":
                continue
            out.append({
                "name": e["name"],
                "ts_us": e["ts"],
                "dur_us": e["dur"],
                "pid": e["pid"],
                "tid": e["tid"],
                "depth": e.get("args", {}).get("depth", 0),
            })
        return out
