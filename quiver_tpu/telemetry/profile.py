"""Per-program time attribution over the unified AOT program registry.

Every executable in the library lives in a
:class:`~quiver_tpu.recovery.registry.ProgramCache`.  When profiling is
enabled, cache insertions (and, retroactively, existing entries) are
wrapped in a :class:`_ProfiledProgram` that records, per call:

  * **host seconds** — dispatch wall time (the python call returning);
  * **total seconds** — dispatch + ``jax.block_until_ready`` on the
    result, i.e. device execution for a jitted program;
  * an honest ``device`` flag — False when the backend is CPU, so a
    rehearsal run can never masquerade as silicon attribution
    (docs/BENCHMARKS.md honesty rules).

Aggregates land in ``program_time_seconds{subsystem=...}`` histograms
and a per-(subsystem, key) table served at ``GET /debug/programs``
(:func:`top_programs`).  Each call also lands on the unified timeline
(:mod:`.timeline`) as a complete slice when that is recording.

The wrapper forwards attribute access to the wrapped callable, so
owners that poke at jit internals (``fn.lower``, ``_fun``) keep
working; ``unwrap`` restores the raw program.  Blocking on the result
serializes async dispatch — that is the point (attribution needs the
device time), and why this is opt-in rather than always-on.

Gating: same discipline as :mod:`.timeline` — ``on()`` is one module
global; disabled, the registry's ``__setitem__`` pays exactly one
global read.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

__all__ = ["on", "enable", "disable", "reset", "wrap", "unwrap",
           "record", "top_programs", "stats", "debug_payload"]

_ON = False

_LOCK = threading.Lock()
# (subsystem, key-repr) -> [calls, host_s, total_s, device_calls]
_STATS: Dict[tuple, List[float]] = {}
_guarded_by = {"_STATS": "_LOCK"}


def on() -> bool:
    """True iff program profiling is recording — one global read."""
    return _ON


class _ProfiledProgram:
    """Callable shim: forwards to the wrapped program, attributing each
    call's host + block-until-ready time to (subsystem, key)."""

    __slots__ = ("__wrapped__", "_subsystem", "_key")

    def __init__(self, fn, subsystem: str, key):
        object.__setattr__(self, "__wrapped__", fn)
        object.__setattr__(self, "_subsystem", subsystem)
        object.__setattr__(self, "_key", key)

    def __call__(self, *args, **kwargs):
        import time

        fn = self.__wrapped__
        if not _ON:                    # profiling stopped after wrap
            return fn(*args, **kwargs)
        t0 = time.perf_counter()
        out = fn(*args, **kwargs)
        host_s = time.perf_counter() - t0
        device = False
        try:
            import jax

            jax.block_until_ready(out)
            device = jax.default_backend() != "cpu"
        except Exception:
            pass                       # non-jax result: host time is all
        total_s = time.perf_counter() - t0
        record(self._subsystem, self._key, host_s, total_s, device)
        return out

    def __getattr__(self, name):
        return getattr(object.__getattribute__(self, "__wrapped__"), name)

    def __repr__(self):
        return (f"_ProfiledProgram({self._subsystem}[{self._key!r}]: "
                f"{self.__wrapped__!r})")


def wrap(subsystem: str, key, fn):
    """Wrap ``fn`` for attribution (idempotent; non-callables pass
    through untouched — a cache may hold tuples of aux data)."""
    if not callable(fn) or isinstance(fn, _ProfiledProgram):
        return fn
    return _ProfiledProgram(fn, subsystem, key)


def unwrap(fn):
    return getattr(fn, "__wrapped__", fn)


def record(subsystem: str, key, host_s: float, total_s: float,
           device: bool) -> None:
    """Fold one call into the table + histogram + timeline."""
    k = (subsystem, repr(key))
    with _LOCK:
        st = _STATS.get(k)
        if st is None:
            _STATS[k] = [1, host_s, total_s, 1 if device else 0]
        else:
            st[0] += 1
            st[1] += host_s
            st[2] += total_s
            st[3] += 1 if device else 0
    from . import histogram
    from . import timeline

    histogram("program_time_seconds", subsystem=subsystem).observe(total_s)
    if timeline._ON:
        timeline.emit(f"program.{subsystem}", cat="registry", dur_s=total_s,
                      attrs={"key": repr(key), "device": device,
                             "host_s": round(host_s, 6)})


def _iter_live_caches():
    import sys

    # never instantiate the program registry just to (un)wrap it: if
    # the module was never imported there is nothing to profile
    mod = sys.modules.get("quiver_tpu.recovery.registry")
    if mod is None:
        return []
    reg = mod.get_program_registry()
    with reg._lock:
        pairs = [(sub, ref()) for sub, ref in reg._caches]
    return [(sub, c) for sub, c in pairs if c is not None]


def enable() -> bool:
    """Start attribution.  Retro-wraps every live cache's existing
    programs (bypassing the seal gate — wrapping is not a build), so a
    warmed server can be profiled without recompiling anything.
    Returns False when telemetry is disabled."""
    global _ON
    from . import enabled

    if not enabled():
        return False
    # quiverlint: ignore[QT008] -- single atomic bool rebind; the
    # registry's __setitem__ tolerates one stale observation (one
    # unwrapped program, caught by the retro-wrap below)
    _ON = True
    for sub, cache in _iter_live_caches():
        for key in list(cache.keys()):
            v = dict.__getitem__(cache, key)
            dict.__setitem__(cache, key, wrap(sub, key, v))
    return True


def disable() -> None:
    """Stop attribution and unwrap every live cache entry."""
    global _ON
    # quiverlint: ignore[QT008] -- single atomic bool rebind, see enable
    _ON = False
    for _sub, cache in _iter_live_caches():
        for key in list(cache.keys()):
            v = dict.__getitem__(cache, key)
            dict.__setitem__(cache, key, unwrap(v))


def reset() -> None:
    disable()
    with _LOCK:
        _STATS.clear()


def stats() -> Dict[tuple, List[float]]:
    with _LOCK:
        return {k: list(v) for k, v in _STATS.items()}


def top_programs(k: int = 20) -> List[dict]:
    """Top-K programs by total attributed seconds (the
    ``GET /debug/programs`` table)."""
    rows = []
    for (sub, key), (calls, host_s, total_s, dev_calls) in stats().items():
        calls = int(calls)
        rows.append({
            "subsystem": sub,
            "key": key,
            "calls": calls,
            "host_s": round(host_s, 6),
            "total_s": round(total_s, 6),
            "mean_ms": round(total_s / calls * 1e3, 4) if calls else 0.0,
            # honest stamping: True only if EVERY call ran on a
            # non-CPU backend — mixed runs read as not-device
            "device": bool(calls) and int(dev_calls) == calls,
        })
    rows.sort(key=lambda r: r["total_s"], reverse=True)
    return rows[:max(int(k), 0)]


def debug_payload(k: int = 20) -> dict:
    return {"enabled": _ON, "top": top_programs(k),
            "programs": len(stats())}
