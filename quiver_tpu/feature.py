"""Cached feature store — TPU-native ``quiver.Feature``.

Reference parity: ``srcs/python/quiver/feature.py:17-459`` (Feature,
DeviceConfig) and the ShardTensor machinery it sits on
(``shard_tensor.py:51-213``, ``quiver_feature.cu:57-376``).

TPU-first redesign of the three storage tiers:

  reference                      | quiver_tpu
  -------------------------------+------------------------------------------
  local-GPU HBM hot cache        | HBM-resident ``jax.Array`` hot prefix
  peer-GPU HBM over NVLink/P2P   | hot prefix **sharded over the ICI mesh**
    (p2p_clique_replicate)       |   (``cache_policy="ici_shard"``); XLA
                                 |   inserts the all-gather/all-to-all that
                                 |   the quiver_tensor_gather kernel did by
                                 |   dereferencing peer pointers
  pinned-host zero-copy (UVA)    | host cold tail (numpy / np.memmap),
                                 |   gathered on host and shipped per batch
  cudaIpc handle sharing         | unnecessary (single-controller jax);
                                 |   ``share_ipc`` keeps API parity

The degree-ordered hot/cold split (``reindex_feature``) and the byte-budget
parsing are identical in spirit to the reference; what changes is the
mechanism of remote access.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import List, Optional, Sequence, Union

import numpy as np

from .utils.topology import CSRTopo, parse_size, reindex_feature

__all__ = ["Feature", "DeviceConfig"]


def _pow2_bucket(n: int) -> int:
    """Pad a row count to the power-of-two executable bucket (0 stays 0)."""
    return 0 if n == 0 else max(16, 1 << int(n - 1).bit_length())


def _fresh_bucket(n: int) -> int:
    """Quarter-octave bucket for the overlay's fresh-row H2D payload.

    Power-of-two padding can double the shipped bytes, erasing the
    overlay's transfer saving at moderate hit rates; four buckets per
    octave cap the pad waste at ~12.5% while the executable count stays
    bounded (~4 log2 B distinct shapes).  Device-side-only buckets keep
    plain pow2 — their padding costs HBM reads, not host-link bytes."""
    if n == 0:
        return 0
    if n <= 16:
        return 16
    p = 1 << int(n - 1).bit_length()   # next pow2 >= n
    h, q = p >> 1, p >> 3              # previous pow2, eighth of p
    for cand in (h + q, h + 2 * q, h + 3 * q):
        if n <= cand:
            return cand
    return p


@dataclass
class DeviceConfig:
    """Pre-partitioned placement (parity: ``feature.py:17-24``)."""

    device_ids: List[int]
    device_paths: List[str]  # .npy per device shard
    host_path: Optional[str] = None  # cold tail on disk (mmap)


class Feature:
    """Hot/cold cached node-feature store.

    Lock discipline (quiverlint QT003): ``_plock`` guards the staging
    state shared between the prefetch pool worker and the gather path —
    the ``_pending`` staging map, the reusable per-bucket staging
    buffers (``_stage_bufs``), and the overlay device table
    (``_overlay``, whose value must stay consistent with the
    ``cold_cache`` slot metadata mutated under the same lock).

    Args:
      rank: local device index (parity arg; single-controller jax mostly
        ignores it).
      device_list: devices participating in the cache (defaults to all).
      device_cache_size: per-device byte budget, e.g. ``"200M"`` (parsed by
        :func:`parse_size`), or rows if ``cache_unit="rows"``.
      cache_policy: ``"device_replicate"`` (hot prefix replicated) or
        ``"ici_shard"`` (hot prefix sharded over the mesh; alias
        ``"p2p_clique_replicate"`` accepted for reference compat).
      csr_topo: optional :class:`CSRTopo`; enables degree-ordered caching
        (``reindex_feature``) so high-degree rows land in the hot tier.
      cold_cache_size: budget for the HBM cold-row overlay cache
        (``docs/FEATURE_CACHE.md``) — same units as ``device_cache_size``
        (``parse_size`` bytes, or rows under ``cache_unit="rows"``).
        ``None`` defers to ``config.cold_cache_size``; ``"auto"`` leaves
        the overlay off until :meth:`enable_cold_cache` (the serving
        pipeline enables it for budgeted features); ``0`` disables.
      cold_cache_policy: overlay eviction policy, ``"clock"`` or
        ``"minfreq"`` (defaults to ``config.cold_cache_policy``).
    """

    _guarded_by = {"_pending": "_plock", "_stage_bufs": "_plock",
                   "_overlay": "_plock", "paged": "_plock",
                   # published table state: writes swap atomically under
                   # _plock; reads are lock-free (double-checked-read
                   # contract shared with QT003/QT008)
                   "hot": "_plock", "cold": "_plock",
                   "feature_order": "_plock", "cache_count": "_plock",
                   "node_count": "_plock", "dim": "_plock"}

    def __init__(self, rank: int = 0, device_list: Optional[Sequence] = None,
                 device_cache_size: Union[int, str] = 0,
                 cache_policy: str = "device_replicate",
                 csr_topo: Optional[CSRTopo] = None,
                 mesh=None, dtype=None, cache_unit: str = "bytes",
                 cold_cache_size: Union[int, str, None] = None,
                 cold_cache_policy: Optional[str] = None):
        assert cache_unit in ("bytes", "rows"), cache_unit
        self.cache_unit = cache_unit
        if cache_policy == "p2p_clique_replicate":
            cache_policy = "ici_shard"
        assert cache_policy in ("device_replicate", "ici_shard"), cache_policy
        self.rank = rank
        self.device_list = device_list
        self.device_cache_size = device_cache_size
        self.cache_policy = cache_policy
        self.csr_topo = csr_topo
        self.mesh = mesh
        self.dtype = dtype
        self.cold_cache_size = cold_cache_size
        self.cold_cache_policy = cold_cache_policy
        self.feature_order = None       # old id -> cached row
        self.hot = None                 # jax.Array [H, D]
        self.cold = None                # numpy/memmap [N-H, D]
        self.cache_count = 0
        self.node_count = 0
        self.dim = 0
        self.cold_cache = None          # ColdRowCache slot metadata
        self._overlay = None            # jax.Array [C, D] overlay table
        self.paged = None               # PagedStore (ops/paged.py)
        self._lazy_state = None
        from .recovery.registry import program_cache

        self._merge_cache = program_cache(
            "feature", owner=self)      # (B, bucket) -> jitted merge
        self._pending = {}              # prefetch staging (ids hash -> parts)
        self._stage_bufs = {}           # bucket -> reusable staging ndarray
        self._inflight = None           # deque of outstanding stage futures
        self._plock = threading.Lock()  # staging lock (see _guarded_by)
        self._pool = None               # lazy ThreadPoolExecutor

    # ------------------------------------------------------------------
    def _budget_rows(self, row_bytes: int, n_devices: int) -> int:
        budget = parse_size(self.device_cache_size)
        if self.cache_unit == "rows":
            rows = budget
        else:
            rows = budget // max(row_bytes, 1)
        if self.cache_policy == "ici_shard":
            rows *= n_devices  # each device holds 1/n of the hot set
        return int(rows)

    def _n_devices(self) -> int:
        import jax

        if self.mesh is not None:
            return int(np.prod(list(self.mesh.shape.values())))
        if self.device_list is not None:
            return len(self.device_list)
        return jax.local_device_count()

    def from_cpu_tensor(self, tensor, prob=None) -> "Feature":
        """Split ``tensor`` into HBM hot prefix + host cold tail.

        Parity: ``feature.py:194-281``.  With ``csr_topo`` set, rows are
        first permuted into degree-descending order (shuffled hot slice) and
        ``feature_order`` records old->new ids; ``csr_topo.feature_order``
        is set as a side effect, as in the reference.  ``prob`` (a per-node
        access-probability vector, e.g. from ``sample_prob``) overrides the
        degree heuristic — the reference's papers100M policy
        (``set_local_order``, feature.py:283).
        """
        import jax
        import jax.numpy as jnp

        tensor = np.asarray(tensor)
        node_count, dim = tensor.shape
        with self._plock:
            self.node_count, self.dim = node_count, dim
        dt = self.dtype or tensor.dtype
        row_bytes = int(np.dtype(dt).itemsize) * dim
        nd = self._n_devices()
        cache_count = min(self._budget_rows(row_bytes, nd), node_count)

        new_order = None
        topo_order = False
        if prob is not None and cache_count > 0:
            order = np.argsort(-np.asarray(prob), kind="stable")
            new_order = np.empty(node_count, dtype=np.int64)
            new_order[order] = np.arange(node_count)
            tensor = tensor[order]
        elif self.csr_topo is not None and cache_count > 0:
            ratio = cache_count / node_count
            tensor, new_order = reindex_feature(self.csr_topo, tensor, ratio)
            topo_order = True

        hot_np = np.ascontiguousarray(tensor[:cache_count], dtype=dt)
        cold_np = np.ascontiguousarray(tensor[cache_count:], dtype=dt)
        hot = self._place_hot(hot_np, dt)
        # Publish the table swap as one atomic step: gather-path readers
        # are lock-free by policy (QT003/QT008 double-checked-read
        # contract), so the swap must never be observable half-done.
        # _maybe_enable_cold_cache stays OUTSIDE the lock — it
        # re-acquires _plock (QT009 flags the nested self-acquire).
        with self._plock:
            if new_order is not None:
                self.feature_order = new_order
                if topo_order:
                    self.csr_topo.feature_order = new_order
            self.cache_count = cache_count
            self.cold = cold_np
            self.hot = hot
        self._maybe_enable_cold_cache()
        self._maybe_enable_paging()
        return self

    def _place_hot(self, hot_np, dt):
        """Put the hot tier in HBM — replicated, or sharded over the mesh
        (``ici_shard``, the p2p-clique equivalent)."""
        import jax
        import jax.numpy as jnp

        if hot_np.shape[0] == 0:
            return jnp.zeros((0, self.dim), dtype=dt)
        if self.cache_policy == "ici_shard" and self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            axis = self.mesh.axis_names[0]
            pad = (-hot_np.shape[0]) % np.prod(self.mesh.devices.shape)
            if pad:
                hot_np = np.concatenate(
                    [hot_np, np.zeros((pad, self.dim), dtype=dt)]
                )
            return jax.device_put(
                hot_np, NamedSharding(self.mesh, P(axis, None))
            )
        return jnp.asarray(hot_np)

    @classmethod
    def from_mmap(cls, path_or_array, device_config: DeviceConfig = None,
                  **kwargs) -> "Feature":
        """Disk-backed features (parity: ``feature.py:84-192``).

        ``path_or_array`` may be a ``.npy`` path (opened as ``np.memmap``)
        or an ndarray; the cold tier then reads through the mmap so features
        larger than host RAM still serve.
        """
        self = cls(**kwargs)
        if isinstance(path_or_array, str):
            arr = np.load(path_or_array, mmap_mode="r")
        else:
            arr = path_or_array
        if device_config is not None and device_config.device_paths:
            import jax.numpy as jnp

            shards = [np.load(p, mmap_mode="r")
                      for p in device_config.device_paths]
            hot_np = np.concatenate([np.asarray(s) for s in shards])
            self.cache_count = hot_np.shape[0]
            self.cold = arr
            self.node_count = self.cache_count + arr.shape[0]
            self.dim = arr.shape[1]
            self.hot = self._place_hot(hot_np, hot_np.dtype)
            self._maybe_enable_cold_cache()
            self._maybe_enable_paging()
            return self
        # budgeted split over the mmap
        self.node_count, self.dim = arr.shape
        row_bytes = int(arr.dtype.itemsize) * self.dim
        cache_count = min(
            self._budget_rows(row_bytes, self._n_devices()), self.node_count
        )
        self.cache_count = cache_count
        self.hot = self._place_hot(
            np.ascontiguousarray(arr[:cache_count]), arr.dtype
        )
        self.cold = arr[cache_count:]
        self._maybe_enable_cold_cache()
        self._maybe_enable_paging()
        return self

    # ------------------------------------------------------------------
    def set_local_order(self, local_order):
        """Parity: ``feature.py:283-294`` — externally computed cache order."""
        local_order = np.asarray(local_order)
        new_order = np.empty(self.node_count, dtype=np.int64)
        new_order[local_order] = np.arange(self.node_count)
        with self._plock:
            self.feature_order = new_order

    # -- cold-row overlay cache (docs/FEATURE_CACHE.md) ----------------
    def _maybe_enable_cold_cache(self):
        """Config-driven overlay enable at build time.  ``"auto"`` (the
        default) leaves the overlay opt-in — ``enable_cold_cache`` for
        training loops, or the serving pipeline's budgeted-feature
        auto-enable; an explicit size turns it on here."""
        size = self.cold_cache_size
        if size is None:
            from .config import get_config

            size = get_config().cold_cache_size
        if size in (None, "auto", "off"):
            return
        budget = parse_size(size)
        if self.cache_unit == "rows":
            rows = int(budget)
        else:
            row_bytes = int(np.dtype(self._hot_dtype()).itemsize) * self.dim
            rows = int(budget) // max(row_bytes, 1)
        if rows > 0:
            self.enable_cold_cache(rows=rows)

    def enable_cold_cache(self, rows: Optional[int] = None,
                          policy: Optional[str] = None,
                          admit_threshold: Optional[int] = None) -> "Feature":
        """Attach the fixed-capacity HBM overlay cache over the cold tail.

        The overlay is a second device-resident tier between the static
        hot prefix and the host cold tail: recurring cold rows are
        admitted on their ``admit_threshold``-th miss and then served
        from HBM instead of crossing the host link (three-tier lookup —
        see ``docs/FEATURE_CACHE.md``).  Requires a built feature; no-op
        when the feature is fully hot.

        Args:
          rows: overlay capacity in rows.  Default: a quarter of the hot
            prefix (min 1024), capped at the cold-tail size — small
            enough to never compete with the hot tier for HBM, big
            enough to absorb a zipf tail's recurring rows.
          policy: ``"clock"`` | ``"minfreq"`` (default from config).
          admit_threshold: admit on the N-th miss (default from config).
        """
        import jax.numpy as jnp

        from .config import get_config

        assert self.node_count > 0, (
            "enable_cold_cache needs a built feature "
            "(from_cpu_tensor / from_mmap first)"
        )
        n_cold = self.node_count - self.cache_count
        if n_cold <= 0:
            return self  # fully HBM-resident: nothing to overlay
        cfg = get_config()
        if rows is None:
            rows = max(1024, self.cache_count // 4)
        rows = int(min(rows, n_cold))
        if rows <= 0:
            return self
        from .ops.coldcache import ColdRowCache

        policy = policy or self.cold_cache_policy or cfg.cold_cache_policy
        admit = (admit_threshold if admit_threshold is not None
                 else cfg.cold_cache_admit)
        with self._plock:
            self.cold_cache = ColdRowCache(rows, n_cold, policy=policy,
                                           admit_threshold=admit)
            self._overlay = jnp.zeros((rows, self.dim),
                                      dtype=self._hot_dtype())
        return self

    # -- paged feature store (docs/FEATURE_CACHE.md) -------------------
    def _maybe_enable_paging(self):
        """Config-driven paged-store enable at build time
        (``feature_paged=on``).  Off by default: the staged three-tier
        merge stays byte-identical — same metric keys, same executable
        keys — until paging is opted into."""
        from .config import get_config

        cfg = get_config()
        if cfg.feature_paged != "on":
            return
        if self.cache_count >= self.node_count:
            return  # fully hot: pure-device gather, nothing to page
        self.enable_paging(
            page_rows=cfg.feature_page_rows or None,
            pool_pages=cfg.feature_page_pool or None)

    def enable_paging(self, page_rows: Optional[int] = None,
                      pool_pages: Optional[int] = None,
                      policy: Optional[str] = None) -> "Feature":
        """Attach the paged store: pack the table into fixed-size HBM
        pages and serve every budgeted gather through the ragged
        page-gather kernel (``ops/paged.py``).

        The three tiers become page residency states — the hot prefix
        is the pinned DEVICE pages, the overlay is the OVERLAY frame
        pool, the host tail is HOST pages faulted in whole.  The staged
        merge stays attached underneath as the correctness fallback for
        batches whose page working set exceeds the pool.

        Args:
          page_rows: rows per page.  Default: smallest row count whose
            page is a multiple of the 512B HBM transaction and at least
            4KiB (``default_page_rows``).
          pool_pages: OVERLAY pool capacity in pages.  Default: a
            quarter of the host-page count (min 8), capped at the
            host-page count.
          policy: page-table eviction policy, ``"clock"`` | ``"minfreq"``
            (default from config ``cold_cache_policy``).
        """
        from .config import get_config
        from .ops.paged import PagedStore, PageTable, default_page_rows

        assert self.node_count > 0, (
            "enable_paging needs a built feature "
            "(from_cpu_tensor / from_mmap first)")
        n_cold = self.node_count - self.cache_count
        if n_cold <= 0:
            return self  # fully HBM-resident: nothing to page
        dt = np.dtype(self._hot_dtype())
        row_bytes = dt.itemsize * self.dim
        R = int(page_rows) if page_rows else default_page_rows(row_bytes)
        n_pages = -(-self.node_count // R)
        hot_pages = -(-self.cache_count // R) if self.cache_count else 0
        n_host_pages = n_pages - min(hot_pages, n_pages)
        if pool_pages is None:
            pool_pages = max(8, n_host_pages // 4)
        pool_pages = min(int(pool_pages), n_host_pages)
        policy = policy or self.cold_cache_policy \
            or get_config().cold_cache_policy
        table = PageTable(self.node_count, self.cache_count, R,
                          pool_pages, policy=policy)
        # quiverlint: sync-ok[one-time hot-set migration at paging enablement]
        # (never on the lookup path)
        hot_np = (np.asarray(self.hot) if self.cache_count else None)
        store = PagedStore(table, self.cold, self.cache_count, self.dim,
                           dt, hot_host=hot_np)
        store._feature = self
        with self._plock:
            self.paged = store
        return self

    def invalidate_rows(self, node_ids) -> int:
        """Drop mutated rows (OLD node ids) from the cold-row overlay.

        The streaming tier calls this for every edge mutation's touched
        endpoints (``StreamingGraph.attach_feature``): a resident
        overlay slot would otherwise keep serving the pre-mutation
        value.  Rows in the static hot prefix are untouched — that tier
        is a partition of the table, not a cache, so staleness there is
        a feature-*update* problem, not an invalidation one.  Touch
        counts reset too: a mutated row re-earns admission from scratch
        (miss on next touch, re-admit on the one after, under the
        default second-touch policy).  Returns overlay slots dropped.
        """
        from . import telemetry

        if self.cold_cache is None and self.paged is None:
            return 0
        ids = np.atleast_1d(np.asarray(node_ids, dtype=np.int64))
        if self.feature_order is not None:
            ids = ids[(ids >= 0) & (ids < len(self.feature_order))]
            ids = np.asarray(self.feature_order)[ids]
        cold_ids = ids - self.cache_count
        cold_ids = cold_ids[cold_ids >= 0]
        with self._plock:
            cache = self.cold_cache
            dropped = (cache.invalidate_rows(cold_ids)
                       if cache is not None else 0)
            if self.paged is not None:
                # whole OVERLAY pages drop: one stale row poisons its page
                self.paged.invalidate_rows(cold_ids)
        if dropped:
            telemetry.counter("coldcache_invalidated_rows_total").inc(
                dropped)
        return dropped

    def export_coldcache_state(self) -> Optional[dict]:
        """Device-cache residency state for a recovery checkpoint
        (``None`` when neither overlay nor paged store is attached).
        Only metadata is exported — the row *values* live in the host
        cold tier and are re-gathered from it on restore.  With paging
        on, the page-table residency is exported instead (tagged
        ``kind="paged"``; the arrays ride the same pinned-dtype
        serialization as the overlay's)."""
        with self._plock:
            if self.paged is not None:
                return self.paged.export_state()
            cache = self.cold_cache
            return cache.export_state() if cache is not None else None

    def restore_coldcache_state(self, state: Optional[dict]) -> int:
        """Re-warm the overlay (or page table) from a checkpointed state.

        Restores the slot metadata, then refills the device table from
        the host cold tier for every resident slot — restoring the map
        without the values would serve zeros for "cached" rows.  The
        geometry must match (``ValueError`` otherwise — the caller
        starts cold).  Kind mismatches degrade cleanly: a paged
        snapshot restored into a ``feature_paged=off`` build (or vice
        versa) starts cold instead of refusing boot.  Returns the
        number of rows re-warmed.
        """
        import jax.numpy as jnp

        if state is None:
            return 0
        if state.get("kind") == "paged":
            if self.paged is None:
                return 0  # paging off now: degrade to a cold start
            with self._plock:
                return self.paged.restore_state(state)
        if self.paged is not None and self.cold_cache is None:
            return 0  # staged snapshot, paged-only build: start cold
        if self.cold_cache is None:
            self.enable_cold_cache(rows=int(state["capacity"]))
        if self.cold_cache is None:
            return 0  # fully hot: nothing to overlay
        with self._plock:
            cache = self.cold_cache
            cache.restore_state(state)
            slots = np.nonzero(cache.node_of >= 0)[0]
            if slots.size:
                rel = cache.node_of[slots]
                rows = np.ascontiguousarray(self.cold[rel],
                                            dtype=self._hot_dtype())
                self._overlay = self._overlay.at[jnp.asarray(slots)].set(
                    jnp.asarray(rows))
        return int(slots.size)

    # ------------------------------------------------------------------
    def __getitem__(self, node_idx):
        """Gather rows by (old) node id; returns a device array.

        Hot rows come from HBM (one fused XLA gather — sharded arrays make
        XLA emit the cross-chip collective); cold rows are gathered on host
        and shipped once per batch, then merged on device.  Parity:
        ``feature.py:296-333`` + ``shard_tensor.py:154-180``.

        Fully-cached features take a pure-device path: jax-array ids never
        round-trip through the host (the reference pays a cudaMemcpy here
        only when ids arrive on CPU; same idea).
        """
        import jax
        import jax.numpy as jnp

        from . import telemetry

        self.lazy_init_from_ipc_handle()
        tier = ("hot" if self.cache_count >= self.node_count else
                ("cold" if self.cache_count == 0 else "mixed"))
        with telemetry.span("feature.getitem"), telemetry.histogram(
                "feature_gather_seconds", tier=tier).time():
            out = self._getitem_impl(node_idx, jax, jnp, telemetry)
        telemetry.counter("feature_gather_batches_total", tier=tier).inc()
        return out

    def _getitem_impl(self, node_idx, jax, jnp, telemetry):
        if self.cache_count >= self.node_count:
            if isinstance(node_idx, jax.Array):
                return self.lookup_device(node_idx)
            idx = np.asarray(node_idx)
            if self.feature_order is not None:
                idx = self.feature_order[idx]
            return jnp.take(self.hot, jnp.asarray(idx), axis=0)
        idx = np.asarray(node_idx)
        staged = self._take_staged(idx.tobytes())
        if self._pool is not None:
            telemetry.counter(
                "feature_prefetch_total",
                result="hit" if staged is not None else "miss").inc()
        if staged is None:
            staged = self._stage(idx)
        if staged[0] == "pg":
            # paged path: ONE ragged-kernel program per batch size (the
            # inverse-permutation take fuses into it) — the entire
            # (B, bucket) x ("z"/"patch", bc/bh) grid collapses here
            return self.paged.finish(staged, self)
        if staged[0] == "ov":
            # additive program structure: base two-way merge keyed by
            # the fresh bucket, then a separate overlay patch keyed by
            # the hit bucket — |bc| + |bh| executables, never |bc|x|bh|
            # combos (hit counts fluctuate batch to batch; a fused
            # three-way program would compile per combination)
            (_, hot_idx, bc, cold_pos_d, cold_rows_d,
             bh, ov_slot_d, ov_pos_d, ov_table) = staged
            B = len(idx)
            if hot_idx is None:
                if bc == 0:
                    out = self._merge_fn(B, ("z", 0), jax, jnp)()
                else:
                    out = self._merge_fn(B, ("z", bc), jax, jnp)(
                        cold_rows_d, cold_pos_d)
            else:
                out = self._merge_fn(B, bc, jax, jnp)(
                    self.hot, hot_idx, cold_rows_d, cold_pos_d)
            if bh:
                out = self._merge_fn(B, ("patch", bh), jax, jnp)(
                    out, ov_table, ov_slot_d, ov_pos_d)
            return out
        _, hot_idx, bucket, cold_pos_d, cold_rows_d = staged
        return self._merge_fn(len(idx), bucket, jax, jnp)(
            self.hot, hot_idx, cold_rows_d, cold_pos_d
        )

    def _take_staged(self, key):
        """Claim a prefetched stage for ``key``, waiting on in-flight
        prefetch work if needed (single FIFO worker: futures complete in
        submit order, so draining the oldest either surfaces our entry or
        proves it was never prefetched — never a duplicated gather)."""
        if self._pool is None:
            return None
        with self._plock:
            staged = self._pending.pop(key, None)
        while staged is None and self._inflight:
            try:
                fut = self._inflight.popleft()
            except IndexError:
                break
            fut.result()
            with self._plock:
                staged = self._pending.pop(key, None)
        return staged

    def _stage(self, idx):
        """Host side of a budgeted gather: translate ids, probe the
        overlay cache (if enabled), fetch ONLY the fresh cold rows from
        the host tier, start their H2D copy.

        The cold-row count is padded to a power-of-two bucket so the device
        merge compiles once per (batch, bucket) instead of per batch — and
        only ``~n_cold`` rows cross PCIe, not the full batch width (the
        round-1 path gathered full-size hot AND cold then ``where``-merged:
        2x traffic; VERDICT weak #6).  With the overlay enabled, the
        recurring part of those cold rows stops crossing at all — it is
        served from the HBM overlay table (``_stage_overlay``).
        """
        import jax
        import jax.numpy as jnp

        from . import telemetry

        if self.feature_order is not None:
            idx = self.feature_order[idx]
        idx = idx.astype(np.int64)
        if self.paged is not None and len(idx):
            with self._plock:
                st = self.paged.stage(idx, jnp, telemetry)
            if st is not None:
                return st
            # pool overflow: this batch's page working set doesn't fit
            # the OVERLAY pool — the staged merge below is the fallback
        if self.cold_cache is not None:
            return self._stage_overlay(idx, jax, jnp, telemetry)
        if self.cache_count == 0:
            n = len(idx)
            telemetry.counter("feature_rows_total", tier="cold").inc(
                float(n))
            return ("m", None, -1, None,
                    self._upload_cold(idx, n, n, jnp, telemetry))
        hot_mask = idx < self.cache_count
        cold_pos = np.nonzero(~hot_mask)[0].astype(np.int32)
        n_cold = len(cold_pos)
        # cache-hit accounting for the budgeted tier: a "hot" row is a
        # cache hit served from HBM, a "cold" row crosses the host link
        telemetry.counter("feature_rows_total", tier="hot").inc(
            float(len(idx) - n_cold))
        from .telemetry import flightrec

        if flightrec.tracing():
            flightrec.event("feature.stage", {
                "rows_hot": int(len(idx) - n_cold), "rows_cold": int(n_cold)})
        if n_cold:
            telemetry.counter("feature_rows_total", tier="cold").inc(
                float(n_cold))
        hot_idx = jnp.asarray(np.where(hot_mask, idx, 0).astype(np.int32))
        if n_cold == 0:
            return ("m", hot_idx, 0, None, None)
        bucket = _pow2_bucket(n_cold)
        # the bucket must cover every real row — padded lanes beyond
        # n_cold read only the zero-filled staging tail, never past the
        # buffer, including when B lands exactly on a bucket edge
        assert 0 < n_cold <= bucket, (n_cold, bucket)
        rows_d = self._upload_cold(idx[cold_pos] - self.cache_count,
                                   n_cold, bucket, jnp, telemetry)
        # pad positions with the out-of-range sentinel len(idx) == B;
        # the device scatter drops them (mode="drop")
        pos = np.full(bucket, len(idx), dtype=np.int32)
        pos[:n_cold] = cold_pos
        assert (pos[n_cold:] >= len(idx)).all(), \
            "padding sentinel must stay out of range of the output"
        return ("m", hot_idx, bucket, jnp.asarray(pos), rows_d)

    def _upload_cold(self, rel_ids, n_rows, bucket, jnp, telemetry):
        """Gather ``rel_ids`` from the host cold tier into the reusable
        per-bucket staging buffer and start its H2D copy.

        One long-lived buffer per bucket size instead of a fresh
        ``np.zeros((bucket, dim))`` per batch; ``jnp.array`` (copy
        semantics — never ``jnp.asarray``, which may alias host memory
        on the CPU backend) detaches the device copy before the buffer
        can be reused.  The shipped payload lands on
        ``feature_h2d_bytes_total``."""
        dt = np.dtype(self._hot_dtype())
        with self._plock:
            buf = self._stage_bufs.get(bucket)
            if buf is None or buf.shape != (bucket, self.dim) \
                    or buf.dtype != dt:
                buf = np.zeros((bucket, self.dim), dtype=dt)
                self._stage_bufs[bucket] = buf
            buf[:n_rows] = self.cold[rel_ids]
            rows_d = jnp.array(buf)
        telemetry.counter("feature_h2d_bytes_total").inc(float(buf.nbytes))
        from .telemetry import flightrec

        if flightrec.tracing():
            flightrec.event("feature.h2d", {"bytes": int(buf.nbytes),
                                            "rows": int(n_rows)})
        return rows_d

    def _stage_overlay(self, idx, jax, jnp, telemetry):
        """Three-tier staging: hot-prefix split, overlay probe, host
        fetch for the remaining fresh rows, then overlay admission.

        Probe + admission + the device-table update run under ``_plock``
        as one atomic step, and the staged tuple captures the overlay
        *value* current at probe time: a concurrent stage (sync gather
        racing the prefetch worker) that admits-and-evicts can never
        retarget slots under an already-staged merge, because jax arrays
        are immutable — the captured value keeps serving exactly the
        rows its metadata promised.
        """
        B = len(idx)
        cc = self.cache_count
        if cc > 0:
            hot_mask = idx < cc
            cold_pos_all = np.nonzero(~hot_mask)[0].astype(np.int32)
            hot_idx = jnp.asarray(
                np.where(hot_mask, idx, 0).astype(np.int32))
            telemetry.counter("feature_rows_total", tier="hot").inc(
                float(B - len(cold_pos_all)))
        else:
            cold_pos_all = np.arange(B, dtype=np.int32)
            hot_idx = None
        n_cold = len(cold_pos_all)
        if n_cold == 0:
            return ("m", hot_idx, 0, None, None)
        telemetry.counter("feature_rows_total", tier="cold").inc(
            float(n_cold))
        rel = idx[cold_pos_all] - cc
        dt = np.dtype(self._hot_dtype())
        h2d_bytes = 0
        n_evicted = 0
        with self._plock:
            cache = self.cold_cache
            hit_mask, slots = cache.probe(rel)
            n_hit = int(hit_mask.sum())
            n_fresh = n_cold - n_hit
            ov_table = self._overlay  # value consistent with the probe
            bh = _pow2_bucket(n_hit)
            ov_slot_d = ov_pos_d = None
            # bucket-edge discipline (regression-tested): every bucket
            # covers its real rows, padded lanes carry the out-of-range
            # sentinel B and zero-filled buffer tails only
            assert n_hit <= bh, (n_hit, bh)
            if bh:
                ov_slot = np.zeros(bh, dtype=np.int32)
                ov_slot[:n_hit] = slots[hit_mask]
                ov_pos = np.full(bh, B, dtype=np.int32)
                ov_pos[:n_hit] = cold_pos_all[hit_mask]
                ov_slot_d = jnp.asarray(ov_slot)
                ov_pos_d = jnp.asarray(ov_pos)
            bc = _fresh_bucket(n_fresh)
            rows_d = cold_pos_d = None
            assert n_fresh <= bc, (n_fresh, bc)
            if bc:
                fresh_rel = rel[~hit_mask]
                buf = self._stage_bufs.get(bc)
                if buf is None or buf.shape != (bc, self.dim) \
                        or buf.dtype != dt:
                    buf = np.zeros((bc, self.dim), dtype=dt)
                    self._stage_bufs[bc] = buf
                buf[:n_fresh] = self.cold[fresh_rel]
                rows_d = jnp.array(buf)  # copy: the buffer is reusable
                h2d_bytes = buf.nbytes
                pos = np.full(bc, B, dtype=np.int32)
                pos[:n_fresh] = cold_pos_all[~hit_mask]
                cold_pos_d = jnp.asarray(pos)
                adm, n_evicted = cache.admit(fresh_rel)
                if (adm >= 0).any():
                    # scatter the admitted subset of the freshly shipped
                    # rows into the overlay, in the same (already paid)
                    # H2D payload; non-admitted rows pad to slot C (drop)
                    adm_slot = np.full(bc, cache.capacity, dtype=np.int32)
                    adm_slot[:n_fresh] = np.where(adm >= 0, adm,
                                                  cache.capacity)
                    self._overlay = self._admit_fn(bc, jax, jnp)(
                        self._overlay, jnp.asarray(adm_slot), rows_d)
        telemetry.counter("feature_coldcache_rows_total",
                          result="hit").inc(float(n_hit))
        telemetry.counter("feature_coldcache_rows_total",
                          result="miss").inc(float(n_fresh))
        if n_evicted:
            telemetry.counter("feature_coldcache_evictions_total").inc(
                float(n_evicted))
        if h2d_bytes:
            telemetry.counter("feature_h2d_bytes_total").inc(
                float(h2d_bytes))
        from .telemetry import flightrec

        if flightrec.tracing():
            # per-request attribution of the aggregate coldcache
            # counters above — which requests are paying the host link
            flightrec.event("feature.coldcache", {
                "hit": int(n_hit), "miss": int(n_fresh),
                "evicted": int(n_evicted), "h2d_bytes": int(h2d_bytes)})
        return ("ov", hot_idx, bc, cold_pos_d, rows_d,
                bh, ov_slot_d, ov_pos_d, ov_table)

    def _hot_dtype(self):
        return self.hot.dtype if self.hot is not None else (
            self.dtype or np.float32
        )

    def _merge_fn(self, B, bucket, jax, jnp):
        """One cached executable per (batch size, cold bucket)."""
        fn = self._merge_cache.get((B, bucket))
        if fn is None:
            if isinstance(bucket, tuple):  # ("z", bc) | ("patch", bh)
                fn = self._build_overlay_fn(B, bucket, jax, jnp)
            elif bucket < 0:    # pure cold tier: rows arrive ready
                fn = lambda hot, hi, rows, pos: rows
            elif bucket == 0:   # all-hot batch

                @jax.jit
                def fn(hot, hot_idx, cold_rows, cold_pos):
                    return jnp.take(hot, hot_idx, axis=0)
            else:

                @jax.jit
                def fn(hot, hot_idx, cold_rows, cold_pos):
                    out = jnp.take(hot, hot_idx, axis=0)
                    return out.at[cold_pos].set(cold_rows, mode="drop")
            # quiverlint: ignore[QT014] -- B is one-executable-per-batch-
            # size by design (serving pads upstream via _pad_ids); the
            # bucket component is always produced by _pow2_bucket /
            # _fresh_bucket in _stage/_stage_overlay, but rides through
            # the prefetch dict as an opaque staged tuple, which is
            # where the symbolic trace loses it.
            self._merge_cache[(B, bucket)] = fn
        return fn

    def _build_overlay_fn(self, B, key, jax, jnp):
        """Overlay companion programs for the base two-way merge:

        * ``("z", bc)`` — pure-cold base (no hot prefix): zeros, with
          the fresh rows scattered in (``bc == 0``: just the zeros).
        * ``("patch", bh)`` — scatter ``bh`` overlay hits (gathered from
          the HBM table) over the base merge's output.

        Pad positions are ``B`` and pad slots ``capacity``; both fall
        off via ``mode="drop"``."""
        kind = key[0]
        dim = self.dim
        dt = self._hot_dtype()
        if kind == "z":
            if key[1] == 0:

                @jax.jit
                def fn():
                    return jnp.zeros((B, dim), dtype=dt)
            else:

                @jax.jit
                def fn(cold_rows, cold_pos):
                    out = jnp.zeros((B, dim), dtype=dt)
                    return out.at[cold_pos].set(cold_rows, mode="drop")
        else:  # "patch"

            @jax.jit
            def fn(out, table, ov_slot, ov_pos):
                rows = jnp.take(table, ov_slot, axis=0)
                return out.at[ov_pos].set(rows, mode="drop")

        return fn

    def _admit_fn(self, bucket, jax, jnp):
        """Cached scatter-update program writing admitted rows into the
        overlay table (pad slot = capacity, dropped).  Keyed in
        ``_merge_cache`` so ``retrace_guard`` counts its builds too.  No
        buffer donation: staged merges may still hold the old table
        value (see ``_stage_overlay``)."""
        fn = self._merge_cache.get(("admit", bucket))
        if fn is None:

            @jax.jit
            def fn(table, slots, rows):
                return table.at[slots].set(rows, mode="drop")

            self._merge_cache[("admit", bucket)] = fn
        return fn

    def _paged_fn(self, B):
        """ONE cached executable per batch size on the paged path: the
        ragged page-gather kernel plus the inverse-permutation take that
        undoes the planner's sort-by-frame.  Keyed ``("paged", B)`` in
        ``_merge_cache`` — the whole additive bucket grid of the staged
        path collapses to this single entry (plus the fault scatter's
        pow2 warmup, ``_paged_fault_fn``)."""
        import jax
        import jax.numpy as jnp

        fn = self._merge_cache.get(("paged", B))
        if fn is None:
            from .ops.pallas.page_gather_kernel import page_gather

            store = self.paged
            page_rows = store.table.page_rows
            block, ppb = store.block, store.ppb
            interpret = store._interpret

            @jax.jit
            def fn(frames, blk_pages, blk_np, row_lp, row_off, rank):
                out = page_gather(
                    frames, blk_pages, blk_np, row_lp, row_off,
                    page_rows=page_rows, block=block, ppb=ppb,
                    interpret=interpret)
                return jnp.take(out, rank, axis=0)

            # quiverlint: ignore[QT014] -- one executable per batch size
            # is this path's contract (the whole (B, bucket) grid
            # collapses to it); B arrives inside the planner's staged
            # tuple through the duck-typed PagedStore.finish edge, which
            # the symbolic trace cannot follow.
            self._merge_cache[("paged", B)] = fn
        return fn

    def _paged_fault_fn(self, k_pad):
        """Cached scatter writing a pow2-padded batch of faulted pages
        into the frame pool (pad slot = ``n_frames``, dropped).  The
        paged analogue of ``_admit_fn`` — no buffer donation: staged
        plans may still hold the old frames value."""
        import jax

        fn = self._merge_cache.get(("pgfault", k_pad))
        if fn is None:

            @jax.jit
            def fn(frames, slots, pages):
                return frames.at[slots].set(pages, mode="drop")

            # quiverlint: ignore[QT014] -- k_pad is pow2-padded at the
            # fault site (ops/paged._fault: _pow2_bucket over the miss
            # count); the call reaches here through the duck-typed
            # PagedStore._feature receiver, which hides the edge from
            # the resolver.
            self._merge_cache[("pgfault", k_pad)] = fn
        return fn

    # -- async cold-tier prefetch --------------------------------------
    def prefetch(self, node_idx):
        """Begin the host-side cold gather + H2D copy for ``node_idx`` on a
        worker thread; the matching ``feature[node_idx]`` call consumes it.

        TPU answer to the reference's in-kernel zero-copy host reads
        (``shard_tensor.cu.hpp:19-61``): there the device pulls host rows on
        demand inside the gather kernel; here the host pushes the (few) cold
        rows toward the device while the previous step computes, so the
        merge sees them already in flight.  ``SeedLoader`` calls this one
        batch ahead automatically.
        """
        if self.cache_count >= self.node_count:
            return  # nothing host-side to hide
        if self._pool is None:
            import atexit
            import collections
            from concurrent.futures import ThreadPoolExecutor

            self._pool = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="feature-prefetch"
            )
            # cancel queued stages at interpreter exit: a straggler
            # worker touching jax arrays during runtime teardown aborts
            # the process (C++ terminate)
            atexit.register(self._pool.shutdown, wait=False,
                            cancel_futures=True)
            self._inflight = collections.deque()

        from .telemetry import flightrec

        # capture the caller's trace contexts at submit time: the pool
        # worker does not inherit contextvars, and re-activating inside
        # work() attributes the staged gather (coldcache probes, H2D) to
        # the originating request instead of to an anonymous thread
        ctxs = flightrec.active()

        def work():
            # materialize here (may block on the device sample that
            # produced node_idx) so the CALLER never does
            with flightrec.activate(ctxs):
                idx = np.asarray(node_idx)
                if flightrec.tracing():
                    flightrec.event("feature.prefetch",
                                    {"rows": int(len(idx))})
                staged = self._stage(idx)
            with self._plock:
                self._pending[idx.tobytes()] = staged
                while len(self._pending) > 8:  # drop oldest unclaimed
                    self._pending.pop(next(iter(self._pending)))

        self._inflight.append(self._pool.submit(work))
        # age out only FINISHED futures: dropping a pending one would break
        # _take_staged's FIFO-drain (its key could never be waited for,
        # forcing a duplicate synchronous gather)
        while len(self._inflight) > 8 and self._inflight[0].done():
            self._inflight.popleft()

    def lookup_device(self, idx):
        """Pure-device gather for jit pipelines (requires full HBM cache).
        Applies ``feature_order`` on device; safe to call under jit."""
        import jax.numpy as jnp

        self.lazy_init_from_ipc_handle()
        assert 0 < self.node_count <= self.cache_count, (
            "lookup_device needs a (built) fully HBM-resident feature"
        )
        if self.feature_order is not None:
            if getattr(self, "_order_dev", None) is None:
                self._order_dev = jnp.asarray(
                    self.feature_order.astype(np.int32)
                )
            idx = jnp.take(self._order_dev, idx, mode="clip")
        return jnp.take(self.hot, idx, axis=0)

    # ------------------------------------------------------------------
    def size(self, dim: int) -> int:
        return (self.node_count, self.dim)[dim]

    @property
    def shape(self):
        return (self.node_count, self.dim)

    def dim_(self):
        return self.dim

    # ------------------------------------------------------------------
    # IPC-parity API: single-controller jax needs no cudaIpc; we pack the
    # construction recipe so reference-style mp code keeps working.
    # (feature.py:383-458)
    def share_ipc(self):
        return (
            dict(rank=self.rank, device_cache_size=self.device_cache_size,
                 cache_policy=self.cache_policy),
            self.hot, self.cold, self.feature_order,
            self.cache_count, self.node_count, self.dim,
        )

    @classmethod
    def new_from_ipc_handle(cls, rank, ipc_handle):
        cfg, hot, cold, order, cc, nc, dim = ipc_handle
        cfg = dict(cfg)
        cfg["rank"] = rank
        self = cls(**cfg)
        self.hot, self.cold, self.feature_order = hot, cold, order
        self.cache_count, self.node_count, self.dim = cc, nc, dim
        return self

    @classmethod
    def lazy_from_ipc_handle(cls, ipc_handle):
        self = cls(rank=0)
        self._lazy_state = ipc_handle
        return self

    def __repr__(self):
        return (
            f"Feature(nodes={self.node_count}, dim={self.dim}, "
            f"hot={self.cache_count}, policy={self.cache_policy!r})"
        )

    def lazy_init_from_ipc_handle(self):
        if self._lazy_state is None:
            return
        cfg, hot, cold, order, cc, nc, dim = self._lazy_state
        with self._plock:
            self.hot, self.cold, self.feature_order = hot, cold, order
            self.cache_count, self.node_count, self.dim = cc, nc, dim
        self._lazy_state = None
