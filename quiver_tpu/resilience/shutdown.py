"""Leak-aware thread shutdown.

``t.join(timeout=...)`` returning is not the same as ``t`` exiting —
a wedged worker sails right past the timeout and the old ``stop()``
paths pretended shutdown succeeded.  :func:`join_and_reap` joins a
batch of threads, reports the ones still alive, ticks
``serving_thread_leak_total{component}``, and logs each leaker with its
name so a hung stage shows up in both the registry and the logs instead
of as a mystery at interpreter exit.
"""

from __future__ import annotations

import logging
from typing import List, Sequence

from .. import telemetry

__all__ = ["join_and_reap"]

_log = logging.getLogger("quiver_tpu.resilience")


def join_and_reap(threads: Sequence, timeout: float,
                  component: str) -> List:
    """Join every thread with a shared deadline; return the leakers.

    The timeout is a total budget, not per-thread: ``n`` wedged threads
    cost one timeout, not ``n``.  Every thread still alive afterwards is
    logged and counted in ``serving_thread_leak_total{component}``.
    """
    import time

    deadline = time.monotonic() + timeout
    for t in threads:
        left = deadline - time.monotonic()
        t.join(timeout=max(left, 0.0))
    leaked = [t for t in threads if t.is_alive()]
    for t in leaked:
        telemetry.counter("serving_thread_leak_total",
                          component=component).inc()
        _log.warning("thread %r leaked at %s shutdown (join timed out "
                     "after %.1fs total)", t.name, component, timeout)
    return leaked
