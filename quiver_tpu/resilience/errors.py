"""Typed failure results for the serving pipeline.

A request that cannot be served is *answered*, never dropped: the
result queue carries ``(request, exception)`` with one of these types,
so a client can tell "you were too late" (:class:`DeadlineExceeded`)
from "we were overloaded" (:class:`LoadShed`) from "the lane is down"
(:class:`LaneUnavailable`) — three different retry policies.
"""

from __future__ import annotations

from typing import Optional

__all__ = [
    "ResilienceError", "DeadlineExceeded", "LoadShed", "LaneUnavailable",
    "PeerTimeout", "ChaosFault", "QuotaExceeded", "NoReplicaAvailable",
]


class ResilienceError(RuntimeError):
    """Base of every typed fault-tolerance result."""


class DeadlineExceeded(ResilienceError):
    """The request's deadline passed before a lane could finish it.

    Carries the elapsed and budgeted milliseconds so clients can tune
    ``SERVING_DEADLINE_MS`` from the answers alone.
    """

    def __init__(self, elapsed_ms: float, budget_ms: float,
                 lane: Optional[str] = None):
        self.elapsed_ms = float(elapsed_ms)
        self.budget_ms = float(budget_ms)
        self.lane = lane
        where = f" at lane {lane!r}" if lane else ""
        super().__init__(
            f"deadline exceeded{where}: {self.elapsed_ms:.1f} ms elapsed "
            f"against a {self.budget_ms:.1f} ms budget")


class LoadShed(ResilienceError):
    """The request was shed by admission control (queue over watermark
    or at capacity) — the system chose to fail it fast rather than let
    every queued request miss its deadline."""

    def __init__(self, reason: str, lane: Optional[str] = None):
        self.reason = reason
        self.lane = lane
        where = f" from lane {lane!r}" if lane else ""
        super().__init__(f"request shed{where} ({reason})")


class QuotaExceeded(ResilienceError):
    """The tenant's token bucket is empty — cooperative backpressure.

    Unlike :class:`LoadShed` (the *system* is overloaded, back off with
    jitter), this answer means *this tenant* exceeded its provisioned
    rate; ``retry_after_s`` is the earliest time a retry can be
    admitted, computed from the bucket's refill rate, so a well-behaved
    client can pace itself instead of hammering the admission gate.
    """

    def __init__(self, tenant: str, retry_after_s: float):
        self.tenant = tenant
        self.retry_after_s = float(retry_after_s)
        super().__init__(
            f"tenant {tenant!r} over quota; retry after "
            f"{self.retry_after_s:.3f} s")


class LaneUnavailable(ResilienceError):
    """The target lane's circuit breaker is open and no failover path
    exists for this request."""

    def __init__(self, lane: str):
        self.lane = lane
        super().__init__(f"lane {lane!r} unavailable (breaker open, "
                         f"no failover path)")


class NoReplicaAvailable(ResilienceError):
    """The fleet router exhausted its bounded re-dispatch budget — every
    eligible replica was down, draining, or breaker-open.  Still an
    *answer*: the caller learns the fleet refused, it is never dropped.
    """

    def __init__(self, partition: int, attempts: int):
        self.partition = int(partition)
        self.attempts = int(attempts)
        super().__init__(
            f"no replica available for partition {self.partition} "
            f"after {self.attempts} dispatch attempt(s)")


class PeerTimeout(ResilienceError):
    """A cross-host exchange (dist feature / sampler all-to-all) timed
    out waiting on a peer shard."""

    def __init__(self, what: str = "exchange"):
        super().__init__(f"peer shard timed out during {what}")


class ChaosFault(ResilienceError):
    """Default exception injected by :mod:`.chaos` — distinguishable
    from every organic failure so a chaos test can assert its faults
    (and only its faults) propagated."""

    def __init__(self, point: str, hit: int):
        self.point = point
        self.hit = hit
        super().__init__(f"injected fault at {point!r} (hit #{hit})")
