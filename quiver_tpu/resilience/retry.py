"""Shared jittered-exponential-backoff policy.

Two retry loops grew independently — the dist sampler's one-shot
exchange retry and the circuit breaker's open→half-open probe delay —
each with its own hardcoded schedule.  :class:`Backoff` centralizes the
schedule; the call sites keep their own loop shapes (the sampler wants
"retry the collective N times", the breaker wants "how long until the
next probe is allowed").

``delay(attempt)`` is a pure function of ``(attempt, rng state)``:

    base_s * multiplier**attempt, capped at cap_s,
    then spread by ±jitter (a fraction of the delay)

With ``jitter=0`` the schedule is exactly deterministic — the breaker
uses that so scripted-clock tests stay exact.  With jitter, pass a
seeded ``random.Random`` for reproducible spreads (the unit tests pin
the sequence); the default RNG is a private instance so concurrent
callers never contend on (or perturb) the global ``random`` state.
"""

from __future__ import annotations

import random
import time
from typing import Callable, Optional, Tuple, Type

__all__ = ["Backoff", "retry_call"]


class Backoff:
    """Exponential backoff schedule with bounded multiplicative jitter."""

    def __init__(self, base_s: float, cap_s: Optional[float] = None,
                 multiplier: float = 2.0, jitter: float = 0.0,
                 rng: Optional[random.Random] = None):
        if base_s < 0:
            raise ValueError(f"base_s must be >= 0, got {base_s}")
        if multiplier < 1.0:
            raise ValueError(f"multiplier must be >= 1, got {multiplier}")
        if not 0.0 <= jitter < 1.0:
            raise ValueError(f"jitter must be in [0, 1), got {jitter}")
        self.base_s = float(base_s)
        self.cap_s = float(cap_s) if cap_s is not None else self.base_s * 64
        self.multiplier = float(multiplier)
        self.jitter = float(jitter)
        self._rng = rng if rng is not None else random.Random()

    def delay(self, attempt: int) -> float:
        """Seconds to wait after failed attempt ``attempt`` (0-based).
        Monotone nondecreasing in ``attempt`` up to the cap; never above
        ``cap_s * (1 + jitter)``."""
        d = min(self.base_s * self.multiplier ** max(int(attempt), 0),
                self.cap_s)
        if self.jitter:
            d *= 1.0 + self.jitter * (2.0 * self._rng.random() - 1.0)
        return max(d, 0.0)


def retry_call(fn: Callable, attempts: int = 2,
               backoff: Optional[Backoff] = None,
               retry_on: Tuple[Type[BaseException], ...] = (Exception,),
               sleep: Callable[[float], None] = time.sleep,
               on_retry: Optional[Callable[[int, BaseException],
                                           None]] = None):
    """Call ``fn()`` up to ``attempts`` times, sleeping the backoff
    delay between tries.  Only ``retry_on`` exceptions retry; anything
    else — and the last ``retry_on`` failure — propagates.  ``on_retry``
    fires before each re-attempt (metrics hooks), ``sleep`` is
    injectable so tests assert the schedule without waiting it."""
    if attempts < 1:
        raise ValueError(f"attempts must be >= 1, got {attempts}")
    for attempt in range(attempts):
        try:
            return fn()
        except retry_on as e:
            if attempt + 1 >= attempts:
                raise
            if on_retry is not None:
                on_retry(attempt, e)
            if backoff is not None:
                d = backoff.delay(attempt)
                if d > 0:
                    sleep(d)
