"""Per-lane circuit breakers (closed → open → half-open).

A lane that keeps failing should stop receiving traffic *before* every
queued request burns its deadline discovering the same fault.  The
breaker counts consecutive failures; at ``failure_threshold`` it opens
and :meth:`CircuitBreaker.allow` answers False (the server reroutes to
the failover lane instead).  After ``reset_timeout_s`` the next
``allow()`` transitions to half-open and admits ``half_open_probes``
probe requests: one success closes the breaker, one failure re-opens
it and restarts the timeout.

Half-open is gated to a **single in-flight probe**: the first ``allow``
after the timeout wins the probe slot; every concurrent caller sees the
breaker as still open until that probe resolves (``record_success`` /
``record_failure``).  Admitting every concurrent caller as a probe —
the original behaviour — stampedes a barely-recovered lane with the
exact burst that tripped it.  Consecutive probe failures also back off
the reset timeout exponentially (:class:`~.retry.Backoff`, capped at
8x), so a hard-down lane is probed ever more gently.

State is exported two ways: the gauge ``serving_breaker_state{lane}``
(0 closed, 1 half-open, 2 open) plus
``serving_breaker_transitions_total{lane, to}`` in the registry, and
``GET /debug/breakers`` serving :func:`breakers_status` over the
process-wide registry of live breakers.

The clock is injectable (``clock=time.monotonic``) so tests drive the
open → half-open timeout deterministically instead of sleeping.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional

from .. import telemetry
from ..telemetry import flightrec

__all__ = ["CircuitBreaker", "get_breaker", "breakers_status", "reset"]

_STATE_VALUES = {"closed": 0, "half_open": 1, "open": 2}


class CircuitBreaker:
    """One lane's failure-driven admission switch.

    Thread-safe: ``allow`` / ``record_*`` are called from every lane
    thread.  Construction registers the breaker under ``name`` in the
    process-wide registry (latest wins — a restarted server's breakers
    replace its predecessor's on the debug endpoint).
    """

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"

    _guarded_by = {"_failures": "_lock", "_state": "_lock",
                   "_opened_at": "_lock", "_probes": "_lock",
                   "_probe_inflight": "_lock", "_reopens": "_lock"}

    def __init__(self, name: str, failure_threshold: Optional[int] = None,
                 reset_timeout_s: Optional[float] = None,
                 half_open_probes: Optional[int] = None,
                 clock: Callable[[], float] = time.monotonic):
        from ..config import get_config

        cfg = get_config()
        self.name = name
        self.failure_threshold = int(
            failure_threshold if failure_threshold is not None
            else cfg.serving_breaker_failures)
        self.reset_timeout_s = float(
            reset_timeout_s if reset_timeout_s is not None
            else cfg.serving_breaker_reset_s)
        self.half_open_probes = int(
            half_open_probes if half_open_probes is not None
            else cfg.serving_breaker_probes)
        self._clock = clock
        self._lock = threading.Lock()
        self._state = self.CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self._probes = 0
        self._probe_inflight = False
        self._reopens = 0  # consecutive failed probes: backs off the timeout
        from .retry import Backoff

        # deterministic (jitter=0) so scripted-clock tests stay exact;
        # delay(0) == reset_timeout_s, doubling per consecutive reopen
        self._reopen_backoff = Backoff(self.reset_timeout_s,
                                       cap_s=self.reset_timeout_s * 8)
        telemetry.gauge("serving_breaker_state", lane=name).set(0)
        _register(self)

    def _current_timeout_s(self) -> float:
        """Caller holds ``_lock``: the open→half-open delay.  The first
        failed probe re-opens at the base timeout; each further
        consecutive failure doubles it (capped), so a hard-down lane is
        probed ever more gently."""
        return self._reopen_backoff.delay(max(self._reopens - 1, 0))

    # -- decisions ------------------------------------------------------
    def allow(self) -> bool:
        """May the caller send one request down this lane right now?"""
        with self._lock:
            if self._state == self.CLOSED:
                return True
            if self._state == self.OPEN:
                if self._clock() - self._opened_at < \
                        self._current_timeout_s():
                    return False
                self._transition(self.HALF_OPEN)
                self._probes = 0
                self._probe_inflight = False
            # half-open: exactly ONE probe in flight; sequential probes
            # up to half_open_probes, concurrent callers see open
            if (not self._probe_inflight
                    and self._probes < self.half_open_probes):
                self._probes += 1
                self._probe_inflight = True
                return True
            return False

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            self._probe_inflight = False
            self._reopens = 0
            if self._state == self.HALF_OPEN:
                self._transition(self.CLOSED)

    def record_failure(self) -> None:
        with self._lock:
            if self._state == self.HALF_OPEN:
                # the probe failed: back to open, restart the (now
                # backed-off) timeout
                self._probe_inflight = False
                self._reopens += 1
                self._opened_at = self._clock()
                self._transition(self.OPEN)
                return
            self._failures += 1
            if (self._state == self.CLOSED
                    and self._failures >= self.failure_threshold):
                self._opened_at = self._clock()
                self._transition(self.OPEN)

    def _transition(self, to: str) -> None:
        """Caller holds ``_lock``.  Metrics + flight-recorder breadcrumb
        (the event lands on whatever request's trace is active — the one
        whose failure tripped the breaker)."""
        # quiverlint: ignore[QT003] -- every caller (allow /
        # record_success / record_failure) holds _lock; the guard is
        # real, just not lexical in this helper
        self._state = to
        telemetry.gauge("serving_breaker_state",
                        lane=self.name).set(_STATE_VALUES[to])
        telemetry.counter("serving_breaker_transitions_total",
                          lane=self.name, to=to).inc()
        if flightrec.tracing():
            flightrec.event("breaker", {"lane": self.name, "to": to})

    # -- read side ------------------------------------------------------
    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def status(self) -> dict:
        with self._lock:
            st = {
                "lane": self.name,
                "state": self._state,
                "failures": self._failures,
                "failure_threshold": self.failure_threshold,
                "reset_timeout_s": self.reset_timeout_s,
                "half_open_probes": self.half_open_probes,
            }
            if self._state != self.CLOSED:
                st["open_age_s"] = round(
                    max(self._clock() - self._opened_at, 0.0), 3)
                st["effective_reset_timeout_s"] = round(
                    self._current_timeout_s(), 3)
            if self._state == self.HALF_OPEN:
                st["probe_inflight"] = self._probe_inflight
        return st


# -- process-wide registry (feeds GET /debug/breakers) ------------------
_BREAKERS: Dict[str, CircuitBreaker] = {}
_registry_lock = threading.Lock()


def _register(br: CircuitBreaker) -> None:
    with _registry_lock:
        _BREAKERS[br.name] = br


def get_breaker(name: str, **kwargs) -> CircuitBreaker:
    """The registered breaker for ``name``, created on first touch
    (``kwargs`` apply only then)."""
    with _registry_lock:
        br = _BREAKERS.get(name)
    if br is None:
        br = CircuitBreaker(name, **kwargs)  # __init__ registers
    return br


def breakers_status() -> dict:
    """JSON view for ``GET /debug/breakers``."""
    with _registry_lock:
        brs = sorted(_BREAKERS.values(), key=lambda b: b.name)
    return {"breakers": [b.status() for b in brs]}


def reset() -> None:
    """Drop every registered breaker (tests)."""
    with _registry_lock:
        _BREAKERS.clear()
