"""BoundedLane — a shedding, priority-aware stage queue.

Drop-in for the ``queue.Queue`` subset the serving pipeline uses
(``put`` / ``get`` / ``get_nowait`` / ``qsize`` / ``empty``), plus
admission control:

  * **capacity** — the lane never holds more than ``maxsize`` requests;
    at capacity the lowest-priority request loses (the arrival, unless
    a strictly lower-priority request is queued to displace).
  * **watermarks with hysteresis** — crossing ``high`` (a fraction of
    capacity) engages shedding mode, which persists until depth drains
    below ``low``; while engaged, arrivals are shed unless they can
    displace lower-priority queued work.  Shedding *early* keeps the
    queue-wait of admitted requests bounded instead of letting every
    request age toward its deadline.
  * **deadline laziness** — an expired request found at ``get`` time is
    shed on the spot (reason ``deadline``) rather than handed to a lane
    that would do dead work.

Sheds go through :func:`quiver_tpu.resilience.deadline.shed`: metric,
flight record, typed answer on ``result_queue``.  Without a result
queue the lane admits-or-forwards but never silently drops — control
items (the ``_STOP`` sentinel and anything that is not a request) are
always admitted and never shed.
"""

from __future__ import annotations

import queue as _queue
import threading
import time
from typing import List, Optional

from .deadline import shed

__all__ = ["BoundedLane"]


def _req_of(item):
    """The ServingRequest inside ``item`` (requests travel bare on the
    batcher lanes and as ``(req, batch, dt)`` on the sampled lane)."""
    if isinstance(item, tuple) and item:
        item = item[0]
    return item if hasattr(item, "t_enqueue") else None


class BoundedLane:
    """Bounded, watermark-shedding queue for one pipeline lane."""

    _guarded_by = {"_items": "_cv", "_shedding": "_cv"}

    def __init__(self, name: str, maxsize: Optional[int] = None,
                 high: Optional[float] = None, low: Optional[float] = None,
                 result_queue=None):
        from ..config import get_config

        cfg = get_config()
        self.name = name
        self.maxsize = int(maxsize if maxsize is not None
                           else cfg.serving_queue_depth)
        if self.maxsize <= 0:
            raise ValueError(f"BoundedLane needs maxsize >= 1, got "
                             f"{self.maxsize}")
        high = float(high if high is not None
                     else cfg.serving_queue_high_watermark)
        low = float(low if low is not None
                    else cfg.serving_queue_low_watermark)
        if not 0.0 < low <= high <= 1.0:
            raise ValueError(f"watermarks need 0 < low <= high <= 1, got "
                             f"low={low} high={high}")
        self.high = max(int(self.maxsize * high), 1)
        self.low = max(int(self.maxsize * low), 0)
        self.result_queue = result_queue
        self._cv = threading.Condition()
        self._items: List[object] = []
        self._shedding = False

    # -- producer side --------------------------------------------------
    def put(self, item, block: bool = True,
            timeout: Optional[float] = None) -> None:
        """Admit, displace, or shed.  Control items always enqueue.
        ``block``/``timeout`` are accepted for queue.Queue compatibility
        but never block: at capacity this lane sheds instead."""
        req = _req_of(item)
        with self._cv:
            if req is None:  # control item (_STOP): always through
                self._items.append(item)
                self._cv.notify()
                return
            depth = len(self._items)
            if self._shedding and depth < self.low:
                self._shedding = False
            if depth >= self.high:
                self._shedding = True
            if not self._shedding and depth < self.maxsize:
                self._items.append(item)
                self._cv.notify()
                return
            # shedding mode (or hard-full): lowest priority loses
            reason = "overflow" if depth >= self.maxsize else "watermark"
            vi = self._victim_index(req)
            if vi is None:
                victim_item = item  # arrival is the lowest priority
            else:
                victim_item = self._items.pop(vi)
                self._items.append(item)
                self._cv.notify()
        victim = _req_of(victim_item)
        if self.result_queue is None:
            # nobody to answer: a shed here would be a silent drop, so
            # admit past the watermark instead (degenerates to the old
            # unbounded queue.Queue behaviour — wire a result_queue to
            # get admission control)
            with self._cv:
                self._items.append(victim_item)
                self._cv.notify()
            return
        shed(victim, self.result_queue, self.name, reason)

    def _victim_index(self, incoming) -> Optional[int]:
        """Index of the oldest queued request with priority strictly
        below ``incoming``'s (None: the incoming request is the victim).
        Caller holds ``_cv``."""
        inc_pri = getattr(incoming, "priority", 0)
        best_i, best_pri = None, inc_pri
        for i, it in enumerate(self._items):
            r = _req_of(it)
            if r is None:
                continue
            pri = getattr(r, "priority", 0)
            if pri < best_pri:
                best_i, best_pri = i, pri
        return best_i

    # -- consumer side --------------------------------------------------
    def get(self, block: bool = True, timeout: Optional[float] = None):
        """Pop the oldest item; expired requests are shed here (when
        answerable) instead of being handed to the lane."""
        deadline = (time.monotonic() + timeout) if timeout is not None \
            else None
        with self._cv:
            while True:
                while not self._items:
                    if not block:
                        raise _queue.Empty
                    if deadline is None:
                        self._cv.wait()
                    else:
                        left = deadline - time.monotonic()
                        if left <= 0 or not self._cv.wait(left):
                            if not self._items:
                                raise _queue.Empty
                    continue
                item = self._items.pop(0)
                if len(self._items) < self.low:
                    self._shedding = False
                req = _req_of(item)
                if (req is not None and self.result_queue is not None
                        and req.deadline is not None
                        and time.perf_counter() >= req.deadline):
                    shed(req, self.result_queue, self.name, "deadline")
                    continue
                return item

    def get_nowait(self):
        return self.get(block=False)

    def qsize(self) -> int:
        with self._cv:
            return len(self._items)

    def empty(self) -> bool:
        return self.qsize() == 0

    @property
    def shedding(self) -> bool:
        with self._cv:
            return self._shedding
