"""BoundedLane — a shedding, priority-aware stage queue.

Drop-in for the ``queue.Queue`` subset the serving pipeline uses
(``put`` / ``get`` / ``get_nowait`` / ``qsize`` / ``empty``), plus
admission control:

  * **capacity** — the lane never holds more than ``maxsize`` requests;
    at capacity the lowest-priority request loses (the arrival, unless
    a strictly lower-priority request is queued to displace).
  * **watermarks with hysteresis** — crossing ``high`` (a fraction of
    capacity) engages shedding mode, which persists until depth drains
    below ``low``; while engaged, arrivals are shed unless they can
    displace lower-priority queued work.  Shedding *early* keeps the
    queue-wait of admitted requests bounded instead of letting every
    request age toward its deadline.
  * **deadline laziness** — an expired request found at ``get`` time is
    shed on the spot (reason ``deadline``) rather than handed to a lane
    that would do dead work.

Sheds go through :func:`quiver_tpu.resilience.deadline.shed`: metric,
flight record, typed answer on ``result_queue``.  Without a result
queue the lane admits-or-forwards but never silently drops — control
items (the ``_STOP`` sentinel and anything that is not a request) are
always admitted and never shed.

:class:`WeightedFairLane` keeps all of the above (capacity, watermark
hysteresis, priority-ordered victims, lazy deadline sheds) but replaces
the single FIFO with **deficit-weighted round-robin across per-tenant
sub-queues**: each tenant class owns a deque, classes take turns, and a
class may dequeue while its deficit counter covers the head request's
cost (``len(ids)``), refilled by ``quantum * weight`` per round — so a
burst in one tenant delays only that tenant's queue, never another's
admitted requests.  Control items still bypass admission and are served
in global arrival order (a checkpoint barrier must run after every
update enqueued before it — fairness must not reorder control flow).
"""

from __future__ import annotations

import queue as _queue
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from .deadline import shed

__all__ = ["BoundedLane", "WeightedFairLane"]


def _req_of(item):
    """The ServingRequest inside ``item`` (requests travel bare on the
    batcher lanes and as ``(req, batch, dt)`` on the sampled lane)."""
    if isinstance(item, tuple) and item:
        item = item[0]
    return item if hasattr(item, "t_enqueue") else None


class BoundedLane:
    """Bounded, watermark-shedding queue for one pipeline lane."""

    # _shedding is rebound lexically under the condition; the storage
    # internals are mutated through the _push/_pop hooks below, whose
    # callers-hold-_cv contract is the requires-lock directives (QT008
    # verifies every resolved call site holds it)
    _guarded_by = {"_shedding": "_cv"}

    def __init__(self, name: str, maxsize: Optional[int] = None,
                 high: Optional[float] = None, low: Optional[float] = None,
                 result_queue=None):
        from ..config import get_config

        cfg = get_config()
        self.name = name
        self.maxsize = int(maxsize if maxsize is not None
                           else cfg.serving_queue_depth)
        if self.maxsize <= 0:
            raise ValueError(f"BoundedLane needs maxsize >= 1, got "
                             f"{self.maxsize}")
        high = float(high if high is not None
                     else cfg.serving_queue_high_watermark)
        low = float(low if low is not None
                    else cfg.serving_queue_low_watermark)
        if not 0.0 < low <= high <= 1.0:
            raise ValueError(f"watermarks need 0 < low <= high <= 1, got "
                             f"low={low} high={high}")
        self.high = max(int(self.maxsize * high), 1)
        self.low = max(int(self.maxsize * low), 0)
        self.result_queue = result_queue
        self._cv = threading.Condition()
        self._items: List[object] = []
        self._shedding = False

    # -- storage hooks (WeightedFairLane overrides these; callers hold
    # ``_cv``).  The base lane is one FIFO list: control items and
    # requests interleave in arrival order.
    # quiverlint: requires-lock[BoundedLane._cv]
    def _push(self, item) -> None:
        self._items.append(item)

    # quiverlint: requires-lock[BoundedLane._cv]
    def _push_control(self, item) -> None:
        self._items.append(item)

    # quiverlint: requires-lock[BoundedLane._cv]
    def _pop(self):
        return self._items.pop(0)

    def _depth(self) -> int:
        return len(self._items)

    def _has_items(self) -> bool:
        return bool(self._items)

    # quiverlint: requires-lock[BoundedLane._cv]
    def _take_victim(self, incoming):
        """Remove and return the oldest queued request with priority
        strictly below ``incoming``'s, or None (the incoming request is
        the victim)."""
        inc_pri = getattr(incoming, "priority", 0)
        best_i, best_pri = None, inc_pri
        for i, it in enumerate(self._items):
            r = _req_of(it)
            if r is None:
                continue
            pri = getattr(r, "priority", 0)
            if pri < best_pri:
                best_i, best_pri = i, pri
        if best_i is None:
            return None
        return self._items.pop(best_i)

    # -- producer side --------------------------------------------------
    def put(self, item, block: bool = True,
            timeout: Optional[float] = None) -> None:
        """Admit, displace, or shed.  Control items always enqueue.
        ``block``/``timeout`` are accepted for queue.Queue compatibility
        but never block: at capacity this lane sheds instead."""
        req = _req_of(item)
        with self._cv:
            if req is None:  # control item (_STOP): always through
                self._push_control(item)
                self._cv.notify()
                return
            depth = self._depth()
            if self._shedding and depth < self.low:
                self._shedding = False
            if depth >= self.high:
                self._shedding = True
            if not self._shedding and depth < self.maxsize:
                self._push(item)
                self._cv.notify()
                return
            # shedding mode (or hard-full): lowest priority loses
            reason = "overflow" if depth >= self.maxsize else "watermark"
            victim_item = self._take_victim(req)
            if victim_item is None:
                victim_item = item  # arrival is the lowest priority
            else:
                self._push(item)
                self._cv.notify()
        victim = _req_of(victim_item)
        if self.result_queue is None:
            # nobody to answer: a shed here would be a silent drop, so
            # admit past the watermark instead (degenerates to the old
            # unbounded queue.Queue behaviour — wire a result_queue to
            # get admission control)
            with self._cv:
                self._push(victim_item)
                self._cv.notify()
            return
        shed(victim, self.result_queue, self.name, reason)

    # -- consumer side --------------------------------------------------
    def get(self, block: bool = True, timeout: Optional[float] = None):
        """Pop the oldest item; expired requests are shed here (when
        answerable) instead of being handed to the lane."""
        deadline = (time.monotonic() + timeout) if timeout is not None \
            else None
        with self._cv:
            while True:
                while not self._has_items():
                    if not block:
                        raise _queue.Empty
                    if deadline is None:
                        self._cv.wait()
                    else:
                        left = deadline - time.monotonic()
                        if left <= 0 or not self._cv.wait(left):
                            if not self._has_items():
                                raise _queue.Empty
                    continue
                item = self._pop()
                if self._depth() < self.low:
                    self._shedding = False
                req = _req_of(item)
                if (req is not None and self.result_queue is not None
                        and req.deadline is not None
                        and time.perf_counter() >= req.deadline):
                    shed(req, self.result_queue, self.name, "deadline")
                    continue
                return item

    def get_nowait(self):
        return self.get(block=False)

    def qsize(self) -> int:
        with self._cv:
            return self._depth()

    def empty(self) -> bool:
        return self.qsize() == 0

    @property
    def shedding(self) -> bool:
        with self._cv:
            return self._shedding


class WeightedFairLane(BoundedLane):
    """Deficit-weighted round-robin lane over per-tenant sub-queues.

    ``weights`` maps tenant-class name → scheduling weight (from
    :meth:`~quiver_tpu.resilience.qos.QoSController.weights`); requests
    are classed by their ``tenant_class`` stamp (set by QoS admission),
    unstamped requests landing in ``default_class``.  ``quantum`` is
    the per-round deficit refill in request-cost units (a request costs
    ``max(len(ids), 1)``) per unit weight.

    DRR (Shreedhar & Varghese): each non-empty class takes a turn; on
    its turn it dequeues head requests while its deficit covers their
    cost, then the residual deficit carries to its next turn.  An empty
    class forfeits its deficit (no banking idle capacity).  Work
    complexity is O(1) amortized per dequeue — one rotation step per
    refill.

    Victim selection for watermark/overflow sheds scans every sub-queue
    for the globally lowest-priority, oldest request, so shedding lands
    on the lowest tenant class first no matter which class's burst
    crossed the watermark.

    Control items never shed AND never reorder: they are served only
    once every request that arrived before them has left the lane, so a
    ``CheckpointBarrier`` still partitions the update stream exactly.
    """

    # all mutable state lives behind the inherited _push/_pop hook
    # surface; the callers-hold-_cv contract is carried by the
    # requires-lock directives on the hooks (QT008 checks call sites),
    # so there is no lexical _guarded_by map here

    def __init__(self, name: str, weights: Dict[str, float],
                 default_class: Optional[str] = None,
                 quantum: Optional[int] = None, **kwargs):
        super().__init__(name, **kwargs)
        from ..config import get_config

        if not weights:
            raise ValueError("WeightedFairLane needs at least one class")
        self.weights = {k: max(float(v), 1e-3) for k, v in weights.items()}
        self.default_class = (default_class if default_class is not None
                              else next(iter(self.weights)))
        if self.default_class not in self.weights:
            self.weights[self.default_class] = 1.0
        self.quantum = int(quantum if quantum is not None
                           else get_config().qos_quantum)
        if self.quantum < 1:
            raise ValueError(f"quantum must be >= 1, got {self.quantum}")
        # per-class deques hold (arrival_seq, item); _active is the DRR
        # rotation of class names with queued work
        self._subq: Dict[str, deque] = {}
        self._ctrl: deque = deque()
        self._active: deque = deque()
        self._deficit: Dict[str, float] = {}
        self._n = 0
        self._seq = 0

    # -- classing ------------------------------------------------------
    def _class_of(self, item) -> str:
        req = _req_of(item)
        cls = getattr(req, "tenant_class", None) if req is not None else None
        return cls if cls in self.weights else self.default_class

    @staticmethod
    def _cost_of(item) -> float:
        req = _req_of(item)
        ids = getattr(req, "ids", None) if req is not None else None
        try:
            return float(max(len(ids), 1)) if ids is not None else 1.0
        except TypeError:
            return 1.0

    # -- storage hooks (caller holds ``_cv``) --------------------------
    # quiverlint: requires-lock[BoundedLane._cv]
    def _push(self, item) -> None:
        cls = self._class_of(item)
        q = self._subq.get(cls)
        if q is None:
            q = self._subq[cls] = deque()
        if not q:
            self._active.append(cls)
            self._deficit[cls] = 0.0
        self._seq += 1
        q.append((self._seq, item))
        self._n += 1

    # quiverlint: requires-lock[BoundedLane._cv]
    def _push_control(self, item) -> None:
        self._seq += 1
        self._ctrl.append((self._seq, item))

    def _depth(self) -> int:
        return self._n

    def _has_items(self) -> bool:
        return self._n > 0 or bool(self._ctrl)

    def _oldest_req_seq(self) -> float:
        return min((q[0][0] for q in self._subq.values() if q),
                   default=float("inf"))

    # quiverlint: requires-lock[BoundedLane._cv]
    def _pop(self):
        # control items: arrival-order fence — serve one only when no
        # earlier-arrived request is still queued
        if self._ctrl and self._ctrl[0][0] < self._oldest_req_seq():
            return self._ctrl.popleft()[1]
        # DRR scan: terminates because every full rotation refills each
        # active class by quantum*weight > 0 while costs are bounded by
        # the top serving bucket
        while True:
            cls = self._active[0]
            q = self._subq.get(cls)
            if not q:
                self._active.popleft()
                self._deficit.pop(cls, None)
                continue
            cost = self._cost_of(q[0][1])
            if self._deficit[cls] >= cost:
                self._deficit[cls] -= cost
                item = q.popleft()[1]
                self._n -= 1
                if not q:
                    self._active.popleft()
                    self._deficit.pop(cls, None)
                return item
            self._deficit[cls] += self.quantum * self.weights.get(
                cls, self.weights[self.default_class])
            self._active.rotate(-1)

    # quiverlint: requires-lock[BoundedLane._cv]
    def _take_victim(self, incoming):
        inc_pri = getattr(incoming, "priority", 0)
        best, best_key = None, (float("inf"), float("inf"))
        for cls, q in self._subq.items():
            for i, (seq, it) in enumerate(q):
                r = _req_of(it)
                if r is None:
                    continue
                pri = getattr(r, "priority", 0)
                if pri >= inc_pri:  # only strictly-lower priority loses
                    continue
                key = (pri, seq)
                if key < best_key:
                    best, best_key = (cls, i), key
        if best is None:
            return None
        cls, i = best
        q = self._subq[cls]
        _, item = q[i]
        del q[i]
        self._n -= 1
        if not q and cls in self._deficit:
            self._active.remove(cls)
            self._deficit.pop(cls, None)
        return item

    # -- read side -----------------------------------------------------
    def class_depths(self) -> Dict[str, int]:
        """Per-class queued counts (for /debug/qos and tests)."""
        with self._cv:
            return {cls: len(q) for cls, q in self._subq.items() if q}
