"""Per-request deadlines and the shared shed path.

A deadline is an **absolute** ``time.perf_counter`` value computed at
enqueue (``t_enqueue + config.serving_deadline_ms / 1e3``), carried on
the :class:`~quiver_tpu.serving.ServingRequest`, and checked at every
stage boundary — batcher route, lane admission, sampler dequeue, server
dequeue, and per coalesced member.  A check is two floats and a compare;
with ``serving_deadline_ms = 0`` (the default) the deadline is ``None``
and every check short-circuits on one ``is None``.

Shedding is centralized in :func:`shed` so every path produces the same
artifacts: ``serving_shed_total{reason, lane}``, a ``shed`` event plus a
retained flight record (status ``shed``), and a typed answer on the
result queue — :class:`~.errors.DeadlineExceeded` for ``reason ==
"deadline"``, :class:`~.errors.LoadShed` otherwise.  A request that
cannot be answered (no result queue in scope) is never shed here; it
flows downstream to a stage that can answer it.
"""

from __future__ import annotations

import contextvars
import time
from typing import Optional, Tuple

from .. import telemetry
from ..telemetry import flightrec
from .errors import DeadlineExceeded, LoadShed

__all__ = ["deadline_for", "shed", "shed_if_expired",
           "deadline_scope", "ambient_deadline", "check_ambient"]

# -- ambient deadline (contextvar) ---------------------------------------
# Serving loops install the in-flight batch's tightest deadline here so
# code they call into WITHOUT a request in hand — the dist feature's
# degraded local-rows gather, most importantly — can still refuse dead
# work.  Holds ``(deadline, t_start)`` or None; with deadlines disabled
# nothing is ever installed and ``check_ambient`` is one contextvar read.
_AMBIENT: "contextvars.ContextVar[Optional[Tuple[float, float]]]" = \
    contextvars.ContextVar("quiver_ambient_deadline", default=None)


class deadline_scope:
    """``with deadline_scope(deadline, t_start):`` — make a deadline
    ambient for the block.  ``deadline=None`` is a no-op scope, so call
    sites need no branch."""

    __slots__ = ("_deadline", "_t_start", "_token")

    def __init__(self, deadline: Optional[float],
                 t_start: Optional[float] = None):
        self._deadline = deadline
        self._t_start = t_start
        self._token = None

    def __enter__(self):
        if self._deadline is not None:
            t0 = self._t_start if self._t_start is not None \
                else time.perf_counter()
            self._token = _AMBIENT.set((self._deadline, t0))
        return self

    def __exit__(self, *exc):
        if self._token is not None:
            _AMBIENT.reset(self._token)
        return False


def ambient_deadline() -> Optional[float]:
    """The ambient absolute deadline, or None."""
    scope = _AMBIENT.get()
    return scope[0] if scope is not None else None


def check_ambient(lane: str) -> None:
    """Raise :class:`DeadlineExceeded` iff the ambient deadline has
    passed — the callee-side twin of :func:`shed_if_expired` for code
    paths that hold no request object (degraded dist lookups).  One
    contextvar read when no scope is installed."""
    scope = _AMBIENT.get()
    if scope is None:
        return
    deadline, t0 = scope
    now = time.perf_counter()
    if now < deadline:
        return
    raise DeadlineExceeded((now - t0) * 1e3, (deadline - t0) * 1e3,
                           lane=lane)


def deadline_for(t_enqueue: float,
                 deadline_ms: Optional[float] = None) -> Optional[float]:
    """Absolute deadline for a request enqueued at ``t_enqueue``
    (perf_counter seconds), or None when deadlines are disabled."""
    if deadline_ms is None:
        from ..config import get_config

        deadline_ms = get_config().serving_deadline_ms
    if not deadline_ms or deadline_ms <= 0:
        return None
    return t_enqueue + float(deadline_ms) / 1e3


def shed(req, result_queue, lane: str, reason: str) -> None:
    """Shed ``req`` unconditionally: tick the metric, retain the flight
    record, answer on ``result_queue`` (when one is in scope).

    The ``tenant`` label appears only on requests that passed QoS
    admission (which stamps the resolved class — an allowlisted name,
    so cardinality stays bounded); without QoS the metric keys are
    byte-identical to the pre-QoS ones."""
    now = time.perf_counter()
    tenant = getattr(req, "tenant_class", None)
    if tenant is not None:
        telemetry.counter("serving_shed_total", reason=reason, lane=lane,
                          tenant=tenant).inc()
    else:
        telemetry.counter("serving_shed_total", reason=reason,
                          lane=lane).inc()
    elapsed = max(now - req.t_enqueue, 0.0)
    if reason == "deadline":
        budget_s = (req.deadline - req.t_enqueue
                    if req.deadline is not None else 0.0)
        exc: Exception = DeadlineExceeded(elapsed * 1e3, budget_s * 1e3,
                                          lane=lane)
    else:
        exc = LoadShed(reason, lane=lane)
    tr = getattr(req, "trace", None)
    if tr is not None:
        tr.add("shed", {"reason": reason, "lane": lane})
        flightrec.get_recorder().finish(tr, elapsed, status="shed",
                                        lane=lane)
    if result_queue is not None:
        result_queue.put((req, exc))


def shed_if_expired(req, result_queue, lane: str) -> bool:
    """Shed ``req`` iff its deadline has passed AND it can be answered.

    Returns True when the caller must drop the request.  Without a
    result queue the request is forwarded instead — a shed that nobody
    hears is just a lost request.
    """
    dl = getattr(req, "deadline", None)
    if dl is None or result_queue is None:
        return False
    if time.perf_counter() < dl:
        return False
    shed(req, result_queue, lane, "deadline")
    return True
