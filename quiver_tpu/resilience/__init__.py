"""quiver_tpu.resilience — fault tolerance for the serving pipeline.

PR 4's SLO watchdog *detects* breaches; this package makes the system
*react* to them.  Four mechanisms, threaded through serving, loader,
prefetch, and dist (``docs/RESILIENCE.md``):

  * **deadlines** (:mod:`.deadline`) — every :class:`ServingRequest`
    carries an absolute deadline (``config.serving_deadline_ms``);
    each stage boundary sheds expired requests with a typed
    :class:`~quiver_tpu.resilience.errors.DeadlineExceeded` answer
    instead of letting them age silently in a queue.
  * **bounded queues + admission control** (:mod:`.lanes`) —
    :class:`BoundedLane` wraps the stage queues with capacity and
    high/low watermarks, shedding lowest-priority work first and
    ticking ``serving_shed_total{reason}``.
  * **circuit breaking + lane failover** (:mod:`.breaker`) — repeated
    device-lane failures trip a per-lane closed→open→half-open
    :class:`CircuitBreaker`; in-flight requests reroute to the CPU
    sampler lane, and ``DistFeature.lookup`` degrades to locally
    resolvable rows (``degraded=True``) on a peer-shard timeout.
  * **multi-tenant QoS + degradation ladder** (:mod:`.qos`,
    :class:`~.lanes.WeightedFairLane`) — per-tenant token-bucket
    admission (typed :class:`~.errors.QuotaExceeded` answers with a
    retry-after hint), deficit-weighted round-robin fair scheduling
    across tenant classes, and a reversible SLO-burn-driven brownout
    ladder (``serving_degradation_level``).  Off by default
    (``config.qos_enabled``); the hot path then pays one check.
  * **deterministic fault injection** (:mod:`.chaos`) — named
    injection points (``chaos.point("serving.device_lane")``) compile
    to one attribute read + None-check when no plan is installed, and
    replay byte-identically under a seeded :class:`ChaosPlan`.

Everything emits flight-recorder events and registry metrics (breaker
state gauge, shed / retry / degraded counters) so ``/debug/slo`` and
``/debug/breakers`` show remediation, not just breach.
"""

from __future__ import annotations

from .breaker import CircuitBreaker, breakers_status, get_breaker
from .chaos import ChaosPlan, point
from .deadline import (check_ambient, deadline_for, deadline_scope, shed,
                       shed_if_expired)
from .errors import (ChaosFault, DeadlineExceeded, LaneUnavailable,
                     LoadShed, PeerTimeout, QuotaExceeded, ResilienceError)
from .lanes import BoundedLane, WeightedFairLane
from .qos import (DegradationLadder, LadderStep, QoSController, TenantClass,
                  TokenBucket, get_qos, install_qos, qos_from_config,
                  qos_status, serving_ladder)
from .retry import Backoff, retry_call
from .shutdown import join_and_reap

__all__ = [
    "Backoff", "BoundedLane", "ChaosFault", "ChaosPlan", "CircuitBreaker",
    "DeadlineExceeded", "DegradationLadder", "LadderStep", "LaneUnavailable",
    "LoadShed", "PeerTimeout", "QoSController", "QuotaExceeded",
    "ResilienceError", "TenantClass", "TokenBucket", "WeightedFairLane",
    "breakers_status", "check_ambient", "deadline_for", "deadline_scope",
    "get_breaker", "get_qos", "install_qos", "join_and_reap", "point",
    "qos_from_config", "qos_status", "retry_call", "serving_ladder",
    "shed", "shed_if_expired",
]
