"""quiver_tpu.resilience — fault tolerance for the serving pipeline.

PR 4's SLO watchdog *detects* breaches; this package makes the system
*react* to them.  Four mechanisms, threaded through serving, loader,
prefetch, and dist (``docs/RESILIENCE.md``):

  * **deadlines** (:mod:`.deadline`) — every :class:`ServingRequest`
    carries an absolute deadline (``config.serving_deadline_ms``);
    each stage boundary sheds expired requests with a typed
    :class:`~quiver_tpu.resilience.errors.DeadlineExceeded` answer
    instead of letting them age silently in a queue.
  * **bounded queues + admission control** (:mod:`.lanes`) —
    :class:`BoundedLane` wraps the stage queues with capacity and
    high/low watermarks, shedding lowest-priority work first and
    ticking ``serving_shed_total{reason}``.
  * **circuit breaking + lane failover** (:mod:`.breaker`) — repeated
    device-lane failures trip a per-lane closed→open→half-open
    :class:`CircuitBreaker`; in-flight requests reroute to the CPU
    sampler lane, and ``DistFeature.lookup`` degrades to locally
    resolvable rows (``degraded=True``) on a peer-shard timeout.
  * **deterministic fault injection** (:mod:`.chaos`) — named
    injection points (``chaos.point("serving.device_lane")``) compile
    to one attribute read + None-check when no plan is installed, and
    replay byte-identically under a seeded :class:`ChaosPlan`.

Everything emits flight-recorder events and registry metrics (breaker
state gauge, shed / retry / degraded counters) so ``/debug/slo`` and
``/debug/breakers`` show remediation, not just breach.
"""

from __future__ import annotations

from .breaker import CircuitBreaker, breakers_status, get_breaker
from .chaos import ChaosPlan, point
from .deadline import deadline_for, shed, shed_if_expired
from .errors import (ChaosFault, DeadlineExceeded, LaneUnavailable,
                     LoadShed, PeerTimeout, ResilienceError)
from .lanes import BoundedLane
from .shutdown import join_and_reap

__all__ = [
    "BoundedLane", "ChaosFault", "ChaosPlan", "CircuitBreaker",
    "DeadlineExceeded", "LaneUnavailable", "LoadShed", "PeerTimeout",
    "ResilienceError", "breakers_status", "deadline_for", "get_breaker",
    "join_and_reap", "point", "shed", "shed_if_expired",
]
