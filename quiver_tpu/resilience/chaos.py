"""Deterministic fault injection for the serving pipeline.

Pipeline stages declare **named injection points** once, at module
scope::

    _CHAOS_DEVICE = chaos.point("serving.device_lane")

and fire them on the hot path with a bare call — ``_CHAOS_DEVICE()``.
With no plan installed (production, and every non-chaos test) a fire is
one module-global read and a None check: no locks, no clocks, no
allocations, nothing jittable anywhere near it (the retrace-budget
guard in ``tests/test_resilience.py`` holds this to zero new jit
builds).

A chaos test installs a seeded :class:`ChaosPlan`::

    plan = ChaosPlan(seed=7).fail("serving.device_lane", times=2)
    with chaos.active(plan):
        ...drive traffic...
    assert plan.log() == expected   # byte-identical on every replay

Determinism: a rule's probabilistic decisions hash ``(seed, point,
hit_index)`` — no wall clock, no global RNG — so the same plan over the
same request sequence takes the same decisions, raises the same faults,
and leaves identical shed / retry / degraded counters behind.  Every
fired action ticks ``chaos_injections_total{point}`` and lands in the
plan's replay log.
"""

from __future__ import annotations

import hashlib
import threading
import time
from contextlib import contextmanager
from typing import Dict, List, Optional, Tuple

from .errors import ChaosFault

__all__ = ["ChaosPlan", "InjectionPoint", "point", "install", "uninstall",
           "active", "current_plan"]


def _hash01(seed: int, name: str, idx: int) -> float:
    """Uniform [0, 1) from (seed, point, hit) — the only randomness
    source, so replays are exact."""
    h = hashlib.blake2b(f"{seed}:{name}:{idx}".encode(), digest_size=8)
    return int.from_bytes(h.digest(), "big") / 2.0 ** 64


class _Rule:
    """One fault rule: fire on hits ``after <= idx`` matching ``every``
    / ``rate``, at most ``times`` times (None = unbounded)."""

    __slots__ = ("exc", "times", "after", "every", "rate", "delay_s",
                 "fired")

    def __init__(self, exc=None, times: Optional[int] = 1, after: int = 0,
                 every: Optional[int] = None, rate: Optional[float] = None,
                 delay_s: float = 0.0):
        self.exc = exc
        self.times = times
        self.after = int(after)
        self.every = every
        self.rate = rate
        self.delay_s = float(delay_s)
        self.fired = 0

    def matches(self, seed: int, name: str, idx: int) -> bool:
        if idx < self.after:
            return False
        if self.times is not None and self.fired >= self.times:
            return False
        if self.every is not None and (idx - self.after) % self.every:
            return False
        if self.rate is not None and _hash01(seed, name, idx) >= self.rate:
            return False
        return True


class ChaosPlan:
    """A seeded script of faults, keyed by injection-point name."""

    _guarded_by = {"_rules": "_lock", "_hits": "_lock", "_log": "_lock"}

    def __init__(self, seed: int = 0):
        self.seed = int(seed)
        self._lock = threading.Lock()
        self._rules: Dict[str, List[_Rule]] = {}
        self._hits: Dict[str, int] = {}
        self._log: List[Tuple[str, int, str]] = []

    def fail(self, point_name: str, exc=None, times: Optional[int] = 1,
             after: int = 0, every: Optional[int] = None,
             rate: Optional[float] = None) -> "ChaosPlan":
        """Raise at ``point_name``: hits ``after, after+1, ...`` matching
        ``every``/``rate``, at most ``times`` total (None = forever).
        ``exc`` may be an exception instance, a class, or None for the
        default :class:`ChaosFault`."""
        with self._lock:
            self._rules.setdefault(point_name, []).append(
                _Rule(exc=exc, times=times, after=after, every=every,
                      rate=rate))
        return self

    def delay(self, point_name: str, delay_s: float,
              times: Optional[int] = 1, after: int = 0,
              every: Optional[int] = None,
              rate: Optional[float] = None) -> "ChaosPlan":
        """Sleep ``delay_s`` at ``point_name`` (same selectors as
        :meth:`fail`) — models a stall rather than a crash."""
        with self._lock:
            self._rules.setdefault(point_name, []).append(
                _Rule(exc=None, times=times, after=after, every=every,
                      rate=rate, delay_s=delay_s))
        return self

    def fire(self, name: str) -> None:
        """One hit of point ``name``: take the scripted decision, log
        it, then act (sleep and/or raise) outside the lock."""
        delay_s = 0.0
        exc: Optional[BaseException] = None
        with self._lock:
            idx = self._hits.get(name, 0)
            self._hits[name] = idx + 1
            action = "pass"
            for rule in self._rules.get(name, ()):
                if not rule.matches(self.seed, name, idx):
                    continue
                rule.fired += 1
                if rule.delay_s:
                    delay_s += rule.delay_s
                    action = f"delay:{rule.delay_s:g}"
                if rule.exc is not None or rule.delay_s == 0.0:
                    e = rule.exc
                    if e is None:
                        e = ChaosFault(name, idx)
                    elif isinstance(e, type):
                        e = e()
                    exc = e
                    action = f"raise:{type(e).__name__}"
                break  # first matching rule wins, like iptables
            self._log.append((name, idx, action))
        if action != "pass":
            from .. import telemetry
            from ..telemetry import timeline

            telemetry.counter("chaos_injections_total", point=name).inc()
            if timeline._ON:
                timeline.emit("chaos.inject", cat="chaos",
                              attrs={"point": name, "hit": idx,
                                     "action": action})
        if delay_s:
            time.sleep(delay_s)
        if exc is not None:
            raise exc

    def log(self) -> List[Tuple[str, int, str]]:
        """The replay log: ``(point, hit_index, action)`` per hit, in
        firing order.  Identical across runs of the same plan over the
        same request sequence — the determinism contract chaos tests
        assert on."""
        with self._lock:
            return list(self._log)

    def hits(self, name: str) -> int:
        with self._lock:
            return self._hits.get(name, 0)


class InjectionPoint:
    """A named chaos call site.  Calling it is free when no plan is
    installed — the production steady state."""

    __slots__ = ("name",)

    def __init__(self, name: str):
        self.name = name

    def __call__(self) -> None:
        plan = _PLAN
        if plan is None:
            return
        plan.fire(self.name)

    def __repr__(self):
        return f"InjectionPoint({self.name!r})"


_PLAN: Optional[ChaosPlan] = None
_POINTS: Dict[str, InjectionPoint] = {}
_points_lock = threading.Lock()


def point(name: str) -> InjectionPoint:
    """The (cached) injection point for ``name`` — call once at module
    scope, fire the returned object on the hot path."""
    p = _POINTS.get(name)
    if p is None:
        with _points_lock:
            p = _POINTS.setdefault(name, InjectionPoint(name))
    return p


# fleet fault-injection points, registered eagerly so a chaos plan can
# arm them by name before any fleet module is imported — a seeded run
# replays byte-identically whether the plan or the fleet loads first
FLEET_POINTS = ("fleet.route", "fleet.ship", "fleet.join",
                "fleet.serve", "fleet.election.claim",
                "fleet.walstream.send", "fleet.walstream.recv")
for _name in FLEET_POINTS:
    point(_name)
del _name


def install(plan: ChaosPlan) -> ChaosPlan:
    """Arm ``plan`` process-wide.  One plan at a time, by design: chaos
    scripts own the process while they run."""
    global _PLAN
    _PLAN = plan
    return plan


def uninstall() -> None:
    global _PLAN
    _PLAN = None


def current_plan() -> Optional[ChaosPlan]:
    return _PLAN


@contextmanager
def active(plan: ChaosPlan):
    """``with chaos.active(plan): ...`` — install for the block, always
    disarm on the way out (a leaked plan would fail every later test)."""
    install(plan)
    try:
        yield plan
    finally:
        uninstall()
