"""Multi-tenant QoS: admission quotas, fair-share classes, and the
adaptive degradation ladder.

Three cooperating pieces (docs/RESILIENCE.md "QoS & degradation
ladder"):

  * **Tenant classes + token buckets** — operators declare classes in
    ``config.qos_tenants`` (``"gold:rate=200,burst=50,weight=8,
    priority=3;..."``); each class gets a token bucket (``rate``
    tokens/s refill, ``burst`` capacity).  :meth:`QoSController.admit`
    is the single admission gate: over-quota requests are answered with
    a typed :class:`~.errors.QuotaExceeded` carrying the earliest
    useful retry time — cooperative backpressure, not a silent drop.
    The class list doubles as the **tenant-label allowlist**: metrics
    only ever carry declared class names (unlabeled or unknown tenants
    map to ``qos_default_tenant``), so label cardinality is bounded by
    config, not by whatever clients send.
  * **Weighted-fair scheduling** — admission stamps the resolved class
    on the request (``tenant_class``) and lifts its priority to the
    class priority; :class:`~.lanes.WeightedFairLane` then drains
    per-class sub-queues by deficit round-robin on the class weights,
    and the priority stamp makes watermark shedding land on the lowest
    class first.
  * **Degradation ladder** — :class:`DegradationLadder` listens to
    SLOWatchdog evaluations and, under ``breach_ticks`` consecutive
    breaching ticks, steps down one reversible level at a time (shrink
    sample fanout → pause coldcache admission writes → route the floor
    class to the CPU lane → shed the floor class at admission);
    ``recover_ticks`` consecutive healthy ticks step back up.  Every
    transition moves the ``serving_degradation_level`` gauge and is
    kept in a bounded history for ``GET /debug/qos``.

Disabled (``config.qos_enabled = False``, the default) none of this is
constructed and the serving hot path pays one ``is None`` attribute
check — the A/B in bench.py's ``serving_qos`` section pins that.

QT003: controller buckets and ladder state are touched from stream
threads, the device loop, and the watchdog thread; all mutation holds
the declared locks.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

from .. import telemetry
from ..telemetry import flightrec
from ..telemetry import timeline as _timeline
from .deadline import shed
from .errors import QuotaExceeded

__all__ = [
    "TenantClass", "TokenBucket", "QoSController", "LadderStep",
    "DegradationLadder", "parse_tenant_spec", "serving_ladder",
    "install_qos", "get_qos", "qos_from_config", "qos_status", "reset",
]


@dataclass(frozen=True)
class TenantClass:
    """One declared tenant class (the unit of quota, weight, and
    shed ordering).  ``rate`` is tokens (requests) per second, ``burst``
    the bucket capacity, ``weight`` the fair-share scheduling weight,
    ``priority`` the shed ordering (higher survives longer)."""

    name: str
    rate: float = 100.0
    burst: float = 25.0
    weight: float = 1.0
    priority: int = 0


def parse_tenant_spec(spec: str) -> Dict[str, TenantClass]:
    """Parse ``config.qos_tenants``: ``;``-separated
    ``name:key=value,...`` entries.  Raises on malformed entries — a
    typo'd quota silently defaulting would be an outage, not a
    convenience."""
    classes: Dict[str, TenantClass] = {}
    for entry in (spec or "").split(";"):
        entry = entry.strip()
        if not entry:
            continue
        name, _, body = entry.partition(":")
        name = name.strip()
        if not name:
            raise ValueError(f"tenant entry {entry!r} has no name")
        kwargs: Dict[str, float] = {}
        for kv in body.split(","):
            kv = kv.strip()
            if not kv:
                continue
            k, _, v = kv.partition("=")
            k = k.strip()
            if k not in ("rate", "burst", "weight", "priority"):
                raise ValueError(
                    f"unknown tenant field {k!r} in {entry!r} "
                    f"(rate|burst|weight|priority)")
            kwargs[k] = float(v)
        if kwargs.get("rate", 1.0) <= 0 or kwargs.get("burst", 1.0) <= 0:
            raise ValueError(f"tenant {name!r} needs rate > 0, burst > 0")
        if "priority" in kwargs:
            kwargs["priority"] = int(kwargs["priority"])
        classes[name] = TenantClass(name=name, **kwargs)
    if not classes:
        raise ValueError(f"tenant spec {spec!r} declares no classes")
    return classes


class TokenBucket:
    """Classic token bucket with an injectable monotonic clock.

    Not internally locked: the owning controller serializes access
    (one lock covers resolve + take, so two racing admits cannot both
    spend the last token).
    """

    def __init__(self, rate: float, burst: float,
                 clock: Callable[[], float] = time.monotonic):
        self.rate = float(rate)
        self.burst = float(burst)
        self._clock = clock
        self._tokens = self.burst
        self._t_last = clock()

    def try_take(self, n: float = 1.0) -> float:
        """Take ``n`` tokens.  Returns 0.0 on success, else the seconds
        until ``n`` tokens will have refilled (the retry-after hint)."""
        now = self._clock()
        self._tokens = min(self.burst,
                           self._tokens + (now - self._t_last) * self.rate)
        self._t_last = now
        if self._tokens >= n:
            self._tokens -= n
            return 0.0
        return (n - self._tokens) / self.rate

    @property
    def tokens(self) -> float:
        return self._tokens


class QoSController:
    """Per-tenant admission gate + the ladder's routing flags.

    ``route_floor_to_cpu`` / ``shed_floor`` are plain booleans written
    only by the ladder (under its lock) and read as single attribute
    loads on the admission path — the reader tolerates one stale
    observation by design (the ladder moves on second-scale ticks).
    """

    _guarded_by = {"_buckets": "_lock"}

    def __init__(self, classes: Optional[Dict[str, TenantClass]] = None,
                 default: Optional[str] = None,
                 ingest: Optional[str] = None,
                 clock: Callable[[], float] = time.monotonic):
        from ..config import get_config

        cfg = get_config()
        self.classes = (dict(classes) if classes is not None
                        else parse_tenant_spec(cfg.qos_tenants))
        self.default = default if default is not None \
            else cfg.qos_default_tenant
        self.ingest = ingest if ingest is not None else cfg.qos_ingest_tenant
        if self.default not in self.classes:
            raise ValueError(f"default tenant {self.default!r} is not a "
                             f"declared class {sorted(self.classes)}")
        # the floor class: lowest priority among query classes (the
        # ingest class sheds on its own lane, so it is not a candidate
        # for the ladder's route-to-cpu / shed steps)
        floor_pool = [c for n, c in self.classes.items() if n != self.ingest]
        self.floor = min(floor_pool or self.classes.values(),
                         key=lambda c: (c.priority, c.name)).name
        self._lock = threading.Lock()
        self._buckets = {n: TokenBucket(c.rate, c.burst, clock)
                         for n, c in self.classes.items()}
        # ladder-written routing flags (single attr read on hot paths)
        self.route_floor_to_cpu = False
        self.shed_floor = False
        self.ladder: Optional["DegradationLadder"] = None

    # -- resolution ----------------------------------------------------
    def resolve(self, tenant: Optional[str]) -> TenantClass:
        """Tenant label -> declared class (the allowlist); unknown or
        missing labels map to the default class."""
        cls = self.classes.get(tenant) if tenant else None
        return cls if cls is not None else self.classes[self.default]

    def weights(self) -> Dict[str, float]:
        return {n: c.weight for n, c in self.classes.items()}

    # -- admission (the gate every enqueue goes through) ---------------
    def admit(self, req, result_queue) -> bool:
        """Admit ``req`` or answer it (False = caller must drop it).

        Stamps the resolved class (``req.tenant_class``) and lifts
        ``req.priority`` to the class priority so downstream fair lanes
        and watermark sheds order by class.  Rejections are answered on
        ``result_queue`` exactly like sheds: typed exception, metric,
        retained flight record.
        """
        cls = self.resolve(getattr(req, "tenant", None))
        req.tenant_class = cls.name
        if req.priority < cls.priority:
            req.priority = cls.priority
        tr = getattr(req, "trace", None)
        ladder = self.ladder
        level = ladder.level if ladder is not None else 0
        if tr is not None and level:
            # degraded-mode breadcrumb: retained flight records show
            # which ladder level was in force when this request entered
            tr.add("qos.level", {"level": level})
        if self.shed_floor and cls.name == self.floor:
            shed(req, result_queue, "qos", "degraded")
            return False
        with self._lock:
            retry_after = self._buckets[cls.name].try_take()
        if retry_after > 0.0:
            telemetry.counter("serving_qos_rejected_total",
                              tenant=cls.name).inc()
            exc = QuotaExceeded(cls.name, retry_after)
            if tr is not None:
                tr.add("reject", {"reason": "quota", "tenant": cls.name,
                                  "retry_after_s": round(retry_after, 4)})
                flightrec.get_recorder().finish(
                    tr, max(time.perf_counter() - req.t_enqueue, 0.0),
                    status="rejected", lane="qos")
            if result_queue is not None:
                result_queue.put((req, exc))
            return False
        telemetry.counter("serving_qos_admitted_total",
                          tenant=cls.name).inc()
        return True

    # -- read side -----------------------------------------------------
    def status(self) -> dict:
        with self._lock:
            buckets = {n: round(b.tokens, 3)
                       for n, b in sorted(self._buckets.items())}
        st = {
            "classes": [
                {"name": c.name, "rate": c.rate, "burst": c.burst,
                 "weight": c.weight, "priority": c.priority}
                for _, c in sorted(self.classes.items())
            ],
            "default": self.default,
            "ingest": self.ingest,
            "floor": self.floor,
            "tokens": buckets,
            "route_floor_to_cpu": self.route_floor_to_cpu,
            "shed_floor": self.shed_floor,
        }
        ladder = self.ladder
        if ladder is not None:
            st["ladder"] = ladder.status()
        return st


@dataclass(frozen=True)
class LadderStep:
    """One reversible degradation: ``apply()`` on step-down, ``revert()``
    on step-up.  Both must be idempotent — the ladder calls each at most
    once per transition, but operators can replay them by hand."""

    name: str
    apply: Callable[[], None]
    revert: Callable[[], None]


class DegradationLadder:
    """Burn-rate-driven reversible brownout.

    ``observe(breaching)`` is fed once per SLO evaluation (attach to a
    watchdog via :meth:`attach`).  ``breach_ticks`` consecutive
    breaching observations step DOWN one level (apply the next step);
    ``recover_ticks`` consecutive healthy observations step UP one
    (revert the newest applied step) — hysteresis in both directions so
    a single noisy window cannot flap the system.  Level 0 = nothing
    applied; level N = steps[0..N-1] applied, in order.
    """

    _guarded_by = {"_level": "_lock", "_breaches": "_lock",
                   "_healthy": "_lock", "_history": "_lock"}

    _MAX_HISTORY = 64

    def __init__(self, steps: List[LadderStep],
                 breach_ticks: Optional[int] = None,
                 recover_ticks: Optional[int] = None):
        from ..config import get_config

        cfg = get_config()
        self.steps = list(steps)
        self.breach_ticks = int(breach_ticks if breach_ticks is not None
                                else cfg.qos_breach_ticks)
        self.recover_ticks = int(recover_ticks if recover_ticks is not None
                                 else cfg.qos_recover_ticks)
        if self.breach_ticks < 1 or self.recover_ticks < 1:
            raise ValueError("breach_ticks and recover_ticks must be >= 1")
        self._lock = threading.Lock()
        self._level = 0
        self._breaches = 0
        self._healthy = 0
        self._history: List[dict] = []
        telemetry.gauge("serving_degradation_level").set(0)

    @property
    def level(self) -> int:
        with self._lock:
            return self._level

    def observe(self, breaching: bool) -> int:
        """Fold one SLO evaluation in; returns the (possibly new) level.
        Step apply/revert callbacks run OUTSIDE the lock — they touch
        foreign subsystems (sampler, caches) that must not nest under
        ladder state."""
        action = None
        with self._lock:
            if breaching:
                self._breaches += 1
                self._healthy = 0
                if (self._breaches >= self.breach_ticks
                        and self._level < len(self.steps)):
                    self._breaches = 0
                    self._level += 1
                    action = ("down", self._level)
            else:
                self._healthy += 1
                self._breaches = 0
                if (self._healthy >= self.recover_ticks
                        and self._level > 0):
                    self._healthy = 0
                    action = ("up", self._level - 1)
                    self._level -= 1
            level = self._level
        if action is not None:
            self._transition(*action)
        return level

    def _transition(self, direction: str, level_arg: int) -> None:
        if direction == "down":
            step = self.steps[level_arg - 1]
            new_level = level_arg
            step.apply()
        else:
            step = self.steps[level_arg]
            new_level = level_arg
            step.revert()
        telemetry.gauge("serving_degradation_level").set(new_level)
        telemetry.counter("serving_qos_ladder_transitions_total",
                          direction=direction, step=step.name).inc()
        if flightrec.tracing():
            # forwards to the unified timeline too, trace-correlated
            flightrec.event("qos.ladder", {"direction": direction,
                                           "step": step.name,
                                           "level": new_level})
        elif _timeline._ON:
            # ladder ticks usually come from the watchdog thread with
            # no request trace active — land them on the timeline anyway
            _timeline.emit("qos.ladder", cat="qos",
                           attrs={"direction": direction,
                                  "step": step.name, "level": new_level})
        with self._lock:
            self._history.append({"t_wall": time.time(),
                                  "direction": direction,
                                  "step": step.name, "level": new_level})
            if len(self._history) > self._MAX_HISTORY:
                self._history.pop(0)

    def attach(self, watchdog,
               objectives: Optional[tuple] = None) -> "DegradationLadder":
        """Subscribe to a :class:`~quiver_tpu.telemetry.slo.SLOWatchdog`:
        each evaluation becomes one ``observe`` tick (breaching iff any
        watched objective breaches; default = all objectives)."""
        names = set(objectives) if objectives else None

        def _on_eval(results):
            self.observe(any(
                r["breaching"] for r in results
                if names is None or r["objective"] in names))

        watchdog.add_listener(_on_eval)
        return self

    def status(self) -> dict:
        with self._lock:
            return {
                "level": self._level,
                "max_level": len(self.steps),
                "steps": [s.name for s in self.steps],
                "breach_ticks": self.breach_ticks,
                "recover_ticks": self.recover_ticks,
                "history": list(self._history[-16:]),
            }


def serving_ladder(controller: QoSController, sampler=None,
                   cold_cache=None,
                   fanout_frac: Optional[float] = None,
                   breach_ticks: Optional[int] = None,
                   recover_ticks: Optional[int] = None
                   ) -> DegradationLadder:
    """The standard four-step serving ladder, mildest first:

      1. ``fanout`` — scale the host sampler's per-hop fanout by
         ``config.qos_degrade_fanout_frac`` (smaller frontiers, cheaper
         batches).  Host path only: device executables bake fanout as a
         closure constant, and recompiling under overload is exactly the
         wrong move.
      2. ``coldcache`` — pause cold-row overlay admission writes (probes
         still hit; the admission bookkeeping + H2D scatter stops).
      3. ``cpu_floor`` — route the floor class to the CPU lane.
      4. ``shed_floor`` — shed the floor class at admission.

    ``sampler`` / ``cold_cache`` may be None (those steps no-op) so the
    ladder degrades gracefully on partial deployments.  Registers
    itself on the controller (``controller.ladder``).
    """
    from ..config import get_config

    frac = float(fanout_frac if fanout_frac is not None
                 else get_config().qos_degrade_fanout_frac)

    def _set_fanout(f: float) -> None:
        if sampler is not None and hasattr(sampler, "set_fanout_frac"):
            sampler.set_fanout_frac(f)

    def _pause_coldcache(paused: bool) -> None:
        cc = cold_cache
        if cc is not None:
            cc.admission_paused = paused

    def _route_floor(on: bool) -> None:
        controller.route_floor_to_cpu = on

    def _shed_floor(on: bool) -> None:
        controller.shed_floor = on

    steps = [
        LadderStep("fanout", lambda: _set_fanout(frac),
                   lambda: _set_fanout(1.0)),
        LadderStep("coldcache", lambda: _pause_coldcache(True),
                   lambda: _pause_coldcache(False)),
        LadderStep("cpu_floor", lambda: _route_floor(True),
                   lambda: _route_floor(False)),
        LadderStep("shed_floor", lambda: _shed_floor(True),
                   lambda: _shed_floor(False)),
    ]
    ladder = DegradationLadder(steps, breach_ticks=breach_ticks,
                               recover_ticks=recover_ticks)
    controller.ladder = ladder
    return ladder


# -- process-wide controller (feeds GET /debug/qos) ----------------------
_CONTROLLER: Optional[QoSController] = None
_controller_lock = threading.Lock()


def install_qos(controller: QoSController) -> QoSController:
    """Register ``controller`` process-wide (latest wins, like breakers:
    a restarted server's controller replaces its predecessor's on the
    debug endpoint)."""
    global _CONTROLLER
    with _controller_lock:
        _CONTROLLER = controller
    return controller


def get_qos() -> Optional[QoSController]:
    with _controller_lock:
        return _CONTROLLER


def qos_from_config() -> Optional[QoSController]:
    """The installed controller when QoS is enabled, creating (and
    installing) one from config on first touch; None when
    ``config.qos_enabled`` is off — callers store the None and their
    hot path pays a single attribute check."""
    global _CONTROLLER
    from ..config import get_config

    if not get_config().qos_enabled:
        return None
    with _controller_lock:
        if _CONTROLLER is None:
            _CONTROLLER = QoSController()
        return _CONTROLLER


def qos_status() -> dict:
    """JSON view for ``GET /debug/qos``."""
    from ..config import get_config

    ctl = get_qos()
    if ctl is None:
        return {"enabled": bool(get_config().qos_enabled),
                "installed": False}
    st = ctl.status()
    st["enabled"] = bool(get_config().qos_enabled)
    st["installed"] = True
    return st


def reset() -> None:
    """Drop the installed controller (tests)."""
    global _CONTROLLER
    with _controller_lock:
        _CONTROLLER = None
