"""quiverlint v3 staging tier — residency dataflow + no-sync regions.

Two halves, one contract ("the frontier never leaves the device"):

* **Static** — :mod:`.dataflow` classifies every value DEVICE / HOST /
  EITHER interprocedurally over PR 7's :class:`Program` model; the
  QT013/QT014/QT015 rules read the solve.  Import it explicitly
  (``from quiver_tpu.analysis.staging import dataflow``) — it pulls in
  the whole-program machinery and has no business on a serving import
  path.
* **Runtime** — :mod:`.regions` exposes :func:`no_sync`, the region
  marker the hot paths wrap around their device-resident spans, and is
  what this package re-exports: the library-facing surface must stay a
  few dozen lines of stdlib with a one-global-read off switch.

The runtime enforcement lives in
:mod:`quiver_tpu.analysis.transfer_witness` (``QUIVER_SANITIZE=1``).
"""

from .regions import active, no_sync, on

__all__ = ["active", "no_sync", "on"]
