"""No-sync regions — the runtime contract half of the staging tier.

A *no-sync region* is a lexical span that promises "nothing in here
forces a device→host transfer": the serving fused-forward dispatch, the
paged gather, the mesh halo combine.  QT013 proves the property over
code the static model can resolve; :mod:`..transfer_witness` watches the
transfers the process *actually* makes and attributes any that land
inside an open region.

The library brackets its hot spans unconditionally::

    from quiver_tpu.analysis.staging import no_sync

    with no_sync("serving.fused_forward"):
        out = fn(padded)

so the gate must cost nothing when the sanitizer is off.  Same contract
as telemetry timeline gating: ``_ON`` is a single module global, read
once; when it is False :func:`no_sync` returns a shared no-op context
manager (no allocation, no thread-local touch).  ``_ON`` is rebound
only by :func:`quiver_tpu.analysis.transfer_witness.install` /
``uninstall`` — tests pin the one-global-read property via
``on.__code__.co_names``.
"""

from __future__ import annotations

import threading
from typing import List, Optional

__all__ = ["active", "no_sync", "on"]

_ON = False


def on() -> bool:
    """True while the transfer witness has regions armed.

    Kept to a single global read; the test suite asserts
    ``on.__code__.co_names == ("_ON",)`` so the off cost can never
    silently grow past one dict lookup.
    """
    return _ON


class _Noop:
    """Shared do-nothing context manager for the witness-off path."""

    __slots__ = ()

    def __enter__(self) -> "_Noop":
        return self

    def __exit__(self, *exc) -> bool:
        return False


_NOOP = _Noop()

_tls = threading.local()


def _stack() -> List[str]:
    st = getattr(_tls, "labels", None)
    if st is None:
        st = _tls.labels = []
    return st


class _Region:
    """An open no-sync span on the current thread."""

    __slots__ = ("label",)

    def __init__(self, label: str):
        self.label = label

    def __enter__(self) -> "_Region":
        _stack().append(self.label)
        return self

    def __exit__(self, *exc) -> bool:
        st = _stack()
        if st and st[-1] == self.label:
            st.pop()
        return False


def no_sync(label: str = "no-sync"):
    """Declare a no-sync region.  Nestable; per-thread.

    With the witness off this returns a shared no-op singleton — the
    hot paths take this branch unconditionally, so it must stay at one
    global read plus one return.
    """
    if not _ON:
        return _NOOP
    return _Region(label)


def active() -> Optional[str]:
    """Innermost open region label on this thread, or None.

    The transfer witness consults this at every intercepted coercion;
    it is only ever called with the witness installed, so the
    thread-local touch is sanitizer-mode-only cost.
    """
    if not _ON:
        return None
    st = getattr(_tls, "labels", None)
    return st[-1] if st else None
