"""Staging dataflow — DEVICE / HOST / EITHER value classification.

quiverlint v3's interprocedural tier.  The per-file rules (QT001) stop
at function boundaries: ``out = self._fused_forward(padded)`` looks like
an opaque call, so the ``np.asarray(out)`` two lines later goes
unflagged even though the callee returns a live device array.  This
module layers a residency lattice over PR 7's :class:`Program` model —
same file set, same name resolution, same call graph — and solves it to
a fixed point across calls, returns, attribute loads, and containers.

Lattice (per value)::

        EITHER          may be device- or host-resident
        /    \\
    DEVICE   HOST       proven residency
        \\    /
        (unknown)       bottom — never reported

Each classified value also carries:

* ``hot`` — True when its device-ness originated inside a hot module
  (the sampler/feature/serving/mesh pipeline).  A harness file like
  ``bench.py`` computing its own throwaway ``jnp`` arrays stays cold;
  the batch it got back from ``sampler.sample`` is hot, and coercing
  *that* is a finding.
* ``inst`` — the class key when the value is a known instance
  (``wb = sampler.sample(...)`` → ``SampledBatch``), which is how
  ``wb.n_id`` resolves to the device field annotation three files away.

Sources: ``jnp.*`` / ``jax.*`` calls are DEVICE; numpy calls, casts,
``len()``, ``.item()`` / ``.tolist()``, ``jax.device_get`` and array
metadata (``.shape`` / ``.dtype`` / ...) are HOST; joins of both are
EITHER.  ``B = seeds.shape[0]`` is therefore host — shape metadata
never costs a transfer — which is what keeps the cache-key rule
(QT014) and this one from tripping over ordinary batch-size plumbing.

Everything is stdlib AST analysis; building the flow for the whole
repo shares the one memoized :func:`build_program` model and is itself
memoized per context list (QT013/14/15 all read the same solve).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from ..concurrency import build_program
from ..concurrency.program import (
    FuncInfo,
    Program,
    _dotted,
    _self_attr,
)
from ..core import ModuleContext

__all__ = [
    "DEVICE", "EITHER", "HOST", "Dataflow", "Val", "build_dataflow", "join",
]

DEVICE = "device"
HOST = "host"
EITHER = "either"


@dataclass(frozen=True)
class Val:
    """Abstract value: residency class + hot-path origin + instance type."""

    cls: Optional[str] = None      # DEVICE | HOST | EITHER | None
    hot: bool = False              # device-ness born in a hot module
    inst: Optional[str] = None     # class key for known instances
    fn: bool = False               # jitted callable: calling it -> DEVICE


def join(a: Optional[Val], b: Optional[Val]) -> Optional[Val]:
    if a is None:
        return b
    if b is None:
        return a
    if a.cls is None:
        cls = b.cls
    elif b.cls is None or a.cls == b.cls:
        cls = a.cls
    else:
        cls = EITHER
    inst = a.inst if a.inst == b.inst else None
    return Val(cls=cls, hot=a.hot or b.hot, inst=inst, fn=a.fn or b.fn)


def broadcast(a: Optional[Val], b: Optional[Val]) -> Optional[Val]:
    """Join under array-op semantics: ``dev + 0`` / ``dev > 0`` is a
    device array (jax broadcasts the host scalar up), so DEVICE wins a
    mixed pairing instead of widening to EITHER."""
    j = join(a, b)
    if j is not None and j.cls == EITHER:
        if (a is not None and a.cls == DEVICE) or \
                (b is not None and b.cls == DEVICE):
            return Val(cls=DEVICE, hot=j.hot, inst=j.inst, fn=j.fn)
    return j


_DEVICE_ROOTS = {"jnp", "jax"}
_HOST_ROOTS = {"np", "numpy", "math"}
_HOST_CALLS = {
    "jax.device_get", "int", "float", "bool", "str", "repr", "len",
    "range", "hash",
}
_HOST_METHODS = {"item", "tolist"}
# metadata reads are free: aval fields live on the host-side handle
_METADATA_ATTRS = {
    "shape", "dtype", "ndim", "size", "nbytes", "itemsize", "weak_type",
    "sharding",
}
# builtins transparent to residency: classify as the join of their args
_TRANSPARENT_CALLS = {
    "list", "tuple", "set", "sorted", "reversed", "sum", "min", "max",
    "abs", "zip", "enumerate", "next", "iter",
}
# staging transforms: the *result* is a callable whose outputs live on
# device — not a device value itself (``if fn is None`` is not a sync)
_DEVICE_FN_CALLS = {
    "jax.jit", "jax.pmap", "pmap", "pjit", "jit", "shard_map",
    "jax.experimental.shard_map.shard_map", "jax.experimental.pjit.pjit",
}

_MAX_PASSES = 10


def ordered_nodes(node: ast.AST):
    """Descendant nodes of a def in source order, not descending into
    nested defs / classes / lambdas (separate scopes with their own
    FuncInfo).  The nested def/class node itself IS yielded — a
    ``@jax.jit``-decorated nested def binds a callable name in this
    scope."""
    for child in ast.iter_child_nodes(node):
        if isinstance(child, ast.Lambda):
            continue
        yield child
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)):
            continue
        yield from ordered_nodes(child)


def _ann_residency(ann: Optional[ast.AST]) -> Optional[str]:
    """DEVICE/HOST hint from an annotation expression, if any."""
    if ann is None:
        return None
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        txt = ann.value
        if txt.startswith(("jnp.", "jax.")):
            return DEVICE
        if txt.startswith(("np.", "numpy.")):
            return HOST
        return None
    if isinstance(ann, ast.Subscript):      # Optional[jnp.ndarray] etc.
        inner = ann.slice
        if isinstance(inner, ast.Tuple) and inner.elts:
            inner = inner.elts[0]
        return _ann_residency(inner)
    dotted = _dotted(ann)
    if dotted:
        root = dotted.split(".")[0]
        if root in _DEVICE_ROOTS:
            return DEVICE
        if root in _HOST_ROOTS and dotted.split(".")[-1] == "ndarray":
            return HOST
    return None


class Dataflow:
    """Solved residency facts over one :class:`Program`."""

    def __init__(self, prog: Program):
        self.prog = prog
        self.ret: Dict[str, Val] = {}             # funckey -> return val
        self.param: Dict[Tuple[str, str], Val] = {}
        self.attr: Dict[Tuple[str, str], Val] = {}   # (clskey, attr)
        self.envs: Dict[str, Dict[str, Val]] = {}    # funckey -> locals
        self._fields: Dict[str, List[str]] = {}      # dataclass field order
        self._changed = False
        self._seed()
        self._solve()

    # ------------------------------------------------------------------
    # seeding: class field annotations give cross-module ground truth

    def _seed(self) -> None:
        for ci in self.prog.classes.values():
            hot = ci.ctx.is_hot()
            fields: List[str] = []
            for stmt in ci.node.body:
                if not isinstance(stmt, ast.AnnAssign) \
                        or not isinstance(stmt.target, ast.Name):
                    continue
                fields.append(stmt.target.id)
                res = _ann_residency(stmt.annotation)
                if res is not None:
                    self.attr[(ci.key, stmt.target.id)] = Val(
                        cls=res, hot=hot and res == DEVICE)
            self._fields[ci.key] = fields

    # ------------------------------------------------------------------
    # fixpoint driver

    def _solve(self) -> None:
        for _ in range(_MAX_PASSES):
            self._changed = False
            for fi in self.prog.functions.values():
                self._pass(fi)
            if not self._changed:
                break

    def _join_into(self, table: Dict, key, val: Optional[Val]) -> None:
        if val is None or (val.cls is None and val.inst is None
                           and not val.fn):
            return
        old = table.get(key)
        new = join(old, val)
        if new != old:
            table[key] = new
            self._changed = True

    # ------------------------------------------------------------------
    # per-function abstract interpretation

    def _pass(self, fi: FuncInfo) -> None:
        env = self.envs.setdefault(fi.key, {})
        self._seed_params(fi, env)
        self._walk(fi, fi.node, env)

    def _seed_params(self, fi: FuncInfo, env: Dict[str, Val]) -> None:
        args = getattr(fi.node, "args", None)
        if args is not None:
            for a in list(args.args) + list(args.kwonlyargs):
                if a.arg == "self" and fi.cls is not None:
                    env["self"] = Val(inst=fi.cls.key)
                    continue
                res = _ann_residency(a.annotation)
                seeded = Val(cls=res, hot=res == DEVICE
                             and fi.ctx.is_hot()) if res else None
                v = join(seeded, self.param.get((fi.key, a.arg)))
                if v is not None:
                    env[a.arg] = v
                elif a.annotation is not None:
                    t = fi.local_types.get(a.arg)
                    if t:
                        env[a.arg] = Val(inst=t)

    def _walk(self, fi: FuncInfo, node: ast.AST,
              env: Dict[str, Val]) -> None:
        for stmt in ordered_nodes(node):
            self._stmt(fi, stmt, env)

    def replay(self, fi: FuncInfo, visit) -> None:
        """Flow-sensitive re-walk for the rules: re-interpret ``fi`` in
        source order against the *solved* interprocedural tables,
        calling ``visit(node, env)`` at every node with the local env as
        it stands at that program point.  A name not yet bound locally
        falls back to the fixpoint env (loop-carried values); a name
        rebound through a materializer is HOST from that point on, so a
        branch-local DEVICE doesn't leak into the other branch the way
        the flow-insensitive final env would."""
        env: Dict[str, Val] = {}
        self._seed_params(fi, env)
        for node in ordered_nodes(fi.node):
            visit(node, env)
            self._stmt(fi, node, env)

    def _stmt(self, fi: FuncInfo, stmt: ast.AST,
              env: Dict[str, Val]) -> None:
        if isinstance(stmt, ast.Assign):
            v = self.classify(fi, stmt.value, env)
            for t in stmt.targets:
                self._bind(fi, t, v, env)
        elif isinstance(stmt, ast.AugAssign):
            v = self.classify(fi, stmt.value, env)
            if isinstance(stmt.target, ast.Name):
                env[stmt.target.id] = join(env.get(stmt.target.id), v) \
                    or Val()
            else:
                self._bind(fi, stmt.target, v, env)
        elif isinstance(stmt, ast.AnnAssign):
            res = _ann_residency(stmt.annotation)
            v = Val(cls=res, hot=res == DEVICE and fi.ctx.is_hot()) \
                if res else (self.classify(fi, stmt.value, env)
                             if stmt.value is not None else None)
            self._bind(fi, stmt.target, v, env)
        elif isinstance(stmt, ast.Return):
            if stmt.value is not None:
                self._join_into(self.ret, fi.key,
                                self.classify(fi, stmt.value, env))
        elif isinstance(stmt, ast.For):
            self._bind(fi, stmt.target,
                       self._element_of(fi, stmt.iter, env), env)
        elif isinstance(stmt, ast.With) or isinstance(stmt, ast.AsyncWith):
            for item in stmt.items:
                v = self.classify(fi, item.context_expr, env)
                if item.optional_vars is not None:
                    self._bind(fi, item.optional_vars, v, env)
        elif isinstance(stmt, ast.Expr):
            self.classify(fi, stmt.value, env)
        elif isinstance(stmt, (ast.If, ast.While)):
            self.classify(fi, stmt.test, env)
        elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # @jax.jit def fn(...) binds a jitted callable in this scope
            for d in stmt.decorator_list:
                dd = _dotted(d)
                if dd is None and isinstance(d, ast.Call):
                    dd = _dotted(d.func)
                    if dd not in _DEVICE_FN_CALLS and d.args:
                        dd = _dotted(d.args[0])   # @partial(jax.jit, ...)
                if dd in _DEVICE_FN_CALLS:
                    env[stmt.name] = Val(fn=True, hot=fi.ctx.is_hot())
                    break
        # compound bodies are visited by _own_statements' flattening

    def _bind(self, fi: FuncInfo, target: ast.AST, v: Optional[Val],
              env: Dict[str, Val]) -> None:
        if isinstance(target, ast.Name):
            env[target.id] = v or Val()
        elif isinstance(target, (ast.Tuple, ast.List)):
            vv = self._element_val(v)
            for e in target.elts:
                self._bind(fi, e, vv, env)
        elif isinstance(target, ast.Starred):
            self._bind(fi, target.value, v, env)
        else:
            attr = _self_attr(target)
            if attr and fi.cls is not None:
                self._join_into(self.attr, (fi.cls.key, attr), v)

    @staticmethod
    def _element_val(v: Optional[Val]) -> Optional[Val]:
        """Value of one element of ``v`` (tuple unpack / iteration):
        residency survives, instance identity doesn't."""
        if v is None:
            return None
        return Val(cls=v.cls, hot=v.hot)

    def _element_of(self, fi: FuncInfo, expr: ast.AST,
                    env: Dict[str, Val]) -> Optional[Val]:
        return self._element_val(self.classify(fi, expr, env))

    # ------------------------------------------------------------------
    # expression classification

    def lookup(self, fi: FuncInfo, name: str) -> Optional[Val]:
        """Name lookup through the enclosing-def chain (closures)."""
        f: Optional[FuncInfo] = fi
        while f is not None:
            env = self.envs.get(f.key)
            if env and name in env:
                return env[name]
            f = f.parent
        return None

    def attr_val(self, clskey: str, attr: str) -> Optional[Val]:
        for ci in self.prog._mro(clskey):
            v = self.attr.get((ci.key, attr))
            if v is not None:
                return v
        return None

    def classify(self, fi: FuncInfo, expr: Optional[ast.AST],
                 env: Optional[Dict[str, Val]] = None) -> Optional[Val]:
        if expr is None:
            return None
        if env is None:
            env = self.envs.get(fi.key, {})
        return self._classify(fi, expr, env)

    def _classify(self, fi: FuncInfo, expr: ast.AST,
                  env: Dict[str, Val]) -> Optional[Val]:
        if isinstance(expr, ast.Name):
            if expr.id in env:
                return env[expr.id]
            v = self.lookup(fi, expr.id)
            if v is not None:
                return v
            t = fi.local_types.get(expr.id)
            return Val(inst=t) if t else None
        if isinstance(expr, ast.Constant):
            # None is "no value", not a host value: `self.paged = None`
            # must not poison the later PagedStore assignment to EITHER
            return None if expr.value is None else Val(cls=HOST)
        if isinstance(expr, ast.JoinedStr):
            return Val(cls=HOST)
        if isinstance(expr, ast.Call):
            return self._classify_call(fi, expr, env)
        if isinstance(expr, ast.Attribute):
            return self._classify_attr(fi, expr, env)
        if isinstance(expr, ast.Subscript):
            return self._classify_subscript(fi, expr, env)
        if isinstance(expr, ast.BinOp):
            return broadcast(self._classify(fi, expr.left, env),
                             self._classify(fi, expr.right, env))
        if isinstance(expr, ast.UnaryOp):
            return self._classify(fi, expr.operand, env)
        if isinstance(expr, ast.Compare):
            if all(isinstance(op, (ast.Is, ast.IsNot, ast.In, ast.NotIn))
                   for op in expr.ops):
                return Val(cls=HOST)       # identity tests are python bools
            v = self._classify(fi, expr.left, env)
            for c in expr.comparators:
                v = broadcast(v, self._classify(fi, c, env))
            return self._element_val(v)
        if isinstance(expr, ast.BoolOp):
            v = None
            for e in expr.values:
                v = join(v, self._classify(fi, e, env))
            return v
        if isinstance(expr, ast.IfExp):
            return join(self._classify(fi, expr.body, env),
                        self._classify(fi, expr.orelse, env))
        if isinstance(expr, (ast.Tuple, ast.List, ast.Set)):
            v = None
            for e in expr.elts:
                v = join(v, self._classify(fi, e, env))
            return self._element_val(v) if v else None
        if isinstance(expr, ast.Dict):
            v = None
            for e in expr.values:
                if e is not None:
                    v = join(v, self._classify(fi, e, env))
            return self._element_val(v) if v else None
        if isinstance(expr, ast.Starred):
            return self._classify(fi, expr.value, env)
        if isinstance(expr, ast.Await):
            return self._classify(fi, expr.value, env)
        if isinstance(expr, ast.NamedExpr):
            v = self._classify(fi, expr.value, env)
            if isinstance(expr.target, ast.Name):
                env[expr.target.id] = v or Val()
            return v
        return None

    def _classify_call(self, fi: FuncInfo, call: ast.Call,
                       env: Dict[str, Val]) -> Optional[Val]:
        dotted = _dotted(call.func)
        arg_vals = [self._classify(fi, a, env) for a in call.args]
        any_hot = any(v.hot for v in arg_vals if v is not None)
        if dotted:
            root = dotted.split(".")[0]
            if dotted in _HOST_CALLS or root in _HOST_ROOTS:
                return Val(cls=HOST)
            if dotted in _TRANSPARENT_CALLS:
                v = None
                for av in arg_vals:
                    v = join(v, av)
                return v
            if dotted in _DEVICE_FN_CALLS:
                return Val(fn=True, hot=fi.ctx.is_hot() or any_hot)
            if root in _DEVICE_ROOTS:
                return Val(cls=DEVICE,
                           hot=fi.ctx.is_hot() or any_hot)
            clskey = self.prog._resolve_class_name(fi.ctx, dotted)
            if clskey is not None:
                self._record_ctor(fi, call, clskey, env)
                return Val(inst=clskey)
            callee = self.prog.resolve_callable(fi, call.func)
            if callee is not None:
                offset = self._callee_offset(callee, call)
                self._record_args(fi, call, callee, offset, env)
                r = self.ret.get(callee)
                if r is not None:
                    return r
                # fall through: a name bound to a jitted callable may
                # shadow-resolve to its (opaque) nested def
        if isinstance(call.func, ast.Attribute):
            if call.func.attr in _HOST_METHODS:
                return Val(cls=HOST)
            if call.func.attr == "setdefault" and len(call.args) >= 2:
                # dict.setdefault returns either the stored value or the
                # one just inserted: at least as device-ish as the insert
                # (`fn = cache.setdefault(B, fn)` keeps fn a jitted
                # callable)
                return arg_vals[1]
            recv = self._classify(fi, call.func.value, env)
            if recv is not None and recv.inst is not None:
                m = self.prog.lookup_method(recv.inst, call.func.attr)
                if m is not None:
                    self._record_args(fi, call, m.key, 1, env)
                    return self.ret.get(m.key)
                return None
            if recv is not None and recv.cls is not None:
                # array method (astype / reshape / sum / ...): residency
                # is preserved
                return Val(cls=recv.cls, hot=recv.hot)
            callee = self.prog.resolve_callable(fi, call.func)
            if callee is not None:
                offset = self._callee_offset(callee, call)
                self._record_args(fi, call, callee, offset, env)
                return self.ret.get(callee)
        # factory results: ``fn = self._merge_fn(B); fn(x)`` or a direct
        # ``self._combine_fn(B, k)(*stack)`` — calling a jitted callable
        # yields a device value
        fv = self._classify(fi, call.func, env)
        if fv is not None and fv.fn:
            return Val(cls=DEVICE, hot=fv.hot or any_hot)
        return None

    def _callee_offset(self, callee: str, call: ast.Call) -> int:
        m = self.prog.functions.get(callee)
        if m is None:
            return 0
        args = getattr(m.node, "args", None)
        if args and args.args and args.args[0].arg in ("self", "cls") \
                and (isinstance(call.func, ast.Attribute)
                     or m.name == "__init__"):
            return 1
        return 0

    def _record_args(self, fi: FuncInfo, call: ast.Call, callee: str,
                     offset: int, env: Dict[str, Val]) -> None:
        m = self.prog.functions.get(callee)
        if m is None:
            return
        args = getattr(m.node, "args", None)
        if args is None:
            return
        names = [a.arg for a in args.args]
        for i, a in enumerate(call.args):
            if isinstance(a, ast.Starred):
                continue
            idx = i + offset
            if idx < len(names):
                self._join_into(self.param, (callee, names[idx]),
                                self._classify(fi, a, env))
        kw_ok = set(names) | {a.arg for a in args.kwonlyargs}
        for kw in call.keywords:
            if kw.arg and kw.arg in kw_ok:
                self._join_into(self.param, (callee, kw.arg),
                                self._classify(fi, kw.value, env))

    def _record_ctor(self, fi: FuncInfo, call: ast.Call, clskey: str,
                     env: Dict[str, Val]) -> None:
        init = self.prog.lookup_method(clskey, "__init__")
        if init is not None:
            self._record_args(fi, call, init.key, 1, env)
            return
        # dataclass-style: positional/keyword args map to annotated fields
        fields = self._fields.get(clskey, [])
        for i, a in enumerate(call.args):
            if isinstance(a, ast.Starred):
                continue
            if i < len(fields):
                self._join_into(self.attr, (clskey, fields[i]),
                                self._classify(fi, a, env))
        for kw in call.keywords:
            if kw.arg:
                self._join_into(self.attr, (clskey, kw.arg),
                                self._classify(fi, kw.value, env))

    def _classify_attr(self, fi: FuncInfo, expr: ast.Attribute,
                       env: Dict[str, Val]) -> Optional[Val]:
        if expr.attr in _METADATA_ATTRS:
            return Val(cls=HOST)
        v = self._classify(fi, expr.value, env)
        if v is not None and v.inst is not None:
            return self.attr_val(v.inst, expr.attr)
        if v is not None and v.cls == DEVICE:
            # unknown attribute of a device array (.T, .at, ...) stays
            # device-resident
            return Val(cls=DEVICE, hot=v.hot)
        return None

    def _classify_subscript(self, fi: FuncInfo, expr: ast.Subscript,
                            env: Dict[str, Val]) -> Optional[Val]:
        v = self._classify(fi, expr.value, env)
        if v is not None and v.inst is not None:
            m = self.prog.lookup_method(v.inst, "__getitem__")
            if m is not None:
                args = getattr(m.node, "args", None)
                if args and len(args.args) > 1:
                    self._join_into(
                        self.param, (m.key, args.args[1].arg),
                        self._classify(fi, expr.slice, env))
                return self.ret.get(m.key)
            return None
        if v is not None and v.cls is not None:
            return Val(cls=v.cls, hot=v.hot)
        return None


# ---------------------------------------------------------------------------
# one-slot identity memo, same shape as concurrency.build_program: within
# one analyze_paths() run every staging rule receives the identical
# context list, so the fixpoint solve runs once.

_CACHE_KEY: Tuple[int, ...] = ()
_CACHE_VAL: Optional[Dataflow] = None


def build_dataflow(ctxs: Sequence[ModuleContext]) -> Dataflow:
    """Build (or reuse) the solved residency model for ``ctxs``."""
    global _CACHE_KEY, _CACHE_VAL
    key = tuple(id(c) for c in ctxs)
    if key != _CACHE_KEY or _CACHE_VAL is None:
        _CACHE_VAL = Dataflow(build_program(ctxs))
        _CACHE_KEY = key
    return _CACHE_VAL
