"""Runtime lock-witness sanitizer — the dynamic half of quiverlint v2.

QT008/QT009 prove ordering properties over the static call graph; this
module watches the locks the process *actually* takes.  With
``QUIVER_SANITIZE=1`` in the environment, ``quiver_tpu`` installs the
witness before any of its submodules import, so every
``threading.Lock()`` / ``threading.RLock()`` constructed afterwards is
wrapped in a :class:`_WitnessLock` that records, per thread:

* the **acquisition order** between every pair of distinct lock labels
  — a cycle in the observed order graph (or a contradiction of the
  canonical order exported by the static analyzer via
  :func:`seed_order`) is a lock-order-inversion violation, caught even
  when the interleaving that would deadlock never actually happens;
* **re-entry on a non-reentrant Lock** — recorded *before* delegating,
  since the real acquire would simply hang;
* **unguarded writes** to attributes declared in a class-level
  ``_guarded_by`` map: when a witness lock is constructed inside some
  object's ``__init__``, the owning class's ``__setattr__`` is wrapped
  to assert the declared lock is held at every later write
  (construction frames — ``__init__``/``__post_init__``/classmethod
  alternate constructors — are exempt, mirroring QT003/QT008).

Violations are **recorded, never raised**: the suite under test keeps
running and the harness (``tests/conftest.py`` under ``make sanitize``)
fails the owning test from :func:`drain`.  With the env var unset this
module is never imported and ``threading.Lock`` is untouched — the
zero-overhead contract ``tests/test_witness.py`` pins.

Everything here is stdlib-only and must stay importable without jax.
"""

from __future__ import annotations

import sys
import threading
import traceback
import weakref
from typing import Dict, List, Optional, Sequence, Set, Tuple

__all__ = [
    "Violation", "drain", "install", "installed", "seed_order",
    "uninstall", "violations",
]

_INIT_NAMES = ("__init__", "__post_init__")

# the real constructors, captured at import so the witness's own state
# can use them without recursing through the patch
_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock

# frames to skip when attributing acquisitions to user code.  Exact
# paths, not suffixes — a user file named test_witness.py must NOT be
# treated as internal.
_INTERNAL_FILES = (__file__, threading.__file__)


def _is_internal(filename: str) -> bool:
    return filename in _INTERNAL_FILES


class Violation:
    """One recorded sanitizer finding (kind, message, capture stack)."""

    __slots__ = ("kind", "message", "stack", "thread")

    def __init__(self, kind: str, message: str):
        self.kind = kind
        self.message = message
        self.thread = threading.current_thread().name
        self.stack = "".join(traceback.format_stack(sys._getframe(2), 8))

    def __repr__(self):
        return f"Violation({self.kind}: {self.message} [{self.thread}])"


class _State:
    def __init__(self):
        self.lock = _REAL_LOCK()          # guards everything below
        self.violations: List[Violation] = []
        # observed order graph: held label -> {acquired labels}
        self.order: Dict[str, Set[str]] = {}
        # where each observed edge was first seen (for messages)
        self.edge_site: Dict[Tuple[str, str], str] = {}
        self.seeded: Set[Tuple[str, str]] = set()
        self.instrumented: Dict[type, object] = {}  # cls -> orig __setattr__
        self.tls = threading.local()      # .held: List[_WitnessLock]

    def held(self) -> List["_WitnessLock"]:
        h = getattr(self.tls, "held", None)
        if h is None:
            h = self.tls.held = []
        return h


_state: Optional[_State] = None


def _record(kind: str, message: str) -> None:
    st = _state
    if st is None:
        return
    v = Violation(kind, message)
    with st.lock:
        st.violations.append(v)


def _reaches(st: _State, src: str, dst: str) -> bool:
    """DFS over the observed+seeded order graph (called under st.lock)."""
    seen: Set[str] = set()
    stack = [src]
    while stack:
        n = stack.pop()
        if n == dst:
            return True
        if n in seen:
            continue
        seen.add(n)
        stack.extend(st.order.get(n, ()))
    return False


class _WitnessLock:
    """Delegating wrapper satisfying both the Lock and the Condition
    inner-lock protocols, with per-thread order witnessing."""

    def __init__(self, inner, kind: str):
        self._inner = inner
        self._kind = kind                 # "lock" | "rlock"
        self._depth = 0                   # re-entry depth (this thread's
        self._owner_ref = None            # view only; see _held_by_me)
        self._label: Optional[str] = None
        self._site = _construction_site(self)

    # -- labelling -----------------------------------------------------
    @property
    def label(self) -> str:
        if self._label is None:
            self._label = self._refine_label() or self._site
        return self._label

    def _refine_label(self) -> Optional[str]:
        owner = self._owner_ref() if self._owner_ref is not None else None
        if owner is None:
            return None
        try:
            attrs = dict(vars(owner))
        except TypeError:  # __slots__ class
            attrs = {}
            for klass in type(owner).__mro__:
                for k in getattr(klass, "__slots__", ()):
                    try:
                        attrs[k] = getattr(owner, k)
                    except AttributeError:
                        pass
        for k, v in attrs.items():
            if v is self:
                return f"{type(owner).__name__}.{k}"
            # a Condition built over this lock: name it by the Condition
            if getattr(v, "_lock", None) is self:
                return f"{type(owner).__name__}.{k}"
        return None

    # -- witness bookkeeping -------------------------------------------
    def _held_by_me(self) -> bool:
        st = _state
        return st is not None and any(h is self for h in st.held())

    def _note_acquired(self) -> None:
        st = _state
        if st is None:
            return
        held = st.held()
        me = self.label
        with st.lock:
            for h in held:
                other = h.label
                if other == me:
                    # same label covers both re-entry (handled before
                    # delegation) and same-role striped instances
                    continue
                edge = (other, me)
                if edge in st.edge_site:
                    continue
                rev = (me, other)
                if rev in st.seeded:
                    _append_violation(st, Violation(
                        "lock-order",
                        f"acquired `{me}` while holding `{other}`, "
                        f"contradicting the static canonical order "
                        f"{me} -> {other}"))
                elif _reaches(st, me, other):
                    _append_violation(st, Violation(
                        "lock-order",
                        f"acquired `{me}` while holding `{other}`, but "
                        f"the reverse order was witnessed at "
                        f"{st.edge_site.get(rev, '<seeded>')} — cyclic "
                        f"acquisition order (potential deadlock)"))
                st.order.setdefault(other, set()).add(me)
                st.edge_site[edge] = _caller_site()
        held.append(self)

    def _note_released(self) -> None:
        st = _state
        if st is None:
            return
        held = st.held()
        for i in range(len(held) - 1, -1, -1):
            if held[i] is self:
                del held[i]
                break

    # -- Lock protocol -------------------------------------------------
    def acquire(self, blocking: bool = True, timeout: float = -1):
        if self._kind == "lock" and self._held_by_me():
            _record(
                "self-deadlock",
                f"re-acquired non-reentrant `{self.label}` already held "
                f"by this thread (the real acquire blocks forever)")
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._note_acquired()
        return ok

    def release(self):
        self._note_released()
        return self._inner.release()

    def locked(self):
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    # -- Condition inner-lock protocol ---------------------------------
    def _is_owned(self):
        inner = self._inner
        if hasattr(inner, "_is_owned"):
            return inner._is_owned()
        # plain-Lock fallback (mirrors threading.Condition's own)
        if inner.acquire(False):
            inner.release()
            return False
        return True

    def _release_save(self):
        self._note_released()
        if hasattr(self._inner, "_release_save"):
            return self._inner._release_save()
        self._inner.release()
        return None

    def _acquire_restore(self, state):
        if hasattr(self._inner, "_acquire_restore"):
            self._inner._acquire_restore(state)
        else:
            self._inner.acquire()
        self._note_acquired()

    def _at_fork_reinit(self):
        # concurrent.futures.thread registers this via os.register_at_fork
        # on its module-level shutdown lock at first import
        return self._inner._at_fork_reinit()

    def __getattr__(self, name):
        # forward any remaining lock-protocol surface (CPython version
        # differences) straight to the real lock
        if name == "_inner":
            raise AttributeError(name)
        return getattr(self._inner, name)

    def __repr__(self):
        return f"<WitnessLock {self.label} over {self._inner!r}>"


def _append_violation(st: _State, v: Violation) -> None:
    # caller already holds st.lock
    st.violations.append(v)


def _caller_site() -> str:
    f = sys._getframe(1)
    for _ in range(16):
        if f is None:
            break
        fn = f.f_code.co_filename
        if not _is_internal(fn):
            return f"{fn.rsplit('/', 1)[-1]}:{f.f_lineno}"
        f = f.f_back
    return "<unknown>"


def _construction_site(wl: "_WitnessLock") -> str:
    """Label fallback from the construction stack; also captures the
    owning object (the ``self`` of the nearest ``__init__`` frame) for
    lazy ``Class.attr`` refinement and ``_guarded_by`` instrumentation.
    """
    f = sys._getframe(2)
    site = "<unknown>"
    for _ in range(12):
        if f is None:
            break
        fn = f.f_code.co_filename
        if _is_internal(fn):
            f = f.f_back
            continue
        if site == "<unknown>":
            site = f"{fn.rsplit('/', 1)[-1]}:{f.f_lineno}"
        if f.f_code.co_name in _INIT_NAMES:
            owner = f.f_locals.get("self")
            if owner is not None:
                try:
                    wl._owner_ref = weakref.ref(owner)
                except TypeError:
                    pass
                _maybe_instrument(type(owner))
            break
        f = f.f_back
    return site


# -- guarded-attribute write checking ----------------------------------

def _maybe_instrument(cls: type) -> None:
    """Wrap ``cls.__setattr__`` to assert the ``_guarded_by`` contract
    at runtime.  Installed the first time a witness lock is constructed
    inside an instance's ``__init__``."""
    st = _state
    if st is None:
        return
    guarded = cls.__dict__.get("_guarded_by")
    if not isinstance(guarded, dict) or not guarded:
        return
    with st.lock:
        if cls in st.instrumented:
            return
        orig = cls.__setattr__
        st.instrumented[cls] = orig

    def checked_setattr(self, name, value, _orig=orig, _guarded=guarded,
                        _cls=cls):
        lock_attr = _guarded.get(name)
        if lock_attr is not None:
            lk = getattr(self, lock_attr, None)  # slots-safe
            if isinstance(lk, _WitnessLock) and not lk._held_by_me() \
                    and not _construction_frames(self, _cls):
                _record(
                    "unguarded-write",
                    f"`{_cls.__name__}.{name}` is _guarded_by "
                    f"`{lock_attr}` but was rebound at {_caller_site()} "
                    f"without holding it")
        _orig(self, name, value)

    cls.__setattr__ = checked_setattr


def _construction_frames(obj, cls: type) -> bool:
    """True when the write happens inside ``obj``'s own construction:
    an ``__init__``/``__post_init__`` frame for this object, or a
    classmethod frame of its class (alternate constructor) — the
    runtime mirror of the static pre-publication exemption."""
    f = sys._getframe(2)
    for _ in range(10):
        if f is None:
            return False
        loc = f.f_locals
        if f.f_code.co_name in _INIT_NAMES and loc.get("self") is obj:
            return True
        if loc.get("cls") is cls and loc.get("self") is obj:
            return True
        f = f.f_back
    return False


# -- factory patching ---------------------------------------------------

def _lock_factory():
    return _WitnessLock(_REAL_LOCK(), "lock")


def _rlock_factory():
    return _WitnessLock(_REAL_RLOCK(), "rlock")


def install() -> None:
    """Patch ``threading.Lock``/``threading.RLock`` so every lock
    constructed from here on is witnessed.  Idempotent."""
    global _state
    if _state is not None:
        return
    _state = _State()
    threading.Lock = _lock_factory
    threading.RLock = _rlock_factory


def uninstall() -> None:
    """Restore the real constructors and instrumented classes; drop all
    recorded state.  Locks already wrapped keep working (they delegate),
    they just stop reporting."""
    global _state
    st = _state
    if st is None:
        return
    threading.Lock = _REAL_LOCK
    threading.RLock = _REAL_RLOCK
    with st.lock:
        for cls, orig in st.instrumented.items():
            cls.__setattr__ = orig
        st.instrumented.clear()
    _state = None


def installed() -> bool:
    return _state is not None


def seed_order(edges: Sequence[Tuple[str, str]]) -> None:
    """Load the canonical acquisition order exported by the static
    analyzer (:func:`quiver_tpu.analysis.concurrency.canonical_lock_edges`)
    so a single runtime acquisition in the *wrong* direction is flagged
    without needing to witness both orders."""
    st = _state
    if st is None:
        return
    with st.lock:
        for a, b in edges:
            if a == b:
                continue
            st.seeded.add((a, b))
            st.order.setdefault(a, set()).add(b)


def violations() -> List[Violation]:
    st = _state
    if st is None:
        return []
    with st.lock:
        return list(st.violations)


def drain() -> List[Violation]:
    """Return and clear the recorded violations (the test-harness hook:
    an autouse fixture drains after every test and fails the owner)."""
    st = _state
    if st is None:
        return []
    with st.lock:
        out = list(st.violations)
        st.violations.clear()
        return out
