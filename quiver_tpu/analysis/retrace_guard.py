"""retrace_guard — a pytest plugin that makes jit-cache behavior testable.

quiverlint's QT002 catches retrace hazards *statically*; this plugin is
the runtime companion: it counts how many executables the data layer
actually builds while a test runs and fails the test when that count
exceeds a declared budget::

    @pytest.mark.retrace_budget(3)           # at most 3 jit builds
    def test_interleaved_batches(sampler):
        for b in [8, 16, 8, 32, 16, 8]:      # 3 distinct shapes
            sampler.sample(np.arange(b))

    @pytest.mark.retrace_budget(1, backend_compiles=2)
    def test_steady_state(...): ...

What counts as a "build": construction of a fresh library-level
executable — ``GraphSageSampler._build_jit``, a ``Feature._merge_cache``
miss, an ``InferenceServer._fused_fns`` miss, a
``HeteroGraphSageSampler._jitted`` miss.  ``backend_compiles``
additionally bounds XLA backend compiles observed through jax's
monitoring events (best effort: the hook is a private jax API, so the
listener degrades to "unavailable" rather than erroring if it moves).

Wiring: ``tests/conftest.py`` re-exports this module's hooks with
``from quiver_tpu.analysis.retrace_guard import *`` *after* its device
environment setup.  The module deliberately imports only pytest and
stdlib at top level — quiver_tpu (and therefore jax) load lazily inside
the counting context, so listing the plugin never defeats conftest's
``JAX_PLATFORMS`` / ``XLA_FLAGS`` staging.
"""

from __future__ import annotations

import contextlib
import functools
import threading
from typing import List, Optional, Tuple

import pytest

__all__ = [
    "JitBuildCounter", "count_jit_builds", "enforce_budget",
    "pytest_configure", "pytest_runtest_call",
]

_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"


class JitBuildCounter:
    """Tally of executable builds observed inside a counting context."""

    def __init__(self) -> None:
        self.builds = 0
        self.backend_compiles = 0
        self.backend_available = False
        self.sites: List[Tuple[str, object]] = []  # (site, shape key)
        self._lock = threading.Lock()

    def record(self, site: str, key: object = None) -> None:
        with self._lock:
            self.builds += 1
            self.sites.append((site, key))

    def record_backend(self) -> None:
        with self._lock:
            self.backend_compiles += 1

    def describe(self) -> str:
        if not self.sites:
            return "<no builds recorded>"
        return ", ".join(
            f"{site}({key})" if key is not None else site
            for site, key in self.sites)


def _count_calls(counter: JitBuildCounter, site: str):
    """Every call to the wrapped method is one build (``_build_jit``)."""
    def factory(orig):
        @functools.wraps(orig)
        def wrapped(self, *a, **kw):
            counter.record(site, a[0] if a else kw.get("batch_size"))
            return orig(self, *a, **kw)
        return wrapped
    return factory


def _count_cache_growth(counter: JitBuildCounter, site: str,
                        cache_attr: str):
    """A call is a build iff it grew the instance's executable cache —
    robust to the method's own key derivation (miss-detection by delta,
    not by re-implementing the key)."""
    def factory(orig):
        @functools.wraps(orig)
        def wrapped(self, *a, **kw):
            cache = getattr(self, cache_attr, None)
            before = len(cache) if cache is not None else 0
            out = orig(self, *a, **kw)
            cache = getattr(self, cache_attr, None)
            after = len(cache) if cache is not None else 0
            for _ in range(max(after - before, 0)):
                counter.record(site)
            return out
        return wrapped
    return factory


def _register_backend_listener(counter: JitBuildCounter):
    """Best-effort XLA compile-event listener (private jax API)."""
    try:
        from jax._src import monitoring
    except ImportError:
        return None

    def listener(event, duration, **kw):
        if event == _COMPILE_EVENT:
            counter.record_backend()

    try:
        monitoring.register_event_duration_secs_listener(listener)
    except Exception:
        return None
    counter.backend_available = True
    return listener


def _unregister_backend_listener(listener) -> None:
    if listener is None:
        return
    try:
        from jax._src import monitoring
        monitoring._unregister_event_duration_listener_by_callback(listener)
    except Exception:
        pass


@contextlib.contextmanager
def count_jit_builds():
    """Context manager: patch the library's executable-build sites and
    yield a live :class:`JitBuildCounter`.  Usable directly in tests for
    exact assertions (``assert c.builds == 2``) — the marker is sugar
    over this."""
    counter = JitBuildCounter()
    patched: List[Tuple[type, str, object]] = []

    def patch(cls, name, factory):
        orig = cls.__dict__.get(name)
        if orig is None:       # subclass without an override: base covers
            return
        setattr(cls, name, factory(orig))
        patched.append((cls, name, orig))

    try:
        from quiver_tpu.sampler import GraphSageSampler
        patch(GraphSageSampler, "_build_jit",
              _count_calls(counter, "sampler._build_jit"))
        # streaming overlay pipeline: builds key on snapshot SHAPES
        # (B, epad, delta_bucket, has_ts, windowed) — steady-state
        # ingestion must hit the same keys, which is exactly what this
        # counter lets tests assert
        patch(GraphSageSampler, "_build_stream_jit",
              _count_calls(counter, "sampler._build_stream_jit"))
    except ImportError:
        pass
    try:
        from quiver_tpu.feature import Feature
        patch(Feature, "_merge_fn",
              _count_cache_growth(counter, "feature._merge_fn",
                                  "_merge_cache"))
        # the overlay's admission scatter shares _merge_cache but builds
        # through its own accessor — count it separately
        patch(Feature, "_admit_fn",
              _count_cache_growth(counter, "feature._admit_fn",
                                  "_merge_cache"))
        # paged path: the ragged-gather program and the page-fault
        # scatter both key into _merge_cache via their own accessors
        patch(Feature, "_paged_fn",
              _count_cache_growth(counter, "feature._paged_fn",
                                  "_merge_cache"))
        patch(Feature, "_paged_fault_fn",
              _count_cache_growth(counter, "feature._paged_fault_fn",
                                  "_merge_cache"))
    except ImportError:
        pass
    try:
        from quiver_tpu.mesh.feature import MeshFeature
        from quiver_tpu.mesh.sampler import MeshSampler
        # mesh tier: the sharded-gather collective and page-fault
        # scatter key into _cache; the frontier-exchange combine into
        # _jitted — steady-state serving over warmed ladders must hold
        # all three flat
        patch(MeshFeature, "_gather_fn",
              _count_cache_growth(counter, "mesh._gather_fn", "_cache"))
        patch(MeshFeature, "_fault_fn",
              _count_cache_growth(counter, "mesh._fault_fn", "_cache"))
        patch(MeshSampler, "_combine_fn",
              _count_cache_growth(counter, "mesh._combine_fn", "_jitted"))
    except ImportError:
        pass
    try:
        from quiver_tpu.serving import InferenceServer
        patch(InferenceServer, "_fused_forward",
              _count_cache_growth(counter, "serving._fused_forward",
                                  "_fused_fns"))
    except ImportError:
        pass
    try:
        from quiver_tpu.hetero import HeteroGraphSageSampler
        patch(HeteroGraphSageSampler, "sample",
              _count_cache_growth(counter, "hetero.sample", "_jitted"))
    except ImportError:
        pass

    listener = _register_backend_listener(counter)
    try:
        yield counter
    finally:
        _unregister_backend_listener(listener)
        for cls, name, orig in reversed(patched):
            setattr(cls, name, orig)


def enforce_budget(counter: JitBuildCounter, builds: Optional[int],
                   backend_compiles: Optional[int] = None,
                   nodeid: str = "", fail=None) -> None:
    """Fail (via ``pytest.fail`` by default) if ``counter`` exceeded the
    budget.  Split out from the hook so the failure path is unit-testable
    without running a nested pytest."""
    fail = fail or pytest.fail
    where = nodeid or "<test>"
    if builds is not None and counter.builds > builds:
        fail(f"retrace budget exceeded: {counter.builds} jit build(s) > "
             f"budget {builds} for {where} — every extra build is a "
             f"latency cliff at serving time. Build sites: "
             f"{counter.describe()}", pytrace=False)
    if backend_compiles is not None and counter.backend_available \
            and counter.backend_compiles > backend_compiles:
        fail(f"retrace budget exceeded: {counter.backend_compiles} XLA "
             f"backend compile(s) > budget {backend_compiles} for "
             f"{where}", pytrace=False)


def _parse_marker(marker) -> Tuple[Optional[int], Optional[int]]:
    builds = marker.args[0] if marker.args else marker.kwargs.get("builds")
    backend = marker.kwargs.get("backend_compiles")
    if builds is None and backend is None:
        raise pytest.UsageError(
            "retrace_budget marker needs a budget: "
            "@pytest.mark.retrace_budget(N) or "
            "retrace_budget(backend_compiles=N)")
    return builds, backend


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "retrace_budget(builds, backend_compiles=None): fail the test if "
        "the data layer builds more than `builds` jit executables "
        "(or exceeds `backend_compiles` XLA compiles) while it runs")


@pytest.hookimpl(wrapper=True)
def pytest_runtest_call(item):
    marker = item.get_closest_marker("retrace_budget")
    if marker is None:
        return (yield)
    builds, backend = _parse_marker(marker)
    with count_jit_builds() as counter:
        result = yield          # test exceptions propagate past the patch
    enforce_budget(counter, builds, backend, nodeid=item.nodeid)
    return result
