"""quiverlint core — findings, config, module context, suppressions, engine.

The TPU data layer's performance contract is structural: hot loops must
not sync with the host (QT001), jit call sites must not retrace per call
(QT002), shared state must stay under its declared lock (QT003), hot
modules must not grow import-time dependencies on the exporter stack
(QT004), and library code must stay free of the Python footguns that
turn into silent serving bugs (QT005).  PR 1's telemetry *observes*
violations after the fact; this package *rejects* them at lint time.

Everything here is stdlib-only AST analysis: the linter itself must be
cheap enough to run in CI on every change and must never need a device
(or even jax) to execute its rules.

Suppression syntax (same line, or a comment-only line directly above)::

    out.block_until_ready()  # quiverlint: ignore[QT001] -- timing probe

Baseline workflow: ``python -m quiver_tpu.analysis --write-baseline``
records the current findings; later runs report only findings whose
fingerprint is not in the baseline, so pre-existing debt never blocks CI
while every *new* finding fails it (see :mod:`.baseline`).
"""

from __future__ import annotations

import ast
import fnmatch
import io
import re
import tokenize
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

__all__ = [
    "Finding", "LintConfig", "LintResult", "ModuleContext", "ProgramRule",
    "Rule", "analyze_paths", "dotted_call_name", "iter_py_files",
    "load_contexts",
]

_SUPPRESS_RE = re.compile(r"#\s*quiverlint:\s*ignore\[([A-Za-z0-9_,\s]+)\]")
# QT013's audited waiver: a *sync* that is part of the design (response
# leaving the process, bench checksum).  Unlike ignore[...], sync-ok is
# tracked — a waiver suppressing nothing is stale and fails
# --strict-baseline, so boundary declarations can't outlive the sync.
_SYNC_OK_RE = re.compile(r"#\s*quiverlint:\s*sync-ok\[([^\]]*)\]")

MODULE_SCOPE = "<module>"


@dataclass(frozen=True)
class Finding:
    """One rule violation, addressed stably by (rule, path, scope, snippet).

    Line/column are carried for display but excluded from the fingerprint
    so unrelated edits above a finding don't invalidate the baseline.
    """

    rule: str
    path: str        # posix path relative to the lint root
    line: int
    col: int
    scope: str       # innermost enclosing def/class qualname, or <module>
    message: str
    snippet: str     # stripped source of the flagged line

    def fingerprint(self) -> Tuple[str, str, str, str]:
        return (self.rule, self.path, self.scope, self.snippet)

    def to_dict(self) -> dict:
        return {
            "rule": self.rule, "path": self.path, "line": self.line,
            "col": self.col, "scope": self.scope, "message": self.message,
            "snippet": self.snippet,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Finding":
        return cls(rule=d["rule"], path=d["path"], line=int(d.get("line", 0)),
                   col=int(d.get("col", 0)),
                   scope=d.get("scope", MODULE_SCOPE),
                   message=d.get("message", ""), snippet=d.get("snippet", ""))

    def format(self) -> str:
        loc = f"{self.path}:{self.line}:{self.col}"
        return f"{loc}: {self.rule} [{self.scope}] {self.message}"


# Default hot-module set: the sampling -> gather -> serve pipeline, where
# a host round-trip is a per-batch tax (GNNSampler / SALIENT's dominant
# cost).  Patterns are fnmatch'd against the posix relpath.
_DEFAULT_HOT = (
    "quiver_tpu/sampler.py",
    "quiver_tpu/feature.py",
    "quiver_tpu/uva.py",
    "quiver_tpu/mixed.py",
    "quiver_tpu/serving.py",
    "quiver_tpu/neighbour_num.py",
    "quiver_tpu/ops/*.py",
    "quiver_tpu/ops/pallas/*.py",
    "quiver_tpu/parallel/*.py",
    "quiver_tpu/resilience/*.py",
    "quiver_tpu/stream/*.py",
    "quiver_tpu/recovery/*.py",
    "quiver_tpu/fleet/*.py",
    "quiver_tpu/mesh/*.py",
)


@dataclass
class LintConfig:
    """Knobs shared by all rules; tests swap in fixture-scoped configs."""

    hot_modules: Tuple[str, ...] = _DEFAULT_HOT
    # QT004: modules that must never be imported at module level from
    # library code (the exporter pulls in http.server; hot paths opt in
    # at call time via expose_metrics()).
    layering_forbidden: Tuple[str, ...] = (
        "quiver_tpu.telemetry.export", "http.server",
    )
    layering_exempt: Tuple[str, ...] = (
        "quiver_tpu/telemetry/export.py", "quiver_tpu/analysis/*",
    )
    # QT011: files whose persisted bytes must flow through the blessed
    # durable-IO helpers, and the helper module itself (the one place
    # raw writes are allowed to live).
    durability_scope: Tuple[str, ...] = ("quiver_tpu/recovery/*.py",)
    durability_exempt: Tuple[str, ...] = ("quiver_tpu/recovery/blockio.py",)
    # QT014: extra bucket-helper function names (beyond the built-in
    # pow2/quarter-octave set) whose results count as bounded key
    # components.
    bucket_helpers: Tuple[str, ...] = ()
    # QT015: modules whose psum operands must be provably integer (the
    # bit-exact halo-combine contract of the mesh tier).
    bitexact_modules: Tuple[str, ...] = ("quiver_tpu/mesh/*.py",)
    # rule codes to run; None = every registered rule
    rules: Optional[Tuple[str, ...]] = None
    exclude: Tuple[str, ...] = ("*/.*", "*/__pycache__/*")

    def want_rule(self, code: str) -> bool:
        return self.rules is None or code in self.rules


class ModuleContext:
    """Parsed view of one file handed to every rule."""

    def __init__(self, path: Path, relpath: str, source: str,
                 config: LintConfig):
        self.path = path
        self.relpath = relpath
        self.config = config
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=str(path))
        self.module = _dotted_module(relpath)
        self.scopes: Dict[int, str] = {}
        _map_scopes(self.tree, "", self.scopes)
        self.functions: List[Tuple[str, ast.AST]] = []
        _collect_functions(self.tree, "", self.functions)

    # -- helpers used by the rules ------------------------------------
    def is_hot(self) -> bool:
        return _match_any(self.relpath, self.config.hot_modules)

    def scope_of(self, node: ast.AST) -> str:
        return self.scopes.get(id(node), MODULE_SCOPE) or MODULE_SCOPE

    def snippet(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def finding(self, rule: str, node: ast.AST, message: str,
                scope: Optional[str] = None) -> Finding:
        return Finding(
            rule=rule, path=self.relpath, line=node.lineno,
            col=node.col_offset, scope=scope or self.scope_of(node),
            message=message, snippet=self.snippet(node.lineno),
        )

    def suppressions(self) -> Dict[int, Set[str]]:
        """line number -> set of suppressed rule codes ('*' = all)."""
        out: Dict[int, Set[str]] = {}
        for i, raw in enumerate(self.lines, start=1):
            m = _SUPPRESS_RE.search(raw)
            if not m:
                continue
            codes = {c.strip().upper() for c in m.group(1).split(",")
                     if c.strip()}
            out.setdefault(i, set()).update(codes)
            if raw.strip().startswith("#"):
                # comment-only line: covers the next non-comment line, so
                # an ignore may sit atop a multi-line justification block
                j = i + 1
                while (j <= len(self.lines)
                       and self.lines[j - 1].strip().startswith("#")):
                    j += 1
                out.setdefault(j, set()).update(codes)
        return out

    def sync_ok(self) -> Dict[int, Tuple[int, str]]:
        """effective line -> (declaration line, reason) for QT013
        ``sync-ok[...]`` waivers.

        Same placement rules as suppressions: same line, or a
        comment-only line directly above (which then covers the next
        non-comment line).  The declaration line identifies the waiver
        for the staleness audit — one comment may register under two
        effective lines but is one declaration."""
        out: Dict[int, Tuple[int, str]] = {}
        # tokenize (not a line scan) so docstrings and message strings
        # may *show* the directive without registering a waiver — the
        # staleness audit would otherwise flag them forever
        comments: Dict[int, str] = {}
        try:
            for tok in tokenize.generate_tokens(
                    io.StringIO(self.source).readline):
                if tok.type == tokenize.COMMENT:
                    comments[tok.start[0]] = tok.string
        except (tokenize.TokenError, IndentationError, SyntaxError):
            comments = dict(enumerate(self.lines, start=1))
        for i in sorted(comments):
            m = _SYNC_OK_RE.search(comments[i])
            if not m:
                continue
            reason = m.group(1).strip()
            out.setdefault(i, (i, reason))
            if self.lines[i - 1].strip().startswith("#"):
                j = i + 1
                while (j <= len(self.lines)
                       and self.lines[j - 1].strip().startswith("#")):
                    j += 1
                out.setdefault(j, (i, reason))
        return out


class Rule:
    """Base class; subclasses set code/name/description and yield findings."""

    code = "QT000"
    name = "base"
    description = ""

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        raise NotImplementedError
        yield  # pragma: no cover


class ProgramRule(Rule):
    """A rule over the *whole* analyzed program at once.

    Per-file rules see one :class:`ModuleContext`; concurrency
    properties (QT008 races, QT009 lock ordering, QT010 thread reaping)
    need the interprocedural call graph spanning every file.  The
    engine collects all contexts first, then runs each program rule's
    :meth:`check_program` once.  Findings flow through the same
    suppression / baseline machinery, keyed by the file each finding
    lands in.
    """

    program = True

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        return iter(())

    def check_program(self, ctxs: Sequence[ModuleContext],
                      ) -> Iterator[Finding]:
        raise NotImplementedError
        yield  # pragma: no cover


@dataclass
class LintResult:
    findings: List[Finding] = field(default_factory=list)
    suppressed: List[Finding] = field(default_factory=list)
    errors: List[str] = field(default_factory=list)
    files: int = 0
    # sync-ok waivers that suppressed nothing this run: (path, line,
    # reason).  Only populated when QT013 actually ran; --strict-baseline
    # fails on them.
    stale_sync_ok: List[Tuple[str, int, str]] = field(default_factory=list)


# ---------------------------------------------------------------------------
# shared AST helpers


def dotted_call_name(func: ast.AST) -> Optional[str]:
    """``jax.device_get`` for an Attribute/Name chain, else None."""
    parts: List[str] = []
    node = func
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _dotted_module(relpath: str) -> str:
    p = relpath[:-3] if relpath.endswith(".py") else relpath
    parts = [x for x in p.split("/") if x]
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


def _match_any(relpath: str, patterns: Sequence[str]) -> bool:
    return any(fnmatch.fnmatch(relpath, pat) for pat in patterns)


_SCOPE_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)


def _map_scopes(node: ast.AST, qual: str, out: Dict[int, str]) -> None:
    """Record, for every node, the qualname of its innermost enclosing
    def/class (the node's *own* name excluded — a def's finding scope is
    where the def lives; its body's scope includes it)."""
    for child in ast.iter_child_nodes(node):
        out[id(child)] = qual or MODULE_SCOPE
        if isinstance(child, _SCOPE_NODES):
            inner = f"{qual}.{child.name}" if qual else child.name
            _map_scopes(child, inner, out)
        else:
            _map_scopes(child, qual, out)


def _collect_functions(node: ast.AST, qual: str,
                       out: List[Tuple[str, ast.AST]]) -> None:
    for child in ast.iter_child_nodes(node):
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
            q = f"{qual}.{child.name}" if qual else child.name
            out.append((q, child))
            _collect_functions(child, q, out)
        elif isinstance(child, ast.ClassDef):
            q = f"{qual}.{child.name}" if qual else child.name
            _collect_functions(child, q, out)
        else:
            _collect_functions(child, qual, out)


# ---------------------------------------------------------------------------
# engine


def iter_py_files(paths: Sequence, root: Path,
                  config: LintConfig) -> Iterator[Path]:
    seen = set()
    for p in paths:
        p = Path(p)
        if not p.is_absolute():
            p = root / p
        if p.is_dir():
            candidates = sorted(p.rglob("*.py"))
        else:
            candidates = [p]
        for f in candidates:
            rel = _relpath(f, root)
            if _match_any(rel, config.exclude) or f in seen:
                continue
            seen.add(f)
            yield f


def _relpath(path: Path, root: Path) -> str:
    try:
        return path.resolve().relative_to(root.resolve()).as_posix()
    except ValueError:
        return path.as_posix()


def load_contexts(paths: Sequence, config: Optional[LintConfig] = None,
                  root: Optional[Path] = None) -> List[ModuleContext]:
    """Parse ``paths`` into :class:`ModuleContext` objects without running
    any rules — the entry point for consumers that want the program model
    alone (e.g. the lock-witness harness seeding the canonical order)."""
    config = config or LintConfig()
    root = Path(root) if root is not None else Path.cwd()
    out: List[ModuleContext] = []
    for f in iter_py_files(paths, root, config):
        try:
            out.append(ModuleContext(f, _relpath(f, root), f.read_text(),
                                     config))
        except (SyntaxError, UnicodeDecodeError, OSError):
            continue
    return out


def analyze_paths(paths: Sequence, config: Optional[LintConfig] = None,
                  root: Optional[Path] = None) -> LintResult:
    """Run every (selected) rule over ``paths``; returns raw + suppressed
    findings.  Baseline filtering is layered on top by the CLI / tests —
    see :func:`quiver_tpu.analysis.baseline.partition`."""
    from .rules import all_rules

    config = config or LintConfig()
    root = Path(root) if root is not None else Path.cwd()
    selected = [r for r in all_rules() if config.want_rule(r.code)]
    rules = [r for r in selected if not getattr(r, "program", False)]
    program_rules = [r for r in selected if getattr(r, "program", False)]
    result = LintResult()
    contexts: List[ModuleContext] = []
    sups: Dict[str, Dict[int, Set[str]]] = {}
    syncoks: Dict[str, Dict[int, str]] = {}
    for f in iter_py_files(paths, root, config):
        try:
            ctx = ModuleContext(f, _relpath(f, root), f.read_text(), config)
        except (SyntaxError, UnicodeDecodeError, OSError) as e:
            result.errors.append(f"{f}: {e}")
            continue
        result.files += 1
        sup = ctx.suppressions()
        contexts.append(ctx)
        sups[ctx.relpath] = sup
        syncoks[ctx.relpath] = ctx.sync_ok()
        for rule in rules:
            for finding in rule.check(ctx):
                codes = sup.get(finding.line, ())
                if finding.rule.upper() in codes or "*" in codes:
                    result.suppressed.append(finding)
                else:
                    result.findings.append(finding)
    if program_rules and contexts:
        # one parse, one program model: every program rule (QT008-010
        # concurrency, QT013-015 staging) reads the same memoized
        # Program / Dataflow built over this exact context list.
        from .concurrency import build_program

        build_program(contexts)
    sync_ok_used: Set[Tuple[str, int]] = set()   # (path, declaration line)
    for rule in program_rules:
        for finding in rule.check_program(contexts):
            codes = sups.get(finding.path, {}).get(finding.line, ())
            decl = syncoks.get(finding.path, {}).get(finding.line)
            if finding.rule.upper() in codes or "*" in codes:
                result.suppressed.append(finding)
            elif finding.rule.upper() == "QT013" and decl is not None:
                sync_ok_used.add((finding.path, decl[0]))
                result.suppressed.append(finding)
            else:
                result.findings.append(finding)
    if any(r.code == "QT013" for r in program_rules):
        for path in sorted(syncoks):
            decls = {(dline, reason)
                     for dline, reason in syncoks[path].values()}
            for dline, reason in sorted(decls):
                if (path, dline) not in sync_ok_used:
                    result.stale_sync_ok.append((path, dline, reason))
    result.findings.sort(key=lambda x: (x.path, x.line, x.rule))
    result.suppressed.sort(key=lambda x: (x.path, x.line, x.rule))
    return result
